"""The ecosystem capstone: an exactly-once pipeline with leader failover.

One simulated app wiring all four facades together — the kind of system
the reference's users build on madsim (tonic-example writ large):

    producer ──> kafka topic "events" ──> elected worker ──> s3 checkpoint
                                       ▲
                 etcd election decides WHICH worker consumes

Two workers campaign for leadership through the etcd election client
(lease-backed: a dead leader's lease expires and the standby takes over).
The leader resumes from the last s3 checkpoint `(next_offset, running
sum)`, consumes from kafka at that offset, and checkpoints atomically
after every event (one `put_object`). Mid-run, chaos kills the current
leader; the standby is elected, resumes from the checkpoint, and the
final checkpoint must hold EXACTLY the sum of all produced events — no
loss, no double-count — on every seed.

Run one seed:  python examples/pipeline.py [seed]
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import madsim_tpu as ms
from madsim_tpu.sims import s3 as s3_mod
from madsim_tpu.sims.s3 import NoSuchKey
from madsim_tpu.sims.etcd import Client as EtcdClient, SimServer
from madsim_tpu.sims.kafka import (
    BaseRecord,
    ClientConfig,
    NewTopic,
    SimBroker,
    TopicPartitionList,
)

N_EVENTS = 40
TOPIC, BUCKET, CKPT = "events", "pipeline", "ckpt/state"


async def producer():
    cfg = ClientConfig({"bootstrap.servers": "10.0.0.2:9092"})
    await (await cfg.create_admin()).create_topics([NewTopic(TOPIC, 1)])
    p = await cfg.create_producer()
    for i in range(1, N_EVENTS + 1):
        p.send(BaseRecord.to(TOPIC).with_payload(str(i).encode()))
        await p.flush()
        await ms.time.sleep(0.05 + ms.rand() * 0.1)


async def worker(name: str, log: list):
    """Campaign -> resume from checkpoint -> consume+checkpoint forever."""
    etcd = await EtcdClient.connect("10.0.0.1:2379")
    lease = await etcd.lease.grant(2)
    keeper, _stream = await etcd.lease.keep_alive(lease.id)

    async def keep():
        while True:
            await keeper.keep_alive()
            await ms.time.sleep(0.5)

    ms.spawn(keep())
    await etcd.election.campaign("pipeline-leader", name, lease.id)
    log.append(("leader", name))

    s3 = await s3_mod.Client.connect("10.0.0.3:9000")
    # ONLY a genuinely absent checkpoint starts from zero; a transient s3
    # error must propagate (the node's init fn re-enters this worker), or a
    # resumed leader would silently rewind to offset 0 and double-count —
    # the exact bug class the atomic checkpoint exists to rule out
    try:
        offset, total = json.loads(await s3.get_object(BUCKET, CKPT))
    except NoSuchKey:
        offset, total = 0, 0

    cfg = ClientConfig({"bootstrap.servers": "10.0.0.2:9092"})
    consumer = await cfg.create_consumer()
    tpl = TopicPartitionList()
    tpl.add_partition_offset(TOPIC, 0, offset)
    consumer.assign(tpl)

    while True:
        msg = await consumer.poll(timeout=1.0)
        if msg is None:
            continue
        total += int(msg.payload)
        offset = msg.offset + 1
        # the atomic exactly-once step: one put carries both cursor and sum
        await s3.put_object(BUCKET, CKPT, json.dumps([offset, total]).encode())
        log.append(("processed", name, offset, total))


async def run_pipeline(rt: ms.Runtime) -> dict:
    h = rt.handle
    h.create_node().name("etcd").ip("10.0.0.1").init(
        lambda: SimServer.builder().serve("10.0.0.1:2379")
    ).build()
    h.create_node().name("kafka").ip("10.0.0.2").init(
        lambda: SimBroker().serve("10.0.0.2:9092")
    ).build()
    h.create_node().name("s3").ip("10.0.0.3").init(
        lambda: s3_mod.S3Server().serve("10.0.0.3:9000")
    ).build()
    await ms.time.sleep(1.0)

    setup = h.create_node().name("setup").ip("10.0.0.9").build()

    async def mkbucket():
        s3c = await s3_mod.Client.connect("10.0.0.3:9000")
        await s3c.create_bucket(BUCKET)

    await setup.spawn(mkbucket())

    log: list = []
    prod = h.create_node().name("producer").ip("10.0.0.4").build()
    prod.spawn(producer())

    workers = {}
    for i, name in enumerate(("worker-a", "worker-b")):
        workers[name] = (
            h.create_node().name(name).ip(f"10.0.0.1{i + 1}")
            .init(lambda name=name: worker(name, log))
            .build()
        )

    # chaos: ask the election itself who leads, kill that worker; its lease
    # expires and the standby takes over from the s3 checkpoint. Restart
    # the victim later (init fn re-enters worker()) so it becomes standby.
    async def chaos():
        etcd = await EtcdClient.connect("10.0.0.1:2379")
        for _ in range(2):
            await ms.time.sleep(1.0 + ms.rand() * 1.5)
            resp = await etcd.election.leader("pipeline-leader")
            if resp.kv is None:
                continue  # mid-election; try again next round
            victim = resp.kv.value.decode()
            log.append(("kill", victim))
            h.kill(workers[victim].id)
            await ms.time.sleep(1.0 + ms.rand() * 1.0)
            h.restart(workers[victim].id)

    ms.spawn(chaos())

    # wait until the checkpoint reaches the last event (bounded)
    async def wait_done():
        s3c = await s3_mod.Client.connect("10.0.0.3:9000")
        while True:
            await ms.time.sleep(0.5)
            try:
                offset, total = json.loads(await s3c.get_object(BUCKET, CKPT))
            except Exception:
                continue
            if offset >= N_EVENTS:
                return offset, total

    offset, total = await ms.time.timeout(120.0, setup.spawn(wait_done()))
    expected = N_EVENTS * (N_EVENTS + 1) // 2
    leaders = [e[1] for e in log if e[0] == "leader"]
    kills = [e[1] for e in log if e[0] == "kill"]
    return {
        "offset": offset,
        "total": total,
        "expected": expected,
        "exactly_once": total == expected and offset == N_EVENTS,
        "leaders": leaders,
        "kills": kills,
        "failovers": max(0, len(leaders) - 1),
    }


def main(seed: int) -> dict:
    rt = ms.Runtime(seed=seed)
    result = rt.block_on(run_pipeline(rt))
    print(json.dumps(result))
    assert result["exactly_once"], result
    return result


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1)
