"""The device-fuzz workflow, end to end, in one file.

This is the madsim user journey (`#[madsim::test]` finds a seed, the seed
replays exactly, you debug) on the TPU engine: plant a classic Raft bug,
sweep thousands of seeds as ONE device batch, then debug a violating seed
three ways — the summary, the device trace microscope, and the host-runtime
re-run — all deterministic from the seed.

    python examples/fuzz_demo.py          # runs on whatever jax backend is live

Expected output: a few violating seeds (the planted bug is real), a
readable event trace of the exact trajectory that broke the invariant, and
a host-runtime repro of one seed.
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp


def buggy_raft_spec():
    """Raft with the canonical split-brain bug: a leader commits as soon
    as ONE follower acks (the majority rule dropped). Harmless on a calm
    network; partitions make it fatal."""
    from madsim_tpu.tpu import make_raft_spec
    from madsim_tpu.tpu import raft as raft_mod

    spec = make_raft_spec(5, client_rate=0.8)

    def buggy_on_message(s, nid, src, kind, payload, now, key):
        state, out, timer = spec.on_message(s, nid, src, kind, payload, now, key)
        is_ar = kind == raft_mod.APPEND_RESP
        bogus = jnp.where(
            is_ar & (payload[1] > 0) & (state.role == raft_mod.LEADER),
            jnp.maximum(state.commit, jnp.minimum(payload[2], state.log_len - 1)),
            state.commit,
        )
        return state._replace(commit=bogus), out, timer

    # replace_handlers (not bare dataclasses.replace): replacing on_message
    # on a fused spec must also drop the fused handler, or the engine keeps
    # running the original body — the helper does that in one place
    from madsim_tpu.tpu.spec import replace_handlers

    return replace_handlers(spec, on_message=buggy_on_message)


def main(n_seeds: int = 2048) -> None:
    from madsim_tpu.tpu import run_batch, raft_workload
    from madsim_tpu.tpu.trace import format_trace

    wl = raft_workload(virtual_secs=8.0, loss_rate=0.1, spec=buggy_raft_spec())
    # partitions are what make this bug bite
    wl = dataclasses.replace(
        wl,
        config=dataclasses.replace(
            wl.config,
            partition_interval_lo_us=300_000,
            partition_interval_hi_us=1_500_000,
            partition_heal_lo_us=500_000,
            partition_heal_hi_us=2_000_000,
        ),
    )

    print(f"sweeping {n_seeds} seeds on {jax.devices()[0]} ...")
    result = run_batch(range(n_seeds), wl, repro_on_host=False, max_traces=1)
    print(f"violations: {result.violations}")
    print(f"violating seeds: {result.violating_seeds[:10]}")
    assert result.violations > 0, "the planted bug should be found"

    seed = result.violating_seeds[0]
    print(f"\n--- device trace microscope: the last events of seed {seed} ---")
    events = result.traces[seed]
    print(format_trace(events[-25:]))

    print(f"\n--- host-runtime re-run of seed {seed} ---")
    # NB: the host face runs the CORRECT protocol (workloads/raft_host) —
    # this demo's bug is planted in the device spec only, so the host run
    # shows the healthy counterfactual under the same seed's chaos. For a
    # real protocol bug both faces reproduce it (see docs/bugs_found.md).
    repro = result.host_repros.get(seed)
    if repro is None and wl.host_repro is not None:
        repro = wl.host_repro(seed)
    print(f"host run (correct raft, same chaos): {repro}")

    print("\nreproduce any seed exactly:  MADSIM_TEST_SEED=<seed>  "
          "(the trace and the batch lane are bit-identical)")


if __name__ == "__main__":
    main()
