"""Greeter: the tonic-example analog — all four RPC shapes.

Reference: tonic-example/src/lib.rs (greeter server with unary,
server-streaming, client-streaming and bidi RPCs) exercised under chaos in
tonic-example/tests/test.rs.

Run a simulated cluster:  python examples/greeter.py [seed]
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")

import madsim_tpu as ms
from madsim_tpu.sims import grpc


class Greeter(grpc.Service):
    SERVICE_NAME = "helloworld.Greeter"

    @grpc.unary
    async def say_hello(self, request):
        return {"message": f"Hello {request['name']}!"}

    @grpc.server_streaming
    async def lots_of_replies(self, request):
        for i in range(5):
            await ms.time.sleep(0.1)
            yield {"message": f"{i}: Hello {request['name']}!"}

    @grpc.client_streaming
    async def lots_of_greetings(self, requests):
        names = [r["name"] async for r in requests]
        return {"message": f"Hello {', '.join(names)}!"}

    @grpc.bidi_streaming
    async def bidi_hello(self, requests):
        async for r in requests:
            yield {"message": f"Hello {r['name']}!"}


async def serve(addr: str) -> None:
    await grpc.Server().add_service(Greeter()).serve(addr)


def main(seed: int = 1) -> None:
    rt = ms.Runtime(seed=seed)

    async def root():
        h = rt.handle
        server = h.create_node().name("server").ip("10.0.0.1").build()
        client = h.create_node().name("client").ip("10.0.0.2").build()
        server.spawn(serve("10.0.0.1:50051"))
        await ms.time.sleep(0.1)

        async def run_client():
            channel = await grpc.connect("http://10.0.0.1:50051")
            stub = grpc.client_for(Greeter, channel)
            print(await stub.say_hello({"name": "madsim"}))
            async for m in await stub.lots_of_replies({"name": "stream"}):
                print(m)
            print(await stub.lots_of_greetings([{"name": n} for n in "abc"]))
            replies = await stub.bidi_hello([{"name": n} for n in ("x", "y")])
            print(await replies.collect())

        await client.spawn(run_client())

    rt.block_on(root())


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1)
