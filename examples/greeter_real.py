"""Greeter in PRODUCTION mode: the exact same service/client code as the
simulated cluster (examples/greeter.py), against real TCP sockets.

This is the reference's dual-mode promise (lib.rs:14-23; tonic-example's
real-mode binaries in src/bin/): code written once runs under the
deterministic simulation for testing and against reality for production.

    python examples/greeter_real.py         # server + client over localhost
"""

from __future__ import annotations

import sys

sys.path.insert(0, ".")

from madsim_tpu import real
from madsim_tpu.sims import grpc

# the UNMODIFIED simulation-tested service
from greeter import Greeter  # noqa: E402


async def main() -> None:
    server = grpc.Server().add_service(Greeter())
    server_task = real.real_spawn(server.serve("127.0.0.1:50061"))
    import asyncio

    await asyncio.sleep(0.2)  # let the listener come up

    channel = await grpc.connect("http://127.0.0.1:50061")
    stub = grpc.client_for(Greeter, channel)

    r = await stub.say_hello({"name": "world"})
    print("unary:", r)
    frames = await (await stub.lots_of_replies({"name": "world"})).collect()
    print("server-streaming:", frames)
    r = await stub.lots_of_greetings([{"name": n} for n in ("a", "b", "c")])
    print("client-streaming:", r)
    out = await (await stub.bidi_hello([{"name": "x"}, {"name": "y"}])).collect()
    print("bidi:", out)

    server.shutdown()
    server_task.abort()


if __name__ == "__main__":
    sys.path.insert(0, "examples")
    real.run(main())
