"""Build the optional native executor core:

    python setup_native.py build_ext --inplace

Produces madsim_tpu/native/_core.*.so; madsim_tpu falls back to the pure
Python implementations when absent.
"""

from setuptools import Extension, setup

setup(
    name="madsim-tpu-native",
    ext_modules=[
        Extension(
            "madsim_tpu.native._core",
            sources=["madsim_tpu/native/_core.cpp"],
            extra_compile_args=["-O2", "-std=c++17"],
            language="c++",
        )
    ],
)
