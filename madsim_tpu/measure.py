"""The perf_notes measurement discipline, codified once (r13).

Every number in docs/perf_notes.md was bought with the same four rules,
re-learned the hard way per bench (the tunnel will lie to you):

  * FRESH SEEDS every timed rep, derived from the rep index — the
    remote-tunnel relay CACHES identical dispatches, so repeating a rep
    with the same inputs returns in microseconds ("the 0.002 ms step").
  * WARM THE EXACT TIMED PROGRAM — same shapes, same static step count.
    `run_steps` jits per (shape, n_steps): warming with a different step
    count leaves the timed call's XLA compile inside the timing window
    (the §1-D node-sharding table caveat, now a regression test in
    tests/test_tune.py instead of a footnote).
  * MEDIANS OVER INTERLEAVED ROUNDS — the chip is shared and contention
    is bursty; interleaving variants within a round makes contention hit
    every variant alike, and the median drops one outlier either way.
  * SCAN ON DEVICE — never time per-step dispatch; a single step over
    the tunnel costs milliseconds of dispatch latency.

This module is the single implementation: `bench.py`,
`benches/ablate_step.py`, `benches/node_sharding.py` (via the
`benches/measure.py` shim) and the `madsim_tpu.tune` autotuner all
measure through it. Wall clocks here are `time.perf_counter` only —
measurement clocks never feed simulation state, so the module meets the
ambient-entropy lint bar with zero pragmas.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Sequence

import numpy as np


def fresh_seeds(rep: int, n: int, base: int = 0) -> np.ndarray:
    """The rep's seed block: `n` consecutive u32 seeds starting at
    `base + rep * n`. Pure function of the rep index — deterministic
    across processes, never equal across reps, which is the whole point
    (a cached dispatch must never be timed)."""
    rep, n = int(rep), int(n)
    if n <= 0:
        raise ValueError(f"seed block size must be positive, got {n}")
    return np.arange(base + rep * n, base + (rep + 1) * n, dtype=np.uint32)


def median(xs: Sequence[float]) -> float:
    """Median of a non-empty sequence (upper median for even lengths —
    matches the `sorted(walls)[len // 2]` idiom every bench used)."""
    xs = sorted(xs)
    if not xs:
        raise ValueError("median of an empty sequence")
    return xs[len(xs) // 2]


def _default_block(x: Any) -> None:
    if x is None:
        return
    import jax

    jax.block_until_ready(x)


def interleaved_medians(
    variants: Dict[str, Callable[[int], Any]],
    rounds: int = 3,
    rep_base: int = 1,
    block: Optional[Callable[[Any], None]] = None,
) -> Dict[str, float]:
    """Median wall seconds per variant over `rounds` INTERLEAVED rounds.

    Each round runs every variant once, in dict order, so bursty host or
    chip contention lands on all variants alike instead of biasing
    whichever ran during the burst. Every call receives a globally
    unique rep index (fresh seeds downstream); the variant must run to
    readback (return a value to block on, or block itself)."""
    block = block or _default_block
    walls: Dict[str, list] = {name: [] for name in variants}
    rep = int(rep_base)
    for _ in range(int(rounds)):
        for name, fn in variants.items():
            t0 = time.perf_counter()
            block(fn(rep))
            walls[name].append(time.perf_counter() - t0)
            rep += 1
    return {name: median(w) for name, w in walls.items()}


def time_sweep(
    run: Callable[[np.ndarray], Any],
    lanes: int,
    rounds: int = 3,
    rep_base: int = 0,
    block: Optional[Callable[[Any], None]] = None,
):
    """(median wall seconds, last result) of `run(seeds)` whole sweeps.

    The bench.py headline protocol: one warm rep compiles the exact
    program (rep `rep_base`, untimed), then `rounds` timed reps on fresh
    seed blocks, median wall. `run` must return something blockable
    (e.g. the final SimState)."""
    block = block or _default_block
    state = run(fresh_seeds(rep_base, lanes))
    block(state)
    walls = []
    for r in range(1, int(rounds) + 1):
        t0 = time.perf_counter()
        state = run(fresh_seeds(rep_base + r, lanes))
        block(state)
        walls.append(time.perf_counter() - t0)
    return median(walls), state


def time_scan_ms(
    init: Callable[[np.ndarray], Any],
    run_steps: Callable[[Any, int], Any],
    lanes: int,
    scan: int = 300,
    warm_steps: int = 200,
    rounds: int = 3,
    rep_base: int = 0,
    block: Optional[Callable[[Any], None]] = None,
) -> float:
    """Median ms/step over `rounds` fresh-seed reps of a `scan`-step
    on-device chunk.

    The warmup compiles BOTH programs this function will time against —
    the (shape, warm_steps) settle chunk and, critically, the exact
    (shape, scan) timed chunk. `run_steps` jits per (shape, n_steps), so
    warming with any other step count would leave the timed program's
    XLA compile inside the first timed rep — the bug that once made
    every cell of the node-sharding table compile-dominated
    (docs/perf_notes.md §1-D caveat; regression-pinned in
    tests/test_tune.py)."""
    block = block or _default_block
    st = init(fresh_seeds(rep_base, lanes))
    if warm_steps > 0:
        st = run_steps(st, warm_steps)
    block(run_steps(st, scan))  # compile the exact timed program
    walls = []
    for r in range(1, int(rounds) + 1):
        st = init(fresh_seeds(rep_base + r, lanes))
        if warm_steps > 0:
            st = run_steps(st, warm_steps)
        block(st)
        t0 = time.perf_counter()
        block(run_steps(st, scan))
        walls.append((time.perf_counter() - t0) / scan * 1e3)
    return median(walls)


class SweepTimer:
    """`measure(assignment, rep) -> wall seconds` with the discipline
    baked in — the autotuner's trial clock.

    `run(assignment, rep)` performs one sweep under the knob assignment,
    deriving its seeds from the rep index (`fresh_seeds`), and returns a
    value to block on (or blocks itself and returns None). The FIRST
    trial of each distinct `compile_key(assignment)` — the knob subset
    that changes compiled shapes or static step counts — runs an extra
    untimed warm rep of the exact program first, so no timed trial ever
    contains an XLA compile. Timed reps must use rep indices disjoint
    from `warm_rep` (the tuner's global trial counter starts above it).
    """

    def __init__(
        self,
        run: Callable[[Dict[str, Any], int], Any],
        compile_key: Callable[[Dict[str, Any]], Any] = lambda a: (),
        block: Optional[Callable[[Any], None]] = None,
        warm_rep: int = 0,
    ) -> None:
        self.run = run
        self.compile_key = compile_key
        self.block = block or _default_block
        self.warm_rep = int(warm_rep)
        self._warmed: set = set()

    def __call__(self, assignment: Dict[str, Any], rep: int) -> float:
        key = self.compile_key(assignment)
        if key not in self._warmed:
            self.block(self.run(assignment, self.warm_rep))
            self._warmed.add(key)
        t0 = time.perf_counter()
        self.block(self.run(assignment, int(rep)))
        return time.perf_counter() - t0
