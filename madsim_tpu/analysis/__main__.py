"""CLI: `python -m madsim_tpu.analysis` — the static verifier entry point.

    python -m madsim_tpu.analysis                 # source lints only (fast)
    python -m madsim_tpu.analysis --workload raft # + jaxpr rules for raft
    python -m madsim_tpu.analysis --all           # lints + all 5 workloads
    python -m madsim_tpu.analysis --all --json out.json

Exit status 0 iff every rule passed. A summary JSON (rule ->
pass/fail/violation count) is always printed with --json-line and written
with --json PATH, so rule counts can be tracked like a coverage metric.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional, Sequence

from . import (
    JAXPR_RULES,
    WORKLOADS,
    render_summary,
    run_analysis,
    write_summary,
)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m madsim_tpu.analysis",
        description=(
            "jaxpr-level determinism/purity verifier + source-level "
            "invariant linter (docs/analysis.md)"
        ),
    )
    parser.add_argument(
        "--all", action="store_true",
        help="run the jaxpr rules over all five workloads (plus the lints)",
    )
    parser.add_argument(
        "--workload", action="append", default=[], metavar="NAME",
        help=f"jaxpr-verify one workload (choices: {', '.join(WORKLOADS)}; "
        "repeatable)",
    )
    parser.add_argument(
        "--no-lint", action="store_true",
        help="skip the source-level lints (jaxpr rules only)",
    )
    parser.add_argument(
        "--rule", action="append", default=[], metavar="NAME",
        help="filter the per-workload jaxpr/range rules (choices: "
        f"{', '.join(JAXPR_RULES)}; repeatable; needs --workload/--all "
        "— e.g. the smoke prologues run `--rule range --workload raft`)",
    )
    parser.add_argument(
        "--json", metavar="PATH", default=None,
        help="write the summary JSON to PATH",
    )
    parser.add_argument(
        "--json-line", action="store_true",
        help="print the summary as one JSON line instead of the table",
    )
    parser.add_argument("--quiet", action="store_true")
    args = parser.parse_args(argv)

    workloads = list(args.workload)
    if args.all:
        workloads = list(WORKLOADS)
    for w in workloads:
        if w not in WORKLOADS:
            parser.error(
                f"unknown workload {w!r} (choose from {', '.join(WORKLOADS)})"
            )
    if args.no_lint and not workloads:
        parser.error(
            "--no-lint without --all/--workload selects zero rules — "
            "nothing would be verified"
        )
    for r in args.rule:
        if r not in JAXPR_RULES:
            parser.error(
                f"unknown rule {r!r} (choose from {', '.join(JAXPR_RULES)})"
            )
    if args.rule and not workloads:
        parser.error("--rule filters per-workload rules: add --workload/--all")

    log = None if (args.quiet or args.json_line) else print
    summary = run_analysis(
        workloads=workloads, lint=not args.no_lint, log=log,
        rules=args.rule or None,
    )
    if args.json:
        write_summary(summary, args.json)
    if args.json_line:
        print(json.dumps(summary, sort_keys=True))
    else:
        print(render_summary(summary))
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
