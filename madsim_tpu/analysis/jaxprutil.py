"""Shared jaxpr / StableHLO introspection for the static verifier.

The jaxpr-level rules (madsim_tpu/analysis/jaxpr_check.py) all reduce to
three primitives implemented here:

  * `iter_eqns` — walk every equation of a closed jaxpr INCLUDING the
    sub-jaxprs nested in pjit / while / scan / cond / custom_* params,
    so a callback or cross-lane reduction can't hide inside a call.
  * `TaintMap` — forward data-flow of a tiny 4-bit taint lattice
    (KEY / STATE / TIME / SALT) from the function's invars through every
    equation. This is what makes the RNG-taint and time-f32 rules
    cheap: no per-variable invar sets, just masks, with an on-demand
    backward slice (`backward_invars`) to name witnesses when a rule
    actually fires.
  * `donated_arg_flags` — parse a lowered program's StableHLO argument
    attributes (`tf.aliasing_output`) into per-flat-arg donation flags,
    aligned with jax's flatten order, so donation coverage is checked on
    the REAL lowered program rather than on intent.

The engine's PRNG is the murmur3 finalizer chain (tpu/prng.py); its two
fmix multiply constants identify every mix equation in a jaxpr, and the
fold structure `mix(key ^ word * GOLDEN)` makes a draw's key lineage and
folded words ordinary data flow — which is why plain taint propagation is
enough to verify the single-RNG funnel.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax import core as jcore

# murmur3 constants (tpu/prng.py / nemesis.mix32): the fmix multiplies
# identify mix equations; GOLDEN identifies fold word-multiplies.
FMIX_C1 = 0x85EBCA6B
FMIX_C2 = 0xC2B2AE35
GOLDEN = 0x9E3779B9

# taint lattice bits
KEY = 1  # derived from the schedule key root (ConstState.key0 / seeds)
STATE = 2  # derived from a protocol/config side channel
TIME = 4  # derived from a virtual-time quantity (us offsets)
SALT = 8  # derived from an allowlisted salt literal (the coverage chain)
KEY2 = 16  # derived from the per-step chain key (SimState.key)

# primitives that imply a host round-trip / sync inside a jitted program
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "debug_print",
    "callback", "infeed", "outfeed", "host_callback_call",
})

# reduction-style primitives whose `axes`/`dimension` params name the
# reduced dims (the lane-independence rule's scan set). Note
# `reduce_precision` is NOT here: it rounds mantissas elementwise.
REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "reduce_prod", "reduce_xor", "argmax", "argmin", "reduce",
})

_CUMULATIVE_PRIMS = frozenset({
    "cumsum", "cumprod", "cummax", "cummin", "cumlogsumexp",
})


def scalar_value(x: Any) -> Optional[int]:
    """The python int of a 0-d integer constant, else None."""
    try:
        arr = np.asarray(x)
    except Exception:
        return None
    if arr.ndim != 0 or arr.dtype.kind not in "iu":
        return None
    return int(arr)


def lit_value(atom: Any) -> Optional[int]:
    """Scalar int value of a jaxpr Literal atom, else None."""
    if isinstance(atom, jcore.Literal):
        return scalar_value(atom.val)
    return None


def _sub_jaxprs(eqn) -> List[Tuple[jcore.Jaxpr, tuple]]:
    """Every (Jaxpr, consts) nested in an equation's params.

    ClosedJaxprs keep their consts (a salt constant closed over by an
    inline-jitted helper must not lose its taint at the call boundary);
    bare Jaxprs yield empty consts."""
    out: List[Tuple[jcore.Jaxpr, tuple]] = []

    def rec(v):
        if isinstance(v, jcore.ClosedJaxpr):
            out.append((v.jaxpr, tuple(v.consts)))
        elif isinstance(v, jcore.Jaxpr):
            out.append((v, ()))
        elif isinstance(v, (tuple, list)):
            for x in v:
                rec(x)

    for v in eqn.params.values():
        rec(v)
    return out


# primitives whose sub-jaxpr re-enters with its own outputs (loop carry):
# one propagation pass under-approximates taint that arrives on
# iteration >= 2, so these bodies are iterated to a fixpoint
_LOOP_PRIMS = frozenset({"while", "scan"})


def iter_eqns(jaxpr: jcore.Jaxpr, depth: int = 0) -> Iterator[Tuple[Any, int]]:
    """(eqn, nesting depth) for every equation, recursing into sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn, depth
        for sub, _consts in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, depth + 1)


class TaintMap:
    """Forward taint propagation over a closed jaxpr.

    `invar_masks[i]` seeds the i-th invar; constvars (and literals, read
    lazily) whose scalar value is in `salt_values` carry SALT. Default
    propagation is the OR of input masks; ALL taint is stripped from
    boolean outputs (r9 — previously only TIME): a bool is a 1-bit
    control value, and every rule here targets VALUE flows — keys,
    times, magnitudes — not control flow that looked at one. The refill
    engine made this load-bearing: lane-retirement flags are data-flow
    descendants of handler state (which carries KEY2 through the event
    merges), and the admission machinery derives from those flags —
    under bool-carried taint no trajectory-dependent scheduler could
    ever verify. The trade is explicit: a draw whose index is rebuilt
    from BOOLEAN trajectory flags launders here (the carry-boundary
    re-seeding of the occurrence counters always laundered the same
    way); integer-valued coupling (index=clock and friends) is still
    caught. Sub-jaxpr handling (r9, grown for the refill step's
    lax.cond):
    `pjit` and `cond` bodies are entered with each inner invar seeded by
    its MATCHING operand's mask (precise 1:1 mapping — the old
    union-of-all-operands seeding made every value inside the refill
    branch carry every taint at once), and their per-branch outvar masks
    map back to the call's outvars (joined across cond branches). The
    cond PREDICATE deliberately does not fold into the outputs: control
    dependence does not launder data taint, the same principle as the
    TIME strip at bools. `while` carries are seeded per-slot and
    iterated to a fixpoint against the body's own outputs (r19 — the
    device-loop boundary's sequential fold/mutate loops carry schedule
    roots next to ctl rows, and the old whole-carry union drowned them);
    `scan` (what a static-trip-count fori_loop lowers to) is handled the
    same way, with the stacked ys joined across fixpoint passes. All
    sub-jaxpr equations are visited too.
    """

    def __init__(
        self,
        closed: jcore.ClosedJaxpr,
        invar_masks: Sequence[int],
        salt_values: Sequence[int] = (),
    ) -> None:
        self.salt_values = frozenset(int(v) for v in salt_values)
        self.env: Dict[Any, int] = {}
        jaxpr = closed.jaxpr
        for cv, val in zip(jaxpr.constvars, closed.consts):
            sv = scalar_value(val)
            self.env[cv] = SALT if sv in self.salt_values else 0
        if len(invar_masks) != len(jaxpr.invars):
            raise ValueError(
                f"invar_masks has {len(invar_masks)} entries for "
                f"{len(jaxpr.invars)} invars"
            )
        for v, m in zip(jaxpr.invars, invar_masks):
            self.env[v] = int(m)
        self._jaxpr = jaxpr

    def read(self, atom: Any) -> int:
        # bools carry no taint wherever they come from (invar, output,
        # constant): they are 1-bit control values — see the class doc
        dt = getattr(getattr(atom, "aval", None), "dtype", None)
        if dt is not None and str(dt) == "bool":
            return 0
        lv = lit_value(atom)
        if lv is not None and lv in self.salt_values:
            return SALT
        if isinstance(atom, jcore.Literal):
            return 0
        return self.env.get(atom, 0)

    def run(self, visit: Optional[Callable[[Any, Callable], None]] = None):
        """Propagate through every eqn; `visit(eqn, read)` is called per
        equation (at every nesting level) AFTER its inputs are resolved.
        During the walk `self.top_eqn` names the top-level equation
        enclosing the current one — witness extraction slices the outer
        jaxpr from it, so violations inside inline-jitted helpers still
        report real leaf names."""
        self.top_eqn: Any = None
        self._run(self._jaxpr, visit, top=True)
        return self

    def _seed_consts(self, sub: jcore.Jaxpr, consts: tuple) -> None:
        for cv, val in zip(sub.constvars, consts):
            sv = scalar_value(val)
            self.env[cv] = SALT if sv in self.salt_values else 0
        for cv in sub.constvars[len(consts):]:
            self.env.setdefault(cv, 0)

    def _set_outs(self, eqn, masks: Sequence[int]) -> None:
        # (bool outputs are additionally zeroed at read() — the one
        # uniform enforcement point of the control-boundary strip)
        for ov, om in zip(eqn.outvars, masks):
            dt = getattr(ov.aval, "dtype", None)
            if dt is not None and str(dt) == "bool":
                om = 0
            self.env[ov] = om

    def _call_sub(
        self, sub: jcore.Jaxpr, consts: tuple, in_masks: Sequence[int],
        visit,
    ) -> List[int]:
        """Enter a sub-jaxpr with 1:1 operand->invar mask seeding and
        return its outvar masks. Seeding OVERWRITES: jax caches traced
        helper jaxprs (clip, where, take, ...), so two call sites can
        share the very same Var objects — OR-accumulating across sites
        would leak one call's taint into every other (a clip used on a
        time value somewhere would time-taint the refill step's cursor
        clip). Each precise call re-propagates the shared body under its
        own operand masks; the body's bindings are recomputed, so
        clobbering a previous site's is sound."""
        self._seed_consts(sub, consts)
        for v, m in zip(sub.invars, in_masks):
            self.env[v] = int(m)
        self._run(sub, visit)
        return [self.read(ov) for ov in sub.outvars]

    def _run(self, jaxpr: jcore.Jaxpr, visit, top: bool = False) -> None:
        for eqn in jaxpr.eqns:
            if top:
                self.top_eqn = eqn
            if visit is not None:
                visit(eqn, self.read)
            name = eqn.primitive.name
            subs = _sub_jaxprs(eqn)
            # precise call handling: pjit (1:1 invars) and cond (operand
            # k+1 -> branch invar k; outvars joined across branches, the
            # predicate excluded — control flow doesn't launder data
            # taint). Shape-mismatched calls fall through to the
            # conservative union path below.
            if name == "pjit" and len(subs) == 1 and len(
                subs[0][0].invars
            ) == len(eqn.invars):
                in_masks = [self.read(iv) for iv in eqn.invars]
                outs = self._call_sub(
                    subs[0][0], subs[0][1], in_masks, visit
                )
                self._set_outs(eqn, outs)
                continue
            if name == "cond" and subs and all(
                len(sub.invars) == len(eqn.invars) - 1 for sub, _ in subs
            ):
                in_masks = [self.read(iv) for iv in eqn.invars]
                outs: Optional[List[int]] = None
                for sub, consts in subs:
                    res = self._call_sub(sub, consts, in_masks[1:], visit)
                    outs = res if outs is None else [
                        a | b for a, b in zip(outs, res)
                    ]
                self._set_outs(eqn, outs or [])
                continue
            # precise while handling (r19): 1:1 carry seeding iterated
            # to a fixpoint. The old conservative union made every carry
            # slot of a sequential loop carry every OTHER slot's taint —
            # sound, but it damned the device-loop generation boundary,
            # whose corpus-fold/mutate fori_loops legitimately carry
            # schedule-root seeds NEXT TO ctl rows and coverage words in
            # one carry. Per-slot masks joined with the body's own
            # outputs per pass model exactly how a while carry re-enters;
            # real cross-slot flows still propagate (they appear in the
            # body's dataflow), so nothing is laundered. The cond jaxpr
            # produces only the loop predicate (a bool — control, not
            # value, flow) but is still walked for visit() coverage.
            if name == "while" and {
                "cond_nconsts", "body_nconsts", "cond_jaxpr", "body_jaxpr",
            } <= set(eqn.params):
                cn = eqn.params["cond_nconsts"]
                bn = eqn.params["body_nconsts"]
                cj = eqn.params["cond_jaxpr"]
                bj = eqn.params["body_jaxpr"]
                in_masks = [self.read(iv) for iv in eqn.invars]
                cconsts = in_masks[:cn]
                bconsts = in_masks[cn:cn + bn]
                carry = in_masks[cn + bn:]
                if len(bj.jaxpr.invars) == bn + len(carry) and len(
                    bj.jaxpr.outvars
                ) == len(carry):
                    # bounded: masks only grow in a 5-bit lattice
                    for _ in range(8):
                        outs = self._call_sub(
                            bj.jaxpr, tuple(bj.consts),
                            bconsts + carry, visit,
                        )
                        new = [a | b for a, b in zip(carry, outs)]
                        if new == carry:
                            break
                        carry = new
                    if len(cj.jaxpr.invars) == cn + len(carry):
                        self._call_sub(
                            cj.jaxpr, tuple(cj.consts),
                            cconsts + carry, visit,
                        )
                    self._set_outs(eqn, carry)
                    continue
            # scan gets the same precise treatment (a static-trip-count
            # fori_loop lowers to scan, so the device-loop boundary's
            # sequential fold/mutate loops arrive HERE): consts stay
            # fixed, the carry slots iterate to a fixpoint against the
            # body's carry outputs, the stacked ys join across passes
            if name == "scan" and {
                "num_consts", "num_carry", "jaxpr",
            } <= set(eqn.params):
                nc = eqn.params["num_consts"]
                nk = eqn.params["num_carry"]
                bj = eqn.params["jaxpr"]
                in_masks = [self.read(iv) for iv in eqn.invars]
                consts = in_masks[:nc]
                carry = in_masks[nc:nc + nk]
                xs = in_masks[nc + nk:]
                if len(bj.jaxpr.invars) == len(in_masks) and len(
                    bj.jaxpr.outvars
                ) >= nk:
                    ys: Optional[List[int]] = None
                    for _ in range(8):
                        outs = self._call_sub(
                            bj.jaxpr, tuple(bj.consts),
                            consts + carry + xs, visit,
                        )
                        youts = outs[nk:]
                        ys = youts if ys is None else [
                            a | b for a, b in zip(ys, youts)
                        ]
                        new = [a | b for a, b in zip(carry, outs[:nk])]
                        if new == carry:
                            break
                        carry = new
                    self._set_outs(eqn, carry + (ys or []))
                    continue
            m = 0
            for iv in eqn.invars:
                m |= self.read(iv)
            # loop bodies re-enter with their own outputs: iterate to a
            # fixpoint (bounded — masks only grow in a 5-bit lattice)
            passes = 4 if name in _LOOP_PRIMS and subs else 1
            for _ in range(passes):
                grew = False
                for sub, consts in subs:
                    self._seed_consts(sub, consts)
                    for iv in sub.invars:
                        old = self.env.get(iv, 0)
                        if old | m != old:
                            grew = True
                        self.env[iv] = old | m
                    self._run(sub, visit)
                    for ov_inner in sub.outvars:
                        nm = m | self.read(ov_inner)
                        if nm != m:
                            grew = True
                        m = nm
                if not grew:
                    break
            self._set_outs(eqn, [m] * len(eqn.outvars))


def is_mix_mul(eqn) -> bool:
    """True for the second-stage fmix multiply — exactly one per mix()."""
    if eqn.primitive.name != "mul":
        return False
    return any(lit_value(iv) == FMIX_C2 for iv in eqn.invars)


def backward_invars(jaxpr: jcore.Jaxpr, seeds: Sequence[Any]) -> List[int]:
    """Indices of the jaxpr invars backward-reachable from `seeds` (vars).

    Witness extraction for taint violations: names which function inputs
    actually feed an offending equation. Single-level (does not descend
    into sub-jaxprs — violations are reported at their own level)."""
    defs: Dict[Any, Any] = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            defs[ov] = eqn
    invar_pos = {v: i for i, v in enumerate(jaxpr.invars)}
    seen: set = set()
    hits: set = set()
    stack = [s for s in seeds if not isinstance(s, jcore.Literal)]
    while stack:
        v = stack.pop()
        if id(v) in seen:
            continue
        seen.add(id(v))
        if v in invar_pos:
            hits.add(invar_pos[v])
            continue
        eqn = defs.get(v)
        if eqn is None:
            continue
        for iv in eqn.invars:
            if not isinstance(iv, jcore.Literal):
                stack.append(iv)
    return sorted(hits)


def find_while_eqns(jaxpr: jcore.Jaxpr) -> List[Any]:
    return [e for e, _ in iter_eqns(jaxpr) if e.primitive.name == "while"]


def while_carry_avals(eqn) -> List[Any]:
    """The carry avals of a `while` equation (consts excluded)."""
    nconsts = eqn.params["cond_nconsts"] + eqn.params["body_nconsts"]
    return [v.aval for v in eqn.invars[nconsts:]]


def while_const_avals(eqn) -> List[Any]:
    nconsts = eqn.params["cond_nconsts"] + eqn.params["body_nconsts"]
    return [v.aval for v in eqn.invars[:nconsts]]


def aval_sig(aval) -> Tuple[Tuple[int, ...], str]:
    return (tuple(aval.shape), str(aval.dtype))


# ---------------------------------------------------------------- StableHLO


def donated_arg_flags(stablehlo_text: str) -> Dict[int, bool]:
    """{flat arg index -> has tf.aliasing_output} from lowered StableHLO.

    jax marks every donated argument it could alias to an output with a
    `tf.aliasing_output` attribute at lowering time; argument order is
    jax's flatten order of the call's dynamic args, so the flags line up
    with `named_leaves` of the same pytrees."""
    import re

    m = re.search(
        r"func\.func\s+public\s+@main\((.*?)\)\s*->", stablehlo_text, re.S
    )
    if m is None:
        raise ValueError("could not find @main signature in lowered text")
    sig = m.group(1)
    flags: Dict[int, bool] = {}
    for am in re.finditer(
        r"%arg(\d+):\s*[^\s,{]+(?:\s*\{([^{}]*)\})?", sig
    ):
        idx = int(am.group(1))
        attrs = am.group(2) or ""
        flags[idx] = "tf.aliasing_output" in attrs
    if not flags:
        raise ValueError("no arguments parsed from @main signature")
    return flags


def reduced_axes(eqn) -> List[Tuple[Tuple[int, ...], Tuple[int, ...]]]:
    """[(operand shape, reduced axes), ...] for reduction-style eqns.

    dot_general yields one entry per contracted operand (lhs AND rhs) —
    a lane contraction on either side is a cross-lane coupling."""
    name = eqn.primitive.name
    params = eqn.params
    if not eqn.invars:
        return []
    shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
    if name in REDUCE_PRIMS:
        axes = params.get("axes")
        if axes is None:
            return []
        return [(shape, tuple(int(a) for a in axes))]
    if name in _CUMULATIVE_PRIMS:
        ax = params.get("axis")
        return [(shape, (int(ax),))] if ax is not None else []
    if name == "sort":
        ax = params.get("dimension")
        return [(shape, (int(ax),))] if ax is not None else []
    if name == "dot_general":
        (lc, rc), _batch = params["dimension_numbers"]
        out = [(shape, tuple(int(a) for a in lc))]
        if len(eqn.invars) > 1:
            rshape = tuple(getattr(eqn.invars[1].aval, "shape", ()))
            out.append((rshape, tuple(int(a) for a in rc)))
        return out
    return []
