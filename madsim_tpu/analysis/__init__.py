"""Static verifier: jaxpr-level determinism/purity rules + source linter.

Every subsystem in this repo (nemesis, triage, explorer, campaign) rests
on invariants that were, until now, enforced only by example-based twin
tests: the single-RNG funnel (every draw a pure function of seed +
occurrence index), host/device mirror completeness, schedule purity, and
the r8 narrow-dtype/donation discipline. The FoundationDB/TigerBeetle DST
lineage argues these should be *checked mechanically* — one un-mirrored
clause or one stray host callback silently breaks bit-exact replay for
every campaign checkpoint downstream. This package checks them:

  Layer 1 — jaxpr verifier (`jaxpr_check.py`): traces each workload's
  actual donated `_step_split` program (chaos + triage + coverage on)
  and walks the closed jaxpr / lowered StableHLO. Rules: `callbacks`,
  `rng-taint`, `donation`, `dtype`, `lane-independence`. One trace per
  workload is shared by EVERY jaxpr rule (jaxpr_check.get_trace).

  Layer 2 — source/mirror linter (`lint.py`): AST + introspection over
  the tree. Rules: `ambient-entropy`, `mirror`, `both-faces`,
  `layout-agreement`, `marker-hygiene`.

  Layer 3 — range certifier (`ranges.py`): interval abstract
  interpretation over the SAME shared trace. Rule: `range` — proves the
  narrow-dtype bounds (certified safe horizon >= the declared
  `narrow_horizon_us` after skew derating), i32 virtual-clock no-wrap,
  dynamic-index bounds, and rederives `_sum64`'s lane-exactness cap.
  Emits per-workload certificates into the summary JSON.

Run it:  `python -m madsim_tpu.analysis [--all] [--workload NAME]`
         (`make lint` = source rules, `make analyze` = everything,
          `--rule NAME` filters the jaxpr/range rule set).
Each run emits a summary JSON (rule -> pass/fail/violation count, plus
the Layer-3 `certificates` section) so rule counts can be tracked like
a coverage metric across BENCH rounds. Rule catalog, allowlists, and
the `# madsim: allow(<rule>)` suppression pragma: docs/analysis.md.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence

SCHEMA = "madsim-tpu-analysis/2"

# Layer-1 (per-workload, jaxpr), Layer-2 (tree-wide, source) and
# Layer-3 (per-workload, interval) rules.
JAXPR_RULES = (
    "callbacks", "rng-taint", "donation", "dtype", "lane-independence",
    "range",
)
LINT_RULES = (
    "ambient-entropy", "mirror", "both-faces", "layout-agreement",
    "marker-hygiene",
)
ALL_RULES = JAXPR_RULES + LINT_RULES

# "raft-refill" is raft's continuously batched step (the refill carry
# partition + device-resident admission queue, docs/continuous_batching.md)
# — every jaxpr/range rule runs against that carry too, so `make analyze`
# gates the refill engine exactly like the plain partitions.
# "raft-refill-sharded" additionally traces the shard_map'd MULTI-CHIP
# segment program (docs/multichip.md): the same refill rules over the
# per-device step, plus the lane-independence rule walking the whole
# sharded segment for cross-device collective primitives — allowlisted
# by EXACT primitive name (jaxpr_check.SHARD_COLLECTIVE_ALLOW, empty
# in-tree), never wholesale.
# "raft-lineage" traces the causal-lineage carry (BatchedSim(lineage=
# True), docs/causality.md): all 11 rules over the step that threads
# Lamport clocks / event ids / pool sent_eid stamps — notably rng-taint
# (the lineage counters must stay schedule-neutral: no draw may fold
# them, and the key funnel must not leak into them) and lane
# independence of the edge-ring bookkeeping (the eid prefix count runs
# over the NODE axis, never lanes).
# "raft-devloop" traces the device-resident search partition (r19,
# docs/explore.md): the refill step PLUS the in-jit generation boundary
# — corpus-ring fold/rank, MetaRng mutation, dedup, respawn — so every
# rule gates the mutator too. Notably rng-taint (the boundary's meta-key
# draws and ring scatters must never fold a lane's schedule-key chain —
# the `leaky_ring` planted fixture pins the detector), lane independence
# (the fire predicate's reduce_and is the ONLY new lane coupling,
# allowlisted by exact primitive name), donation (const must be EMPTY:
# the boundary rewrites even the admission queue), and range (ring/seen
# cursor bounds via engine.interval_hints(devloop=True)).
def _registry_targets() -> tuple:
    # the per-protocol targets come from the consolidated workload
    # registry (madsim_tpu.workloads) — speclang-generated entries
    # (twopc-gen, lease-gen, backup) are gated exactly like hand-written
    # ones; the registry import is jax-free, so building the CLI choices
    # costs nothing
    from .. import workloads as registry

    return registry.names(analysis=True)


WORKLOADS = _registry_targets() + (
    "raft-refill", "raft-refill-sharded", "raft-lineage", "raft-devloop",
)


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule violation: where it is and what it breaks."""

    rule: str
    where: str  # file:line, workload:leaf, or registry face
    detail: str

    def render(self) -> str:
        return f"[{self.rule}] {self.where}: {self.detail}"


@dataclasses.dataclass
class RuleResult:
    rule: str
    checked: int = 0  # units examined (eqns, files, clauses, tests, ...)
    violations: List[Violation] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def add(self, where: str, detail: str) -> None:
        self.violations.append(Violation(self.rule, where, detail))


def merge_results(results: Sequence[RuleResult]) -> Dict[str, RuleResult]:
    """Fold per-workload results for the same rule into one row."""
    out: Dict[str, RuleResult] = {}
    for r in results:
        cur = out.setdefault(r.rule, RuleResult(r.rule))
        cur.checked += r.checked
        cur.violations.extend(r.violations)
    return out


def summary_json(
    results: Sequence[RuleResult],
    workloads: Sequence[str],
    certificates: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The per-run summary (satellite: rule -> pass/fail/violation count,
    trackable like a coverage metric by a future BENCH round). Schema
    /2 adds the Layer-3 `certificates` section: per-workload narrow-
    field / horizon / clock / index rows plus the shared _sum64 row."""
    merged = merge_results(results)
    rules = {
        name: {
            "status": "pass" if r.ok else "fail",
            "violations": len(r.violations),
            "checked": r.checked,
        }
        for name, r in sorted(merged.items())
    }
    return {
        "schema": SCHEMA,
        # an empty rule set is NOT a pass: silent no-coverage must never
        # read as "covered everything"
        "ok": bool(merged) and all(r.ok for r in merged.values()),
        "workloads": list(workloads),
        "rules": rules,
        "certificates": dict(certificates or {}),
        "violation_details": [
            dataclasses.asdict(v)
            for r in merged.values()
            for v in r.violations
        ],
    }


def run_analysis(
    workloads: Sequence[str] = (),
    lint: bool = True,
    root: Optional[str] = None,
    log=print,
    rules: Optional[Sequence[str]] = None,
) -> Dict[str, Any]:
    """Run the selected rule set; returns the summary JSON dict.

    `workloads` names the Layer-1/Layer-3 targets (jaxpr + range rules
    share ONE trace of each one's real step program); `lint` toggles the
    Layer-2 source rules; `rules` optionally filters the per-workload
    rule set by name (e.g. ("range",) for the fast smoke prologue). The
    lint tier never TRACES anything, but its mirror/layout faces do
    import jax (compile_plan / the raft spec), so `make lint` costs a
    few seconds; only workload runs pay for tracing."""
    rule_filter = set(rules) if rules is not None else None
    if rule_filter is not None:
        unknown = rule_filter - set(JAXPR_RULES)
        if unknown:
            raise ValueError(
                f"unknown jaxpr/range rules {sorted(unknown)} "
                f"(choose from {', '.join(JAXPR_RULES)})"
            )
    results: List[RuleResult] = []
    certificates: Dict[str, Any] = {}
    if lint:
        from . import lint as lint_mod

        results.extend(lint_mod.run_source_lints(root=root, log=log))
    for name in workloads:
        from . import jaxpr_check, ranges

        trace = jaxpr_check.get_trace(name, log=log)
        layer1_rules = (
            None if rule_filter is None
            else tuple(rule_filter - {"range"})
        )
        if layer1_rules is None or layer1_rules:
            results.extend(jaxpr_check.verify_workload(
                name, log=log, trace=trace, rules=layer1_rules,
            ))
        if rule_filter is None or "range" in rule_filter:
            rres, cert = ranges.verify_ranges(trace, log=log)
            results.extend(rres)
            certificates[name] = cert
    if workloads and (rule_filter is None or "range" in rule_filter):
        from . import ranges

        sum64_res = RuleResult("range")
        certificates["_sum64"] = ranges.sum64_certificate(sum64_res)
        results.append(sum64_res)
    return summary_json(results, workloads, certificates)


def render_summary(summary: Dict[str, Any]) -> str:
    lines = []
    for name, row in summary["rules"].items():
        mark = "ok " if row["status"] == "pass" else "FAIL"
        lines.append(
            f"  {mark} {name:<18} checked {row['checked']:>5}  "
            f"violations {row['violations']}"
        )
    for wl, cert in summary.get("certificates", {}).items():
        if wl == "_sum64":
            lines.append(
                f"  cert _sum64: asserted {cert['asserted_lanes']} <= "
                f"rederived {cert['rederived_lanes']} lanes"
            )
            continue
        hz = cert.get("horizon", {})
        c_us = hz.get("certified_us")
        lines.append(
            f"  cert {wl}: {len(cert.get('fields', []))} narrow fields, "
            f"horizon certified "
            f"{'unbounded' if c_us is None else f'{c_us} us'}"
            + (
                f" (declared {hz['declared_us']} us, binding "
                f"{hz.get('binding_field')})"
                if hz.get("declared_us") is not None else ""
            )
        )
    for v in summary["violation_details"]:
        lines.append(f"    -> [{v['rule']}] {v['where']}: {v['detail']}")
    lines.append("ANALYSIS " + ("PASS" if summary["ok"] else "FAIL"))
    return "\n".join(lines)


def write_summary(summary: Dict[str, Any], path: str) -> None:
    with open(path, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
        f.write("\n")
