"""Static verifier: jaxpr-level determinism/purity rules + source linter.

Every subsystem in this repo (nemesis, triage, explorer, campaign) rests
on invariants that were, until now, enforced only by example-based twin
tests: the single-RNG funnel (every draw a pure function of seed +
occurrence index), host/device mirror completeness, schedule purity, and
the r8 narrow-dtype/donation discipline. The FoundationDB/TigerBeetle DST
lineage argues these should be *checked mechanically* — one un-mirrored
clause or one stray host callback silently breaks bit-exact replay for
every campaign checkpoint downstream. This package checks them:

  Layer 1 — jaxpr verifier (`jaxpr_check.py`): traces each workload's
  actual donated `_step_split` program (chaos + triage + coverage on)
  and walks the closed jaxpr / lowered StableHLO. Rules: `callbacks`,
  `rng-taint`, `donation`, `dtype`, `lane-independence`.

  Layer 2 — source/mirror linter (`lint.py`): AST + introspection over
  the tree. Rules: `ambient-entropy`, `mirror`, `both-faces`,
  `layout-agreement`, `marker-hygiene`.

Run it:  `python -m madsim_tpu.analysis [--all] [--workload NAME]`
         (`make lint` = source rules, `make analyze` = everything).
Each run emits a summary JSON (rule -> pass/fail/violation count) so
rule counts can be tracked like a coverage metric across BENCH rounds.
Rule catalog, allowlists, and the `# madsim: allow(<rule>)` suppression
pragma: docs/analysis.md.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence

SCHEMA = "madsim-tpu-analysis/1"

# Layer-1 (per-workload, jaxpr) and Layer-2 (tree-wide, source) rules.
JAXPR_RULES = (
    "callbacks", "rng-taint", "donation", "dtype", "lane-independence",
)
LINT_RULES = (
    "ambient-entropy", "mirror", "both-faces", "layout-agreement",
    "marker-hygiene",
)
ALL_RULES = JAXPR_RULES + LINT_RULES

WORKLOADS = ("raft", "kv", "paxos", "twopc", "chain")


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule violation: where it is and what it breaks."""

    rule: str
    where: str  # file:line, workload:leaf, or registry face
    detail: str

    def render(self) -> str:
        return f"[{self.rule}] {self.where}: {self.detail}"


@dataclasses.dataclass
class RuleResult:
    rule: str
    checked: int = 0  # units examined (eqns, files, clauses, tests, ...)
    violations: List[Violation] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def add(self, where: str, detail: str) -> None:
        self.violations.append(Violation(self.rule, where, detail))


def merge_results(results: Sequence[RuleResult]) -> Dict[str, RuleResult]:
    """Fold per-workload results for the same rule into one row."""
    out: Dict[str, RuleResult] = {}
    for r in results:
        cur = out.setdefault(r.rule, RuleResult(r.rule))
        cur.checked += r.checked
        cur.violations.extend(r.violations)
    return out


def summary_json(
    results: Sequence[RuleResult], workloads: Sequence[str]
) -> Dict[str, Any]:
    """The per-run summary (satellite: rule -> pass/fail/violation count,
    trackable like a coverage metric by a future BENCH round)."""
    merged = merge_results(results)
    rules = {
        name: {
            "status": "pass" if r.ok else "fail",
            "violations": len(r.violations),
            "checked": r.checked,
        }
        for name, r in sorted(merged.items())
    }
    return {
        "schema": SCHEMA,
        # an empty rule set is NOT a pass: silent no-coverage must never
        # read as "covered everything"
        "ok": bool(merged) and all(r.ok for r in merged.values()),
        "workloads": list(workloads),
        "rules": rules,
        "violation_details": [
            dataclasses.asdict(v)
            for r in merged.values()
            for v in r.violations
        ],
    }


def run_analysis(
    workloads: Sequence[str] = (),
    lint: bool = True,
    root: Optional[str] = None,
    log=print,
) -> Dict[str, Any]:
    """Run the selected rule set; returns the summary JSON dict.

    `workloads` names the Layer-1 targets (jaxpr rules trace each one's
    real step program); `lint` toggles the Layer-2 source rules. The
    lint tier never TRACES anything, but its mirror/layout faces do
    import jax (compile_plan / the raft spec), so `make lint` costs a
    few seconds; only workload runs pay for tracing."""
    results: List[RuleResult] = []
    if lint:
        from . import lint as lint_mod

        results.extend(lint_mod.run_source_lints(root=root, log=log))
    for name in workloads:
        from . import jaxpr_check

        results.extend(jaxpr_check.verify_workload(name, log=log))
    return summary_json(results, workloads)


def render_summary(summary: Dict[str, Any]) -> str:
    lines = []
    for name, row in summary["rules"].items():
        mark = "ok " if row["status"] == "pass" else "FAIL"
        lines.append(
            f"  {mark} {name:<18} checked {row['checked']:>5}  "
            f"violations {row['violations']}"
        )
    for v in summary["violation_details"]:
        lines.append(f"    -> [{v['rule']}] {v['where']}: {v['detail']}")
    lines.append("ANALYSIS " + ("PASS" if summary["ok"] else "FAIL"))
    return "\n".join(lines)


def write_summary(summary: Dict[str, Any], path: str) -> None:
    with open(path, "w") as f:
        json.dump(summary, f, indent=2, sort_keys=True)
        f.write("\n")
