"""Layer 3: the range certifier — interval abstract interpretation over
the traced step program.

Layers 1-2 (jaxpr_check.py, lint.py) verify SHAPE-level discipline:
dtypes, donation, purity, mirrors. What nothing checked mechanically
until now is VALUE-level safety: every `spec.narrow_horizon_us` cap —
raft's `65_535 * election_lo_us // N`, twopc's `32_767 * 1_000` — was a
hand-derived formula in a comment, enforced by an engine refusal whose
correctness rested on pencil-and-paper reasoning about adversarial fault
schedules. This module closes that gap with a classical interval
abstract interpretation (the Cousot/Astrée tradition, built for exactly
this silent-wraparound bug class) over the SAME traced donated
`_step_split` jaxpr the Layer-1 rules walk — one shared trace per
workload across all rules.

The abstract domain is a per-variable integer interval extended with two
flags: `inf` (the value may additionally be exactly the INF_US sentinel
— disarmed timers, empty pool slots, disabled chaos) and `poison` (the
value may hold sentinel-derived junk: the engine's compute-then-discard
idiom runs arithmetic over sentinel lanes and masks the result away, so
arithmetic on a maybe-sentinel operand yields values the finite interval
cannot claim). Input intervals seed from three sources: the engine's own
documented invariants (`engine.interval_hints`: live time offsets stay
below INF_GUARD — the rebase guard's exact premise), the spec's
machine-readable `rate_floors` declarations, and an interval run of the
real `_init` program (init bounds are DERIVED, not assumed). Protocol
state then iterates to a widening fixpoint over the step loop
(threshold widening: dtype boundaries, powers of two, REBASE_US).

Per-workload certificates:

  (a) narrow fields — every `spec.narrow_fields` entry is certified
      either step-CLOSED (its reachable interval never escapes the
      narrow dtype: enums, masks, ids), HARD-capped (a declared
      horizon-independent bound fits the dtype), or RATE-bounded: the
      interpreter verifies the per-event increment (`inc`) against the
      step program, and the certified safe horizon
      `(dtype_max - init_max) * floor_us // (ratchet * inc)` must cover
      the spec's declared `narrow_horizon_us` — both derated for clock
      skew through the SAME `spec.derate_horizon` the engine refusal
      uses. The hand-derived formulas become checked, not trusted.
  (b) clock no-wrap — given the rebase invariant (offsets < INF_GUARD),
      no signed-int arithmetic in the virtual-time cone (TIME taint,
      same lattice as Layer 1) can exceed int32 — including the spike /
      reorder latency adders and the exact integer-ppm skew scaling at
      the maximal traced config.
  (c) index bounds — every dynamic index site (gather / scatter /
      dynamic_slice: ring cursors, occurrence counters, pool slots) is
      statically in-bounds for its array extent. Sites lowered with
      PROMISE_IN_BOUNDS (undefined behavior when violated) MUST prove;
      sites with defined out-of-bounds semantics (FILL_OR_DROP / CLIP)
      are enumerated with status `guarded` when intervals alone cannot
      prove them.
  (d) `_sum64` — the engine's 65536-lane exactness guard is rederived
      from the traced reduction's own interval transfer
      (max_lanes = u32_max // addend_max) instead of asserted.

What is and is not provable (docs/analysis.md#layer-3): interval
analysis is non-relational. Two documented assumptions close the gaps:
the MESSAGE-COPY induction (every in-flight payload word is a copy of an
in-range protocol value; payload leaves are seeded accordingly, and a
narrow store provable only under that premise is reported with status
`assumed-copy`, never silently) and ONE-HOT routing (a dot_general whose
mask operand is 0/1-valued is modeled as selection — the engine's
documented pool-routing idiom — not as a subset sum). Violations carry a
backward witness slice naming the contributing carry leaves, same UX as
the rng-taint rule.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import numpy as np

import jax
from jax import core as jcore
import jax.numpy as jnp

from . import RuleResult
from .jaxprutil import TIME, TaintMap, _sub_jaxprs, backward_invars

NEG_INF = float("-inf")
POS_INF = float("inf")

INF_US_VAL = 2**31 - 1  # spec.INF_US
INF_GUARD_VAL = 1 << 30  # spec.INF_GUARD: live-offset / sentinel boundary


class Iv(NamedTuple):
    """One abstract value: a finite interval plus sentinel flags.

    `lo > hi` encodes an EMPTY finite part (a value that is only ever
    the sentinel). `inf` — may additionally be exactly INF_US. `poison`
    — may additionally hold sentinel-derived junk (arithmetic that ran
    over a sentinel lane before the mask discarded it); checks skip
    poisoned operands rather than report junk wraps as findings."""

    lo: Any
    hi: Any
    inf: bool = False
    poison: bool = False

    @property
    def empty(self) -> bool:
        return self.lo > self.hi

    def render(self) -> str:
        fin = "()" if self.empty else f"[{self.lo}, {self.hi}]"
        return fin + ("+INF" if self.inf else "") + (
            "+poison" if self.poison else ""
        )


EMPTY = Iv(POS_INF, NEG_INF)
BOOL_IV = Iv(0, 1)


def iv(lo, hi, inf: bool = False, poison: bool = False) -> Iv:
    return Iv(lo, hi, inf, poison)


def dtype_range(dt) -> Iv:
    dt = np.dtype(dt)
    if dt.kind == "b":
        return BOOL_IV
    if dt.kind == "u":
        return Iv(0, int(2 ** (8 * dt.itemsize) - 1))
    if dt.kind == "i":
        n = 8 * dt.itemsize
        return Iv(-(2 ** (n - 1)), 2 ** (n - 1) - 1)
    return Iv(NEG_INF, POS_INF)  # floats: unbounded


def fits(x: Iv, dt) -> bool:
    """The finite part of `x` fits dtype `dt` (sentinel flags excluded:
    INF_US is the legal i32 sentinel, poison is judged at its source)."""
    if x.empty:
        return True
    r = dtype_range(dt)
    return x.lo >= r.lo and x.hi <= r.hi


def join(a: Iv, b: Iv) -> Iv:
    return Iv(
        min(a.lo, b.lo), max(a.hi, b.hi),
        a.inf or b.inf, a.poison or b.poison,
    )


# threshold-widening ladders: dtype boundaries, small enums, powers of
# two, and the engine's own landmark constants (REBASE_US, INF_GUARD)
_HI_LADDER = (
    [0, 1, 2, 3, 7, 15, 31, 63, 127, 255, 511, 1023, 4095, 16383, 32767,
     65535, 1 << 20, 1 << 24, 1 << 28, (1 << 30) - 1, 2**31 - 1,
     2**32 - 1]
)
_LO_LADDER = (
    [0, -1, -2, -3, -7, -15, -31, -127, -128, -255, -32768, -(1 << 20),
     -(2**31)]
)


def widen(old: Iv, new: Iv) -> Iv:
    """old ∇ new: jump escaped bounds to the next ladder threshold."""
    j = join(old, new)
    lo, hi = j.lo, j.hi
    if hi > old.hi:
        hi = next((t for t in _HI_LADDER if t >= j.hi), POS_INF)
    if lo < old.lo:
        lo = next((t for t in _LO_LADDER if t <= j.lo), NEG_INF)
    return Iv(lo, hi, j.inf, j.poison)


def _flags(*xs: Iv, poison_on_inf: bool = True) -> Tuple[bool, bool]:
    """(inf, poison) for an ARITHMETIC result: sentinels don't survive
    arithmetic as sentinels — they become junk (poison)."""
    p = any(x.poison for x in xs)
    if poison_on_inf:
        p = p or any(x.inf for x in xs)
    return False, p


def _arith(xs: Sequence[Iv], lo, hi) -> Iv:
    if any(x.empty for x in xs):
        # finite part vacuous: the value is sentinel-only junk
        return Iv(POS_INF, NEG_INF, False, True)
    _, p = _flags(*xs)
    return Iv(lo, hi, False, p)


def iv_add(a: Iv, b: Iv) -> Iv:
    return _arith((a, b), a.lo + b.lo, a.hi + b.hi)


def iv_sub(a: Iv, b: Iv) -> Iv:
    return _arith((a, b), a.lo - b.hi, a.hi - b.lo)


def _mul1(x, y):
    if x in (NEG_INF, POS_INF) and y == 0:
        return 0
    if y in (NEG_INF, POS_INF) and x == 0:
        return 0
    return x * y


def iv_mul(a: Iv, b: Iv) -> Iv:
    cs = [_mul1(x, y) for x in (a.lo, a.hi) for y in (b.lo, b.hi)]
    return _arith((a, b), min(cs), max(cs))


def _trunc_div(x, m):
    if m == 0:
        return 0
    if x in (NEG_INF, POS_INF) or m in (NEG_INF, POS_INF):
        q = x / m if m != 0 else 0
        return q if q in (NEG_INF, POS_INF) else int(q)
    q = abs(x) // abs(m)
    return q if (x >= 0) == (m > 0) else -q


def iv_div(a: Iv, b: Iv, out_dt) -> Iv:
    if not a.empty and not b.empty and (b.lo > 0 or b.hi < 0):
        cs = [_trunc_div(x, y) for x in (a.lo, a.hi) for y in (b.lo, b.hi)]
        return _arith((a, b), min(cs), max(cs))
    r = dtype_range(out_dt)  # divisor may be 0: backend-defined
    return Iv(r.lo, r.hi, False, a.poison or b.poison or a.inf or b.inf)


def iv_rem(a: Iv, b: Iv, out_dt) -> Iv:
    """lax.rem: sign follows the dividend, |r| < |divisor| — but ONLY
    for a provably nonzero divisor: rem-by-zero is backend-defined (the
    same fallback iv_div takes), so a maybe-zero divisor yields the
    dtype range."""
    if a.empty or b.empty:
        return Iv(POS_INF, NEG_INF, False, True)
    m = max(abs(b.lo), abs(b.hi))
    maybe_zero = not (b.lo > 0 or b.hi < 0)
    if maybe_zero or m in (NEG_INF, POS_INF):
        r = dtype_range(out_dt)
        return Iv(r.lo, r.hi, False, a.poison or b.poison or a.inf or b.inf)
    lo = 0 if a.lo >= 0 else -(m - 1) if m > 0 else 0
    hi = 0 if a.hi <= 0 else (m - 1) if m > 0 else 0
    if a.lo >= 0:
        hi = min(hi, a.hi)  # dividend smaller than divisor is unchanged
    return _arith((a, b), lo, hi)


def _eff_hi(x: Iv):
    """Upper bound including a possible INF_US sentinel."""
    if x.inf:
        return INF_US_VAL
    return x.hi


def _eff_lo(x: Iv):
    if x.empty:
        return INF_US_VAL if x.inf else POS_INF
    return x.lo


def iv_min(a: Iv, b: Iv) -> Iv:
    lo = min(_eff_lo(a), _eff_lo(b))
    hi = min(_eff_hi(a) if not a.empty or a.inf else POS_INF,
             _eff_hi(b) if not b.empty or b.inf else POS_INF)
    inf = a.inf and b.inf and hi >= INF_US_VAL
    if inf:
        # min may be exactly the sentinel only when both sides can be
        fin_lo = min(a.lo, b.lo)
        fin_hi = max(a.hi, b.hi)  # finite candidates from either side
        return Iv(fin_lo, fin_hi, True, a.poison or b.poison)
    return Iv(lo, hi, False, a.poison or b.poison)


def iv_max(a: Iv, b: Iv) -> Iv:
    inf = a.inf or b.inf
    lo = max(_eff_lo(a) if not a.empty else NEG_INF,
             _eff_lo(b) if not b.empty else NEG_INF)
    if lo in (POS_INF,):
        lo = NEG_INF
    hi = max(a.hi, b.hi)
    if inf:
        return Iv(lo, hi, True, a.poison or b.poison)
    return Iv(lo, hi, False, a.poison or b.poison)


def _bit_hull(hi) -> int:
    """Smallest 2^k - 1 >= hi (the bitwise-or/xor upper bound)."""
    if hi in (NEG_INF, POS_INF):
        return POS_INF
    return (1 << int(hi).bit_length()) - 1


def iv_of_value(val, dt) -> Iv:
    """Interval of a concrete constant/literal, sentinel-aware for i32."""
    arr = np.asarray(val)
    if arr.size == 0:
        return EMPTY
    if arr.dtype.kind == "b":
        return Iv(int(arr.min()), int(arr.max()))
    if arr.dtype.kind not in "iu" or np.dtype(dt).kind not in "iu":
        try:
            return Iv(float(arr.min()), float(arr.max()))
        except (TypeError, ValueError):
            return dtype_range(dt)
    vals = arr.astype(np.int64)
    if np.dtype(dt) == np.int32:
        finite = vals[vals < INF_GUARD_VAL]
        has_inf = bool((vals == INF_US_VAL).any())
        guard_vals = vals[(vals >= INF_GUARD_VAL) & (vals != INF_US_VAL)]
        if guard_vals.size:  # non-sentinel large constants stay finite
            finite = vals
            has_inf = False
        if finite.size == 0:
            return Iv(POS_INF, NEG_INF, has_inf, False)
        return Iv(int(finite.min()), int(finite.max()), has_inf, False)
    return Iv(int(vals.min()), int(vals.max()))


# ------------------------------------------------------------ the machine


class IndexSite(NamedTuple):
    """One dynamic-index site examined by the bounds certificate."""

    prim: str
    mode: str
    index_iv: Iv
    allowed: Tuple[int, int]
    ok: bool
    where_eqn: Any  # enclosing top-level eqn, for the backward witness


class IntervalMap:
    """Forward interval propagation over a closed jaxpr.

    Same recursion skeleton as jaxprutil.TaintMap: sub-jaxprs (pjit /
    cond / while / scan) are entered with operand intervals, `top_eqn`
    names the enclosing top-level equation for witness slicing, and loop
    bodies iterate to a (threshold-widened) fixpoint. `on_eqn(eqn,
    in_ivs, out_ivs, top_eqn)` fires per equation on every pass; checks
    that must not double-count run on the caller's FINAL pass only."""

    def __init__(
        self,
        closed: jcore.ClosedJaxpr,
        invar_ivs: Sequence[Iv],
        on_eqn: Optional[Callable] = None,
    ) -> None:
        self.env: Dict[Any, Iv] = {}
        self.on_eqn = on_eqn
        self.index_sites: List[IndexSite] = []
        self.eqns_seen = 0
        # contraction sites modeled under the ONE-HOT assumption (dot
        # routing / masked sums): counted so the certificate can surface
        # how much of the claim rests on that premise, like assumed-copy
        self.onehot_sites = 0
        self._defs: Dict[Any, Any] = {}  # var -> defining eqn
        jaxpr = closed.jaxpr
        self._seed_consts(jaxpr, closed.consts)
        if len(invar_ivs) != len(jaxpr.invars):
            raise ValueError(
                f"{len(invar_ivs)} seed intervals for "
                f"{len(jaxpr.invars)} invars"
            )
        for v, x in zip(jaxpr.invars, invar_ivs):
            self.env[v] = x
        self._jaxpr = jaxpr
        self.top_eqn: Any = None

    def _seed_consts(self, jaxpr, consts) -> None:
        for cv, val in zip(jaxpr.constvars, consts):
            self.env[cv] = iv_of_value(val, getattr(cv.aval, "dtype", None))
        for cv in jaxpr.constvars[len(consts):]:
            self.env.setdefault(cv, dtype_range(cv.aval.dtype))

    def read(self, atom: Any) -> Iv:
        if isinstance(atom, jcore.Literal):
            return iv_of_value(atom.val, getattr(atom.aval, "dtype", None))
        got = self.env.get(atom)
        if got is None:
            return dtype_range(getattr(atom.aval, "dtype", None))
        return got

    def run(self) -> "IntervalMap":
        self.top_eqn = None
        self._run(self._jaxpr, top=True)
        return self

    # -- recursion ---------------------------------------------------------

    def _run(self, jaxpr: jcore.Jaxpr, top: bool = False) -> None:
        for eqn in jaxpr.eqns:
            if top:
                self.top_eqn = eqn
            self.eqns_seen += 1
            in_ivs = [self.read(v) for v in eqn.invars]
            name = eqn.primitive.name
            if name == "pjit":
                outs = self._run_call(eqn.params["jaxpr"], in_ivs)
            elif name == "cond":
                outs = self._run_cond(eqn, in_ivs)
            elif name == "while":
                outs = self._run_while(eqn, in_ivs)
            elif name == "scan":
                outs = self._run_scan(eqn, in_ivs)
            elif _sub_jaxprs(eqn):
                # unknown higher-order primitive: sound fallback
                for sub, consts in _sub_jaxprs(eqn):
                    self._seed_consts(sub, consts)
                    for ivr in sub.invars:
                        self.env[ivr] = dtype_range(
                            getattr(ivr.aval, "dtype", None)
                        )
                    self._run(sub)
                outs = [
                    dtype_range(getattr(ov.aval, "dtype", None))
                    for ov in eqn.outvars
                ]
            else:
                outs = self._transfer(eqn, in_ivs)
            for ov, x in zip(eqn.outvars, outs):
                self.env[ov] = x
                self._defs[ov] = eqn
            if self.on_eqn is not None:
                self.on_eqn(eqn, in_ivs, outs, self.top_eqn)

    def _run_call(self, closed_sub, in_ivs) -> List[Iv]:
        sub = closed_sub.jaxpr
        self._seed_consts(sub, closed_sub.consts)
        for v, x in zip(sub.invars, in_ivs):
            self.env[v] = x
        self._run(sub)
        return [self.read(ov) for ov in sub.outvars]

    def _run_cond(self, eqn, in_ivs) -> List[Iv]:
        branches = eqn.params["branches"]
        pred = in_ivs[0]
        outs: Optional[List[Iv]] = None
        for bi, br in enumerate(branches):
            if not pred.empty and not (pred.lo <= bi <= pred.hi):
                continue  # branch statically unreachable
            res = self._run_call(br, in_ivs[1:])
            outs = res if outs is None else [
                join(a, b) for a, b in zip(outs, res)
            ]
        if outs is None:
            outs = [
                dtype_range(getattr(ov.aval, "dtype", None))
                for ov in eqn.outvars
            ]
        return outs

    def _loop_fix(self, body, consts_ivs, carry0: List[Iv],
                  extra: Sequence[Iv] = ()) -> List[Iv]:
        dts = [getattr(v.aval, "dtype", None) for v in body.jaxpr.invars[
            len(consts_ivs): len(consts_ivs) + len(carry0)
        ]]
        carry = list(carry0)
        for i in range(12):
            res = self._run_call(body, consts_ivs + carry + list(extra))
            nxt = res[: len(carry)]
            grown = []
            for c, n, dt in zip(carry, nxt, dts):
                g = join(c, n)
                if i >= 6 and g != c:
                    # still growing after the ladder passes: jump to the
                    # dtype top so the final result IS a fixpoint (a
                    # non-fixpoint fallback would under-approximate the
                    # carry and silently miss in-loop wraps)
                    top = dtype_range(dt)
                    g = Iv(top.lo, top.hi, g.inf, g.poison)
                elif i >= 1:
                    g = widen(c, g)
                grown.append(g)
            if grown == carry:
                return res
            carry = grown
        return self._run_call(body, consts_ivs + carry + list(extra))

    def _run_while(self, eqn, in_ivs) -> List[Iv]:
        cn, bn = eqn.params["cond_nconsts"], eqn.params["body_nconsts"]
        body = eqn.params["body_jaxpr"]
        carry0 = in_ivs[cn + bn:]
        res = self._loop_fix(body, in_ivs[cn: cn + bn], carry0)
        # cond jaxpr runs for its side conditions' visit coverage
        self._run_call(eqn.params["cond_jaxpr"], in_ivs[:cn] + res)
        return [join(a, b) for a, b in zip(carry0, res)]

    def _run_scan(self, eqn, in_ivs) -> List[Iv]:
        nc, ncar = eqn.params["num_consts"], eqn.params["num_carry"]
        body = eqn.params["jaxpr"]
        consts, carry0, xs = (
            in_ivs[:nc], in_ivs[nc: nc + ncar], in_ivs[nc + ncar:],
        )
        length = int(eqn.params.get("length") or 0)
        n_body_eqns = len(body.jaxpr.eqns)
        if 0 < length * max(n_body_eqns, 1) <= 65536:
            # small static trip count: exact abstract unroll (the planted
            # wrap fixtures live here; real steps carry no scans)
            carry = list(carry0)
            ys: Optional[List[Iv]] = None
            for _ in range(length):
                res = self._run_call(body, consts + carry + xs)
                carry = res[:ncar]
                yrow = res[ncar:]
                ys = yrow if ys is None else [
                    join(a, b) for a, b in zip(ys, yrow)
                ]
            return carry + (ys or [])
        res = self._loop_fix(body, consts, list(carry0), xs)
        return [join(a, b) for a, b in zip(list(carry0) + res[ncar:],
                                           res[:ncar] + res[ncar:])]

    # -- transfer functions ------------------------------------------------

    def _transfer(self, eqn, ivs: List[Iv]) -> List[Iv]:
        name = eqn.primitive.name
        out_dt = getattr(eqn.outvars[0].aval, "dtype", None)
        h = getattr(self, f"_t_{name}", None)
        if h is not None:
            out = h(eqn, ivs, out_dt)
        else:
            out = self._t_default(eqn, ivs, out_dt)
        if not isinstance(out, list):
            out = [out]
        if len(out) != len(eqn.outvars):
            out = [
                dtype_range(getattr(ov.aval, "dtype", None))
                for ov in eqn.outvars
            ]
        return out

    def _t_default(self, eqn, ivs, out_dt):
        return [
            dtype_range(getattr(ov.aval, "dtype", None))
            for ov in eqn.outvars
        ]

    # identity / shape-only
    def _ident(self, eqn, ivs, out_dt):
        return ivs[0]

    _t_copy = _ident
    _t_device_put = _ident
    _t_reshape = _ident
    _t_squeeze = _ident
    _t_expand_dims = _ident
    _t_broadcast_in_dim = _ident
    _t_transpose = _ident
    _t_slice = _ident
    _t_rev = _ident
    _t_stop_gradient = _ident
    _t_reduce_min = _ident  # hull-preserving (incl. the inf flag)
    _t_reduce_max = _ident
    _t_sort = lambda self, eqn, ivs, out_dt: list(ivs)  # noqa: E731

    def _t_concatenate(self, eqn, ivs, out_dt):
        out = ivs[0]
        for x in ivs[1:]:
            out = join(out, x)
        return out

    _IDENT_PRIMS = frozenset({
        "device_put", "copy", "broadcast_in_dim", "reshape", "squeeze",
        "expand_dims", "stop_gradient",
    })

    def _peel(self, atom):
        """Walk `atom` back through identity ops to its source atom."""
        for _ in range(8):
            eqn = self._defs.get(atom)
            if eqn is None or eqn.primitive.name not in self._IDENT_PRIMS:
                return atom
            atom = eqn.invars[0]
        return atom

    def _affine_of(self, atom) -> Optional[Tuple[Any, int]]:
        """(base atom, offset) when `atom` is base or base +/- literal."""
        atom = self._peel(atom)
        eqn = self._defs.get(atom)
        if eqn is not None and eqn.primitive.name in ("add", "sub"):
            sign = 1 if eqn.primitive.name == "add" else -1
            a, b = eqn.invars
            for x, y, s in ((a, b, sign), (b, a, 1)):
                if sign == -1 and x is b:
                    continue  # c - x is not affine in x
                if isinstance(y, jcore.Literal):
                    c = np.asarray(y.val)
                    if c.ndim == 0 and c.dtype.kind in "iu":
                        return self._peel(x), s * int(c)
        return atom, 0

    _CMP_OPS = {"lt": "lt", "le": "le", "gt": "gt", "ge": "ge"}

    def _t_select_n(self, eqn, ivs, out_dt):
        pred, cases = ivs[0], ivs[1:]
        if not pred.empty and pred.lo == pred.hi and not pred.poison:
            k = int(pred.lo)
            if 0 <= k < len(cases):
                return cases[k]
        # branch-condition refinement for the jnp negative-index idiom
        # `select(x < c, x + d, x)`: restrict x per branch when the pred
        # compares the SAME base the branches are affine in
        if len(cases) == 2:
            refined = self._refine_binary_select(eqn, cases)
            if refined is not None:
                return refined
        live = [
            c for i, c in enumerate(cases)
            if pred.empty or pred.poison or (pred.lo <= i <= pred.hi)
        ] or cases
        out = live[0]
        for c in live[1:]:
            out = join(out, c)
        return out

    def _refine_binary_select(self, eqn, cases) -> Optional[Iv]:
        pred_eqn = self._defs.get(self._peel(eqn.invars[0]))
        if pred_eqn is None or pred_eqn.primitive.name not in self._CMP_OPS:
            return None
        xa, ca = pred_eqn.invars
        if not isinstance(ca, jcore.Literal):
            return None
        cval = np.asarray(ca.val)
        if cval.ndim != 0 or cval.dtype.kind not in "iu":
            return None
        c = int(cval)
        base = self._peel(xa)
        x = self.read(base)
        if x.empty or x.poison:
            return None
        affs = [self._affine_of(a) for a in eqn.invars[1:]]
        if any(b is not base for b, _ in affs):
            return None
        op = pred_eqn.primitive.name
        # case index 1 = pred true, 0 = pred false
        bounds = {
            "lt": ((c, x.hi), (x.lo, c - 1)),
            "le": ((c + 1, x.hi), (x.lo, c)),
            "gt": ((x.lo, c), (c + 1, x.hi)),
            "ge": ((x.lo, c - 1), (c, x.hi)),
        }[op]
        out: Optional[Iv] = None
        for (blo, bhi), (_, off) in zip(bounds, affs):
            lo, hi = max(x.lo, blo), min(x.hi, bhi)
            if lo > hi:
                continue  # branch unreachable for this x
            piece = Iv(lo + off, hi + off, x.inf, x.poison)
            out = piece if out is None else join(out, piece)
        return out

    @staticmethod
    def _uwrap(x: Iv, out_dt) -> Iv:
        """Unsigned arithmetic wraps BY DESIGN (the murmur hash chain
        lives on u32 wrap): when the mathematical interval escapes an
        unsigned dtype, fold to the full dtype range instead of letting
        hash math grow without bound. SIGNED results stay mathematical —
        a signed escape is exactly what the wrap checks must see."""
        if out_dt is None or np.dtype(out_dt).kind != "u":
            return x
        if x.empty or fits(x, out_dt):
            return x
        r = dtype_range(out_dt)
        return Iv(r.lo, r.hi, x.inf, x.poison)

    def _t_add(self, eqn, ivs, out_dt):
        return self._uwrap(iv_add(ivs[0], ivs[1]), out_dt)

    def _t_sub(self, eqn, ivs, out_dt):
        return self._uwrap(iv_sub(ivs[0], ivs[1]), out_dt)

    def _t_mul(self, eqn, ivs, out_dt):
        return self._uwrap(iv_mul(ivs[0], ivs[1]), out_dt)

    def _t_div(self, eqn, ivs, out_dt):
        return iv_div(ivs[0], ivs[1], out_dt)

    def _t_rem(self, eqn, ivs, out_dt):
        return iv_rem(ivs[0], ivs[1], out_dt)

    def _t_max(self, eqn, ivs, out_dt):
        return iv_max(ivs[0], ivs[1])

    def _t_min(self, eqn, ivs, out_dt):
        return iv_min(ivs[0], ivs[1])

    def _t_clamp(self, eqn, ivs, out_dt):
        return iv_min(iv_max(ivs[0], ivs[1]), ivs[2])

    def _t_neg(self, eqn, ivs, out_dt):
        a = ivs[0]
        return _arith((a,), -a.hi, -a.lo)

    def _t_abs(self, eqn, ivs, out_dt):
        a = ivs[0]
        if a.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        lo = 0 if a.lo <= 0 <= a.hi else min(abs(a.lo), abs(a.hi))
        return _arith((a,), lo, max(abs(a.lo), abs(a.hi)))

    def _t_sign(self, eqn, ivs, out_dt):
        a = ivs[0]
        if a.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        return Iv(
            -1 if a.lo < 0 else 0 if a.lo == 0 else 1,
            1 if a.hi > 0 else 0 if a.hi == 0 else -1,
            False, a.poison or a.inf,
        )

    @staticmethod
    def _cmp_fold(op: str, a: Iv, b: Iv) -> Iv:
        """Constant-fold a comparison when the intervals decide it (the
        modulo/negative-index guards hinge on this: `lt(rem, 0)` over a
        provably non-negative rem is FALSE, which lets select_n pick the
        un-shifted branch). Poisoned operands never fold — junk values
        are not bounded by their finite interval. A possible INF_US
        sentinel participates at the top of the effective hull."""
        if a.poison or b.poison or a.empty or b.empty:
            return BOOL_IV
        a_hi = INF_US_VAL if a.inf and a.hi < INF_US_VAL else a.hi
        b_hi = INF_US_VAL if b.inf and b.hi < INF_US_VAL else b.hi
        if op == "lt":
            if a_hi < b.lo:
                return Iv(1, 1)
            if a.lo >= b_hi:
                return Iv(0, 0)
        elif op == "le":
            if a_hi <= b.lo:
                return Iv(1, 1)
            if a.lo > b_hi:
                return Iv(0, 0)
        elif op == "gt":
            if a.lo > b_hi:
                return Iv(1, 1)
            if a_hi <= b.lo:
                return Iv(0, 0)
        elif op == "ge":
            if a.lo >= b_hi:
                return Iv(1, 1)
            if a_hi < b.lo:
                return Iv(0, 0)
        elif op == "eq":
            if a_hi < b.lo or b_hi < a.lo:
                return Iv(0, 0)
            if (a.lo == a_hi == b.lo == b_hi) and not (a.inf or b.inf):
                return Iv(1, 1)
        elif op == "ne":
            if a_hi < b.lo or b_hi < a.lo:
                return Iv(1, 1)
            if (a.lo == a_hi == b.lo == b_hi) and not (a.inf or b.inf):
                return Iv(0, 0)
        return BOOL_IV

    def _cmp(self, eqn, ivs, out_dt):
        return self._cmp_fold(eqn.primitive.name, ivs[0], ivs[1])

    _t_eq = _cmp
    _t_ne = _cmp
    _t_lt = _cmp
    _t_le = _cmp
    _t_gt = _cmp
    _t_ge = _cmp

    def _t_is_finite(self, eqn, ivs, out_dt):
        return BOOL_IV

    def _t_not(self, eqn, ivs, out_dt):
        a = ivs[0]
        dt = np.dtype(out_dt)
        if dt.kind == "b":
            if a.empty:
                return BOOL_IV
            return Iv(1 - a.hi, 1 - a.lo, False, a.poison)
        if dt.kind == "u":  # unsigned ~x = (2^N - 1) - x
            top = int(2 ** (8 * dt.itemsize) - 1)
            if a.empty or a.lo < 0 or a.hi in (POS_INF,):
                return dtype_range(out_dt)
            return _arith((a,), top - a.hi, top - a.lo)
        return _arith((a,), -a.hi - 1, -a.lo - 1)  # signed ~x = -x-1

    def _bitint(self, eqn, ivs, out_dt, kind):
        a, b = ivs[0], ivs[1]
        if np.dtype(out_dt).kind == "b":
            # monotone 0/1 fold for and/or (xor stays undecided): keeps
            # constant guard conjunctions decidable for select_n
            if (
                kind in ("and", "or") and not (a.poison or b.poison)
                and not (a.empty or b.empty)
                and 0 <= a.lo and a.hi <= 1 and 0 <= b.lo and b.hi <= 1
            ):
                if kind == "and":
                    return Iv(int(a.lo) & int(b.lo), int(a.hi) & int(b.hi))
                return Iv(int(a.lo) | int(b.lo), int(a.hi) | int(b.hi))
            return BOOL_IV
        if a.empty or b.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        if a.lo < 0 or b.lo < 0:
            r = dtype_range(out_dt)
            return Iv(r.lo, r.hi, False, a.poison or b.poison)
        _, p = _flags(a, b)
        if kind == "and":
            return Iv(0, min(a.hi, b.hi), False, p)
        return Iv(0, _bit_hull(max(a.hi, b.hi)), False, p)

    def _t_and(self, eqn, ivs, out_dt):
        return self._bitint(eqn, ivs, out_dt, "and")

    def _t_or(self, eqn, ivs, out_dt):
        return self._bitint(eqn, ivs, out_dt, "or")

    def _t_xor(self, eqn, ivs, out_dt):
        return self._bitint(eqn, ivs, out_dt, "xor")

    def _t_shift_left(self, eqn, ivs, out_dt):
        a, s = ivs[0], ivs[1]
        if a.empty or s.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        if (
            a.lo < 0 or s.lo < 0 or s.hi > 64
            or a.hi in (POS_INF,) or s.hi in (POS_INF,)
        ):
            r = dtype_range(out_dt)
            return Iv(r.lo, r.hi, False, a.poison or s.poison)
        return self._uwrap(
            _arith((a, s), int(a.lo) << int(s.lo), int(a.hi) << int(s.hi)),
            out_dt,
        )

    def _t_shift_right_logical(self, eqn, ivs, out_dt):
        a, s = ivs[0], ivs[1]
        bits = 8 * np.dtype(out_dt).itemsize
        if a.empty or s.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        smin = 0 if s.lo in (NEG_INF,) else max(int(s.lo), 0)
        smax = bits if s.hi in (POS_INF,) else min(max(int(s.hi), 0), bits)
        if a.lo < 0 or a.hi in (POS_INF,):
            # negative (or unbounded) reinterprets as a large unsigned
            return Iv(0, (2**bits - 1) >> smin, False, a.poison or s.poison)
        return _arith((a, s), int(a.lo) >> smax, int(a.hi) >> smin)

    def _t_shift_right_arithmetic(self, eqn, ivs, out_dt):
        a, s = ivs[0], ivs[1]
        if a.empty or s.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        if a.lo in (NEG_INF,) or a.hi in (POS_INF,):
            return _arith((a, s), a.lo, a.hi)  # shrinks toward 0
        smin = 0 if s.lo in (NEG_INF,) else max(int(s.lo), 0)
        smax = 63 if s.hi in (POS_INF,) else min(max(int(s.hi), 0), 63)
        cs = [int(x) >> sh for x in (a.lo, a.hi) for sh in (smin, smax)]
        return _arith((a, s), min(cs), max(cs))

    def _t_convert_element_type(self, eqn, ivs, out_dt):
        """Math-preserving: the interval claims PRE-WRAP mathematical
        values; dtype-escape is judged at the narrow-store checks, not
        silently folded back in here (a wrapping cast is exactly the
        bug class this layer exists to surface)."""
        a = ivs[0]
        if np.dtype(out_dt).kind == "b":
            return BOOL_IV
        if np.dtype(out_dt).kind in "iu" and not a.empty and not (
            a.lo in (NEG_INF,) or a.hi in (POS_INF,)
        ):
            return Iv(
                math.floor(a.lo), math.ceil(a.hi), a.inf, a.poison
            )
        return a

    def _t_iota(self, eqn, ivs, out_dt):
        dim = eqn.params["dimension"]
        return Iv(0, max(int(eqn.params["shape"][dim]) - 1, 0))

    def _t_population_count(self, eqn, ivs, out_dt):
        a = ivs[0]
        bits = 8 * np.dtype(out_dt).itemsize
        if not a.empty and 0 <= a.lo and a.hi not in (POS_INF,):
            return Iv(0, int(a.hi).bit_length(), False, a.poison or a.inf)
        return Iv(0, bits, False, a.poison)

    def _t_clz(self, eqn, ivs, out_dt):
        bits = 8 * np.dtype(out_dt).itemsize
        return Iv(0, bits, False, ivs[0].poison)

    def _t_argmin(self, eqn, ivs, out_dt):
        axes = eqn.params.get("axes", (0,))
        shape = tuple(getattr(eqn.invars[0].aval, "shape", (1,)))
        n = 1
        for a in axes:
            n *= shape[a]
        return Iv(0, max(n - 1, 0))

    _t_argmax = _t_argmin

    _MASK_TRANSPARENT = frozenset({
        "broadcast_in_dim", "reshape", "convert_element_type", "squeeze",
        "transpose", "expand_dims", "copy",
    })

    def _masked_product(self, atom) -> bool:
        """True when `atom` is (through shape-only ops) a product with a
        0/1 mask operand against a non-mask operand — the engine's
        one-hot-contraction idiom written as `(mask * x).sum(axis)`.
        Such a sum is modeled as SELECTION (at most one term survives),
        the same documented one-hot assumption as dot_general routing."""
        for _ in range(6):
            eqn = self._defs.get(atom)
            if eqn is None:
                return False
            name = eqn.primitive.name
            if name in self._MASK_TRANSPARENT:
                atom = eqn.invars[0]
                continue
            if name != "mul":
                return False
            a, b = self.read(eqn.invars[0]), self.read(eqn.invars[1])
            is_mask = [
                not x.empty and not x.poison and x.lo >= 0 and x.hi <= 1
                for x in (a, b)
            ]
            return is_mask[0] != is_mask[1]  # exactly one 0/1 operand
        return False

    def _t_reduce_sum(self, eqn, ivs, out_dt):
        a = ivs[0]
        axes = eqn.params.get("axes", ())
        shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        n = 1
        for ax in axes:
            if ax < len(shape):
                n *= shape[ax]
        if a.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        if self._masked_product(eqn.invars[0]):
            self.onehot_sites += 1
            return join(Iv(0, 0), Iv(a.lo, a.hi, False, a.poison))
        # sum of exactly n terms each in [lo, hi]
        return self._uwrap(
            _arith((a,), _mul1(n, a.lo), _mul1(n, a.hi)), out_dt,
        )

    def _t_cumsum(self, eqn, ivs, out_dt):
        # coarse: every prefix is bounded by the full-axis sum hull.
        # NOTE cumsum's param is `axis` (scalar), not reduce_sum's `axes`
        a = ivs[0]
        shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        ax = eqn.params.get("axis")
        n = shape[ax] if ax is not None and ax < len(shape) else 1
        if a.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        return self._uwrap(
            _arith(
                (a,),
                min(a.lo, _mul1(n, a.lo)), max(a.hi, _mul1(n, a.hi)),
            ),
            out_dt,
        )

    _t_cumprod = _t_default  # no precise need; sound dtype fallback
    _t_cummax = _ident
    _t_cummin = _ident

    def _t_reduce_or(self, eqn, ivs, out_dt):
        if np.dtype(out_dt).kind == "b":
            return BOOL_IV
        a = ivs[0]
        if not a.empty and a.lo >= 0:
            return Iv(0, _bit_hull(a.hi), False, a.poison or a.inf)
        return dtype_range(out_dt)

    def _t_reduce_and(self, eqn, ivs, out_dt):
        if np.dtype(out_dt).kind == "b":
            return BOOL_IV
        a = ivs[0]
        if not a.empty and a.lo >= 0:
            return Iv(0, a.hi, False, a.poison or a.inf)
        return dtype_range(out_dt)

    def _t_dot_general(self, eqn, ivs, out_dt):
        (lc, rc), _ = eqn.params["dimension_numbers"]
        lshape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
        k = 1
        for ax in lc:
            if ax < len(lshape):
                k *= lshape[ax]
        a, b = ivs[0], ivs[1]
        p = iv_mul(a, b)
        is_mask = [
            not x.empty and x.lo >= 0 and x.hi <= 1 and not x.poison
            for x in (a, b)
        ]
        if is_mask[0] != is_mask[1]:
            # the engine's routing idiom: EXACTLY ONE 0/1 mask operand
            # against a value operand selects (at most one hit per
            # output) — modeled as selection, not a subset sum. A
            # mask-x-mask contraction is a COUNT (hull [0, k]) and must
            # fall through to the k-scaled path below. Documented
            # assumption; see module docstring.
            self.onehot_sites += 1
            return join(Iv(0, 0), Iv(p.lo, p.hi, False, p.poison))
        if p.empty:
            return Iv(POS_INF, NEG_INF, False, True)
        return Iv(_mul1(k, p.lo) if p.lo < 0 else min(p.lo, _mul1(k, p.lo)),
                  _mul1(k, p.hi), False, p.poison)

    # -- dynamic indexing: the bounds certificate's scan set ---------------

    def _record_site(self, eqn, idx_iv: Iv, allowed: Tuple[int, int],
                     mode) -> None:
        ok = (
            not idx_iv.poison and not idx_iv.inf and not idx_iv.empty
            and idx_iv.lo >= allowed[0] and idx_iv.hi <= allowed[1]
        )
        self.index_sites.append(IndexSite(
            prim=eqn.primitive.name,
            mode=str(mode) if mode is not None else "none",
            index_iv=idx_iv,
            allowed=(int(allowed[0]), int(allowed[1])),  # JSON-pure ints
            ok=ok,
            where_eqn=self.top_eqn if self.top_eqn is not None else eqn,
        ))

    def _t_gather(self, eqn, ivs, out_dt):
        operand, idx = ivs[0], ivs[1]
        dn = eqn.params["dimension_numbers"]
        sizes = eqn.params["slice_sizes"]
        oshape = tuple(eqn.invars[0].aval.shape)
        allowed_hi = min(
            (oshape[d] - sizes[d] for d in dn.start_index_map), default=0
        )
        self._record_site(eqn, idx, (0, allowed_hi), eqn.params.get("mode"))
        return Iv(operand.lo, operand.hi, operand.inf, operand.poison)

    def _t_scatter(self, eqn, ivs, out_dt):
        operand, idx, upd = ivs[0], ivs[1], ivs[2]
        dn = eqn.params["dimension_numbers"]
        oshape = tuple(eqn.invars[0].aval.shape)
        allowed_hi = 0
        if dn.scatter_dims_to_operand_dims:
            # every in-tree site scatters whole windows at single
            # positions (inserted dims), so the start bound is the dim
            # extent; a windowed scatter start would need extent - size
            allowed_hi = min(
                oshape[d] - 1 for d in dn.scatter_dims_to_operand_dims
            )
        self._record_site(eqn, idx, (0, allowed_hi), eqn.params.get("mode"))
        return join(operand, upd)

    _t_scatter_add = _t_scatter

    def _t_dynamic_slice(self, eqn, ivs, out_dt):
        operand = ivs[0]
        oshape = tuple(eqn.invars[0].aval.shape)
        sizes = eqn.params["slice_sizes"]
        for d, idx in enumerate(ivs[1:]):
            self._record_site(eqn, idx, (0, oshape[d] - sizes[d]), "clamp")
        return operand

    def _t_dynamic_update_slice(self, eqn, ivs, out_dt):
        operand, upd = ivs[0], ivs[1]
        oshape = tuple(eqn.invars[0].aval.shape)
        ushape = tuple(eqn.invars[1].aval.shape)
        for d, idx in enumerate(ivs[2:]):
            self._record_site(eqn, idx, (0, oshape[d] - ushape[d]), "clamp")
        return join(operand, upd)


# ----------------------------------------------------------- seeding layer


PAYLOAD_PREFIXES = ("hot.msgs.payload", "hot.strag.payload")

# default protocol-value hull when a spec declares no rate fields: wide
# enough to exercise real arithmetic, far from i32 overflow
DEFAULT_PV = (1 << 24) - 1


def _rate_kind(entry) -> str:
    from ..tpu.spec import HardCap, RateFloor

    if isinstance(entry, RateFloor):
        return "rate"
    if isinstance(entry, HardCap):
        return "hard"
    raise TypeError(
        f"rate_floors values must be RateFloor or HardCap, got {entry!r}"
    )


def classify_narrow(spec) -> Dict[str, str]:
    """{field -> 'rate' | 'hard' | 'closed'} for spec.narrow_fields."""
    floors = dict(spec.rate_floors or {})
    out = {}
    for f in (spec.narrow_fields or {}):
        out[f] = _rate_kind(floors[f]) if f in floors else "closed"
    return out


def init_intervals(trace) -> Dict[str, Iv]:
    """Interval-run the REAL `_init` program: {leaf name -> iv} over the
    full SimState template. Init bounds are derived, not assumed."""
    from ..tpu.engine import named_leaves

    closed = trace.closed_init
    seeds = [dtype_range(v.aval.dtype) for v in closed.jaxpr.invars]
    im = IntervalMap(closed, seeds).run()
    names = [n for n, _ in named_leaves(trace.init_template)]
    out = {}
    for name, ov in zip(names, closed.jaxpr.outvars):
        out[name] = im.read(ov)
    return out


def step_seeds(
    trace,
    init_ivs: Dict[str, Iv],
    payload_override: Optional[Iv] = None,
) -> Tuple[List[Iv], Dict[str, Iv], Set[str]]:
    """(per-invar seeds, {name -> seed}, evolving-leaf names) for one
    fixpoint run over `_step_split`.

    Sources, in priority order: engine invariants (interval_hints),
    narrow-field classification (rate fields pinned at
    [init_lo, dtype_max - inc]; hard caps pinned at [init_lo, cap];
    closed fields EVOLVE from their init interval), payload leaves
    pinned at the message-copy hull, everything else protocol-owned and
    evolving from init."""
    from ..tpu.engine import interval_hints
    from ..tpu.spec import HardCap, RateFloor

    sim = trace.sim
    hints = interval_hints(
        sim,
        refill=getattr(trace, "refill", False),
        devloop=getattr(trace, "devloop", False),
    )
    kinds = classify_narrow(sim.spec)
    floors = dict(sim.spec.rate_floors or {})

    rate_caps = [
        dtype_range(sim.spec.narrow_fields[f]).hi - floors[f].inc
        for f, k in kinds.items() if k == "rate"
    ]
    pv_hi = min(rate_caps) if rate_caps else DEFAULT_PV
    payload_iv = payload_override or Iv(-pv_hi, pv_hi)

    seeds: Dict[str, Iv] = {}
    evolve: Set[str] = set()
    for name in trace.names:
        if any(name.startswith(p) for p in PAYLOAD_PREFIXES):
            seeds[name] = payload_iv
            continue
        if name in hints:
            lo, hi, may_inf = hints[name]
            seeds[name] = Iv(lo, hi, may_inf)
            continue
        leaf_field = None
        ini_key = name.replace("hot.", "", 1)
        if name.startswith("hot.node."):
            leaf_field = name[len("hot.node."):]
        elif name.startswith("hot.dur."):
            # durability watermark: every dur leaf is a SNAPSHOT of its
            # node twin (advance/reset copy node -> dur, disk recovery
            # copies dur -> node), so it carries the node field's
            # spec-declared interval — seeding it wider would let the
            # recovery copy-back break the node leaf's own certificate
            leaf_field = name[len("hot.dur."):]
            ini_key = f"node.{leaf_field}"
        ini = init_ivs.get(ini_key, None)
        if leaf_field in kinds:
            k = kinds[leaf_field]
            dt_hi = dtype_range(sim.spec.narrow_fields[leaf_field]).hi
            ini = ini or Iv(0, 0)
            if k == "rate":
                seeds[name] = Iv(
                    min(ini.lo, 0), dt_hi - floors[leaf_field].inc
                )
            elif k == "hard":
                seeds[name] = Iv(min(ini.lo, 0), floors[leaf_field].cap)
            else:
                seeds[name] = ini
                evolve.add(name)
            continue
        # plain protocol leaf: evolve from init (or dtype range when the
        # leaf has no init twin, e.g. trace-only extras)
        if ini is not None:
            seeds[name] = ini
            evolve.add(name)
        else:
            dt = None
            for n2, leaf in zip(trace.names, trace.invars_avals):
                if n2 == name:
                    dt = leaf.dtype
                    break
            seeds[name] = dtype_range(dt)
    return [seeds[n] for n in trace.names], seeds, evolve


# ------------------------------------------------------------ the fixpoint


@dataclasses.dataclass
class StepAnalysis:
    """One converged interval pass over a step program."""

    im: IntervalMap
    in_env: Dict[str, Iv]
    out_env: Dict[str, Iv]
    passes: int
    converged: bool


def fixpoint_step(
    closed,
    in_names: Sequence[str],
    out_names: Sequence[str],
    seeds: Dict[str, Iv],
    evolve: Set[str] = frozenset(),
    max_passes: int = 16,
) -> StepAnalysis:
    """Iterate the step program to a widening fixpoint over `evolve`
    leaves (in-leaf name == out-leaf name join, threshold widening from
    pass 2), then one FINAL pass whose IntervalMap carries the converged
    environment — the pass every check reads.

    Evolving seeds are intersected with their leaf's DTYPE range: the
    carry physically stores that dtype, so the at-rest value is in range
    by construction (i32 wrap-around included — unbounded counters like
    log indices stabilize at full i32 instead of diverging; whether a
    WRAP on the way there matters is the narrow-store and TIME-cone
    checks' business, which read the mathematical pre-store intervals)."""
    in_avals = {
        n: v.aval for n, v in zip(in_names, closed.jaxpr.invars)
    }
    cur = dict(seeds)
    out_pos = {n: i for i, n in enumerate(out_names)}
    passes = 0
    converged = False
    for i in range(max_passes):
        passes += 1
        im = IntervalMap(closed, [cur[n] for n in in_names]).run()
        outs = [im.read(ov) for ov in closed.jaxpr.outvars]
        changed = False
        for n in evolve:
            j = out_pos.get(n)
            if j is None:
                continue
            new = join(cur[n], outs[j])
            dtr = dtype_range(in_avals[n].dtype)
            if i >= 4 and new != cur[n]:
                # still growing after the ladder passes: an unbounded
                # counter — jump straight to its dtype top
                new = Iv(dtr.lo, dtr.hi, new.inf, new.poison)
            elif i >= 1:
                new = widen(cur[n], new)
            if not new.empty:
                new = Iv(
                    max(new.lo, dtr.lo), min(new.hi, dtr.hi),
                    new.inf, new.poison,
                )
            if new != cur[n]:
                cur[n] = new
                changed = True
        if not changed:
            converged = True
            break
    im = IntervalMap(closed, [cur[n] for n in in_names]).run()
    outs = [im.read(ov) for ov in closed.jaxpr.outvars]
    return StepAnalysis(
        im=im, in_env=cur,
        out_env={n: outs[j] for n, j in out_pos.items()},
        passes=passes, converged=converged,
    )


def time_tainted_eqns(closed, in_names, time_leaves) -> Set[int]:
    """{id(eqn)} whose inputs carry TIME taint (jaxprutil lattice)."""
    masks = [TIME if n in time_leaves else 0 for n in in_names]
    hit: Set[int] = set()

    def visit(eqn, read):
        if any(read(v) & TIME for v in eqn.invars):
            hit.add(id(eqn))

    TaintMap(closed, masks).run(visit)
    return hit


_OVERFLOW_PRIMS = frozenset({"add", "sub", "mul"})


def time_overflow_findings(
    closed,
    in_names: Sequence[str],
    seeds: Dict[str, Iv],
    time_leaves: Set[str],
    res: RuleResult,
    where: str,
) -> Tuple[int, int]:
    """Certificate (b): no signed-int arithmetic in the TIME cone can
    exceed its dtype, given the seeded invariants. Sentinel-poisoned
    operands are skipped (the engine's compute-then-discard idiom);
    everything else that wraps is a finding with a backward witness."""
    tainted = time_tainted_eqns(closed, in_names, time_leaves)
    checked_ids: Set[int] = set()
    # keyed by eqn id, joined across visits: a loop body's wrap may only
    # appear on a LATER unrolled/widened visit of the same equation
    flagged_by_id: Dict[int, Tuple[Any, Any, str, Iv]] = {}

    def on_eqn(eqn, in_ivs, out_ivs, top_eqn):
        if id(eqn) not in tainted or eqn.primitive.name not in _OVERFLOW_PRIMS:
            return
        dt = getattr(eqn.outvars[0].aval, "dtype", None)
        if dt is None or np.dtype(dt).kind != "i":
            return
        checked_ids.add(id(eqn))
        out = out_ivs[0]
        if out.poison or out.empty:
            return
        # an operand already saturating its dtype is no longer a bounded
        # time quantity (an unbounded counter that data-flowed past a
        # clock): arithmetic on it wraps vacuously, and the FIRST wrap
        # in any real chain fires on bounded operands upstream
        full = dtype_range(dt)
        for x in in_ivs:
            if not x.empty and (x.lo <= full.lo or x.hi >= full.hi):
                return
        if not fits(out, dt):
            prev = flagged_by_id.get(id(eqn))
            joined = out if prev is None else join(prev[3], out)
            flagged_by_id[id(eqn)] = (eqn, top_eqn, str(dt), joined)

    im = IntervalMap(closed, [seeds[n] for n in in_names], on_eqn=on_eqn)
    im.run()
    checked = len(checked_ids)
    flagged = len(flagged_by_id)
    for eqn, top_eqn, dt, out in flagged_by_id.values():
        src = top_eqn if top_eqn is not None else eqn
        hits = backward_invars(closed.jaxpr, list(src.invars))
        names = [in_names[i] for i in hits if in_names[i] in time_leaves][:6]
        res.add(
            where,
            f"virtual-clock wrap: `{eqn.primitive.name}` on a time-typed "
            f"value reaches {out.render()} — outside {dt} (reaches "
            f"{names or ['<local>']}); the i32-us clock must never wrap "
            "within the horizon",
        )
    return checked, flagged


def index_bound_rows(
    analysis: StepAnalysis,
    closed,
    in_names: Sequence[str],
    res: RuleResult,
    where: str,
) -> List[Dict[str, Any]]:
    """Certificate (c): every dynamic index statically in-bounds.
    PROMISE_IN_BOUNDS sites must prove (out-of-bounds there is undefined
    behavior the engine merely trusted until now); defined-semantics
    sites (fill/drop/clip) that intervals alone cannot prove are
    enumerated with status `guarded`."""
    rows = []
    for site in analysis.im.index_sites:
        hits = backward_invars(closed.jaxpr, list(site.where_eqn.invars))
        witness = [
            in_names[i] for i in hits
            if not in_names[i].startswith("const.")
        ][:4]
        promised = "PROMISE_IN_BOUNDS" in site.mode
        status = (
            "proved" if site.ok
            else "violated" if promised else "guarded"
        )
        rows.append({
            "prim": site.prim,
            "mode": site.mode,
            "index": [
                None if site.index_iv.lo in (NEG_INF, POS_INF)
                else int(site.index_iv.lo),
                None if site.index_iv.hi in (NEG_INF, POS_INF)
                else int(site.index_iv.hi),
            ],
            "allowed": list(site.allowed),
            "status": status,
            "witness": witness,
        })
        if status == "violated":
            res.add(
                where,
                f"dynamic index not provably in-bounds: `{site.prim}` "
                f"(mode {site.mode}) index {site.index_iv.render()} vs "
                f"allowed [0, {site.allowed[1]}] — out of bounds here is "
                f"UNDEFINED; witness {witness or ['<local>']}",
            )
    return rows


# -------------------------------------------------------- narrow-field rows


def narrow_field_rows(
    trace,
    analysis: StepAnalysis,
    init_ivs: Dict[str, Iv],
    res: RuleResult,
    where: str,
    reanalyze: Callable[[Iv], StepAnalysis],
) -> List[Dict[str, Any]]:
    """Certificate (a): one row per narrow field. A store that escapes
    its dtype under the message-copy hull is re-analyzed with payloads
    pinned to the field's own dtype range: if it then fits, the row is
    `assumed-copy` (provable only under the copy induction — reported,
    never silent); if it still escapes, the narrowing is UNSOUND and the
    rule fires with a witness naming the field."""
    from ..tpu.spec import HardCap, RateFloor, derate_horizon

    sim = trace.sim
    spec = sim.spec
    kinds = classify_narrow(spec)
    floors = dict(spec.rate_floors or {})
    closed = trace.closed_step
    out_pos = {n: i for i, n in enumerate(trace.out_names)}
    rows: List[Dict[str, Any]] = []
    retry_cache: Dict[Tuple[int, int], StepAnalysis] = {}

    for f, dt in (spec.narrow_fields or {}).items():
        leaf = f"hot.node.{f}"
        kind = kinds[f]
        dtr = dtype_range(dt)
        seed = analysis.in_env.get(leaf, dtr)
        store = analysis.out_env.get(leaf)
        ini = init_ivs.get(f"node.{f}", Iv(0, 0))
        row: Dict[str, Any] = {
            "field": f,
            "dtype": str(jnp.dtype(dt)),
            "kind": kind,
            "init": [int(ini.lo), int(ini.hi)] if not ini.empty else None,
            "certified_horizon_us": None,  # None = unbounded
        }
        if store is None:
            res.add(where, f"narrow field {f}: no matching carry out leaf")
            row["status"] = "violated"
            rows.append(row)
            continue
        budget_hi = dtr.hi
        budget_lo = dtr.lo
        if kind == "rate":
            fl: RateFloor = floors[f]
            budget_hi = seed.hi + fl.inc  # growth bound: <= inc per event
            row.update(
                floor_us=fl.floor_us, ratchet=fl.ratchet, inc=fl.inc,
            )
            init_hi = max(int(ini.hi), 0) if not ini.empty else 0
            row["certified_horizon_us"] = (
                (dtr.hi - init_hi) * fl.floor_us // (fl.ratchet * fl.inc)
            )
        elif kind == "hard":
            hc: HardCap = floors[f]
            row["hard_cap"] = hc.cap
            if hc.cap > dtr.hi:
                res.add(
                    where,
                    f"narrow field {f}: declared HardCap {hc.cap} does "
                    f"not fit {row['dtype']} (max {dtr.hi})",
                )
                row["status"] = "violated"
                rows.append(row)
                continue
            budget_hi = hc.cap
        # a maybe-INF_US sentinel does NOT fit a narrow store: the cast
        # would wrap 2^31-1, so the inf flag disqualifies alongside
        # poison (fits() tolerates the sentinel only for i32 leaves)
        ok = (
            not store.empty and store.lo >= budget_lo
            and store.hi <= budget_hi and not store.poison
            and not store.inf
        )
        row["store"] = (
            None if store.empty
            else [
                None if store.lo in (NEG_INF,) else int(store.lo),
                None if store.hi in (POS_INF,) else int(store.hi),
            ]
        )
        if ok:
            # (a rate field's one-step growth budget reaches dtype_max
            # exactly — that is the certified boundary, not a wrap)
            row["status"] = "proved"
            rows.append(row)
            continue
        # retry under the copy premise: payloads bounded like the field
        # itself (for rate fields, the same pre-wrap budget the state
        # seed uses — a copied value is a copy of an IN-BUDGET value)
        retry_hi = dtr.hi - floors[f].inc if kind == "rate" else dtr.hi
        key = (int(dtr.lo), int(retry_hi))
        retry = retry_cache.get(key)
        if retry is None:
            retry = reanalyze(Iv(dtr.lo, retry_hi))
            retry_cache[key] = retry
        store2 = retry.out_env.get(leaf, store)
        seed2 = retry.in_env.get(leaf, seed)
        budget2_hi = budget_hi
        if kind == "rate":
            budget2_hi = seed2.hi + floors[f].inc
        ok2 = (
            not store2.empty and store2.lo >= budget_lo
            and store2.hi <= budget2_hi and not store2.poison
            and not store2.inf
        )
        if ok2:
            row["status"] = "assumed-copy"
            row["store"] = [int(store2.lo), int(store2.hi)]
            rows.append(row)
            continue
        row["status"] = "violated"
        outvar = closed.jaxpr.outvars[out_pos[leaf]]
        hits = backward_invars(closed.jaxpr, [outvar])
        witness = [
            trace.names[i] for i in hits
            if trace.names[i].startswith("hot.node.")
            or any(trace.names[i].startswith(p) for p in PAYLOAD_PREFIXES)
        ][:6]
        res.add(
            where,
            f"narrow field {f} ({row['dtype']}, {kind}) may wrap: store "
            f"interval {store2.render()} escapes "
            f"[{budget_lo}, {budget2_hi}]"
            + (" (growth exceeds the declared per-event inc)"
               if kind == "rate" else
               " and no rate floor is declared for it")
            + f"; witness {witness or [leaf]}",
        )
        rows.append(row)
    return rows


# ------------------------------------------------------------- certificates


def horizon_certificate(trace, rows: List[Dict[str, Any]],
                        res: RuleResult, where: str) -> Dict[str, Any]:
    """Fold the per-field rows into the workload's horizon certificate:
    min certified horizon over rate fields, derated for the traced
    config's clock skew through spec.derate_horizon (the engine's own
    helper), and checked against BOTH the declared narrow_horizon_us and
    the traced config's horizon_us."""
    from ..tpu.spec import derate_horizon

    sim = trace.sim
    declared = sim.spec.narrow_horizon_us
    ppm = (
        sim.config.nem_skew_max_ppm if sim.config.nem_skew_enabled else 0
    )
    finite = [
        (r["certified_horizon_us"], r["field"]) for r in rows
        if r.get("certified_horizon_us") is not None
    ]
    certified = min(finite)[0] if finite else None
    binding = min(finite)[1] if finite else None
    cert = {
        "declared_us": declared,
        "certified_us": certified,
        "binding_field": binding,
        "skew_max_ppm": ppm,
        "derated_declared_us": (
            None if declared is None else derate_horizon(declared, ppm)
        ),
        "derated_certified_us": (
            None if certified is None else derate_horizon(certified, ppm)
        ),
        "config_horizon_us": sim.config.horizon_us,
    }
    ok = True
    if certified is not None and declared is None:
        ok = False
        res.add(
            where,
            f"rate-bounded narrow fields (binding: {binding}, certified "
            f"{certified} us) but the spec declares no narrow_horizon_us "
            "— the engine refusal is not guarding this table",
        )
    if certified is not None and declared is not None:
        if derate_horizon(declared, ppm) > derate_horizon(certified, ppm):
            ok = False
            res.add(
                where,
                f"declared narrow_horizon_us={declared} exceeds the "
                f"certified safe horizon {certified} us (binding field: "
                f"{binding}) — the hand-derived cap over-promises",
            )
        if sim.config.horizon_us > derate_horizon(certified, ppm):
            ok = False
            res.add(
                where,
                f"traced config horizon_us={sim.config.horizon_us} "
                f"exceeds the derated certified horizon "
                f"{derate_horizon(certified, ppm)} us",
            )
    cert["ok"] = ok
    return cert


def sum64_certificate(res: RuleResult) -> Dict[str, Any]:
    """Certificate (d): rederive `_sum64`'s lane-exactness bound from
    the traced reduction's interval transfer instead of asserting it.
    Each u32 partial sums L addends; the lo half's addends reach
    2^16 - 1, so exactness needs L <= u32_max // (2^16 - 1). The
    engine's asserted cap must be <= the rederived one, and the guard
    must actually exist at the asserted cap."""
    from ..tpu.engine import _sum64

    asserted = 65536
    x = jax.ShapeDtypeStruct((asserted,), jnp.int32)
    closed = jax.make_jaxpr(lambda v: _sum64(v))(x)
    addend_hi = 0
    sum_his: List[int] = []
    reduce_ok = True

    def on_eqn(eqn, in_ivs, out_ivs, top_eqn):
        nonlocal addend_hi, reduce_ok
        if eqn.primitive.name != "reduce_sum":
            return
        a, out = in_ivs[0], out_ivs[0]
        addend_hi = max(addend_hi, int(a.hi))
        sum_his.append(int(out.hi))
        dt = eqn.outvars[0].aval.dtype
        if not fits(out, dt):
            reduce_ok = False

    IntervalMap(
        closed, [Iv(0, 2**31 - 1)], on_eqn=on_eqn,
    ).run()
    rederived = (2**32 - 1) // max(addend_hi, 1)
    guard_fires = False
    try:
        _sum64(jax.ShapeDtypeStruct((asserted + 1,), jnp.int32))
    except ValueError:
        guard_fires = True  # the lane-cap refusal, raised pre-trace
    except Exception:
        # any OTHER error means the guard no longer fires before the
        # first array op (e.g. it was removed and the ShapeDtypeStruct
        # probe hit real array code) — report it as a certificate
        # failure, never crash the analysis run
        guard_fires = False
    ok = reduce_ok and asserted <= rederived and guard_fires
    res.checked += 1
    if not ok:
        res.add(
            "_sum64",
            f"lane-exactness bound broken: asserted {asserted}, "
            f"rederived {rederived} (addend max {addend_hi}), partials "
            f"exact: {reduce_ok}, guard fires at cap+1: {guard_fires}",
        )
    return {
        "asserted_lanes": asserted,
        "rederived_lanes": rederived,
        "addend_max": addend_hi,
        "partials_exact": reduce_ok,
        "guard_fires_past_cap": guard_fires,
        "ok": ok,
    }


# ----------------------------------------------------------------- entry


def verify_ranges(trace, log=None) -> Tuple[List[RuleResult], Dict[str, Any]]:
    """Run the `range` rule over one workload's shared trace: the
    interval fixpoint, certificates (a)-(c), and the summary rows.
    Returns ([RuleResult], certificate dict for the summary JSON)."""
    res = RuleResult("range")
    name = trace.name
    where = f"{name}:_step_split"
    if log:
        log(f"[analysis] range: interval fixpoint over {name} ...")

    init_ivs = init_intervals(trace)
    _, seed_env, evolve = step_seeds(trace, init_ivs)
    closed = trace.closed_step

    analysis = fixpoint_step(
        closed, trace.names, trace.out_names, seed_env, evolve,
    )
    res.checked += analysis.im.eqns_seen

    def reanalyze(payload_iv: Iv) -> StepAnalysis:
        _, s_env, ev = step_seeds(
            trace, init_ivs, payload_override=payload_iv,
        )
        return fixpoint_step(
            closed, trace.names, trace.out_names, s_env, ev,
        )

    rows = narrow_field_rows(
        trace, analysis, init_ivs, res, where, reanalyze,
    )
    res.checked += len(rows)
    horizon = horizon_certificate(trace, rows, res, where)

    time_leaves = trace.time_leaves
    checked_t, flagged_t = time_overflow_findings(
        closed, trace.names, analysis.in_env, time_leaves, res, where,
    )
    res.checked += checked_t

    idx_rows = index_bound_rows(analysis, closed, trace.names, res, where)
    res.checked += len(idx_rows)

    cert = {
        "workload": name,
        "fields": rows,
        "horizon": horizon,
        "clock": {
            "time_eqns_checked": checked_t,
            "overflows": flagged_t,
            "offset_invariant_hi": INF_GUARD_VAL - 1,
            "fixpoint_passes": analysis.passes,
            "converged": analysis.converged,
        },
        "assumptions": {
            # premise-dependence made visible, never silent: copy rows
            # carry status assumed-copy; one-hot-modeled contraction
            # sites are counted here
            "one_hot_selection_sites": analysis.im.onehot_sites,
            "assumed_copy_fields": sum(
                1 for r in rows if r["status"] == "assumed-copy"
            ),
        },
        "indices": {
            "sites": len(idx_rows),
            "violated": sum(1 for r in idx_rows if r["status"] == "violated"),
            "guarded": sum(1 for r in idx_rows if r["status"] == "guarded"),
            "rows": idx_rows,
        },
    }
    if log:
        log(
            f"[analysis] range {name}: {len(rows)} narrow fields, "
            f"{checked_t} time eqns, {len(idx_rows)} index sites, "
            f"{len(res.violations)} violations"
        )
    return [res], cert
