"""Layer 1: jaxpr-level determinism/purity rules over the real step program.

For each workload this module builds the ACTUAL sweep configuration the
fuzzer runs — every nemesis clause enabled (crash+wipe, partition, clog,
spike, loss, dup, reorder, skew), the buggify straggler tail on, triage
ctl threaded, coverage instrumented — and traces the donated
`_step_split` program abstractly (ShapeDtypeStructs; no device compute,
no XLA compile). Five rules walk the closed jaxpr / lowered StableHLO:

  callbacks          no host-sync primitive anywhere in the step (a
                     single io_callback/debug.print re-serializes every
                     chunked dispatch on the host and is invisible in
                     tests that only check values).
  rng-taint          (a) schedule purity: any murmur mix touched by
                     `key0` taint must see NOTHING but key0 and the
                     occurrence counters — fault schedules stay pure
                     functions of (seed, clause, k), the invariant
                     `FaultPlan.schedule` mirrors. (b) funnel
                     containment: the per-step key chain's own update
                     must derive from the key alone — protocol state
                     must never leak INTO the RNG funnel carry.
                     (Handler draws keyed off the step chain may fold
                     event identity — e.g. twopc's per-tid vote coin —
                     that is per-seed deterministic and allowed.)
  donation           the hot+cold carry is fully donated/aliased in the
                     lowered program and ConstState leaves never are;
                     plus the structural split: const = {key0, ctl,
                     skew_ppm} exactly, and the `_run` while-loop carry
                     is hot+cold only (key0 leaking back into the carry
                     is the regression the r8 split can silently lose).
  dtype              narrow_fields leaves hold their declared at-rest
                     dtype across the loop carry, time_fields stay i32,
                     and NO float arithmetic touches a time-typed value
                     (the integer-ppm skew bug as a checked rule class).
  lane-independence  no reduction over the lane (batch) axis inside the
                     step outside the allowlist — lanes must stay
                     embarrassingly parallel or sharded sweeps and
                     chunking stop being bit-identical.

All rules fail loudly with leaf/eqn names. Allowlists and suppression:
docs/analysis.md.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import RuleResult
from .jaxprutil import (
    CALLBACK_PRIMS,
    KEY,
    KEY2,
    SALT,
    STATE,
    TIME,
    TaintMap,
    aval_sig,
    backward_invars,
    donated_arg_flags,
    find_while_eqns,
    is_mix_mul,
    iter_eqns,
    reduced_axes,
    while_carry_avals,
    while_const_avals,
)

# default lane count for abstract tracing: a small prime that no
# structural dimension (node count, pool slots, payload width, clause
# rows) uses, so "shape[0] == LANES" identifies the lane axis reliably
LANES = 13
# admission-queue length for the refill trace: a distinct prime, so the
# queue axis can never be mistaken for the lane axis
REFILL_ADMISSIONS = 29

# the refill step's sanctioned lane-axis primitives (engine._refill_apply):
# the retirement rank (cumsum), the admitted count (reduce_sum) and the
# any-retired cond predicate (reduce_or) couple lanes ONLY in the
# seed->lane ASSIGNMENT — never in any admission's trajectory, which stays
# the pure per-seed function chunking/sharding bit-identity needs (the
# refill determinism tests pin exactly that). Everything else in the
# refill step remains subject to the lane rule.
REFILL_LANE_ALLOW = ("cumsum", "reduce_sum", "reduce_or")

# the device-loop step adds ONE lane-axis primitive on top of the refill
# set: the generation-boundary fire predicate `jnp.all(done)` (engine
# `_devloop_apply`) lowers to reduce_and. Like the refill reductions it
# couples lanes only in WHEN the boundary fires — never inside any
# admission's trajectory, which the devloop bit-identity tests pin
# against the host loop lane by lane.
DEVLOOP_LANE_ALLOW = REFILL_LANE_ALLOW + ("reduce_and",)

# cross-device collective primitives: the multi-chip determinism contract
# (docs/multichip.md) says the shard_map'd refill segment contains ZERO of
# these — each device owns its sub-queue/lanes/result buffers and gathers
# happen at segment end on the host. Any future exception must be
# allowlisted by EXACT primitive name in SHARD_COLLECTIVE_ALLOW (empty
# in-tree), never by disabling the walk.
# real jaxpr PRIMITIVE names only (eqn.primitive.name): API sugar like
# jnp/pmean/pshuffle and grouped collectives (axis_index_groups is a
# psum/all_gather PARAM) all lower to these underlying primitives, so
# they are caught via this set — listing non-primitive names here would
# only misstate the coverage.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "ppermute", "all_gather", "all_to_all",
    "reduce_scatter", "pbroadcast", "pgather",
})
SHARD_COLLECTIVE_ALLOW: Tuple[str, ...] = ()

# occurrence counters: the ONLY non-key values a schedule draw may touch
NEUTRAL_LEAVES = frozenset({
    "hot.nem.crash_k", "hot.nem.part_k", "hot.nem.clog_k",
    "hot.nem.spike_k", "hot.nem.reconfig_k", "hot.nem.disk_k",
})
# the schedule key root: ConstState.key0 on the plain partition, carried
# as hot.key0 on the refill partition (a refilled lane adopts a new root)
KEY0_LEAVES = frozenset({"const.key0", "hot.key0"})
KEYCHAIN_LEAVES = frozenset({"hot.key"})

# time-typed leaves (virtual-us offsets): the operands the integer-ppm
# rule guards — float arithmetic on any of these loses microseconds
TIME_LEAF_NAMES = frozenset({
    "hot.clock", "hot.timer", "hot.chaos_at", "hot.part_at",
    "hot.msgs.deliver", "hot.strag.deliver",
    "hot.nem.clog_at", "hot.nem.spike_at", "hot.nem.reconfig_at",
    "hot.nem.disk_at",
    "cold.violation_at", "const.ctl.h_off",
})


def full_fault_plan():
    """Every clause kind at once: the maximal step program (what a storm
    campaign actually compiles; any rule that holds here holds for every
    subset config, which compiles strictly less machinery)."""
    from .. import nemesis as nem

    return nem.FaultPlan(
        name="analysis-full",
        clauses=(
            nem.Crash(wipe_rate=0.3),
            nem.Partition(),
            nem.LinkClog(),
            nem.LatencySpike(),
            nem.MsgLoss(rate=0.05),
            nem.Duplicate(rate=0.05),
            nem.Reorder(rate=0.1, window_us=50_000),
            nem.ClockSkew(max_ppm=50_000),
            nem.Reconfig(),
            nem.DiskFault(torn_rate=0.5),
        ),
    )


def spec_factories() -> Dict[str, object]:
    # one map, derived from the consolidated workload registry
    # (madsim_tpu.workloads) — includes wal (the one hand spec with a
    # durable plane: its hot.dur.* watermark leaves and recovery
    # copy-back are range-certified here) and every speclang-generated
    # entry, which is gated by the same rules as the hand-written specs
    from .. import workloads as registry

    return registry.spec_factories(analysis=True)


def build_verified_sim(
    name: str, lanes: int = LANES, refill: bool = False,
    lineage: bool = False, devloop: bool = False,
):
    """(sim, state, hot, cold, const) — all abstract (ShapeDtypeStructs).

    `state` is the eval_shape of the real `_init` (or, with `refill`, of
    the real `init_refill` with a REFILL_ADMISSIONS-deep queue — the
    continuous-batching carry partition; with `devloop`, of the real
    `init_devloop` — the device-resident search partition, whose step
    additionally contains the whole generation boundary: fold, rank,
    mutate, respawn; with `lineage`, of the causal-lineage carry);
    hot/cold/const the real `split_state` partition. Nothing touches a
    device."""
    from ..nemesis import OCC_CLAUSES, RATE_CLAUSES
    from ..tpu import nemesis as tpun
    from ..tpu.engine import (
        BatchedSim, TriageCtl, make_devloop_plan, split_state,
    )
    from ..tpu.spec import SimConfig

    factories = spec_factories()
    if name not in factories:
        raise ValueError(
            f"unknown workload {name!r} (choose from {sorted(factories)})"
        )
    spec = factories[name]()
    cfg = tpun.compile_plan(
        full_fault_plan(),
        SimConfig(
            horizon_us=2_000_000,
            loss_rate=0.05,
            buggify_delay_rate=0.01,  # straggler side pool in the program
        ),
    )
    plan = None
    if devloop:
        # trace capacities: the population reuses the REFILL_ADMISSIONS
        # prime (same queue axis, same role); ring/seen/window sizes are
        # small distinct values none of which equals LANES, so the lane
        # rule keeps identifying the lane axis by shape alone
        plan = make_devloop_plan(
            cfg, pop=REFILL_ADMISSIONS, top_k=7, seen_cap=64,
        )
    sim = BatchedSim(
        spec, cfg, triage=True, coverage=True, lineage=lineage,
        devloop=plan,
    )
    seeds = jax.ShapeDtypeStruct((lanes,), jnp.uint32)
    if refill or devloop:
        A = REFILL_ADMISSIONS
        qseeds = jax.ShapeDtypeStruct((A,), jnp.uint32)
        qctl = TriageCtl(
            off=jax.ShapeDtypeStruct((A,), jnp.int32),
            occ=jax.ShapeDtypeStruct((A, len(OCC_CLAUSES)), jnp.int32),
            rate_scale=jax.ShapeDtypeStruct(
                (A, len(RATE_CLAUSES)), jnp.float32
            ),
            h_epoch=jax.ShapeDtypeStruct((A,), jnp.int32),
            h_off=jax.ShapeDtypeStruct((A,), jnp.int32),
        )
        if devloop:
            # window G=2: the smallest shape that exercises BOTH boundary
            # branches (next_gen on gen 0, window_done on gen G-1)
            state = jax.eval_shape(
                lambda s, c: sim.init_devloop(s, lanes, c, window=2),
                qseeds, qctl,
            )
        else:
            state = jax.eval_shape(
                lambda s, c: sim.init_refill(s, lanes, c), qseeds, qctl,
            )
    else:
        state = jax.eval_shape(sim._init, seeds)
    hot, cold, const = split_state(state)
    return sim, state, hot, cold, const


def _leaf_names(hot, cold, const) -> List[str]:
    from ..tpu.engine import named_leaves

    return (
        [n for n, _ in named_leaves(hot, "hot")]
        + [n for n, _ in named_leaves(cold, "cold")]
        + [n for n, _ in named_leaves(const, "const")]
    )


def _time_leaves(sim) -> Set[str]:
    names = set(TIME_LEAF_NAMES)
    for f in sim.spec.time_fields:
        names.add(f"hot.node.{f}")
    return names


# refill admission inputs: the queue's seed column and the cursor /
# per-lane admission indices are schedule ROOTS (which work runs next),
# not trajectory material — neutral like the occurrence counters, so a
# refilled lane's re-init draws read as the pure (seed, site, k)
# functions they are. The queue's ctl rows stay STATE like every ctl.
REFILL_NEUTRAL = frozenset({
    "const.queue.seeds", "cold.refill.cursor", "cold.refill.admitted",
})

# device-loop search cursors: the same schedule-root argument extended to
# the in-jit generation boundary. The queue seed column now RIDES THE
# CARRY (the boundary rewrites it from the mutated ring, so it is
# hot.queue.seeds on this partition), and the boundary derives the next
# generation's seeds from the MetaRng cursor (meta_key/counter — the
# host MetaRng's murmur chain, deliberately disjoint from every lane's
# schedule key), the fresh-seed counter, and the corpus ring's seed
# column + row count (parent picks gather through them). All of these
# decide WHICH work runs next, never how any admission's trajectory
# unfolds — exactly the refill-queue argument. Everything else in the
# DevLoop carry (ring ctl rows, novelty bits, coverage union, dedup
# hashes, archives) stays STATE: those values flow into ctl rows and
# result buffers, and the rng-taint rule must keep proving they never
# reach a schedule mix.
DEVLOOP_NEUTRAL = frozenset({
    "hot.queue.seeds",
    "cold.loop.meta_key", "cold.loop.counter", "cold.loop.next_fresh",
    "cold.loop.ring_n", "cold.loop.ring_seed",
})


def _invar_masks(names: Sequence[str], time_leaves: Set[str]) -> List[int]:
    masks = []
    for n in names:
        if n in KEY0_LEAVES:
            masks.append(KEY)
        elif n in KEYCHAIN_LEAVES:
            masks.append(KEY2)
        elif n in NEUTRAL_LEAVES or n in REFILL_NEUTRAL or n in DEVLOOP_NEUTRAL:
            masks.append(0)
        elif n in time_leaves:
            masks.append(STATE | TIME)
        else:
            masks.append(STATE)
    return masks


# ------------------------------------------------------------------- rules


def check_callbacks(closed, where: str = "step") -> RuleResult:
    """No host-sync primitives anywhere in the program."""
    res = RuleResult("callbacks")
    for eqn, depth in iter_eqns(closed.jaxpr):
        res.checked += 1
        name = eqn.primitive.name
        if name in CALLBACK_PRIMS or "callback" in name:
            res.add(
                where,
                f"host-sync primitive `{name}` at nesting depth {depth} — "
                "the jitted step must never round-trip to the host",
            )
    return res


def check_rng_taint(
    closed,
    invar_names: Sequence[str],
    time_leaves: Set[str],
    where: str = "step",
    key_out_index: Optional[int] = None,
    salt_values: Sequence[int] = (),
) -> RuleResult:
    """Schedule purity + funnel containment over the murmur mix eqns.

    The refill trace passes this check STRICTLY too: the admission
    inputs a refilled lane's chain root derives from (queue seed column,
    cursor, admission ids) are classified neutral (REFILL_NEUTRAL — they
    are schedule roots, like the occurrence counters), retirement FLAGS
    shed their taint at the bool boundary (control flow doesn't launder
    values; jaxprutil.TaintMap), and the re-init select then carries the
    chain key alone."""
    res = RuleResult("rng-taint")
    masks = _invar_masks(invar_names, time_leaves)
    # taint per mix eqn is ACCUMULATED across visits and judged after the
    # walk: loop bodies are re-propagated to a fixpoint, so the taint a
    # mix sees can GROW on pass >= 2 — gating on first visit would throw
    # the later, larger mask away and miss carry-borne violations
    mix_taint: Dict[int, Tuple[object, int, object]] = {}
    tm = TaintMap(closed, masks, salt_values=salt_values)

    def visit(eqn, read):
        if not is_mix_mul(eqn):
            return
        m = 0
        for iv in eqn.invars:
            m |= read(iv)
        prev = mix_taint.get(id(eqn))
        if prev is not None:
            m |= prev[1]
        # witness via the enclosing TOP-LEVEL eqn: an offending mix
        # inside an inline-jitted helper still names real leaves
        mix_taint[id(eqn)] = (eqn, m, tm.top_eqn)

    tm.run(visit)
    res.checked += len(mix_taint)
    flagged = [
        (eqn, m, top)
        for eqn, m, top in mix_taint.values()
        if (m & KEY) and (m & (STATE | TIME | KEY2 | SALT))
    ]
    for eqn, m, top in flagged:
        src = top if top is not None else eqn
        hits = backward_invars(closed.jaxpr, list(src.invars))
        offenders = [
            invar_names[i]
            for i in hits
            if masks[i] & (STATE | TIME)
        ][:6]
        res.add(
            where,
            "schedule-purity violation: a key0-rooted draw mixes "
            f"non-schedule material (taint {m:#x}; reaches "
            f"{offenders or ['<literal/chain>']}) — fault schedules must "
            "be pure functions of (seed, clause, occurrence)",
        )
    if key_out_index is not None:
        ov = closed.jaxpr.outvars[key_out_index]
        m = tm.read(ov)
        res.checked += 1
        if m & (STATE | TIME | SALT | KEY):
            res.add(
                where,
                f"RNG funnel contaminated: the step's key-chain update "
                f"carries taint {m:#x} (expected the chain key alone) — "
                "protocol/config state must never feed the PRNG carry",
            )
    return res


def check_dtype(
    closed,
    sim,
    hot,
    out_template,
    invar_names: Sequence[str],
    where: str = "step",
) -> RuleResult:
    """Narrow at-rest dtypes across the carry + no float-on-time math."""
    res = RuleResult("dtype")
    h2 = out_template[0]
    narrow = dict(sim.spec.narrow_fields or {})
    for f, dt in narrow.items():
        res.checked += 1
        want = str(jnp.dtype(dt))
        got_in = str(getattr(hot.node, f).dtype)
        got_out = str(getattr(h2.node, f).dtype)
        if got_in != want:
            res.add(
                where,
                f"node.{f} enters the carry as {got_in}, declared {want}",
            )
        if got_out != want:
            res.add(
                where,
                f"node.{f} leaves the step as {got_out}, declared {want} — "
                "the at-rest narrowing was silently widened in the carry",
            )
    for f in sim.spec.time_fields:
        res.checked += 1
        got = str(getattr(h2.node, f).dtype)
        if got != "int32":
            res.add(
                where,
                f"time field node.{f} is {got} in the carry — time-typed "
                "values must stay i32 (epoch-rebased offsets)",
            )

    # float-on-time: forward TIME taint; a floating-dtype output of an
    # ARITHMETIC/conversion eqn with a TIME-tainted operand is the
    # f32-skew bug class. Call primitives (their bodies are recursed
    # into, so real arithmetic inside is still seen) and dtype-preserving
    # data movement (a gather whose INDEX is time-derived moves float
    # data, it doesn't do float math on a time value) are excluded —
    # the refill step's cond/gather/select plumbing made the
    # every-primitive form fire on pure routing.
    time_leaves = _time_leaves(sim)
    masks = _invar_masks(invar_names, time_leaves)
    hits: List[Tuple[object, str]] = []
    from .jaxprutil import _sub_jaxprs

    move_prims = frozenset({
        "select_n", "gather", "scatter", "scatter-add", "concatenate",
        "broadcast_in_dim", "transpose", "reshape", "squeeze",
        "expand_dims", "slice", "dynamic_slice", "dynamic_update_slice",
        "copy", "rev",
    })

    def visit(eqn, read):
        if eqn.primitive.name in move_prims or _sub_jaxprs(eqn):
            return
        tainted = any(read(iv) & TIME for iv in eqn.invars)
        if not tainted:
            return
        for ov in eqn.outvars:
            dt = getattr(ov.aval, "dtype", None)
            if dt is not None and jnp.issubdtype(dt, jnp.floating):
                hits.append((eqn, str(dt)))

    TaintMap(closed, masks).run(visit)
    res.checked += 1
    for eqn, dt in hits:
        res.add(
            where,
            f"float arithmetic on a time-typed value: `{eqn.primitive.name}`"
            f" -> {dt} with TIME-tainted input — f32 loses integer "
            "microseconds past 2^24 us; use exact int math "
            "(scale_delay_ppm)",
        )
    return res


def check_lane_independence(
    closed,
    lanes: int = LANES,
    where: str = "step",
    allow: Sequence[str] = (),
) -> RuleResult:
    """No reduction over the lane axis anywhere in the step.

    A reduced/contracted/sorted dimension of size `lanes` is flagged in
    ANY axis position (not just axis 0): `lanes` is chosen as a small
    prime no structural dimension uses, so a transposed lane axis is
    still caught. dot_general is checked on BOTH contracted operands.
    `allow` names primitives permitted to cross lanes (empty by default:
    decode-side reductions live in `_summary_reduction`, outside the
    step)."""
    res = RuleResult("lane-independence")
    allowed = set(allow)
    for eqn, depth in iter_eqns(closed.jaxpr):
        entries = reduced_axes(eqn)
        if not entries:
            continue
        res.checked += 1
        for shape, axes in entries:
            hit = [
                a for a in axes if a < len(shape) and shape[a] == lanes
            ]
            if not hit:
                continue
            if eqn.primitive.name in allowed:
                continue
            res.add(
                where,
                f"cross-lane reduction: `{eqn.primitive.name}` over axis "
                f"{hit[0]} of {shape} (the lane-sized dim) at depth {depth}"
                " — lanes must stay independent for sharding/chunking "
                "bit-identity",
            )
            break
    return res


def check_collectives(
    closed,
    where: str = "sharded-segment",
    allow: Sequence[str] = SHARD_COLLECTIVE_ALLOW,
) -> RuleResult:
    """No cross-device collective primitive anywhere in the shard_map'd
    refill segment (recursing every sub-jaxpr: the shard_map body, its
    while_loop, the retire-and-admit cond). Folded into the
    lane-independence rule: a cross-device collective is exactly a
    cross-lane coupling lifted to the mesh axis, and it breaks the same
    bit-identity contract. `allow` names permitted primitives EXACTLY
    (empty in-tree)."""
    res = RuleResult("lane-independence")
    allowed = set(allow)
    for eqn, depth in iter_eqns(closed.jaxpr):
        res.checked += 1
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS and name not in allowed:
            res.add(
                where,
                f"cross-device collective `{name}` at nesting depth "
                f"{depth} inside the sharded refill segment — devices "
                "must stay independent between segment boundaries "
                "(allowlist by exact primitive in "
                "SHARD_COLLECTIVE_ALLOW if ever intended)",
            )
    return res


def check_step_donation(
    step_fn,
    hot,
    cold,
    const,
    hot_names: Sequence[str],
    cold_names: Sequence[str],
    const_names: Sequence[str],
    where: str = "step",
    res: Optional[RuleResult] = None,
) -> RuleResult:
    """Lower `step_fn(hot, cold, const)` with the carry donated and assert
    every hot+cold leaf is aliased to an output while no const leaf is."""
    res = res or RuleResult("donation")
    step = jax.jit(step_fn, donate_argnums=(0, 1))
    text = step.lower(hot, cold, const).as_text()
    flags = donated_arg_flags(text)
    names = list(hot_names) + list(cold_names) + list(const_names)
    res.checked += len(names)
    for i, n in enumerate(names):
        donated = flags.get(i, False)
        is_const = n.startswith("const.")
        if not is_const and not donated:
            res.add(
                where,
                f"carry leaf {n} is NOT donated/aliased in the lowered "
                "step — the sweep would allocate a second copy of it per "
                "dispatch segment",
            )
        if is_const and donated:
            # unreachable under current jax semantics (const is outside
            # donate_argnums here, and only donated args get aliasing
            # attributes) — kept as a sanity check of that lowering
            # assumption; the load-bearing const protection is the
            # while-carry check (check_run_carry) + the structural split
            res.add(
                where,
                f"ConstState leaf {n} IS donated/aliased — loop-invariant "
                "operands must never rotate through the donation",
            )
    return res


def check_run_carry(
    closed_run,
    hot,
    cold,
    const,
    where: str = "run",
    res: Optional[RuleResult] = None,
) -> RuleResult:
    """The sweep's while-loop carry must be hot+cold (+counter) exactly,
    with every const leaf entering as a loop-invariant operand."""
    from ..tpu.engine import named_leaves

    res = res or RuleResult("donation")
    res.checked += 1
    whiles = find_while_eqns(closed_run.jaxpr)
    if not whiles:
        res.add(where, "no while_loop found — sweep structure changed?")
        return res
    weqn = whiles[0]
    got = sorted(aval_sig(a) for a in while_carry_avals(weqn))
    want = sorted(
        [aval_sig(x) for _, x in named_leaves(hot)]
        + [aval_sig(x) for _, x in named_leaves(cold)]
        + [((), "int32")]  # the loop counter
    )
    if got != want:
        from collections import Counter

        extra = Counter(got) - Counter(want)
        missing = Counter(want) - Counter(got)
        res.add(
            where,
            "while-loop carry != hot+cold (+counter): extra "
            f"{sorted(extra.elements())}, missing {sorted(missing.elements())}"
            " — a ConstState leaf leaked into (or a carry leaf fell out "
            "of) the sweep carry",
        )
    cdict: Dict[Tuple, int] = {}
    for a in while_const_avals(weqn):
        sig = aval_sig(a)
        cdict[sig] = cdict.get(sig, 0) + 1
    for n, x in named_leaves(const, "const"):
        sig = aval_sig(x)
        if cdict.get(sig, 0) <= 0:
            res.add(
                where,
                f"{n} is not a loop-invariant operand of the sweep "
                "while-loop (missing from the body consts)",
            )
        else:
            cdict[sig] -= 1
    return res


def check_donation(sim, state, hot, cold, const, where: str = "step") -> RuleResult:
    """Donated/aliased carry coverage + the hot/cold/const structural split.

    Three partitions are legal (engine.split_state): the plain sweep's
    const = {key0, ctl, skew_ppm}; the refill sweep's inverted split —
    key0/ctl/skew IN the carry (a refilled lane rewrites them from its
    new admission) with the admission queue as the only const; and the
    device-loop sweep, where NOTHING is loop-invariant — the generation
    boundary rewrites even the admission queue from the mutated corpus
    ring, so the queue rides the carry, the DevLoop search state rides
    cold, and const is EMPTY. Which one applies is read off the state's
    own structure."""
    from ..tpu.engine import carry_partition

    res = RuleResult("donation")
    devloop = getattr(state, "loop", None) is not None
    refill = state.refill is not None
    # the engine's own introspection hook IS the name source: if the
    # split and the hook ever disagree, this rule is checking the wrong
    # partition and should fail loudly with it
    part = carry_partition(state)
    hot_names = [f"hot.{n}" for n in part["hot"]]
    cold_names = [f"cold.{n}" for n in part["cold"]]
    const_names = [f"const.{n}" for n in part["const"]]

    res.checked += 1
    if devloop:
        # (1'') device-loop structural split: const is EMPTY (everything
        # the boundary can rewrite must be donated), the queue seed/ctl
        # rows ride hot (the boundary respawns them from the ring), and
        # the DevLoop search carry rides cold
        if const_names:
            res.add(
                where,
                "device-loop const must be empty — the generation "
                f"boundary rewrites everything, but found {const_names}",
            )
        if "hot.queue.seeds" not in hot_names:
            res.add(
                where,
                "device-loop carry without hot.queue.seeds — the "
                "boundary cannot respawn the next generation's queue",
            )
        if "hot.key0" not in hot_names:
            res.add(
                where,
                "device-loop carry without hot.key0 — a respawned lane "
                "cannot adopt its admission's schedule root",
            )
        if not any(n.startswith("cold.loop.") for n in cold_names):
            res.add(
                where,
                "device-loop state without cold.loop.* DevLoop leaves",
            )
    elif refill:
        # (1') refill structural split: the queue is const, the (now
        # per-admission) key0/ctl ride the carry, and no queue leaf may
        # leak into the donated carry
        if "const.queue.seeds" not in const_names:
            res.add(where, "refill state without a const admission queue")
        if "hot.key0" not in hot_names:
            res.add(
                where,
                "refill carry without hot.key0 — a refilled lane cannot "
                "adopt its admission's schedule root",
            )
        if sim.triage and not any(
            n.startswith("hot.ctl.") for n in hot_names
        ):
            res.add(where, "refill carry without per-lane TriageCtl rows")
        leaked = [
            n for n in hot_names + cold_names
            if n.split(".", 1)[1].startswith("queue")
        ]
        if leaked:
            res.add(where, f"queue leaves leaked into the carry: {leaked}")
    else:
        # (1) structural split: const is exactly key0 + ctl (+ skew_ppm)
        if sim.triage and not any(
            n.startswith("const.ctl.") for n in const_names
        ):
            res.add(where, "TriageCtl leaves missing from ConstState")
        if "const.key0" not in const_names:
            res.add(
                where,
                "key0 is not in ConstState — if it rides the carry, donation "
                "rotates the schedule root through fresh buffers every segment",
            )
        for n in ("key0", "ctl"):
            leaked = [
                h for h in hot_names + cold_names
                if h.split(".", 1)[1].startswith(n)
            ]
            if leaked:
                res.add(
                    where,
                    f"loop-invariant leaf leaked into the carry: {leaked}",
                )

    # (2) lowered donation flags on the real _step_split program
    check_step_donation(
        lambda h, c, k: sim._step_split(h, c, k),
        hot, cold, const, hot_names, cold_names, const_names, where, res,
    )

    # (3) the production `_run` while-loop carries hot+cold ONLY
    run_fn = getattr(type(sim)._run, "__wrapped__", None)
    if run_fn is not None:
        closed_run = jax.make_jaxpr(lambda st: run_fn(sim, st, 8))(state)
    else:  # trace through the jitted wrapper (shows up as a pjit eqn)
        closed_run = jax.make_jaxpr(lambda st: sim._run(st, 8))(state)
    check_run_carry(closed_run, hot, cold, const, where, res)
    return res


# --------------------------------------------------- the shared trace


import dataclasses


@dataclasses.dataclass
class WorkloadTrace:
    """ONE abstract trace of a workload's real programs, shared by EVERY
    jaxpr-level rule (purity, taint, donation, dtype, lane, range).

    Tracing is the dominant cost of a Layer-1/Layer-3 run (seconds per
    workload; the rules themselves are milliseconds of jaxpr walking),
    so it is hoisted here and cached per (workload, lanes): the CLI, the
    range certifier and the test suite all reuse the same trace instead
    of re-tracing per rule. Donation additionally lowers the step — that
    stays inside check_donation, the only consumer of StableHLO."""

    name: str
    lanes: int
    sim: Any
    state: Any
    hot: Any
    cold: Any
    const: Any
    closed_step: Any  # jaxpr of the donated _step_split (the sweep body)
    out_template: Any  # eval_shape of _step_split: (h2, c2, rec)
    closed_init: Any  # jaxpr of _init (runs once, draws schedule roots)
    init_template: Any  # eval_shape of _init: the full SimState
    names: List[str]  # invar leaf names (hot./cold./const. prefixed)
    out_names: List[str]  # outvar leaf names (hot./cold./rec. prefixed)
    invars_avals: List[Any]
    time_leaves: Set[str]
    refill: bool = False  # tracing the continuous-batching partition?
    devloop: bool = False  # tracing the device-resident search partition?
    sharded: bool = False  # also tracing the shard_map'd segment?
    closed_sharded: Any = None  # jaxpr of the multi-chip segment program


_TRACE_CACHE: Dict[Tuple[str, int], WorkloadTrace] = {}


def get_trace(name: str, lanes: int = LANES, log=None) -> WorkloadTrace:
    """The per-workload trace, built once per process (abstract only:
    ShapeDtypeStructs, no XLA compile, no device). A `<workload>-refill`
    name traces the SAME workload's continuously batched step (the
    refill carry partition + a REFILL_ADMISSIONS-deep queue) — the
    target `make analyze` runs every rule against alongside the plain
    partitions."""
    from ..tpu.engine import named_leaves

    key = (name, lanes)
    cached = _TRACE_CACHE.get(key)
    if cached is not None:
        return cached
    sharded = name.endswith("-sharded")
    base = name[: -len("-sharded")] if sharded else name
    refill = base.endswith("-refill")
    base = base[: -len("-refill")] if refill else base
    devloop = base.endswith("-devloop")
    base = base[: -len("-devloop")] if devloop else base
    lineage = base.endswith("-lineage")
    base = base[: -len("-lineage")] if lineage else base
    if sharded and not refill:
        raise ValueError(
            f"{name!r}: only the refill step has a sharded trace target"
        )
    if log:
        log(f"[analysis] tracing {name} step program (L={lanes}) ...")
    sim, state, hot, cold, const = build_verified_sim(
        base, lanes=lanes, refill=refill, lineage=lineage, devloop=devloop,
    )
    closed_sharded = None
    if sharded:
        # the multi-chip segment: the EXACT engine._sharded_segment
        # program, traced abstractly over a 1-device mesh (the mesh size
        # changes block shapes, never the primitive vocabulary — a
        # collective would appear in this jaxpr at any device count)
        import numpy as _np

        mesh = jax.sharding.Mesh(_np.array(jax.devices()[:1]), ("devices",))
        stacked = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct((1,) + x.shape, x.dtype), state
        )
        closed_sharded = jax.make_jaxpr(
            lambda st: sim._sharded_segment(mesh, 8)(st)
        )(stacked)
    trace = _finish_trace(
        sim, state, hot, cold, const, name=name, lanes=lanes,
        refill=refill, devloop=devloop, sharded=sharded,
        closed_sharded=closed_sharded,
    )
    _TRACE_CACHE[key] = trace
    return trace


def _finish_trace(
    sim, state, hot, cold, const, name: str, lanes: int,
    refill: bool = False, devloop: bool = False, sharded: bool = False,
    closed_sharded=None,
) -> WorkloadTrace:
    """The shared trace-construction tail (abstract jaxprs + leaf-name
    registries) over an already-built sim/state partition — split out of
    get_trace so `trace_sim` can certify ARBITRARY (spec, config) pairs,
    not just the in-tree workload registry."""
    from ..tpu.engine import named_leaves

    closed = jax.make_jaxpr(sim._step_split)(hot, cold, const)
    out_template = jax.eval_shape(sim._step_split, hot, cold, const)
    seeds = jax.ShapeDtypeStruct((lanes,), jnp.uint32)
    closed_init = jax.make_jaxpr(sim._init)(seeds)
    init_template = jax.eval_shape(sim._init, seeds)
    h2, c2, rec = out_template
    out_names = (
        [n for n, _ in named_leaves(h2, "hot")]
        + [n for n, _ in named_leaves(c2, "cold")]
        + [n for n, _ in named_leaves(rec, "rec")]
    )
    return WorkloadTrace(
        name=name, lanes=lanes, sim=sim, state=state,
        hot=hot, cold=cold, const=const,
        closed_step=closed, out_template=out_template,
        closed_init=closed_init, init_template=init_template,
        names=_leaf_names(hot, cold, const),
        out_names=out_names,
        invars_avals=(
            [x for _, x in named_leaves(hot, "hot")]
            + [x for _, x in named_leaves(cold, "cold")]
            + [x for _, x in named_leaves(const, "const")]
        ),
        time_leaves=_time_leaves(sim),
        refill=refill,
        devloop=devloop,
        sharded=sharded,
        closed_sharded=closed_sharded,
    )


def trace_sim(sim, name: str = "custom", lanes: int = LANES) -> WorkloadTrace:
    """A WorkloadTrace over an ARBITRARY BatchedSim (uncached, abstract —
    ShapeDtypeStructs only, no compile, no device).

    The autotuner's Tier-B gate re-runs the range certifier on every
    TUNED config through this before it is cached
    (madsim_tpu/tune.py, docs/tuning.md): the in-tree `get_trace`
    registry pins the shipped configs, but a tuned pool layout is a new
    program and must re-earn its range certificate."""
    from ..tpu.engine import split_state

    seeds = jax.ShapeDtypeStruct((lanes,), jnp.uint32)
    state = jax.eval_shape(sim._init, seeds)
    hot, cold, const = split_state(state)
    return _finish_trace(sim, state, hot, cold, const, name=name, lanes=lanes)


def verify_workload(
    name: str, lanes: int = LANES, log=print,
    trace: Optional[WorkloadTrace] = None,
    rules: Optional[Sequence[str]] = None,
) -> List[RuleResult]:
    """Run the selected Layer-1 jaxpr rules over workload `name`'s shared
    trace (the lane-width reuse trick: a small fixed lane count keeps
    tracing seconds-fast and identifies the lane axis unambiguously).
    `rules=None` runs them all; a filter skips unselected checks
    entirely — notably `donation`, the only rule that LOWERS the step to
    StableHLO rather than just walking the trace."""
    from ..tpu.engine import COV_SALT, named_leaves

    trace = trace or get_trace(name, lanes=lanes, log=log)
    want = None if rules is None else set(rules)

    def on(rule: str) -> bool:
        return want is None or rule in want

    sim = trace.sim
    closed = trace.closed_step
    out_template = trace.out_template
    names = trace.names
    time_leaves = trace.time_leaves

    where = f"{name}:_step_split"
    results = []
    if on("callbacks"):
        results.append(check_callbacks(closed, where))
    if on("rng-taint"):
        # outvar index of the step's key-chain update (h2.key)
        h2_names = [n for n, _ in named_leaves(out_template[0], "hot")]
        key_out = h2_names.index("hot.key")
        results.append(check_rng_taint(
            closed, names, time_leaves, where,
            key_out_index=key_out, salt_values=(COV_SALT,),
        ))
    if on("dtype"):
        results.append(check_dtype(
            closed, sim, trace.hot, out_template, names, where,
        ))
    if on("lane-independence"):
        results.append(check_lane_independence(
            closed, trace.lanes, where,
            allow=(
                DEVLOOP_LANE_ALLOW if trace.devloop
                else REFILL_LANE_ALLOW if trace.refill
                else ()
            ),
        ))
        if trace.sharded:
            # the multi-chip face of the same rule: the whole shard_map'd
            # segment program must contain zero cross-device collectives
            # (exact-primitive allowlist, empty in-tree)
            results.append(check_collectives(
                trace.closed_sharded, f"{name}:_sharded_segment",
            ))
    if on("donation"):
        results.append(check_donation(
            sim, trace.state, trace.hot, trace.cold, trace.const,
            f"{name}:_run",
        ))
    # init runs once per sweep but draws the schedule roots: callbacks +
    # purity hold there too (seeds are the key root at init)
    closed_init = trace.closed_init
    init_names = ["const.key0"] + [
        f"const.ctl.{i}" for i in range(len(closed_init.jaxpr.invars) - 1)
    ]
    if on("callbacks"):
        results.append(check_callbacks(closed_init, f"{name}:_init"))
    if on("rng-taint"):
        results.append(check_rng_taint(
            closed_init,
            init_names[: len(closed_init.jaxpr.invars)],
            set(),
            f"{name}:_init",
            salt_values=(COV_SALT,),
        ))
    if log:
        bad = sum(len(r.violations) for r in results)
        log(
            f"[analysis] {name}: {len(closed.jaxpr.eqns)} step eqns, "
            f"{bad} violations"
        )
    return results
