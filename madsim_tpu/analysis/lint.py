"""Layer 2: source-level invariant linter (AST + registry introspection).

Five rules over the tree itself — the invariants that live BETWEEN files,
where no single test's assertions can see them:

  ambient-entropy    no wall-clock / ambient-entropy calls (`time.time`,
                     `random.*`, `np.random.*`, `os.urandom`, `secrets`,
                     `uuid.uuid4`, `datetime.now`) inside `madsim_tpu/`
                     outside the allowlist: `core/interpose.py` (the
                     patcher that VIRTUALIZES these inside sims) and
                     `real/` (wall-clock mode by definition). Measurement
                     clocks (`time.perf_counter`/`monotonic`) are allowed
                     — they never feed simulation state. Suppress a
                     deliberate use with `# madsim: allow(ambient-entropy)`.
  mirror             every fault clause exists on all three faces — pure
                     schedule, host NemesisDriver, device `nem_*` knobs —
                     cross-checked against the enumerable registries in
                     `madsim_tpu/nemesis.py` (SCHEDULE_CLAUSES,
                     MESSAGE_CLAUSES, CLAUSE_EVENT_KINDS, ...). The same
                     rule also covers the workload registry mirror
                     (`check_workload_registry`): every `WorkloadEntry`
                     row resolves to real factories and host twins, the
                     consumer modules actually read the registry instead
                     of re-growing private lists, and speclang-generated
                     rows' `SPECLANG_DIGEST` pins match the current spec
                     sources (with `emit --check` run in-process).
  both-faces         every field folded into the device coverage bitmap
                     is also folded by the pure trace mirror
                     (`explore.cov_index`), counted against the
                     `engine.COV_FIELDS` registry — the rule behind every
                     recorded cov_digest staying replayable.
  layout-agreement   the LAYOUT dtype table in tests/test_state_layout.py
                     agrees with the raft spec's `narrow_fields` in both
                     directions.
  marker-hygiene     tests flagged long-running (by name pattern or a
                     `~Ns` runtime note in their docstring) carry
                     slow/deep/chaos markers — tier-1 runs `-m 'not
                     slow'` under an 870 s budget, and an unmarked slow
                     test is a time bomb.

All file/line findings honor the inline pragma
`# madsim: allow(<rule>)` on the offending line or the line above.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import RuleResult

PRAGMA_RE = re.compile(r"#\s*madsim:\s*allow\(([a-z0-9_,\- ]+)\)")

# files (repo-relative, forward slashes) exempt from ambient-entropy
ENTROPY_ALLOWED_FILES = (
    "madsim_tpu/core/interpose.py",  # the virtualization layer itself
)
ENTROPY_ALLOWED_DIRS = (
    "madsim_tpu/real/",  # real-socket/wall-clock mode by definition
)

# long-running test-name indicators (marker-hygiene)
LONG_NAME_RE = re.compile(
    r"(?:^|_)(?:soak|cross_process|fresh_runtimes?|two_hour|acceptance)"
    r"(?:_|$)"
)
# "~45 s"-style runtime note in a test docstring
RUNTIME_NOTE_RE = re.compile(r"[~≈]\s*(\d+)\s*s\b")
RUNTIME_NOTE_FLOOR_S = 30
HYGIENE_MARKS = {"slow", "deep", "chaos"}


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _read(path: str) -> Tuple[str, List[str]]:
    with open(path, "r") as f:
        src = f.read()
    return src, src.splitlines()


def _pragma_allows(lines: List[str], lineno: int, rule: str) -> bool:
    """True if line `lineno` (1-based) or the line above carries
    `# madsim: allow(<rule>)` naming this rule."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = PRAGMA_RE.search(lines[ln - 1])
            if m and rule in [s.strip() for s in m.group(1).split(",")]:
                return True
    return False


def _py_files(root: str, rel: str) -> List[str]:
    out = []
    base = os.path.join(root, rel)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return out


# ------------------------------------------------------------ ambient entropy


def check_entropy_file(path: str, root: str = "") -> RuleResult:
    """Scan one python file for wall-clock/ambient-entropy calls."""
    res = RuleResult("ambient-entropy")
    rel = os.path.relpath(path, root).replace(os.sep, "/") if root else path
    src, lines = _read(path)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        res.add(f"{rel}:{e.lineno}", f"unparseable: {e.msg}")
        return res

    mod_alias: Dict[str, str] = {}  # local name -> stdlib module
    direct: Dict[str, str] = {}  # local name -> dotted origin (forbidden)
    dt_class: Set[str] = set()  # `from datetime import datetime` aliases

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                top = a.name.split(".")[0]
                if a.name == "numpy.random" and a.asname:
                    # `import numpy.random as npr`: npr IS the rng module
                    mod_alias[a.asname] = "numpy.random"
                elif top in (
                    "time", "random", "os", "secrets", "uuid", "datetime",
                    "numpy",
                ):
                    mod_alias[a.asname or top] = top
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                local = a.asname or a.name
                if mod == "time" and a.name in ("time", "time_ns"):
                    direct[local] = f"time.{a.name}"
                elif mod == "os" and a.name == "urandom":
                    direct[local] = "os.urandom"
                elif mod == "random":
                    direct[local] = f"random.{a.name}"
                elif mod == "secrets":
                    direct[local] = f"secrets.{a.name}"
                elif mod == "uuid" and a.name in ("uuid1", "uuid4"):
                    direct[local] = f"uuid.{a.name}"
                elif mod == "datetime" and a.name in ("datetime", "date"):
                    dt_class.add(local)
                elif mod == "numpy" and a.name == "random":
                    mod_alias[local] = "numpy.random"
                elif mod == "numpy.random":
                    direct[local] = f"numpy.random.{a.name}"

    def chain_of(func) -> List[str]:
        parts: List[str] = []
        while isinstance(func, ast.Attribute):
            parts.append(func.attr)
            func = func.value
        if isinstance(func, ast.Name):
            parts.append(func.id)
        else:
            return []
        return parts[::-1]

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        res.checked += 1
        bad: Optional[str] = None
        ch = chain_of(node.func)
        if ch:
            root_mod = mod_alias.get(ch[0])
            dotted = ".".join(ch)
            if root_mod == "time" and ch[-1] in ("time", "time_ns"):
                bad = dotted
            elif root_mod == "random" and len(ch) >= 2:
                bad = dotted
            elif root_mod == "numpy" and len(ch) >= 3 and ch[1] == "random":
                bad = dotted
            elif root_mod == "numpy.random" and len(ch) >= 2:
                bad = dotted
            elif root_mod == "os" and ch[-1] == "urandom":
                bad = dotted
            elif root_mod == "secrets" and len(ch) >= 2:
                bad = dotted
            elif root_mod == "uuid" and ch[-1] in ("uuid1", "uuid4"):
                bad = dotted
            elif root_mod == "datetime" and ch[-1] in (
                "now", "utcnow", "today"
            ):
                bad = dotted
            elif len(ch) == 2 and ch[0] in dt_class and ch[1] in (
                "now", "utcnow", "today"
            ):
                bad = dotted
            elif len(ch) == 1 and ch[0] in direct:
                bad = direct[ch[0]]
        if bad is None:
            continue
        if _pragma_allows(lines, node.lineno, "ambient-entropy"):
            continue
        res.add(
            f"{rel}:{node.lineno}",
            f"ambient entropy / wall clock: `{bad}` — simulation behavior "
            "must derive from the seed; suppress a deliberate use with "
            "`# madsim: allow(ambient-entropy)`",
        )
    return res


def check_entropy(root: Optional[str] = None) -> RuleResult:
    root = root or repo_root()
    res = RuleResult("ambient-entropy")
    for path in _py_files(root, "madsim_tpu"):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        if rel in ENTROPY_ALLOWED_FILES:
            continue
        if any(rel.startswith(d) for d in ENTROPY_ALLOWED_DIRS):
            continue
        one = check_entropy_file(path, root)
        res.checked += one.checked
        res.violations.extend(one.violations)
    return res


# ----------------------------------------------------------------- both-faces


def _ordered_stmts(body: Iterable[ast.stmt]) -> Iterable[ast.stmt]:
    """Statements in source order, descending into compound statements."""
    for st in body:
        yield st
        for attr in ("body", "orelse", "finalbody"):
            sub = getattr(st, attr, None)
            if sub:
                yield from _ordered_stmts(sub)
        for h in getattr(st, "handlers", []) or []:
            yield from _ordered_stmts(h.body)


def _find_function(tree: ast.AST, name: str) -> Optional[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == name:
                return node
    return None


def _word_ident(node: ast.AST) -> str:
    """The folded-field identifier of a fold call's word argument."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - ancient AST nodes
        return "<expr>"


def fold_chain_fields(
    fn: ast.AST, fold_names: Set[str], salt_name: str = "COV_SALT"
) -> List[str]:
    """The SEQUENCE of field identifiers folded into the salt-rooted hash
    chain inside a function: the seed fold (whose first argument mentions
    `salt_name`) contributes its word argument, each subsequent
    `x = fold(x, field)` appends its word. Comparing sequences (not
    counts) catches a field SUBSTITUTED on one face, not just added."""
    chains: Dict[str, List[str]] = {}
    best: List[str] = []
    for st in _ordered_stmts(fn.body):
        if not isinstance(st, ast.Assign) or len(st.targets) != 1:
            continue
        tgt = st.targets[0]
        if not isinstance(tgt, ast.Name):
            continue
        call = st.value
        if not isinstance(call, ast.Call) or len(call.args) < 2:
            continue
        fname = None
        if isinstance(call.func, ast.Attribute):
            fname = call.func.attr
        elif isinstance(call.func, ast.Name):
            fname = call.func.id
        if fname not in fold_names:
            continue
        arg0 = call.args[0]
        word = _word_ident(call.args[1])
        mentions_salt = any(
            isinstance(n, ast.Name) and n.id == salt_name
            for n in ast.walk(arg0)
        )
        if mentions_salt:
            chains[tgt.id] = [word]
        elif isinstance(arg0, ast.Name) and arg0.id in chains:
            chains[tgt.id] = chains[arg0.id] + [word]
        else:
            continue
        if len(chains[tgt.id]) >= len(best):
            best = list(chains[tgt.id])
    return best


def registry_cov_fields(engine_src: str) -> Optional[List[str]]:
    """COV_FIELDS names parsed from engine.py source (no import needed)."""
    tree = ast.parse(engine_src)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "COV_FIELDS":
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    return [
                        e.value
                        for e in node.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str)
                    ]
    return None


def check_both_faces(
    engine_path: Optional[str] = None,
    mirror_path: Optional[str] = None,
    engine_fn: str = "_step_traced",
    mirror_fn: str = "cov_index",
    root: Optional[str] = None,
) -> RuleResult:
    """Device coverage-hash chain == trace-mirror chain == COV_FIELDS."""
    root = root or repo_root()
    engine_path = engine_path or os.path.join(
        root, "madsim_tpu", "tpu", "engine.py"
    )
    mirror_path = mirror_path or os.path.join(root, "madsim_tpu", "explore.py")
    res = RuleResult("both-faces")
    eng_src, _ = _read(engine_path)
    mir_src, _ = _read(mirror_path)
    dev_fn = _find_function(ast.parse(eng_src), engine_fn)
    mir_fn_node = _find_function(ast.parse(mir_src), mirror_fn)
    if dev_fn is None:
        res.add(engine_path, f"device face function {engine_fn} not found")
        return res
    if mir_fn_node is None:
        res.add(mirror_path, f"mirror face function {mirror_fn} not found")
        return res
    dev = fold_chain_fields(dev_fn, {"fold"})
    mir = fold_chain_fields(mir_fn_node, {"fold", "fold32"})
    reg = registry_cov_fields(eng_src)
    res.checked += 3
    if not dev:
        res.add(
            f"{engine_path}:{engine_fn}",
            "no COV_SALT-rooted fold chain found on the device face",
        )
    if not mir:
        res.add(
            f"{mirror_path}:{mirror_fn}",
            "no COV_SALT-rooted fold chain found on the trace mirror",
        )
    if dev and mir and len(dev) != len(mir):
        res.add(
            f"{engine_fn} vs {mirror_fn}",
            f"coverage hash folds {len(dev)} fields on the device face "
            f"({dev}) but {len(mir)} on the trace mirror ({mir}) — a "
            "field hashed on one face only desyncs every recorded "
            "cov_digest (the both-faces rule)",
        )
    # each face's i-th folded identifier must NAME the registered field
    # (substring match: the device face uses e.g. `src_w` for `src`) —
    # comparing the sequences, not just the counts, catches a field
    # SUBSTITUTED on one face
    if reg:
        for label, seq in (("device face", dev), ("trace mirror", mir)):
            if not seq:
                continue
            if len(seq) != len(reg):
                res.add(
                    f"{engine_fn if label == 'device face' else mirror_fn}"
                    " vs COV_FIELDS",
                    f"{label} folds {len(seq)} fields ({seq}) but "
                    f"COV_FIELDS registers {len(reg)} ({reg}) — update "
                    "the registry with the new field",
                )
                continue
            for i, (got, want) in enumerate(zip(seq, reg)):
                if want not in got:
                    res.add(
                        f"COV_FIELDS[{i}]",
                        f"{label} folds `{got}` where the registry names "
                        f"`{want}` — a substituted hash field desyncs "
                        "recorded cov_digests exactly like an added one",
                    )
    # the mirror must consume BOTH event faces of the trace
    body_names = {
        n.attr for n in ast.walk(mir_fn_node) if isinstance(n, ast.Attribute)
    }
    mirror_module = ast.parse(mir_src)
    bft = _find_function(mirror_module, "bitmap_from_trace")
    if bft is not None:
        body_names |= {
            n.attr for n in ast.walk(bft) if isinstance(n, ast.Attribute)
        }
    res.checked += 1
    for field in ("msg_fired", "timer_fired"):
        if field not in body_names:
            res.add(
                f"{mirror_path}",
                f"trace mirror never reads `{field}` — one event face of "
                "the coverage encoding is unmirrored",
            )
    return res


# --------------------------------------------------------------------- mirror


def check_mirror(
    schedule_clauses: Optional[Dict[str, type]] = None,
    message_clauses: Optional[Dict[str, type]] = None,
    assign_clauses: Optional[Dict[str, type]] = None,
    event_kinds: Optional[Dict[str, Tuple[str, ...]]] = None,
    driver_source: Optional[str] = None,
    root: Optional[str] = None,
    host_coin_methods: Optional[Dict[str, Tuple[str, ...]]] = None,
    net_source: Optional[str] = None,
    oracle_source: Optional[str] = None,
    fs_source: Optional[str] = None,
) -> RuleResult:
    """Every clause exists on all four faces: the pure schedule, the
    device tensor program, the host driver, and the oracle comparator's
    input (HOST_COIN_METHODS — the draw methods the net layer calls and
    madsim_tpu/oracle.py recomputes).

    Parameters exist for fixture injection; by default the real
    registries, driver source, net/oracle sources, and compile_plan are
    checked."""
    from .. import nemesis as nem

    res = RuleResult("mirror")
    root = root or repo_root()
    schedule_clauses = (
        nem.SCHEDULE_CLAUSES if schedule_clauses is None else schedule_clauses
    )
    message_clauses = (
        nem.MESSAGE_CLAUSES if message_clauses is None else message_clauses
    )
    assign_clauses = (
        nem.ASSIGN_CLAUSES if assign_clauses is None else assign_clauses
    )
    event_kinds = (
        nem.CLAUSE_EVENT_KINDS if event_kinds is None else event_kinds
    )

    all_named = {**schedule_clauses, **message_clauses, **assign_clauses}

    # (a) registry completeness vs the clause type universe
    res.checked += 1
    registered = set(all_named.values())
    universe = set(nem._CLAUSE_TYPES)
    for cls in sorted(universe - registered, key=lambda c: c.__name__):
        res.add(
            "nemesis registries",
            f"clause type {cls.__name__} is not in SCHEDULE_CLAUSES / "
            "MESSAGE_CLAUSES / ASSIGN_CLAUSES — the verifier cannot prove "
            "its mirrors exist",
        )
    for cls in sorted(registered - universe, key=lambda c: c.__name__):
        res.add(
            "nemesis registries",
            f"registered clause {cls.__name__} is not a FaultPlan clause "
            "type",
        )

    # (b) vocabulary agreement with the triage/occurrence tables
    res.checked += 1
    if set(schedule_clauses) != set(nem.OCC_CLAUSES):
        res.add(
            "nemesis registries",
            f"SCHEDULE_CLAUSES {sorted(schedule_clauses)} != OCC_CLAUSES "
            f"{sorted(nem.OCC_CLAUSES)} — occurrence masks and schedule "
            "clauses must share one vocabulary",
        )
    if set(message_clauses) != set(nem.RATE_CLAUSES):
        res.add(
            "nemesis registries",
            f"MESSAGE_CLAUSES {sorted(message_clauses)} != RATE_CLAUSES "
            f"{sorted(nem.RATE_CLAUSES)}",
        )
    missing_triage = (
        set(all_named) - set(nem.TRIAGE_CLAUSES)
    )
    if missing_triage:
        res.add(
            "nemesis registries",
            f"clauses {sorted(missing_triage)} have no TRIAGE_CLAUSES atom "
            "— they cannot be shrunk out of a repro",
        )

    # (c) event-kind tables are mutually inverse
    res.checked += 1
    windowed = {**schedule_clauses, **assign_clauses}
    for name in windowed:
        kinds = event_kinds.get(name)
        if not kinds:
            res.add(
                "CLAUSE_EVENT_KINDS",
                f"clause {name!r} has no registered event kinds",
            )
            continue
        for k in kinds:
            owner = nem.CLAUSE_OF_EVENT.get(k)
            if owner != name:
                res.add(
                    "CLAUSE_OF_EVENT",
                    f"event kind {k!r} maps to {owner!r}, expected {name!r}",
                )

    # (d) host driver face: NemesisDriver handles every event kind
    driver_src = driver_source
    if driver_src is None:
        driver_src, _ = _read(os.path.join(root, "madsim_tpu", "nemesis.py"))
    tree = ast.parse(driver_src)
    apply_fn = _find_function(tree, "_apply")
    install_fn = _find_function(tree, "install")
    handled: Set[str] = set()
    for fn in (apply_fn, install_fn):
        if fn is None:
            continue
        # standalone string statements (docstrings, prose) must NOT count
        # as handling — a kind surviving only in a docstring after its
        # code was deleted is exactly the regression this rule hunts
        prose_ids = {
            id(node.value)
            for node in ast.walk(fn)
            if isinstance(node, ast.Expr)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        }
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in prose_ids
            ):
                handled.add(node.value)
    res.checked += 1
    for name, kinds in sorted(event_kinds.items()):
        if name not in windowed and name not in all_named:
            continue
        for k in kinds:
            if k not in handled:
                res.add(
                    "NemesisDriver",
                    f"host driver never handles event kind {k!r} (clause "
                    f"{name!r}) — the device face would fire it unmirrored",
                )

    # (e) device + schedule faces per clause (single-clause plans)
    from ..tpu import nemesis as tpun
    from ..tpu.spec import SimConfig

    base = SimConfig()
    for name, cls in sorted(schedule_clauses.items()):
        res.checked += 1
        try:
            plan = nem.FaultPlan(clauses=(cls(),), name=f"lint-{name}")
        except Exception as e:  # fixture clause types may not construct
            res.add(name, f"clause {cls.__name__} not constructible: {e}")
            continue
        enabled_prop = f"nem_{name}_enabled"
        if not hasattr(SimConfig, enabled_prop):
            res.add(
                "SimConfig",
                f"no `{enabled_prop}` switch — schedule clause {name!r} has "
                "no device face",
            )
            continue
        cfg = tpun.compile_plan(plan, base)
        if not getattr(cfg, enabled_prop):
            res.add(
                "compile_plan",
                f"compiling a {cls.__name__} plan leaves {enabled_prop} "
                "False — the device face ignores the clause",
            )
        evs = plan.schedule(seed=1, horizon_us=60_000_000, n_nodes=5)
        got_kinds = {e.kind for e in evs}
        want = set(event_kinds.get(name, ()))
        if not got_kinds:
            res.add(
                "plan_schedule",
                f"single-clause {cls.__name__} plan produced no schedule "
                "events over a 60 s horizon",
            )
        elif want and not got_kinds <= want:
            res.add(
                "plan_schedule",
                f"clause {name!r} emitted kinds {sorted(got_kinds - want)} "
                "outside its registered event kinds",
            )
        if want and evs and event_kinds[name][0] not in got_kinds:
            res.add(
                "plan_schedule",
                f"clause {name!r} never emitted its open-half kind "
                f"{event_kinds[name][0]!r}",
            )
        for fk in nem.CLAUSE_FIRE_KINDS.get(name, ()):
            if fk not in nem.FIRE_KINDS:
                res.add(
                    "FIRE_KINDS",
                    f"fire kind {fk!r} (clause {name!r}) missing from "
                    "FIRE_KINDS",
                )
    for name, cls in sorted(message_clauses.items()):
        res.checked += 1
        cfg = tpun.compile_plan(
            nem.FaultPlan(clauses=(cls(),), name=f"lint-{name}"), base
        )
        knob = f"nem_{name}_rate"
        if getattr(cfg, knob, 0) <= 0:
            res.add(
                "compile_plan",
                f"compiling a {cls.__name__} plan leaves {knob} at 0 — no "
                "device face",
            )
    for name, cls in sorted(assign_clauses.items()):
        res.checked += 1
        plan = nem.FaultPlan(clauses=(cls(),), name=f"lint-{name}")
        cfg = tpun.compile_plan(plan, base)
        if name == "skew":
            if not cfg.nem_skew_enabled:
                res.add("compile_plan", "ClockSkew plan leaves skew disabled")
            if not any(plan.skew_ppm(3, 5)):
                res.add(
                    "plan.skew_ppm",
                    "ClockSkew plan assigns zero ppm everywhere for seed 3",
                )

    # (f) oracle-comparator face: every message clause's host draws are
    # schedule-matched. Each MESSAGE_CLAUSES clause must map to
    # HOST_COIN_METHODS; each listed method must exist on ScheduleCoins
    # AND be called somewhere in the host net layer (ast.Attribute — a
    # clause whose draws never route through ScheduleCoins falls back to
    # the ambient rng and the oracle cannot verify it); and oracle.py
    # must consume the registry itself, so a new clause added to three
    # faces but not the comparator still fails `make lint`.
    coin_methods = (
        nem.HOST_COIN_METHODS if host_coin_methods is None
        else host_coin_methods
    )
    net_src = net_source
    if net_src is None:
        netsim_src, _ = _read(
            os.path.join(root, "madsim_tpu", "net", "netsim.py")
        )
        network_src, _ = _read(
            os.path.join(root, "madsim_tpu", "net", "network.py")
        )
        net_src = netsim_src + "\n" + network_src
    net_attrs = {
        node.attr
        for node in ast.walk(ast.parse(net_src))
        if isinstance(node, ast.Attribute)
    }
    res.checked += 1
    for name in sorted(message_clauses):
        methods = coin_methods.get(name)
        if not methods:
            res.add(
                "HOST_COIN_METHODS",
                f"message clause {name!r} has no ScheduleCoins draw methods "
                "registered — its host draws are not schedule-matched and "
                "the oracle comparator cannot verify them",
            )
            continue
        for m in methods:
            if not callable(getattr(nem.ScheduleCoins, m, None)):
                res.add(
                    "ScheduleCoins",
                    f"registered draw method {m!r} (clause {name!r}) does "
                    "not exist on ScheduleCoins",
                )
            if m not in net_attrs:
                res.add(
                    "net layer",
                    f"ScheduleCoins.{m} (clause {name!r}) is never called "
                    "from net/netsim.py or net/network.py — the host draw "
                    "falls back to the ambient rng, unverifiable by the "
                    "oracle",
                )
    # schedule clauses may ALSO register host draws (DiskFault's torn
    # extent: the one value only the host stream contains, applied by
    # FsSim at a torn power failure). Their apply path is the driver +
    # fs layer, not net/ — a registered method no driver arm ever passes
    # to the filesystem means every scheduled torn crash silently
    # un-tears on the host face.
    fs_src = fs_source
    if fs_src is None:
        fs_src, _ = _read(os.path.join(root, "madsim_tpu", "fs.py"))
    driver_attrs = {
        node.attr
        for src in (driver_src, fs_src)
        for node in ast.walk(ast.parse(src))
        if isinstance(node, ast.Attribute)
    }
    res.checked += 1
    for name in sorted(set(coin_methods) & set(schedule_clauses)):
        for m in coin_methods[name]:
            if not callable(getattr(nem.ScheduleCoins, m, None)):
                res.add(
                    "ScheduleCoins",
                    f"registered draw method {m!r} (schedule clause "
                    f"{name!r}) does not exist on ScheduleCoins",
                )
            if m not in driver_attrs:
                res.add(
                    "NemesisDriver/fs",
                    f"ScheduleCoins.{m} (schedule clause {name!r}) is never "
                    "referenced from the host driver's apply path "
                    "(madsim_tpu/nemesis.py) or accepted by the fs layer — "
                    "the host face drops the draw, so e.g. a scheduled torn "
                    "crash silently un-tears on the host",
                )
    stray = sorted(
        set(coin_methods) - set(message_clauses) - set(schedule_clauses)
    )
    if stray:
        res.add(
            "HOST_COIN_METHODS",
            f"entries {stray} name no MESSAGE_CLAUSES or SCHEDULE_CLAUSES "
            "clause — the comparator would verify draws no clause produces",
        )
    res.checked += 1
    orc_src = oracle_source
    if orc_src is None:
        orc_src, _ = _read(os.path.join(root, "madsim_tpu", "oracle.py"))
    if "HOST_COIN_METHODS" not in orc_src:
        res.add(
            "oracle.py",
            "the comparator never reads nemesis.HOST_COIN_METHODS — new "
            "message clauses would ship without an oracle face",
        )
    return res


# ----------------------------------------------------------- layout agreement


def parse_layout_table(src: str) -> Dict[str, Optional[str]]:
    """{leaf name -> declared dtype string (None entries preserved)} from
    the LAYOUT literal in tests/test_state_layout.py (pure AST; the test
    module is never imported)."""
    tree = ast.parse(src)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == "LAYOUT" and isinstance(
                node.value, ast.Dict
            ):
                out: Dict[str, Optional[str]] = {}
                for k, v in zip(node.value.keys, node.value.values):
                    if not (
                        isinstance(k, ast.Constant) and isinstance(k.value, str)
                    ):
                        continue
                    if isinstance(v, ast.Constant) and v.value is None:
                        out[k.value] = None
                    elif isinstance(v, (ast.Tuple, ast.List)) and v.elts:
                        first = v.elts[0]
                        if isinstance(first, ast.Constant) and isinstance(
                            first.value, str
                        ):
                            out[k.value] = first.value
                return out
    raise ValueError("LAYOUT table not found")


_NARROW_DTYPES = {"uint8", "int8", "uint16", "int16"}


def check_layout_agreement(
    layout: Optional[Dict[str, Optional[str]]] = None,
    narrow_fields: Optional[Dict[str, object]] = None,
    root: Optional[str] = None,
) -> RuleResult:
    """tests/test_state_layout.py LAYOUT vs the raft spec narrow table."""
    res = RuleResult("layout-agreement")
    root = root or repo_root()
    if layout is None:
        src, _ = _read(os.path.join(root, "tests", "test_state_layout.py"))
        layout = parse_layout_table(src)
    if narrow_fields is None:
        from ..tpu.raft import make_raft_spec

        narrow_fields = dict(make_raft_spec().narrow_fields or {})
    import numpy as np

    declared = {
        k[len("node."):]: v
        for k, v in layout.items()
        if k.startswith("node.") and v is not None
    }
    for f, dt in sorted(narrow_fields.items()):
        res.checked += 1
        want = np.dtype(dt).name
        got = declared.get(f)
        if got is None:
            res.add(
                "LAYOUT",
                f"narrow field node.{f} ({want}) missing from the LAYOUT "
                "table — the layout lint cannot guard it",
            )
        elif got != want:
            res.add(
                "LAYOUT",
                f"node.{f}: LAYOUT declares {got}, spec.narrow_fields "
                f"declares {want} — the two tables drifted",
            )
    for f, got in sorted(declared.items()):
        if got in _NARROW_DTYPES and f not in narrow_fields:
            res.checked += 1
            res.add(
                "LAYOUT",
                f"LAYOUT declares node.{f} narrow ({got}) but the raft "
                "spec's narrow_fields does not narrow it — stale table "
                "entry or missing spec declaration",
            )
    return res


# ------------------------------------------------------------- marker hygiene


def _marks_of(fn: ast.AST, module_marks: Set[str]) -> Set[str]:
    marks = set(module_marks)
    for dec in getattr(fn, "decorator_list", []):
        node = dec.func if isinstance(dec, ast.Call) else dec
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
        parts = parts[::-1]
        if len(parts) >= 3 and parts[0] == "pytest" and parts[1] == "mark":
            marks.add(parts[2])
        elif len(parts) == 2 and parts[0] == "mark":
            marks.add(parts[1])
    return marks


def _module_marks(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in node.targets
        ):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Attribute):
                    out.add(sub.attr)
    return out - {"mark", "pytest"}


def check_marker_hygiene_file(path: str, root: str = "") -> RuleResult:
    res = RuleResult("marker-hygiene")
    rel = os.path.relpath(path, root).replace(os.sep, "/") if root else path
    src, lines = _read(path)
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        res.add(f"{rel}:{e.lineno}", f"unparseable: {e.msg}")
        return res
    module_marks = _module_marks(tree)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith("test_"):
            continue
        res.checked += 1
        reasons = []
        accepted = set(HYGIENE_MARKS)
        if LONG_NAME_RE.search(node.name):
            reasons.append(f"name matches {LONG_NAME_RE.pattern!r}")
        doc = ast.get_docstring(node) or ""
        m = RUNTIME_NOTE_RE.search(doc)
        if m and int(m.group(1)) >= RUNTIME_NOTE_FLOOR_S:
            reasons.append(f"docstring notes a ~{m.group(1)}s runtime")
            # a MEASURED budget note demands a tier-excluding mark:
            # `chaos` alone does not take a test out of the default run
            accepted = {"slow", "deep"}
        if not reasons:
            continue
        marks = _marks_of(node, module_marks)
        if marks & accepted:
            continue
        if _pragma_allows(lines, node.lineno, "marker-hygiene"):
            continue
        res.add(
            f"{rel}:{node.lineno}",
            f"{node.name} looks long-running ({'; '.join(reasons)}) but "
            f"carries no slow/deep/chaos marker — tier-1 runs `-m 'not "
            "slow'` under a hard budget; mark it or suppress with "
            "`# madsim: allow(marker-hygiene)`",
        )
    return res


def check_marker_hygiene(
    root: Optional[str] = None, tests_dir: str = "tests"
) -> RuleResult:
    root = root or repo_root()
    res = RuleResult("marker-hygiene")
    for path in _py_files(root, tests_dir):
        if "fixtures" in path.replace(os.sep, "/").split("/"):
            continue
        if not os.path.basename(path).startswith("test_"):
            continue
        one = check_marker_hygiene_file(path, root)
        res.checked += one.checked
        res.violations.extend(one.violations)
    return res


# ------------------------------------------------------- workload registry

# modules whose factory tables are DERIVED from the workload registry —
# each must textually import `workloads` (the consolidation contract:
# no consumer re-grows a private protocol list)
REGISTRY_CONSUMERS = (
    "madsim_tpu/explore.py",
    "madsim_tpu/tune.py",
    "madsim_tpu/oracle.py",
    "madsim_tpu/analysis/__init__.py",
    "madsim_tpu/analysis/jaxpr_check.py",
)

_REGISTRY_IMPORT_RE = re.compile(
    r"(?:from\s+\.{1,2}\s+import\s+workloads"
    r"|import\s+madsim_tpu\.workloads)"
)


def check_workload_registry(root: Optional[str] = None) -> RuleResult:
    """The consolidated workload registry (madsim_tpu/workloads) is the
    single wiring table, and it is LIVE:

      (a) every row's device face resolves — the module imports and the
          spec/workload factory attributes (plus `knobs_attr` when
          declared) exist and are callable;
      (b) every row's host face (when declared) exposes `fuzz_one_seed`
          and `InvariantViolation`; rows flagged `oracle_twin` must
          declare a host face (the comparator needs a plan-mode twin);
      (c) the consumer modules whose tables were folded into the
          registry actually import it — re-grown private lists would
          silently drop new rows from those faces;
      (d) speclang-generated rows name their spec source, both emitted
          faces carry a `SPECLANG_DIGEST` equal to the current sha256
          of that source, and `emit --check` is clean in-process — an
          edited spec with stale generated modules fails HERE, not at
          3am in a chaos sweep.
    """
    import importlib

    res = RuleResult("mirror")
    root = root or repo_root()
    from .. import workloads as registry
    from ..speclang import emit as speclang_emit

    # (a) + (b): every row resolves on every declared face
    for e in registry.ENTRIES:
        res.checked += 1
        where = f"workloads registry [{e.name}]"
        try:
            mod = importlib.import_module(e.module)
        except Exception as exc:  # pragma: no cover - wiring error
            res.add(where, f"device module {e.module} fails to import: "
                           f"{exc!r}")
            continue
        for attr in filter(None, (e.spec_attr, e.workload_attr,
                                  e.knobs_attr)):
            fn = getattr(mod, attr, None)
            if not callable(fn):
                res.add(
                    where,
                    f"{e.module}.{attr} is missing or not callable — the "
                    "row's device face does not resolve",
                )
        if e.oracle_twin and e.host_module is None:
            res.add(
                where,
                "flagged oracle_twin but declares no host_module — the "
                "differential oracle has no plan-mode twin to run",
            )
        if e.host_module is not None:
            try:
                hmod = importlib.import_module(e.host_module)
            except Exception as exc:  # pragma: no cover - wiring error
                res.add(where, f"host module {e.host_module} fails to "
                               f"import: {exc!r}")
                continue
            if not callable(getattr(hmod, "fuzz_one_seed", None)):
                res.add(
                    where,
                    f"{e.host_module} exposes no callable fuzz_one_seed",
                )
            if getattr(hmod, "InvariantViolation", None) is None:
                res.add(
                    where,
                    f"{e.host_module} exposes no InvariantViolation — "
                    "fuzz drivers cannot classify its failures",
                )

    # (c): the consumers read the registry, not private lists
    for rel in REGISTRY_CONSUMERS:
        res.checked += 1
        path = os.path.join(root, *rel.split("/"))
        if not os.path.exists(path):
            res.add(rel, "registry consumer file is missing")
            continue
        src, _ = _read(path)
        if not _REGISTRY_IMPORT_RE.search(src):
            res.add(
                rel,
                "never imports the workload registry — its factory "
                "table has de-consolidated into a private list",
            )

    # (d): generated rows pin their spec source by digest, and the
    # checked-in generated modules match a fresh in-process render
    for e in registry.ENTRIES:
        if not e.generated:
            continue
        res.checked += 1
        where = f"workloads registry [{e.name}]"
        if e.source_module is None:
            res.add(where, "generated=True but source_module is unset")
            continue
        src_name = e.source_module.rsplit(".", 1)[-1]
        try:
            want = speclang_emit.source_digest(src_name)
        except OSError as exc:
            res.add(where, f"spec source {e.source_module} unreadable: "
                           f"{exc!r}")
            continue
        for face_mod in (e.module, e.host_module):
            if face_mod is None:
                continue
            got = getattr(importlib.import_module(face_mod),
                          "SPECLANG_DIGEST", None)
            if got != want:
                res.add(
                    where,
                    f"{face_mod}.SPECLANG_DIGEST {str(got)[:12]}... != "
                    f"sha256({e.source_module}) {want[:12]}... — the "
                    "spec source changed without `python -m "
                    "madsim_tpu.speclang emit`",
                )
    res.checked += 1
    _, drifted = speclang_emit.emit(check=True)
    for fname in drifted:
        res.add(
            f"madsim_tpu/speclang/generated/{fname}",
            "drifts from an in-process re-render of its spec source — "
            "re-run `python -m madsim_tpu.speclang emit`",
        )
    return res


# -------------------------------------------------------------------- runner


def run_source_lints(root: Optional[str] = None, log=print) -> List[RuleResult]:
    root = root or repo_root()
    if log:
        log(f"[analysis] source lints over {root} ...")
    return [
        check_entropy(root),
        check_both_faces(root=root),
        check_mirror(root=root),
        check_workload_registry(root=root),
        check_layout_agreement(root=root),
        check_marker_hygiene(root),
    ]
