"""Lease/watch on the host runtime: lease's debuggable twin.

Same protocol as `madsim_tpu.tpu.lease` written as host coroutines: a
lease server (node 0) granting time-bound exclusive leases with fenced
tokens, clients renewing by keepalive and releasing after they stop
believing, and a best-effort NOTIFY watch plane. The rpc
request/response pairing plays the device spec's echo-matching role: a
grant for a timed-out acquire is dropped by the runtime, so belief can
only come from a response to the live request.

The membership hook is the durable incarnation nonce: drawn at node
construction, carried across crash/restart, REDRAWN when a wipe-join
builds a fresh node — host-native chaos wipes a fraction of restarts,
and plan mode replays compiled `reconfig` clauses through
`NemesisDriver.on_wipe`. The zombie-lease invariant is checked by a
periodic checker task (the violation persists for the lease lifetime,
unlike isr's transient one) plus at the end.

`fuzz_one_seed(seed)` runs one execution under loss + crash/wipe chaos
and verifies the same invariant as the device face.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, rpc

RPC_TIMEOUT = 0.120
TICK = 0.025
TTL = 1.5
KA_INTERVAL = 0.200
ACQUIRE_RATE = 0.5
RELEASE_RATE = 0.04
WIPE_FRAC = 0.5  # host-native chaos: fraction of restarts that wipe


class InvariantViolation(AssertionError):
    pass


@rpc.rpc_request
class Acquire:
    def __init__(self, src, inc):
        self.src, self.inc = src, inc


@rpc.rpc_request
class Ka:
    def __init__(self, src, inc):
        self.src, self.inc = src, inc


@rpc.rpc_request
class Release:
    def __init__(self, src, token):
        self.src, self.token = src, token


@rpc.rpc_request
class Notify:
    def __init__(self, token, holder):
        self.token, self.holder = token, holder


@dataclass
class LeaseNode:
    node_id: int
    n: int
    addrs: List[str]
    buggy: bool = False  # zombie lease: renewal matches node id only

    def __post_init__(self):
        # durable client identity: the incarnation nonce rotates ONLY
        # when a wipe-join constructs a fresh node
        self.inc = 1 + ms.randrange(1 << 30)
        # client belief (durable)
        self.held = False
        self.my_token = 0
        self.my_expiry = 0.0
        self.ka_t = 0.0
        self.wseen = 0
        # the lease head (server only; durable)
        self.l_holder = -1
        self.l_inc = 0
        self.l_token = 0
        self.l_expiry = 0.0

    # ------------------------------------------------------ server handlers

    def _match_holder(self, src: int, inc: int) -> bool:
        if self.buggy:
            # THE PLANTED BUG: the incarnation is ignored, so a
            # wipe-joined client's fresh ACQUIRE/KA renews the removed
            # incarnation's live lease
            return self.l_holder == src
        return self.l_holder == src and self.l_inc == inc

    async def on_acquire(self, req: Acquire):
        now = ms.time.current().elapsed()
        free = self.l_holder < 0 or now > self.l_expiry
        if free:
            self.l_token += 1
            self.l_holder, self.l_inc = req.src, req.inc
            self.l_expiry = now + TTL
            return (True, self.l_token, self.l_expiry)
        if self._match_holder(req.src, req.inc):
            self.l_token += 1  # fencing bump on renewal too
            self.l_expiry = now + TTL
            return (True, self.l_token, self.l_expiry)
        return (False, 0, 0.0)

    async def on_ka(self, req: Ka):
        now = ms.time.current().elapsed()
        if now <= self.l_expiry and self._match_holder(req.src, req.inc):
            self.l_token += 1
            self.l_expiry = now + TTL
            return (True, self.l_token, self.l_expiry)
        return (False, 0, 0.0)

    async def on_release(self, req: Release):
        if self.l_holder == req.src and self.l_token == req.token:
            self.l_holder = -1
        return True

    async def on_notify(self, req: Notify):
        self.wseen = max(self.wseen, req.token)
        return True

    # --------------------------------------------------------------- loops

    async def _call(self, msg):
        try:
            return await ms.time.timeout(
                RPC_TIMEOUT, rpc.call(self.ep, self.addrs[0], msg)
            )
        except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
            return None

    async def run(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[self.node_id])
        if self.node_id == 0:
            rpc.add_rpc_handler(self.ep, Acquire, self.on_acquire)
            rpc.add_rpc_handler(self.ep, Ka, self.on_ka)
            rpc.add_rpc_handler(self.ep, Release, self.on_release)
        else:
            rpc.add_rpc_handler(self.ep, Notify, self.on_notify)
        t = ms.time.current()
        while True:
            await ms.time.sleep(TICK)
            now = t.elapsed()
            if self.node_id == 0:
                # watch plane: tell one random watcher the lease head
                w = 1 + ms.randrange(self.n - 1)
                try:
                    await ms.time.timeout(
                        RPC_TIMEOUT,
                        rpc.call(self.ep, self.addrs[w],
                                 Notify(self.l_token, self.l_holder)),
                    )
                except (ms.time.TimeoutError_, OSError,
                        ms.sync.ChannelClosed):
                    pass
                continue
            if self.held and now > self.my_expiry:
                self.held = False  # local expiry ends belief
            if self.held and ms.rand() < RELEASE_RATE:
                self.held = False  # stop believing BEFORE sending
                await self._call(Release(self.node_id, self.my_token))
            elif self.held and now - self.ka_t > KA_INTERVAL:
                self.ka_t = now
                resp = await self._call(Ka(self.node_id, self.inc))
                if resp and resp[0] and self.held:
                    self.my_token = max(self.my_token, resp[1])
                    self.my_expiry = max(self.my_expiry, resp[2])
                    self.wseen = max(self.wseen, resp[1])
            elif not self.held and ms.rand() < ACQUIRE_RATE:
                resp = await self._call(Acquire(self.node_id, self.inc))
                if resp and resp[0]:
                    self.held = True
                    self.my_token, self.my_expiry = resp[1], resp[2]
                    self.ka_t = t.elapsed()
                    self.wseen = max(self.wseen, resp[1])


# ------------------------------------------------------------------ harness


def check_invariants(cns: List[LeaseNode], now: float) -> dict:
    """The incarnation-identity claim (same as the device face): when
    the server records node i as holder AND i currently believes, the
    recorded incarnation is i's current one. Mutual exclusion across
    holders is out of scope — a server wipe loses the lease log, and no
    server-local fact separates that amnesia from a double-grant."""
    srv = cns[0]
    believers = 0
    for i in range(1, len(cns)):
        c = cns[i]
        if c is None or not c.held or now > c.my_expiry:
            continue
        believers += 1
        if srv is None or srv.l_holder != i:
            continue
        if srv.l_inc != c.inc:
            raise InvariantViolation(
                f"zombie lease: node {i} (inc {c.inc}, token "
                f"{c.my_token}) believes it holds the lease, but the "
                f"server records holder {srv.l_holder} with inc "
                f"{srv.l_inc} (token {srv.l_token})"
            )
    return {"believers": believers}


async def _fuzz_body(
    n_nodes: int,
    virtual_secs: float,
    chaos: bool,
    buggy: bool,
    plan=None,
    occ_off=None,
    seed=None,
) -> dict:
    handle = ms.Handle.current()
    from madsim_tpu.net import NetSim

    addrs = [f"10.0.7.{i + 1}:7500" for i in range(n_nodes)]
    cns: list = [None] * n_nodes

    def make_node(i: int) -> LeaseNode:
        """Fresh node; identity + belief + the lease head carry over
        from the previous incarnation unless wiped (a wipe rotates the
        incarnation nonce — that is the membership epoch)."""
        old = cns[i]
        fresh = LeaseNode(i, n_nodes, addrs, buggy=buggy)
        if old is not None:
            fresh.inc = old.inc
            fresh.held = old.held
            fresh.my_token, fresh.my_expiry = old.my_token, old.my_expiry
            fresh.wseen = old.wseen
            fresh.l_holder, fresh.l_inc = old.l_holder, old.l_inc
            fresh.l_token, fresh.l_expiry = old.l_token, old.l_expiry
        cns[i] = fresh
        return fresh

    nodes = []
    if plan is not None:
        def make_init(i: int):
            def _init():
                return make_node(i).run()

            return _init

        for i in range(n_nodes):
            node = (
                handle.create_node()
                .name(f"lease-{i}")
                .ip(f"10.0.7.{i + 1}")
                .init(make_init(i))
                .build()
            )
            nodes.append(node)
    else:
        for i in range(n_nodes):
            node = handle.create_node().name(f"lease-{i}").ip(
                f"10.0.7.{i + 1}"
            ).build()
            node.spawn(make_node(i).run())
            nodes.append(node)

    async def chaos_task() -> None:
        while True:
            await ms.time.sleep(0.5 + ms.rand() * 1.5)
            victim = ms.randrange(n_nodes)
            handle.kill(nodes[victim].id)
            await ms.time.sleep(0.3 + ms.rand() * 0.6)
            if ms.rand() < WIPE_FRAC:
                cns[victim] = None  # membership churn: fresh incarnation
            fresh = make_node(victim)
            handle.restart(nodes[victim].id)
            nodes[victim].spawn(fresh.run())

    if chaos and plan is None:
        ms.spawn(chaos_task())

    driver = None
    if plan is not None:
        from madsim_tpu import nemesis as nem

        def on_wipe(i: int) -> None:
            cns[i] = None

        driver = nem.NemesisDriver(
            plan,
            handle,
            node_ids=[n.id for n in nodes],
            horizon_us=int(virtual_secs * 1e6),
            seed=seed,
            on_wipe=on_wipe,
            occ_off=occ_off,
        )
        driver.install()

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    stats = {"believers": 0}
    while t.elapsed() < end:
        await ms.time.sleep(0.05)
        # the zombie persists for the lease lifetime; a periodic
        # checker catches it long before the horizon
        got = check_invariants(cns, t.elapsed())
        stats["believers"] = max(stats["believers"], got["believers"])
    stats["final_token"] = cns[0].l_token if cns[0] else 0
    stats["events"] = ms.plugin.simulator(NetSim).stat().msg_count
    if driver is not None:
        stats["nemesis"] = {
            "applied": list(driver.applied),
            "occ_fired": dict(driver.occ_fired),
            "node_skew": dict(getattr(handle.time, "node_skew", {}) or {}),
            "node_ids": [n.id for n in nodes],
            "coins": driver.coins,
            "fires": driver.fire_counts(),
            "state": [
                (cn.inc, int(cn.held), cn.my_token, cn.l_holder,
                 cn.l_inc, cn.l_token) if cn else None
                for cn in cns
            ],
        }
    return stats


def fuzz_one_seed(
    seed: int,
    n_nodes: int = 5,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.1,
    chaos: bool = True,
    buggy: bool = False,
    plan=None,
    occ_off=None,
) -> dict:
    """One complete fuzzed execution, verified by the same oracle.

    With `plan=` (a `nemesis.FaultPlan`), chaos — including reconfig
    membership churn — comes from the compiled per-seed schedule via
    `NemesisDriver`; the returned dict then carries a `"nemesis"`
    artifact bundle."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(
            n_nodes, virtual_secs, chaos, buggy,
            plan=plan, occ_off=occ_off, seed=seed,
        )
    )
