"""The consolidated workload registry: one enumeration, every face.

Before this existed, wiring a protocol into the tree meant editing five
scattered tables by hand — the explore/campaign CLI factory dict
(`explore._named_workload`), the analysis target tuple
(`analysis.WORKLOADS`), the jaxpr-verifier factory map
(`analysis.jaxpr_check.spec_factories`), the oracle's plan-mode twin
table (`oracle.HOST_TWINS`) and the tune sweep list (`tune.WORKLOADS`) —
and nothing but review discipline kept them agreeing. Those tables are
now all DERIVED from the `WorkloadEntry` rows here, and the mirror lint
(`analysis.lint.check_workload_registry`) checks each row resolves to
real factories/host twins and that the consumers actually read this
registry rather than re-growing private lists.

Speclang-generated protocols (madsim_tpu/speclang/) register through the
same rows — `generated=True` marks entries whose device/host modules are
emitted from a single spec source and drift-checked against it — so a
new protocol is ONE spec file plus ONE row, not two modules and five
table edits.

The module must stay import-light: entries hold dotted module paths and
attribute names, resolved lazily on first use (importing this package
must not pull in jax — the analysis lint tier and CLI help paths read it
without tracing anything).
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Callable, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class WorkloadEntry:
    """One protocol's complete wiring, every face in one row."""

    name: str
    # device face: the module exposing the spec factory + BatchWorkload
    # factory (hand-written `tpu/<x>.py` or a speclang-generated module)
    module: str
    spec_attr: str
    workload_attr: str
    # host face: module exposing `fuzz_one_seed` (+ `InvariantViolation`)
    host_module: Optional[str] = None
    # schedule-matched plan-mode twin for the differential oracle
    # (oracle.HOST_TWINS): fuzz_one_seed must accept plan=/occ_off=/
    # lineage= and return the "nemesis" artifact bundle
    oracle_twin: bool = False
    # member of the `python -m madsim_tpu.tune` CLI sweep list
    tunable: bool = False
    # member of the explore/campaign CLI factory table
    explorable: bool = True
    # analysis target: jaxpr verifier + range certifier trace this name
    analysis: bool = True
    # emitted by speclang from a spec source (drift-checked by lint +
    # `make speclang-smoke`); `source_module` names that spec source
    generated: bool = False
    source_module: Optional[str] = None
    # optional Tier-B SpecKnob hook: `knobs_attr(virtual_secs)` on
    # `module` returns tune.SpecKnob rows derived from the spec source
    knobs_attr: Optional[str] = None


_GEN = "madsim_tpu.speclang.generated"
_SRC = "madsim_tpu.speclang.specs"

ENTRIES: Tuple[WorkloadEntry, ...] = (
    WorkloadEntry(
        "raft", "madsim_tpu.tpu.raft", "make_raft_spec", "raft_workload",
        host_module="madsim_tpu.workloads.raft_host",
        oracle_twin=True, tunable=True,
    ),
    WorkloadEntry(
        "kv", "madsim_tpu.tpu.kv", "make_kv_spec", "kv_workload",
        host_module="madsim_tpu.workloads.kv_host", tunable=True,
    ),
    WorkloadEntry(
        "twopc", "madsim_tpu.tpu.twopc", "make_twopc_spec",
        "twopc_workload",
        host_module="madsim_tpu.workloads.twopc_host", tunable=True,
    ),
    WorkloadEntry(
        "paxos", "madsim_tpu.tpu.paxos", "make_paxos_spec",
        "paxos_workload",
        host_module="madsim_tpu.workloads.paxos_host", tunable=True,
    ),
    WorkloadEntry(
        "chain", "madsim_tpu.tpu.chain", "make_chain_spec",
        "chain_workload",
        host_module="madsim_tpu.workloads.chain_host",
        oracle_twin=True, tunable=True,
    ),
    WorkloadEntry(
        "isr", "madsim_tpu.tpu.isr", "make_isr_spec", "isr_workload",
        host_module="madsim_tpu.workloads.isr_host",
    ),
    WorkloadEntry(
        "lease", "madsim_tpu.tpu.lease", "make_lease_spec",
        "lease_workload",
        host_module="madsim_tpu.workloads.lease_host",
    ),
    # wal is an analysis + twin-test workload, not an explore CLI target
    # (historical parity: the explore factory table never carried it —
    # its durability plane is exercised by the disk-fault twin tests)
    WorkloadEntry(
        "wal", "madsim_tpu.tpu.wal", "make_wal_spec", "wal_workload",
        host_module="madsim_tpu.workloads.wal_host", explorable=False,
    ),
    # --- speclang-generated (single spec source, both faces emitted) ---
    WorkloadEntry(
        "twopc-gen", f"{_GEN}.twopc_device", "make_spec", "make_workload",
        host_module=f"{_GEN}.twopc_host",
        generated=True, source_module=f"{_SRC}.twopc",
        knobs_attr="spec_knobs",
    ),
    WorkloadEntry(
        "lease-gen", f"{_GEN}.lease_device", "make_spec", "make_workload",
        host_module=f"{_GEN}.lease_host",
        generated=True, source_module=f"{_SRC}.lease",
    ),
    WorkloadEntry(
        "backup", f"{_GEN}.backup_device", "make_spec", "make_workload",
        host_module=f"{_GEN}.backup_host",
        generated=True, source_module=f"{_SRC}.backup",
    ),
)

_BY_NAME: Dict[str, WorkloadEntry] = {e.name: e for e in ENTRIES}
if len(_BY_NAME) != len(ENTRIES):  # pragma: no cover - authoring error
    raise RuntimeError("duplicate workload registry names")


def get(name: str) -> WorkloadEntry:
    try:
        return _BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"unknown workload {name!r} (choose from {sorted(_BY_NAME)})"
        ) from None


def names(
    *,
    explorable: Optional[bool] = None,
    tunable: Optional[bool] = None,
    analysis: Optional[bool] = None,
    oracle_twin: Optional[bool] = None,
    generated: Optional[bool] = None,
) -> Tuple[str, ...]:
    """Registry names filtered by face flags (None = don't filter);
    registry order (= the historical hand-list order) is preserved."""
    out = []
    for e in ENTRIES:
        if explorable is not None and e.explorable != explorable:
            continue
        if tunable is not None and e.tunable != tunable:
            continue
        if analysis is not None and e.analysis != analysis:
            continue
        if oracle_twin is not None and e.oracle_twin != oracle_twin:
            continue
        if generated is not None and e.generated != generated:
            continue
        out.append(e.name)
    return tuple(out)


def _resolve(module: str, attr: str):
    return getattr(importlib.import_module(module), attr)


def spec_factory(name: str) -> Callable:
    e = get(name)
    return _resolve(e.module, e.spec_attr)


def workload_factory(name: str) -> Callable:
    e = get(name)
    return _resolve(e.module, e.workload_attr)


def spec_factories(**filters) -> Dict[str, Callable]:
    """{name -> spec factory} for every (filtered) registry entry — the
    map the jaxpr verifier keys its shared traces on."""
    return {n: spec_factory(n) for n in names(**filters)}


def host_fuzz(name: str) -> Callable:
    """The host twin's fuzz_one_seed for one entry (KeyError if the
    entry ships no host face)."""
    e = get(name)
    if e.host_module is None:
        raise KeyError(f"workload {name!r} has no host twin module")
    return _resolve(e.host_module, "fuzz_one_seed")


def _plan_twin(host_module: str) -> Callable[..., dict]:
    def run(seed, plan, occ_off, n_nodes, virtual_secs, loss_rate):
        fuzz = _resolve(host_module, "fuzz_one_seed")
        return fuzz(
            seed, n_nodes=n_nodes, virtual_secs=virtual_secs,
            loss_rate=loss_rate, chaos=False, plan=plan, occ_off=occ_off,
            lineage=True,
        )

    return run


def oracle_twins() -> Dict[str, Callable[..., dict]]:
    """{spec-name prefix -> plan-mode twin runner} for oracle.HOST_TWINS:
    every entry flagged oracle_twin, run with NemesisDriver plan mode and
    lineage on (the artifact surface the comparator consumes)."""
    return {
        e.name: _plan_twin(e.host_module)
        for e in ENTRIES
        if e.oracle_twin and e.host_module is not None
    }


def spec_knobs(name: str, virtual_secs: float) -> tuple:
    """The entry's Tier-B SpecKnob hooks ((), if it declares none) —
    generated entries derive these from their spec source."""
    e = get(name)
    if e.knobs_attr is None:
        return ()
    return tuple(_resolve(e.module, e.knobs_attr)(virtual_secs))
