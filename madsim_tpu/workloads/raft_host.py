"""Raft on the host runtime: the single-seed CPU baseline + flagship example.

This is the same protocol as `madsim_tpu.tpu.raft` written the way a *user* of
the host runtime writes distributed code: async tasks, typed RPC over
`Endpoint`, virtual-time timers, chaos via `Handle.kill/restart` — the MadRaft
analog running on this framework's tokio-analog core. `bench.py` measures it
one-seed-per-run (the reference's thread-per-seed model,
runtime/builder.rs:118-136) against the TPU batched engine fuzzing thousands
of lanes per step.

Run one seed: `fuzz_one_seed(seed)` -> dict of stats; raises
InvariantViolation on a safety bug. `buggy=True` injects the classic
unsafe-commit mistake (commit on a single ack, no current-term check — what
Raft §5.4.2 forbids) to validate that the invariant monitors catch real
protocol bugs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, rpc

FOLLOWER, CANDIDATE, LEADER = 0, 1, 2

ELECTION_LO, ELECTION_HI = 0.150, 0.300
HEARTBEAT = 0.050


class InvariantViolation(AssertionError):
    pass


@rpc.rpc_request
class RequestVote:
    def __init__(self, term, cand, last_idx, last_term):
        self.term, self.cand = term, cand
        self.last_idx, self.last_term = last_idx, last_term


@rpc.rpc_request
class AppendEntries:
    def __init__(self, term, leader, prev_idx, prev_term, entry, commit):
        self.term, self.leader = term, leader
        self.prev_idx, self.prev_term = prev_idx, prev_term
        self.entry = entry  # None (heartbeat) or (term, cmd)
        self.commit = commit


@dataclass
class RaftNode:
    node_id: int
    n: int
    addrs: List[str]
    client_rate: float = 0.5
    log_capacity: int = 24
    buggy: bool = False

    term: int = 0
    voted_for: Optional[int] = None
    role: int = FOLLOWER
    votes: int = 0
    log: List[tuple] = field(default_factory=list)  # (term, cmd)
    commit: int = -1
    next_idx: Dict[int, int] = field(default_factory=dict)
    match_idx: Dict[int, int] = field(default_factory=dict)
    next_cmd: int = 1
    last_contact: float = 0.0
    timeout: float = 0.0

    async def run(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[self.node_id])
        rpc.add_rpc_handler(self.ep, RequestVote, self.on_request_vote)
        rpc.add_rpc_handler(self.ep, AppendEntries, self.on_append)
        self.reset_election_timer()
        while True:
            if self.role == LEADER:
                await ms.time.sleep(HEARTBEAT)
                self.maybe_client_write()
                ms.spawn(self.broadcast_append())
            else:
                now = ms.time.current().elapsed()
                wait = self.timeout - now
                if wait > 0:
                    # short ticks: a mid-sleep promotion to leader must start
                    # heartbeating promptly, not after the residual wait
                    await ms.time.sleep(min(wait, HEARTBEAT / 2))
                    continue
                ms.spawn(self.start_election())
                self.reset_election_timer()

    # -- timers --

    def reset_election_timer(self) -> None:
        self.timeout = ms.time.current().elapsed() + ELECTION_LO + ms.rand() * (
            ELECTION_HI - ELECTION_LO
        )

    # -- election --

    async def start_election(self) -> None:
        self.term += 1
        self.role = CANDIDATE
        self.voted_for = self.node_id
        self.votes = 1 << self.node_id
        term = self.term
        last_idx = len(self.log) - 1
        last_term = self.log[last_idx][0] if last_idx >= 0 else 0
        for peer in range(self.n):
            if peer != self.node_id:
                ms.spawn(self.request_vote_from(peer, term, last_idx, last_term))

    async def request_vote_from(self, peer, term, last_idx, last_term) -> None:
        try:
            rterm, granted = await rpc.call_timeout(
                self.ep,
                self.addrs[peer],
                RequestVote(term, self.node_id, last_idx, last_term),
                0.1,
            )
        except (TimeoutError, OSError):
            return
        if rterm > self.term:
            self.step_down(rterm)
            return
        if self.role != CANDIDATE or self.term != term or not granted:
            return
        self.votes |= 1 << peer
        majority = self.n // 2 + 1
        if bin(self.votes).count("1") >= majority and self.role == CANDIDATE:
            self.role = LEADER
            self.next_idx = {p: len(self.log) for p in range(self.n)}
            self.match_idx = {p: -1 for p in range(self.n)}
            self.match_idx[self.node_id] = len(self.log) - 1
            # assert leadership NOW — waiting for the next run-loop tick can
            # exceed followers' election timeouts and livelock elections
            ms.spawn(self.broadcast_append())

    async def on_request_vote(self, req: RequestVote):
        if req.term > self.term:
            self.step_down(req.term)
        my_last_idx = len(self.log) - 1
        my_last_term = self.log[my_last_idx][0] if my_last_idx >= 0 else 0
        log_ok = (req.last_term, req.last_idx) >= (my_last_term, my_last_idx)
        grant = (
            req.term == self.term
            and self.voted_for in (None, req.cand)
            and log_ok
        )
        if grant:
            self.voted_for = req.cand
            self.reset_election_timer()
        return (self.term, grant)

    def step_down(self, term: int) -> None:
        self.term = term
        self.role = FOLLOWER
        self.voted_for = None
        self.votes = 0

    # -- replication --

    def maybe_client_write(self) -> None:
        if (
            self.role == LEADER
            and len(self.log) < self.log_capacity
            and ms.rand() < self.client_rate
        ):
            self.log.append((self.term, self.node_id * 100_000 + self.next_cmd))
            self.next_cmd += 1
            self.match_idx[self.node_id] = len(self.log) - 1

    async def broadcast_append(self) -> None:
        for peer in range(self.n):
            if peer != self.node_id:
                ms.spawn(self.append_to(peer))

    async def append_to(self, peer: int) -> None:
        # spawned-task races found by partition fuzzing: between
        # broadcast_append spawning this task and it running, this node may
        # have (a) stepped down and adopted a NEWER term — sending its stale
        # log stamped with that term would forge "current leader" messages
        # that make followers truncate committed entries — or (b) had its
        # log truncated, leaving next_idx past the end.
        if self.role != LEADER:
            return
        term = self.term
        ni = min(self.next_idx.get(peer, 0), len(self.log))
        prev_idx = ni - 1
        prev_term = self.log[prev_idx][0] if 0 <= prev_idx < len(self.log) else 0
        entry = self.log[ni] if ni < len(self.log) else None
        try:
            rterm, ok, match = await rpc.call_timeout(
                self.ep,
                self.addrs[peer],
                AppendEntries(term, self.node_id, prev_idx, prev_term, entry, self.commit),
                0.1,
            )
        except (TimeoutError, OSError):
            return
        if rterm > self.term:
            self.step_down(rterm)
            return
        if self.role != LEADER or self.term != term:
            return
        if ok:
            self.match_idx[peer] = max(self.match_idx.get(peer, -1), match)
            self.next_idx[peer] = max(self.next_idx.get(peer, 0), match + 1)
            self.advance_commit()
        else:
            self.next_idx[peer] = max(0, self.next_idx.get(peer, 1) - 1)

    def advance_commit(self) -> None:
        matches = sorted(self.match_idx.get(p, -1) for p in range(self.n))
        if self.buggy:
            # injected bug (for detector validation): commit as soon as ANY
            # single replica acks, and skip the current-term check — the
            # classic unsafe-commit mistake Raft §5.4.2 exists to prevent
            majority_idx = matches[-1]
            if majority_idx > self.commit and majority_idx < len(self.log):
                self.commit = majority_idx
            return
        majority_idx = matches[self.n - (self.n // 2 + 1)]
        if majority_idx > self.commit and (
            majority_idx < len(self.log) and self.log[majority_idx][0] == self.term
        ):
            self.commit = majority_idx

    async def on_append(self, req: AppendEntries):
        if req.term < self.term:
            return (self.term, False, -1)
        if req.term > self.term:
            self.step_down(req.term)
        self.role = FOLLOWER
        self.reset_election_timer()
        prev_ok = req.prev_idx < 0 or (
            req.prev_idx < len(self.log)
            and self.log[req.prev_idx][0] == req.prev_term
        )
        if not prev_ok:
            return (self.term, False, -1)
        match = req.prev_idx
        if req.entry is not None:
            w = req.prev_idx + 1
            if w < len(self.log):
                if self.log[w][0] != req.entry[0]:
                    del self.log[w:]
                    self.log.append(req.entry)
            elif w == len(self.log):
                self.log.append(req.entry)
            match = w if w < self.log_capacity else req.prev_idx
        self.commit = max(self.commit, min(req.commit, match))
        return (self.term, True, match)


async def _fuzz_body(
    n_nodes: int,
    virtual_secs: float,
    chaos: bool,
    buggy: bool,
    client_rate: float,
    partitions: bool = False,
    plan=None,
    occ_off=None,
    seed=None,
    lineage: bool = False,
) -> dict:
    handle = ms.Handle.current()
    from madsim_tpu.net import NetSim

    addrs = [f"10.0.1.{i + 1}:6000" for i in range(n_nodes)]
    rafts: list = [None] * n_nodes

    first_committed: dict = {}  # index -> (term, cmd) first observed committed
    dead: set = set()  # node ids currently killed (state frozen mid-crash)

    def make_node(i: int) -> RaftNode:
        """Fresh node object; durable state (term/vote/log/next_cmd) is
        carried over from the previous incarnation unless it was wiped."""
        old = rafts[i]
        fresh = RaftNode(i, n_nodes, addrs, buggy=buggy, client_rate=client_rate)
        if old is not None:
            fresh.term, fresh.voted_for = old.term, old.voted_for
            fresh.log = list(old.log)
            fresh.next_cmd = old.next_cmd
        rafts[i] = fresh
        return fresh

    nodes = []
    if plan is not None:
        # schedule-matched mode: crash/restart come from the compiled
        # FaultPlan stream (NemesisDriver), so nodes are built with
        # `.init(...)` closures — `handle.restart` respawns the protocol
        # node through the same durable-state carry the host-native
        # chaos_task below performs
        def make_init(i: int):
            def _init():
                dead.discard(i)
                return make_node(i).run()

            return _init

        for i in range(n_nodes):
            node = (
                handle.create_node()
                .name(f"raft-{i}")
                .ip(f"10.0.1.{i + 1}")
                .init(make_init(i))
                .build()
            )
            nodes.append(node)
    else:
        for i in range(n_nodes):
            node = (
                handle.create_node().name(f"raft-{i}").ip(f"10.0.1.{i + 1}").build()
            )
            node.spawn(make_node(i).run())
            nodes.append(node)

    def check_invariants() -> None:
        # election safety (a killed node's state is frozen; still applies)
        leaders = [(r.term, r.node_id) for r in rafts if r.role == LEADER]
        terms = [t for t, _ in leaders]
        if len(terms) != len(set(terms)):
            raise InvariantViolation(f"two leaders in one term: {leaders}")
        # a committed entry must exist: commit index beyond the log means a
        # committed entry was truncated away
        for r in rafts:
            if r.commit >= len(r.log):
                raise InvariantViolation(
                    f"node {r.node_id} committed up to {r.commit} but log has "
                    f"only {len(r.log)} entries (committed entry truncated)"
                )
        # committed-prefix agreement
        for a in rafts:
            for b in rafts:
                for i in range(min(a.commit, b.commit) + 1):
                    if a.log[i] != b.log[i]:
                        raise InvariantViolation(
                            f"log mismatch at {i}: {a.log[i]} vs {b.log[i]}"
                        )
        # committed entries are immutable (catches unsafe early commits even
        # when no two nodes disagree at the same instant)
        for r in rafts:
            for i in range(r.commit + 1):
                seen = first_committed.get(i)
                if seen is None:
                    first_committed[i] = r.log[i]
                elif r.log[i] != seen:
                    raise InvariantViolation(
                        f"committed entry rewritten at {i}: {seen} -> {r.log[i]} "
                        f"(node {r.node_id})"
                    )
        # leader completeness (Raft §5.4), mirroring tpu/raft.py's device
        # check: a live leader must hold every node's committed prefix once
        # its term has reached that node's (a's commits happened at terms
        # <= a.term; a deposed lower-term leader is legitimately behind)
        for leader in rafts:
            if leader.role != LEADER or leader.node_id in dead:
                continue
            for a in rafts:
                if a.term > leader.term:
                    continue
                for i in range(a.commit + 1):
                    if i >= len(leader.log) or leader.log[i] != a.log[i]:
                        raise InvariantViolation(
                            f"incomplete leader {leader.node_id} (term "
                            f"{leader.term}): misses node {a.node_id}'s "
                            f"committed entry {i}"
                        )

    async def chaos_task() -> None:
        while True:
            await ms.time.sleep(0.5 + ms.rand() * 2.5)
            victim = ms.randrange(n_nodes)
            dead.add(victim)
            handle.kill(nodes[victim].id)
            await ms.time.sleep(0.3 + ms.rand() * 1.7)
            # fresh RaftNode object: volatile state lost, durable state kept
            old = rafts[victim]
            fresh = RaftNode(
                victim, n_nodes, addrs, buggy=buggy, client_rate=client_rate
            )
            fresh.term, fresh.voted_for = old.term, old.voted_for
            fresh.log = list(old.log)
            fresh.next_cmd = old.next_cmd
            rafts[victim] = fresh
            dead.discard(victim)
            handle.restart(nodes[victim].id)
            nodes[victim].spawn(fresh.run())

    if chaos and plan is None:
        ms.spawn(chaos_task())

    async def partition_task() -> None:
        # random bipartition, hold, heal — mirrors the TPU engine's
        # partition chaos (SimState.link_ok) on the host NetSim clog masks
        net = ms.plugin.simulator(NetSim)
        ids = [n.id for n in nodes]
        while True:
            await ms.time.sleep(0.3 + ms.rand() * 1.2)
            side = [ms.rand() < 0.5 for _ in ids]
            group_a = [i for i, s_ in zip(ids, side) if s_]
            group_b = [i for i, s_ in zip(ids, side) if not s_]
            net.partition(group_a, group_b)
            await ms.time.sleep(0.5 + ms.rand() * 1.5)
            net.heal_partition(group_a, group_b)

    if partitions and plan is None:
        ms.spawn(partition_task())

    driver = None
    if plan is not None:
        from madsim_tpu import nemesis as nem

        net = ms.plugin.simulator(NetSim)
        if lineage:
            net.lineage.enable()

        def on_wipe(i: int) -> None:
            # crash-with-wipe: the next incarnation starts from init
            # state (durable state gone), like the device's wipe path
            rafts[i] = None

        driver = nem.NemesisDriver(
            plan,
            handle,
            node_ids=[n.id for n in nodes],
            horizon_us=int(virtual_secs * 1e6),
            seed=seed,
            on_wipe=on_wipe,
            occ_off=occ_off,
            on_crash=dead.add,
        )
        driver.install()

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    while t.elapsed() < end:
        await ms.time.sleep(0.01)
        check_invariants()
    stats = {
        "events": ms.plugin.simulator(NetSim).stat().msg_count,
        "commits": [r.commit for r in rafts],
        "max_term": max(r.term for r in rafts),
    }
    if driver is not None:
        # the comparator surfaces (madsim_tpu/oracle.py): the applied
        # schedule stream, occurrence masks, skew assignment, coin draw
        # log, fire counts, lineage mirror, and a canonical durable-state
        # snapshot for digesting
        net = ms.plugin.simulator(NetSim)
        stats["nemesis"] = {
            "applied": list(driver.applied),
            "occ_fired": dict(driver.occ_fired),
            "node_skew": dict(getattr(handle.time, "node_skew", {}) or {}),
            "node_ids": [n.id for n in nodes],
            "coins": driver.coins,
            "fires": driver.fire_counts(),
            "lineage": net.lineage if lineage else None,
            "state": [
                (r.term, r.voted_for, tuple(r.log), r.commit, r.next_cmd)
                for r in rafts
            ],
        }
    return stats


def fuzz_one_seed(
    seed: int,
    n_nodes: int = 5,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.1,
    chaos: bool = True,
    buggy: bool = False,
    client_rate: float = 0.5,
    partitions: bool = False,
    plan=None,
    occ_off=None,
    lineage: bool = False,
) -> dict:
    """One complete fuzzed execution (the unit the reference runs per thread).

    With `plan=` (a `nemesis.FaultPlan`), chaos comes from the compiled
    per-seed schedule via `NemesisDriver` instead of the host-native
    chaos/partition tasks — the schedule-matched mode the differential
    oracle (`madsim_tpu/oracle.py`) replays; the returned dict carries a
    `"nemesis"` artifact bundle (applied stream, coin draws, skew, state
    snapshot, optional lineage when `lineage=True`)."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(
            n_nodes, virtual_secs, chaos, buggy, client_rate, partitions,
            plan=plan, occ_off=occ_off, seed=seed, lineage=lineage,
        )
    )
