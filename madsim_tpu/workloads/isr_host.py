"""ISR log replication on the host runtime: isr's debuggable twin.

Same protocol as `madsim_tpu.tpu.isr` written as host coroutines: a
fixed leader (node 0) with a dynamic In-Sync Replica set, follower
fetch/response replication (an rpc return IS the fetch response, so the
device spec's echo matching is the runtime's request/response pairing
here), eviction of stale fetchers, and a high watermark advanced to the
minimum acked offset across the ISR. The membership axis shows up two
ways: host-native chaos wipes a fraction of restarts (a rejoining
replica restarts from offset 0), and plan mode replays a compiled
FaultPlan — including `reconfig` clauses — through `NemesisDriver`,
whose `on_wipe` hook is what makes a join a FRESH disk.

The ISR catch-up contract is checked at every leader mutation point
(fetch apply, produce/evict tick), not just at the end: the planted
bug's stale admission heals within a fetch round-trip, so an end-only
check would miss it.

`fuzz_one_seed(seed)` runs one execution under loss + crash/wipe chaos
and verifies the same invariants as the device face.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Set

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, rpc

RPC_TIMEOUT = 0.080
TICK = 0.025
REPL_TIMEOUT = 0.150
PRODUCE_RATE = 0.7
WIPE_FRAC = 0.4  # host-native chaos: fraction of restarts that wipe


class InvariantViolation(AssertionError):
    pass


@rpc.rpc_request
class Fetch:
    def __init__(self, src, leo, sent_t):
        self.src, self.leo, self.sent_t = src, leo, sent_t


@dataclass
class IsrNode:
    node_id: int
    n: int
    addrs: List[str]
    buggy: bool = False  # stale ISR re-admission: no catch-up check

    # durable (the log and the leader's replication bookkeeping)
    leo: int = 0
    hw: int = 0
    isr: Set[int] = field(default_factory=set)
    fa: Dict[int, int] = field(default_factory=dict)
    lf_t: Dict[int, float] = field(default_factory=dict)

    def __post_init__(self):
        if not self.isr and not self.fa:
            self.isr = set(range(self.n))
            self.fa = {r: 0 for r in range(self.n)}

    # ------------------------------------------------------- leader internals

    def _advance_hw(self) -> None:
        self.isr.add(0)  # the leader's own membership is pinned
        self.hw = max(self.hw, min(self.fa.get(r, 0) for r in self.isr))

    def _assert_contract(self) -> None:
        if self.hw > self.leo:
            raise InvariantViolation(
                f"watermark sanity: leader hw {self.hw} > leo {self.leo}"
            )
        for r in sorted(self.isr):
            if self.fa.get(r, 0) < self.hw:
                raise InvariantViolation(
                    f"ISR catch-up contract: replica {r} is in the ISR "
                    f"with acked offset {self.fa.get(r, 0)} < hw {self.hw}"
                )

    # ------------------------------------------------------------- handlers

    async def on_fetch(self, req: Fetch):
        # apply only a fetch newer than the last applied from this
        # replica: reorders/duplicates drop, a wipe-join's legitimate
        # offset regression (fresh send time) applies
        if req.sent_t > self.lf_t.get(req.src, 0.0):
            self.lf_t[req.src] = req.sent_t
            ack = min(req.leo, self.leo)
            self.fa[req.src] = ack
            if self.buggy:
                # THE PLANTED BUG: unconditional re-admission
                self.isr.add(req.src)
            elif ack >= self.hw:
                self.isr.add(req.src)
            else:
                self.isr.discard(req.src)
            self._advance_hw()
            self._assert_contract()
        return (self.leo, self.hw)

    # --------------------------------------------------------------- loops

    async def run(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[self.node_id])
        if self.node_id == 0:
            rpc.add_rpc_handler(self.ep, Fetch, self.on_fetch)
        t = ms.time.current()
        while True:
            await ms.time.sleep(TICK)
            now = t.elapsed()
            if self.node_id == 0:
                if ms.rand() < PRODUCE_RATE:
                    self.leo += 1
                    self.fa[0] = self.leo
                for r in list(self.isr):
                    if r != 0 and now - self.lf_t.get(r, 0.0) > REPL_TIMEOUT:
                        self.isr.discard(r)
                self._advance_hw()
                self._assert_contract()
                continue
            try:
                l_leo, l_hw = await ms.time.timeout(
                    RPC_TIMEOUT,
                    rpc.call(self.ep, self.addrs[0],
                             Fetch(self.node_id, self.leo, now)),
                )
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                continue
            # wholesale adoption of the leader's (leo, hw) — instant
            # catch-up, truncation after a leader wipe falls out free
            self.leo, self.hw = l_leo, l_hw
            if self.hw > self.leo:
                raise InvariantViolation(
                    f"watermark sanity: node {self.node_id} adopted "
                    f"hw {self.hw} > leo {self.leo}"
                )


# ------------------------------------------------------------------ harness


def check_invariants(nodes: List[IsrNode]) -> dict:
    nodes[0]._assert_contract()
    for node in nodes:
        if node.hw > node.leo:
            raise InvariantViolation(
                f"watermark sanity: node {node.node_id} has hw "
                f"{node.hw} > leo {node.leo}"
            )
    return {"hw": nodes[0].hw, "isr_size": len(nodes[0].isr)}


async def _fuzz_body(
    n_nodes: int,
    virtual_secs: float,
    chaos: bool,
    buggy: bool,
    plan=None,
    occ_off=None,
    seed=None,
) -> dict:
    handle = ms.Handle.current()
    from madsim_tpu.net import NetSim

    addrs = [f"10.0.6.{i + 1}:7400" for i in range(n_nodes)]
    cns: list = [None] * n_nodes

    def make_node(i: int) -> IsrNode:
        """Fresh node; the log and leader bookkeeping carry over from
        the previous incarnation unless wiped."""
        old = cns[i]
        fresh = IsrNode(i, n_nodes, addrs, buggy=buggy)
        if old is not None:
            fresh.leo, fresh.hw = old.leo, old.hw
            fresh.isr = set(old.isr)
            fresh.fa = dict(old.fa)
            fresh.lf_t = dict(old.lf_t)
        cns[i] = fresh
        return fresh

    nodes = []
    if plan is not None:
        def make_init(i: int):
            def _init():
                return make_node(i).run()

            return _init

        for i in range(n_nodes):
            node = (
                handle.create_node()
                .name(f"isr-{i}")
                .ip(f"10.0.6.{i + 1}")
                .init(make_init(i))
                .build()
            )
            nodes.append(node)
    else:
        for i in range(n_nodes):
            node = handle.create_node().name(f"isr-{i}").ip(
                f"10.0.6.{i + 1}"
            ).build()
            node.spawn(make_node(i).run())
            nodes.append(node)

    async def chaos_task() -> None:
        while True:
            await ms.time.sleep(0.5 + ms.rand() * 1.5)
            victim = ms.randrange(n_nodes)
            handle.kill(nodes[victim].id)
            await ms.time.sleep(0.3 + ms.rand() * 0.6)
            if ms.rand() < WIPE_FRAC:
                cns[victim] = None  # membership churn: rejoin fresh
            fresh = make_node(victim)
            handle.restart(nodes[victim].id)
            nodes[victim].spawn(fresh.run())

    if chaos and plan is None:
        ms.spawn(chaos_task())

    driver = None
    if plan is not None:
        from madsim_tpu import nemesis as nem

        def on_wipe(i: int) -> None:
            cns[i] = None  # next incarnation starts from init state

        driver = nem.NemesisDriver(
            plan,
            handle,
            node_ids=[n.id for n in nodes],
            horizon_us=int(virtual_secs * 1e6),
            seed=seed,
            on_wipe=on_wipe,
            occ_off=occ_off,
        )
        driver.install()

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    while t.elapsed() < end:
        await ms.time.sleep(0.05)
    stats = check_invariants(cns)
    stats["events"] = ms.plugin.simulator(NetSim).stat().msg_count
    if driver is not None:
        stats["nemesis"] = {
            "applied": list(driver.applied),
            "occ_fired": dict(driver.occ_fired),
            "node_skew": dict(getattr(handle.time, "node_skew", {}) or {}),
            "node_ids": [n.id for n in nodes],
            "coins": driver.coins,
            "fires": driver.fire_counts(),
            "state": [
                (cn.leo, cn.hw, tuple(sorted(cn.isr)),
                 tuple(sorted(cn.fa.items())))
                for cn in cns
            ],
        }
    return stats


def fuzz_one_seed(
    seed: int,
    n_nodes: int = 5,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.1,
    chaos: bool = True,
    buggy: bool = False,
    plan=None,
    occ_off=None,
) -> dict:
    """One complete fuzzed execution, verified by the same oracle.

    With `plan=` (a `nemesis.FaultPlan`), chaos — including reconfig
    membership churn — comes from the compiled per-seed schedule via
    `NemesisDriver`; the returned dict then carries a `"nemesis"`
    artifact bundle."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(
            n_nodes, virtual_secs, chaos, buggy,
            plan=plan, occ_off=occ_off, seed=seed,
        )
    )
