"""WAL append service on the host runtime: wal's debuggable twin.

Same protocol as `madsim_tpu.tpu.wal` written as host coroutines — and
unlike the device face, with REAL bytes: the server appends checksummed
records to an `fs.File`, fsyncs via `sync_all` (which raises EIO inside
a DiskFault degraded window), and recovery RE-READS the file, parsing
the record stream until the first incomplete or checksum-failing record
— the byte-level torn-tail handling the device spec abstracts behind
its watermark. A `disk_crash` power-fails the node's filesystem (the
unsynced tail is lost, or torn to a seed-pure prefix), so the lost-ack
invariant means exactly what it means on the device:

    a client whose last ack was observed under the server's current
    incarnation nonce must never be ahead of the server's log.

The planted bug (`buggy=True`) acks the append the moment the write
lands, syncing only on a periodic group-commit loop; the correct server
fsyncs before acking and refuses the ack when the dying disk's fsync
raises EIO. `fuzz_one_seed(seed)` runs one execution under host-native
durability chaos, or — with `plan=` — replays a compiled DiskFault
schedule through `NemesisDriver` (the twin-test/oracle path).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional

import madsim_tpu as ms
from madsim_tpu import fs
from madsim_tpu.net import Endpoint, rpc

RPC_TIMEOUT = 0.120
TICK = 0.020
SYNC_INTERVAL = 0.120
APPEND_RATE = 0.7
WAL_PATH = "wal"
MASK = 0xFFFFFFFF
HEADER = 8  # bytes: (nonce, nonce ^ MASK)
REC = 8  # bytes per record: (index, index ^ MASK)


class InvariantViolation(AssertionError):
    pass


@rpc.rpc_request
class Append:
    def __init__(self, src, count):
        self.src, self.count = src, count


def parse_wal(data: bytes) -> "tuple[Optional[int], int]":
    """(nonce, record count) from raw WAL bytes — recovery's only input.

    Records are length-fixed and checksummed, and carry their own
    1-based index: parsing stops at the first incomplete, corrupt, or
    out-of-sequence record, so a TORN tail (a prefix of the last
    unsynced append) is dropped exactly like a missing one. A header
    that fails its checksum means no durable identity at all."""
    if len(data) < HEADER:
        return None, 0
    nonce, chk = struct.unpack_from(">II", data, 0)
    if chk != nonce ^ MASK or nonce == 0:
        return None, 0
    n, off = 0, HEADER
    while off + REC <= len(data):
        idx, ichk = struct.unpack_from(">II", data, off)
        if ichk != idx ^ MASK or idx != n + 1:
            break
        n, off = n + 1, off + REC
    return nonce, n


@dataclass
class WalNode:
    node_id: int
    n: int
    addrs: List[str]
    buggy: bool = False  # ack-before-fsync

    def __post_init__(self):
        # server durable identity/log — None until recovery reads the
        # file (the checker skips a still-recovering server)
        self.nonce: Optional[int] = None
        self.log_len: Optional[int] = None
        # client observation plane (carried across crash/restart like
        # the device's crash-preserve; a wipe constructs a fresh node)
        self.sent = 0
        self.acked = 0
        self.srv_nonce = 0

    # ------------------------------------------------------ server handlers

    async def on_append(self, req: Append):
        if self.log_len is None:
            return (0, 0)  # still recovering
        idx = self.log_len + 1
        rec = struct.pack(">II", idx, idx ^ MASK)
        try:
            await self.f.write_all_at(rec, HEADER + REC * self.log_len)
        except OSError:
            return (0, 0)
        self.log_len = idx
        if not self.buggy:
            # fsync-before-ack: the dying disk's EIO means the append
            # is NOT durable — refuse the ack (the record stays in the
            # page cache; recovery keeps it only if a later sync lands)
            try:
                await self.f.sync_all()
            except OSError:
                return (0, 0)
        # THE PLANTED BUG (buggy=True): this ack leaves now; the bytes
        # reach the disk only at the next group-commit sync
        return (self.nonce, self.log_len)

    # --------------------------------------------------------------- loops

    async def _recover(self) -> None:
        """Rebuild the durable plane from the file — the host analog of
        the device's watermark restore + on_recover."""
        try:
            data = await fs.read(WAL_PATH)
        except FileNotFoundError:
            data = b""
        nonce, count = parse_wal(data)
        if nonce is None:
            # fresh disk (first boot or a wipe): mint an incarnation
            # and make its directory entry + header durable before
            # serving anything — boot is fsynced
            nonce, count = 1 + ms.randrange(1 << 30), 0
            f = await fs.File.create(WAL_PATH)
            await f.write_all_at(struct.pack(">II", nonce, nonce ^ MASK), 0)
            while True:
                try:
                    await f.sync_all()
                    break
                except OSError:
                    await ms.time.sleep(TICK)
            self.f = f
        else:
            self.f = await fs.File.open(WAL_PATH)
            # drop any torn/unsynced garbage past the parsed prefix so
            # new records land contiguously
            await self.f.set_len(HEADER + REC * count)
        self.nonce, self.log_len = nonce, count

    async def _call(self, msg):
        try:
            return await ms.time.timeout(
                RPC_TIMEOUT, rpc.call(self.ep, self.addrs[0], msg)
            )
        except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
            return None

    async def run(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[self.node_id])
        if self.node_id == 0:
            await self._recover()
            rpc.add_rpc_handler(self.ep, Append, self.on_append)
            while True:
                # group commit: best-effort — a degraded disk refuses
                # (EIO), a dead one loses whatever never synced
                await ms.time.sleep(SYNC_INTERVAL)
                try:
                    await self.f.sync_all()
                except OSError:
                    pass
            return
        while True:
            await ms.time.sleep(TICK)
            if ms.rand() >= APPEND_RATE:
                continue
            self.sent += 1
            resp = await self._call(Append(self.node_id, self.sent))
            if not resp or not resp[0]:
                continue
            nonce, count = resp
            if nonce == self.srv_nonce:
                self.acked = max(self.acked, count)
            else:
                # a fresh incarnation voids the old observation
                self.srv_nonce, self.acked = nonce, count


# ------------------------------------------------------------------ harness


def check_invariants(cns: List[Optional[WalNode]]) -> dict:
    """The lost-ack claim, host face (same guards as the device's):
    only clients observing the server's CURRENT incarnation count, and
    a still-recovering (or down) server is skipped — its in-memory log
    is the pre-crash maximum, never below an acked count."""
    srv = cns[0]
    stats = {"max_acked": 0}
    if srv is None or srv.log_len is None:
        return stats
    for i in range(1, len(cns)):
        c = cns[i]
        if c is None or c.srv_nonce != srv.nonce:
            continue
        stats["max_acked"] = max(stats["max_acked"], c.acked)
        if c.acked > srv.log_len:
            raise InvariantViolation(
                f"lost ack: node {i} was acked {c.acked} appends under "
                f"nonce {c.srv_nonce}, but the server recovered only "
                f"{srv.log_len} — an acked append never reached the disk"
            )
    return stats


async def _fuzz_body(
    n_nodes: int,
    virtual_secs: float,
    chaos: bool,
    buggy: bool,
    disk: bool,
    plan=None,
    occ_off=None,
    seed=None,
) -> dict:
    handle = ms.Handle.current()
    from madsim_tpu.net import NetSim

    addrs = [f"10.0.8.{i + 1}:7600" for i in range(n_nodes)]
    cns: list = [None] * n_nodes

    def make_node(i: int) -> WalNode:
        """Fresh node. The server carries NOTHING — its state is the
        file, recovery re-reads it (that asymmetry is the protocol).
        Clients carry their observation plane unless wiped."""
        old = cns[i]
        fresh = WalNode(i, n_nodes, addrs, buggy=buggy)
        if old is not None and i != 0:
            fresh.sent = old.sent
            fresh.acked = old.acked
            fresh.srv_nonce = old.srv_nonce
        cns[i] = fresh
        return fresh

    nodes = []
    if plan is not None:
        def make_init(i: int):
            def _init():
                return make_node(i).run()

            return _init

        for i in range(n_nodes):
            node = (
                handle.create_node()
                .name(f"wal-{i}")
                .ip(f"10.0.8.{i + 1}")
                .init(make_init(i))
                .build()
            )
            nodes.append(node)
    else:
        for i in range(n_nodes):
            node = handle.create_node().name(f"wal-{i}").ip(
                f"10.0.8.{i + 1}"
            ).build()
            node.spawn(make_node(i).run())
            nodes.append(node)

    async def chaos_task() -> None:
        """Host-native durability chaos, the DiskFault phase shape:
        degrade (slow writes + EIO fsync) -> die (power fail, maybe
        torn) -> recover."""
        fs_sim = ms.plugin.simulator(fs.FsSim)
        while True:
            await ms.time.sleep(0.3 + ms.rand() * 0.9)
            victim = ms.randrange(n_nodes)
            vid = nodes[victim].id
            fs_sim.set_disk_fault(vid, extra_ns=30_000_000)
            await ms.time.sleep(0.08 + ms.rand() * 0.17)
            handle.kill(vid)
            fs_sim.clear_disk_fault(vid)
            torn = ms.rand() < 0.5
            fs_sim.power_fail_node(
                vid,
                torn_extent=(
                    (lambda n: ms.randrange(n + 1)) if torn else None
                ),
            )
            await ms.time.sleep(0.2 + ms.rand() * 0.6)
            fresh = make_node(victim)
            handle.restart(vid)
            nodes[victim].spawn(fresh.run())

    if chaos and disk and plan is None:
        ms.spawn(chaos_task())

    driver = None
    if plan is not None:
        from madsim_tpu import nemesis as nem

        def on_wipe(i: int) -> None:
            cns[i] = None

        driver = nem.NemesisDriver(
            plan,
            handle,
            node_ids=[n.id for n in nodes],
            horizon_us=int(virtual_secs * 1e6),
            seed=seed,
            on_wipe=on_wipe,
            occ_off=occ_off,
        )
        driver.install()

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    stats = {"max_acked": 0}
    while t.elapsed() < end:
        await ms.time.sleep(0.05)
        got = check_invariants(cns)
        stats["max_acked"] = max(stats["max_acked"], got["max_acked"])
    srv = cns[0]
    stats["final_log_len"] = (
        srv.log_len if srv is not None and srv.log_len is not None else -1
    )
    stats["events"] = ms.plugin.simulator(NetSim).stat().msg_count
    if driver is not None:
        stats["nemesis"] = {
            "applied": list(driver.applied),
            "occ_fired": dict(driver.occ_fired),
            "node_skew": dict(getattr(handle.time, "node_skew", {}) or {}),
            "node_ids": [n.id for n in nodes],
            "coins": driver.coins,
            "fires": driver.fire_counts(),
            "state": [
                (cn.nonce, cn.log_len) if cn and i == 0
                else (cn.srv_nonce, cn.acked) if cn else None
                for i, cn in enumerate(cns)
            ],
        }
    return stats


def fuzz_one_seed(
    seed: int,
    n_nodes: int = 4,
    virtual_secs: float = 8.0,
    loss_rate: float = 0.02,
    chaos: bool = True,
    buggy: bool = False,
    disk: bool = True,
    plan=None,
    occ_off=None,
) -> dict:
    """One complete fuzzed execution, verified by the same oracle.

    `disk=False` is the quiet-disk control: no durability chaos at all
    — the buggy server's early acks are then indistinguishable from
    correct ones, and the run must be clean. With `plan=` (a
    `nemesis.FaultPlan`), chaos comes from the compiled per-seed
    schedule via `NemesisDriver` (torn extents drawn through
    `ScheduleCoins.disk_torn_extent` — the oracle-checked host coin);
    the returned dict then carries a `"nemesis"` artifact bundle."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(
            n_nodes, virtual_secs, chaos, buggy, disk,
            plan=plan, occ_off=occ_off, seed=seed,
        )
    )
