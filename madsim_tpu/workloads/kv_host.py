"""The replicated KV on the host runtime: kv's debuggable twin.

Same protocol as `madsim_tpu.tpu.kv` written the way a user of the host
runtime writes distributed code — async tasks, typed RPC over `Endpoint`,
virtual-time timers, chaos via `Handle.kill/restart` and NetSim partitions:

  * primary/backup with epoch claims (epoch = gen * N + node_id); a replica
    missing heartbeats claims a higher epoch and gathers CLAIM acks that
    carry each responder's whole store (merged by highest revision);
  * mandate recovery: a fresh primary re-commits every merged key under its
    own epoch through the normal write quorum before serving anything
    (adopt-then-repropose — the fuzz-found stale-serve bug's fix);
  * quorum writes and read-index reads; replicas reject lower epochs;
  * every ACKED client op is recorded with invoke/response virtual times.

`fuzz_one_seed(seed)` runs one complete execution and verifies the
recorded histories with the SAME exact oracle as the device face: per-key
Wing-Gong linearizability (`tpu/linearize.py`) plus pairwise real-time
revision monotonicity. `buggy=True` plants the canonical stale-read bug
(serve reads locally, no quorum probe) to prove the oracle bites.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, rpc

REPLICA, CLAIMING, PRIMARY = 0, 1, 2
OP_READ, OP_WRITE = 1, 2
REV_STRIDE = 1 << 10

TICK = 0.025
HB_TIMEOUT_LO, HB_TIMEOUT_HI = 0.150, 0.300
RPC_TIMEOUT = 0.120
CLIENT_RATE = 0.7
WRITE_FRAC = 0.5


class InvariantViolation(AssertionError):
    pass


@rpc.rpc_request
class Heartbeat:
    def __init__(self, epoch):
        self.epoch = epoch


@rpc.rpc_request
class Claim:
    def __init__(self, epoch):
        self.epoch = epoch


@rpc.rpc_request
class WriteRep:
    def __init__(self, epoch, rev, key, val):
        self.epoch, self.rev, self.key, self.val = epoch, rev, key, val


@rpc.rpc_request
class ReadProbe:
    def __init__(self, epoch):
        self.epoch = epoch


@rpc.rpc_request
class ClientReq:
    def __init__(self, kind, key, val):
        self.kind, self.key, self.val = kind, key, val


@dataclass
class KvNode:
    node_id: int
    n: int
    addrs: List[str]
    n_keys: int = 4
    buggy: bool = False

    epoch: int = 0
    role: int = REPLICA
    last_hb: float = 0.0
    store: Dict[int, Tuple[int, int]] = field(default_factory=dict)  # k -> (val, rev)
    wcount: int = 0
    recover_left: List[int] = field(default_factory=list)
    serving: bool = True  # False while a mandate recovery is in flight
    history: List[tuple] = field(default_factory=list)  # (kind,key,val,rev,tinv,trsp)
    next_val: int = 1

    def believed_primary(self) -> int:
        return self.epoch % self.n

    # ------------------------------------------------------------- handlers

    def adopt(self, epoch: int) -> None:
        if epoch > self.epoch:
            self.epoch = epoch
            self.role = REPLICA
            self.serving = True
        if epoch >= self.epoch:
            self.last_hb = ms.time.current().elapsed()

    async def on_heartbeat(self, req: Heartbeat):
        self.adopt(req.epoch)
        return self.epoch

    async def on_claim(self, req: Claim):
        if req.epoch > self.epoch:
            self.epoch = req.epoch
            self.role = REPLICA  # deposes a primary
            self.last_hb = ms.time.current().elapsed()
            return (True, dict(self.store))
        return (False, {})

    async def on_write_rep(self, req: WriteRep):
        ok = req.epoch >= self.epoch
        self.adopt(req.epoch)
        if ok:
            cur = self.store.get(req.key)
            if cur is None or req.rev > cur[1]:
                self.store[req.key] = (req.val, req.rev)
        return ok

    async def on_read_probe(self, req: ReadProbe):
        ok = req.epoch >= self.epoch
        self.adopt(req.epoch)
        return ok

    async def on_client_req(self, req: ClientReq):
        """Returns (ok, val, rev). Dropped requests return ok=False (the
        client retries) — a primary mid-recovery sheds load exactly like
        the device spec."""
        if self.buggy and req.kind == OP_READ:
            # the planted stale-read bug: ANY node answers a read straight
            # from its local store, no quorum probe
            val, rev = self.store.get(req.key, (0, 0))
            return (True, val, rev)
        if self.role != PRIMARY or not self.serving:
            return (False, 0, 0)
        if req.kind == OP_WRITE:
            rev = await self.quorum_write(req.key, req.val)
            if rev is None:
                return (False, 0, 0)
            return (True, req.val, rev)
        # read-index: serve only after a majority confirms this epoch
        if not await self.quorum_probe():
            return (False, 0, 0)
        val, rev = self.store.get(req.key, (0, 0))
        return (True, val, rev)

    # ------------------------------------------------------- quorum rounds

    async def _gather(self, make_call) -> int:
        """Fan a call to every peer CONCURRENTLY; 1 + positive acks (self
        counts). Serial awaits would stack up to (n-1) x RPC_TIMEOUT of
        pure waiting under a partition — enough to starve client timeouts
        and stretch the heartbeat period past follower patience."""

        async def one(peer):
            try:
                return bool(await make_call(peer))
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                return False

        tasks = [
            ms.spawn(one(peer))
            for peer in range(self.n) if peer != self.node_id
        ]
        acks = 1
        for t in tasks:
            if await t:
                acks += 1
        return acks

    async def quorum_write(self, key: int, val: int) -> Optional[int]:
        epoch = self.epoch
        self.wcount += 1
        rev = epoch * REV_STRIDE + self.wcount

        async def call(peer):
            return await ms.time.timeout(
                RPC_TIMEOUT,
                rpc.call(self.ep, self.addrs[peer], WriteRep(epoch, rev, key, val)),
            )

        acks = await self._gather(call)
        if self.epoch != epoch or acks <= self.n // 2:
            return None
        cur = self.store.get(key)
        if cur is None or rev > cur[1]:
            self.store[key] = (val, rev)
        return rev

    async def quorum_probe(self) -> bool:
        epoch = self.epoch

        async def call(peer):
            return await ms.time.timeout(
                RPC_TIMEOUT, rpc.call(self.ep, self.addrs[peer], ReadProbe(epoch))
            )

        # depose re-check must run AFTER the gather (a mid-probe adopt of
        # a higher epoch invalidates the mandate even with majority acks)
        acks = await self._gather(call)
        return self.epoch == epoch and acks > self.n // 2

    async def try_claim(self) -> None:
        gen = self.epoch // self.n + 1
        new_epoch = gen * self.n + self.node_id
        self.role = CLAIMING
        self.epoch = new_epoch
        merged: Dict[int, Tuple[int, int]] = dict(self.store)
        acks = 1

        for peer in range(self.n):
            if peer == self.node_id:
                continue
            try:
                ok, peer_store = await ms.time.timeout(
                    RPC_TIMEOUT,
                    rpc.call(self.ep, self.addrs[peer], Claim(new_epoch)),
                )
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                continue
            if self.epoch != new_epoch:
                return  # deposed mid-claim
            if ok:
                acks += 1
                for k, (v, r) in peer_store.items():
                    cur = merged.get(k)
                    if cur is None or r > cur[1]:
                        merged[k] = (v, r)
        if self.epoch != new_epoch or acks <= self.n // 2:
            return
        # won: merge, then MANDATE RECOVERY — re-commit every merged key
        # under this epoch before serving anything
        self.store = merged
        self.role = PRIMARY
        self.wcount = 0
        self.serving = False
        for k, (v, _r) in sorted(merged.items()):
            while self.role == PRIMARY and self.epoch == new_epoch:
                if await self.quorum_write(k, v) is not None:
                    break
                await ms.time.sleep(TICK)
        if self.role == PRIMARY and self.epoch == new_epoch:
            self.serving = True

    # ----------------------------------------------------------- main loops

    async def run(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[self.node_id])
        rpc.add_rpc_handler(self.ep, Heartbeat, self.on_heartbeat)
        rpc.add_rpc_handler(self.ep, Claim, self.on_claim)
        rpc.add_rpc_handler(self.ep, WriteRep, self.on_write_rep)
        rpc.add_rpc_handler(self.ep, ReadProbe, self.on_read_probe)
        rpc.add_rpc_handler(self.ep, ClientReq, self.on_client_req)
        self.last_hb = ms.time.current().elapsed()
        ms.spawn(self.client_loop())
        hb_timeout = HB_TIMEOUT_LO + ms.rand() * (HB_TIMEOUT_HI - HB_TIMEOUT_LO)
        while True:
            await ms.time.sleep(TICK)
            now = ms.time.current().elapsed()
            if self.role == PRIMARY:
                # (recovery runs inside try_claim, so this loop only ever
                # heartbeats for a serving primary)
                epoch = self.epoch

                async def hb(peer):
                    return await ms.time.timeout(
                        RPC_TIMEOUT,
                        rpc.call(self.ep, self.addrs[peer], Heartbeat(epoch)),
                    )

                await self._gather(hb)
            elif now - self.last_hb > hb_timeout:
                await self.try_claim()
                hb_timeout = HB_TIMEOUT_LO + ms.rand() * (
                    HB_TIMEOUT_HI - HB_TIMEOUT_LO
                )

    async def client_loop(self) -> None:
        """Every node is also a client issuing ops against its believed
        primary, recording every ACKED op with real invoke/response times."""
        cep = await Endpoint.bind(f"{self.addrs[self.node_id].split(':')[0]}:0")
        while True:
            await ms.time.sleep(TICK)
            if ms.rand() >= CLIENT_RATE:
                continue
            is_write = ms.rand() < WRITE_FRAC
            key = ms.randrange(self.n_keys)
            if is_write:
                val = self.node_id * 100_000 + self.next_val
                self.next_val += 1
                req = ClientReq(OP_WRITE, key, val)
            else:
                req = ClientReq(OP_READ, key, 0)
            target = self.addrs[self.believed_primary()]
            tinv = ms.time.current().elapsed()
            try:
                ok, val, rev = await ms.time.timeout(
                    0.4, rpc.call(cep, target, req)
                )
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                continue
            if ok:
                trsp = ms.time.current().elapsed()
                self.history.append(
                    (req.kind, key, val, rev, tinv, trsp)
                )


# ------------------------------------------------------------------ harness


def _check_histories(nodes: List[KvNode]) -> dict:
    """The SAME oracle as the device face: per-key Wing-Gong
    linearizability + pairwise real-time revision monotonicity."""
    from madsim_tpu.tpu.linearize import Op, check_key_history

    ops: List[Op] = []
    for node in nodes:
        for kind, key, val, rev, tinv, trsp in node.history:
            ops.append(Op(
                tinv=int(tinv * 1e6), trsp=int(trsp * 1e6),
                is_write=kind == OP_WRITE, key=key, val=val, rev=rev,
                node=node.node_id,
            ))
    # pairwise rev monotonicity (the device's cheap net)
    by_key: Dict[int, List[Op]] = {}
    for o in ops:
        by_key.setdefault(o.key, []).append(o)
    unmatched = 0
    for key_ops in by_key.values():
        for a in key_ops:
            for b in key_ops:
                if b.tinv > a.trsp and b.rev < a.rev:
                    raise InvariantViolation(
                        f"stale revision: {b} observed after {a} completed"
                    )
        ok, ce, um = check_key_history(key_ops)
        unmatched += um
        if not ok:
            tail = "\n  ".join(str(o) for o in (ce or [])[-12:])
            raise InvariantViolation(
                f"history not linearizable on key "
                f"{key_ops[0].key}:\n  {tail}"
            )
    return {"acked_ops": len(ops), "unmatched_reads": unmatched,
            "keys": len(by_key)}


async def _fuzz_body(
    n_nodes: int, virtual_secs: float, chaos: bool, partitions: bool,
    buggy: bool,
) -> dict:
    handle = ms.Handle.current()
    from madsim_tpu.net import NetSim

    addrs = [f"10.0.2.{i + 1}:7000" for i in range(n_nodes)]
    kvs = [KvNode(i, n_nodes, addrs, buggy=buggy) for i in range(n_nodes)]
    nodes = []
    for i in range(n_nodes):
        node = handle.create_node().name(f"kv-{i}").ip(f"10.0.2.{i + 1}").build()
        node.spawn(kvs[i].run())
        nodes.append(node)

    async def chaos_task() -> None:
        while True:
            await ms.time.sleep(0.8 + ms.rand() * 3.2)
            victim = ms.randrange(n_nodes)
            handle.kill(nodes[victim].id)
            await ms.time.sleep(0.3 + ms.rand() * 1.7)
            old = kvs[victim]
            fresh = KvNode(victim, n_nodes, addrs, buggy=buggy)
            # durable: epoch + store + history (oracle memory); volatile:
            # role/round state (mirrors the device spec's on_restart)
            fresh.epoch = old.epoch
            fresh.store = dict(old.store)
            fresh.history = old.history  # shared list: acked is acked
            fresh.next_val = old.next_val
            kvs[victim] = fresh
            handle.restart(nodes[victim].id)
            nodes[victim].spawn(fresh.run())

    if chaos:
        ms.spawn(chaos_task())

    async def partition_task() -> None:
        net = ms.plugin.simulator(NetSim)
        ids = [n.id for n in nodes]
        while True:
            await ms.time.sleep(0.4 + ms.rand() * 1.6)
            side = [ms.rand() < 0.5 for _ in ids]
            group_a = [i for i, s_ in zip(ids, side) if s_]
            group_b = [i for i, s_ in zip(ids, side) if not s_]
            net.partition(group_a, group_b)
            await ms.time.sleep(0.5 + ms.rand() * 1.5)
            net.heal_partition(group_a, group_b)

    if partitions:
        ms.spawn(partition_task())

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    while t.elapsed() < end:
        await ms.time.sleep(0.05)
    stats = _check_histories(kvs)
    stats["events"] = ms.plugin.simulator(NetSim).stat().msg_count
    stats["max_epoch"] = max(k.epoch for k in kvs)
    return stats


def fuzz_one_seed(
    seed: int,
    n_nodes: int = 5,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.05,
    chaos: bool = False,
    partitions: bool = True,
    buggy: bool = False,
) -> dict:
    """One complete fuzzed execution, verified by the exact oracle."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(n_nodes, virtual_secs, chaos, partitions, buggy)
    )
