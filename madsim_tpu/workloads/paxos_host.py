"""Single-decree Paxos on the host runtime: paxos's debuggable twin.

Same synod as `madsim_tpu.tpu.paxos` written as host coroutines — every
node is proposer, acceptor and learner; dueling proposers are the steady
state (the reference's debuggable-multi-node-sim pattern,
tonic-example/tests/test.rs:155-278):

  * an undecided node's retry timer starts PREPARE with a fresh unique
    ballot b = round * N + nid; acceptors promise (never regressing) and
    report their highest accepted (ballot, value);
  * on a promise majority the proposer pushes THE HIGHEST-BALLOT ACCEPTED
    VALUE IT DISCOVERED — its own candidate only if phase 1 found none
    (the rule whose omission is the canonical Paxos bug, `buggy=True`);
  * self-votes follow the same acceptor rules as any peer and are
    RECORDED (the phantom-self-vote bug the device fuzz caught as trophy
    #8 — docs/bugs_found.md — is ruled out on both faces the same way);
  * acceptors accept unless promised higher; an ACCEPTED majority decides;
    decided nodes gossip DECIDED so laggards learn.

`fuzz_one_seed(seed)` runs one execution under loss + crash + partition
chaos and verifies AGREEMENT (all decided values equal) — the same
invariant as the device face.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Tuple

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, rpc

RETRY_LO, RETRY_HI = 0.150, 0.400
GOSSIP = 0.200
RPC_TIMEOUT = 0.060


class InvariantViolation(AssertionError):
    pass


@rpc.rpc_request
class Prep:
    def __init__(self, bal):
        self.bal = bal


@rpc.rpc_request
class Acc:
    def __init__(self, bal, val):
        self.bal, self.val = bal, val


@rpc.rpc_request
class Learn:
    def __init__(self, val):
        self.val = val


@dataclass
class PaxosNode:
    node_id: int
    n: int
    addrs: List[str]
    buggy: bool = False

    # acceptor stable storage (durable — Paxos' one hard requirement)
    promised: int = -1
    acc_bal: int = -1
    acc_val: int = 0
    decided: int = 0
    round: int = 0  # durable: ballots stay unique across restarts

    # ------------------------------------------------------------- handlers

    async def on_prepare(self, req: Prep) -> Tuple[bool, int, int]:
        if req.bal > self.promised:
            self.promised = req.bal
            return (True, self.acc_bal, self.acc_val)
        return (False, -1, 0)

    async def on_accept(self, req: Acc) -> bool:
        if req.bal >= self.promised:
            self.promised = req.bal
            self.acc_bal = req.bal
            self.acc_val = req.val
            return True
        return False

    async def on_learn(self, req: Learn) -> bool:
        if self.decided == 0:
            self.decided = req.val
        return True

    # --------------------------------------------------------------- loops

    async def _quorum(self, make_call) -> List[Optional[object]]:
        """Concurrent fan-out to every peer; None for drops/timeouts."""

        async def one(peer):
            try:
                return await ms.time.timeout(RPC_TIMEOUT, make_call(peer))
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                return None

        tasks = [
            ms.spawn(one(p)) for p in range(self.n) if p != self.node_id
        ]
        return [await t for t in tasks]

    async def propose_once(self) -> None:
        self.round += 1
        bal = self.round * self.n + self.node_id
        my_val = self.node_id * 100_000 + self.round
        # phase 1 — the proposer's own acceptor votes by the same rule,
        # RECORDED (no phantom self-votes), and discovery starts from its
        # own accepted pair
        acks = 0
        best_bal, best_val = self.acc_bal, self.acc_val
        if bal > self.promised:
            self.promised = bal
            acks = 1
        rsp = await self._quorum(
            lambda p: rpc.call(self.ep, self.addrs[p], Prep(bal))
        )
        for r in rsp:
            if r is None or not r[0]:
                continue
            acks += 1
            if r[1] > best_bal:
                best_bal, best_val = r[1], r[2]
        if acks <= self.n // 2 or self.decided:
            return
        # THE rule: push the discovered value when one exists
        if self.buggy:
            push = my_val  # canonical bug: ignore the discovery
        else:
            push = best_val if best_bal >= 0 else my_val
        # phase 2 — self-accept iff our own promise still allows it
        acks = 0
        if bal >= self.promised:
            self.promised = bal
            self.acc_bal, self.acc_val = bal, push
            acks = 1
        rsp = await self._quorum(
            lambda p: rpc.call(self.ep, self.addrs[p], Acc(bal, push))
        )
        acks += sum(1 for r in rsp if r)
        if acks > self.n // 2:
            if self.decided == 0:
                self.decided = push
            await self._quorum(
                lambda p: rpc.call(self.ep, self.addrs[p], Learn(push))
            )

    async def run(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[self.node_id])
        rpc.add_rpc_handler(self.ep, Prep, self.on_prepare)
        rpc.add_rpc_handler(self.ep, Acc, self.on_accept)
        rpc.add_rpc_handler(self.ep, Learn, self.on_learn)
        while True:
            if self.decided:
                await ms.time.sleep(GOSSIP)
                await self._quorum(
                    lambda p: rpc.call(self.ep, self.addrs[p],
                                       Learn(self.decided))
                )
            else:
                await ms.time.sleep(RETRY_LO + ms.rand() * (RETRY_HI - RETRY_LO))
                await self.propose_once()


# ------------------------------------------------------------------ harness


def check_agreement(nodes: List["PaxosNode"]) -> dict:
    vals = {p.decided for p in nodes if p.decided != 0}
    if len(vals) > 1:
        raise InvariantViolation(
            "agreement violated: decided values "
            + str({p.node_id: p.decided for p in nodes})
        )
    return {
        "decided_nodes": sum(1 for p in nodes if p.decided != 0),
        "value": next(iter(vals)) if vals else 0,
    }


async def _fuzz_body(
    n_nodes: int, virtual_secs: float, chaos: bool, partitions: bool,
    buggy: bool,
) -> dict:
    handle = ms.Handle.current()
    from madsim_tpu.net import NetSim

    addrs = [f"10.0.4.{i + 1}:7200" for i in range(n_nodes)]
    pxs = [PaxosNode(i, n_nodes, addrs, buggy=buggy) for i in range(n_nodes)]
    nodes = []
    for i in range(n_nodes):
        node = handle.create_node().name(f"px-{i}").ip(f"10.0.4.{i + 1}").build()
        node.spawn(pxs[i].run())
        nodes.append(node)

    async def chaos_task() -> None:
        while True:
            await ms.time.sleep(0.4 + ms.rand() * 1.6)
            victim = ms.randrange(n_nodes)
            handle.kill(nodes[victim].id)
            await ms.time.sleep(0.2 + ms.rand() * 0.8)
            old = pxs[victim]
            fresh = PaxosNode(victim, n_nodes, addrs, buggy=buggy)
            # durable: the acceptor's stable storage (+ round uniqueness)
            fresh.promised = old.promised
            fresh.acc_bal = old.acc_bal
            fresh.acc_val = old.acc_val
            fresh.decided = old.decided
            fresh.round = old.round
            pxs[victim] = fresh
            handle.restart(nodes[victim].id)
            nodes[victim].spawn(fresh.run())

    if chaos:
        ms.spawn(chaos_task())

    async def partition_task() -> None:
        net = ms.plugin.simulator(NetSim)
        ids = [n.id for n in nodes]
        while True:
            await ms.time.sleep(0.3 + ms.rand() * 1.2)
            side = [ms.rand() < 0.5 for _ in ids]
            group_a = [i for i, s_ in zip(ids, side) if s_]
            group_b = [i for i, s_ in zip(ids, side) if not s_]
            net.partition(group_a, group_b)
            await ms.time.sleep(0.4 + ms.rand() * 1.1)
            net.heal_partition(group_a, group_b)

    if partitions:
        ms.spawn(partition_task())

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    while t.elapsed() < end:
        await ms.time.sleep(0.05)
        # agreement is checked CONTINUOUSLY (like the device's per-step
        # invariant), not only at the horizon — a transient split matters
        check_agreement(pxs)
    stats = check_agreement(pxs)
    stats["events"] = ms.plugin.simulator(NetSim).stat().msg_count
    stats["max_round"] = max(p.round for p in pxs)
    return stats


def fuzz_one_seed(
    seed: int,
    n_nodes: int = 5,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.1,
    chaos: bool = True,
    partitions: bool = True,
    buggy: bool = False,
) -> dict:
    """One complete fuzzed execution, verified continuously."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(n_nodes, virtual_secs, chaos, partitions, buggy)
    )
