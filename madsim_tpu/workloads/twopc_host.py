"""Two-Phase Commit on the host runtime: twopc's debuggable twin.

Same protocol as `madsim_tpu.tpu.twopc` written the way a user of the host
runtime writes distributed code — async tasks, typed RPC over `Endpoint`,
virtual-time timers, chaos via `Handle.kill/restart` and NetSim partitions
(the reference's everything-is-a-debuggable-multi-node-sim pattern,
tonic-example/tests/test.rs:155-278):

  * node 0 is the COORDINATOR running one-shot presumed-abort rounds:
    start txn `tid`, broadcast PREPARE, decide COMMIT only on unanimous
    yes-votes, record the decision durably BEFORE broadcasting OUTCOME
    (the commit point);
  * participants vote (seeded coin), record yes-votes durably (the
    in-doubt state), and run cooperative termination: an unresolved
    yes-vote periodically asks the coordinator (DREQ) for the recorded
    outcome;
  * coordinator recovery: a restart finds an open undecided txn and
    presumed-aborts it.

`fuzz_one_seed(seed)` runs one complete execution under loss + crash +
partition chaos and verifies the SAME invariants as the device face:
atomicity (no two nodes record different outcomes for one tid) and vote
respect (no COMMIT recorded for a txn the node voted NO on). `buggy=True`
plants the canonical wrong participant — an in-doubt timeout unilaterally
aborts instead of asking — to prove the oracle bites on this face too.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, rpc

NONE, COMMIT, ABORT = 0, 1, 2

TXN_GAP = 0.040
PREPARE_TIMEOUT = 0.120
DOUBT_RETRY = 0.080
RPC_TIMEOUT = 0.060
VOTE_YES_P = 0.85


class InvariantViolation(AssertionError):
    pass


@rpc.rpc_request
class Prepare:
    def __init__(self, tid):
        self.tid = tid


@rpc.rpc_request
class Outcome:
    def __init__(self, tid, val):
        self.tid, self.val = tid, val


@rpc.rpc_request
class Dreq:
    def __init__(self, tid):
        self.tid = tid


@dataclass
class TpcNode:
    node_id: int
    n: int
    addrs: List[str]
    buggy: bool = False

    # durable (survives crash/restart — the paper's stable log)
    tid_cur: int = -1
    outcomes: Dict[int, int] = field(default_factory=dict)  # tid -> COMMIT/ABORT
    votes: Dict[int, int] = field(default_factory=dict)  # tid -> my vote

    def record_outcome(self, tid: int, val: int) -> None:
        # first write wins: a recorded outcome is immutable (re-delivered
        # OUTCOMEs / late DREQ answers must not flip it)
        self.outcomes.setdefault(tid, val)

    # ------------------------------------------------------------- handlers

    async def on_prepare(self, req: Prepare):
        """Participant votes. Returns COMMIT (yes) or ABORT (no)."""
        tid = req.tid
        if tid in self.votes:  # duplicate PREPARE must not re-roll
            return self.votes[tid]
        if tid in self.outcomes:
            return ABORT if self.outcomes[tid] == ABORT else COMMIT
        yes = ms.rand() < VOTE_YES_P
        vote = COMMIT if yes else ABORT
        self.votes[tid] = vote
        if not yes:
            # presumed abort: a no-voter records the abort and may forget
            self.record_outcome(tid, ABORT)
        return vote

    async def on_outcome(self, req: Outcome):
        self.record_outcome(req.tid, req.val)
        return True

    async def on_dreq(self, req: Dreq):
        """Coordinator re-sends a recorded outcome; NONE while undecided
        (the in-doubt participant retries)."""
        return self.outcomes.get(req.tid, NONE)

    # --------------------------------------------------------------- loops

    async def run_coordinator(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[0])
        rpc.add_rpc_handler(self.ep, Dreq, self.on_dreq)
        while True:
            await ms.time.sleep(TXN_GAP / 2 + ms.rand() * TXN_GAP / 2)
            # post-restart recovery / presumed abort of an open txn
            if self.tid_cur >= 0 and self.tid_cur not in self.outcomes:
                self.record_outcome(self.tid_cur, ABORT)
                await self._broadcast_outcome(self.tid_cur, ABORT)
                continue
            tid = self.tid_cur = self.tid_cur + 1

            async def ask(peer, tid=tid):
                try:
                    return await ms.time.timeout(
                        PREPARE_TIMEOUT,
                        rpc.call(self.ep, self.addrs[peer], Prepare(tid)),
                    )
                except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                    return NONE

            tasks = [ms.spawn(ask(p)) for p in range(1, self.n)]
            votes = [await t for t in tasks]
            outcome = COMMIT if all(v == COMMIT for v in votes) else ABORT
            # the commit point: record durably, THEN broadcast
            self.record_outcome(tid, outcome)
            await self._broadcast_outcome(tid, outcome)

    async def _broadcast_outcome(self, tid: int, val: int) -> None:
        async def tell(peer):
            try:
                await ms.time.timeout(
                    RPC_TIMEOUT,
                    rpc.call(self.ep, self.addrs[peer], Outcome(tid, val)),
                )
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                pass  # cooperative termination recovers the laggard

        for t in [ms.spawn(tell(p)) for p in range(1, self.n)]:
            await t

    async def run_participant(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[self.node_id])
        rpc.add_rpc_handler(self.ep, Prepare, self.on_prepare)
        rpc.add_rpc_handler(self.ep, Outcome, self.on_outcome)
        while True:
            await ms.time.sleep(DOUBT_RETRY)
            # cooperative termination for the OLDEST unresolved yes-vote
            doubt = [
                t for t, v in self.votes.items()
                if v == COMMIT and t not in self.outcomes
            ]
            if not doubt:
                continue
            tid = min(doubt)
            if self.buggy:
                # the canonical WRONG participant: patience ran out =>
                # abort the in-doubt txn locally instead of asking
                self.record_outcome(tid, ABORT)
                continue
            try:
                known = await ms.time.timeout(
                    RPC_TIMEOUT, rpc.call(self.ep, self.addrs[0], Dreq(tid))
                )
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                continue
            if known != NONE:
                self.record_outcome(tid, known)

    async def run(self) -> None:
        if self.node_id == 0:
            await self.run_coordinator()
        else:
            await self.run_participant()


# ------------------------------------------------------------------ harness


def check_invariants(nodes: List[TpcNode]) -> dict:
    """The SAME oracle as the device face (tpu/twopc.py
    check_invariants): atomicity + vote respect, over full recorded
    histories instead of device rings."""
    decided = 0
    for a in nodes:
        for tid, val in a.outcomes.items():
            decided += 1
            for b in nodes:
                other = b.outcomes.get(tid)
                if other is not None and other != val:
                    raise InvariantViolation(
                        f"atomicity: txn {tid} recorded {val} on node "
                        f"{a.node_id} but {other} on node {b.node_id}"
                    )
        for tid, vote in a.votes.items():
            if vote == ABORT and a.outcomes.get(tid) == COMMIT:
                raise InvariantViolation(
                    f"vote respect: node {a.node_id} recorded COMMIT for "
                    f"txn {tid} it voted NO on"
                )
    return {"decided_records": decided}


async def _fuzz_body(
    n_nodes: int, virtual_secs: float, chaos: bool, partitions: bool,
    buggy: bool,
) -> dict:
    handle = ms.Handle.current()
    from madsim_tpu.net import NetSim

    addrs = [f"10.0.3.{i + 1}:7100" for i in range(n_nodes)]
    tps = [TpcNode(i, n_nodes, addrs, buggy=buggy) for i in range(n_nodes)]
    nodes = []
    for i in range(n_nodes):
        node = handle.create_node().name(f"tpc-{i}").ip(f"10.0.3.{i + 1}").build()
        node.spawn(tps[i].run())
        nodes.append(node)

    async def chaos_task() -> None:
        while True:
            await ms.time.sleep(0.4 + ms.rand() * 1.6)
            victim = ms.randrange(n_nodes)
            handle.kill(nodes[victim].id)
            await ms.time.sleep(0.2 + ms.rand() * 0.8)
            old = tps[victim]
            fresh = TpcNode(victim, n_nodes, addrs, buggy=buggy)
            # durable: tid_cur + both rings; volatile: everything else
            fresh.tid_cur = old.tid_cur
            fresh.outcomes = old.outcomes  # shared dict: recorded is recorded
            fresh.votes = old.votes
            tps[victim] = fresh
            handle.restart(nodes[victim].id)
            nodes[victim].spawn(fresh.run())

    if chaos:
        ms.spawn(chaos_task())

    async def partition_task() -> None:
        net = ms.plugin.simulator(NetSim)
        ids = [n.id for n in nodes]
        while True:
            await ms.time.sleep(0.4 + ms.rand() * 1.1)
            side = [ms.rand() < 0.5 for _ in ids]
            group_a = [i for i, s_ in zip(ids, side) if s_]
            group_b = [i for i, s_ in zip(ids, side) if not s_]
            net.partition(group_a, group_b)
            await ms.time.sleep(0.3 + ms.rand() * 0.9)
            net.heal_partition(group_a, group_b)

    if partitions:
        ms.spawn(partition_task())

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    while t.elapsed() < end:
        await ms.time.sleep(0.05)
    stats = check_invariants(tps)
    stats["events"] = ms.plugin.simulator(NetSim).stat().msg_count
    stats["txns_started"] = tps[0].tid_cur + 1
    return stats


def fuzz_one_seed(
    seed: int,
    n_nodes: int = 5,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.1,
    chaos: bool = True,
    partitions: bool = True,
    buggy: bool = False,
) -> dict:
    """One complete fuzzed execution, verified by the exact oracle."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(n_nodes, virtual_secs, chaos, partitions, buggy)
    )
