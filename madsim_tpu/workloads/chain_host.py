"""Chain replication on the host runtime: chain's debuggable twin.

Same protocol as `madsim_tpu.tpu.chain` written as host coroutines: a
fixed chain head -> tail, writes enter at the head, propagate as nested
RPCs (a hop's rpc return IS the hop-ack), commit when the tail applies;
reads are served at the tail. Heavy-tail delays come from the runtime's
own buggify (`ms.buggify.enable()` arms NetSim's 1-5 s straggler tail),
which is what makes the canonical planted bug — a replica missing the
apply-if-newer guard blindly applying late duplicate forwards — roll
stores backwards observably.

`fuzz_one_seed(seed)` runs one execution under loss + crash + tail chaos
and verifies the same invariants as the device face: chain monotonicity,
version coherence, and client-observed version monotonicity.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Tuple

import madsim_tpu as ms
from madsim_tpu.net import Endpoint, rpc

RPC_TIMEOUT = 0.080
TICK = 0.020


class InvariantViolation(AssertionError):
    pass


@rpc.rpc_request
class Fwd:
    def __init__(self, key, val, ver):
        self.key, self.val, self.ver = key, val, ver


@rpc.rpc_request
class WReq:
    def __init__(self, key, val):
        self.key, self.val = key, val


@rpc.rpc_request
class RReq:
    def __init__(self, key):
        self.key = key


@dataclass
class ChainNode:
    node_id: int
    n: int
    addrs: List[str]
    n_keys: int = 4
    buggy: bool = False  # blind apply: no if-newer guard

    # durable
    store: Dict[int, Tuple[int, int]] = field(default_factory=dict)  # k -> (val, ver)
    vnext: Dict[int, int] = field(default_factory=dict)  # head only
    history: List[tuple] = field(default_factory=list)  # (kind, key, ver, tinv, trsp)

    def apply(self, key: int, val: int, ver: int) -> None:
        cur = self.store.get(key)
        if self.buggy or cur is None or ver > cur[1]:
            self.store[key] = (val, ver)

    async def _forward(self, key: int, val: int, ver: int) -> bool:
        """Relay down the chain until the hop-ack; True once acked."""
        nxt = self.addrs[self.node_id + 1]
        for _ in range(40):
            try:
                return bool(await ms.time.timeout(
                    RPC_TIMEOUT, rpc.call(self.ep, nxt, Fwd(key, val, ver))
                ))
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                await ms.time.sleep(TICK)
        return False

    # ------------------------------------------------------------- handlers

    async def on_fwd(self, req: Fwd) -> bool:
        self.apply(req.key, req.val, req.ver)
        if self.node_id == self.n - 1:
            return True  # tail: committed
        # relay; the nested ack unwinds the chain hop by hop
        return await self._forward(req.key, req.val, req.ver)

    async def on_wreq(self, req: WReq):
        """Head: assign a fresh version, apply, push to the tail; the
        reply (the commit ack) carries the committed version."""
        ver = self.vnext.get(req.key, 1)
        self.vnext[req.key] = ver + 1
        self.apply(req.key, req.val, ver)
        ok = await self._forward(req.key, req.val, ver)
        return (ok, ver)

    async def on_rreq(self, req: RReq):
        val, ver = self.store.get(req.key, (0, 0))
        return (val, ver)

    # --------------------------------------------------------------- loops

    async def run(self) -> None:
        self.ep = await Endpoint.bind(self.addrs[self.node_id])
        rpc.add_rpc_handler(self.ep, Fwd, self.on_fwd)
        if self.node_id == 0:
            rpc.add_rpc_handler(self.ep, WReq, self.on_wreq)
        if self.node_id == self.n - 1:
            rpc.add_rpc_handler(self.ep, RReq, self.on_rreq)
        t = ms.time.current()
        nextval = 1
        while True:
            await ms.time.sleep(TICK)
            if ms.rand() >= 0.6:
                continue
            key = ms.randrange(self.n_keys)
            tinv = t.elapsed()
            try:
                if ms.rand() < 0.5:
                    val = self.node_id * 100_000 + nextval
                    nextval += 1
                    ok, ver = await ms.time.timeout(
                        0.4, rpc.call(self.ep, self.addrs[0], WReq(key, val))
                    )
                    if ok:
                        self.history.append(
                            ("w", key, ver, tinv, t.elapsed())
                        )
                else:
                    _val, ver = await ms.time.timeout(
                        0.4,
                        rpc.call(self.ep, self.addrs[self.n - 1], RReq(key)),
                    )
                    self.history.append(
                        ("r", key, ver, tinv, t.elapsed())
                    )
            except (ms.time.TimeoutError_, OSError, ms.sync.ChannelClosed):
                continue


# ------------------------------------------------------------------ harness


def check_invariants(nodes: List[ChainNode]) -> dict:
    # chain monotonicity + version coherence over final stores
    for i in range(len(nodes) - 1):
        up, down = nodes[i].store, nodes[i + 1].store
        for k, (_dv, dver) in down.items():
            uver = up.get(k, (0, 0))[1]
            if uver < dver:
                raise InvariantViolation(
                    f"chain monotonicity: node {i} has ver {uver} for key "
                    f"{k} but downstream node {i + 1} has {dver}"
                )
    seen: Dict[Tuple[int, int], int] = {}
    for node in nodes:
        for k, (val, ver) in node.store.items():
            if ver == 0:
                continue
            if seen.setdefault((k, ver), val) != val:
                raise InvariantViolation(
                    f"coherence: (key {k}, ver {ver}) has two values"
                )
    # client-observed per-key version monotonicity in invocation order
    # real-time check: an op INVOKED after a higher version's ack
    # RESPONDED must not observe a smaller version (ops concurrent with
    # the higher ack are free to see older state)
    ops = sorted(
        (o for node in nodes for o in node.history), key=lambda o: o[3]
    )
    high: Dict[int, Tuple[int, float]] = {}  # key -> (max acked ver, trsp)
    acked = 0
    for kind, key, ver, tinv, trsp in ops:
        acked += 1
        prev = high.get(key)
        if prev is not None and tinv > prev[1] and ver < prev[0]:
            raise InvariantViolation(
                f"observed version regression on key {key}: {ver} after "
                f"{prev[0]} was acked"
            )
        if prev is None or ver > prev[0]:
            high[key] = (ver, trsp)
    return {"acked_ops": acked}


async def _fuzz_body(
    n_nodes: int,
    virtual_secs: float,
    chaos: bool,
    tails: bool,
    buggy: bool,
    plan=None,
    occ_off=None,
    seed=None,
    lineage: bool = False,
) -> dict:
    handle = ms.Handle.current()
    from madsim_tpu.net import NetSim

    if tails:
        ms.buggify.enable()  # arms NetSim's 1-5 s straggler tail
    addrs = [f"10.0.5.{i + 1}:7300" for i in range(n_nodes)]
    cns: list = [None] * n_nodes

    def make_node(i: int) -> ChainNode:
        """Fresh node; durable store/version counter/history carried over
        from the previous incarnation unless wiped."""
        old = cns[i]
        fresh = ChainNode(i, n_nodes, addrs, buggy=buggy)
        if old is not None:
            fresh.store = dict(old.store)
            fresh.vnext = dict(old.vnext)
            fresh.history = old.history
        cns[i] = fresh
        return fresh

    nodes = []
    if plan is not None:
        # schedule-matched mode: crash/restart come from the compiled
        # FaultPlan stream; `.init(...)` closures let NemesisDriver's
        # handle.restart respawn the protocol node with the same
        # durable-state carry the host-native chaos_task performs
        def make_init(i: int):
            def _init():
                return make_node(i).run()

            return _init

        for i in range(n_nodes):
            node = (
                handle.create_node()
                .name(f"ch-{i}")
                .ip(f"10.0.5.{i + 1}")
                .init(make_init(i))
                .build()
            )
            nodes.append(node)
    else:
        for i in range(n_nodes):
            node = handle.create_node().name(f"ch-{i}").ip(f"10.0.5.{i + 1}").build()
            node.spawn(make_node(i).run())
            nodes.append(node)

    async def chaos_task() -> None:
        while True:
            await ms.time.sleep(0.5 + ms.rand() * 1.5)
            victim = ms.randrange(n_nodes)
            handle.kill(nodes[victim].id)
            await ms.time.sleep(0.2 + ms.rand() * 0.8)
            old = cns[victim]
            fresh = ChainNode(victim, n_nodes, addrs, buggy=buggy)
            # durable: store + head's version counter + the histories
            fresh.store = dict(old.store)
            fresh.vnext = dict(old.vnext)
            fresh.history = old.history
            cns[victim] = fresh
            handle.restart(nodes[victim].id)
            nodes[victim].spawn(fresh.run())

    if chaos and plan is None:
        ms.spawn(chaos_task())

    driver = None
    if plan is not None:
        from madsim_tpu import nemesis as nem

        net = ms.plugin.simulator(NetSim)
        if lineage:
            net.lineage.enable()

        def on_wipe(i: int) -> None:
            cns[i] = None  # next incarnation starts from init state

        driver = nem.NemesisDriver(
            plan,
            handle,
            node_ids=[n.id for n in nodes],
            horizon_us=int(virtual_secs * 1e6),
            seed=seed,
            on_wipe=on_wipe,
            occ_off=occ_off,
        )
        driver.install()

    t = ms.time.current()
    end = t.elapsed() + virtual_secs
    while t.elapsed() < end:
        await ms.time.sleep(0.05)
    stats = check_invariants(cns)
    stats["events"] = ms.plugin.simulator(NetSim).stat().msg_count
    stats["committed_max_ver"] = max(
        (v for _k, (_x, v) in cns[-1].store.items()), default=0
    )
    if driver is not None:
        net = ms.plugin.simulator(NetSim)
        stats["nemesis"] = {
            "applied": list(driver.applied),
            "occ_fired": dict(driver.occ_fired),
            "node_skew": dict(getattr(handle.time, "node_skew", {}) or {}),
            "node_ids": [n.id for n in nodes],
            "coins": driver.coins,
            "fires": driver.fire_counts(),
            "lineage": net.lineage if lineage else None,
            "state": [
                (
                    tuple(sorted(cn.store.items())),
                    tuple(sorted(cn.vnext.items())),
                    len(cn.history),
                )
                for cn in cns
            ],
        }
    # no buggify.disable() needed: the flag is per-Runtime handle state
    # and dies with this runtime when block_on returns
    return stats


def fuzz_one_seed(
    seed: int,
    n_nodes: int = 5,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.1,
    chaos: bool = True,
    tails: bool = False,
    buggy: bool = False,
    plan=None,
    occ_off=None,
    lineage: bool = False,
) -> dict:
    """One complete fuzzed execution, verified by the same oracle.

    With `plan=` (a `nemesis.FaultPlan`), chaos comes from the compiled
    per-seed schedule via `NemesisDriver` (the schedule-matched mode the
    differential oracle replays); the returned dict then carries a
    `"nemesis"` artifact bundle."""
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(
            n_nodes, virtual_secs, chaos, tails, buggy,
            plan=plan, occ_off=occ_off, seed=seed, lineage=lineage,
        )
    )
