"""gRPC server over simulated connections.

Analog of reference madsim-tonic/src/transport/server.rs:196-318: the server
accepts `connect1` streams, routes the first message by "/Service/method"
path, spawns one task per request, and speaks the four streaming shapes with
typed frames (the BoxMessage protocol analog — message matrix documented in
madsim-tonic/src/client.rs:33-37):

    request:  (path, client_streaming?, payload, metadata)
    frames:   ("frame", msg) ... ("end", None)          client->server stream
    response: ("ok", msg) | ("err", Status)             unary response
              ("frame", msg) ... ("trailer", None)      server->client stream

Unknown service/method responds Status UNIMPLEMENTED (server.rs:246-256).
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Dict, List, Optional

from ...core import context, task as task_mod
from ...core.sync import ChannelClosed, Event
from ...net import Endpoint
from ...net.netsim import PayloadReceiver, PayloadSender
from . import service as svc_mod
from .status import Status

# Request metadata is carried on the task handling the request (one task per
# connection), never in a global: concurrent handlers interleave at every
# await, so a global would leak one request's metadata into another. The
# reference carries it on the request itself (madsim-tonic/src/sim.rs:20-42).
_METADATA_KEY = "grpc_request_metadata"

# production mode: request metadata rides a ContextVar (asyncio-task-scoped);
# sim mode uses task_locals on the DES task instead
import contextvars

_real_metadata: "contextvars.ContextVar[Optional[Dict[str, str]]]" = (
    contextvars.ContextVar("grpc_request_metadata", default=None)
)


def current_metadata() -> Dict[str, str]:
    """Metadata of the request the current task is handling."""
    task = context.try_current_task()
    if task is None:
        return _real_metadata.get() or {}
    if task.task_locals is None:
        return {}
    return task.task_locals.get(_METADATA_KEY) or {}


class _RequestStream:
    """Async iterator over incoming client-stream frames."""

    def __init__(self, rx: PayloadReceiver) -> None:
        self._rx = rx
        self._done = False

    def __aiter__(self) -> "AsyncIterator[Any]":
        return self

    async def __anext__(self) -> Any:
        if self._done:
            raise StopAsyncIteration
        try:
            tag, payload = await self._rx.recv()
        except ChannelClosed:
            self._done = True
            raise StopAsyncIteration from None
        if tag == "end":
            self._done = True
            raise StopAsyncIteration
        return payload


class Server:
    """Builder + router (tonic `Server::builder()` analog)."""

    def __init__(self) -> None:
        self._services: Dict[str, svc_mod.Service] = {}
        self._shutdown = Event()

    def add_service(self, service: svc_mod.Service) -> "Server":
        self._services[service.service_name()] = service
        return self

    async def serve(self, addr) -> None:
        """Bind and accept until the node dies or `shutdown()` is called."""
        ep = await Endpoint.bind(addr)
        await self._accept_loop(ep)

    def spawn_serve(self, addr) -> "task_mod.JoinHandle":
        """Convenience: run `serve` as a task on the current node."""
        return task_mod.spawn(self.serve(addr), name="grpc-server")

    def shutdown(self) -> None:
        self._shutdown.set()

    async def serve_with_shutdown(self, addr, signal) -> None:
        """Serve until `signal` (an awaitable) completes (tonic analog)."""

        async def waiter() -> None:
            await signal
            self._shutdown.set()

        task_mod.spawn(waiter(), name="grpc-shutdown")
        await self.serve(addr)

    # -- internals --

    async def _accept_loop(self, ep: Endpoint) -> None:
        while not self._shutdown.is_set():
            try:
                tx, rx, peer = await ep.accept1()
            except ChannelClosed:
                return
            task_mod.spawn(self._handle_conn(tx, rx), name="grpc-conn")

    async def _handle_conn(self, tx: PayloadSender, rx: PayloadReceiver) -> None:
        try:
            path, client_streaming, payload, metadata = await rx.recv()
        except ChannelClosed:
            return
        try:
            service_name, method_name = path.strip("/").split("/", 1)
        except ValueError:
            self._send_err(tx, Status.unimplemented(f"bad path: {path}"))
            return
        service = self._services.get(service_name)
        handler = getattr(service, method_name, None) if service else None
        mode = getattr(handler, "_grpc_mode", None)
        if handler is None or mode is None:
            self._send_err(
                tx, Status.unimplemented(f"unknown rpc: {service_name}/{method_name}")
            )
            return

        task = context.try_current_task()
        if task is not None:
            if task.task_locals is None:
                task.task_locals = {}
            task.task_locals[_METADATA_KEY] = metadata or {}
        else:  # production mode: one asyncio task per connection
            _real_metadata.set(metadata or {})
        try:
            if mode == svc_mod.UNARY:
                rsp = await handler(payload)
                tx.send(("ok", rsp))
            elif mode == svc_mod.SERVER_STREAMING:
                async for frame in handler(payload):
                    tx.send(("frame", frame))
                tx.send(("trailer", None))
            elif mode == svc_mod.CLIENT_STREAMING:
                rsp = await handler(_RequestStream(rx))
                tx.send(("ok", rsp))
            elif mode == svc_mod.BIDI_STREAMING:
                async for frame in handler(_RequestStream(rx)):
                    tx.send(("frame", frame))
                tx.send(("trailer", None))
        except Status as status:
            self._send_err(tx, status)
        except ChannelClosed:
            pass  # client went away mid-stream
        except Exception as exc:  # noqa: BLE001 - handler bug => INTERNAL status
            self._send_err(tx, Status.internal(f"{type(exc).__name__}: {exc}"))

    @staticmethod
    def _send_err(tx: PayloadSender, status: Status) -> None:
        try:
            tx.send(("err", status))
        except ChannelClosed:
            pass
