"""gRPC facade over the simulated network (madsim-tonic analog, 1053 LoC ref).

All four RPC shapes (unary, server-streaming, client-streaming, bidi),
status codes, metadata, interceptors, virtual-time deadlines, and full chaos
integration: killing the server node surfaces UNAVAILABLE at clients,
mid-stream kills reset streams, restarts re-bind.
"""

from .client import (  # noqa: F401
    Channel,
    Streaming,
    client_for,
    connect,
    connect_lazy,
)
from .server import Server, current_metadata  # noqa: F401
from .service import (  # noqa: F401
    Service,
    bidi_streaming,
    client_streaming,
    server_streaming,
    unary,
)
from .status import Code, Status  # noqa: F401
