"""gRPC status codes + Status exception (tonic `Status`/`Code` analog).

The reference reuses real tonic's Status/Code types in simulation
(madsim-tonic/src/sim.rs:1-5); here Status is a plain exception carrying a
code, message, and metadata.
"""

from __future__ import annotations

from typing import Dict, Optional


class Code:
    OK = 0
    CANCELLED = 1
    UNKNOWN = 2
    INVALID_ARGUMENT = 3
    DEADLINE_EXCEEDED = 4
    NOT_FOUND = 5
    ALREADY_EXISTS = 6
    PERMISSION_DENIED = 7
    RESOURCE_EXHAUSTED = 8
    FAILED_PRECONDITION = 9
    ABORTED = 10
    OUT_OF_RANGE = 11
    UNIMPLEMENTED = 12
    INTERNAL = 13
    UNAVAILABLE = 14
    DATA_LOSS = 15
    UNAUTHENTICATED = 16

    _NAMES = {}


Code._NAMES = {
    v: k for k, v in vars(Code).items() if isinstance(v, int) and not k.startswith("_")
}


class Status(Exception):
    """RPC error status; raise from handlers, caught by clients."""

    def __init__(
        self, code: int, message: str = "", metadata: Optional[Dict[str, str]] = None
    ) -> None:
        super().__init__(message)
        self.code = code
        self.message = message
        self.metadata = metadata or {}

    def code_name(self) -> str:
        return Code._NAMES.get(self.code, str(self.code))

    def __reduce__(self):
        # default Exception pickling would re-init with (message,) as the
        # code argument; needed in production mode (statuses cross real TCP)
        return (Status, (self.code, self.message, self.metadata))

    def __repr__(self) -> str:
        return f"Status(code={self.code_name()}, message={self.message!r})"

    # convenience constructors, mirroring tonic's Status::not_found etc.
    @staticmethod
    def cancelled(msg: str = "") -> "Status":
        return Status(Code.CANCELLED, msg)

    @staticmethod
    def unknown(msg: str = "") -> "Status":
        return Status(Code.UNKNOWN, msg)

    @staticmethod
    def invalid_argument(msg: str = "") -> "Status":
        return Status(Code.INVALID_ARGUMENT, msg)

    @staticmethod
    def deadline_exceeded(msg: str = "") -> "Status":
        return Status(Code.DEADLINE_EXCEEDED, msg)

    @staticmethod
    def not_found(msg: str = "") -> "Status":
        return Status(Code.NOT_FOUND, msg)

    @staticmethod
    def permission_denied(msg: str = "") -> "Status":
        return Status(Code.PERMISSION_DENIED, msg)

    @staticmethod
    def unimplemented(msg: str = "") -> "Status":
        return Status(Code.UNIMPLEMENTED, msg)

    @staticmethod
    def internal(msg: str = "") -> "Status":
        return Status(Code.INTERNAL, msg)

    @staticmethod
    def unavailable(msg: str = "") -> "Status":
        return Status(Code.UNAVAILABLE, msg)
