"""Service definition: decorators in place of protoc codegen.

The reference generates sim clients/servers from .proto files
(madsim-tonic-build dual codegen, src/prost.rs:313-364). Python needs no
codegen: a `Service` subclass declares its RPC methods with mode decorators,
and both the server router and the typed client are derived from it by
reflection. Messages are arbitrary Python objects.

    class Greeter(grpc.Service):
        SERVICE_NAME = "helloworld.Greeter"

        @grpc.unary
        async def say_hello(self, request): ...

        @grpc.server_streaming
        async def lots_of_replies(self, request): yield ...

        @grpc.client_streaming
        async def lots_of_greetings(self, requests): ...

        @grpc.bidi_streaming
        async def bidi_hello(self, requests): yield ...
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

UNARY = "unary"
SERVER_STREAMING = "server_streaming"
CLIENT_STREAMING = "client_streaming"
BIDI_STREAMING = "bidi_streaming"


def _mark(mode: str) -> Callable:
    def deco(fn: Callable) -> Callable:
        fn._grpc_mode = mode
        return fn

    return deco


unary = _mark(UNARY)
server_streaming = _mark(SERVER_STREAMING)
client_streaming = _mark(CLIENT_STREAMING)
bidi_streaming = _mark(BIDI_STREAMING)


class Service:
    """Base class for RPC services; SERVICE_NAME routes requests."""

    SERVICE_NAME: str = ""

    @classmethod
    def rpc_methods(cls) -> Dict[str, str]:
        """{method_name: mode} for all decorated methods."""
        out = {}
        for name in dir(cls):
            fn = getattr(cls, name, None)
            mode = getattr(fn, "_grpc_mode", None)
            if mode is not None:
                out[name] = mode
        return out

    @classmethod
    def service_name(cls) -> str:
        return cls.SERVICE_NAME or cls.__name__
