"""gRPC client over simulated connections.

Analog of reference madsim-tonic client (src/client.rs:39-207 +
transport/channel.rs:12-208): a `Channel` resolves its target through sim
DNS, opens one `connect1` connection per call, and a typed client is derived
from the `Service` class by reflection (in place of tonic-build codegen).

Connection failures surface as Status UNAVAILABLE; virtual-time deadlines as
Status DEADLINE_EXCEEDED.
"""

from __future__ import annotations

from typing import Any, AsyncIterator, Callable, Dict, Iterable, List, Optional, Type

from ...core import task as task_mod, vtime
from ...core.sync import ChannelClosed
from ...net import Endpoint, lookup_host
from ...net.netsim import PayloadReceiver, PayloadSender
from . import service as svc_mod
from .status import Code, Status

Interceptor = Callable[[Any, Dict[str, str]], None]


def _parse_uri(uri: str) -> str:
    for prefix in ("http://", "https://", "grpc://"):
        if uri.startswith(prefix):
            return uri[len(prefix):]
    return uri


class Channel:
    """A (lazy) connection target; one sim connection per call."""

    def __init__(
        self,
        ep: Endpoint,
        addr,
        *,
        timeout: Optional[float] = None,
        interceptor: Optional[Interceptor] = None,
    ) -> None:
        self._ep = ep
        self._addr = addr
        self.default_timeout = timeout
        self.interceptor = interceptor

    async def _open(self):
        try:
            return await self._ep.connect1(self._addr)
        except (ConnectionRefusedError, OSError) as e:
            raise Status.unavailable(str(e)) from None

    async def call_raw(
        self,
        path: str,
        mode: str,
        payload: Any,
        metadata: Optional[Dict[str, str]] = None,
        timeout: Optional[float] = None,
    ) -> Any:
        metadata = dict(metadata or {})
        if self.interceptor is not None:
            self.interceptor(payload, metadata)  # may raise Status
        timeout = timeout if timeout is not None else self.default_timeout

        async def run() -> Any:
            tx, rx, _ = await self._open()
            client_streaming = mode in (
                svc_mod.CLIENT_STREAMING,
                svc_mod.BIDI_STREAMING,
            )
            first_payload = None if client_streaming else payload
            try:
                tx.send((path, client_streaming, first_payload, metadata))
            except ChannelClosed:
                raise Status.unavailable("connection closed") from None
            if client_streaming:
                task_mod.spawn(_pump(tx, payload), name="grpc-send-stream")
            if mode in (svc_mod.UNARY, svc_mod.CLIENT_STREAMING):
                try:
                    tag, body = await rx.recv()
                except ChannelClosed:
                    raise Status.unavailable("connection reset by peer") from None
                if tag == "err":
                    raise body
                return body
            return Streaming(rx)

        if timeout is None:
            return await run()
        try:
            return await vtime.timeout(timeout, run())
        except TimeoutError:
            raise Status.deadline_exceeded("request timed out") from None


async def _pump(tx: PayloadSender, messages) -> None:
    try:
        if hasattr(messages, "__aiter__"):
            async for m in messages:
                tx.send(("frame", m))
        else:
            for m in messages:
                tx.send(("frame", m))
        tx.send(("end", None))
    except ChannelClosed:
        pass  # server went away; receiver side will surface the error


class Streaming:
    """Async iterator over server-stream frames (tonic `Streaming<T>`)."""

    def __init__(self, rx: PayloadReceiver) -> None:
        self._rx = rx
        self._done = False

    def __aiter__(self) -> AsyncIterator[Any]:
        return self

    async def __anext__(self) -> Any:
        if self._done:
            raise StopAsyncIteration
        try:
            tag, body = await self._rx.recv()
        except ChannelClosed:
            self._done = True
            raise Status.unavailable("connection reset by peer") from None
        if tag == "trailer":
            self._done = True
            raise StopAsyncIteration
        if tag == "err":
            self._done = True
            raise body
        return body

    async def collect(self) -> List[Any]:
        return [m async for m in self]


async def connect(
    uri: str,
    *,
    timeout: Optional[float] = None,
    interceptor: Optional[Interceptor] = None,
) -> Channel:
    """Open a channel to `uri` ("http://host:port"); DNS goes through NetSim.

    Like tonic's `Endpoint::connect`, fails fast with UNAVAILABLE if the
    target is unreachable right now.
    """
    addr = await lookup_host(_parse_uri(uri))
    ep = await Endpoint.bind(("0.0.0.0", 0))
    channel = Channel(ep, addr, timeout=timeout, interceptor=interceptor)
    # probe connectivity (tonic connects eagerly; lazy() skips this)
    tx, _rx, _ = await channel._open()
    tx.close()
    return channel


async def connect_lazy(
    uri: str,
    *,
    timeout: Optional[float] = None,
    interceptor: Optional[Interceptor] = None,
) -> Channel:
    addr = await lookup_host(_parse_uri(uri))
    ep = await Endpoint.bind(("0.0.0.0", 0))
    return Channel(ep, addr, timeout=timeout, interceptor=interceptor)


def client_for(service_cls: Type[svc_mod.Service], channel: Channel):
    """Typed client derived from the Service class (codegen analog).

    Every decorated RPC method becomes an async callable:
        client.say_hello(msg, metadata=..., timeout=...)
    """

    class _Client:
        def __init__(self) -> None:
            self.channel = channel

        def __repr__(self) -> str:
            return f"<grpc client {service_cls.service_name()}>"

    for name, mode in service_cls.rpc_methods().items():
        path = f"/{service_cls.service_name()}/{name}"

        def make(path=path, mode=mode):
            async def call(self, message=None, *, metadata=None, timeout=None):
                return await self.channel.call_raw(
                    path, mode, message, metadata=metadata, timeout=timeout
                )

            return call

        setattr(_Client, name, make())
    return _Client()
