"""etcd error type (reference madsim-etcd-client/src/error.rs:1-106).

The reference wraps tonic::Status for server-side errors and a string for
election errors; here one exception type carries a grpc-style code + message
(we reuse the sims.grpc Code space) so user code can match on either.
"""

from __future__ import annotations

from ..grpc.status import Code


class EtcdError(Exception):
    """An etcd operation failed."""

    def __init__(self, message: str, code: Code = Code.UNKNOWN) -> None:
        super().__init__(message)
        self.message = message
        self.code = code

    def __reduce__(self):
        return (type(self), (self.message, self.code))


def lease_not_found() -> EtcdError:
    # reference service.rs:594-599
    return EtcdError("etcdserver: requested lease not found", Code.NOT_FOUND)


def request_too_large() -> EtcdError:
    # reference service.rs:179-187
    return EtcdError("etcdserver: request is too large", Code.INVALID_ARGUMENT)


def request_timed_out() -> EtcdError:
    # reference service.rs:166-177
    return EtcdError("etcdserver: request timed out", Code.UNAVAILABLE)


def session_expired() -> EtcdError:
    # reference service.rs:601-603
    return EtcdError("session expired", Code.FAILED_PRECONDITION)
