"""The etcd state machine: revisioned KV + leases + txn + election + watch.

Analog of reference madsim-etcd-client/src/service.rs:190-592 (ServiceInner)
and :12-188 (EtcdService). Differences from the reference are idiomatic, not
semantic: the KV store is a dict iterated in sorted order (Python has no
BTreeMap), watches are an EventBus of bounded channels exactly like the
reference's mpsc fan-out, and the lease clock ticks once per virtual second
from a background task spawned by the server.

Snapshot format: TOML, like the reference (service.rs:161-164). Keys/values
are binary-safe via base64. Parsing uses stdlib tomllib; emission uses the
small writer in this module (stdlib has no TOML writer).
"""

from __future__ import annotations

import base64
import dataclasses
import enum
try:
    import tomllib
except ImportError:  # Python < 3.11: vendored reader
    from ... import _toml as tomllib
from typing import Dict, List, Optional, Tuple, Union

from ...core import context
from ...core.sync import Channel, ChannelClosed
from .errors import (
    EtcdError,
    lease_not_found,
    request_timed_out,
    request_too_large,
    session_expired,
)

Key = bytes
Value = bytes


def _b(x: Union[str, bytes, bytearray]) -> bytes:
    return x.encode() if isinstance(x, str) else bytes(x)


# --------------------------------------------------------------------- types


@dataclasses.dataclass
class ResponseHeader:
    """reference sim.rs:112-125."""

    revision: int


@dataclasses.dataclass
class KeyValue:
    """reference kv.rs KeyValue."""

    key: bytes
    value: bytes
    lease: int = 0
    create_revision: int = 0
    mod_revision: int = 0


class EventType(enum.Enum):
    PUT = 0
    DELETE = 1


@dataclasses.dataclass
class Event:
    """reference service.rs:221-225."""

    type: EventType
    kv: KeyValue


@dataclasses.dataclass
class LeaderKey:
    """reference election.rs LeaderKey."""

    name: bytes
    key: bytes
    rev: int
    lease: int


class CompareOp(enum.Enum):
    EQUAL = 0
    GREATER = 1
    LESS = 2
    NOT_EQUAL = 3


@dataclasses.dataclass
class Compare:
    """One txn guard on a key's value (reference service.rs:365-373)."""

    key: bytes
    op: CompareOp
    value: bytes

    @staticmethod
    def value_eq(key, value) -> "Compare":
        return Compare(_b(key), CompareOp.EQUAL, _b(value))


@dataclasses.dataclass
class TxnOp:
    """get/put/delete/nested-txn op (reference server.rs TxnOp)."""

    kind: str  # "get" | "put" | "delete" | "txn"
    key: bytes = b""
    value: bytes = b""
    options: Optional[dict] = None
    txn: Optional["Txn"] = None

    @staticmethod
    def get(key, **options) -> "TxnOp":
        return TxnOp("get", key=_b(key), options=options)

    @staticmethod
    def put(key, value, **options) -> "TxnOp":
        return TxnOp("put", key=_b(key), value=_b(value), options=options)

    @staticmethod
    def delete(key, **options) -> "TxnOp":
        return TxnOp("delete", key=_b(key), options=options)

    @staticmethod
    def nested(txn: "Txn") -> "TxnOp":
        return TxnOp("txn", txn=txn)


@dataclasses.dataclass
class Txn:
    """compare / then / else transaction (reference kv.rs Txn)."""

    compare: List[Compare] = dataclasses.field(default_factory=list)
    success: List[TxnOp] = dataclasses.field(default_factory=list)
    failure: List[TxnOp] = dataclasses.field(default_factory=list)

    def when(self, *compares: Compare) -> "Txn":
        self.compare.extend(compares)
        return self

    def and_then(self, *ops: TxnOp) -> "Txn":
        self.success.extend(ops)
        return self

    def or_else(self, *ops: TxnOp) -> "Txn":
        self.failure.extend(ops)
        return self

    def size(self) -> int:
        return sum(len(c.key) + len(c.value) for c in self.compare) + sum(
            len(op.key) + len(op.value) + (op.txn.size() if op.txn else 0)
            for op in self.success + self.failure
        )


# response envelopes (reference kv.rs / lease.rs / election.rs response types)


@dataclasses.dataclass
class PutResponse:
    header: ResponseHeader
    prev_kv: Optional[KeyValue] = None


@dataclasses.dataclass
class GetResponse:
    header: ResponseHeader
    kvs: List[KeyValue] = dataclasses.field(default_factory=list)

    def count(self) -> int:
        return len(self.kvs)


@dataclasses.dataclass
class DeleteResponse:
    header: ResponseHeader
    deleted: int = 0


@dataclasses.dataclass
class TxnResponse:
    header: ResponseHeader
    succeeded: bool = False
    op_responses: List[object] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class LeaseGrantResponse:
    header: ResponseHeader
    id: int = 0
    ttl: int = 0


@dataclasses.dataclass
class LeaseRevokeResponse:
    header: ResponseHeader


@dataclasses.dataclass
class LeaseKeepAliveResponse:
    header: ResponseHeader
    id: int = 0
    ttl: int = 0


@dataclasses.dataclass
class LeaseTimeToLiveResponse:
    header: ResponseHeader
    id: int = 0
    ttl: int = 0
    granted_ttl: int = 0
    keys: List[bytes] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class LeaseStatus:
    id: int


@dataclasses.dataclass
class LeaseLeasesResponse:
    header: ResponseHeader
    leases: List[LeaseStatus] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class CampaignResponse:
    header: ResponseHeader
    leader: Optional[LeaderKey] = None


@dataclasses.dataclass
class ProclaimResponse:
    header: ResponseHeader


@dataclasses.dataclass
class LeaderResponse:
    header: ResponseHeader
    kv: Optional[KeyValue] = None


@dataclasses.dataclass
class ResignResponse:
    header: ResponseHeader


@dataclasses.dataclass
class StatusResponse:
    header: ResponseHeader


@dataclasses.dataclass
class _Lease:
    """reference service.rs:251-266."""

    ttl: int
    granted_ttl: int
    keys: List[bytes] = dataclasses.field(default_factory=list)


# ------------------------------------------------------------------ EventBus


class EventBus:
    """Prefix-matched watch fan-out (reference service.rs:201-245)."""

    def __init__(self) -> None:
        self._subs: List[Tuple[bytes, Channel]] = []

    def subscribe(self, prefix: bytes, capacity: Optional[int] = None) -> Channel:
        """Subscribe to events under a prefix.

        Internal subscriptions (campaign waits, observe loops) default to
        unbounded so an event burst — e.g. lease_revoke deleting several
        election keys at once — cannot close a parked waiter's channel and
        surface as a spurious 'server closed' error. Client-driven `watch`
        streams pass an explicit capacity (backpressure stays real there).
        """
        ch = Channel(capacity=capacity)
        self._subs.append((prefix, ch))
        return ch

    def publish(self, event: Event) -> None:
        live: List[Tuple[bytes, Channel]] = []
        for prefix, ch in self._subs:
            if not event.kv.key.startswith(prefix):
                live.append((prefix, ch))
                continue
            try:
                ok = ch.try_send(event)
            except ChannelClosed:
                ok = False
            if ok:
                live.append((prefix, ch))
            else:
                # receiver gone or full: drop the subscription (ref :237-243)
                # AND close the channel so a parked receiver errors out
                # instead of waiting forever (the mpsc-sender-drop analog)
                ch.close()
        self._subs = live


# -------------------------------------------------------------- ServiceInner


class ServiceInner:
    """The synchronous state machine (reference service.rs:268-592)."""

    def __init__(self) -> None:
        self.revision: int = 0
        self.kv: Dict[bytes, KeyValue] = {}
        self.lease: Dict[int, _Lease] = {}
        self.watcher = EventBus()
        self._txn_depth = 0  # >0: inside a txn; ops share ONE revision
        # MVCC history for get(revision=N): per key, (mod_revision,
        # KeyValue-or-None) versions in order; None is a delete tombstone.
        # The reference leaves historical reads as todo!() (service.rs:325);
        # this sim implements them — a snapshot load() compacts history
        # away, and reads below the compaction point raise like real etcd.
        self.history: Dict[bytes, list] = {}
        self.compacted: int = 0

    def _hist_put(self, kv: KeyValue) -> None:
        self.history.setdefault(kv.key, []).append((kv.mod_revision, kv))

    def _hist_del(self, key: Key) -> None:
        self.history.setdefault(key, []).append((self.revision, None))

    # -- header

    def header(self) -> ResponseHeader:
        return ResponseHeader(revision=self.revision)

    # -- kv (service.rs:275-361)

    def put(self, key: Key, value: Value, lease: int = 0, prev_kv: bool = False) -> PutResponse:
        prev = self.kv.get(key)
        if lease != 0:
            lease_obj = self.lease.get(lease)
            if lease_obj is None:
                raise lease_not_found()
            if key not in lease_obj.keys:
                lease_obj.keys.append(key)
        if prev is not None and prev.lease != 0 and prev.lease != lease:
            old = self.lease.get(prev.lease)
            if old is not None and key in old.keys:
                old.keys.remove(key)
        if self._txn_depth == 0:
            self.revision += 1
        kv = KeyValue(
            key=key,
            value=value,
            lease=lease,
            create_revision=prev.create_revision if prev else self.revision,
            mod_revision=self.revision,
        )
        self.kv[key] = kv
        self._hist_put(kv)
        self.watcher.publish(Event(EventType.PUT, kv))
        return PutResponse(header=self.header(), prev_kv=prev if prev_kv else None)

    def get(self, key: Key, prefix: bool = False, revision: int = 0) -> GetResponse:
        if revision > 0:
            return self._get_at(key, prefix, revision)
        if prefix:
            kvs = [self.kv[k] for k in sorted(self.kv) if k.startswith(key)]
        else:
            kvs = [self.kv[key]] if key in self.kv else []
        return GetResponse(header=self.header(), kvs=list(kvs))

    def _get_at(self, key: Key, prefix: bool, revision: int) -> GetResponse:
        """Historical read at a past revision, from the MVCC history.

        The reference panics here (service.rs:325 todo!()); real etcd
        serves it, so this sim does too — with real etcd's error shapes at
        the edges (future revision / compacted revision).
        """
        if revision > self.revision:
            raise EtcdError("etcdserver: mvcc: required revision is a future revision")
        if revision <= self.compacted:
            raise EtcdError("etcdserver: mvcc: required revision has been compacted")
        keys = (
            sorted(k for k in self.history if k.startswith(key))
            if prefix
            else ([key] if key in self.history else [])
        )
        kvs = []
        for k in keys:
            snap = None
            for rev, kv in self.history[k]:
                if rev > revision:
                    break
                snap = kv  # txn writes share a revision: last one wins
            if snap is not None:
                kvs.append(snap)
        return GetResponse(header=self.header(), kvs=kvs)

    def delete(self, key: Key, prefix: bool = False) -> DeleteResponse:
        keys = (
            [k for k in self.kv if k.startswith(key)] if prefix
            else ([key] if key in self.kv else [])
        )
        deleted = 0
        for k in keys:
            kv = self.kv.pop(k)
            deleted += 1
            if self._txn_depth == 0:
                self.revision += 1
            self._hist_del(k)
            if kv.lease != 0:
                lease_obj = self.lease.get(kv.lease)
                if lease_obj is not None and k in lease_obj.keys:
                    lease_obj.keys.remove(k)
            self.watcher.publish(Event(EventType.DELETE, kv))
        return DeleteResponse(header=self.header(), deleted=deleted)

    def txn(self, txn: Txn) -> TxnResponse:
        def check(cmp: Compare) -> bool:
            value = self.kv[cmp.key].value if cmp.key in self.kv else None
            if cmp.op is CompareOp.EQUAL:
                return value == cmp.value
            if cmp.op is CompareOp.GREATER:
                return value is not None and value > cmp.value
            if cmp.op is CompareOp.LESS:
                return value is not None and value < cmp.value
            return value != cmp.value  # NOT_EQUAL

        succeeded = all(check(c) for c in txn.compare)
        # The whole txn is atomic: ONE revision bump, every inner write
        # stamped with it (real etcd semantics). The reference instead
        # rewinds self.revision after inner ops bumped it
        # (service.rs:375-390), which leaves duplicate mod_revisions behind
        # — a reference bug not worth reproducing.
        self._txn_depth += 1
        if self._txn_depth == 1:
            self.revision += 1
        try:
            op_responses: List[object] = []
            for op in txn.success if succeeded else txn.failure:
                opts = op.options or {}
                if op.kind == "get":
                    op_responses.append(self.get(op.key, **opts))
                elif op.kind == "put":
                    op_responses.append(self.put(op.key, op.value, **opts))
                elif op.kind == "delete":
                    op_responses.append(self.delete(op.key, **opts))
                elif op.kind == "txn":
                    op_responses.append(self.txn(op.txn))
        finally:
            self._txn_depth -= 1
        return TxnResponse(
            header=self.header(), succeeded=succeeded, op_responses=op_responses
        )

    # -- lease (service.rs:399-486)

    def lease_grant(self, ttl: int, id: int = 0) -> LeaseGrantResponse:
        if id == 0:
            handle = context.try_current_handle()
            if handle is not None:
                draw = lambda: handle.rng.next_u64() >> 1  # noqa: E731
            else:  # production mode: OS entropy (determinism is sim-only)
                import os as _os

                draw = lambda: int.from_bytes(_os.urandom(8), "little") >> 1  # noqa: E731  # madsim: allow(ambient-entropy)
            while id == 0 or id in self.lease:
                id = draw()  # non-negative i64
        if id in self.lease:
            raise EtcdError("lease ID already exists")
        self.lease[id] = _Lease(ttl=ttl, granted_ttl=ttl)
        self.revision += 1
        return LeaseGrantResponse(header=self.header(), id=id, ttl=ttl)

    def lease_revoke(self, id: int) -> LeaseRevokeResponse:
        lease_obj = self.lease.pop(id, None)
        if lease_obj is None:
            raise lease_not_found()
        self.revision += 1
        for key in lease_obj.keys:
            kv = self.kv.pop(key)
            self._hist_del(key)
            self.watcher.publish(Event(EventType.DELETE, kv))
        return LeaseRevokeResponse(header=self.header())

    def lease_keep_alive(self, id: int) -> LeaseKeepAliveResponse:
        lease_obj = self.lease.get(id)
        if lease_obj is None:
            raise lease_not_found()
        lease_obj.ttl = lease_obj.granted_ttl
        self.revision += 1
        return LeaseKeepAliveResponse(
            header=self.header(), id=id, ttl=lease_obj.ttl
        )

    def lease_time_to_live(self, id: int, keys: bool = False) -> LeaseTimeToLiveResponse:
        lease_obj = self.lease.get(id)
        if lease_obj is None:
            raise lease_not_found()
        return LeaseTimeToLiveResponse(
            header=self.header(),
            id=id,
            ttl=lease_obj.ttl,
            granted_ttl=lease_obj.granted_ttl,
            keys=list(lease_obj.keys) if keys else [],
        )

    def lease_leases(self) -> LeaseLeasesResponse:
        return LeaseLeasesResponse(
            header=self.header(),
            leases=[LeaseStatus(id=i) for i in self.lease],
        )

    def tick(self) -> None:
        """Expire leases; called once per virtual second (service.rs:467-486)."""
        expired = []
        for id, lease_obj in self.lease.items():
            lease_obj.ttl -= 1
            if lease_obj.ttl <= 0:
                expired.append(id)
        if expired:
            self.revision += 1
        for id in expired:
            lease_obj = self.lease.pop(id)
            for key in lease_obj.keys:
                kv = self.kv.pop(key)
                self._hist_del(key)
                self.watcher.publish(Event(EventType.DELETE, kv))

    # -- election (service.rs:488-592)

    def campaign_once(
        self, name: Key, value: Value, lease: int
    ) -> Union[CampaignResponse, Tuple[bytes, Channel]]:
        """One campaign attempt: win, or (my key, event stream to wait on)."""
        key = name + b"/" + format(lease, "016x").encode()
        existing = self.kv.get(key)
        if existing is None or existing.value != value:
            self.revision += 1
            kv = KeyValue(
                key=key,
                value=value,
                lease=lease,
                create_revision=self.revision,
                mod_revision=self.revision,
            )
            lease_obj = self.lease.get(lease)
            if lease_obj is None:
                raise lease_not_found()
            if key not in lease_obj.keys:
                lease_obj.keys.append(key)
            self.kv[key] = kv
            self._hist_put(kv)
            self.watcher.publish(Event(EventType.PUT, kv))

        leader = self.leader(name)
        if leader.kv is not None and leader.kv.key == key:
            return CampaignResponse(
                header=self.header(),
                leader=LeaderKey(name=name, key=key, rev=self.revision, lease=lease),
            )
        return key, self.watcher.subscribe(name)

    def proclaim(self, leader: LeaderKey, value: Value) -> ProclaimResponse:
        kv = self.kv.get(leader.key)
        if kv is None:
            raise session_expired()
        self.revision += 1
        # replace, don't mutate: observers hold references to the old object
        # and detect changes by comparison (server.rs observe loop)
        kv = dataclasses.replace(kv, value=value, mod_revision=self.revision)
        self.kv[leader.key] = kv
        self._hist_put(kv)
        self.watcher.publish(Event(EventType.PUT, kv))
        return ProclaimResponse(header=self.header())

    def leader(self, name: Key) -> LeaderResponse:
        # lowest create_revision among keys with prefix name (service.rs:554-562)
        candidates = [v for k, v in self.kv.items() if k.startswith(name)]
        kv = min(candidates, key=lambda v: v.create_revision, default=None)
        return LeaderResponse(header=self.header(), kv=kv)

    def observe(self, name: Key) -> Tuple[LeaderResponse, Channel]:
        ch = self.watcher.subscribe(name)
        return self.leader(name), ch

    def resign(self, leader: LeaderKey) -> ResignResponse:
        kv = self.kv.pop(leader.key, None)
        if kv is None:
            raise session_expired()
        lease_obj = self.lease.get(kv.lease)
        if lease_obj is not None and leader.key in lease_obj.keys:
            lease_obj.keys.remove(leader.key)
        self.revision += 1
        self._hist_del(leader.key)
        self.watcher.publish(Event(EventType.DELETE, kv))
        return ResignResponse(header=self.header())

    def status(self) -> StatusResponse:
        return StatusResponse(header=self.header())

    # -- snapshot (service.rs:161-164; TOML like the reference)

    def dump(self) -> str:
        lines = [f"revision = {self.revision}", ""]
        for k in sorted(self.kv):
            v = self.kv[k]
            lines += [
                "[[kv]]",
                f'key = "{base64.b64encode(v.key).decode()}"',
                f'value = "{base64.b64encode(v.value).decode()}"',
                f"lease = {v.lease}",
                f"create_revision = {v.create_revision}",
                f"modify_revision = {v.mod_revision}",
                "",
            ]
        for id in sorted(self.lease):
            l = self.lease[id]
            keys = ", ".join(f'"{base64.b64encode(k).decode()}"' for k in l.keys)
            lines += [
                "[[lease]]",
                f"id = {id}",
                f"ttl = {l.ttl}",
                f"granted_ttl = {l.granted_ttl}",
                f"keys = [{keys}]",
                "",
            ]
        return "\n".join(lines)

    @staticmethod
    def load(data: str) -> "ServiceInner":
        doc = tomllib.loads(data)
        inner = ServiceInner()
        inner.revision = int(doc.get("revision", 0))
        # a snapshot is COMPACTED state (real etcd restore semantics):
        # historical reads below the snapshot revision raise; at or after
        # it they serve from the re-seeded history
        inner.compacted = max(0, inner.revision - 1)
        for e in doc.get("kv", []):
            key = base64.b64decode(e["key"])
            inner.kv[key] = KeyValue(
                key=key,
                value=base64.b64decode(e["value"]),
                lease=int(e.get("lease", 0)),
                create_revision=int(e.get("create_revision", 0)),
                mod_revision=int(e.get("modify_revision", 0)),
            )
            inner._hist_put(inner.kv[key])
        for e in doc.get("lease", []):
            inner.lease[int(e["id"])] = _Lease(
                ttl=int(e["ttl"]),
                granted_ttl=int(e["granted_ttl"]),
                keys=[base64.b64decode(k) for k in e.get("keys", [])],
            )
        return inner


# --------------------------------------------------------------- EtcdService


class EtcdService:
    """Async wrapper: injected timeouts + request-size cap + lease ticking.

    Reference service.rs:12-188. `timeout_rate` injects random
    'etcdserver: request timed out' failures (5-15 s stalls) before the
    state-machine op — the etcd-level fault injection used by chaos tests.
    """

    MAX_REQUEST_BYTES = 0x18_0000  # 1.5 MiB (service.rs:37)

    def __init__(self, timeout_rate: float = 0.0, data: Optional[str] = None) -> None:
        self.timeout_rate = timeout_rate
        self.inner = ServiceInner.load(data) if data else ServiceInner()

    async def start_ticker(self) -> None:
        """Lease-expiry clock; run as a task on the server node (service.rs:28-34)."""
        from ...core.vtime import sleep

        while True:
            await sleep(1.0)
            self.inner.tick()

    async def _timeout(self) -> None:
        # production mode has no sim context (and no injected timeouts —
        # they are a chaos feature of the simulation, lib.rs:14-23 switch)
        handle = context.try_current_handle()
        if handle is None:
            return
        if self.timeout_rate > 0 and handle.rng.random() < self.timeout_rate:
            from ...core.vtime import sleep

            await sleep(5.0 + handle.rng.random() * 10.0)
            raise request_timed_out()

    def _assert_size(self, size: int) -> None:
        if size > self.MAX_REQUEST_BYTES:
            raise request_too_large()

    # every op: size check -> injected timeout -> synchronous state machine

    async def put(self, key, value, lease: int = 0, prev_kv: bool = False) -> PutResponse:
        key, value = _b(key), _b(value)
        self._assert_size(len(key) + len(value))
        await self._timeout()
        return self.inner.put(key, value, lease=lease, prev_kv=prev_kv)

    async def get(self, key, prefix: bool = False, revision: int = 0) -> GetResponse:
        key = _b(key)
        self._assert_size(len(key))
        await self._timeout()
        return self.inner.get(key, prefix=prefix, revision=revision)

    async def delete(self, key, prefix: bool = False) -> DeleteResponse:
        key = _b(key)
        self._assert_size(len(key))
        await self._timeout()
        return self.inner.delete(key, prefix=prefix)

    async def txn(self, txn: Txn) -> TxnResponse:
        self._assert_size(txn.size())
        await self._timeout()
        return self.inner.txn(txn)

    async def lease_grant(self, ttl: int, id: int = 0) -> LeaseGrantResponse:
        await self._timeout()
        return self.inner.lease_grant(ttl, id)

    async def lease_revoke(self, id: int) -> LeaseRevokeResponse:
        await self._timeout()
        return self.inner.lease_revoke(id)

    async def lease_keep_alive(self, id: int) -> LeaseKeepAliveResponse:
        await self._timeout()
        return self.inner.lease_keep_alive(id)

    async def lease_time_to_live(self, id: int, keys: bool = False) -> LeaseTimeToLiveResponse:
        await self._timeout()
        return self.inner.lease_time_to_live(id, keys)

    async def lease_leases(self) -> LeaseLeasesResponse:
        await self._timeout()
        return self.inner.lease_leases()

    async def campaign(self, name, value, lease: int) -> CampaignResponse:
        """Block until leadership is acquired (reference service.rs:100-125)."""
        name, value = _b(name), _b(value)
        self._assert_size(len(name) + len(value))
        await self._timeout()
        result = self.inner.campaign_once(name, value, lease)
        if isinstance(result, CampaignResponse):
            return result
        key, events = result
        try:
            while True:
                await events.recv()
                leader = self.inner.leader(name)
                if leader.kv is None:
                    raise session_expired()
                if leader.kv.key == key:
                    return CampaignResponse(
                        header=leader.header,
                        leader=LeaderKey(
                            name=name, key=key,
                            rev=leader.kv.mod_revision, lease=leader.kv.lease,
                        ),
                    )
        finally:
            events.close()

    async def proclaim(self, leader: LeaderKey, value) -> ProclaimResponse:
        value = _b(value)
        self._assert_size(len(leader.key) + len(value))
        await self._timeout()
        return self.inner.proclaim(leader, value)

    async def leader(self, name) -> LeaderResponse:
        name = _b(name)
        self._assert_size(len(name))
        await self._timeout()
        return self.inner.leader(name)

    async def observe(self, name) -> Tuple[LeaderResponse, Channel]:
        name = _b(name)
        self._assert_size(len(name))
        await self._timeout()
        return self.inner.observe(name)

    async def resign(self, leader: LeaderKey) -> ResignResponse:
        self._assert_size(len(leader.key))
        await self._timeout()
        return self.inner.resign(leader)

    async def status(self) -> StatusResponse:
        await self._timeout()
        return self.inner.status()

    async def dump(self) -> str:
        return self.inner.dump()
