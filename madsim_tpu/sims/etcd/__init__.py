"""etcd v3 simulation: in-sim server + client over the simulated network.

Analog of reference madsim-etcd-client (2790 LoC): a revisioned KV store with
leases, transactions, elections, prefix watches, and TOML dump/load snapshots,
served over the Endpoint connection API (`connect1`/`accept1`) exactly like
the reference's SimServer (server.rs:34-103). The client exposes pythonic
sub-clients (kv/lease/election/watch/maintenance) mirroring
etcd-client's fluent API (sim.rs:27-77).

    server.spawn(SimServer().serve("10.0.0.1:2379"))
    client = await Client.connect("10.0.0.1:2379")
    await client.kv.put("foo", "bar")
    resp = await client.kv.get("foo")
"""

from .client import (  # noqa: F401
    Client,
    DeleteOptions,
    GetOptions,
    PutOptions,
)
from .server import SimServer  # noqa: F401
from .service import (  # noqa: F401
    Compare,
    CompareOp,
    Event,
    EventType,
    KeyValue,
    LeaderKey,
    ResponseHeader,
    Txn,
    TxnOp,
)
from .errors import EtcdError  # noqa: F401
