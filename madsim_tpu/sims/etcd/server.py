"""SimServer: the in-sim etcd server loop (reference server.rs:14-101).

Accepts `connect1` streams on the simulated network; each connection carries
one request (or one long-lived KeepAlive/Observe stream). Requests are plain
tuples ("op", args...) — the wire enum of server.rs:105-167 — answered with
either ("ok", response) or ("err", EtcdError).
"""

from __future__ import annotations

from typing import Optional

from ...core import task as task_mod
from ...core.sync import ChannelClosed, select
from ...net import Endpoint
from .errors import EtcdError
from .service import EtcdService, Txn


class SimServer:
    """Builder + server (reference server.rs:14-32)."""

    def __init__(self) -> None:
        self._timeout_rate = 0.0
        self._load: Optional[str] = None

    @staticmethod
    def builder() -> "SimServer":
        return SimServer()

    def timeout_rate(self, rate: float) -> "SimServer":
        """Rate of injected 'etcdserver: request timed out' errors."""
        if not 0.0 <= rate <= 1.0:
            raise ValueError("rate must be within [0, 1]")
        self._timeout_rate = rate
        return self

    def load(self, data: str) -> "SimServer":
        """Start from a TOML dump (restart-with-snapshot, server.rs:27-31)."""
        self._load = data
        return self

    async def serve(self, addr) -> None:
        ep = await Endpoint.bind(addr)
        service = EtcdService(self._timeout_rate, self._load)
        task_mod.spawn(service.start_ticker(), name="etcd-ticker")
        while True:
            try:
                tx, rx, _peer = await ep.accept1()
            except ChannelClosed:
                return
            task_mod.spawn(self._serve_conn(service, tx, rx), name="etcd-conn")

    async def _serve_conn(self, service: EtcdService, tx, rx) -> None:
        try:
            request = await rx.recv()
        except ChannelClosed:
            return
        op, *args = request
        try:
            if op == "put":
                key, value, lease, prev_kv = args
                rsp = await service.put(key, value, lease=lease, prev_kv=prev_kv)
            elif op == "get":
                key, prefix, revision = args
                rsp = await service.get(key, prefix=prefix, revision=revision)
            elif op == "delete":
                key, prefix = args
                rsp = await service.delete(key, prefix=prefix)
            elif op == "txn":
                (txn,) = args
                assert isinstance(txn, Txn)
                rsp = await service.txn(txn)
            elif op == "lease_grant":
                ttl, id = args
                rsp = await service.lease_grant(ttl, id)
            elif op == "lease_revoke":
                (id,) = args
                rsp = await service.lease_revoke(id)
            elif op == "lease_keep_alive":
                # long-lived stream: respond to each ping (server.rs:55-59)
                (id,) = args
                while True:
                    rsp = await service.lease_keep_alive(id)
                    tx.send(("ok", rsp))
                    await rx.recv()
            elif op == "lease_time_to_live":
                id, keys = args
                rsp = await service.lease_time_to_live(id, keys)
            elif op == "lease_leases":
                rsp = await service.lease_leases()
            elif op == "campaign":
                # a campaign can block on watch events indefinitely; race it
                # against client disconnect so the task (and its EventBus
                # subscription) is reclaimed when the caller goes away — the
                # select_biased!-on-tx.closed() of reference server.rs:64-69
                name, value, lease = args

                async def _client_gone():
                    # the client sends nothing else on a campaign stream:
                    # recv only resolves (with ChannelClosed) on disconnect
                    try:
                        await rx.recv()
                    except ChannelClosed:
                        pass

                which, rsp = await select(
                    service.campaign(name, value, lease), _client_gone()
                )
                if which == 1:
                    return
            elif op == "proclaim":
                leader, value = args
                rsp = await service.proclaim(leader, value)
            elif op == "leader":
                (name,) = args
                rsp = await service.leader(name)
            elif op == "observe":
                # long-lived stream: push leader changes (server.rs:74-91)
                (name,) = args
                name = name.encode() if isinstance(name, str) else bytes(name)
                leader, events = await service.observe(name)
                try:
                    while True:
                        await events.recv()
                        new_leader = service.inner.leader(name)
                        if new_leader.kv == leader.kv:
                            continue
                        leader = new_leader
                        tx.send(("ok", new_leader))
                finally:
                    events.close()
            elif op == "watch":
                # long-lived stream: raw PUT/DELETE events under a prefix
                # (the EventBus surfaced directly; service.rs:226-244)
                (prefix, capacity) = args
                prefix = prefix.encode() if isinstance(prefix, str) else bytes(prefix)
                events = service.inner.watcher.subscribe(prefix, capacity)
                try:
                    while True:
                        tx.send(("ok", await events.recv()))
                finally:
                    events.close()
            elif op == "resign":
                (leader,) = args
                rsp = await service.resign(leader)
            elif op == "status":
                rsp = await service.status()
            elif op == "dump":
                rsp = await service.dump()
            else:
                raise EtcdError(f"unknown request: {op}")
        except EtcdError as e:
            try:
                tx.send(("err", e))
            except ChannelClosed:
                pass
            return
        except ChannelClosed:
            return  # client went away mid-stream
        try:
            tx.send(("ok", rsp))
        except ChannelClosed:
            pass
