"""etcd client facade: kv / lease / election / watch / maintenance.

Analog of reference sim.rs:27-77 (Client + sub-clients) and the fluent APIs
in kv.rs / lease.rs / election.rs. One request = one `connect1` connection
(exactly the reference's wire discipline, sim.rs:70-76); KeepAlive and
Observe hold their connection open as streams.

    client = await Client.connect("10.0.0.1:2379")
    await client.kv.put("foo", "bar")
    resp = await client.kv.get("foo", prefix=True)
    lease = await client.lease.grant(60)
    keeper, responses = await client.lease.keep_alive(lease.id)
"""

from __future__ import annotations

import dataclasses
from typing import AsyncIterator, List, Optional, Tuple

from ...net import Endpoint
from ...core.sync import ChannelClosed
from .errors import EtcdError
from .service import (
    CampaignResponse,
    LeaderKey,
    LeaderResponse,
    Txn,
    TxnResponse,
)


@dataclasses.dataclass
class PutOptions:
    lease: int = 0
    prev_kv: bool = False

    def with_lease(self, lease: int) -> "PutOptions":
        self.lease = lease
        return self

    def with_prev_key(self) -> "PutOptions":
        self.prev_kv = True
        return self


@dataclasses.dataclass
class GetOptions:
    prefix: bool = False
    revision: int = 0

    def with_prefix(self) -> "GetOptions":
        self.prefix = True
        return self


@dataclasses.dataclass
class DeleteOptions:
    prefix: bool = False

    def with_prefix(self) -> "DeleteOptions":
        self.prefix = True
        return self


class _Conn:
    """One request/stream connection."""

    def __init__(self, tx, rx) -> None:
        self.tx = tx
        self.rx = rx

    async def recv(self):
        try:
            status, payload = await self.rx.recv()
        except ChannelClosed as e:
            raise EtcdError("etcd server connection closed") from e
        if status == "err":
            raise payload
        return payload


class Client:
    """Asynchronous etcd v3 client over the simulated network (sim.rs:27-44)."""

    def __init__(self, ep: Endpoint, server_addr) -> None:
        self._ep = ep
        self._server_addr = server_addr
        self.kv = KvClient(self)
        self.lease = LeaseClient(self)
        self.election = ElectionClient(self)
        self.watch = WatchClient(self)
        self.maintenance = MaintenanceClient(self)

    @staticmethod
    async def connect(endpoints, options=None) -> "Client":
        """Connect to the first of `endpoints` (reference sim.rs:33-44)."""
        if isinstance(endpoints, (list, tuple)):
            endpoints = endpoints[0]
        ep = await Endpoint.connect(endpoints)
        return Client(ep, ep.peer_addr())

    # sub-client accessors in the reference style (kv_client() etc.)

    def kv_client(self) -> "KvClient":
        return self.kv

    def lease_client(self) -> "LeaseClient":
        return self.lease

    def election_client(self) -> "ElectionClient":
        return self.election

    def watch_client(self) -> "WatchClient":
        return self.watch

    def maintenance_client(self) -> "MaintenanceClient":
        return self.maintenance

    async def dump(self) -> str:
        return await self._call(("dump",))

    # -- wire discipline: one connection per request (sim.rs:70-76) --

    async def _open(self, request) -> _Conn:
        tx, rx, _ = await self._ep.connect1(self._server_addr)
        tx.send(request)
        return _Conn(tx, rx)

    async def _call(self, request):
        conn = await self._open(request)
        return await conn.recv()


class KvClient:
    """reference kv.rs KvClient."""

    def __init__(self, client: Client) -> None:
        self._client = client

    async def put(self, key, value, options: Optional[PutOptions] = None):
        opt = options or PutOptions()
        return await self._client._call(("put", key, value, opt.lease, opt.prev_kv))

    async def get(self, key, options: Optional[GetOptions] = None, *, prefix: bool = False):
        opt = options or GetOptions(prefix=prefix)
        return await self._client._call(("get", key, opt.prefix, opt.revision))

    async def delete(self, key, options: Optional[DeleteOptions] = None, *, prefix: bool = False):
        opt = options or DeleteOptions(prefix=prefix)
        return await self._client._call(("delete", key, opt.prefix))

    async def txn(self, txn: Txn) -> TxnResponse:
        return await self._client._call(("txn", txn))


@dataclasses.dataclass
class _LeaseKeeper:
    """Streaming keep-alive handle (reference lease.rs LeaseKeeper)."""

    _conn: _Conn
    id: int

    async def keep_alive(self) -> None:
        """Send one ping; the response arrives on the paired stream."""
        self._conn.tx.send(("ping",))


class _LeaseKeepAliveStream:
    """Response stream for keep-alive pings."""

    def __init__(self, conn: _Conn) -> None:
        self._conn = conn

    async def message(self):
        return await self._conn.recv()


class LeaseClient:
    """reference lease.rs LeaseClient."""

    def __init__(self, client: Client) -> None:
        self._client = client

    async def grant(self, ttl: int, id: int = 0):
        return await self._client._call(("lease_grant", ttl, id))

    async def revoke(self, id: int):
        return await self._client._call(("lease_revoke", id))

    async def keep_alive(self, id: int) -> Tuple[_LeaseKeeper, _LeaseKeepAliveStream]:
        """Open the keep-alive stream; the first ping is sent immediately
        (reference server.rs:55-59 answers each ping with a fresh TTL)."""
        conn = await self._client._open(("lease_keep_alive", id))
        return _LeaseKeeper(conn, id), _LeaseKeepAliveStream(conn)

    async def time_to_live(self, id: int, keys: bool = False):
        return await self._client._call(("lease_time_to_live", id, keys))

    async def leases(self):
        return await self._client._call(("lease_leases",))


class _ObserveStream:
    """Leader-change stream (reference election.rs ObserveStream)."""

    def __init__(self, conn: _Conn, first: LeaderResponse) -> None:
        self._conn = conn
        self._first: Optional[LeaderResponse] = first

    async def message(self) -> LeaderResponse:
        if self._first is not None:
            first, self._first = self._first, None
            if first.kv is not None:
                return first
        return await self._conn.recv()

    def __aiter__(self) -> "AsyncIterator[LeaderResponse]":
        return self

    async def __anext__(self) -> LeaderResponse:
        try:
            return await self.message()
        except EtcdError:
            raise StopAsyncIteration from None


class ElectionClient:
    """reference election.rs ElectionClient."""

    def __init__(self, client: Client) -> None:
        self._client = client

    async def campaign(self, name, value, lease: int) -> CampaignResponse:
        return await self._client._call(("campaign", name, value, lease))

    async def proclaim(self, value, leader: LeaderKey):
        return await self._client._call(("proclaim", leader, value))

    async def leader(self, name) -> LeaderResponse:
        return await self._client._call(("leader", name))

    async def observe(self, name) -> _ObserveStream:
        """Stream of leader changes; yields the current leader first if any
        (the reference's observe emits on each change, server.rs:74-91)."""
        conn = await self._client._open(("observe", name))
        current = await self._client.election.leader(name)
        return _ObserveStream(conn, current)

    async def resign(self, leader: LeaderKey):
        return await self._client._call(("resign", leader))


class WatchClient:
    """Prefix watch: a stream of raw PUT/DELETE events.

    The reference exposes watching only through election observe (its
    watch.rs holds just EventType); here the same EventBus mechanism is
    surfaced directly, pythonically, since the underlying server already
    supports arbitrary prefix subscriptions (service.rs:226-233).
    """

    def __init__(self, client: Client) -> None:
        self._client = client

    async def watch_prefix(self, prefix, capacity: int = 64) -> "_WatchStream":
        if isinstance(prefix, str):
            prefix = prefix.encode()
        conn = await self._client._open(("watch", prefix, capacity))
        return _WatchStream(conn)


class _WatchStream:
    """Async iterator of Events under the watched prefix."""

    def __init__(self, conn: _Conn) -> None:
        self._conn = conn

    async def message(self):
        return await self._conn.recv()

    def __aiter__(self):
        return self

    async def __anext__(self):
        try:
            return await self.message()
        except EtcdError:
            raise StopAsyncIteration from None


class MaintenanceClient:
    """reference maintenance.rs."""

    def __init__(self, client: Client) -> None:
        self._client = client

    async def status(self):
        return await self._client._call(("status",))
