"""S3 error types (reference src/server/service.rs:608-625 error ctors)."""

from __future__ import annotations


class S3Error(Exception):
    def __reduce__(self):
        return (type(self), tuple(self.args))


class NoSuchBucket(S3Error):
    def __init__(self, bucket: str) -> None:
        super().__init__(f"no such bucket: {bucket}")
        self.bucket = bucket

    def __reduce__(self):
        return (NoSuchBucket, (self.bucket,))


class NoSuchKey(S3Error):
    def __init__(self, key: str) -> None:
        super().__init__(f"no such key: {key}")
        self.key = key

    def __reduce__(self):
        return (NoSuchKey, (self.key,))


class NoSuchUpload(S3Error):
    def __init__(self, upload_id: str) -> None:
        super().__init__(f"no such upload: {upload_id}")
        self.upload_id = upload_id

    def __reduce__(self):
        return (NoSuchUpload, (self.upload_id,))


class InvalidRange(S3Error):
    def __init__(self, detail: str) -> None:
        super().__init__(f"invalid range: {detail}")
        self.detail = detail

    def __reduce__(self):
        return (InvalidRange, (self.detail,))
