"""S3 simulation: in-sim object store + client over the simulated network.

Analog of reference madsim-aws-sdk-s3 (1520 LoC): buckets, objects,
multipart upload assembly, ranged gets, list-objects-v2, bucket lifecycle
configuration — an `S3Service` served over the Endpoint connection API plus
a pythonic `Client` mirroring the fluent aws-sdk surface.

    server.spawn(S3Server().serve("10.0.0.1:9000"))
    s3 = await Client.connect("10.0.0.1:9000")
    await s3.create_bucket("b")
    await s3.put_object("b", "k", b"data")
    out = await s3.get_object("b", "k", range="bytes=1-3")
"""

from .client import Client  # noqa: F401
from .errors import (  # noqa: F401
    NoSuchBucket,
    NoSuchKey,
    NoSuchUpload,
    S3Error,
)
from .service import LifecycleRule, ObjectInfo, S3Service  # noqa: F401
from .server import S3Server  # noqa: F401
