"""S3Server: the object store served over the simulated network.

Analog of reference src/server/rpc_server.rs: one request per `connect1`
connection, ("ok", value) / ("err", S3Error) responses.
"""

from __future__ import annotations

from ...core import task as task_mod
from ...core.sync import ChannelClosed
from ...net import Endpoint
from .errors import S3Error
from .service import S3Service


class S3Server:
    def __init__(self) -> None:
        self.service = S3Service()

    async def serve(self, addr) -> None:
        ep = await Endpoint.bind(addr)
        while True:
            try:
                tx, rx, _peer = await ep.accept1()
            except ChannelClosed:
                return
            task_mod.spawn(self._serve_conn(tx, rx), name="s3-conn")

    async def _serve_conn(self, tx, rx) -> None:
        try:
            request = await rx.recv()
        except ChannelClosed:
            return
        op, *args = request
        try:
            method = getattr(self.service, op, None)
            if method is None or op.startswith("_"):
                raise S3Error(f"unknown request: {op}")
            rsp = method(*args)
        except (S3Error, ValueError) as e:
            try:
                tx.send(("err", e))
            except ChannelClosed:
                pass
            return
        try:
            tx.send(("ok", rsp))
        except ChannelClosed:
            pass
