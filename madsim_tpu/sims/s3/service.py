"""The S3 state machine (reference src/server/service.rs:203-606).

Buckets map keys to objects; an object is complete (visible) after
put_object or complete_multipart_upload. Multipart uploads accumulate
e-tagged parts per upload id and assemble in part-number order on
completion. Ranged gets follow RFC 9110 `bytes=` semantics. The reference
leaves get-by-part-number a todo!(); here it returns that part's bytes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ...core import context
from .errors import InvalidRange, NoSuchBucket, NoSuchKey, NoSuchUpload


@dataclasses.dataclass
class LifecycleRule:
    """One bucket lifecycle rule (id + expiration days; enough for parity
    tests — the reference stores aws-sdk rule structs opaquely)."""

    id: str = ""
    expiration_days: Optional[int] = None
    prefix: str = ""
    status: str = "Enabled"


@dataclasses.dataclass
class ObjectInfo:
    """list_objects_v2 entry (reference types::Object)."""

    key: str
    size: int
    last_modified: Optional[float] = None


@dataclasses.dataclass
class _Part:
    part_number: int
    body: bytes
    e_tag: str


class _Object:
    __slots__ = ("body", "completed", "parts", "last_modified")

    def __init__(self) -> None:
        self.body = b""
        self.completed = False
        self.parts: Dict[str, List[_Part]] = {}  # upload_id -> parts
        self.last_modified: Optional[float] = None


def _parse_range(range_header: str, body: bytes) -> bytes:
    """RFC 9110 bytes= range (service.rs:386-419)."""
    unit, _, range_set = range_header.partition("=")
    if unit != "bytes" or not range_set:
        raise InvalidRange(range_header)
    begin_str, sep, end_str = range_set.partition("-")
    if not sep:
        raise InvalidRange(range_header)
    try:
        begin = int(begin_str) if begin_str else None
        end = int(end_str) if end_str else None
    except ValueError:
        raise InvalidRange(range_header) from None
    if begin is not None and end is not None:
        return body[begin : end + 1]
    if begin is not None:
        return body[begin:]
    if end is not None:  # suffix form: last N bytes
        return body[len(body) - end :]
    raise InvalidRange(range_header)


class S3Service:
    """Synchronous object-store state machine."""

    def __init__(self) -> None:
        # bucket -> key -> object
        self.storage: Dict[str, Dict[str, _Object]] = {}
        self.lifecycle: Dict[str, List[LifecycleRule]] = {}

    # -- buckets --

    def create_bucket(self, name: str) -> None:
        if name in self.storage:
            raise ValueError(f"bucket already exists: {name}")
        self.storage[name] = {}

    def _bucket(self, name: str) -> Dict[str, _Object]:
        bucket = self.storage.get(name)
        if bucket is None:
            raise NoSuchBucket(name)
        return bucket

    def _object(self, bucket: str, key: str) -> _Object:
        obj = self._bucket(bucket).get(key)
        if obj is None:
            raise NoSuchKey(key)
        return obj

    # -- plain objects --

    def put_object(self, bucket: str, key: str, body: bytes) -> None:
        obj = self._bucket(bucket).setdefault(key, _Object())
        obj.body = bytes(body)
        obj.completed = True
        obj.last_modified = self._now()

    def get_object(
        self,
        bucket: str,
        key: str,
        range: Optional[str] = None,
        part_number: Optional[int] = None,
    ) -> bytes:
        obj = self._object(bucket, key)
        if not obj.completed:
            raise NoSuchKey(key)
        if range is not None:
            return _parse_range(range, obj.body)
        if part_number is not None:
            raise InvalidRange(f"part number gets need an active upload: {part_number}")
        return obj.body

    def head_object(self, bucket: str, key: str) -> Tuple[int, Optional[float]]:
        obj = self._object(bucket, key)
        if not obj.completed:
            raise NoSuchKey(key)
        return (len(obj.body), obj.last_modified)

    def delete_object(self, bucket: str, key: str) -> None:
        self._bucket(bucket).pop(key, None)

    def delete_objects(self, bucket: str, keys: List[str]) -> None:
        b = self._bucket(bucket)
        for key in keys:
            b.pop(key, None)

    def list_objects_v2(
        self, bucket: str, prefix: Optional[str] = None
    ) -> List[ObjectInfo]:
        b = self._bucket(bucket)
        out = []
        for key in sorted(b):
            obj = b[key]
            if not obj.completed:
                continue
            if prefix is not None and not key.startswith(prefix):
                continue
            out.append(
                ObjectInfo(key=key, size=len(obj.body), last_modified=obj.last_modified)
            )
        return out

    # -- multipart (service.rs:242-366) --

    def create_multipart_upload(self, bucket: str, key: str) -> str:
        obj = self._bucket(bucket).setdefault(key, _Object())
        while True:
            upload_id = str(self._rand_u32())
            if upload_id not in obj.parts:
                obj.parts[upload_id] = []
                return upload_id

    def upload_part(
        self, bucket: str, key: str, upload_id: str, part_number: int, body: bytes
    ) -> str:
        obj = self._object(bucket, key)
        parts = obj.parts.get(upload_id)
        if parts is None:
            raise NoSuchUpload(upload_id)
        e_tag = str(self._rand_u32())
        parts.append(_Part(part_number, bytes(body), e_tag))
        return e_tag

    def complete_multipart_upload(
        self,
        bucket: str,
        key: str,
        upload_id: str,
        completed_parts: List[Tuple[int, Optional[str]]],
    ) -> None:
        """Assemble parts in part-number order; a part matches by number and
        (when given) e-tag (service.rs:301-345)."""
        obj = self._object(bucket, key)
        parts = obj.parts.pop(upload_id, None)
        if parts is None:
            raise NoSuchUpload(upload_id)
        body = bytearray()
        for part_number, e_tag in sorted(completed_parts, key=lambda p: p[0]):
            for part in parts:
                if part.part_number == part_number and (
                    e_tag is None or e_tag == part.e_tag
                ):
                    body.extend(part.body)
                    break
        obj.body = bytes(body)
        obj.completed = True
        obj.last_modified = self._now()

    def abort_multipart_upload(self, bucket: str, key: str, upload_id: str) -> None:
        obj = self._object(bucket, key)
        if obj.parts.pop(upload_id, None) is None:
            raise NoSuchUpload(upload_id)

    # -- lifecycle (service.rs:580-606) --

    def get_bucket_lifecycle_configuration(self, bucket: str) -> List[LifecycleRule]:
        return list(self.lifecycle.setdefault(bucket, []))

    def put_bucket_lifecycle_configuration(
        self, bucket: str, rules: List[LifecycleRule]
    ) -> None:
        self.lifecycle[bucket] = list(rules)

    # -- deterministic helpers --

    @staticmethod
    def _rand_u32() -> int:
        h = context.try_current_handle()
        if h is not None:
            return h.rng.next_u64() & 0xFFFF_FFFF
        import os

        # production-mode branch; sims take the seeded-rng path above
        return int.from_bytes(os.urandom(4), "little")  # madsim: allow(ambient-entropy)

    @staticmethod
    def _now() -> Optional[float]:
        h = context.try_current_handle()
        return h.time.now_time() if h is not None else None
