"""S3 client: the aws-sdk fluent surface, pythonically.

Analog of reference src/client.rs + src/operation/ fluent builders: each
operation is one method with keyword options, shipped as one request over
one `connect1` connection (the rpc_server wire discipline).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ...core.sync import ChannelClosed
from ...net import Endpoint
from .errors import S3Error
from .service import LifecycleRule, ObjectInfo


class Client:
    """Async S3 client over the simulated network."""

    def __init__(self, ep: Endpoint, server_addr) -> None:
        self._ep = ep
        self._addr = server_addr

    @staticmethod
    async def connect(addr) -> "Client":
        ep = await Endpoint.connect(addr)
        return Client(ep, ep.peer_addr())

    async def _call(self, request):
        tx, rx, _ = await self._ep.connect1(self._addr)
        tx.send(request)
        try:
            status, payload = await rx.recv()
        except ChannelClosed as e:
            raise S3Error("s3 server connection closed") from e
        if status == "err":
            raise payload
        return payload

    # -- buckets / objects --

    async def create_bucket(self, bucket: str) -> None:
        await self._call(("create_bucket", bucket))

    async def put_object(self, bucket: str, key: str, body: bytes) -> None:
        await self._call(("put_object", bucket, key, bytes(body)))

    async def get_object(
        self,
        bucket: str,
        key: str,
        range: Optional[str] = None,
        part_number: Optional[int] = None,
    ) -> bytes:
        return await self._call(("get_object", bucket, key, range, part_number))

    async def head_object(self, bucket: str, key: str) -> Tuple[int, Optional[float]]:
        return await self._call(("head_object", bucket, key))

    async def delete_object(self, bucket: str, key: str) -> None:
        await self._call(("delete_object", bucket, key))

    async def delete_objects(self, bucket: str, keys: List[str]) -> None:
        await self._call(("delete_objects", bucket, list(keys)))

    async def list_objects_v2(
        self, bucket: str, prefix: Optional[str] = None
    ) -> List[ObjectInfo]:
        return await self._call(("list_objects_v2", bucket, prefix))

    # -- multipart --

    async def create_multipart_upload(self, bucket: str, key: str) -> str:
        return await self._call(("create_multipart_upload", bucket, key))

    async def upload_part(
        self, bucket: str, key: str, upload_id: str, part_number: int, body: bytes
    ) -> str:
        return await self._call(
            ("upload_part", bucket, key, upload_id, part_number, bytes(body))
        )

    async def complete_multipart_upload(
        self,
        bucket: str,
        key: str,
        upload_id: str,
        parts: List[Tuple[int, Optional[str]]],
    ) -> None:
        await self._call(
            ("complete_multipart_upload", bucket, key, upload_id, list(parts))
        )

    async def abort_multipart_upload(
        self, bucket: str, key: str, upload_id: str
    ) -> None:
        await self._call(("abort_multipart_upload", bucket, key, upload_id))

    # -- lifecycle --

    async def get_bucket_lifecycle_configuration(
        self, bucket: str
    ) -> List[LifecycleRule]:
        return await self._call(("get_bucket_lifecycle_configuration", bucket))

    async def put_bucket_lifecycle_configuration(
        self, bucket: str, rules: List[LifecycleRule]
    ) -> None:
        await self._call(("put_bucket_lifecycle_configuration", bucket, list(rules)))
