"""Kafka simulation: in-sim broker + producers/consumers/admin.

Analog of reference madsim-rdkafka's sim side (src/sim/, 2603 LoC): a
`SimBroker` serving topics/partitions/offsets/watermarks/fetch over the
simulated network, with `BaseProducer` (buffered sends + flush),
`BaseConsumer`/`StreamConsumer` (assign/subscribe + poll/stream), and
`AdminClient` (create_topics) configured through the familiar
`ClientConfig` key-value API.

    broker.spawn(SimBroker().serve("10.0.0.1:9092"))
    producer = await ClientConfig({"bootstrap.servers": "10.0.0.1:9092"}).create_producer()
    producer.send(BaseRecord.to("topic").with_key(b"k").with_payload(b"v"))
    await producer.flush()
"""

from .broker import Broker, FetchOptions, OwnedMessage, OwnedRecord  # noqa: F401
from .client import (  # noqa: F401
    AdminClient,
    AdminOptions,
    BaseConsumer,
    BaseProducer,
    BaseRecord,
    ClientConfig,
    NewPartitions,
    NewTopic,
    StreamConsumer,
)
from .errors import KafkaError  # noqa: F401
from .server import SimBroker  # noqa: F401
from .tpl import OFFSET_BEGINNING, OFFSET_END, OFFSET_INVALID, TopicPartitionList  # noqa: F401
