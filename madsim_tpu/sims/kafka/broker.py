"""The broker state machine: topics, partitions, offsets, watermarks, fetch.

Analog of reference madsim-rdkafka/src/sim/broker.rs:14-213. One divergence,
deliberate: the reference round-robins every record across partitions and
ignores `BaseRecord.partition` entirely; here an explicit partition (or a
key hash, like real Kafka) wins, with round-robin as the keyless fallback —
otherwise keyed ordering tests can't be written at all.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .errors import (
    KafkaError,
    invalid_partitions,
    invalid_timestamp,
    no_offset,
    unknown_partition,
    unknown_topic,
)
from .tpl import OFFSET_BEGINNING, OFFSET_END, OFFSET_INVALID, TopicPartitionList


@dataclasses.dataclass
class OwnedMessage:
    """A stored record (reference src/sim/message.rs OwnedMessage)."""

    payload: Optional[bytes]
    key: Optional[bytes]
    topic: str
    timestamp: Optional[int]  # ms since epoch (CreateTime), None = unavailable
    partition: int
    offset: int
    headers: Optional[Dict[str, bytes]] = None

    def size(self) -> int:
        return (
            len(self.payload or b"")
            + len(self.key or b"")
            + sum(len(k) + len(v) for k, v in (self.headers or {}).items())
        )


@dataclasses.dataclass
class OwnedRecord:
    """A record to produce (reference broker.rs:232-252)."""

    topic: str
    partition: Optional[int] = None
    payload: Optional[bytes] = None
    key: Optional[bytes] = None
    timestamp: Optional[int] = None
    headers: Optional[Dict[str, bytes]] = None


@dataclasses.dataclass
class FetchOptions:
    """reference broker.rs:254-275."""

    max_partition_fetch_bytes: int = 1_048_576  # 1 MiB
    fetch_max_bytes: int = 52_428_800  # 50 MiB


class _Partition:
    def __init__(self, id: int) -> None:
        self.id = id
        self.log_end_offset = 0
        self.low_watermark = 0
        self.high_watermark = 0
        self.msgs: List[OwnedMessage] = []

    def offset_for_time(self, timestamp: int) -> Optional[int]:
        """Earliest offset whose timestamp >= the given one (broker.rs:46-59)."""
        for msg in self.msgs:
            if (msg.timestamp or 0) >= timestamp:
                return msg.offset
        return None


class _Topic:
    def __init__(self, name: str, partitions: int) -> None:
        self.name = name
        self.partitions = [_Partition(i) for i in range(partitions)]
        self.last_partition = 0


class Broker:
    """Topics + partitions + message logs (broker.rs:14-31)."""

    def __init__(self) -> None:
        self.topics: Dict[str, _Topic] = {}

    def create_topic(self, name: str, partitions: int) -> None:
        self.topics[name] = _Topic(name, partitions)

    def create_partitions(self, name: str, new_total: int) -> None:
        """Grow a topic to `new_total` partitions (admin.rs NewPartitions);
        shrinking is rejected like real Kafka."""
        topic = self.topics.get(name)
        if topic is None:
            raise unknown_topic(name)
        if new_total <= len(topic.partitions):
            raise invalid_partitions(name, new_total)
        for i in range(len(topic.partitions), new_total):
            topic.partitions.append(_Partition(i))

    def produce(self, records: List[OwnedRecord]) -> None:
        for record in records:
            self._produce_one(record)

    def _produce_one(self, record: OwnedRecord) -> None:
        topic = self.topics.get(record.topic)
        if topic is None:
            raise unknown_topic(record.topic)
        n = len(topic.partitions)
        if record.partition is not None:
            if not 0 <= record.partition < n:
                raise unknown_partition(record.topic, record.partition)
            idx = record.partition
        elif record.key is not None:
            # stable key hash (Python's hash() is salted per process)
            import zlib

            idx = zlib.crc32(record.key) % n
        else:
            idx = topic.last_partition
            topic.last_partition = (topic.last_partition + 1) % n
        partition = topic.partitions[idx]
        msg = OwnedMessage(
            payload=record.payload,
            key=record.key,
            topic=record.topic,
            timestamp=record.timestamp,
            partition=idx,
            offset=partition.log_end_offset,
            headers=record.headers,
        )
        partition.msgs.append(msg)
        partition.log_end_offset += 1
        partition.high_watermark = partition.log_end_offset

    def fetch(
        self, tpl: TopicPartitionList, opts: Optional[FetchOptions] = None
    ) -> List[OwnedMessage]:
        """Fetch from each element's offset, advancing the tpl offsets
        (broker.rs:113-160). Size caps bound the batch."""
        opts = opts or FetchOptions()
        rets: List[OwnedMessage] = []
        total_bytes = 0
        for e in tpl.list:
            partition = self._get_partition(e.topic, e.partition)
            msgs = partition.msgs
            if not msgs:
                continue
            if e.offset == OFFSET_BEGINNING:
                start = 0
            elif e.offset == OFFSET_END:
                start = len(msgs) - 1
            elif e.offset == OFFSET_INVALID:
                raise no_offset()
            else:
                start = sum(1 for m in msgs if m.offset < e.offset)
            bytes_in_partition = 0
            for msg in msgs[start:]:
                size = msg.size()
                if msg.offset >= partition.high_watermark:
                    continue
                if (
                    total_bytes + size > opts.fetch_max_bytes
                    or bytes_in_partition + size > opts.max_partition_fetch_bytes
                ):
                    return rets
                e.offset = msg.offset + 1
                rets.append(msg)
                total_bytes += size
                bytes_in_partition += size
        return rets

    def metadata(self) -> Dict[str, List[int]]:
        """topic -> partition ids (reference Metadata, broker.rs:162-166)."""
        return {
            name: [p.id for p in t.partitions] for name, t in self.topics.items()
        }

    def metadata_of_topic(self, topic: str) -> Dict[str, List[int]]:
        t = self.topics.get(topic)
        if t is None:
            raise unknown_topic(topic)
        return {topic: [p.id for p in t.partitions]}

    def fetch_watermarks(self, topic: str, partition: int) -> Tuple[int, int]:
        p = self._get_partition(topic, partition)
        return (p.low_watermark, p.high_watermark)

    def offsets_for_times(self, tpl: TopicPartitionList) -> TopicPartitionList:
        """tpl offsets are interpreted as timestamps (broker.rs:184-203)."""
        ret = TopicPartitionList()
        for e in tpl.list:
            partition = self._get_partition(e.topic, e.partition)
            if e.offset < 0:
                raise invalid_timestamp()
            offset = partition.offset_for_time(e.offset)
            ret.add_partition_offset(
                e.topic, e.partition, OFFSET_INVALID if offset is None else offset
            )
        return ret

    def _get_partition(self, topic: str, partition: int) -> _Partition:
        t = self.topics.get(topic)
        if t is None:
            raise unknown_topic(topic)
        if not 0 <= partition < len(t.partitions):
            raise unknown_partition(topic, partition)
        return t.partitions[partition]
