"""TopicPartitionList: (topic, partition, offset) triples.

Analog of reference madsim-rdkafka/src/sim/topic_partition_list.rs. Offsets
use librdkafka's integer sentinels: OFFSET_BEGINNING (-2), OFFSET_END (-1),
OFFSET_INVALID (-1001); any value >= 0 is a concrete offset.
"""

from __future__ import annotations

import dataclasses
from typing import List

OFFSET_BEGINNING = -2
OFFSET_END = -1
OFFSET_INVALID = -1001


@dataclasses.dataclass
class TopicPartitionListElem:
    topic: str
    partition: int
    offset: int = OFFSET_INVALID


@dataclasses.dataclass
class TopicPartitionList:
    list: List[TopicPartitionListElem] = dataclasses.field(default_factory=list)

    def add_partition(self, topic: str, partition: int) -> None:
        self.list.append(TopicPartitionListElem(topic, partition))

    def add_partition_offset(self, topic: str, partition: int, offset: int) -> None:
        self.list.append(TopicPartitionListElem(topic, partition, offset))

    def count(self) -> int:
        return len(self.list)

    def elements(self) -> List[TopicPartitionListElem]:
        return list(self.list)
