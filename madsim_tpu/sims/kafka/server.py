"""SimBroker: the broker served over the simulated network.

Analog of reference madsim-rdkafka/src/sim/sim_broker.rs:14-77: one request
per `connect1` connection, wire enum as plain tuples, responses
("ok", value) or ("err", KafkaError).
"""

from __future__ import annotations

from ...core import task as task_mod
from ...core.sync import ChannelClosed
from ...net import Endpoint
from .broker import Broker, FetchOptions
from .errors import KafkaError


class SimBroker:
    """A simulated Kafka broker (sim_broker.rs:10-50)."""

    def __init__(self) -> None:
        self._broker = Broker()

    async def serve(self, addr) -> None:
        ep = await Endpoint.bind(addr)
        while True:
            try:
                tx, rx, _peer = await ep.accept1()
            except ChannelClosed:
                return
            task_mod.spawn(self._serve_conn(tx, rx), name="kafka-conn")

    async def _serve_conn(self, tx, rx) -> None:
        try:
            request = await rx.recv()
        except ChannelClosed:
            return
        op, *args = request
        b = self._broker
        try:
            if op == "create_topic":
                name, partitions = args
                b.create_topic(name, partitions)
                rsp = None
            elif op == "create_partitions":
                name, new_total = args
                b.create_partitions(name, new_total)
                rsp = None
            elif op == "produce":
                (records,) = args
                b.produce(records)
                rsp = None
            elif op == "fetch":
                tpl, opts = args
                msgs = b.fetch(tpl, opts or FetchOptions())
                rsp = (msgs, tpl)  # tpl comes back with advanced offsets
            elif op == "fetch_metadata":
                (topic,) = args
                rsp = b.metadata() if topic is None else b.metadata_of_topic(topic)
            elif op == "fetch_watermarks":
                topic, partition = args
                rsp = b.fetch_watermarks(topic, partition)
            elif op == "offsets_for_times":
                (tpl,) = args
                rsp = b.offsets_for_times(tpl)
            else:
                raise KafkaError(f"unknown request: {op}")
        except KafkaError as e:
            try:
                tx.send(("err", e))
            except ChannelClosed:
                pass
            return
        try:
            tx.send(("ok", rsp))
        except ChannelClosed:
            pass
