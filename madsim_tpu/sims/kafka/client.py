"""Kafka clients: ClientConfig, producer, consumers, admin.

Analog of reference madsim-rdkafka/src/sim/{client,config,producer,consumer,
admin}.rs. `ClientConfig` is the rdkafka-style key-value bag; recognized keys:

    bootstrap.servers         broker address (required)
    auto.offset.reset         "earliest" (default) | "latest" — where
                              subscribe() starts when no offset is stored
    fetch.max.bytes / max.partition.fetch.bytes — fetch size caps

Producers buffer records locally; `flush()` ships the batch (the inflight
model of producer.rs:218-245). Consumers either `assign()` explicit
partitions or `subscribe()` whole topics (partition discovery via metadata).
"""

from __future__ import annotations

import dataclasses
from typing import AsyncIterator, Dict, List, Optional

from ...core.sync import ChannelClosed
from ...net import Endpoint
from ...net.addr import lookup_host
from .broker import FetchOptions, OwnedMessage, OwnedRecord
from .errors import KafkaError, invalid_transaction_state, queue_full
from .tpl import OFFSET_BEGINNING, OFFSET_END, OFFSET_INVALID, TopicPartitionList


class BaseRecord:
    """Fluent record builder (producer.rs:21-86)."""

    def __init__(self, topic: str) -> None:
        self.topic = topic
        self.partition: Optional[int] = None
        self.payload: Optional[bytes] = None
        self.key: Optional[bytes] = None
        self.timestamp: Optional[int] = None
        self.headers: Optional[Dict[str, bytes]] = None

    @staticmethod
    def to(topic: str) -> "BaseRecord":
        return BaseRecord(topic)

    def with_partition(self, partition: int) -> "BaseRecord":
        self.partition = partition
        return self

    def with_payload(self, payload) -> "BaseRecord":
        self.payload = payload.encode() if isinstance(payload, str) else bytes(payload)
        return self

    def with_key(self, key) -> "BaseRecord":
        self.key = key.encode() if isinstance(key, str) else bytes(key)
        return self

    def with_timestamp(self, timestamp_ms: int) -> "BaseRecord":
        self.timestamp = timestamp_ms
        return self

    def with_headers(self, headers: Dict[str, bytes]) -> "BaseRecord":
        self.headers = headers
        return self

    def _to_owned(self) -> OwnedRecord:
        return OwnedRecord(
            topic=self.topic,
            partition=self.partition,
            payload=self.payload,
            key=self.key,
            timestamp=self.timestamp,
            headers=self.headers,
        )


class _Conn:
    """One request over one connection (the SimBroker wire discipline)."""

    def __init__(self, ep: Endpoint, addr) -> None:
        self._ep = ep
        self._addr = addr

    async def call(self, request):
        tx, rx, _ = await self._ep.connect1(self._addr)
        tx.send(request)
        try:
            status, payload = await rx.recv()
        except ChannelClosed as e:
            raise KafkaError("broker connection closed", "Transport") from e
        if status == "err":
            raise payload
        return payload


class ClientConfig:
    """rdkafka-style config bag (config.rs)."""

    def __init__(self, conf: Optional[Dict[str, str]] = None) -> None:
        self.conf: Dict[str, str] = dict(conf or {})

    def set(self, key: str, value: str) -> "ClientConfig":
        self.conf[key] = str(value)
        return self

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self.conf.get(key, default)

    async def _connect(self) -> _Conn:
        servers = self.conf.get("bootstrap.servers")
        if not servers:
            raise KafkaError("bootstrap.servers is required", "InvalidConfig")
        addr = await lookup_host(servers.split(",")[0].strip())
        ep = await Endpoint.bind(("0.0.0.0", 0))
        return _Conn(ep, addr)

    async def create_producer(self) -> "BaseProducer":
        return BaseProducer(await self._connect(), self)

    async def create_consumer(self) -> "BaseConsumer":
        return BaseConsumer(await self._connect(), self)

    async def create_stream_consumer(self) -> "StreamConsumer":
        return StreamConsumer(await self._connect(), self)

    async def create_admin(self) -> "AdminClient":
        return AdminClient(await self._connect())


class BaseProducer:
    """Buffering producer with transactions (producer.rs:155-320).

    State machine mirrors the reference's Inner enum (producer.rs:162-175):
    INIT until the first send() (-> NON_TXN) or init_transactions()
    (-> TXN). A transactional producer buffers sends while a transaction is
    open; commit ships the whole buffer as ONE produce request — atomic on
    the broker by construction (the sim broker appends a batch
    synchronously) — and abort discards it. A non-transactional producer
    buffers records until flush()/poll(), raising QueueFull when a send
    finds more than 10 already queued (the reference's exact simulated
    queue-full boundary, producer.rs:196-198).
    """

    _INIT, _NON_TXN, _TXN = 0, 1, 2

    def __init__(self, conn: _Conn, config: Optional["ClientConfig"] = None) -> None:
        self._conn = conn
        self._config = config
        self._queue: List[OwnedRecord] = []
        self._state = self._INIT
        self._in_txn = False

    def send(self, record: BaseRecord) -> None:
        if self._state == self._INIT:
            self._state = self._NON_TXN
        if self._state == self._NON_TXN:
            if len(self._queue) > 10:  # simulated queue full (producer.rs:191)
                raise queue_full()
            self._queue.append(record._to_owned())
            return
        if not self._in_txn:
            raise invalid_transaction_state(
                "messages should only be sent when a transaction is active"
            )
        self._queue.append(record._to_owned())

    # -- transactions (producer.rs:246-320) --

    async def init_transactions(self, timeout: Optional[float] = None) -> None:
        tid = self._config.get("transactional.id") if self._config else None
        if not tid:
            raise invalid_transaction_state("transactional ID not set")
        if self._state != self._INIT:
            raise invalid_transaction_state(
                "init_transactions must be called before any operations"
            )
        self._state = self._TXN

    def begin_transaction(self) -> None:
        if self._state != self._TXN:
            raise invalid_transaction_state("transaction not initialized")
        if self._in_txn:
            raise invalid_transaction_state("transaction already in progress")
        self._in_txn = True

    async def commit_transaction(self, timeout: Optional[float] = None) -> None:
        if self._state != self._TXN or not self._in_txn:
            raise invalid_transaction_state("no opened transaction")
        batch, self._queue = self._queue, []
        try:
            if batch:
                await self._conn.call(("produce", batch))
        except BaseException:
            self._queue = batch  # commit retryable: buffer not lost
            raise
        self._in_txn = False

    async def abort_transaction(self, timeout: Optional[float] = None) -> None:
        if self._state != self._TXN or not self._in_txn:
            raise invalid_transaction_state("no opened transaction")
        self._queue.clear()
        self._in_txn = False

    # -- delivery --

    async def flush(self, timeout: Optional[float] = None) -> None:
        if self._state == self._TXN or not self._queue:
            return  # txn buffers ship on commit, never on flush
        batch, self._queue = self._queue, []
        try:
            await self._conn.call(("produce", batch))
        except BaseException:
            self._queue = batch + self._queue  # retryable: batch not lost
            raise

    async def poll(self, timeout: Optional[float] = None) -> int:
        """Deliver queued records; returns how many were shipped."""
        if self._state == self._TXN:
            return 0
        n = len(self._queue)
        await self.flush(timeout)
        return n

    def in_flight_count(self) -> int:
        return len(self._queue)


@dataclasses.dataclass
class _ConsumerState:
    tpl: TopicPartitionList = dataclasses.field(default_factory=TopicPartitionList)
    subscribed: List[str] = dataclasses.field(default_factory=list)
    buffer: List[OwnedMessage] = dataclasses.field(default_factory=list)


class BaseConsumer:
    """Pull consumer (consumer.rs:64-254): explicit assign() or topic
    subscribe(); poll() returns one message or None when caught up."""

    def __init__(self, conn: _Conn, config: ClientConfig) -> None:
        self._conn = conn
        self._config = config
        self._state = _ConsumerState()
        self._fetch_opts = FetchOptions(
            fetch_max_bytes=int(config.get("fetch.max.bytes", "52428800")),
            max_partition_fetch_bytes=int(
                config.get("max.partition.fetch.bytes", "1048576")
            ),
        )

    def assign(self, assignment: TopicPartitionList) -> None:
        reset = self._initial_offset()
        tpl = TopicPartitionList()
        for e in assignment.list:
            # only OFFSET_INVALID falls back to auto.offset.reset; explicit
            # OFFSET_BEGINNING/OFFSET_END sentinels pass through to the broker
            offset = reset if e.offset == OFFSET_INVALID else e.offset
            tpl.add_partition_offset(e.topic, e.partition, offset)
        self._state.tpl = tpl

    def subscribe(self, topics: List[str]) -> None:
        self._state.subscribed = list(topics)

    def _initial_offset(self) -> int:
        return (
            OFFSET_END
            if self._config.get("auto.offset.reset", "earliest") == "latest"
            else OFFSET_BEGINNING
        )

    async def _resolve_subscription(self) -> None:
        if not self._state.subscribed:
            return
        topics, self._state.subscribed = self._state.subscribed, []
        reset = self._initial_offset()
        for topic in topics:
            meta = await self._conn.call(("fetch_metadata", topic))
            for partition in meta[topic]:
                self._state.tpl.add_partition_offset(topic, partition, reset)

    async def poll(self, timeout: Optional[float] = None) -> Optional[OwnedMessage]:
        """Next message, or None if nothing new is available."""
        await self._resolve_subscription()
        if not self._state.buffer:
            if not self._state.tpl.list:
                raise KafkaError("no partitions assigned", "NoAssignment")
            msgs, tpl = await self._conn.call(("fetch", self._state.tpl, self._fetch_opts))
            self._state.tpl = tpl  # offsets advanced by the broker
            self._state.buffer.extend(msgs)
        if self._state.buffer:
            return self._state.buffer.pop(0)
        return None

    async def fetch_watermarks(self, topic: str, partition: int):
        return await self._conn.call(("fetch_watermarks", topic, partition))

    async def offsets_for_times(self, tpl: TopicPartitionList) -> TopicPartitionList:
        return await self._conn.call(("offsets_for_times", tpl))

    async def fetch_metadata(self, topic: Optional[str] = None):
        return await self._conn.call(("fetch_metadata", topic))


class StreamConsumer(BaseConsumer):
    """Async-iterating consumer (consumer.rs:256-301 + MessageStream)."""

    def stream(self, idle_wait: float = 0.05) -> "MessageStream":
        return MessageStream(self, idle_wait)


class MessageStream:
    """Endless async iterator over a StreamConsumer's messages."""

    def __init__(self, consumer: StreamConsumer, idle_wait: float) -> None:
        self._consumer = consumer
        self._idle_wait = idle_wait

    def __aiter__(self) -> "AsyncIterator[OwnedMessage]":
        return self

    async def __anext__(self) -> OwnedMessage:
        from ...core.vtime import sleep

        while True:
            msg = await self._consumer.poll()
            if msg is not None:
                return msg
            await sleep(self._idle_wait)


@dataclasses.dataclass
class NewTopic:
    """admin.rs:155-188 (replication is accepted and ignored, like the sim)."""

    name: str
    num_partitions: int
    replication: int = 1


@dataclasses.dataclass
class NewPartitions:
    """admin.rs:184-208: grow a topic's partition count."""

    topic_name: str
    new_partition_count: int


@dataclasses.dataclass
class AdminOptions:
    request_timeout: Optional[float] = None


class AdminClient:
    """admin.rs:66-112."""

    def __init__(self, conn: _Conn) -> None:
        self._conn = conn

    async def create_topics(
        self, topics: List[NewTopic], options: Optional[AdminOptions] = None
    ) -> None:
        for t in topics:
            await self._conn.call(("create_topic", t.name, t.num_partitions))

    async def create_partitions(
        self, partitions: List[NewPartitions], options: Optional[AdminOptions] = None
    ) -> None:
        """Grow topics' partition counts (admin.rs:205 NewPartitions op)."""
        for p in partitions:
            await self._conn.call(
                ("create_partitions", p.topic_name, p.new_partition_count)
            )
