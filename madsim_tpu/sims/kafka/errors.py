"""Kafka error type (reference madsim-rdkafka/src/sim/error.rs)."""

from __future__ import annotations


class KafkaError(Exception):
    """A Kafka operation failed; `code` is an RDKafkaErrorCode-style name."""

    def __init__(self, message: str, code: str = "Unknown") -> None:
        super().__init__(f"{code}: {message}" if code != "Unknown" else message)
        self.message = message
        self.code = code

    def __reduce__(self):
        return (type(self), (self.message, self.code))


def unknown_topic(name: str) -> KafkaError:
    return KafkaError(f"unknown topic: {name}", "UnknownTopic")


def unknown_partition(topic: str, partition: int) -> KafkaError:
    return KafkaError(f"unknown partition: {topic}/{partition}", "UnknownPartition")


def no_offset() -> KafkaError:
    return KafkaError("no offset stored", "NoOffset")


def invalid_timestamp() -> KafkaError:
    return KafkaError("invalid timestamp", "InvalidTimestamp")


def invalid_transaction_state(msg: str) -> KafkaError:
    return KafkaError(msg, "InvalidTransactionalState")


def queue_full() -> KafkaError:
    return KafkaError("producer queue full", "QueueFull")


def invalid_partitions(topic: str, count: int) -> KafkaError:
    return KafkaError(
        f"cannot shrink {topic} to {count} partitions", "InvalidPartitions"
    )
