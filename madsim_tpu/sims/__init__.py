"""Ecosystem facades: in-sim servers speaking familiar APIs.

Analogs of the reference's `#[cfg(madsim)]`-switched crates (SURVEY.md §2.2):
grpc (madsim-tonic), etcd (madsim-etcd-client), kafka (madsim-rdkafka),
s3 (madsim-aws-sdk-s3). All ride on `madsim_tpu.net.Endpoint`.
"""
