"""Filesystem simulation (reference madsim/src/sim/fs.rs:24-296).

Each node owns an in-memory map of path -> inode. Files support positional
reads/writes (`read_at` / `write_all_at`), truncation, metadata, and fsync.
State survives node restarts (it models a disk, not memory); `power_fail`
models crash-induced loss of unsynced data by restoring every file to its
content as of the last `sync_all` (a snapshot, so unsynced in-place
overwrites of synced ranges are lost too, not just appended bytes).

The reference leaves `power_fail` as a TODO stub (fs.rs:51-53); here it is
implemented, snapshotting synced content per inode.
"""

from __future__ import annotations

from typing import Dict, Optional

from .core import context
from .core.plugin import Simulator
from .core.task import NodeId


class _INode:
    __slots__ = ("data", "synced", "ever_synced")

    def __init__(self) -> None:
        self.data = bytearray()
        self.synced = b""  # snapshot of content as of the last sync_all
        # whether ANY sync has happened: a file created but never synced
        # has no durable directory entry, so a power failure loses the
        # whole inode — not just its bytes (matching a real filesystem,
        # where the create itself needs a directory fsync to survive)
        self.ever_synced = False


class FsSim(Simulator):
    """Per-node in-memory filesystem."""

    def __init__(self, rng, time, config) -> None:
        super().__init__(rng, time, config)
        self._fs: Dict[NodeId, Dict[str, _INode]] = {}

    def create_node(self, node_id: NodeId) -> None:
        self._fs.setdefault(node_id, {})

    def reset_node(self, node_id: NodeId) -> None:
        # a kill/restart does NOT wipe the disk; it only loses unsynced data
        self.power_fail(node_id)

    # -- chaos / inspection API --

    def power_fail(self, node_id: NodeId) -> None:
        """Lose ALL unsynced data on the node's disk.

        Restores each file to its exact content at the last `sync_all` —
        unsynced in-place overwrites of previously-synced byte ranges are
        rolled back too, not just appended length. Files created since the
        last sync are REMOVED entirely: their directory entry was never
        made durable, so the path must not survive as a present-but-empty
        file (that lie is exactly the bug class power_fail exists to
        expose — recovery code stat()ing a file that a real power loss
        would have erased).
        """
        node_fs = self._fs.get(node_id, {})
        for path in [p for p, ino in node_fs.items() if not ino.ever_synced]:
            del node_fs[path]
        for inode in node_fs.values():
            inode.data[:] = inode.synced

    def wipe_node(self, node_id: NodeId) -> None:
        """Blank the node's disk entirely — the membership-JOIN rule.

        `power_fail` models a crash: synced inodes survive, never-synced
        ones vanish. A node re-entering the cluster after a `reconfig`
        removal is a DIFFERENT machine (a fresh replica receiving state
        transfer), so nothing survives — not even synced inodes. Before
        this existed, a create→remove→rejoin sequence would stat() the
        pre-removal file on the "new" replica: the joining node's rebuild
        resurrected pre-wipe inodes, the exact lie `power_fail`'s
        never-synced rule exists to prevent, extended here to joins
        (NemesisDriver applies it before the join's restart)."""
        self._fs[node_id] = {}

    def get_file_size(self, node_id: NodeId, path: str) -> Optional[int]:
        inode = self._fs.get(node_id, {}).get(str(path))
        return len(inode.data) if inode is not None else None

    def _node_fs(self, node_id: NodeId) -> Dict[str, _INode]:
        return self._fs.setdefault(node_id, {})


def _sim() -> FsSim:
    from .core.plugin import simulator

    return simulator(FsSim)


def _here() -> NodeId:
    return context.current_task().node.id


class Metadata:
    __slots__ = ("_len",)

    def __init__(self, length: int) -> None:
        self._len = length

    def len(self) -> int:
        return self._len

    def is_file(self) -> bool:
        return True


class File:
    """Positional-IO file handle (reference fs.rs:148-229)."""

    def __init__(self, sim: FsSim, node_id: NodeId, path: str, inode: _INode) -> None:
        self._sim = sim
        self._node_id = node_id
        self._path = path
        self._inode = inode

    @staticmethod
    async def open(path: str) -> "File":
        sim, node_id = _sim(), _here()
        inode = sim._node_fs(node_id).get(str(path))
        if inode is None:
            raise FileNotFoundError(f"file not found: {path}")
        return File(sim, node_id, str(path), inode)

    @staticmethod
    async def create(path: str) -> "File":
        sim, node_id = _sim(), _here()
        inode = _INode()
        sim._node_fs(node_id)[str(path)] = inode
        return File(sim, node_id, str(path), inode)

    async def read_at(self, buf_len: int, offset: int) -> bytes:
        if offset < 0 or buf_len < 0:
            raise ValueError("negative offset or length")
        data = self._inode.data
        return bytes(data[offset : offset + buf_len])

    async def read_exact_at(self, buf_len: int, offset: int) -> bytes:
        data = await self.read_at(buf_len, offset)
        if len(data) < buf_len:
            raise EOFError("failed to fill whole buffer")
        return data

    async def read_to_end(self) -> bytes:
        return bytes(self._inode.data)

    async def write_all_at(self, buf: bytes, offset: int) -> None:
        if offset < 0:
            raise ValueError("negative offset")
        data = self._inode.data
        if offset > len(data):
            data.extend(b"\x00" * (offset - len(data)))
        data[offset : offset + len(buf)] = buf

    async def set_len(self, size: int) -> None:
        data = self._inode.data
        if size <= len(data):
            del data[size:]
        else:
            data.extend(b"\x00" * (size - len(data)))

    async def sync_all(self) -> None:
        self._inode.synced = bytes(self._inode.data)
        self._inode.ever_synced = True

    async def metadata(self) -> Metadata:
        return Metadata(len(self._inode.data))


async def read(path: str) -> bytes:
    f = await File.open(path)
    return await f.read_to_end()


async def write(path: str, data: bytes) -> None:
    f = await File.create(path)
    await f.write_all_at(bytes(data), 0)


async def remove_file(path: str) -> None:
    sim, node_id = _sim(), _here()
    if sim._node_fs(node_id).pop(str(path), None) is None:
        raise FileNotFoundError(f"file not found: {path}")


async def metadata(path: str) -> Metadata:
    f = await File.open(path)
    return await f.metadata()
