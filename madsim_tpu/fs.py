"""Filesystem simulation (reference madsim/src/sim/fs.rs:24-296).

Each node owns an in-memory map of path -> inode. Files support positional
reads/writes (`read_at` / `write_all_at`), truncation, metadata, and fsync.
State survives node restarts (it models a disk, not memory); `power_fail`
models crash-induced loss of unsynced data by restoring every file to its
content as of the last `sync_all` (a snapshot, so unsynced in-place
overwrites of synced ranges are lost too, not just appended bytes).

The reference leaves `power_fail` as a TODO stub (fs.rs:51-53); here it is
implemented, snapshotting synced content per inode.
"""

from __future__ import annotations

import errno
from typing import Callable, Dict, Optional

from .core import context
from .core.plugin import Simulator
from .core.task import NodeId


class _INode:
    __slots__ = ("data", "synced", "ever_synced")

    def __init__(self) -> None:
        self.data = bytearray()
        self.synced = b""  # snapshot of content as of the last sync_all
        # whether ANY sync has happened: a file created but never synced
        # has no durable directory entry, so a power failure loses the
        # whole inode — not just its bytes (matching a real filesystem,
        # where the create itself needs a directory fsync to survive)
        self.ever_synced = False


class FsSim(Simulator):
    """Per-node in-memory filesystem."""

    def __init__(self, rng, time, config) -> None:
        super().__init__(rng, time, config)
        self.time = time
        self._fs: Dict[NodeId, Dict[str, _INode]] = {}
        # DiskFault degraded windows (nemesis disk_slow..disk_crash): a
        # faulted node's writes each pay extra_ns of virtual latency and
        # its fsync raises EIO — the dying-disk regime where an app that
        # acks before fsync quietly stops being durable
        self._fault_ns: Dict[NodeId, int] = {}
        # last path with an unsynced APPEND tail per node: the torn-write
        # target (a torn power failure keeps a prefix of the LAST
        # unsynced write, not of every dirty file)
        self._last_write: Dict[NodeId, str] = {}

    def create_node(self, node_id: NodeId) -> None:
        self._fs.setdefault(node_id, {})

    def reset_node(self, node_id: NodeId) -> None:
        # a kill/restart does NOT wipe the disk; it only loses unsynced data
        self.power_fail(node_id)

    # -- chaos / inspection API --

    def power_fail(
        self,
        node_id: NodeId,
        torn_extent: Optional[Callable[[int], int]] = None,
    ) -> None:
        """Lose ALL unsynced data on the node's disk.

        Restores each file to its exact content at the last `sync_all` —
        unsynced in-place overwrites of previously-synced byte ranges are
        rolled back too, not just appended length. Files created since the
        last sync are REMOVED entirely: their directory entry was never
        made durable, so the path must not survive as a present-but-empty
        file (that lie is exactly the bug class power_fail exists to
        expose — recovery code stat()ing a file that a real power loss
        would have erased).

        `torn_extent` (the nemesis DiskFault torn-crash path) is a
        callable drawing how many bytes of the LAST unsynced append
        survive on top of the synced snapshot (`ScheduleCoins.
        disk_torn_extent` — seed-pure, oracle-verified): a torn write is
        a partially-persisted tail, never a resurrected synced-past.
        It is consulted only when that last-written file both survives
        the failure (ever synced) and actually has an unsynced append
        tail — a torn coin with nothing torn to keep is a no-op.
        """
        node_fs = self._fs.get(node_id, {})
        torn_path = self._last_write.pop(node_id, None)
        for path in [p for p, ino in node_fs.items() if not ino.ever_synced]:
            del node_fs[path]
        for path, inode in node_fs.items():
            keep = b""
            if (
                torn_extent is not None
                and path == torn_path
                and len(inode.data) > len(inode.synced)
            ):
                tail = bytes(inode.data[len(inode.synced):])
                keep = tail[: torn_extent(len(tail))]
            inode.data[:] = inode.synced + keep

    def power_fail_node(
        self,
        node_id: NodeId,
        torn_extent: Optional[Callable[[int], int]] = None,
    ) -> None:
        """NemesisDriver-facing alias of `power_fail` (disk_crash apply)."""
        self.power_fail(node_id, torn_extent=torn_extent)

    def set_disk_fault(self, node_id: NodeId, extra_ns: int) -> None:
        """Open a degraded-disk window (nemesis `disk_slow`): every write
        on the node pays `extra_ns` additional virtual latency and fsync
        raises EIO until `clear_disk_fault`."""
        self._fault_ns[node_id] = int(extra_ns)

    def clear_disk_fault(self, node_id: NodeId) -> None:
        """Close the node's degraded-disk window (at `disk_crash`)."""
        self._fault_ns.pop(node_id, None)

    def disk_fault_extra_ns(self, node_id: NodeId) -> int:
        """The node's per-write fault latency in ns (0 = healthy)."""
        return self._fault_ns.get(node_id, 0)

    def wipe_node(self, node_id: NodeId) -> None:
        """Blank the node's disk entirely — the membership-JOIN rule.

        `power_fail` models a crash: synced inodes survive, never-synced
        ones vanish. A node re-entering the cluster after a `reconfig`
        removal is a DIFFERENT machine (a fresh replica receiving state
        transfer), so nothing survives — not even synced inodes. Before
        this existed, a create→remove→rejoin sequence would stat() the
        pre-removal file on the "new" replica: the joining node's rebuild
        resurrected pre-wipe inodes, the exact lie `power_fail`'s
        never-synced rule exists to prevent, extended here to joins
        (NemesisDriver applies it before the join's restart)."""
        self._fs[node_id] = {}
        self._last_write.pop(node_id, None)
        self._fault_ns.pop(node_id, None)

    def get_file_size(self, node_id: NodeId, path: str) -> Optional[int]:
        inode = self._fs.get(node_id, {}).get(str(path))
        return len(inode.data) if inode is not None else None

    def _node_fs(self, node_id: NodeId) -> Dict[str, _INode]:
        return self._fs.setdefault(node_id, {})


def _sim() -> FsSim:
    from .core.plugin import simulator

    return simulator(FsSim)


def _here() -> NodeId:
    return context.current_task().node.id


class Metadata:
    __slots__ = ("_len",)

    def __init__(self, length: int) -> None:
        self._len = length

    def len(self) -> int:
        return self._len

    def is_file(self) -> bool:
        return True


class File:
    """Positional-IO file handle (reference fs.rs:148-229)."""

    def __init__(self, sim: FsSim, node_id: NodeId, path: str, inode: _INode) -> None:
        self._sim = sim
        self._node_id = node_id
        self._path = path
        self._inode = inode

    @staticmethod
    async def open(path: str) -> "File":
        sim, node_id = _sim(), _here()
        inode = sim._node_fs(node_id).get(str(path))
        if inode is None:
            raise FileNotFoundError(f"file not found: {path}")
        return File(sim, node_id, str(path), inode)

    @staticmethod
    async def create(path: str) -> "File":
        sim, node_id = _sim(), _here()
        node_fs = sim._node_fs(node_id)
        inode = node_fs.get(str(path))
        if inode is None:
            inode = _INode()
            node_fs[str(path)] = inode
        else:
            # O_CREAT|O_TRUNC over an EXISTING path truncates the
            # content (an unsynced change like any write), but must not
            # discard the inode's durable history: replacing the inode
            # here used to reset `synced`/`ever_synced`, so a power
            # failure after re-create LOST a path whose directory entry
            # was already durable — recovery saw nothing where a real
            # disk still holds the last-synced content
            del inode.data[:]
        return File(sim, node_id, str(path), inode)

    async def read_at(self, buf_len: int, offset: int) -> bytes:
        if offset < 0 or buf_len < 0:
            raise ValueError("negative offset or length")
        data = self._inode.data
        return bytes(data[offset : offset + buf_len])

    async def read_exact_at(self, buf_len: int, offset: int) -> bytes:
        data = await self.read_at(buf_len, offset)
        if len(data) < buf_len:
            raise EOFError("failed to fill whole buffer")
        return data

    async def read_to_end(self) -> bytes:
        return bytes(self._inode.data)

    async def _pay_fault_latency(self) -> None:
        # DiskFault degraded window: each write on a faulted node pays
        # the clause's extra_us of virtual latency (set_disk_fault)
        extra = self._sim.disk_fault_extra_ns(self._node_id)
        if extra > 0:
            from .core.vtime import Sleep

            time = self._sim.time
            await Sleep(time.now_ns() + extra, time)

    async def write_all_at(self, buf: bytes, offset: int) -> None:
        if offset < 0:
            raise ValueError("negative offset")
        await self._pay_fault_latency()
        data = self._inode.data
        if offset > len(data):
            data.extend(b"\x00" * (offset - len(data)))
        data[offset : offset + len(buf)] = buf
        self._sim._last_write[self._node_id] = self._path

    async def set_len(self, size: int) -> None:
        await self._pay_fault_latency()
        data = self._inode.data
        if size <= len(data):
            del data[size:]
        else:
            data.extend(b"\x00" * (size - len(data)))
        self._sim._last_write[self._node_id] = self._path

    async def sync_all(self) -> None:
        if self._sim.disk_fault_extra_ns(self._node_id) > 0:
            # the dying disk refuses durability: an app that treats this
            # EIO as success (or never looks) is the ack-before-fsync
            # bug class the DiskFault clause exists to surface
            raise OSError(errno.EIO, "fsync failed: injected disk fault")
        self._inode.synced = bytes(self._inode.data)
        self._inode.ever_synced = True

    async def metadata(self) -> Metadata:
        return Metadata(len(self._inode.data))


async def read(path: str) -> bytes:
    f = await File.open(path)
    return await f.read_to_end()


async def write(path: str, data: bytes) -> None:
    f = await File.create(path)
    await f.write_all_at(bytes(data), 0)


async def remove_file(path: str) -> None:
    sim, node_id = _sim(), _here()
    if sim._node_fs(node_id).pop(str(path), None) is None:
        raise FileNotFoundError(f"file not found: {path}")


async def metadata(path: str) -> Metadata:
    f = await File.open(path)
    return await f.metadata()
