"""Endpoint: tag-matched message passing — the substrate every shim rides on.

Analog of reference madsim/src/sim/net/endpoint.rs:13-583. An `Endpoint`
binds an address and exchanges *tagged* messages: `send_to(dst, tag, bytes)` /
`recv_from(tag)` with mailbox tag-matching (endpoint.rs:329-361), raw payload
variants carrying arbitrary Python objects (the `Box<dyn Any>` analog used by
all ecosystem sims), and reliable ordered connections `connect1`/`accept1`.

Since Python has no RAII, `BindGuard` exposes explicit `close()` (also called
from node reset); endpoints are context managers.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..core import context
from ..core.futures import Future
from ..core.plugin import simulator
from ..core.sync import Channel
from .addr import SocketAddr, ToSocketAddrs, lookup_host
from .netsim import NetSim, Payload, PayloadReceiver, PayloadSender

UDP = "udp"


class _Message:
    __slots__ = ("tag", "data", "from_addr")

    def __init__(self, tag: int, data: Payload, from_addr: SocketAddr) -> None:
        self.tag = tag
        self.data = data
        self.from_addr = from_addr


# Max dead one-shot tags remembered per mailbox; beyond this the oldest are
# evicted (their late responses, if any, park as ordinary messages).
_DEAD_TAG_CAP = 4096


class Mailbox:
    """Tag-matching mailbox (reference endpoint.rs:329-361).

    `forget(tag)` prunes state for one-shot tags nobody will ever read again
    (e.g. the unique response tag of a timed-out rpc call): parked messages
    and registrations are dropped, and a late-arriving message for the tag is
    discarded on delivery instead of parking forever.
    """

    def __init__(self) -> None:
        self.registered: List[Tuple[int, Future[_Message]]] = []
        self.msgs: List[_Message] = []
        self.dead_tags: Dict[int, None] = {}  # insertion-ordered set

    def deliver(self, msg: _Message) -> None:
        for i, (tag, fut) in enumerate(self.registered):
            if tag == msg.tag and fut.try_set_result(msg):
                self.registered.pop(i)
                return
        self.registered = [
            (t, f) for t, f in self.registered if not (f.done() or f.abandoned())
        ]
        if msg.tag in self.dead_tags:
            # a one-shot tag is sent to at most once: drop and forget
            del self.dead_tags[msg.tag]
            return
        self.msgs.append(msg)

    def recv(self, tag: int) -> Future[_Message]:
        fut: Future[_Message] = Future()
        for i, msg in enumerate(self.msgs):
            if msg.tag == tag:
                self.msgs.pop(i)
                fut.set_result(msg)
                return fut
        self.registered.append((tag, fut))
        return fut

    def forget(self, tag: int) -> None:
        self.msgs = [m for m in self.msgs if m.tag != tag]
        self.registered = [(t, f) for t, f in self.registered if t != tag]
        self.dead_tags[tag] = None
        while len(self.dead_tags) > _DEAD_TAG_CAP:
            del self.dead_tags[next(iter(self.dead_tags))]


class EndpointSocket:
    """The `Socket` bound into the network for an Endpoint."""

    def __init__(self) -> None:
        self.mailbox = Mailbox()
        self.conn_chan: Channel = Channel()  # (tx, rx, from_addr)

    def deliver(self, src: SocketAddr, dst: SocketAddr, msg: Payload) -> None:
        tag, data = msg
        self.mailbox.deliver(_Message(tag, data, src))

    def new_connection(
        self, src: SocketAddr, dst: SocketAddr, tx: PayloadSender, rx: PayloadReceiver
    ) -> None:
        try:
            self.conn_chan.send_nowait((tx, rx, src))
        except Exception:
            pass  # endpoint closed: refuse silently (peer sees EOF)


class BindGuard:
    """Holds a bound (node, addr, protocol) registration; explicit close
    (reference net/mod.rs:436-494 uses Drop)."""

    def __init__(self, net: NetSim, node_id: int, addr: SocketAddr, protocol: str) -> None:
        self.net = net
        self.node_id = node_id
        self.addr = addr
        self.protocol = protocol
        self._closed = False

    @staticmethod
    async def bind(
        addr: ToSocketAddrs, protocol: str, socket: Any
    ) -> "BindGuard":
        net = simulator(NetSim)
        node_id = context.current_task().node.id
        resolved = await lookup_host(addr)
        bound = net.network.bind(node_id, resolved, protocol, socket)
        return BindGuard(net, node_id, bound, protocol)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self.net.network.close(self.node_id, self.addr, self.protocol)


class Endpoint:
    """Tag-matched datagrams + reliable connections on a bound address."""

    def __init__(self, guard: BindGuard, socket: EndpointSocket) -> None:
        self._guard = guard
        self._socket = socket
        self._peer: Optional[SocketAddr] = None

    # -- constructors --

    @staticmethod
    async def bind(addr: ToSocketAddrs) -> "Endpoint":
        if context.try_current_handle() is None:
            # production mode: same API over real TCP (std/net/tcp.rs analog)
            from ..real.net import RealEndpoint

            return await RealEndpoint.bind(addr)  # type: ignore[return-value]
        socket = EndpointSocket()
        guard = await BindGuard.bind(addr, UDP, socket)
        return Endpoint(guard, socket)

    @staticmethod
    async def connect(addr: ToSocketAddrs) -> "Endpoint":
        if context.try_current_handle() is None:
            from ..real.net import RealEndpoint

            return await RealEndpoint.connect(addr)  # type: ignore[return-value]
        peer = await lookup_host(addr)
        ep = await Endpoint.bind(("0.0.0.0", 0))
        ep._peer = peer
        return ep

    # -- properties --

    def local_addr(self) -> SocketAddr:
        return self._guard.addr

    def peer_addr(self) -> SocketAddr:
        if self._peer is None:
            raise OSError("not connected")
        return self._peer

    @property
    def net(self) -> NetSim:
        return self._guard.net

    @property
    def node_id(self) -> int:
        return self._guard.node_id

    def close(self) -> None:
        self._guard.close()
        self._socket.conn_chan.close()

    def __enter__(self) -> "Endpoint":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()

    # -- tagged datagrams --

    async def send_to(self, dst: ToSocketAddrs, tag: int, buf: bytes) -> None:
        resolved = await lookup_host(dst)
        await self.send_to_raw(resolved, tag, bytes(buf))

    async def recv_from(self, tag: int) -> Tuple[bytes, SocketAddr]:
        data, from_addr = await self.recv_from_raw(tag)
        if not isinstance(data, (bytes, bytearray)):
            raise TypeError("message is not data")
        return bytes(data), from_addr

    async def send(self, tag: int, buf: bytes) -> None:
        await self.send_to(self.peer_addr(), tag, buf)

    async def recv(self, tag: int) -> bytes:
        peer = self.peer_addr()
        data, from_addr = await self.recv_from(tag)
        if from_addr != peer:
            raise OSError(
                f"received a message from {from_addr}, not from the connected "
                f"address {peer}"
            )
        return data

    # -- raw payloads (used by ecosystem sims) --

    async def send_to_raw(self, dst: SocketAddr, tag: int, data: Payload) -> None:
        await self.net.send(
            self.node_id, self.local_addr()[1], dst, UDP, (tag, data)
        )

    async def recv_from_raw(self, tag: int) -> Tuple[Payload, SocketAddr]:
        msg = await self._socket.mailbox.recv(tag)
        await self.net.rand_delay()
        return msg.data, msg.from_addr

    def forget_tag(self, tag: int) -> None:
        """Drop all mailbox state for a one-shot tag nobody will read again."""
        self._socket.mailbox.forget(tag)

    # -- reliable connections --

    async def connect1(
        self, dst: ToSocketAddrs
    ) -> Tuple[PayloadSender, PayloadReceiver, SocketAddr]:
        resolved = await lookup_host(dst)
        return await self.net.connect1(
            self.node_id, self.local_addr()[1], resolved, UDP
        )

    async def accept1(self) -> Tuple[PayloadSender, PayloadReceiver, SocketAddr]:
        return await self.conn_chan_recv()

    async def conn_chan_recv(self) -> Tuple[PayloadSender, PayloadReceiver, SocketAddr]:
        return await self._socket.conn_chan.recv()
