"""UDP socket sim — thin adapter over Endpoint tag 0 (reference net/udp.rs:9-73)."""

from __future__ import annotations

from typing import Tuple

from .addr import SocketAddr, ToSocketAddrs
from .endpoint import Endpoint

_TAG = 0


class UdpSocket:
    def __init__(self, ep: Endpoint) -> None:
        self._ep = ep

    @staticmethod
    async def bind(addr: ToSocketAddrs) -> "UdpSocket":
        return UdpSocket(await Endpoint.bind(addr))

    async def connect(self, addr: ToSocketAddrs) -> None:
        from .addr import lookup_host

        self._ep._peer = await lookup_host(addr)

    def local_addr(self) -> SocketAddr:
        return self._ep.local_addr()

    def peer_addr(self) -> SocketAddr:
        return self._ep.peer_addr()

    async def send_to(self, buf: bytes, dst: ToSocketAddrs) -> int:
        await self._ep.send_to(dst, _TAG, buf)
        return len(buf)

    async def recv_from(self) -> Tuple[bytes, SocketAddr]:
        return await self._ep.recv_from(_TAG)

    async def send(self, buf: bytes) -> int:
        await self._ep.send(_TAG, buf)
        return len(buf)

    async def recv(self) -> bytes:
        return await self._ep.recv(_TAG)

    def close(self) -> None:
        self._ep.close()
