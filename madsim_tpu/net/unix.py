"""Unix domain socket simulation: path-addressed streams + datagrams.

The reference only STUBS these (madsim/src/sim/net/unix/{mod,stream,
datagram}.rs are `#![doc(hidden)]` bodies of `todo!()`); this is a working
implementation of the API they promise (tokio's `UnixListener`/`UnixStream`/
`UnixDatagram`), modeled faithfully: a unix socket path is HOST-LOCAL, so
the namespace is per simulated node — a path bound on one node is invisible
to every other node, and traffic between tasks of one node is loopback
(reliable, no loss/latency roll — the kernel, not the network).

Kill/restart semantics: a node's paths are released when the node resets
(the fs is in-memory; a dead process's sockets vanish with it), mirroring
how NetSim closes the node's sockets (network.rs:142-147).

    listener = await UnixListener.bind("/tmp/app.sock")
    stream, peer = await listener.accept()
    ...
    client = await UnixStream.connect("/tmp/app.sock")
    await client.write_all(b"hi")
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from ..core import context
from ..core.sync import Channel, ChannelClosed
from .tcp import TcpStream

_REGISTRY_ATTR = "_unix_path_registry"


class _Pipe:
    """One direction of a loopback connection (PayloadSender/Receiver duck)."""

    def __init__(self, chan: Channel) -> None:
        self._chan = chan

    def send(self, payload: object) -> None:
        try:
            self._chan.send_nowait(payload)
        except (RuntimeError, ChannelClosed):
            raise ChannelClosed("peer closed") from None

    async def recv(self) -> object:
        return await self._chan.recv()

    def close(self) -> None:
        self._chan.close()

    def is_closed(self) -> bool:
        return self._chan.closed


def _registry() -> Dict[Tuple[int, str], object]:
    """Per-runtime (node_id, path) -> bound socket registry, reset-aware."""
    handle = context.current_handle()
    reg = getattr(handle, _REGISTRY_ATTR, None)
    if reg is None:
        reg = {}
        setattr(handle, _REGISTRY_ATTR, reg)

        def on_reset(node_id: int) -> None:
            for key in [k for k in reg if k[0] == int(node_id)]:
                sock = reg.pop(key)
                close = getattr(sock, "_release", None)
                if close is not None:
                    close()

        handle.executor.on_node_reset.append(on_reset)
    return reg


def _here() -> int:
    return int(context.current_task().node.id)


def _bind(path: str, sock: object) -> Tuple[int, str]:
    reg = _registry()
    key = (_here(), str(path))
    if key in reg:
        raise OSError(f"address already in use: {path}")
    reg[key] = sock
    return key


def _unbind(key: Tuple[int, str]) -> None:
    handle = context.try_current_handle()
    if handle is None:
        return
    reg = getattr(handle, _REGISTRY_ATTR, None)
    if reg is not None:
        reg.pop(key, None)


def _lookup(path: str) -> object:
    reg = _registry()
    sock = reg.get((_here(), str(path)))
    if sock is None:
        raise ConnectionRefusedError(f"connection refused: {path}")
    return sock


class UnixStream(TcpStream):
    """Byte stream over a node-local path (stream.rs:36-64's promise).

    Inherits the flush-based write buffer / EOF read semantics of the TCP
    sim; the transport is a loopback channel pair instead of NetSim.
    """

    @staticmethod
    async def connect(path: str) -> "UnixStream":  # type: ignore[override]
        listener = _lookup(path)
        if not isinstance(listener, _UnixListenerSocket):
            raise ConnectionRefusedError(f"not a stream socket: {path}")
        a2b: Channel = Channel()
        b2a: Channel = Channel()
        stream = UnixStream(_Pipe(a2b), _Pipe(b2a), "", str(path))
        try:
            listener.conn_chan.send_nowait(
                (UnixStream(_Pipe(b2a), _Pipe(a2b), str(path), ""), "")
            )
        except (RuntimeError, ChannelClosed):
            raise ConnectionRefusedError(f"connection refused: {path}") from None
        return stream

    @staticmethod
    def pair() -> Tuple["UnixStream", "UnixStream"]:
        """Connected anonymous pair (socketpair(2) / tokio's pair())."""
        a2b: Channel = Channel()
        b2a: Channel = Channel()
        return (
            UnixStream(_Pipe(a2b), _Pipe(b2a), "", ""),
            UnixStream(_Pipe(b2a), _Pipe(a2b), "", ""),
        )


class _UnixListenerSocket:
    def __init__(self) -> None:
        self.conn_chan: Channel = Channel()

    def _release(self) -> None:
        self.conn_chan.close()


class UnixListener:
    def __init__(self, key: Tuple[int, str], socket: _UnixListenerSocket) -> None:
        self._key = key
        self._socket = socket

    @staticmethod
    async def bind(path: str) -> "UnixListener":
        socket = _UnixListenerSocket()
        return UnixListener(_bind(path, socket), socket)

    def local_addr(self) -> str:
        return self._key[1]

    async def accept(self) -> Tuple[UnixStream, str]:
        try:
            stream, peer = await self._socket.conn_chan.recv()
        except ChannelClosed:
            raise OSError("listener closed") from None
        return stream, peer

    def close(self) -> None:
        _unbind(self._key)
        self._socket.conn_chan.close()

    def __enter__(self) -> "UnixListener":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


class UnixDatagram:
    """Connectionless node-local datagrams (datagram.rs:6-30's promise)."""

    def __init__(self, key: Optional[Tuple[int, str]]) -> None:
        self._key = key
        self._chan: Channel = Channel()
        self._peer: Optional[str] = None

    def _release(self) -> None:
        self._chan.close()

    @staticmethod
    async def bind(path: str) -> "UnixDatagram":
        dg = UnixDatagram(None)
        dg._key = _bind(path, dg)
        return dg

    @staticmethod
    async def unbound() -> "UnixDatagram":
        return UnixDatagram(None)

    def local_addr(self) -> Optional[str]:
        return self._key[1] if self._key else None

    def connect(self, path: str) -> None:
        _lookup(path)  # fail fast like the kernel
        self._peer = str(path)

    async def send_to(self, buf: bytes, path: str) -> int:
        target = _lookup(path)
        if not isinstance(target, UnixDatagram):
            raise ConnectionRefusedError(f"not a datagram socket: {path}")
        src = self._key[1] if self._key else ""
        try:
            target._chan.send_nowait((bytes(buf), src))
        except (RuntimeError, ChannelClosed):
            raise ConnectionRefusedError(f"connection refused: {path}") from None
        return len(buf)

    async def send(self, buf: bytes) -> int:
        if self._peer is None:
            raise OSError("datagram socket not connected")
        return await self.send_to(buf, self._peer)

    async def recv_from(self) -> Tuple[bytes, str]:
        try:
            return await self._chan.recv()
        except ChannelClosed:
            raise OSError("datagram socket closed") from None

    async def recv(self) -> bytes:
        return (await self.recv_from())[0]

    def close(self) -> None:
        if self._key is not None:
            _unbind(self._key)
        self._chan.close()
