"""NetSim: the network simulator plugin + chaos API.

Analog of reference madsim/src/sim/net/mod.rs:84-494. Owns the `Network`
graph, DNS records, IPVS table, and RPC drop-hooks. Every message ride is:

    rand_delay (0-5 us, buggify 10% => 1-5 s)
    -> request hook (may drop)
    -> IPVS rewrite
    -> Network.try_send (clog? loss? latency roll)
    -> timer at now+latency fires response hook + socket.deliver

Connections (`connect1`) are paired reliable ordered channels whose receiver
re-tests the link per message with exponential backoff (1 ms doubling to 10 s)
while it is clogged, mirroring net/mod.rs:337-405.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core.buggify import buggify_with_prob
from ..core.config import Config
from ..core.plugin import Simulator
from ..core.rng import GlobalRng
from ..core.sync import Channel, ChannelClosed
from ..core.vtime import TimeHandle
from .addr import SocketAddr, format_addr
from .ipvs import Ipvs, ServiceAddr
from .network import Direction, Network, NodeId, Socket, Stat

Payload = Any
# a message hook returns False to drop the message (net/mod.rs:245-284)
Hook = Callable[[Payload], bool]


class PayloadSender:
    """Send half of a reliable ordered connection."""

    __slots__ = ("_test_link", "_chan")

    def __init__(self, test_link: Callable[[], Optional[int]], chan: Channel) -> None:
        self._test_link = test_link
        self._chan = chan

    def send(self, payload: Payload) -> None:
        """Queue a message; raises ChannelClosed if the peer is gone."""
        # roll the link at send time; None = link down at send (receiver
        # will retry with backoff)
        state = self._test_link()
        self._chan.send_nowait((payload, state))

    def is_closed(self) -> bool:
        return self._chan.closed

    def close(self) -> None:
        self._chan.close()


class PayloadReceiver:
    """Receive half of a reliable ordered connection."""

    __slots__ = ("_test_link", "_chan", "_time")

    def __init__(
        self,
        test_link: Callable[[], Optional[int]],
        chan: Channel,
        time: TimeHandle,
    ) -> None:
        self._test_link = test_link
        self._chan = chan
        self._time = time

    async def recv(self) -> Payload:
        """Next message; raises ChannelClosed on disconnect (EOF)."""
        from ..core.vtime import Sleep

        value, arrive_ns = await self._chan.recv()
        backoff_ns = 1_000_000  # 1 ms
        while arrive_ns is None:
            # link was down when sent: retry until it heals
            await Sleep(self._time.now_ns() + backoff_ns, self._time)
            backoff_ns = min(backoff_ns * 2, 10_000_000_000)
            arrive_ns = self._test_link()
        if arrive_ns > self._time.now_ns():
            await Sleep(arrive_ns, self._time)
        return value

    async def try_recv_eof(self) -> Optional[Payload]:
        """Like recv() but returns None on disconnect."""
        try:
            return await self.recv()
        except ChannelClosed:
            return None

    def close(self) -> None:
        self._chan.close()


# bound on retained lineage events/edges: a long soak must not grow host
# memory without bound; overflow is counted, never silent
MAX_LINEAGE_EDGES = 100_000


class HostLineage:
    """The host runtime's Lamport mirror of the device lineage plane
    (madsim_tpu/causal.py, docs/causality.md).

    The device engine attributes a send to its emitting handler EVENT;
    the host runtime has no handler-event notion, so a send is its own
    Lamport event (the classic process model): `on_send` ticks the
    node's clock and allocates the next runtime-global event id,
    `on_deliver` updates `max(local, send event id) + 1` — the SAME
    sender-value vocabulary as the engine's in-jit update (the message
    carries its send EVENT's id), so one law checker
    (`causal.check_host_lineage`) validates both faces. Clocks survive
    node resets (a Lamport clock is observer metadata, not node state —
    the device's `lin.lam` likewise survives crash-with-wipe).

    OPT-IN, like the device plane (`BatchedSim(lineage=True)` costs zero
    when off): call `enable()` BEFORE traffic starts — e.g.
    `Handle.current().metrics().lineage().enable()` at the top of the
    root task. Disabled (the default), the delivery path pays two
    truthiness checks and retains nothing."""

    def __init__(self) -> None:
        self.enabled = False
        self.lam: Dict[NodeId, int] = {}
        self.next_eid = 0
        # (eid, node, lam-after, kind) rows, eid order; bounded
        self.events: List[tuple] = []
        self.edges: List[tuple] = []  # (send_eid, deliver_eid)
        self.dropped = 0

    def enable(self) -> "HostLineage":
        self.enabled = True
        return self

    def on_send(self, node: NodeId) -> int:
        if not self.enabled:
            return -1
        lam = self.lam.get(node, 0) + 1
        self.lam[node] = lam
        eid = self.next_eid
        self.next_eid += 1
        self._record(eid, node, lam, "send")
        return eid

    def on_deliver(self, node: NodeId, send_eid: int) -> int:
        if not self.enabled or send_eid < 0:
            # send_eid < 0: the message was stamped before enable() —
            # skip rather than record a half-history edge
            return -1
        lam = max(self.lam.get(node, 0), send_eid) + 1
        self.lam[node] = lam
        eid = self.next_eid
        self.next_eid += 1
        if len(self.edges) < MAX_LINEAGE_EDGES:
            self.edges.append((send_eid, eid))
        else:
            self.dropped += 1
        self._record(eid, node, lam, "deliver")
        return eid

    def _record(self, eid: int, node: NodeId, lam: int, kind: str) -> None:
        if len(self.events) < 2 * MAX_LINEAGE_EDGES:
            self.events.append((eid, node, lam, kind))
        else:
            self.dropped += 1


class NetSim(Simulator):
    """Network simulator + chaos API (net/mod.rs:126-284)."""

    def __init__(self, rng: GlobalRng, time: TimeHandle, config: Config) -> None:
        super().__init__(rng, time, config)
        self.rng = rng
        self.time = time
        self.network = Network(rng, config.net)
        self.ipvs = Ipvs()
        self._dns: Dict[str, str] = {}
        self._hooks_req: Dict[NodeId, Hook] = {}
        self._hooks_rsp: Dict[NodeId, Hook] = {}
        # channels owned by each node, closed on reset (the analog of task
        # drop closing connection halves on kill)
        self._node_channels: Dict[NodeId, List[Channel]] = {}
        # Lamport mirror over the datagram delivery path (docs/causality.md)
        self.lineage = HostLineage()

    @staticmethod
    def current() -> "NetSim":
        """The current simulation's NetSim (reference `NetSim::current()`)."""
        from ..core.plugin import simulator

        return simulator(NetSim)

    # -- plugin lifecycle --

    def create_node(self, node_id: NodeId) -> None:
        self.network.insert_node(node_id)
        if self.network.get_ip(node_id) is None:
            # auto-assign a unique IP so nodes are reachable without explicit
            # `.ip()` calls (the reference requires explicit IPs; auto-assign
            # from 192.168.0.0/16 is a usability extension — `.ip()` overrides)
            n = node_id
            while True:
                candidate = f"192.168.{(n // 256) % 256}.{n % 256}"
                if candidate not in self.network.addr_to_node:
                    break
                n += 1
            self.network.set_ip(node_id, candidate)

    def reset_node(self, node_id: NodeId) -> None:
        self.network.reset_node(node_id)
        for chan in self._node_channels.pop(node_id, []):
            chan.close()

    # -- chaos API --

    def update_config(self, config) -> None:
        self.network.update_config(config)

    def stat(self) -> Stat:
        return self.network.stat

    def clog_node(self, id: NodeId, direction: str = Direction.BOTH) -> None:
        self.network.clog_node(id, direction)

    def unclog_node(self, id: NodeId, direction: str = Direction.BOTH) -> None:
        self.network.unclog_node(id, direction)

    def clog_link(self, src: NodeId, dst: NodeId) -> None:
        self.network.clog_link(src, dst)

    def unclog_link(self, src: NodeId, dst: NodeId) -> None:
        self.network.unclog_link(src, dst)

    def partition(self, group_a: List[NodeId], group_b: List[NodeId]) -> None:
        """Clog every link between the two groups (both directions)."""
        for a in group_a:
            for b in group_b:
                self.network.clog_link(a, b)
                self.network.clog_link(b, a)

    def heal_partition(self, group_a: List[NodeId], group_b: List[NodeId]) -> None:
        for a in group_a:
            for b in group_b:
                self.network.unclog_link(a, b)
                self.network.unclog_link(b, a)

    def set_ip(self, node_id: NodeId, ip: str) -> None:
        self.network.insert_node(node_id)
        self.network.set_ip(node_id, ip)

    def get_ip(self, node_id: NodeId) -> Optional[str]:
        return self.network.get_ip(node_id)

    # -- DNS (dns.rs:6-26) --

    def add_dns_record(self, name: str, ip: str) -> None:
        self._dns[name] = ip

    def dns_lookup(self, name: str) -> Optional[str]:
        return self._dns.get(name)

    # -- RPC hooks (net/mod.rs:245-284) --

    def hook_rpc_req(self, node: NodeId, hook: Optional[Hook]) -> None:
        """Install a hook on messages *sent by* node; return False to drop."""
        if hook is None:
            self._hooks_req.pop(node, None)
        else:
            self._hooks_req[node] = hook

    def hook_rpc_rsp(self, node: NodeId, hook: Optional[Hook]) -> None:
        """Install a hook on messages *delivered to* node; return False to drop."""
        if hook is None:
            self._hooks_rsp.pop(node, None)
        else:
            self._hooks_rsp[node] = hook

    # -- data path --

    async def rand_delay(self) -> None:
        """0-5 us random delay; 10% buggify => 1-5 s (net/mod.rs:287-295)."""
        from ..core.vtime import Sleep

        delay_ns = self.rng.randrange(0, 5_000)
        if buggify_with_prob(0.1):
            delay_ns = self.rng.randrange(1, 5) * 1_000_000_000
        if delay_ns:
            await Sleep(self.time.now_ns() + delay_ns, self.time)

    def _ipvs_rewrite(self, dst: SocketAddr, protocol: str) -> SocketAddr:
        addr: ServiceAddr = (dst[0], dst[1], protocol)
        server = self.ipvs.get_server(addr)
        if server is not None:
            host, _, port = server.rpartition(":")
            return (host, int(port))
        return dst

    async def send(
        self,
        node: NodeId,
        port: int,
        dst: SocketAddr,
        protocol: str,
        msg: Payload,
    ) -> None:
        """Datagram send: silently dropped on clog/loss (net/mod.rs:298-333).

        Nemesis message-level clauses (FaultPlan → NetConfig knobs):
        duplication re-delivers the datagram once more with an independent
        latency roll, and bounded reordering adds a uniform extra delay in
        [0, reorder_window] so later sends can overtake. Both apply to
        datagrams only — `connect1` channels are reliable ORDERED, the TCP
        face — mirroring the TPU engine's per-candidate dup/reorder rolls.
        """
        await self.rand_delay()
        hook = self._hooks_req.get(node)
        if hook is not None and not hook(msg):
            return
        dst = self._ipvs_rewrite(dst, protocol)
        cfg = self.network.config
        # the dup coin flips BEFORE the original's loss roll (mirroring the
        # engine, which coins every candidate): the copy's fate — its own
        # loss roll, its own latency — is independent of the original's.
        # With a NemesisDriver installed the coin is schedule-matched
        # (ScheduleCoins: pure in (seed, site, index)); otherwise ambient.
        dup = cfg.packet_duplicate_rate > 0.0 and (
            cfg.coins.dup(cfg.packet_duplicate_rate)
            if cfg.coins is not None
            else self.rng.gen_bool(cfg.packet_duplicate_rate)
        )
        if dup:
            cfg.count_fire("dup")
        # Lamport mirror (opt-in; -1 when disabled): the send is an event
        # whether or not any copy survives the link (the device's emitting
        # handler event likewise exists regardless of drops); duplicates
        # share it — one cause, two deliveries, the engine's dup semantics
        send_eid = self.lineage.on_send(node)
        result = self.network.try_send(node, dst, protocol)
        if result is None and not dup:
            return  # dropped, and no copy can survive it
        dst_node = (
            result[1]
            if result is not None
            else self.network.resolve_dest_node(node, dst, protocol)
        )
        rsp_hook = self._hooks_rsp.get(dst_node) if dst_node is not None else None

        def deliver_from(src_ip: str, socket) -> None:
            src = (src_ip, port)
            if rsp_hook is not None and not rsp_hook(msg):
                return
            if dst_node is not None:
                self.lineage.on_deliver(dst_node, send_eid)
            socket.deliver(src, dst, msg)

        def schedule(latency_ns: int, src_ip: str, socket) -> None:
            if cfg.packet_reorder_rate > 0.0 and cfg.packet_reorder_window > 0.0:
                hit = (
                    cfg.coins.reorder(cfg.packet_reorder_rate)
                    if cfg.coins is not None
                    else self.rng.gen_bool(cfg.packet_reorder_rate)
                )
                if hit:
                    cfg.count_fire("reorder")
                    span_ns = max(round(cfg.packet_reorder_window * 1e9), 1)
                    latency_ns += (
                        cfg.coins.reorder_extra(span_ns)
                        if cfg.coins is not None
                        else self.rng.randrange(0, span_ns)
                    )
            # absolute-deadline timers: network latency is wire time, never
            # subject to the sender's nemesis clock skew (vtime.sleep-side)
            self.time.add_timer_at_ns(
                self.time.now_ns() + latency_ns,
                lambda: deliver_from(src_ip, socket),
            )

        if result is not None:
            src_ip, _, socket, latency_ns = result
            schedule(latency_ns, src_ip, socket)
        if dup:
            copy = self.network.try_send(node, dst, protocol)
            if copy is not None:
                src_ip2, _, socket2, latency2 = copy
                schedule(latency2, src_ip2, socket2)

    async def connect1(
        self,
        node: NodeId,
        port: int,
        dst: SocketAddr,
        protocol: str,
    ) -> Tuple[PayloadSender, PayloadReceiver, SocketAddr]:
        """Open a reliable ordered connection (net/mod.rs:337-367).

        Raises ConnectionRefusedError when the peer is unreachable/clogged.
        """
        await self.rand_delay()
        dst = self._ipvs_rewrite(dst, protocol)
        result = self.network.try_send(node, dst, protocol)
        if result is None:
            raise ConnectionRefusedError(f"connection refused: {format_addr(dst)}")
        src_ip, dst_node, socket, _latency = result
        src = (src_ip, port)
        # each half is owned by BOTH endpoint nodes: killing either side
        # closes the connection (sender gets BrokenPipe, receiver gets EOF),
        # matching the reference where task drop closes the mpsc halves
        tx1, rx1 = self.channel(node, dst, protocol, owners=(node, dst_node))
        tx2, rx2 = self.channel(dst_node, src, protocol, owners=(node, dst_node))
        socket.new_connection(src, dst, tx2, rx1)
        return tx1, rx2, src

    def channel(
        self,
        node: NodeId,
        dst: SocketAddr,
        protocol: str,
        owners: Optional[Tuple[NodeId, ...]] = None,
    ) -> Tuple[PayloadSender, PayloadReceiver]:
        """A one-direction reliable channel from `node` toward `dst`
        (net/mod.rs:369-405): each message rolls the link at send time and
        arrives at now+latency; while clogged the receiver retries with
        exponential backoff. Reset of any owner node closes the channel."""
        chan: Channel = Channel()
        for owner in owners if owners is not None else (node,):
            self._node_channels.setdefault(owner, []).append(chan)

        def test_link() -> Optional[int]:
            result = self.network.try_send(node, dst, protocol)
            if result is None:
                return None
            return self.time.now_ns() + result[3]

        return (
            PayloadSender(test_link, chan),
            PayloadReceiver(test_link, chan, self.time),
        )
