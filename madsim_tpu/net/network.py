"""The network graph: nodes, IPs, sockets, clogs, loss and latency.

Analog of reference madsim/src/sim/net/network.rs:20-313. Pure bookkeeping +
RNG rolls; all *delivery* happens via timers scheduled by `NetSim`.

On the TPU batched backend the same state lives as tensors — link masks
`[lane, node, node]` (SimState.link_ok), per-lane loss/latency draws — see
`madsim_tpu/tpu/engine.py`; this class is the single-lane host semantics.
"""

from __future__ import annotations

from typing import Dict, Optional, Protocol, Set, Tuple

from ..core.config import NetConfig
from ..core.rng import GlobalRng
from .addr import (
    SocketAddr,
    UNSPECIFIED,
    format_addr,
    is_loopback,
    is_unspecified,
)

NodeId = int
# protocols are plain strings: "udp" | "tcp"
Protocol_ = str


class Socket(Protocol):
    """Receiver side of a bound address (reference network.rs:51-64)."""

    def deliver(self, src: SocketAddr, dst: SocketAddr, msg: object) -> None: ...

    def new_connection(self, src: SocketAddr, dst: SocketAddr, tx, rx) -> None: ...


class Direction:
    IN = "in"
    OUT = "out"
    BOTH = "both"


class Stat:
    """Network statistics (reference network.rs:99-105)."""

    def __init__(self) -> None:
        self.msg_count = 0

    def __repr__(self) -> str:
        return f"Stat(msg_count={self.msg_count})"


class _NetNode:
    __slots__ = ("ip", "sockets")

    def __init__(self) -> None:
        self.ip: Optional[str] = None
        self.sockets: Dict[Tuple[SocketAddr, Protocol_], Socket] = {}


class AddrInUse(OSError):
    pass


class AddrNotAvailable(OSError):
    pass


class ConnectionRefused(ConnectionRefusedError):
    pass


class Network:
    def __init__(self, rng: GlobalRng, config: NetConfig) -> None:
        self.rng = rng
        self.config = config
        self.stat = Stat()
        self.nodes: Dict[NodeId, _NetNode] = {}
        self.addr_to_node: Dict[str, NodeId] = {}
        self.clogged_node_in: Set[NodeId] = set()
        self.clogged_node_out: Set[NodeId] = set()
        self.clogged_link: Set[Tuple[NodeId, NodeId]] = set()

    def update_config(self, config: NetConfig) -> None:
        self.config = config

    def insert_node(self, id: NodeId) -> None:
        self.nodes.setdefault(id, _NetNode())

    def reset_node(self, id: NodeId) -> None:
        node = self.nodes.get(id)
        if node is not None:
            node.sockets.clear()

    def set_ip(self, id: NodeId, ip: str) -> None:
        node = self.nodes[id]
        if node.ip is not None:
            self.addr_to_node.pop(node.ip, None)
        if ip in self.addr_to_node and self.addr_to_node[ip] != id:
            raise ValueError(f"IP conflict: {ip} already assigned to node {self.addr_to_node[ip]}")
        node.ip = ip
        self.addr_to_node[ip] = id

    def get_ip(self, id: NodeId) -> Optional[str]:
        node = self.nodes.get(id)
        return node.ip if node else None

    # -- clogging (partitions) --

    def clog_node(self, id: NodeId, direction: str = Direction.BOTH) -> None:
        assert id in self.nodes, "node not found"
        if direction in (Direction.IN, Direction.BOTH):
            self.clogged_node_in.add(id)
        if direction in (Direction.OUT, Direction.BOTH):
            self.clogged_node_out.add(id)

    def unclog_node(self, id: NodeId, direction: str = Direction.BOTH) -> None:
        assert id in self.nodes, "node not found"
        if direction in (Direction.IN, Direction.BOTH):
            self.clogged_node_in.discard(id)
        if direction in (Direction.OUT, Direction.BOTH):
            self.clogged_node_out.discard(id)

    def clog_link(self, src: NodeId, dst: NodeId) -> None:
        assert src in self.nodes and dst in self.nodes, "node not found"
        self.clogged_link.add((src, dst))

    def unclog_link(self, src: NodeId, dst: NodeId) -> None:
        self.clogged_link.discard((src, dst))

    def link_clogged(self, src: NodeId, dst: NodeId) -> bool:
        return (
            src in self.clogged_node_out
            or dst in self.clogged_node_in
            or (src, dst) in self.clogged_link
        )

    # -- sockets --

    def bind(
        self, node_id: NodeId, addr: SocketAddr, protocol: Protocol_, socket: Socket
    ) -> SocketAddr:
        node = self.nodes[node_id]
        ip, port = addr
        if (
            not is_unspecified(ip)
            and not is_loopback(ip)
            and node.ip is not None
            and ip != node.ip
        ):
            raise AddrNotAvailable(f"invalid address: {format_addr(addr)}")
        if port == 0:
            port = next(
                (
                    p
                    for p in range(1, 65536)
                    if ((ip, p), protocol) not in node.sockets
                ),
                None,
            )
            if port is None:
                raise AddrInUse("no available ephemeral port")
        key = ((ip, port), protocol)
        if key in node.sockets:
            raise AddrInUse(f"address already in use: {ip}:{port}")
        node.sockets[key] = socket
        return (ip, port)

    def close(self, node_id: NodeId, addr: SocketAddr, protocol: Protocol_) -> None:
        node = self.nodes.get(node_id)
        if node is not None:
            node.sockets.pop((addr, protocol), None)

    # -- the rolls --

    def test_link(self, src: NodeId, dst: NodeId) -> Optional[int]:
        """Latency in ns, or None on clog/loss (reference network.rs:261-269).

        Nemesis message-level clauses ride here too: the extra loss coin
        (FaultPlan MsgLoss, counted per fire) and the latency-spike window
        (additive extra latency while a NemesisDriver holds a spike open).
        """
        if self.link_clogged(src, dst):
            return None
        if self.config.packet_loss_rate > 0.0 and self.rng.gen_bool(
            self.config.packet_loss_rate
        ):
            return None
        if self.config.packet_extra_loss_rate > 0.0:
            # schedule-matched when a NemesisDriver installed ScheduleCoins
            hit = (
                self.config.coins.loss(self.config.packet_extra_loss_rate)
                if self.config.coins is not None
                else self.rng.gen_bool(self.config.packet_extra_loss_rate)
            )
            if hit:
                self.config.count_fire("loss")
                return None
        self.stat.msg_count += 1
        lo = round(self.config.send_latency_min * 1e9)
        hi = round(self.config.send_latency_max * 1e9)
        latency = self.rng.randrange(lo, max(hi, lo + 1))
        if self.config.spike_extra_latency > 0.0:
            latency += round(self.config.spike_extra_latency * 1e9)
        return latency

    def resolve_dest_node(
        self, node: NodeId, dst: SocketAddr, protocol: Protocol_
    ) -> Optional[NodeId]:
        node0 = self.nodes[node]
        if is_loopback(dst[0]) or (dst, protocol) in node0.sockets:
            return node
        if node0.ip is None:
            return None
        return self.addr_to_node.get(dst[0])

    def try_send(
        self, node: NodeId, dst: SocketAddr, protocol: Protocol_
    ) -> Optional[Tuple[str, NodeId, Socket, int]]:
        """Resolve + roll; returns (src_ip, dst_node, socket, latency_ns)."""
        dst_node = self.resolve_dest_node(node, dst, protocol)
        if dst_node is None:
            return None
        latency = self.test_link(node, dst_node)
        if latency is None:
            return None
        sockets = self.nodes[dst_node].sockets
        sock = sockets.get((dst, protocol)) or sockets.get(
            ((UNSPECIFIED, dst[1]), protocol)
        )
        if sock is None:
            return None
        src_ip = "127.0.0.1" if is_loopback(dst[0]) else self.nodes[node].ip
        if src_ip is None:
            return None
        return (src_ip, dst_node, sock, latency)
