"""TCP simulation: listener + byte stream over reliable connections.

Analog of reference madsim/src/sim/net/tcp/ (591 LoC): flush-based delivery
(written bytes are buffered until `flush()` and travel as one message), EOF on
close/drop, connection-refused when the peer is clogged or absent
(tcp/stream.rs:21-175, tcp/listener.rs:8-96).
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..core.sync import Channel, ChannelClosed
from .addr import SocketAddr, ToSocketAddrs, lookup_host
from .endpoint import BindGuard
from .netsim import NetSim, PayloadReceiver, PayloadSender

TCP = "tcp"


class _TcpListenerSocket:
    """Socket accepting connections only (datagrams are not TCP)."""

    def __init__(self) -> None:
        self.conn_chan: Channel = Channel()

    def deliver(self, src: SocketAddr, dst: SocketAddr, msg: object) -> None:
        pass  # no datagrams on a TCP socket

    def new_connection(
        self, src: SocketAddr, dst: SocketAddr, tx: PayloadSender, rx: PayloadReceiver
    ) -> None:
        try:
            self.conn_chan.send_nowait((tx, rx, src))
        except Exception:
            pass


class TcpListener:
    def __init__(self, guard: BindGuard, socket: _TcpListenerSocket) -> None:
        self._guard = guard
        self._socket = socket

    @staticmethod
    async def bind(addr: ToSocketAddrs) -> "TcpListener":
        socket = _TcpListenerSocket()
        guard = await BindGuard.bind(addr, TCP, socket)
        return TcpListener(guard, socket)

    def local_addr(self) -> SocketAddr:
        return self._guard.addr

    async def accept(self) -> Tuple["TcpStream", SocketAddr]:
        try:
            tx, rx, from_addr = await self._socket.conn_chan.recv()
        except ChannelClosed:
            raise OSError("listener closed") from None
        return TcpStream(tx, rx, self._guard.addr, from_addr), from_addr

    def close(self) -> None:
        self._guard.close()
        self._socket.conn_chan.close()


class TcpStream:
    """Byte stream with flush-based delivery."""

    def __init__(
        self,
        tx: PayloadSender,
        rx: PayloadReceiver,
        local: SocketAddr,
        peer: SocketAddr,
        guard: Optional[BindGuard] = None,
    ) -> None:
        self._tx = tx
        self._rx = rx
        self._local = local
        self._peer = peer
        self._guard = guard  # ephemeral bind of a client-side connect
        self._wbuf = bytearray()
        self._rbuf = bytearray()
        self._eof = False

    @staticmethod
    async def connect(addr: ToSocketAddrs) -> "TcpStream":
        from ..core import context
        from ..core.plugin import simulator

        net = simulator(NetSim)
        node_id = context.current_task().node.id
        resolved = await lookup_host(addr)
        # bind an ephemeral local socket so the peer can address us
        socket = _TcpListenerSocket()
        guard = await BindGuard.bind(("0.0.0.0", 0), TCP, socket)
        tx, rx, src = await net.connect1(node_id, guard.addr[1], resolved, TCP)
        return TcpStream(tx, rx, src, resolved, guard=guard)

    def local_addr(self) -> SocketAddr:
        return self._local

    def peer_addr(self) -> SocketAddr:
        return self._peer

    # -- write side --

    def write(self, buf: bytes) -> int:
        self._wbuf += buf
        return len(buf)

    async def flush(self) -> None:
        if self._wbuf:
            data, self._wbuf = bytes(self._wbuf), bytearray()
            try:
                self._tx.send(data)
            except ChannelClosed:
                raise BrokenPipeError("connection closed by peer") from None

    async def write_all(self, buf: bytes) -> None:
        self.write(buf)
        await self.flush()

    # -- read side --

    async def read(self, max_len: int = 65536) -> bytes:
        """Up to max_len bytes; b"" at EOF."""
        if not self._rbuf and not self._eof:
            try:
                data = await self._rx.recv()
            except ChannelClosed:
                self._eof = True
                return b""
            self._rbuf += data
        out = bytes(self._rbuf[:max_len])
        del self._rbuf[:max_len]
        return out

    async def read_exact(self, n: int) -> bytes:
        while len(self._rbuf) < n and not self._eof:
            try:
                data = await self._rx.recv()
            except ChannelClosed:
                self._eof = True
                break
            self._rbuf += data
        if len(self._rbuf) < n:
            raise EOFError("early eof")
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    def shutdown(self) -> None:
        """Close the write half; the peer reads EOF."""
        self._tx.close()

    def close(self) -> None:
        self._tx.close()
        self._rx.close()
        if self._guard is not None:
            self._guard.close()

    def __enter__(self) -> "TcpStream":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()
