"""Built-in typed RPC over Endpoint tag-matching (reference net/rpc.rs:73-167).

A request type declares itself with `@rpc_request` (analog of
`#[derive(Request)]`, madsim-macros/src/request.rs:32-68): it gets a stable
64-bit `RPC_ID` derived from its qualified name. `call` sends the request
under `RPC_ID` with a freshly drawn random response tag; the server handler
loop receives requests under `RPC_ID`, spawns one task per request, and sends
the response back under the response tag.
"""

from __future__ import annotations

import hashlib
from typing import Any, Awaitable, Callable, Optional, Tuple, Type

from ..core import context
from ..core import task as task_mod
from ..core.vtime import timeout as time_timeout
from .addr import ToSocketAddrs, lookup_host
from .endpoint import Endpoint


def hash_str(s: str) -> int:
    """Stable 64-bit id from a string (analog of request.rs hash_str)."""
    return int.from_bytes(hashlib.sha256(s.encode()).digest()[:8], "little")


def rpc_request(cls: type) -> type:
    """Class decorator assigning a stable RPC_ID (derive(Request) analog)."""
    cls.RPC_ID = hash_str(f"{cls.__module__}::{cls.__qualname__}")
    return cls


def _rpc_id(req_type: type) -> int:
    rpc_id = getattr(req_type, "RPC_ID", None)
    if rpc_id is None:
        raise TypeError(
            f"{req_type.__name__} is not an RPC request type; decorate it with @rpc_request"
        )
    return rpc_id


async def call(ep: Endpoint, dst: ToSocketAddrs, req: Any) -> Any:
    """Send a request and await its typed response (rpc.rs:108-111)."""
    rsp, _data = await call_with_data(ep, dst, req, b"")
    return rsp


async def call_timeout(ep: Endpoint, dst: ToSocketAddrs, req: Any, timeout: float) -> Any:
    return await time_timeout(timeout, call(ep, dst, req))


async def call_with_data(
    ep: Endpoint, dst: ToSocketAddrs, req: Any, data: bytes
) -> Tuple[Any, bytes]:
    """Request + raw data payload; returns (response, response data)."""
    handle = context.try_current_handle()
    if handle is not None:
        rsp_tag = handle.rng.next_u64()
    else:  # production mode: any unique tag works
        import os as _os

        # inside a sim, interpose patches os.urandom onto the seeded
        # GlobalRng; this branch is explicitly production-mode
        rsp_tag = int.from_bytes(_os.urandom(8), "little")  # madsim: allow(ambient-entropy)
    resolved = await lookup_host(dst)
    await ep.send_to_raw(resolved, _rpc_id(type(req)), (rsp_tag, req, bytes(data)))
    try:
        payload, _from = await ep.recv_from_raw(rsp_tag)
    finally:
        # the response tag is single-use: prune mailbox state so a timed-out
        # or cancelled call doesn't park its late response forever
        ep.forget_tag(rsp_tag)
    rsp, rsp_data = payload
    return rsp, rsp_data


def add_rpc_handler(
    ep: Endpoint,
    req_type: Type[Any],
    handler: Callable[[Any], Awaitable[Any]],
) -> None:
    """Serve `req_type` requests: one spawned task per request (rpc.rs:143-166)."""

    async def wrapped(req: Any, _data: bytes) -> Tuple[Any, bytes]:
        return await handler(req), b""

    add_rpc_handler_with_data(ep, req_type, wrapped)


def add_rpc_handler_with_data(
    ep: Endpoint,
    req_type: Type[Any],
    handler: Callable[[Any, bytes], Awaitable[Tuple[Any, bytes]]],
) -> None:
    rpc_id = _rpc_id(req_type)

    async def serve_loop() -> None:
        while True:
            payload, from_addr = await ep.recv_from_raw(rpc_id)
            rsp_tag, req, data = payload

            async def handle_one(rsp_tag=rsp_tag, req=req, data=data, from_addr=from_addr):
                rsp, rsp_data = await handler(req, data)
                await ep.send_to_raw(from_addr, rsp_tag, (rsp, bytes(rsp_data)))

            task_mod.spawn(handle_one(), name=f"rpc-{req_type.__name__}")

    task_mod.spawn(serve_loop(), name=f"rpc-serve-{req_type.__name__}")
