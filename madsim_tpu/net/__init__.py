"""Network simulation (reference madsim/src/sim/net/, ~2.5k LoC)."""

from .addr import SocketAddr, ToSocketAddrs, lookup_host  # noqa: F401
from .endpoint import Endpoint  # noqa: F401
from .ipvs import Ipvs, Scheduler, ServiceAddr  # noqa: F401
from .netsim import NetSim, PayloadReceiver, PayloadSender  # noqa: F401
from .network import Direction, Network, Stat  # noqa: F401
from .rpc import (  # noqa: F401
    add_rpc_handler,
    add_rpc_handler_with_data,
    call,
    call_timeout,
    call_with_data,
    rpc_request,
)
from .tcp import TcpListener, TcpStream  # noqa: F401
from .udp import UdpSocket  # noqa: F401
from .unix import UnixDatagram, UnixListener, UnixStream  # noqa: F401
