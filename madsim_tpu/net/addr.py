"""Socket addresses + hostname resolution through the simulated DNS.

Analog of reference madsim/src/sim/net/{addr.rs,dns.rs}. Addresses are
`(ip: str, port: int)` tuples; public APIs also accept `"ip:port"` /
`"host:port"` strings, resolving hostnames through the current `NetSim`'s
DNS records (reference addr.rs:241).
"""

from __future__ import annotations

from typing import Tuple, Union

SocketAddr = Tuple[str, int]
ToSocketAddrs = Union[str, SocketAddr]

UNSPECIFIED = "0.0.0.0"
LOCALHOST = "127.0.0.1"


def is_ip_literal(host: str) -> bool:
    parts = host.split(".")
    return len(parts) == 4 and all(p.isdigit() and int(p) <= 255 for p in parts)


def is_unspecified(ip: str) -> bool:
    return ip == UNSPECIFIED


def is_loopback(ip: str) -> bool:
    return ip.startswith("127.")


def split_host_port(addr: str) -> Tuple[str, int]:
    host, sep, port = addr.rpartition(":")
    if not sep:
        raise ValueError(f"invalid socket address: {addr!r} (expected host:port)")
    return host, int(port)


def format_addr(addr: SocketAddr) -> str:
    return f"{addr[0]}:{addr[1]}"


async def lookup_host(addr: ToSocketAddrs) -> SocketAddr:
    """Resolve to a concrete (ip, port); hostnames go through sim DNS."""
    if isinstance(addr, tuple):
        host, port = addr
    else:
        host, port = split_host_port(addr)
    if host == "localhost":
        return (LOCALHOST, port)
    if is_ip_literal(host):
        return (host, port)
    from ..core import context

    if context.try_current_handle() is None:
        # production mode: real DNS
        import socket

        infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)
        if not infos:
            raise OSError(f"failed to lookup address information: {host!r}")
        return (infos[0][4][0], port)
    from .netsim import NetSim
    from ..core.plugin import simulator

    ip = simulator(NetSim).dns_lookup(host)
    if ip is None:
        raise OSError(f"failed to lookup address information: {host!r}")
    return (ip, port)
