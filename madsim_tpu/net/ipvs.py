"""IPVS: virtual-service load balancing (reference madsim/src/sim/net/ipvs.rs:10-105).

A virtual service address (vip:port/protocol) maps to a set of real server
addresses; `NetSim.send`/`connect1` consult it to rewrite destinations
(net/mod.rs:312-317, 345-349). Round-robin is the only scheduler, like the
reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

ServiceAddr = Tuple[str, int, str]  # (vip, port, protocol)


class Scheduler:
    ROUND_ROBIN = "rr"


class _Service:
    __slots__ = ("scheduler", "servers", "next_idx")

    def __init__(self, scheduler: str) -> None:
        self.scheduler = scheduler
        self.servers: List[str] = []  # "ip:port" strings
        self.next_idx = 0


class Ipvs:
    def __init__(self) -> None:
        self._services: Dict[ServiceAddr, _Service] = {}

    def add_service(self, addr: ServiceAddr, scheduler: str = Scheduler.ROUND_ROBIN) -> None:
        self._services.setdefault(addr, _Service(scheduler))

    def del_service(self, addr: ServiceAddr) -> None:
        self._services.pop(addr, None)

    def add_server(self, addr: ServiceAddr, server: str) -> None:
        svc = self._services.get(addr)
        if svc is None:
            raise KeyError(f"service not found: {addr}")
        if server not in svc.servers:
            svc.servers.append(server)

    def del_server(self, addr: ServiceAddr, server: str) -> None:
        svc = self._services.get(addr)
        if svc is not None and server in svc.servers:
            svc.servers.remove(server)

    def get_server(self, addr: ServiceAddr) -> Optional[str]:
        svc = self._services.get(addr)
        if svc is None or not svc.servers:
            return None
        server = svc.servers[svc.next_idx % len(svc.servers)]
        svc.next_idx += 1
        return server
