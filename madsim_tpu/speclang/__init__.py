"""Speclang — the single-source protocol spec compiler (ROADMAP item 1).

The reference madsim's whole product is that ONE source of user code
runs unchanged on both the real and the simulated runtime behind the
`--cfg madsim` boundary. This reproduction had drifted into the
opposite regime: every protocol was authored twice — a fused device
`on_event` in `tpu/<x>.py` plus a host-runtime twin in
`workloads/<x>_host.py` — and then wired by hand through narrow_fields,
rate_floors, narrow_horizon_us, durable/sync fields, msg_kind_names and
five scattered registries. Speclang closes the gap: a protocol is ONE
spec source (`speclang/specs/<x>.py`, written in the restricted
vocabulary `lang.py` validates) and two thin generated modules
(`speclang/generated/<x>_device.py` / `<x>_host.py`) that are emitted by
`python -m madsim_tpu.speclang emit`, checked in, and drift-checked.

  lang.py    the language surface: Field/Rate/Cap/Messages/KnobDecl/
             DiskPlane declarations + the Protocol container, plus the
             AST restriction validator (no unbounded loops, literal
             PRNG sites, no ambient entropy).
  device.py  the device backend: `build(proto)` derives the state
             NamedTuple, init, on_restart, narrow_fields, rate_floors,
             narrow_horizon_us, time_fields, msg_kind_names, the
             durable plane and Tier-B SpecKnob rows FROM the
             declarations — never re-stated — and emits the fused
             masked `ProtocolSpec` the engine runs.
  hostrt.py  the host backend: a generic host-runtime twin that runs
             the SAME handler bodies as breakpointable per-node tasks
             over `net.Endpoint`, with chaos (native or NemesisDriver
             plan mode) and the spec's own invariant as the oracle.
  emit.py    the deterministic generated-module emitter + the
             spec-source digest that pins generated output to source.

Every generated spec is gated by the PR 7 verifier (all jaxpr/lint
rules) and the PR 8 range certifier exactly like a hand-written one —
declared bounds are PROVED, not trusted (`python -m madsim_tpu.analysis
--all` traces twopc-gen/lease-gen/backup). Registration is one row in
`madsim_tpu/workloads/__init__.py`. See docs/speclang.md.
"""

from __future__ import annotations

from .lang import (  # noqa: F401
    Cap,
    DiskPlane,
    Field,
    KnobDecl,
    Protocol,
    Rate,
    validate_protocol,
)
