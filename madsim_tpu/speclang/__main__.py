"""CLI: `python -m madsim_tpu.speclang emit [--check]`.

`emit` regenerates the checked-in modules under `speclang/generated/`
from the spec sources under `speclang/specs/`; `emit --check` diffs
instead of writing and exits nonzero on drift (the CI drift gate)."""

from __future__ import annotations

import argparse
import sys

from . import emit as emit_mod


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m madsim_tpu.speclang")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_emit = sub.add_parser(
        "emit", help="regenerate speclang/generated/ from specs/"
    )
    p_emit.add_argument(
        "--check", action="store_true",
        help="diff against the checked-in files; exit 1 on drift",
    )
    args = ap.parse_args(argv)

    clean, drifted = emit_mod.emit(check=args.check)
    for f in clean:
        print(f"  ok  {f}")
    for f in drifted:
        print(f"DRIFT {f} (re-run `python -m madsim_tpu.speclang emit`)")
    if drifted:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
