"""The speclang language surface: declarations + the restriction validator.

A protocol spec source (speclang/specs/<x>.py) declares ONE `Protocol`:
typed state fields with bounds and durability, the message vocabulary,
tunable knobs, and a `body` function holding the handler bodies — the
single source both backends compile. The vocabulary is deliberately
restricted (docs/authoring_protocol_specs.md prescribes it): frozen
declarations, masked dataflow handlers, bounded loops only, literal PRNG
site constants. `validate_protocol` enforces the restrictions by AST
walk over the spec source so a generated spec can never smuggle in the
constructs the verifier exists to catch (unbounded loops, computed draw
sites, ambient entropy, host callbacks).

What each declaration DERIVES on the device face (device.py):

  Field.dtype/shape      the state NamedTuple leaf (i32 at rest, like
                         every hand spec; the engine owns narrowing)
  Field.init             the init leaf (int constant, or a callable
                         `(key, nid) -> array` for draw-based identity
                         like lease's incarnation nonce — draw inits
                         must be durable, there is no constant to
                         restore on restart)
  Field.durable          on_restart: volatile fields reset to their
                         init constants, durable ones survive — the
                         restart handler is derived, not authored
  Field.narrow           the narrow_fields entry ("u8"/"u16"/"i16")
  Field.rate (Rate)      the rate_floors RateFloor entry AND the spec's
                         narrow_horizon_us via the shared formula
                         (dtype_max - max(0, init)) * floor_us
                             // (ratchet * inc * margin)
                         — the same hand-derived bound the range
                         certifier independently proves
  Field.rate (Cap)       a HardCap entry (horizon-independent bound)
  Field.time             the time_fields entry (epoch-rebased stamps)
  Messages               msg_kind_names + the payload width
  DiskPlane              durable_fields / sync_field / on_recover
  KnobDecl               the Tier-B SpecKnob rows (tune.py), rebuilt
                         through `device.build` itself
"""

from __future__ import annotations

import ast
import dataclasses
import inspect
import textwrap
from types import SimpleNamespace
from typing import Any, Callable, Mapping, Optional, Tuple

# the narrow vocabulary: at-rest storage dtypes the engine supports for
# r8 carry compaction (signed variants exist for -1-sentinel fields)
NARROW_DTYPES = ("u8", "u16", "i8", "i16")
# inclusive maxima used by the horizon derivation
NARROW_MAX = {"u8": 255, "u16": 65_535, "i8": 127, "i16": 32_767}


@dataclasses.dataclass(frozen=True)
class Rate:
    """A rate-argument bound: the field's global max gains at most
    `ratchet * inc` per `floor_us` of virtual time; `margin` divides the
    derived horizon once more (skew derating / authoring headroom —
    lease halves its budget, twopc runs at margin 1)."""

    floor_us: int
    ratchet: int = 1
    inc: int = 1
    margin: int = 1
    why: str = ""

    def __post_init__(self):
        if min(self.floor_us, self.ratchet, self.inc, self.margin) <= 0:
            raise ValueError("Rate floor_us/ratchet/inc/margin must be > 0")


@dataclasses.dataclass(frozen=True)
class Cap:
    """A horizon-independent bound: the field provably never exceeds
    `cap` regardless of virtual time."""

    cap: int
    why: str = ""


@dataclasses.dataclass(frozen=True)
class Field:
    """One state leaf. `init` is an int constant (broadcast over
    `shape`) or a callable `(key, nid) -> array` for draw-based
    identity; `shape` is a tuple of ints (params are applied before
    `Protocol.fields` runs, so shapes are already concrete there)."""

    name: str
    init: Any = 0
    shape: Tuple[int, ...] = ()
    durable: bool = True
    narrow: Optional[str] = None
    rate: Any = None  # Rate | Cap | None
    time: bool = False
    doc: str = ""

    def __post_init__(self):
        if self.narrow is not None and self.narrow not in NARROW_DTYPES:
            raise ValueError(
                f"field {self.name}: narrow must be one of {NARROW_DTYPES}"
            )
        if self.rate is not None and not isinstance(self.rate, (Rate, Cap)):
            raise ValueError(f"field {self.name}: rate must be Rate or Cap")
        if self.rate is not None and self.narrow is None:
            raise ValueError(
                f"field {self.name}: a Rate/Cap bound only backs a "
                "narrowed field"
            )
        if self.time and self.narrow is not None:
            raise ValueError(
                f"field {self.name}: time fields may never be narrowed"
            )
        if callable(self.init) and not self.durable:
            raise ValueError(
                f"field {self.name}: a draw-based init must be durable — "
                "there is no constant to restore on restart"
            )
        if (
            isinstance(self.rate, Rate)
            and not isinstance(self.init, int)
        ):
            raise ValueError(
                f"field {self.name}: a Rate-bounded field needs an int "
                "init (the horizon formula starts from it)"
            )


@dataclasses.dataclass(frozen=True)
class KnobDecl:
    """A Tier-B spec knob: `param` names the Protocol param the values
    re-parameterize; tune.py measures each candidate through a rebuild
    of the whole generated spec."""

    name: str
    param: str
    values: Tuple[Any, ...]
    default: Any = None


@dataclasses.dataclass(frozen=True)
class DiskPlane:
    """The durability contract (r18): `fields` are watermarked at every
    `sync_field` bump; `recover` (optional) is the on_recover hook —
    `(durable_state, nid, now, torn, key) -> (state, timer)` — None
    uses the watermark with init's timer verbatim."""

    fields: Tuple[str, ...]
    sync_field: str
    recover: Any = None


@dataclasses.dataclass(frozen=True)
class Protocol:
    """One protocol, single-sourced. `fields(p)` and `body(p, State)`
    receive the resolved params namespace `p`; `body` returns a dict
    with the handler bodies both backends compile:

      on_event(s, nid, src, kind, payload, now, key)  (fused=True), or
      on_message(...) + on_timer(...)                 (fused=False —
          the device backend routes them through fuse_two_handlers)
      first_timer(key, nid)        init's first deadline
      restart_timer(s, nid, now, key)   post-crash deadline; receives
          the PRE-reset state (a spec may inspect what survived)
      check_invariants(ns, alive, now)  the per-lane safety oracle
      lane_metrics(node)           optional diagnostics
      host_stats(ns)               optional host-twin summary fields
    """

    name: str
    messages: Tuple[str, ...]
    payload_width: int
    params: Mapping[str, Any]
    fields: Callable[[Any], Tuple[Field, ...]]
    body: Callable[[Any, Any], Mapping[str, Any]]
    fused: bool = True
    max_out: Callable[[Any], int] = lambda p: 1
    max_out_msg: Optional[Callable[[Any], int]] = None
    horizon_margin: int = 1
    knobs: Tuple[KnobDecl, ...] = ()
    disk: Optional[DiskPlane] = None
    buggy_param: Optional[str] = None
    workload: Optional[Callable[..., Any]] = None
    doc: str = ""

    def resolve(self, **overrides) -> SimpleNamespace:
        """The params namespace `p` with overrides applied; unknown
        override names fail loudly (the classic silent-typo hazard of
        kwargs-driven factories)."""
        params = dict(self.params)
        unknown = set(overrides) - set(params)
        if unknown:
            raise ValueError(
                f"{self.name}: unknown spec params {sorted(unknown)} "
                f"(declared: {sorted(params)})"
            )
        params.update(overrides)
        return SimpleNamespace(**params)


# --------------------------------------------------------------- validation
#
# The restriction walk. Speclang bodies are plain JAX, but a restricted
# subset: the constructs below are exactly the ones the verifier tiers
# exist to catch, refused at AUTHORING time instead of trace time.

_FORBIDDEN_CALLS = {
    # unbounded control flow — a spec handler must be a bounded circuit
    "while_loop": "lax.while_loop (unbounded loop) in a spec body",
    # host re-entry — invisible step-serializing callbacks
    "io_callback": "host callback in a spec body",
    "pure_callback": "host callback in a spec body",
    "debug_callback": "host callback in a spec body",
    # ambient entropy (the source-lint rule, enforced earlier here)
    "urandom": "ambient entropy in a spec body",
}
# prng helpers whose SITE argument (position 1, after the key) must be
# an int literal. `fold` is exempt: its second argument is DATA mixed
# into the key (twopc folds the txn id before its vote draw), and the
# site contract is carried by the draw call that consumes the folded key.
_PRNG_FNS = {"bits", "uniform", "randint", "bernoulli"}
_PRNG_SITE_ARG = {"bits": 1, "uniform": 1, "randint": 1, "bernoulli": 1}


def _is_literal_int(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value, int)


def validate_protocol(proto: Protocol) -> None:
    """AST-walk the spec source module for restriction violations.

    Enforced: no `while` statements or lax.while_loop, no host
    callbacks, no ambient-entropy modules, and every prng draw names
    its site as an int literal (sites are the replay contract — a
    computed site would make two draws collide or drift between
    emits). `for` loops are allowed only over literal/range bounds
    (bounded unrolling)."""
    src = textwrap.dedent(inspect.getsource(inspect.getmodule(proto.body)))
    tree = ast.parse(src)
    errors = []
    for node in ast.walk(tree):
        if isinstance(node, ast.While):
            errors.append(f"line {node.lineno}: while loop in a spec source")
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            names = [a.name for a in node.names]
            mod = getattr(node, "module", "") or ""
            for n in names:
                top = (mod or n).split(".")[0]
                if top in ("random", "secrets", "uuid"):
                    errors.append(
                        f"line {node.lineno}: ambient-entropy import {top!r}"
                    )
        elif isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else ""
            )
            if name in _FORBIDDEN_CALLS:
                errors.append(
                    f"line {node.lineno}: {_FORBIDDEN_CALLS[name]}"
                )
            elif name in _PRNG_FNS:
                pos = _PRNG_SITE_ARG[name]
                if len(node.args) > pos and not _is_literal_int(
                    node.args[pos]
                ):
                    errors.append(
                        f"line {node.lineno}: prng.{name} site must be an "
                        "int literal (the draw-site replay contract)"
                    )
        elif isinstance(node, ast.For):
            it = node.iter
            ok = (
                isinstance(it, (ast.List, ast.Tuple))
                or (
                    isinstance(it, ast.Call)
                    and isinstance(it.func, ast.Name)
                    and it.func.id in ("range", "enumerate")
                )
            )
            if not ok:
                errors.append(
                    f"line {node.lineno}: for loop over a non-literal "
                    "iterable (bounded unrolls only: range/enumerate/"
                    "literal sequences)"
                )
    if errors:
        raise ValueError(
            f"speclang restriction violations in {proto.name}:\n  "
            + "\n  ".join(errors)
        )

    # declaration-level cross-checks (cheap; params at defaults)
    p = proto.resolve()
    fields = proto.fields(p)
    names = [f.name for f in fields]
    if len(set(names)) != len(names):
        raise ValueError(f"{proto.name}: duplicate field names")
    by_name = {f.name: f for f in fields}
    if proto.disk is not None:
        for f in proto.disk.fields:
            if f not in by_name:
                raise ValueError(
                    f"{proto.name}: disk plane names unknown field {f!r}"
                )
        if proto.disk.sync_field not in by_name:
            raise ValueError(
                f"{proto.name}: sync_field {proto.disk.sync_field!r} is "
                "not a declared field"
            )
    for k in proto.knobs:
        if k.param not in proto.params:
            raise ValueError(
                f"{proto.name}: knob {k.name!r} names unknown param "
                f"{k.param!r}"
            )
    if proto.buggy_param is not None and proto.buggy_param not in proto.params:
        raise ValueError(
            f"{proto.name}: buggy_param {proto.buggy_param!r} is not a "
            "declared param"
        )
