"""The speclang device backend: compile a Protocol to a ProtocolSpec.

Everything the hand-written `tpu/<x>.py` modules re-state by hand is
DERIVED here from the spec-source declarations, exactly once:

  state NamedTuple   field order = declaration order (the r8 layout
                     contract: leaf order is the carry layout)
  init               constant leaves from Field.init, draw leaves from
                     the callable form, first deadline from the body's
                     `first_timer`
  on_restart         volatile fields reset to their init constants;
                     the deadline comes from `restart_timer`, which
                     receives the PRE-reset state (twopc inspects its
                     in-doubt set across the reset boundary)
  narrow_fields      Field.narrow
  rate_floors        Field.rate (Rate -> RateFloor, Cap -> HardCap)
  narrow_horizon_us  min over Rate-bounded fields of
                     (dtype_max - max(0, init)) * floor_us
                         // (ratchet * inc * margin)
                     — reproduces the hand-derived formulas exactly
                     (twopc's 32_767 * 1_000, lease's
                     65_535 * tick_us // (4 * N)) and is then PROVED,
                     not trusted, by the range certifier
  time_fields        Field.time
  msg_kind_names     Protocol.messages
  durable plane      DiskPlane.fields / .sync_field + the body's
                     optional on_recover
  SpecKnob rows      KnobDecl, rebuilt through `build` itself

Digest discipline: `build` introduces NO operations of its own into the
handler dataflow — handler bodies, helper formulas and PRNG sites come
verbatim from the spec source, so a spec transcribed from a hand module
runs bit-identically to it (tests/test_speclang.py pins twopc and lease
against the canonical golden digests).
"""

from __future__ import annotations

import dataclasses
from collections import namedtuple
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp

from ..tpu.spec import (
    HardCap,
    ProtocolSpec,
    RateFloor,
    fuse_two_handlers,
    wraps_event,
)
from .lang import NARROW_MAX, Cap, Field, Protocol, Rate, validate_protocol

_NARROW_JNP = {
    "u8": jnp.uint8,
    "u16": jnp.uint16,
    "i8": jnp.int8,
    "i16": jnp.int16,
}

# one NamedTuple class per (protocol, resolved field layout): handler
# jit caches key on the class, and two builds of the same protocol must
# produce tree-compatible states
_STATE_CACHE: Dict[Tuple, Any] = {}
_VALIDATED: set = set()


def _state_type(proto: Protocol, fields: Tuple[Field, ...]):
    key = (proto.name, tuple((f.name, tuple(f.shape)) for f in fields))
    if key not in _STATE_CACHE:
        cls_name = "".join(
            w.capitalize() for w in proto.name.replace("-", "_").split("_")
        ) + "State"
        _STATE_CACHE[key] = namedtuple(cls_name, [f.name for f in fields])
    return _STATE_CACHE[key]


def _const_leaf(f: Field):
    if callable(f.init):
        raise ValueError(
            f"field {f.name}: draw-based init has no restart constant"
        )
    if f.shape == ():
        return jnp.int32(f.init)
    return jnp.full(tuple(f.shape), f.init, jnp.int32)


def derive_tables(proto: Protocol, fields: Tuple[Field, ...]) -> dict:
    """The declaration-derived ProtocolSpec tables (shared by `build`
    and the emitter, which renders them as reviewable literals)."""
    narrow: Dict[str, Any] = {}
    floors: Dict[str, Any] = {}
    horizon: Optional[int] = None
    for f in fields:
        if f.narrow is not None:
            narrow[f.name] = _NARROW_JNP[f.narrow]
        if isinstance(f.rate, Rate):
            floors[f.name] = RateFloor(
                floor_us=f.rate.floor_us, ratchet=f.rate.ratchet,
                inc=f.rate.inc, why=f.rate.why,
            )
            top = NARROW_MAX[f.narrow] - max(0, f.init)
            h = (top * f.rate.floor_us) // (
                f.rate.ratchet * f.rate.inc * f.rate.margin
                * proto.horizon_margin
            )
            horizon = h if horizon is None else min(horizon, h)
        elif isinstance(f.rate, Cap):
            floors[f.name] = HardCap(cap=f.rate.cap, why=f.rate.why)
    return {
        "narrow_fields": narrow or None,
        "rate_floors": floors or None,
        "narrow_horizon_us": horizon,
        "time_fields": tuple(f.name for f in fields if f.time),
        "msg_kind_names": tuple(proto.messages),
        "durable_fields": (
            tuple(proto.disk.fields) if proto.disk is not None else ()
        ),
        "sync_field": (
            proto.disk.sync_field if proto.disk is not None else None
        ),
    }


def build(proto: Protocol, **overrides) -> ProtocolSpec:
    """Compile one Protocol (with param overrides) to the fused masked
    ProtocolSpec the engine runs. Validation (the restriction walk)
    runs once per protocol object."""
    if id(proto) not in _VALIDATED:
        validate_protocol(proto)
        _VALIDATED.add(id(proto))
    p = proto.resolve(**overrides)
    fields = proto.fields(p)
    State = _state_type(proto, fields)
    handlers = dict(proto.body(p, State))

    first_timer = handlers["first_timer"]
    restart_timer = handlers["restart_timer"]
    volatile = tuple(f for f in fields if not f.durable)

    def init(key, nid):
        state = State(**{
            f.name: (f.init(key, nid) if callable(f.init) else
                     _const_leaf(f))
            for f in fields
        })
        return state, first_timer(key, nid)

    def on_restart(s, nid, now, key):
        state = s._replace(**{f.name: _const_leaf(f) for f in volatile})
        # the deadline may inspect the PRE-reset state (what survived)
        return state, restart_timer(s, nid, now, key)

    tables = derive_tables(proto, fields)
    max_out = proto.max_out(p)
    max_out_msg = (
        proto.max_out_msg(p) if proto.max_out_msg is not None else max_out
    )
    common = dict(
        name=f"{proto.name}{p.n_nodes}",
        n_nodes=p.n_nodes,
        payload_width=proto.payload_width,
        max_out=max_out,
        max_out_msg=max_out_msg,
        init=init,
        on_restart=on_restart,
        check_invariants=handlers["check_invariants"],
        lane_metrics=handlers.get("lane_metrics"),
        on_recover=handlers.get("on_recover"),
        **tables,
    )
    if proto.fused:
        on_event = handlers["on_event"]

        @wraps_event(on_event)
        def on_message(s, nid, src, kind, payload, now, key):
            return on_event(s, nid, src, kind, payload, now, key)

        @wraps_event(on_event)
        def on_timer(s, nid, now, key):
            return on_event(
                s, nid, jnp.int32(0), jnp.int32(-1),
                jnp.zeros((proto.payload_width,), jnp.int32), now, key,
            )

        return ProtocolSpec(
            on_message=on_message, on_timer=on_timer, on_event=on_event,
            **common,
        )
    return fuse_two_handlers(ProtocolSpec(
        on_message=handlers["on_message"], on_timer=handlers["on_timer"],
        **common,
    ))


def build_workload(
    proto: Protocol,
    n_nodes: Optional[int] = None,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.1,
    buggy: bool = False,
    **spec_overrides,
):
    """The BatchWorkload: generated spec + SimConfig from the spec
    source's `workload` section + the generic host twin as host_repro
    (the same debugging-microscope contract every hand workload
    ships)."""
    from ..tpu.batch import BatchWorkload

    if proto.workload is None:
        raise ValueError(f"{proto.name}: spec source declares no workload")
    overrides = dict(spec_overrides)
    if n_nodes is not None:
        overrides["n_nodes"] = n_nodes
    if buggy:
        if proto.buggy_param is None:
            raise ValueError(
                f"{proto.name}: no planted-bug param declared"
            )
        overrides[proto.buggy_param] = True
    spec = build(proto, **overrides)
    p = proto.resolve(**overrides)
    cfg = proto.workload(spec, p, virtual_secs, loss_rate)

    def host_repro(seed: int):
        from . import hostrt

        try:
            out = hostrt.fuzz_one_seed(
                proto, seed, n_nodes=p.n_nodes,
                virtual_secs=virtual_secs, loss_rate=loss_rate,
                buggy=buggy,
            )
            out["violations"] = 0
            return out
        except hostrt.InvariantViolation as e:
            return {"violations": 1, "violation": str(e)}

    return BatchWorkload(spec=spec, config=cfg, host_repro=host_repro)


def knob_rows(proto: Protocol, virtual_secs: float = 10.0) -> tuple:
    """The Tier-B SpecKnob rows derived from the spec source's KnobDecl
    declarations — every generated spec is born autotunable."""
    from ..tune import SpecKnob

    rows = []
    for k in proto.knobs:
        def rebuild(wl, v, _param=k.param):
            val = int(v) if isinstance(v, (int, float)) else v
            return dataclasses.replace(wl, spec=build(proto, **{_param: val}))

        rows.append(SpecKnob(k.name, tuple(k.values), rebuild,
                             default=k.default))
    return tuple(rows)
