"""Speclang spec sources — the single-source protocol definitions.

Each module here declares exactly one `PROTOCOL` (a `speclang.lang.
Protocol`): typed fields with bounds and durability, the message
vocabulary, knobs, the handler bodies, and the workload chaos recipe.
Both generated faces — the fused device `ProtocolSpec` and the
host-runtime twin — compile from these files and NOTHING else; edit a
spec source, re-run `python -m madsim_tpu.speclang emit`, and both
faces move together (CI's `make speclang-smoke` fails on drift).

  twopc.py   the hand 2PC spec re-derived (golden-digest-identical)
  lease.py   the hand lease/watch spec re-derived (ditto)
  backup.py  primary-backup log shipping — the first speclang-native
             protocol, with the planted stale-read regression bug
"""

from __future__ import annotations

from . import backup, lease, twopc  # noqa: F401

# emit CLI enumeration: spec-source module name -> Protocol
PROTOCOLS = {
    "twopc": twopc.PROTOCOL,
    "lease": lease.PROTOCOL,
    "backup": backup.PROTOCOL,
}
