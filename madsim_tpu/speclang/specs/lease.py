"""etcd-family lease/watch, as a speclang spec source.

The same protocol as the hand-written `tpu/lease.py` (lease server on
node 0, keepalive renewal, fenced release, best-effort watch plane,
durable incarnation nonces rotated only by reconfig wipe-joins — see
that module's header), re-derived: the two-handler bodies below are the
hand module's verbatim (same ops, same PRNG sites 70-75, same state
field order); the state NamedTuple, init, on_restart, narrow_fields,
rate_floors, narrow_horizon_us, time_fields and msg_kind_names are
DERIVED from the `Field` declarations. The planted zombie-lease bug
(`buggy_zombie_lease`) rides along as a spec param, so the generated
workload keeps the membership-axis planted-bug contract.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...tpu import prng
from ...tpu.spec import Outbox, SimConfig, pool_kw_for
from ..lang import Field, Protocol, Rate

ACQUIRE, GRANT, KA, KACK, RELEASE, NOTIFY = range(6)
PAYLOAD_WIDTH = 3
SERVER = 0

_TOKEN_WHY = (
    "the server bumps l_token at most once per arriving lease "
    "message; each client sends at most one lease message per tick "
    "(the timer's three sends are mutually exclusive, re-arm is "
    "now + tick_us, init/restart arm >= tick_us out), so <= N-1 "
    "bumps per tick window, doubled for the Duplicate clause"
)


def _fields(p):
    N = p.n_nodes
    # u16 token budget at <= 2N bumps per tick, halved again (margin=2)
    # for skew derating headroom — proves ~80 s at defaults; my_token
    # and wseen hold COPIES of l_token, certified by the copy induction
    def tok_rate(why):
        return Rate(floor_us=p.tick_us, ratchet=2 * N, inc=1, margin=2,
                    why=why)

    return (
        Field("inc",
              init=lambda key, nid: prng.randint(key, 70, 1, 1 << 30),
              doc="client identity: durable init-drawn incarnation nonce "
                  "(a wipe-join rotates it; i32 — narrowing a 30-bit "
                  "nonce would collide incarnations)"),
        Field("held", narrow="u8", doc="client belief flag"),
        Field("my_token", narrow="u16",
              rate=tok_rate("copy: GRANT/KACK payload of l_token"),
              doc="fencing token of my lease"),
        Field("my_expiry", time=True, doc="server-stamped expiry"),
        Field("pend", durable=False, narrow="u8",
              doc="acquire outstanding (volatile)"),
        Field("req_t", time=True, doc="acquire send time (GRANT echo)"),
        Field("ka_t", time=True, doc="last keepalive send time"),
        Field("wseen", narrow="u16",
              rate=tok_rate("copy: max over observed l_token values"),
              doc="watch plane: max token observed via NOTIFY"),
        Field("l_holder", init=-1,
              doc="lease head (server only): holder node id, -1 = free "
                  "(i32 for the sentinel)"),
        Field("l_inc", doc="holder's incarnation at grant"),
        Field("l_token", narrow="u16", rate=tok_rate(_TOKEN_WHY),
              doc="monotone fencing token"),
        Field("l_expiry", time=True),
    )


def _body(p, State):
    N = p.n_nodes
    assert N >= 3
    tick_us = p.tick_us
    ttl_us = p.ttl_us
    ka_interval_us = p.ka_interval_us
    req_timeout_us = p.req_timeout_us
    acquire_rate = p.acquire_rate
    release_rate = p.release_rate
    buggy_zombie_lease = p.buggy_zombie_lease
    peers = jnp.arange(N, dtype=jnp.int32)

    def first_timer(key, nid):
        # first fire >= tick_us out (part of the l_token rate-floor
        # argument: at most one lease message per client per tick)
        return tick_us + prng.randint(key, 71, 0, tick_us)

    def on_timer(s, nid, now, key):
        is_server = nid == SERVER
        is_client = ~is_server
        # client: local expiry ends belief
        holding = is_client & (s.held > 0) & (now <= s.my_expiry)
        held = jnp.where(is_client & (s.held > 0) & ~holding, 0, s.held)
        # client: release (rare), else keepalive, else maybe acquire
        send_rel = holding & (prng.uniform(key, 72) < release_rate)
        held = jnp.where(send_rel, 0, held)  # stop believing BEFORE sending
        send_ka = holding & ~send_rel & (now - s.ka_t > ka_interval_us)
        pend = jnp.where(
            is_client & (s.pend > 0) & (now - s.req_t > req_timeout_us),
            0, s.pend,
        )
        send_acq = (
            is_client & ~holding & (held == 0) & (pend == 0)
            & (prng.uniform(key, 73) < acquire_rate)
        )
        # server: watch plane — tell one random watcher the lease head
        watcher = prng.randint(key, 74, 1, N)

        state = s._replace(
            held=held,
            pend=jnp.where(send_acq, 1, pend),
            req_t=jnp.where(send_acq, now, s.req_t),
            ka_t=jnp.where(send_ka, now, s.ka_t),
        )
        c_pay = jnp.where(
            send_acq,
            jnp.stack([s.inc, now, jnp.int32(0)]),
            jnp.where(
                send_rel,
                jnp.stack([s.my_token, s.inc, jnp.int32(0)]),
                jnp.stack([s.inc, s.my_token, jnp.int32(0)]),  # KA
            ),
        )
        c_kind = jnp.where(
            send_acq, ACQUIRE, jnp.where(send_rel, RELEASE, KA)
        ).astype(jnp.int32)
        out = Outbox(
            valid=jnp.stack([is_server | send_acq | send_rel | send_ka]),
            dst=jnp.stack([jnp.where(is_server, watcher, SERVER)
                           .astype(jnp.int32)]),
            kind=jnp.stack([jnp.where(is_server, NOTIFY, c_kind)
                            .astype(jnp.int32)]),
            payload=jnp.stack([jnp.where(
                is_server,
                jnp.stack([s.l_token, s.l_holder, jnp.int32(0)]),
                c_pay,
            )]),
        )
        return state, out, now + tick_us

    def on_message(s, nid, src, kind, payload, now, key):
        f = payload
        is_server = nid == SERVER
        live = now <= s.l_expiry

        # -- server: ACQUIRE — grant when free/expired, renew when the
        # caller is the current holder
        is_acq = (kind == ACQUIRE) & is_server
        if buggy_zombie_lease:
            # THE PLANTED BUG: renewal matches the holder NODE ID alone
            # — the incarnation is ignored, so a wipe-joined client's
            # fresh ACQUIRE renews the removed incarnation's live lease
            match_holder = s.l_holder == src
        else:
            match_holder = (s.l_holder == src) & (s.l_inc == f[0])
        free = (s.l_holder < 0) | ~live
        grant_new = is_acq & free
        renew = is_acq & ~free & match_holder
        granted = grant_new | renew
        # -- server: KA — extend a live lease for the matching holder
        ka_ok = (kind == KA) & is_server & live & match_holder
        # every renewal bumps the fencing token (etcd-revision style):
        # stale RELEASEs reordered past a re-acquire bounce off it
        bump = granted | ka_ok
        l_token = jnp.where(bump, s.l_token + 1, s.l_token)
        # -- server: RELEASE — free iff holder and token match
        rel_ok = (
            (kind == RELEASE) & is_server
            & (s.l_holder == src) & (s.l_token == f[0])
        )

        # -- client: GRANT — believe only against the pending request
        is_grant = (
            (kind == GRANT) & ~is_server & (s.pend > 0) & (f[2] == s.req_t)
        )
        # -- client: KACK — fold in the renewed token/expiry
        is_kack = (
            (kind == KACK) & ~is_server & (s.held > 0)
            & (f[0] >= s.my_token)
        )
        # -- client: NOTIFY — watch plane
        is_ntf = (kind == NOTIFY) & ~is_server

        state = s._replace(
            l_holder=jnp.where(grant_new, src,
                               jnp.where(rel_ok, -1, s.l_holder)),
            l_inc=jnp.where(grant_new, f[0], s.l_inc),
            l_token=l_token,
            l_expiry=jnp.where(bump, now + ttl_us, s.l_expiry),
            held=jnp.where(is_grant, 1, s.held),
            my_token=jnp.where(is_grant | is_kack, f[0], s.my_token),
            my_expiry=jnp.where(
                is_grant, f[1],
                jnp.where(is_kack, jnp.maximum(s.my_expiry, f[1]),
                          s.my_expiry),
            ),
            pend=jnp.where(is_grant, 0, s.pend),
            ka_t=jnp.where(is_grant, now, s.ka_t),
            wseen=jnp.where(
                is_grant | is_kack | is_ntf,
                jnp.maximum(s.wseen, f[0]), s.wseen,
            ),
        )
        out = Outbox(
            valid=jnp.stack([granted | ka_ok]),
            dst=jnp.stack([src.astype(jnp.int32)]),
            kind=jnp.stack([jnp.where(granted, GRANT, KACK)
                            .astype(jnp.int32)]),
            payload=jnp.stack([jnp.stack([
                l_token, now + ttl_us,
                jnp.where(granted, f[1], jnp.int32(0)),
            ])]),
        )
        return state, out, jnp.int32(-1)

    def restart_timer(s, nid, now, key):
        # inc/held/my_* are durable: a restarted client resumes a live
        # lease and renews under the SAME incarnation — crash/restart is
        # deliberately invisible to the lease server
        return now + tick_us + prng.randint(key, 75, 0, tick_us)

    def check_invariants(ns, alive, now):
        # ns leaves are [N, ...] for one lane. The incarnation-identity
        # claim: whenever the server records node i as holder AND i
        # itself currently believes, the recorded incarnation is i's
        # CURRENT one (cross-holder mutual exclusion is deliberately out
        # of scope — a server wipe loses the lease log; see the hand
        # module's header for the full argument)
        lh, li = ns.l_holder[SERVER], ns.l_inc[SERVER]
        believer = (peers != SERVER) & (ns.held > 0) & (now <= ns.my_expiry)
        checked = believer & (lh == peers)
        ok = ~checked | (li == ns.inc)
        return ok.all()

    def lane_metrics(node):
        return {
            "mean_lease_token": node.l_token[:, SERVER].astype(jnp.float32),
            "mean_believers": (
                (node.held[:, 1:] > 0).sum(-1).astype(jnp.float32)
            ),
            "mean_wseen": node.wseen[:, 1:].max(-1).astype(jnp.float32),
        }

    return {
        "on_message": on_message,
        "on_timer": on_timer,
        "first_timer": first_timer,
        "restart_timer": restart_timer,
        "check_invariants": check_invariants,
        "lane_metrics": lane_metrics,
    }


def _workload(spec, p, virtual_secs, loss_rate):
    # the hand lease_workload's chaos recipe: loss + crash + RECONFIG
    # (crash/restart keeps the durable nonce, so only the membership
    # axis rotates client identity — the zombie-lease bug cannot fire
    # without a wipe-join)
    return SimConfig(
        horizon_us=int(virtual_secs * 1e6),
        **pool_kw_for(
            spec,
            fused=dict(msg_depth_msg=2, msg_spare_slots=2),
            two_handler=dict(msg_depth_msg=2, msg_depth_timer=2),
        ),
        loss_rate=loss_rate,
        crash_interval_lo_us=500_000,
        crash_interval_hi_us=2_000_000,
        restart_delay_lo_us=200_000,
        restart_delay_hi_us=900_000,
        # down windows well under ttl_us: the removed holder's lease is
        # still live when its fresh incarnation rejoins and re-acquires
        nem_reconfig_interval_lo_us=600_000,
        nem_reconfig_interval_hi_us=1_800_000,
        nem_reconfig_down_lo_us=300_000,
        nem_reconfig_down_hi_us=900_000,
    )


PROTOCOL = Protocol(
    name="lease-gen",
    messages=("ACQUIRE", "GRANT", "KA", "KACK", "RELEASE", "NOTIFY"),
    payload_width=PAYLOAD_WIDTH,
    params=dict(
        n_nodes=5,
        tick_us=25_000,
        ttl_us=1_500_000,
        ka_interval_us=200_000,
        req_timeout_us=300_000,
        acquire_rate=0.5,
        release_rate=0.04,
        buggy_zombie_lease=False,
    ),
    fields=_fields,
    body=_body,
    fused=False,  # authored two-handler; fused via fuse_two_handlers
    max_out=lambda p: 1,
    buggy_param="buggy_zombie_lease",
    workload=_workload,
    doc="etcd-family lease/watch with durable incarnation nonces",
)
