"""Two-Phase Commit, as a speclang spec source.

The same protocol as the hand-written `tpu/twopc.py` (presumed abort,
cooperative termination, static coordinator on node 0 — see that
module's header for the full protocol narrative), re-derived: the
handler bodies below are the hand module's fused `on_event` verbatim
(same ops, same PRNG sites 31-35, same state field order), while
everything the hand module re-states by hand — the state NamedTuple,
init, on_restart, narrow_fields, rate_floors, narrow_horizon_us,
msg_kind_names — is DERIVED from the `Field` declarations by
`speclang.device`. tests/test_speclang.py pins the generated spec
against the hand spec's canonical golden digest: bit-identical
trajectories, or the build is wrong.
"""

from __future__ import annotations

import jax.numpy as jnp

from ...tpu import prng
from ...tpu.spec import Outbox, SimConfig
from ..lang import Field, KnobDecl, Protocol, Rate

NONE, COMMIT, ABORT = 0, 1, 2
PREPARE, VOTE, OUTCOME, DREQ = 0, 1, 2, 3
PAYLOAD_WIDTH = 3  # (tid, flag, spare)

_TID_WHY = (
    "a mint needs a coordinator timer fire; every re-arm "
    "(init, post-start, retry, restart) draws >= 1_000 us"
)


def _fields(p):
    N, TXN = p.n_nodes, p.txn_ring
    # the i16 tid bound is a RATE argument (one global mint per 1 ms
    # hard floor, ratchet=1 — only the coordinator mints); o_tid/v_tid
    # hold COPIES of minted tids, so tid_cur's bound is theirs too.
    # 32_767 mints ~ 32.7 nonstop virtual seconds before the engine
    # refuses the soak (skew derating shaves it further).
    tid_rate = Rate(floor_us=1_000, ratchet=1, inc=1, why=_TID_WHY)
    return (
        Field("tid_cur", init=-1, narrow="i16", rate=tid_rate,
              doc="coordinator: last txn started"),
        Field("vote_mask", durable=False,
              narrow=("u8" if N <= 8 else "u16" if N <= 16 else None),
              doc="coordinator: yes-voter bitmask (volatile)"),
        Field("o_tid", init=-1, shape=(TXN,), narrow="i16", rate=tid_rate,
              doc="outcome ring: absolute tid, -1 empty (slot = tid % TXN)"),
        Field("o_val", shape=(TXN,), narrow="u8",
              doc="outcome ring: COMMIT/ABORT"),
        Field("v_tid", init=-1, shape=(TXN,), narrow="i16", rate=tid_rate,
              doc="own-vote ring: absolute tid, -1 empty"),
        Field("v_val", shape=(TXN,), narrow="u8",
              doc="own-vote ring: COMMIT(yes)/ABORT(no)"),
        Field("decided", doc="outcomes recorded (diagnostics, stays i32)"),
    )


def _body(p, State):
    N, TXN = p.n_nodes, p.txn_ring
    assert N >= 3
    txn_gap_us = p.txn_gap_us
    prepare_timeout_us = p.prepare_timeout_us
    doubt_retry_us = p.doubt_retry_us
    vote_yes_p = p.vote_yes_p
    peers = jnp.arange(N, dtype=jnp.int32)
    tidx = jnp.arange(TXN, dtype=jnp.int32)
    ALL_YES = (1 << N) - 2  # bits 1..N-1
    IDLE_FAR = 2**28  # "unarmed" participant timer offset (ns-safe int32)

    def record_outcome(s, do, tid, outcome):
        """Claim slot tid%TXN for (tid, outcome) when `do`; first write
        for a given tid wins; a tid >= TXN behind the newest recorded
        one is dropped rather than allowed to evict a newer txn's
        slot."""
        at = tidx == (tid % TXN)
        not_stale = tid > s.o_tid.max() - TXN
        fresh = do & not_stale & ~(at & (s.o_tid == tid)).any()
        w = at & fresh
        return s._replace(
            o_tid=jnp.where(w, tid, s.o_tid),
            o_val=jnp.where(w, outcome, s.o_val),
            decided=s.decided + fresh.astype(jnp.int32),
        )

    def record_vote(s, do, tid, vote):
        at = tidx == (tid % TXN)
        return s._replace(
            v_tid=jnp.where(do & at, tid, s.v_tid),
            v_val=jnp.where(do & at, vote, s.v_val),
        )

    def outcome_of(s, tid):
        """Recorded outcome for absolute tid, NONE if absent."""
        hit = (tidx == (tid % TXN)) & (s.o_tid == tid)
        return jnp.where(hit, s.o_val, 0).sum()

    def unresolved_yes(s):
        """[TXN] mask: yes-votes with no recorded outcome — the derived
        in-doubt set (both rings slot a tid identically)."""
        voted_yes = (s.v_tid >= 0) & (s.v_val == COMMIT)
        resolved = (s.v_tid == s.o_tid) & (s.o_tid >= 0)
        return voted_yes & ~resolved

    def first_timer(key, nid):
        return jnp.where(
            nid == 0,
            prng.randint(key, 31, 1_000, txn_gap_us),
            jnp.int32(IDLE_FAR),
        )

    def on_event(s, nid, src, kind, payload, now, key):
        """ALL events — PREPARE/VOTE/OUTCOME/DREQ and the timer tick
        (kind == -1) — as ONE masked handler; the direct transcription
        of tpu/twopc.py's fused form (PRNG sites 32/33/34 unchanged)."""
        f = payload
        is_timer = kind == -1
        is_coord = nid == 0
        tid_msg = f[0]
        flag = f[1]
        out_msg = outcome_of(s, tid_msg)  # recorded outcome for f[0]

        # ====================== timer path (kind == -1) ===================
        # coordinator: a timer fire with an open undecided txn means the
        # prepare deadline passed OR post-restart recovery — both are
        # the presumed-abort case. Otherwise start the next txn.
        open_undecided = (s.tid_cur >= 0) & (
            outcome_of(s, s.tid_cur) == NONE
        )
        do_abort = is_timer & is_coord & open_undecided
        do_start = is_timer & is_coord & ~open_undecided
        new_tid = s.tid_cur + 1
        # participant: cooperative termination for the OLDEST in-doubt
        # yes-vote (retries walk the set oldest-first as outcomes land)
        doubt = unresolved_yes(s)
        in_doubt = (~is_coord) & doubt.any()
        dreq_tid = jnp.where(doubt, s.v_tid, jnp.int32(2**30)).min()
        do_dreq_send = is_timer & in_doubt

        # ====================== message path (kind >= 0) ==================
        is_prep = kind == PREPARE
        is_vote = kind == VOTE
        is_outc = kind == OUTCOME
        is_dreq = kind == DREQ

        # -- PREPARE: defensive dedupe; NO records a local abort
        # (presumed abort lets a no-voter forget), YES records the
        # durable in-doubt vote
        voted = ((tidx == (tid_msg % TXN)) & (s.v_tid == tid_msg)).any()
        do_prep = is_prep & (nid != 0) & ~((out_msg != NONE) | voted)
        yes = (
            prng.uniform(prng.fold(key.astype(jnp.uint32), tid_msg), 33)
            < vote_yes_p
        )
        vote_flag = jnp.where(yes, COMMIT, ABORT)

        # -- VOTE: the coordinator's one open round; any NO => ABORT,
        # all N-1 YES => COMMIT, decided in the same event that
        # broadcasts
        live = (
            is_vote & is_coord & (tid_msg == s.tid_cur) & (out_msg == NONE)
        )
        no = live & (flag == ABORT)
        mask = jnp.where(
            live & (flag == COMMIT), s.vote_mask | (1 << src), s.vote_mask
        )
        all_yes = live & (mask == ALL_YES)
        decide = no | all_yes

        # -- DREQ: the coordinator re-sends a recorded outcome (stays
        # silent while itself undecided; the participant retries)
        have = is_dreq & is_coord & (out_msg != NONE)

        # -- merged ring writes: the event masks are mutually exclusive,
        # so all record_outcome sites collapse to ONE ring pass
        rec_do = do_abort | (do_prep & ~yes) | decide | is_outc
        rec_tid = jnp.where(do_abort, s.tid_cur, tid_msg)
        rec_val = jnp.where(
            do_abort | (do_prep & ~yes) | no, ABORT,
            jnp.where(all_yes, COMMIT, flag),
        )
        state = s._replace(
            tid_cur=jnp.where(do_start, new_tid, s.tid_cur),
            vote_mask=jnp.where(do_start | do_abort | decide, 0, mask),
        )
        state = record_vote(state, do_prep, tid_msg, vote_flag)
        state = record_outcome(state, rec_do, rec_tid, rec_val)

        # ================== merged outbox (E = N rows) ====================
        # broadcast events (coordinator only) use rows 1..N-1;
        # single-message events put the payload in outbox ROW dst so
        # each destination gets its own pool region
        bcast = do_abort | do_start | decide
        bc_kind = jnp.where(do_start, PREPARE, OUTCOME)
        bc_tid = jnp.where(
            do_abort, s.tid_cur, jnp.where(do_start, new_tid, tid_msg)
        )
        bc_flag = jnp.where(
            do_start, 0, jnp.where(do_abort | no, ABORT, COMMIT)
        )
        single = do_prep | have | do_dreq_send
        s_dst = jnp.where(do_dreq_send, jnp.int32(0), src)
        s_kind = jnp.where(
            do_prep, VOTE, jnp.where(have, OUTCOME, DREQ)
        )
        s_tid = jnp.where(do_dreq_send, dreq_tid, tid_msg)
        s_flag = jnp.where(do_prep, vote_flag, jnp.where(have, out_msg, 0))
        at_row = peers == s_dst  # [N]

        def fields(tid, fl):
            row = jnp.stack([
                jnp.asarray(tid, jnp.int32), jnp.asarray(fl, jnp.int32),
                jnp.int32(0),
            ])
            return row  # [P]

        out = Outbox(
            valid=jnp.where(bcast, peers != 0, single & at_row),
            dst=jnp.where(
                bcast, peers,
                jnp.where(single, jnp.full((N,), 1, jnp.int32) * s_dst, 0),
            ),
            kind=jnp.where(
                bcast, bc_kind, jnp.where(single, s_kind, 0)
            ) * jnp.ones((N,), jnp.int32),
            payload=jnp.where(
                jnp.reshape(bcast, (1, 1)),
                fields(bc_tid, bc_flag)[None, :],
                jnp.where(
                    (single & at_row)[:, None],
                    fields(s_tid, s_flag)[None, :], 0,
                ),
            ),
        )

        # -- timer: coordinator reschedules every tick; a yes-voting
        # participant arms its in-doubt retry; a deciding coordinator
        # schedules the next round; everything else keeps its deadline
        timer_t = jnp.where(
            is_coord,
            jnp.where(
                do_start,
                now + prepare_timeout_us,
                now + prng.randint(key, 32, txn_gap_us // 2, txn_gap_us),
            ),
            now + jnp.where(in_doubt, doubt_retry_us, IDLE_FAR),
        )
        timer_m = jnp.where(
            do_prep & yes,
            now + doubt_retry_us,
            jnp.where(
                decide,
                now + prng.randint(key, 34, txn_gap_us // 2, txn_gap_us),
                jnp.int32(-1),
            ),
        )
        return state, out, jnp.where(is_timer, timer_t, timer_m)

    def restart_timer(s, nid, now, key):
        # receives the PRE-reset state: the participant arm inspects the
        # surviving in-doubt set
        return jnp.where(
            nid == 0,
            # fire soon: an open undecided tid_cur gets presumed-aborted
            now + prng.randint(key, 35, 1_000, txn_gap_us),
            now + jnp.where(unresolved_yes(s).any(), doubt_retry_us,
                            IDLE_FAR),
        )

    def check_invariants(ns, alive, now):
        # ns leaves are [N, ...] for one lane; slot-aligned joins only
        # (equal tids can only ever share a slot)
        ot, ov = ns.o_tid, ns.o_val  # [N, TXN]
        # atomicity: same absolute tid on two nodes => same outcome
        same_tid = (ot[:, None, :] == ot[None, :, :]) & (ot[:, None, :] >= 0)
        diff_out = ov[:, None, :] != ov[None, :, :]
        atomicity = ~(same_tid & diff_out).any()
        # vote respect: a node recording COMMIT for a tid it voted NO on
        joined = (
            (ns.o_tid == ns.v_tid)
            & (ns.o_tid >= 0)
            & (ns.o_val == COMMIT)
            & (ns.v_val == ABORT)
        )
        vote_respect = ~joined.any()
        return atomicity & vote_respect

    def lane_metrics(node):
        voted_yes = (node.v_tid >= 0) & (node.v_val == COMMIT)  # [L,N,TXN]
        resolved = (
            (node.v_tid[..., :, None] == node.o_tid[..., None, :])
            & (node.o_tid[..., None, :] >= 0)
        ).any(-1)
        return {
            "mean_decided_txns": node.decided[:, 0].astype(jnp.float32),
            "in_doubt_lanes": (
                voted_yes[:, 1:] & ~resolved[:, 1:]
            ).any((-2, -1)),
        }

    return {
        "on_event": on_event,
        "first_timer": first_timer,
        "restart_timer": restart_timer,
        "check_invariants": check_invariants,
        "lane_metrics": lane_metrics,
    }


def _workload(spec, p, virtual_secs, loss_rate):
    # the hand twopc_workload's chaos recipe: loss, coordinator crashes
    # (the blocking case) and partitions; ring depth 2 for overlapping
    # OUTCOME re-sends and back-to-back PREPARE/OUTCOME broadcasts
    return SimConfig(
        horizon_us=int(virtual_secs * 1e6),
        msg_depth_msg=2,
        msg_depth_timer=2,
        loss_rate=loss_rate,
        crash_interval_lo_us=400_000,
        crash_interval_hi_us=2_000_000,
        restart_delay_lo_us=200_000,
        restart_delay_hi_us=1_000_000,
        partition_interval_lo_us=400_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=300_000,
        partition_heal_hi_us=1_200_000,
    )


PROTOCOL = Protocol(
    name="twopc-gen",
    messages=("PREPARE", "VOTE", "OUTCOME", "DREQ"),
    payload_width=PAYLOAD_WIDTH,
    params=dict(
        n_nodes=5,
        txn_ring=16,
        txn_gap_us=40_000,
        prepare_timeout_us=120_000,
        doubt_retry_us=80_000,
        vote_yes_p=0.85,
    ),
    fields=_fields,
    body=_body,
    fused=True,
    max_out=lambda p: p.n_nodes,
    max_out_msg=lambda p: p.n_nodes,  # a VOTE receipt can broadcast
    knobs=(
        KnobDecl("txn_ring", param="txn_ring", values=(8, 16, 32),
                 default=16),
    ),
    workload=_workload,
    doc="two-phase commit (presumed abort, cooperative termination)",
)
