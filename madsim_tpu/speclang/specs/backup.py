"""Primary-backup log shipping — the first speclang-NATIVE protocol.

Unlike twopc/lease (hand specs re-derived to prove bit-identity), this
protocol never existed as a hand module: the whole thing is this one
spec source, and both faces — the fused device `ProtocolSpec` and the
host-runtime twin — are generated.

Shape: node 0 is the PRIMARY, nodes 1..N-1 are BACKUPS. The primary's
timer mints versions and broadcasts REPL(ver, val) to every backup
(fsync-before-ack: the apply bumps `syncs`, the spec's sync_field, in
the same step), and occasionally reads from one random backup
(READ -> RESP(b_ver, b_val)) — the stand-in for a client hitting a
read replica. A backup applies a REPL iff it is NEWER than what it
holds (`ver > b_ver`) and ACKs; it answers READs from its local copy.

Safety — monotone reads per replica: the versions one backup serves
never go backwards. Each backup tracks `served_max` (the highest b_ver
it has ever answered a READ with) and latches the sticky `regress` flag
the moment it is about to serve an OLDER version. Detection is local to
the backup (race-free: no cross-node join), and every reset path moves
the plane together — a reconfig wipe re-inits b_ver/served_max/regress
as one, a disk crash rolls all three back to the same watermark
(they share the durable plane), a plain restart keeps all three.

THE PLANTED BUG (`buggy=True`): the apply guard degrades from
`ver > b_ver` to `ver != b_ver` — "anything different must be news".
A DUPLICATED or REORDERED stale REPL then re-applies an old version
over a newer one, the next READ observes b_ver < served_max, and the
invariant fires. The bug lives purely on the duplicate/reorder axis
(the workload arms `nem_dup_rate`/`nem_reorder_rate`), which is what
lets ddmin shrink a repro down to those clauses — crash/restart alone
cannot fire it (durable state restarts exactly where it stopped).

PRNG sites: 90 (repl-vs-read coin), 91 (read target), 92 (timer
re-arm), 93 (first fire), 94 (restart fire).
"""

from __future__ import annotations

import jax.numpy as jnp

from ...tpu import prng
from ...tpu.spec import Outbox, SimConfig, pool_kw_for
from ..lang import DiskPlane, Field, Protocol, Rate

REPL, ACK, READ, RESP = 0, 1, 2, 3
PAYLOAD_WIDTH = 3  # (ver, val, spare)

_VER_WHY = (
    "only the primary mints, at most one ver per timer fire; every "
    "primary arm (first, re-arm, restart) draws >= tick_us, margin 2 "
    "for skew derating"
)


def _fields(p):
    N = p.n_nodes
    # ver is the one minted counter; b_ver/served_max/ack_ver hold
    # COPIES of it (REPL / served REPL / ACK payloads), certified by the
    # range certifier's copy induction
    def ver_rate(why):
        return Rate(floor_us=p.tick_us, ratchet=1, inc=1, margin=2,
                    why=why)

    return (
        Field("ver", narrow="u16", rate=ver_rate(_VER_WHY),
              doc="primary: latest minted version"),
        Field("val", doc="primary: payload of the latest version"),
        Field("b_ver", narrow="u16", rate=ver_rate("copy: REPL payload"),
              doc="backup: version held"),
        Field("b_val", doc="backup: value held"),
        Field("served_max", narrow="u16",
              rate=ver_rate("copy: max over served b_ver values"),
              doc="backup: highest version ever served to a READ"),
        Field("regress", narrow="u8",
              doc="backup: sticky monotone-reads violation flag "
                  "(step-closed in {0,1})"),
        Field("ack_ver", shape=(N,), durable=False, narrow="u16",
              rate=ver_rate("copy: ACK payload of minted vers"),
              doc="primary: highest ver acked per backup (volatile)"),
        Field("r_seen", durable=False,
              doc="primary: highest version read back (diagnostics)"),
        Field("syncs", durable=False,
              doc="fsync counter — the spec's sync_field"),
        Field("serves", durable=False,
              doc="backup: READs answered (diagnostics)"),
    )


def _body(p, State):
    N = p.n_nodes
    assert N >= 3
    tick_us = p.tick_us
    repl_rate = p.repl_rate
    buggy = p.buggy
    peers = jnp.arange(N, dtype=jnp.int32)
    IDLE_FAR = 2**28  # backups never self-fire

    def first_timer(key, nid):
        # first fire >= tick_us out: part of the ver rate-floor argument
        return jnp.where(
            nid == 0,
            tick_us + prng.randint(key, 93, 0, tick_us),
            jnp.int32(IDLE_FAR),
        )

    def on_event(s, nid, src, kind, payload, now, key):
        f = payload
        is_timer = kind == -1
        is_primary = nid == 0

        # ================= timer path (primary only) ==================
        coin = prng.uniform(key, 90) < repl_rate
        do_repl = is_timer & is_primary & coin
        do_read = is_timer & is_primary & ~coin
        new_ver = s.ver + 1
        new_val = new_ver * 7 + 1  # deterministic payload for the ver
        target = prng.randint(key, 91, 1, N)

        # ================= message path (kind >= 0) ===================
        is_repl = kind == REPL
        if buggy:
            # THE PLANTED BUG: "anything different must be news" — a
            # duplicated/reordered STALE REPL re-applies an old version
            news = f[0] != s.b_ver
        else:
            news = f[0] > s.b_ver
        apply = is_repl & ~is_primary & news
        serve = (kind == READ) & ~is_primary
        ackin = (kind == ACK) & is_primary
        respin = (kind == RESP) & is_primary

        state = s._replace(
            ver=jnp.where(do_repl, new_ver, s.ver),
            val=jnp.where(do_repl, new_val, s.val),
            b_ver=jnp.where(apply, f[0], s.b_ver),
            b_val=jnp.where(apply, f[1], s.b_val),
            # latch BEFORE folding this serve into served_max
            regress=jnp.where(serve & (s.b_ver < s.served_max),
                              1, s.regress),
            served_max=jnp.where(
                serve, jnp.maximum(s.served_max, s.b_ver), s.served_max
            ),
            ack_ver=jnp.where(ackin & (peers == src),
                              jnp.maximum(s.ack_ver, f[0]), s.ack_ver),
            r_seen=jnp.where(respin, jnp.maximum(s.r_seen, f[0]),
                             s.r_seen),
            # fsync-before-ack: mint and apply both hit the disk plane
            syncs=s.syncs + (do_repl | apply).astype(jnp.int32),
            serves=s.serves + serve.astype(jnp.int32),
        )

        # ============== merged outbox (E = N rows) ====================
        # REPL broadcasts on rows 1..N-1; single-message events (READ,
        # ACK, RESP) put the payload in outbox ROW dst
        bcast = do_repl
        single = do_read | apply | serve
        s_dst = jnp.where(do_read, target, src)
        s_kind = jnp.where(do_read, READ, jnp.where(apply, ACK, RESP))
        s_a = jnp.where(do_read, 0, jnp.where(apply, f[0], s.b_ver))
        s_b = jnp.where(serve, s.b_val, 0)
        at_row = peers == s_dst  # [N]

        def row(a, b):
            return jnp.stack([
                jnp.asarray(a, jnp.int32), jnp.asarray(b, jnp.int32),
                jnp.int32(0),
            ])

        out = Outbox(
            valid=jnp.where(bcast, peers != 0, single & at_row),
            dst=jnp.where(
                bcast, peers,
                jnp.where(single, jnp.full((N,), 1, jnp.int32) * s_dst, 0),
            ),
            kind=jnp.where(
                bcast, REPL, jnp.where(single, s_kind, 0)
            ) * jnp.ones((N,), jnp.int32),
            payload=jnp.where(
                jnp.reshape(bcast, (1, 1)),
                row(new_ver, new_val)[None, :],
                jnp.where(
                    (single & at_row)[:, None], row(s_a, s_b)[None, :], 0,
                ),
            ),
        )

        # primary re-arms every tick (draw >= tick_us: the rate floor);
        # backups stay unarmed; message events keep their deadline
        timer_t = jnp.where(
            is_primary,
            now + prng.randint(key, 92, tick_us, 2 * tick_us),
            now + jnp.int32(IDLE_FAR),
        )
        return state, out, jnp.where(is_timer, timer_t, jnp.int32(-1))

    def restart_timer(s, nid, now, key):
        return jnp.where(
            nid == 0,
            now + tick_us + prng.randint(key, 94, 0, tick_us),
            now + jnp.int32(IDLE_FAR),
        )

    def check_invariants(ns, alive, now):
        # monotone reads per replica, detected locally by each backup:
        # the sticky flag is the violation. No cross-node join — wipes
        # and disk rollbacks reset/rewind the whole plane together, so
        # the CORRECT spec holds under every chaos axis.
        return (ns.regress[1:] == 0).all()

    def lane_metrics(node):
        return {
            "mean_primary_ver": node.ver[:, 0].astype(jnp.float32),
            "mean_backup_ver": (
                node.b_ver[:, 1:].astype(jnp.float32).mean(-1)
            ),
            "regressed_lanes": (node.regress[:, 1:] > 0).any(-1),
        }

    return {
        "on_event": on_event,
        "first_timer": first_timer,
        "restart_timer": restart_timer,
        "check_invariants": check_invariants,
        "lane_metrics": lane_metrics,
    }


def _workload(spec, p, virtual_secs, loss_rate):
    # the bug's axes: duplicates and reorder (plus loss to create the
    # version gaps stale re-applies land in); plain crash/restart rides
    # along to prove the durable plane keeps the invariant wipe-safe
    return SimConfig(
        horizon_us=int(virtual_secs * 1e6),
        **pool_kw_for(
            spec,
            fused=dict(msg_depth_msg=2, msg_spare_slots=2),
            two_handler=dict(msg_depth_msg=2, msg_depth_timer=2),
        ),
        loss_rate=loss_rate,
        crash_interval_lo_us=500_000,
        crash_interval_hi_us=2_000_000,
        restart_delay_lo_us=200_000,
        restart_delay_hi_us=900_000,
        nem_dup_rate=0.1,
        # the window must span several REPL gaps (a mint every
        # tick..2*tick, REPL on ~60% of fires => ~100_000 us apart):
        # a reordered stale REPL has to land AFTER a newer apply for
        # the planted guard to regress b_ver
        nem_reorder_rate=0.25,
        nem_reorder_window_us=250_000,
    )


PROTOCOL = Protocol(
    name="backup",
    messages=("REPL", "ACK", "READ", "RESP"),
    payload_width=PAYLOAD_WIDTH,
    params=dict(
        n_nodes=5,
        tick_us=40_000,
        repl_rate=0.6,
        buggy=False,
    ),
    fields=_fields,
    body=_body,
    fused=True,
    max_out=lambda p: p.n_nodes,
    disk=DiskPlane(
        fields=("ver", "val", "b_ver", "b_val", "served_max", "regress"),
        sync_field="syncs",
    ),
    buggy_param="buggy",
    workload=_workload,
    doc="primary-backup log shipping with monotone-read replicas",
)
