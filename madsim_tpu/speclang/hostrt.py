"""The speclang host backend: a generic host-runtime twin.

The hand-written `workloads/<x>_host.py` twins re-implement each
protocol as bespoke coroutines and hope review keeps the two faces
agreeing. The speclang twin closes that gap structurally: it runs the
SAME compiled handler bodies the device face runs — `spec.on_message` /
`spec.on_timer` from `device.build(proto)`, jitted once — as one
breakpointable task per node over the host runtime's simulated network
(`net.Endpoint` raw datagrams, so loss/delay/dup come from the runtime,
not the engine). There is no second implementation to drift.

Per-node event loop = the device contract, verbatim:
  * wait for a datagram until the node's timer deadline; deliver it via
    `on_message` (a negative returned timer KEEPS the deadline),
  * on deadline, fire `on_timer` (a negative returned timer DISARMS),
  * send every valid outbox row as a raw datagram to its destination.

Chaos mirrors the hand twins: host-native kill/restart (durable state
survives through `spec.on_restart`; a wipe fraction rebuilds from
`spec.init` — the membership epoch), or NemesisDriver plan mode
(`plan=`) with `on_wipe` doing the rebuild. The oracle is the spec's
own `check_invariants`, stacked over the per-node states by a periodic
checker task — the same function, same masks, as the device face.

`fuzz_one_seed(proto, seed, ...)` is the debugging-microscope entry
the generated `<x>_host.py` modules re-export with the protocol bound.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

import madsim_tpu as ms
from ..net import Endpoint, NetSim
from ..tpu import prng
from . import device
from .lang import Protocol

_PORT = 7900
_TAG = 0
WIPE_FRAC = 0.5  # host-native chaos: fraction of restarts that wipe
CHECK_EVERY = 0.05  # virtual seconds between invariant sweeps


class InvariantViolation(AssertionError):
    pass


# one compiled twin kit per (protocol, overrides): the spec build plus
# its jitted handlers — a fuzz sweep over many seeds compiles once
_KITS: dict = {}


class _TwinKit:
    def __init__(self, proto: Protocol, overrides: dict):
        self.proto = proto
        self.spec = device.build(proto, **overrides)
        self.n_nodes = self.spec.n_nodes
        self.payload_width = self.spec.payload_width
        self.on_message = jax.jit(self.spec.on_message)
        self.on_timer = jax.jit(self.spec.on_timer)
        self.check = jax.jit(self.spec.check_invariants)

    def init(self, key, nid):
        return self.spec.init(key, jnp.int32(nid))

    def restart(self, state, nid, now_us, key):
        return self.spec.on_restart(state, jnp.int32(nid),
                                    jnp.int32(now_us), key)


def kit_for(proto: Protocol, **overrides) -> _TwinKit:
    key = (id(proto), tuple(sorted(overrides.items())))
    if key not in _KITS:
        _KITS[key] = _TwinKit(proto, overrides)
    return _KITS[key]


class _TwinNode:
    """One node: the device state + timer deadline, driven by events."""

    def __init__(self, kit: _TwinKit, nid: int, seed: int,
                 addrs: List[str], born_us: int):
        self.kit = kit
        self.nid = nid
        self.seed = seed
        self.addrs = addrs
        self._draws = 0
        state, first = kit.init(self._key(), nid)
        self.state = state
        # init's deadline is an offset from the node's birth (a fresh
        # wipe-join init starts its clock at the join, like the engine)
        self.timer: Optional[int] = born_us + int(first)

    def _key(self):
        # a private deterministic key chain per (seed, node, draw):
        # the twin needs determinism, not the engine's lane key stream
        self._draws += 1
        return prng.fold(
            prng.fold(jnp.uint32(self.seed), self.nid + 1),
            self._draws,
        )

    def apply_restart(self, now_us: int) -> None:
        state, t = self.kit.restart(self.state, self.nid, now_us,
                                    self._key())
        self.state = state
        self.timer = int(t)

    async def _deliver(self, out) -> None:
        valid = np.asarray(out.valid)
        dst = np.asarray(out.dst)
        kind = np.asarray(out.kind)
        payload = np.asarray(out.payload)
        for row in np.nonzero(valid)[0]:
            d = int(dst[row])
            msg = (int(kind[row]), tuple(int(x) for x in payload[row]))
            try:
                await self.ep.send_to_raw(
                    (self.addrs[d], _PORT), _TAG, msg
                )
            except (OSError, ms.sync.ChannelClosed):
                pass

    async def run(self) -> None:
        self.ep = await Endpoint.bind(f"{self.addrs[self.nid]}:{_PORT}")
        t = ms.time.current()
        while True:
            now_us = int(t.elapsed() * 1e6)
            if self.timer is not None and self.timer <= now_us:
                st, out, nt = self.kit.on_timer(
                    self.state, jnp.int32(self.nid), jnp.int32(now_us),
                    self._key(),
                )
                self.state = st
                nt = int(nt)
                self.timer = nt if nt >= 0 else None  # negative disarms
                await self._deliver(out)
                continue
            wait = (
                (self.timer - now_us) / 1e6 if self.timer is not None
                else 3600.0
            )
            try:
                data, frm = await ms.time.timeout(
                    wait, self.ep.recv_from_raw(_TAG)
                )
            except ms.time.TimeoutError_:
                continue  # the timer branch fires on the next pass
            except (OSError, ms.sync.ChannelClosed):
                return
            kind, vals = data
            src = self.addrs.index(frm[0])
            now_us = int(t.elapsed() * 1e6)
            st, out, nt = self.kit.on_message(
                self.state, jnp.int32(self.nid), jnp.int32(src),
                jnp.int32(kind), jnp.asarray(vals, jnp.int32),
                jnp.int32(now_us), self._key(),
            )
            self.state = st
            nt = int(nt)
            if nt >= 0:  # negative keeps the deadline on a message
                self.timer = nt
            await self._deliver(out)


def _check_now(kit: _TwinKit, cns: list, alive: list, now_us: int):
    ns = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[c.state for c in cns]
    )
    ok = kit.check(ns, jnp.asarray(alive), jnp.int32(now_us))
    if not bool(ok):
        raise InvariantViolation(
            f"{kit.spec.name}: check_invariants failed at t={now_us}us "
            "on the host twin (same oracle as the device face)"
        )


def _state_digest(c: "_TwinNode") -> tuple:
    return tuple(
        int(np.asarray(leaf).astype(np.int64).sum())
        for leaf in c.state
    )


async def _fuzz_body(
    kit: _TwinKit,
    seed: int,
    virtual_secs: float,
    chaos: bool,
    plan=None,
    occ_off=None,
) -> dict:
    handle = ms.Handle.current()
    n = kit.n_nodes
    addrs = [f"10.0.9.{i + 1}" for i in range(n)]
    cns: list = [None] * n
    alive = [True] * n
    t = ms.time.current()

    def make_node(i: int, wipe: bool) -> _TwinNode:
        now_us = int(t.elapsed() * 1e6)
        old = cns[i]
        if old is None or wipe:
            fresh = _TwinNode(kit, i, seed, addrs, born_us=now_us)
        else:
            fresh = old
            fresh.apply_restart(now_us)
        cns[i] = fresh
        return fresh

    nodes = []
    if plan is not None:
        def make_init(i: int):
            def _init():
                # plan-mode wipes route through on_wipe (below), which
                # marks the slot; init rebuilds accordingly
                return make_node(i, wipe=cns[i] is None).run()

            return _init

        for i in range(n):
            node = (
                handle.create_node()
                .name(f"{kit.spec.name}-{i}")
                .ip(addrs[i])
                .init(make_init(i))
                .build()
            )
            nodes.append(node)
    else:
        for i in range(n):
            node = handle.create_node().name(
                f"{kit.spec.name}-{i}"
            ).ip(addrs[i]).build()
            node.spawn(make_node(i, wipe=True).run())
            nodes.append(node)

    async def chaos_task() -> None:
        while True:
            await ms.time.sleep(0.5 + ms.rand() * 1.5)
            victim = ms.randrange(n)
            alive[victim] = False
            handle.kill(nodes[victim].id)
            await ms.time.sleep(0.3 + ms.rand() * 0.6)
            wipe = ms.rand() < WIPE_FRAC
            if wipe:
                cns[victim] = None
            fresh = make_node(victim, wipe=wipe)
            alive[victim] = True
            handle.restart(nodes[victim].id)
            nodes[victim].spawn(fresh.run())

    if chaos and plan is None:
        ms.spawn(chaos_task())

    driver = None
    if plan is not None:
        from .. import nemesis as nem

        def on_wipe(i: int) -> None:
            cns[i] = None

        driver = nem.NemesisDriver(
            plan,
            handle,
            node_ids=[nd.id for nd in nodes],
            horizon_us=int(virtual_secs * 1e6),
            seed=seed,
            on_wipe=on_wipe,
            occ_off=occ_off,
        )
        driver.install()

    end = t.elapsed() + virtual_secs
    checks = 0
    while t.elapsed() < end:
        await ms.time.sleep(CHECK_EVERY)
        if all(c is not None for c in cns):
            _check_now(kit, cns, alive, int(t.elapsed() * 1e6))
            checks += 1
    stats = {
        "checks": checks,
        "events": ms.plugin.simulator(NetSim).stat().msg_count,
        "state": [_state_digest(c) if c is not None else None
                  for c in cns],
    }
    if driver is not None:
        stats["nemesis"] = {
            "applied": list(driver.applied),
            "occ_fired": dict(driver.occ_fired),
            "node_skew": dict(getattr(handle.time, "node_skew", {}) or {}),
            "node_ids": [nd.id for nd in nodes],
            "coins": driver.coins,
            "fires": driver.fire_counts(),
            "state": stats["state"],
        }
    return stats


def fuzz_one_seed(
    proto: Protocol,
    seed: int,
    n_nodes: Optional[int] = None,
    virtual_secs: float = 10.0,
    loss_rate: float = 0.1,
    chaos: bool = True,
    buggy: bool = False,
    plan=None,
    occ_off=None,
    lineage: bool = False,  # accepted for twin-runner parity; unused
) -> dict:
    """One complete fuzzed host execution of a speclang protocol,
    verified by the spec's own invariant. Raises InvariantViolation."""
    overrides = {}
    if n_nodes is not None:
        overrides["n_nodes"] = n_nodes
    if buggy:
        if proto.buggy_param is None:
            raise ValueError(f"{proto.name}: no planted-bug param declared")
        overrides[proto.buggy_param] = True
    kit = kit_for(proto, **overrides)
    cfg = ms.Config()
    cfg.net.packet_loss_rate = loss_rate
    rt = ms.Runtime(seed=seed, config=cfg)
    return rt.block_on(
        _fuzz_body(kit, seed, virtual_secs, chaos, plan=plan,
                   occ_off=occ_off)
    )
