"""Speclang generated modules — checked in, never hand-edited.

Every `<x>_device.py` / `<x>_host.py` here is emitted from the single
spec source `speclang/specs/<x>.py` by `python -m madsim_tpu.speclang
emit`, carries the source file's sha256 as `SPECLANG_DIGEST`, and is
drift-checked by `emit --check` (wired into `make speclang-smoke`) and
the workload-registry mirror lint. The workload registry's generated
rows (`twopc-gen`, `lease-gen`, `backup`) point at these modules."""
