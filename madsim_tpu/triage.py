"""Triage: batched shrinking of violating seeds into minimal repro bundles.

`run_batch` finds violating seeds by the thousand; before this module every
one of them was triaged BY HAND — re-run the seed, stare at the trace, guess
which of the fault plan's many clauses actually mattered (docs/bugs_found.md
is explicit about it). Mature DST stacks close that loop automatically:
FoundationDB-style simulators and TigerBeetle's VOPR ship QuickCheck-style
delta-debugging that reduces a failure to a minimal schedule. This is that
loop for the batched engine, built on the one property the nemesis subsystem
guarantees everywhere: fault draws are PURE in (seed, clause site, occurrence
index), so suppressing one fault never perturbs another's time, victim or
side.

Shrinking is ddmin over three axes:

  (a) CLAUSES and individual clause OCCURRENCES — each schedule-level fault
      window (crash k, split k, clog k, spike k) and each message-level
      clause (loss, dup, reorder, skew, wipe) is one ddmin atom;
  (b) TIME HORIZON — the engine records `first_violation_step` /
      violation time per lane, and every candidate runs with its horizon
      truncated just past the baseline violation, so the final bundle's
      horizon is bisected down to the earliest violating instant;
  (c) RATES — surviving message-level clauses are re-tried at reduced
      rates (the coin is `u < rate * scale`, so a scaled lane's fire set
      is a strict subset of the full run's).

The batching trick: shrink candidates are evaluated as LANES of one
dispatch. `BatchedSim(..., triage=True)` threads a per-lane `TriageCtl`
(clause bitmask, occurrence bitmasks, rate scales, per-lane horizon) through
the jitted step, so one compiled program evaluates a whole ddmin generation
— a full shrink costs a handful of device dispatches, not a re-run per
candidate.

The output is a portable JSON `ReproBundle` (seed, shrunk plan, full
`SimConfig.to_toml`, config hash, violation step/time, ctl spec, trace tail)
replayable by `python -m madsim_tpu.repro bundle.json [--backend host|tpu]`.
See docs/triage.md.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .nemesis import (
    CLAUSE_OF_EVENT,
    ClockSkew,
    Crash,
    DiskFault,
    Duplicate,
    FaultPlan,
    LatencySpike,
    LinkClog,
    MsgLoss,
    OCC_CLAUSES,
    OCC_ROW,
    Partition,
    RATE_CLAUSES,
    RATE_ROW,
    Reconfig,
    Reorder,
    TRIAGE_BIT,
    TRIAGE_CLAUSES,
    filter_schedule,
)

# v2 adds the campaign provenance fields (signature/campaign/generation —
# see madsim_tpu/campaign.py); v3 the optional causal digest (the
# violation's minimal happens-before slice, madsim_tpu/causal.py). Older
# bundles read back with the newer fields defaulted — replay is unchanged.
BUNDLE_FORMAT = "madsim-tpu-repro/3"
BUNDLE_FORMATS_READ = (
    "madsim-tpu-repro/1", "madsim-tpu-repro/2", BUNDLE_FORMAT,
)

# an atom is (clause_name, occurrence k | None); k=None means the whole
# clause (message-level clauses, skew, wipe, and legacy chaos knobs)
Atom = Tuple[str, Optional[int]]

_CLAUSE_TYPES = {
    "crash": Crash, "partition": Partition, "clog": LinkClog,
    "spike": LatencySpike, "skew": ClockSkew, "loss": MsgLoss,
    "dup": Duplicate, "reorder": Reorder, "reconfig": Reconfig,
    "disk": DiskFault,
}


class NotReproducible(AssertionError):
    """The seed did not violate under the full configuration — nothing to
    shrink (wrong workload/config for this seed, or a nondeterminism bug
    upstream, which check_determinism exists to catch)."""


# --------------------------------------------------------------------------
# FaultPlan <-> SimConfig <-> JSON plumbing
# --------------------------------------------------------------------------


def plan_from_config(cfg, name: str = "recovered") -> FaultPlan:
    """Reconstruct the nemesis FaultPlan a SimConfig was compiled from.

    compile_plan is a bijection clause-by-clause, so any nemesis-enabled
    workload is shrinkable without threading the plan object through
    run_batch. Legacy trajectory-coupled knobs (crash_interval_*,
    partition_interval_*) have no plan face — they shrink clause-level via
    the ctl bitmask and ride the bundle's config TOML.
    """
    clauses: list = []
    if cfg.nem_crash_enabled:
        clauses.append(Crash(
            interval_lo_us=cfg.nem_crash_interval_lo_us,
            interval_hi_us=cfg.nem_crash_interval_hi_us,
            down_lo_us=cfg.nem_crash_down_lo_us,
            down_hi_us=cfg.nem_crash_down_hi_us,
            wipe_rate=cfg.nem_crash_wipe_rate,
        ))
    if cfg.nem_partition_enabled:
        clauses.append(Partition(
            interval_lo_us=cfg.nem_partition_interval_lo_us,
            interval_hi_us=cfg.nem_partition_interval_hi_us,
            heal_lo_us=cfg.nem_partition_heal_lo_us,
            heal_hi_us=cfg.nem_partition_heal_hi_us,
        ))
    if cfg.nem_clog_enabled:
        clauses.append(LinkClog(
            interval_lo_us=cfg.nem_clog_interval_lo_us,
            interval_hi_us=cfg.nem_clog_interval_hi_us,
            heal_lo_us=cfg.nem_clog_heal_lo_us,
            heal_hi_us=cfg.nem_clog_heal_hi_us,
        ))
    if cfg.nem_spike_enabled:
        clauses.append(LatencySpike(
            interval_lo_us=cfg.nem_spike_interval_lo_us,
            interval_hi_us=cfg.nem_spike_interval_hi_us,
            duration_lo_us=cfg.nem_spike_duration_lo_us,
            duration_hi_us=cfg.nem_spike_duration_hi_us,
            extra_us=cfg.nem_spike_extra_us,
        ))
    if cfg.nem_loss_rate > 0:
        clauses.append(MsgLoss(rate=cfg.nem_loss_rate))
    if cfg.nem_dup_enabled:
        clauses.append(Duplicate(rate=cfg.nem_dup_rate))
    if cfg.nem_reorder_rate > 0:
        clauses.append(Reorder(
            rate=cfg.nem_reorder_rate, window_us=cfg.nem_reorder_window_us
        ))
    if cfg.nem_skew_enabled:
        clauses.append(ClockSkew(max_ppm=cfg.nem_skew_max_ppm))
    if cfg.nem_reconfig_enabled:
        clauses.append(Reconfig(
            interval_lo_us=cfg.nem_reconfig_interval_lo_us,
            interval_hi_us=cfg.nem_reconfig_interval_hi_us,
            down_lo_us=cfg.nem_reconfig_down_lo_us,
            down_hi_us=cfg.nem_reconfig_down_hi_us,
        ))
    if cfg.nem_disk_enabled:
        clauses.append(DiskFault(
            interval_lo_us=cfg.nem_disk_interval_lo_us,
            interval_hi_us=cfg.nem_disk_interval_hi_us,
            slow_lo_us=cfg.nem_disk_slow_lo_us,
            slow_hi_us=cfg.nem_disk_slow_hi_us,
            down_lo_us=cfg.nem_disk_down_lo_us,
            down_hi_us=cfg.nem_disk_down_hi_us,
            torn_rate=cfg.nem_disk_torn_rate,
            extra_us=cfg.nem_disk_extra_us,
        ))
    return FaultPlan(clauses=tuple(clauses), name=name)


def plan_to_json(plan: FaultPlan) -> dict:
    return {
        "name": plan.name,
        "clauses": [
            {"type": type(c).__name__, **dataclasses.asdict(c)}
            for c in plan.clauses
        ],
    }


def plan_from_json(doc: dict) -> FaultPlan:
    by_name = {cls.__name__: cls for cls in _CLAUSE_TYPES.values()}
    clauses = []
    for c in doc.get("clauses", []):
        kw = dict(c)
        cls = by_name[kw.pop("type")]
        clauses.append(cls(**kw))
    return FaultPlan(clauses=tuple(clauses), name=doc.get("name", "bundle"))


def shrink_plan(
    plan: FaultPlan, dropped: Sequence[str], rate_scale: Dict[str, float],
) -> FaultPlan:
    """The human/host-twin face of a shrink outcome: dropped clauses
    removed, surviving message rates scaled down (occurrence masks live
    beside the plan — see ReproBundle.occ_off / nemesis.filter_schedule)."""
    dropped = set(dropped)
    out = []
    for c in plan.clauses:
        name = next(n for n, cls in _CLAUSE_TYPES.items() if isinstance(c, cls))
        if name in dropped:
            continue
        if isinstance(c, Crash) and "wipe" in dropped and c.wipe_rate > 0:
            c = dataclasses.replace(c, wipe_rate=0.0)
        if name in RATE_CLAUSES and rate_scale.get(name, 1.0) != 1.0:
            c = dataclasses.replace(c, rate=c.rate * rate_scale[name])
        out.append(c)
    return FaultPlan(clauses=tuple(out), name=f"{plan.name}-shrunk")


def build_ctl(
    L: int,
    horizon_us: int,
    off_clauses: Sequence[str] = (),
    occ_off: Optional[Dict[str, int]] = None,
    rate_scale: Optional[Dict[str, float]] = None,
):
    """A uniform TriageCtl (every lane identical) — the repro-replay shape."""
    import jax.numpy as jnp
    import numpy as np

    from .tpu.engine import default_ctl

    ctl = default_ctl(L, horizon_us)
    off = 0
    for name in off_clauses:
        off |= TRIAGE_BIT[name]
    occ = np.zeros((L, len(OCC_CLAUSES)), np.int32)
    for name, mask in (occ_off or {}).items():
        occ[:, OCC_ROW[name]] = mask
    rs = np.ones((L, len(RATE_CLAUSES)), np.float32)
    for name, s in (rate_scale or {}).items():
        rs[:, RATE_ROW[name]] = s
    return ctl._replace(
        off=jnp.full((L,), off, jnp.int32),
        occ=jnp.asarray(occ),
        rate_scale=jnp.asarray(rs),
    )


# --------------------------------------------------------------------------
# the repro bundle
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ReproBundle:
    """A portable, self-describing repro of one shrunk violation.

    `config_toml` is the FULL compiled SimConfig the shrinker ran under —
    shapes and draw layouts must match the verified candidate exactly, so
    dropped clauses are expressed through the ctl fields
    (`dropped_clauses` / `occ_off` / `rate_scale`), never by removing
    their knobs from the config. `plan` is the shrunk FaultPlan for human
    reading and the host schedule twin.
    """

    seed: int
    spec_ref: Optional[str]  # "module:factory" rebuilding the ProtocolSpec
    spec_kwargs: Dict[str, Any]
    spec_name: str
    n_nodes: int
    config_toml: str
    config_hash: str
    violation_kind: str  # "invariant"
    violation_step: int  # first violating step (run-to-step truncation)
    violation_t_us: int  # absolute virtual time of the violation
    dropped_clauses: List[str]
    occ_off: Dict[str, int]
    rate_scale: Dict[str, float]
    horizon_us: int  # bisected: just past the violation
    max_steps: int
    plan: dict  # shrunk FaultPlan (plan_to_json)
    trace_tail: List[str]
    format: str = BUNDLE_FORMAT
    # -- v2: campaign provenance (None on bundles shrunk outside a
    # campaign, and on every v1 bundle read back) --
    signature: Optional[str] = None  # campaign.bug_signature dedup key
    campaign: Optional[str] = None  # producing campaign id
    generation: Optional[int] = None  # explorer generation that surfaced it
    # -- v3: optional causal digest (causal.causal_digest of the shrunk
    # candidate's violation slice: canonical labels, cone stats, label
    # sha). None on bundles shrunk without `causal=True` and on every
    # v1/v2 bundle read back; `repro --explain` recomputes the slice and
    # cross-checks the sha when present. --
    causal: Optional[Dict[str, Any]] = None

    # -- serialization --

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2) + "\n"

    @staticmethod
    def from_json(text: str) -> "ReproBundle":
        doc = json.loads(text)
        fmt = doc.get("format", "")
        if fmt not in BUNDLE_FORMATS_READ:
            raise ValueError(
                f"unsupported bundle format {fmt!r} "
                f"(want one of {list(BUNDLE_FORMATS_READ)})"
            )
        fields = {f.name for f in dataclasses.fields(ReproBundle)}
        unknown = set(doc) - fields
        if unknown:
            raise ValueError(f"unknown bundle fields: {sorted(unknown)}")
        # v1 bundles predate the campaign provenance fields; the dataclass
        # defaults (None) fill them in. The format string is kept as read —
        # it records what wrote the file, not what loaded it.
        return ReproBundle(**doc)

    def stamp(
        self, signature: str, campaign: Optional[str] = None,
        generation: Optional[int] = None,
    ) -> "ReproBundle":
        """Attach campaign provenance (the dedup signature and where it
        came from) in place; the caller re-saves. Returns self."""
        self.signature = signature
        self.campaign = campaign
        self.generation = generation
        return self

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_json())
        return path

    @staticmethod
    def load(path: str) -> "ReproBundle":
        with open(path) as f:
            return ReproBundle.from_json(f.read())

    # -- replay plumbing --

    def ctl(self, L: int = 1):
        """The TriageCtl that replays exactly the verified candidate."""
        return build_ctl(
            L, self.horizon_us, self.dropped_clauses, self.occ_off,
            self.rate_scale,
        )

    def config(self):
        from .tpu.spec import simconfig_from_toml

        cfg = simconfig_from_toml(self.config_toml)
        if cfg.hash() != self.config_hash:
            raise ValueError(
                "bundle config hash mismatch: the TOML was edited or the "
                f"SimConfig schema drifted ({cfg.hash()} != {self.config_hash})"
            )
        return cfg

    def shrunk_plan(self) -> FaultPlan:
        return plan_from_json(self.plan)

    def repro_command(self, path: str) -> str:
        return f"python -m madsim_tpu.repro {path}"


# --------------------------------------------------------------------------
# batched ddmin
# --------------------------------------------------------------------------


def ddmin(
    atoms: List[Atom],
    batch_violates: Callable[[List[List[Atom]]], List[bool]],
) -> List[Atom]:
    """Zeller/Hildebrandt ddmin, with every generation's candidate subsets
    AND complements evaluated by ONE `batch_violates` call (one batched
    device dispatch). Returns a 1-minimal kept-set: the result violates,
    and removing any single atom from it does not.
    """
    cur = list(atoms)
    if not cur:
        return cur
    if len(cur) == 1:
        # the only generation ddmin proper never tests: nothing at all
        if batch_violates([[]])[0]:
            return []
        return cur
    n = 2
    while len(cur) >= 2:
        chunk = -(-len(cur) // n)
        subsets = [cur[i:i + chunk] for i in range(0, len(cur), chunk)]
        cands: List[List[Atom]] = list(subsets)
        compl: List[List[Atom]] = []
        if len(subsets) > 2:
            compl = [
                [a for s in (subsets[:i] + subsets[i + 1:]) for a in s]
                for i in range(len(subsets))
            ]
        res = batch_violates(cands + compl)
        hit = next((i for i, r in enumerate(res[: len(cands)]) if r), None)
        if hit is not None:
            cur = cands[hit]
            n = 2
            continue
        chit = next((i for i, r in enumerate(res[len(cands):]) if r), None)
        if chit is not None:
            cur = compl[chit]
            n = max(n - 1, 2)
            continue
        if n >= len(cur):
            break
        n = min(len(cur), 2 * n)
    return cur


# --------------------------------------------------------------------------
# the shrinker
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ShrinkResult:
    bundle: ReproBundle
    bundle_path: Optional[str]
    dispatches: int  # batched device evaluations the whole shrink cost
    original_atoms: int
    kept_atoms: List[Atom]

    @property
    def repro_command(self) -> str:
        if self.bundle_path:
            return self.bundle.repro_command(self.bundle_path)
        return f"seed={self.bundle.seed} (bundle not written)"


class _Eval:
    """Evaluates shrink candidates as lanes of one batched dispatch."""

    def __init__(
        self, sim, seed: int, max_steps: int, lane_width: int,
        refill: bool = True, mesh=None,
    ):
        import jax.numpy as jnp  # noqa: F401  (device backend required)

        self.sim = sim
        self.seed = int(seed)
        self.max_steps = int(max_steps)
        self.lane_width = max(2, int(lane_width))
        self.refill = bool(refill)
        # multi-chip ddmin (r10, docs/multichip.md): with a mesh, each
        # refill generation's candidate queue is partitioned into
        # per-device sub-queues and evaluated as ONE shard_map'd sweep —
        # verdicts stay bit-identical (pure per-(seed, ctl) rows), the
        # generation just spreads over the fleet. Only the refill path
        # shards; an explicit mesh must never be silently dropped.
        if mesh is not None and not self.refill:
            raise ValueError(
                "shrink mesh requires the refill evaluator (refill=True): "
                "the chunked ddmin path has no sharded form — drop the "
                "mesh or keep refill on"
            )
        self.mesh = mesh
        self.dispatches = 0

    def _rows_ctl(self, rows):
        """One TriageCtl with a row per candidate (the refill queue)."""
        import jax.numpy as jnp
        import numpy as np

        from .tpu.engine import TriageCtl
        from .tpu.spec import REBASE_US

        return TriageCtl(
            off=jnp.asarray(np.asarray([r[0] for r in rows], np.int32)),
            occ=jnp.asarray(np.asarray([r[1] for r in rows], np.int32)),
            rate_scale=jnp.asarray(
                np.asarray([r[2] for r in rows], np.float32)
            ),
            h_epoch=jnp.asarray(
                np.asarray([r[3] // REBASE_US for r in rows], np.int32)
            ),
            h_off=jnp.asarray(
                np.asarray([r[3] % REBASE_US for r in rows], np.int32)
            ),
        )

    def _run_refill(
        self, rows: List[Tuple[int, List[int], List[float], int]]
    ) -> List[Dict[str, int]]:
        """The continuous-batching generation (r9): every candidate is an
        ADMISSION of one refill sweep over `lane_width` lanes — a lane
        whose candidate violates (or hits its bisected horizon) retires
        and admits the next candidate in-jit, so a generation is one
        always-full engine run instead of padded chunks all running to
        the longest candidate's horizon. Per-candidate verdicts are
        bit-identical to the chunked path (pure per-(seed, ctl) rows)."""
        import numpy as np

        from . import telemetry
        from .tpu.engine import refill_results, refill_results_sharded
        from .tpu.spec import REBASE_US

        A = len(rows)
        # pad the QUEUE to a lane_width multiple with replays of row 0
        # (results discarded): the refill program's shapes are (lanes,
        # queue length), so bucketing queue lengths keeps the number of
        # compiled programs per shrink at O(distinct buckets), like the
        # chunked path's fixed lane_width padding
        pad = (-A) % self.lane_width
        rows_p = rows + [rows[0]] * pad
        seeds = np.full((len(rows_p),), self.seed, np.uint32)
        with telemetry.span("dispatch", site="shrink", candidates=A):
            if self.mesh is not None:
                st = self.sim.run_refill_sharded(
                    seeds, lanes=self.lane_width, mesh=self.mesh,
                    max_steps=self.max_steps, ctl=self._rows_ctl(rows_p),
                )
                self.dispatches += 1
                res = refill_results_sharded(st, admissions=len(rows_p))
            else:
                st = self.sim.run_refill(
                    seeds, lanes=self.lane_width,
                    max_steps=self.max_steps, ctl=self._rows_ctl(rows_p),
                )
                self.dispatches += 1
                res = refill_results(st)
        t_us = (
            res["violation_epoch"].astype(np.int64) * REBASE_US
            + res["violation_at"].astype(np.int64)
        )
        return [
            {
                "violated": bool(res["violated"][i]),
                "step": int(res["violation_step"][i]),
                "t_us": int(t_us[i]) if res["violated"][i] else -1,
            }
            for i in range(A)
        ]

    def run(
        self, rows: List[Tuple[int, List[int], List[float], int]]
    ) -> List[Dict[str, int]]:
        """rows: (off_bits, occ_masks[4], rate_scales[3], horizon_us) per
        candidate. Returns per-candidate {violated, step, t_us}. With
        `refill` (the default) the whole generation runs as admissions of
        one continuously batched sweep (`_run_refill`). The chunked
        fallback pads rows to `lane_width` so every generation reuses ONE
        compiled program; oversized generations chunk into several
        dispatches, double-buffered like run_batch's chunk loop — chunk
        k+1's device program is dispatched before the host decodes chunk
        k's violation scalars (legal: every candidate of one generation
        is independent), so the host decode overlaps device time instead
        of serializing."""
        import numpy as np

        from . import telemetry
        from .tpu.spec import REBASE_US

        if self.refill:
            return self._run_refill(rows)
        out: List[Dict[str, int]] = []

        def dispatch(lo: int):
            part = rows[lo:lo + self.lane_width]
            n = len(part)
            pad = self.lane_width - n
            # pad lanes replay the first candidate; results are discarded
            part = part + [part[0]] * pad
            ctl = self._rows_ctl(part)
            seeds = np.full((self.lane_width,), self.seed, np.uint32)
            with telemetry.span("dispatch", site="shrink", candidates=n):
                state = self.sim.run(
                    seeds, max_steps=self.max_steps, ctl=ctl
                )
            self.dispatches += 1
            return n, state

        def decode(entry) -> None:
            n, state = entry
            violated = np.asarray(state.violated)
            step = np.asarray(state.violation_step)
            t_us = (
                np.asarray(state.violation_epoch, np.int64) * REBASE_US
                + np.asarray(state.violation_at, np.int64)
            )
            for i in range(n):
                out.append({
                    "violated": bool(violated[i]),
                    "step": int(step[i]),
                    "t_us": int(t_us[i]) if violated[i] else -1,
                })

        from .tpu.batch import pipelined

        pipelined(range(0, len(rows), self.lane_width), dispatch, decode)
        return out


def _atom_rows(
    kept: Sequence[Atom], all_atoms: Sequence[Atom], horizon_us: int,
    rate_scale: Optional[Dict[str, float]] = None,
    extra_occ: Optional[Dict[str, int]] = None,
) -> Tuple[int, List[int], List[float], int]:
    """One candidate row: every atom NOT in `kept` is suppressed.

    `extra_occ` (clause -> occurrence bitmask) is ORed in unconditionally —
    a base candidate's suppressions must hold in every row even when the
    vocabulary collapsed that clause to a single clause-level atom (>31
    occurrences), where no per-occurrence atom exists to carry them."""
    kept_set = set(kept)
    off = 0
    occ = [0] * len(OCC_CLAUSES)
    for atom in all_atoms:
        if atom in kept_set:
            continue
        name, k = atom
        if k is None:
            off |= TRIAGE_BIT[name]
        else:
            occ[OCC_ROW[name]] |= 1 << k
    for name, mask in (extra_occ or {}).items():
        occ[OCC_ROW[name]] |= int(mask)
    rs = [1.0] * len(RATE_CLAUSES)
    for name, s in (rate_scale or {}).items():
        rs[RATE_ROW[name]] = float(s)
    return (off, occ, rs, int(horizon_us))


def enumerate_atoms(
    plan: FaultPlan, cfg, seed: int, horizon_us: int, n_nodes: int,
    max_occ: int = 31,
) -> List[Atom]:
    """The ddmin universe for one (plan, seed, horizon).

    Schedule clauses contribute one atom per occurrence whose window OPENS
    inside the horizon (pure — read off `plan.schedule`, no device run);
    clauses with more than `max_occ` occurrences fall back to a single
    clause-level atom. Occurrence bits live in an int32 mask whose sign
    bit (bit 31) is unusable, so indices >= 31 also force the fallback.
    Message clauses, skew, wipe and legacy chaos knobs are clause-level
    atoms.
    """
    atoms: List[Atom] = []
    occ_of: Dict[str, set] = {}
    for ev in plan.schedule(seed, horizon_us, n_nodes):
        clause = CLAUSE_OF_EVENT.get(ev.kind)
        if clause in OCC_ROW and ev.k >= 0:
            occ_of.setdefault(clause, set()).add(ev.k)
    for clause in OCC_CLAUSES:
        ks = sorted(occ_of.get(clause, ()))
        if not ks:
            continue
        if len(ks) > max_occ or max(ks) >= 31:
            atoms.append((clause, None))
        else:
            atoms.extend((clause, k) for k in ks)
    if plan.get(MsgLoss) is not None:
        atoms.append(("loss", None))
    if plan.get(Duplicate) is not None:
        atoms.append(("dup", None))
    if plan.get(Reorder) is not None:
        atoms.append(("reorder", None))
    if plan.get(ClockSkew) is not None:
        atoms.append(("skew", None))
    crash = plan.get(Crash)
    if crash is not None and crash.wipe_rate > 0:
        atoms.append(("wipe", None))
    # legacy trajectory-coupled knobs: clause-level only (no pure schedule)
    if cfg.chaos_enabled:
        atoms.append(("crash", None))
    if cfg.partition_enabled:
        atoms.append(("partition", None))
    return atoms


def shrink_seed(
    workload,
    seed: int,
    out_dir: Optional[str] = None,
    spec_ref: Optional[str] = None,
    spec_kwargs: Optional[Dict[str, Any]] = None,
    slack_us: int = 2_000,
    lane_width: Optional[int] = None,
    rate_steps: Sequence[float] = (0.5, 0.25),
    trace_tail: int = 40,
    sim=None,
    log: Optional[Callable[[str], None]] = None,
    base_ctl: Optional[Dict[str, Any]] = None,
    refill: bool = True,
    mesh=None,
    causal: bool = False,
    tuning: Any = None,
) -> ShrinkResult:
    """Shrink one violating seed of a BatchWorkload into a ReproBundle.

    `base_ctl` shrinks WITHIN a candidate's suppression set instead of the
    full plan: keys `off_clauses` (names), `occ_off` (clause -> occurrence
    bitmask), `rate_scale` (clause -> factor), `horizon_us`. The baseline
    lane replays exactly that candidate (the explorer's mutants violate
    under ctl masks the full plan may not reproduce — a bug REQUIRING a
    suppressed heal is invisible to a full-plan baseline), ddmin minimizes
    the surviving atoms, and every suppression the base carries stays in
    the bundle's ctl, so the bundle replays the shrunk candidate exactly.

    Pipeline (each numbered item is ONE batched dispatch unless noted):

      1. baseline — the full plan AND the empty plan as two lanes of one
         run; the full lane must violate (else NotReproducible), and its
         violation time bisects the horizon for everything after;
      2..k. ddmin generations over clause/occurrence atoms, every
         generation one dispatch (subsets + complements as lanes);
      k+1. optional rate-reduction probe for surviving message clauses
         (one dispatch for the scale grid, one to confirm the combination);
      k+2. final confirmation under the exact bundle ctl (also re-reads
         the final violation step/time the bundle records).

    The trace tail is captured with a separate single-lane traced run of
    the final candidate (the microscope, not a shrink dispatch). `sim`
    accepts a pre-built `BatchedSim(spec, config, triage=True)` so a test
    suite can amortize one compile across many shrinks.
    """
    from .tpu.batch import BatchWorkload  # noqa: F401  (doc pointer)
    from .tpu.engine import BatchedSim
    from .tpu.spec import SimConfig

    say = log or (lambda msg: None)
    spec = workload.spec
    cfg = workload.config or SimConfig()
    if tuning is not None:
        # Tier-A only (docs/tuning.md): the tuned refill lane width sizes
        # the ddmin evaluator's generation dispatches. Result-invariant —
        # a shrink's verdicts (and hence its bundle) are bit-identical at
        # any lane_width, which the triage width-matrix tests already pin.
        # The lookup is at the DDMIN scale (lane_width's bucket, l16 by
        # default), deliberately not the 32k sweep bucket `make tune`
        # populates: knobs do not transfer across scale (that is why lane
        # buckets exist), so a hit requires a tuner run at ddmin scale
        # (e.g. `python -m madsim_tpu.tune --lanes 16`); a miss runs the
        # hand-pinned default width.
        from . import tune as _tune

        tn = _tune.resolve_tuning(tuning, spec.name, cfg, lane_width or 16)
        if tn.get("refill_lanes") and lane_width is None:
            lane_width = int(tn["refill_lanes"])
    if lane_width is None:
        lane_width = 16
    if sim is None:
        sim = BatchedSim(spec, cfg, triage=True)
    elif not sim.triage:
        raise ValueError("shrink_seed needs a BatchedSim(..., triage=True)")
    # refill=True (default): each ddmin generation runs as admissions of
    # one continuously batched sweep — the engine refills lanes whose
    # candidates finished early instead of padding chunks to lane_width
    # and running every lane to the longest candidate's horizon. Verdicts
    # are bit-identical either way (tested); refill=False keeps the
    # chunked reference path. `mesh` spreads each refill generation's
    # candidate queue over the device fleet as one shard_map'd sweep
    # (docs/multichip.md); verdicts — and therefore bundles — are
    # bit-identical to the single-device shrink (tested).
    ev = _Eval(
        sim, seed, workload.max_steps, lane_width, refill=refill, mesh=mesh,
    )
    plan = plan_from_config(cfg)
    base_ctl = base_ctl or {}
    base_off = set(base_ctl.get("off_clauses") or ())
    base_occ: Dict[str, int] = dict(base_ctl.get("occ_off") or {})
    base_rs: Dict[str, float] = dict(base_ctl.get("rate_scale") or {})
    full_h = int(cfg.horizon_us)
    if base_ctl.get("horizon_us"):
        full_h = min(full_h, int(base_ctl["horizon_us"]))

    def _base_on(atom: Atom) -> bool:
        name, k = atom
        if name in base_off:
            return False
        return k is None or not (base_occ.get(name, 0) >> k) & 1

    # -- 1. baseline: the (base-suppressed) plan + empty plan, one dispatch -
    base_atoms = enumerate_atoms(plan, cfg, seed, full_h, spec.n_nodes)
    enabled0 = [a for a in base_atoms if _base_on(a)]
    full_row = _atom_rows(enabled0, base_atoms, full_h, rate_scale=base_rs,
                          extra_occ=base_occ)
    empty_row = _atom_rows([], base_atoms, full_h, rate_scale=base_rs,
                           extra_occ=base_occ)
    base, empty = ev.run([full_row, empty_row])[:2]
    if not base["violated"]:
        raise NotReproducible(
            f"seed {seed} does not violate under the "
            f"{'candidate' if base_ctl else 'full'} configuration "
            f"(horizon {full_h} us) — nothing to shrink"
        )
    trunc_h = min(full_h, base["t_us"] + slack_us)
    say(
        f"baseline: violation at step {base['step']}, t={base['t_us']}us; "
        f"horizon truncated {full_h} -> {trunc_h}us"
    )

    # -- 2..k. ddmin over the truncated-horizon atom universe ---------------
    if empty["violated"]:
        # the protocol violates with no chaos at all: the minimal plan is
        # empty and the empty lane's own violation bisects the horizon.
        # The suppression universe stays base_atoms so the confirmation
        # (and the bundle ctl) really runs chaos-free.
        all_atoms: List[Atom] = list(base_atoms)
        universe: List[Atom] = list(enabled0)
        kept: List[Atom] = []
        trunc_h = min(full_h, empty["t_us"] + slack_us)
    else:
        # `all_atoms` is the suppression vocabulary at the truncated
        # horizon; ddmin searches only the base-enabled subset, so base
        # suppressions stay suppressed in every candidate row
        all_atoms = enumerate_atoms(plan, cfg, seed, trunc_h, spec.n_nodes)
        universe = [a for a in all_atoms if _base_on(a)]

        def batch_violates(cands: List[List[Atom]]) -> List[bool]:
            rows = [
                _atom_rows(c, all_atoms, trunc_h, rate_scale=base_rs,
                           extra_occ=base_occ)
                for c in cands
            ]
            res = ev.run(rows)
            say(
                f"ddmin generation: {len(cands)} candidates -> "
                f"{sum(r['violated'] for r in res)} violating"
            )
            return [r["violated"] for r in res]

        kept = ddmin(universe, batch_violates)
    say(f"ddmin: {len(universe)} atoms -> {len(kept)} kept: {kept}")

    # -- k+1. rate reduction for surviving message clauses ------------------
    # (clauses the base already scaled are left at the base scale: probing
    # them at the grid's scales could INCREASE fires past the candidate's)
    kept_clauses = {name for name, _ in kept}
    rate_scale: Dict[str, float] = {}
    rate_targets = [
        n for n in RATE_CLAUSES if (n, None) in kept and n not in base_rs
    ]
    if rate_targets and rate_steps:
        grid: List[Tuple[str, float]] = [
            (n, s) for n in rate_targets for s in rate_steps
        ]
        res = ev.run([
            _atom_rows(kept, all_atoms, trunc_h,
                       rate_scale={**base_rs, n: s}, extra_occ=base_occ)
            for n, s in grid
        ])
        for n in rate_targets:
            best = min(
                (s for (gn, s), r in zip(grid, res)
                 if gn == n and r["violated"]),
                default=1.0,
            )
            if best < 1.0:
                rate_scale[n] = best
    final: Optional[Dict[str, int]] = None
    if rate_targets and rate_steps and rate_scale:
        # scales probed one clause at a time; the combination must be
        # re-confirmed (falls back to full rates if it stops violating).
        # A confirmed combination row is byte-identical to the final
        # confirmation below, so it doubles as it — one dispatch saved.
        ok = ev.run([
            _atom_rows(kept, all_atoms, trunc_h,
                       rate_scale={**base_rs, **rate_scale},
                       extra_occ=base_occ)
        ])[0]
        if ok["violated"]:
            final = ok
        else:
            rate_scale = {}
    if rate_targets:
        say(f"rate reduction: {rate_scale or 'none'}")

    # -- k+2. final confirmation under the exact bundle ctl -----------------
    if final is None:
        final = ev.run([
            _atom_rows(kept, all_atoms, trunc_h,
                       rate_scale={**base_rs, **rate_scale},
                       extra_occ=base_occ)
        ])[0]
    assert final["violated"], "shrunk candidate must still violate"
    final_h = min(trunc_h, final["t_us"] + slack_us)

    # the bundle's ctl spec: everything in the vocabulary minus the kept
    # set (base suppressions merge in here — a clause or occurrence the
    # candidate already dropped lands in dropped/occ_off like any other)
    dropped = sorted({name for name, _ in all_atoms} - kept_clauses)
    occ_off: Dict[str, int] = {}
    for name, k in all_atoms:
        if k is not None and (name, k) not in kept and name in kept_clauses:
            occ_off[name] = occ_off.get(name, 0) | (1 << k)
    # base occurrence suppressions on clauses that survive must stay in the
    # bundle even when the vocabulary had no per-occurrence atom to carry
    # them (the >31-occurrence clause-level fallback)
    for name, mask in base_occ.items():
        if name not in dropped and mask:
            occ_off[name] = occ_off.get(name, 0) | int(mask)
    rate_scale = {
        n: s for n, s in {**base_rs, **rate_scale}.items()
        if n in kept_clauses
    }

    # -- trace tail: single-lane microscope of the final candidate ----------
    tail: List[str] = []
    if trace_tail > 0:
        from .tpu.trace import trace_seed

        events = trace_seed(
            sim, seed, max_steps=max(final["step"] + 2, 64),
            kind_names=spec.msg_kind_names,
            ctl=build_ctl(1, final_h, dropped, occ_off, rate_scale),
        )
        tail = [str(e) for e in events[-trace_tail:]]

    bundle = ReproBundle(
        seed=int(seed),
        spec_ref=spec_ref,
        spec_kwargs=dict(spec_kwargs or {}),
        spec_name=spec.name,
        n_nodes=spec.n_nodes,
        config_toml=cfg.to_toml(),
        config_hash=cfg.hash(),
        violation_kind="invariant",
        violation_step=final["step"],
        violation_t_us=final["t_us"],
        dropped_clauses=list(dropped),
        occ_off=occ_off,
        rate_scale=rate_scale,
        horizon_us=int(final_h),
        max_steps=int(workload.max_steps),
        plan=plan_to_json(shrink_plan(plan, dropped, rate_scale)),
        trace_tail=tail,
    )
    if causal:
        # optional causal digest (bundle schema v3): one extra
        # single-lane LINEAGE-enabled traced replay of the final
        # candidate — the violation's minimal happens-before slice,
        # canonicalized so cross-witness anatomy can align it
        # (docs/causality.md). Separate sim: the lineage plane changes
        # the carry structure, so the shrink dispatches above never pay
        # for it.
        from . import causal as causal_mod

        _, sl = causal_mod.explain(
            spec, cfg, int(seed),
            ctl=build_ctl(1, final_h, dropped, occ_off, rate_scale),
            max_steps=max(final["step"] + 2, 64),
        )
        bundle.causal = causal_mod.causal_digest(sl)
    path = None
    if out_dir is not None:
        os.makedirs(out_dir, exist_ok=True)
        # the config hash keys the name: concurrent runs of the same spec
        # under different configs must not overwrite each other's bundles
        path = os.path.join(
            out_dir,
            f"repro_{spec.name}_{cfg.hash()}_seed{int(seed)}.json",
        )
        bundle.save(path)
    say(
        f"shrunk seed {seed}: {len(base_atoms)} atoms -> {len(kept)} in "
        f"{ev.dispatches} dispatches; bundle {path or '(unsaved)'}"
    )
    result = ShrinkResult(
        bundle=bundle,
        bundle_path=path,
        dispatches=ev.dispatches,
        original_atoms=len(base_atoms),
        kept_atoms=kept,
    )
    from . import telemetry

    if telemetry.enabled():
        # shrink progress (atoms remaining, dispatch cost) at the host
        # boundary — the sweep/ddmin work above is already complete
        telemetry.record_shrink(result, workload=spec.name, seed=int(seed))
        if bundle.causal is not None:
            telemetry.record_causal(bundle.causal, workload=spec.name)
    return result


def default_bundle_dir() -> str:
    """Where run_batch drops bundles unless told otherwise (per-uid, like
    the jax compilation cache dir: a shared path would leave second users
    unable to write)."""
    uid = os.getuid() if hasattr(os, "getuid") else "all"
    return os.environ.get(
        "MADSIM_TRIAGE_DIR",
        os.path.join(tempfile.gettempdir(), f"madsim_tpu_repros-{uid}"),
    )
