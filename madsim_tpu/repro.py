"""Replay a triage repro bundle: `python -m madsim_tpu.repro bundle.json`.

The counterpart of `madsim_tpu/triage.py`: a bundle is only worth shipping
in a bug report if a fresh process — with no access to the sweep that found
it — replays the violation bit-deterministically. This module is that
check, as a library (`replay`) and a CLI:

    python -m madsim_tpu.repro bundle.json                 # device replay
    python -m madsim_tpu.repro bundle.json --backend host  # schedule twin
    python -m madsim_tpu.repro bundle.json --trace 60      # + event tail

Device replay (`--backend tpu`, the default) rebuilds the ProtocolSpec from
the bundle's `spec_ref`, the SimConfig from its TOML (hash-checked), runs
the seed under the bundle's shrink ctl TWICE, asserts the two final states
are bitwise identical, and asserts the violation fires at the recorded
step and virtual time.

Host replay (`--backend host`) drives the bundle's SHRUNK FaultPlan through
a fresh host runtime's NemesisDriver (idle nodes; the schedule needs no
traffic) and asserts the applied fault stream equals the occurrence-filtered
pure schedule — the twin invariant, surviving the shrink.

Divergence bundles (`violation_kind == "divergence"`, written by
madsim_tpu/oracle.py) are inherently differential, so EVERY backend choice
routes to the oracle replay: the shrunk plan re-runs schedule-matched on
the host twin `--repeats` times, each run must reproduce the SAME first
divergent event bit-identically (same site/index/applied/expected, same
state digest), and the bundle's v3 `causal` digest is cross-checked
against the replayed host slice. A reproduced divergence prints the
readable first-divergent-event report and the CLI exits NON-ZERO — the
two backends still disagree, which is a live bug, not a clean replay.
"""

from __future__ import annotations

import argparse
import importlib
import os
import sys
from typing import Any, Dict, List, Optional

from .triage import ReproBundle


class ReplayError(AssertionError):
    """The bundle did not replay as recorded."""


def resolve_spec(spec_ref: str, spec_kwargs: Optional[Dict[str, Any]] = None):
    """Rebuild a ProtocolSpec from a dotted "module:factory" reference."""
    mod_name, _, fn_name = spec_ref.partition(":")
    if not mod_name or not fn_name:
        raise ValueError(
            f"spec_ref must look like 'package.module:factory', got {spec_ref!r}"
        )
    # bundles written inside a checkout reference test modules by their
    # repo-relative dotted path; make the common case work from anywhere.
    # Remove the exact entry we added (not pop(0)): the spec module's own
    # import may mutate sys.path, and a positional pop would evict it.
    cwd = os.getcwd()
    sys.path.insert(0, cwd)
    try:
        mod = importlib.import_module(mod_name)
    finally:
        try:
            sys.path.remove(cwd)
        except ValueError:
            pass
    return getattr(mod, fn_name)(**(spec_kwargs or {}))


def _configure_jax_cache() -> None:
    """Persistent XLA cache (same location as the test suite): a repro run
    in a fresh process should pay seconds, not a cold compile."""
    try:
        import jax
    except ImportError:
        return
    if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        jax.config.update(
            "jax_compilation_cache_dir",
            f"/tmp/madsim_tpu_jaxcache-{os.getuid()}",
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2.0)


def replay_device(
    bundle: ReproBundle,
    spec=None,
    repeats: int = 2,
    trace: int = 0,
    perfetto: Optional[str] = None,
    explain: int = 0,
    out=print,
) -> Dict[str, Any]:
    """Device replay: the violation must fire at the recorded step/time,
    bit-identically across `repeats` runs. Returns a report dict.

    `trace=N` prints the last N trace events; `perfetto=PATH` additionally
    writes the FULL replayed trajectory as a Chrome-trace/Perfetto
    timeline (madsim_tpu.telemetry.write_perfetto) — one track per node,
    deliveries as src→dst flow arrows, chaos windows as slices, the
    violation as an instant marker. `explain=N` replays the bundle once
    more with the causal-lineage plane on (BatchedSim(lineage=True)) and
    prints the last N links of the violation's minimal causal slice —
    the chain of deliveries/timer fires the violation transitively
    depends on (docs/causality.md); when the bundle carries a v3 causal
    digest, the replayed slice's label sha is cross-checked against it
    (schema drift fails loudly, like the config hash)."""
    _configure_jax_cache()
    import jax
    import numpy as np

    from .tpu.engine import BatchedSim
    from .tpu.spec import REBASE_US

    if spec is None:
        if not bundle.spec_ref:
            raise ReplayError(
                "bundle has no spec_ref — pass the ProtocolSpec explicitly "
                "(replay_device(bundle, spec=...)) or re-emit the bundle "
                "with shrink_seed(spec_ref=...)"
            )
        spec = resolve_spec(bundle.spec_ref, bundle.spec_kwargs)
    if spec.n_nodes != bundle.n_nodes:
        raise ReplayError(
            f"spec has {spec.n_nodes} nodes, bundle recorded {bundle.n_nodes}"
        )
    cfg = bundle.config()  # hash-checked
    sim = BatchedSim(spec, cfg, triage=True)
    ctl = bundle.ctl(1)
    states = [
        sim.run([bundle.seed], max_steps=bundle.max_steps, ctl=ctl)
        for _ in range(max(1, repeats))
    ]
    a = states[0]
    for i, b in enumerate(states[1:], start=2):
        la = jax.tree_util.tree_leaves(a)
        lb = jax.tree_util.tree_leaves(b)
        for j, (x, y) in enumerate(zip(la, lb)):
            if not np.array_equal(np.asarray(x), np.asarray(y)):
                raise ReplayError(
                    f"replay {i} diverged from replay 1 at state leaf {j} — "
                    "the device stream is not bit-deterministic"
                )
    violated = bool(np.asarray(a.violated)[0])
    step = int(np.asarray(a.violation_step)[0])
    t_us = int(
        np.asarray(a.violation_epoch, np.int64)[0] * REBASE_US
        + np.asarray(a.violation_at, np.int64)[0]
    )
    if not violated:
        raise ReplayError(
            f"seed {bundle.seed} did NOT violate under the bundle's shrunk "
            "configuration — stale bundle or schema drift"
        )
    if step != bundle.violation_step or t_us != bundle.violation_t_us:
        raise ReplayError(
            f"violation replayed at step {step} / t={t_us}us but the bundle "
            f"recorded step {bundle.violation_step} / "
            f"t={bundle.violation_t_us}us"
        )
    if trace > 0 or perfetto:
        from .tpu.trace import trace_seed

        events = trace_seed(
            sim, bundle.seed, max_steps=step + 2,
            kind_names=spec.msg_kind_names, ctl=ctl,
        )
        for e in events[-trace:] if trace > 0 else []:
            out(str(e))
        if perfetto:
            from . import telemetry

            telemetry.write_perfetto(
                perfetto, events, n_nodes=spec.n_nodes,
                label=f"{bundle.spec_name} seed {bundle.seed}",
            )
            out(f"perfetto timeline: {perfetto}")
    rep = {"violated": True, "step": step, "t_us": t_us, "repeats": repeats}
    if explain > 0:
        from . import causal

        g, sl = causal.explain(
            spec, cfg, bundle.seed, ctl=ctl, max_steps=step + 2,
        )
        digest = causal.causal_digest(sl)
        tail = (
            causal.causal_slice(g, max_len=explain)
            if len(sl.chain) > explain else sl
        )
        out(causal.format_slice(tail))
        if bundle.causal is not None and (
            bundle.causal.get("sha") != digest["sha"]
        ):
            raise ReplayError(
                "causal slice diverged from the bundle's recorded digest "
                f"({digest['sha']} != {bundle.causal.get('sha')}) — the "
                "lineage plane or the slice semantics drifted"
            )
        rep["causal"] = digest
    out(
        f"device replay OK: seed {bundle.seed} violates at step {step}, "
        f"t={t_us}us, bit-identical across {max(1, repeats)} runs"
    )
    if bundle.signature:
        # campaign provenance (bundle schema v2): the dedup signature keys
        # this bug class across seeds/campaigns — docs/campaign.md
        provenance = ""
        if bundle.campaign is not None:
            provenance = f" (campaign {bundle.campaign}"
            if bundle.generation is not None:
                provenance += f", generation {bundle.generation}"
            provenance += ")"
        out(f"bug signature: {bundle.signature}{provenance}")
        rep["signature"] = bundle.signature
    return rep


def replay_host(bundle: ReproBundle, out=print) -> Dict[str, Any]:
    """Host schedule twin: a fresh runtime's NemesisDriver applies exactly
    the shrunk plan's occurrence-filtered pure schedule."""
    import madsim_tpu as ms
    from .nemesis import NemesisDriver, filter_schedule

    plan = bundle.shrunk_plan()
    horizon_us = int(bundle.horizon_us)
    n = int(bundle.n_nodes)

    async def body():
        handle = ms.Handle.current()

        async def idle():
            while True:
                await ms.time.sleep(3600.0)

        nodes = [
            handle.create_node().name(f"r{i}").ip(f"10.9.9.{i + 1}")
            .init(idle).build()
            for i in range(n)
        ]
        driver = NemesisDriver(
            plan, handle, [nd.id for nd in nodes], horizon_us=horizon_us,
            seed=bundle.seed, occ_off=bundle.occ_off,
        )
        driver.install()
        t = ms.time.current()
        end = t.elapsed() + horizon_us / 1e6 + 0.001
        while t.elapsed() < end:
            await ms.time.sleep(0.05)
        return driver

    rt = ms.Runtime(seed=bundle.seed)
    driver = rt.block_on(body())
    want = [
        e for e in filter_schedule(
            plan.schedule(bundle.seed, horizon_us, n), bundle.occ_off
        )
        if e.kind != "skew"  # applied at install time, not replayed
    ]
    got = list(driver.applied)
    if got != want:
        raise ReplayError(
            "host driver stream diverged from the shrunk pure schedule:\n"
            f"  want ({len(want)}): {[str(e) for e in want]}\n"
            f"  got  ({len(got)}): {[str(e) for e in got]}"
        )
    out(
        f"host schedule twin OK: {len(want)} shrunk fault events applied "
        "exactly as scheduled"
    )
    return {"events": len(want)}


def replay_divergence(
    bundle: ReproBundle, repeats: int = 2, out=print,
) -> Dict[str, Any]:
    """Replay a host/device divergence bundle (madsim_tpu/oracle.py):
    re-run the shrunk plan schedule-matched on the host twin `repeats`
    times and assert the SAME first divergent event reproduces
    bit-identically every time. Raises ReplayError when the lane no
    longer diverges (stale bundle / fixed tree) or when repeats disagree
    (the replay itself is nondeterministic — a worse bug). Returns a
    report with `diverged=True`; callers treat that as a failing exit,
    because a reproduced divergence means the backends still disagree."""
    from . import oracle

    plan = bundle.shrunk_plan()
    horizon_us = int(bundle.horizon_us)
    n = int(bundle.n_nodes)
    loss_rate = 0.1
    if bundle.config_toml:
        loss_rate = float(getattr(bundle.config(), "loss_rate", 0.1))
    repeats = max(1, repeats)
    reps = [
        oracle.check_seed(
            bundle.spec_name, plan, bundle.seed, horizon_us, n_nodes=n,
            loss_rate=loss_rate, occ_off=bundle.occ_off, repeats=1,
        )
        for _ in range(repeats)
    ]
    for i, rep in enumerate(reps, start=1):
        if not rep.diverged:
            raise ReplayError(
                f"replay {i}: seed {bundle.seed} did NOT diverge under the "
                "bundle's shrunk plan — stale bundle, or the host/device "
                "skew it recorded has been fixed"
            )

    def ident(r):
        d = r.first
        return (d.kind, d.site, d.index, d.applied, d.expected, d.eid,
                r.digest, len(r.divergences))

    first = reps[0]
    for i, rep in enumerate(reps[1:], start=2):
        if ident(rep) != ident(first):
            raise ReplayError(
                "divergence replay is not bit-deterministic: replay "
                f"{i} reproduced {ident(rep)} but replay 1 gave "
                f"{ident(first)}"
            )
    d = first.first
    if bundle.causal is not None and d.slice_digest is not None and (
        bundle.causal.get("sha") != d.slice_digest.get("sha")
    ):
        raise ReplayError(
            "host causal slice diverged from the bundle's recorded digest "
            f"({d.slice_digest.get('sha')} != {bundle.causal.get('sha')}) — "
            "the lineage plane or the slice semantics drifted"
        )
    out(first.render())
    out(
        f"divergence reproduced bit-identically across {repeats} "
        "schedule-matched host replays — the backends still disagree"
    )
    return {
        "diverged": True,
        "repeats": repeats,
        "first": d.to_dict(),
        "digest": first.digest,
    }


def replay(
    bundle: ReproBundle, backend: str = "tpu", spec=None, repeats: int = 2,
    trace: int = 0, perfetto: Optional[str] = None, explain: int = 0,
    out=print,
) -> Dict[str, Any]:
    if bundle.violation_kind == "divergence":
        # differential by construction: there is no single-backend replay
        # of a host-vs-device divergence, so tpu/host/both all route here
        return replay_divergence(bundle, repeats=repeats, out=out)
    if backend == "tpu":
        return replay_device(
            bundle, spec=spec, repeats=repeats, trace=trace,
            perfetto=perfetto, explain=explain, out=out,
        )
    if backend == "host":
        return replay_host(bundle, out=out)
    if backend == "both":
        rep = replay_device(
            bundle, spec=spec, repeats=repeats, trace=trace,
            perfetto=perfetto, explain=explain, out=out,
        )
        rep.update(replay_host(bundle, out=out))
        return rep
    raise ValueError(f"unknown backend {backend!r} (tpu|host|both)")


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m madsim_tpu.repro",
        description="Replay a triage repro bundle and assert the violation "
        "still fires (see docs/triage.md).",
    )
    p.add_argument("bundle", help="path to a repro bundle JSON")
    p.add_argument(
        "--backend", choices=("tpu", "host", "both"), default="tpu",
        help="tpu: replay the violation on the batched engine; host: assert "
        "the shrunk plan's schedule twin on the host runtime",
    )
    p.add_argument(
        "--spec-ref", default=None,
        help="override the bundle's 'module:factory' ProtocolSpec reference",
    )
    p.add_argument(
        "--repeats", type=int, default=2,
        help="device replays to compare bitwise (default 2)",
    )
    p.add_argument(
        "--trace", type=int, default=0, metavar="N",
        help="print the last N trace events of the replayed violation",
    )
    p.add_argument(
        "--perfetto", nargs="?", const="", default=None, metavar="PATH",
        help="write the replayed trajectory as a Chrome-trace/Perfetto "
        "timeline; with no PATH it lands next to the bundle "
        "(<bundle>.perfetto.json). Device replay only.",
    )
    p.add_argument(
        "--explain", nargs="?", const=20, type=int, default=0, metavar="N",
        help="replay once more with the causal-lineage plane on and print "
        "the last N links (default 20) of the violation's minimal causal "
        "slice — the happens-before chain it depends on (docs/causality"
        ".md). Cross-checks the bundle's v3 causal digest when present. "
        "Device replay only.",
    )
    args = p.parse_args(argv)
    bundle = ReproBundle.load(args.bundle)
    if args.spec_ref:
        bundle.spec_ref = args.spec_ref
    perfetto = args.perfetto
    if perfetto == "":
        # default: next to the bundle, so the timeline ships with it
        root, _ = os.path.splitext(args.bundle)
        perfetto = f"{root}.perfetto.json"
    try:
        rep = replay(
            bundle, backend=args.backend, repeats=args.repeats,
            trace=args.trace, perfetto=perfetto, explain=args.explain,
        )
    except (ReplayError, ValueError) as e:
        print(f"REPLAY FAILED: {e}", file=sys.stderr)
        return 1
    if rep.get("diverged"):
        # the divergence reproduced — that's a live host-vs-device bug,
        # so the CLI fails even though the replay itself succeeded
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
