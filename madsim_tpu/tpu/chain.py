"""Chain Replication — the fifth device fuzz protocol.

A fifth *shape* (raft: symmetric replicated log; kv: primary/backup quorum
rounds; twopc: asymmetric one-shot commit; paxos: ballot duels): a FIXED
LINEAR TOPOLOGY 0 (head) -> N-1 (tail) where writes enter at the head,
propagate hop by hop with per-hop acks and retransmission, commit when
they reach the tail, and linearizable reads are served AT THE TAIL only
(van Renesse & Schneider, OSDI'04). Written with `fuse_two_handlers` per
docs/authoring_protocol_specs.md — the guide's "the next protocol is an
afternoon" claim, exercised a second time.

Protocol:

  * Every node is also a client (like tpu/kv.py): writes go to the HEAD
    (WREQ), reads to the TAIL (RREQ); one outstanding client op per node
    with timeout + retry.
  * The head assigns a per-key monotone version (vnext, durable) and
    APPLIES + forwards (FWD) down the chain. Each node holds ONE
    outstanding forward slot, retransmitting on its tick until the
    DOWNSTREAM hop-ack (HACK) clears it; a node accepts a FWD only when
    its own slot is free (upstream retransmission covers the refusal).
    Apply-if-newer makes redelivery idempotent.
  * The tail applies, hop-acks, and sends the commit ack (CACK) straight
    to the writing client. Only tail-applied writes are ever acked —
    that is the whole linearizability argument.
  * Crash/restart: the store, the head's version counter, and the oracle
    memory are durable; the forward slot and client state are volatile.
    A mid-chain crash may therefore LOSE an uncommitted write (its hop
    was acked upstream but not yet forwarded) — safe, because it was
    never tail-acked; the client times out and retries with a FRESH
    version. Liveness, not safety.

Device invariants (per lane, per step):
  * Chain monotonicity: versions never increase downstream —
    kv_ver[i][k] >= kv_ver[i+1][k] for every adjacent pair (writes flow
    strictly head->tail; durable stores preserve this across restarts).
  * Version coherence: two nodes holding the same (key, version>0) hold
    the same value (head-assigned versions are per-key unique).
  * Client-observed monotonicity (the kv-style incremental oracle): each
    node's most recently ACKED client op (la_* register) is checked
    against per-(node,key) acked watermarks — an op invoked after a
    higher version was observable is stale.

The canonical injected bug (`buggy_blind_apply=True`): a replica missing
the apply-if-newer guard applies REDELIVERED forwards unconditionally. A
hop-ack lost to the network makes the upstream retransmit; when the
duplicate arrives late — after newer versions flowed through — the blind
replica rolls its store BACK, and the chain-monotonicity invariant fires
(its downstream neighbor now holds a newer version than it does). Only
message loss + latency jitter make it fire: the redelivery must overtake
a newer write. (`buggy_read_at_head=True` also exists — the dirty-read
bug — but it is deliberately NOT device-catchable: head-assigned
versions are globally monotone, so observing an uncommitted version
violates nothing the per-step oracle can see; catching it takes the
recorded-history Wing-Gong class of check, which is the kv workload's
job. The spec keeps the knob as documentation of that boundary.)
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import prng
from .spec import Outbox, ProtocolSpec, fuse_two_handlers

FWD, HACK, WREQ, RREQ, RRSP, CACK = range(6)
OP_READ, OP_WRITE = 1, 2
PAYLOAD_WIDTH = 5  # (key, val, ver, writer, echo_t)


class ChainState(NamedTuple):
    # replicated store
    kv_val: jnp.ndarray  # i32 [K]               (durable)
    kv_ver: jnp.ndarray  # i32 [K]               (durable)
    vnext: jnp.ndarray  # i32 [K] head's next version per key (durable)
    # the ONE outstanding downstream forward (volatile: a crash may lose
    # an uncommitted write — safe, it was never tail-acked)
    fw_valid: jnp.ndarray  # i32 0|1
    fw_key: jnp.ndarray  # i32
    fw_val: jnp.ndarray  # i32
    fw_ver: jnp.ndarray  # i32
    fw_writer: jnp.ndarray  # i32
    fw_echo: jnp.ndarray  # i32 the writer's invocation-time echo (rides
    # the whole chain so the tail's CACK can match the client's request)
    fw_t: jnp.ndarray  # i32 last (re)transmit time    (volatile)
    # client side (volatile)
    creq_kind: jnp.ndarray  # i32 0=none
    creq_key: jnp.ndarray  # i32
    creq_t: jnp.ndarray  # i32
    ccount: jnp.ndarray  # i32                   (durable)
    # oracle memory (durable — a crash must not amnesty a violation):
    # per-key max version this node ever observed in an ACKED client op,
    # with the time it became observable; plus the kv-style most-recently
    # acked op register for incremental checking
    wm_ver: jnp.ndarray  # i32 [K]
    wm_t: jnp.ndarray  # i32 [K]
    la_kind: jnp.ndarray  # i32 0=none
    la_key: jnp.ndarray  # i32
    la_ver: jnp.ndarray  # i32
    la_tinv: jnp.ndarray  # i32


def make_chain_spec(
    n_nodes: int = 5,
    n_keys: int = 4,
    tick_us: int = 20_000,
    retx_us: int = 60_000,
    req_timeout_us: int = 300_000,
    client_rate: float = 0.6,
    write_frac: float = 0.5,
    buggy_read_at_head: bool = False,
    buggy_blind_apply: bool = False,
) -> ProtocolSpec:
    N, K = n_nodes, n_keys
    assert N >= 3
    peers = jnp.arange(N, dtype=jnp.int32)
    kidx = jnp.arange(K, dtype=jnp.int32)
    HEAD, TAIL = 0, N - 1

    # ------------------------------------------------------------------ init

    def init(key, nid):
        z = jnp.int32(0)
        state = ChainState(
            kv_val=jnp.zeros((K,), jnp.int32),
            kv_ver=jnp.zeros((K,), jnp.int32),
            vnext=jnp.ones((K,), jnp.int32),
            fw_valid=z, fw_key=z, fw_val=z, fw_ver=z, fw_writer=z,
            fw_echo=z, fw_t=z,
            creq_kind=z, creq_key=z, creq_t=z,
            ccount=jnp.int32(1),
            wm_ver=jnp.zeros((K,), jnp.int32),
            wm_t=jnp.zeros((K,), jnp.int32),
            la_kind=z, la_key=z, la_ver=z, la_tinv=z,
        )
        return state, prng.randint(key, 50, 0, tick_us)

    # ----------------------------------------------------------------- timer

    def on_timer(s: ChainState, nid, now, key):
        is_tail = nid == TAIL
        # retransmit the pending forward to the next hop
        retx = (s.fw_valid > 0) & ~is_tail & (now - s.fw_t > retx_us)
        # client: expire a stuck request, maybe issue a new one
        req_expired = (s.creq_kind > 0) & (now - s.creq_t > req_timeout_us)
        creq_kind = jnp.where(req_expired, 0, s.creq_kind)
        issue = (creq_kind == 0) & (prng.uniform(key, 51) < client_rate)
        is_write = prng.uniform(key, 52) < write_frac
        op_kind = jnp.where(is_write, OP_WRITE, OP_READ)
        op_key = prng.randint(key, 53, 0, K)
        op_val = jnp.where(is_write, nid * 100_000 + s.ccount, 0)
        read_target = HEAD if buggy_read_at_head else TAIL

        state = s._replace(
            fw_t=jnp.where(retx, now, s.fw_t),
            creq_kind=jnp.where(issue, op_kind, creq_kind),
            creq_key=jnp.where(issue, op_key, s.creq_key),
            creq_t=jnp.where(issue, now, s.creq_t),
            ccount=s.ccount + (issue & is_write).astype(jnp.int32),
        )
        # row 0: the retransmitted FWD; row 1: the client op
        fwd_pay = jnp.stack([s.fw_key, s.fw_val, s.fw_ver, s.fw_writer,
                             s.fw_echo])
        req_pay = jnp.stack([op_key, op_val, jnp.int32(0), nid, now])
        out = Outbox(
            valid=jnp.stack([retx, issue]),
            dst=jnp.stack([
                jnp.minimum(nid + 1, N - 1),
                jnp.where(issue & is_write, HEAD, read_target).astype(
                    jnp.int32
                ),
            ]),
            kind=jnp.stack([
                jnp.int32(FWD),
                jnp.where(issue & is_write, WREQ, RREQ).astype(jnp.int32),
            ]),
            payload=jnp.stack([fwd_pay, req_pay]),
        )
        return state, out, now + tick_us

    # --------------------------------------------------------------- message

    def on_message(s: ChainState, nid, src, kind, payload, now, key):
        f = payload
        is_fwd = kind == FWD
        is_hack = kind == HACK
        is_wreq = kind == WREQ
        is_rreq = kind == RREQ
        is_rrsp = kind == RRSP
        is_cack = kind == CACK
        is_head = nid == HEAD
        is_tail = nid == TAIL
        at_k = kidx == f[0]  # [K]

        # -- WREQ (head only): assign a fresh per-key version, apply,
        # take the forward slot (drop when busy: client retries)
        w_ok = is_wreq & is_head & (s.fw_valid == 0) & (f[1] != 0)
        new_ver = (s.vnext * at_k.astype(jnp.int32)).sum()
        w_apply = w_ok & at_k

        # -- FWD: accept iff my slot is free (or I'm the tail, which
        # never forwards); apply-if-newer makes redelivery idempotent
        f_ok = is_fwd & (is_tail | (s.fw_valid == 0))
        if buggy_blind_apply:
            # the planted bug: no apply-if-newer guard — a delayed
            # duplicate rolls the store back
            f_apply = f_ok & at_k
        else:
            f_apply = f_ok & at_k & (f[2] > s.kv_ver)

        # -- HACK from downstream: clear the matching forward
        h_clear = is_hack & (s.fw_valid > 0) & (f[2] == s.fw_ver) & (
            f[0] == s.fw_key
        )

        # -- CACK / RRSP at the client: record the acked op. A read's
        # version comes from the responder (f[2]); match on the echoed
        # invocation time so a stale retransmitted ack can't match a
        # newer request.
        mine = (is_cack | is_rrsp) & (s.creq_kind > 0) & (f[4] == s.creq_t)
        raise_wm = mine & at_k & (f[2] > s.wm_ver)

        take_fw = w_ok | (f_ok & ~is_tail & is_fwd)
        state = s._replace(
            kv_val=jnp.where(
                w_apply, f[1], jnp.where(f_apply, f[1], s.kv_val)
            ),
            kv_ver=jnp.where(
                w_apply, new_ver, jnp.where(f_apply, f[2], s.kv_ver)
            ),
            vnext=jnp.where(w_apply, s.vnext + 1, s.vnext),
            fw_valid=jnp.where(take_fw, 1, jnp.where(h_clear, 0, s.fw_valid)),
            fw_key=jnp.where(take_fw, f[0], s.fw_key),
            fw_val=jnp.where(take_fw, f[1], s.fw_val),
            fw_ver=jnp.where(w_ok, new_ver, jnp.where(take_fw, f[2], s.fw_ver)),
            fw_writer=jnp.where(take_fw, f[3], s.fw_writer),
            fw_echo=jnp.where(take_fw, f[4], s.fw_echo),
            fw_t=jnp.where(take_fw, now, s.fw_t),
            creq_kind=jnp.where(mine, 0, s.creq_kind),
            wm_ver=jnp.where(raise_wm, f[2], s.wm_ver),
            wm_t=jnp.where(raise_wm, now, s.wm_t),
            la_kind=jnp.where(mine, jnp.where(is_cack, OP_WRITE, OP_READ),
                              s.la_kind),
            la_key=jnp.where(mine, f[0], s.la_key),
            la_ver=jnp.where(mine, f[2], s.la_ver),
            la_tinv=jnp.where(mine, s.creq_t, s.la_tinv),
        )

        # -- outbox (2 rows). Row 0: the new FWD downstream (head WREQ or
        # a middle node relaying) OR the read response. Row 1: the hop-ack
        # upstream OR the tail's commit ack to the writer.
        fwd_ver = jnp.where(w_ok, new_ver, f[2])
        serve_read = is_rreq & (is_tail | jnp.bool_(buggy_read_at_head))
        r_val = (s.kv_val * at_k.astype(jnp.int32)).sum()
        r_ver = (s.kv_ver * at_k.astype(jnp.int32)).sum()
        row0_fwd = (w_ok | (f_ok & is_fwd)) & ~is_tail
        row0_valid = row0_fwd | serve_read
        row0_dst = jnp.where(
            serve_read, src, jnp.minimum(nid + 1, N - 1)
        ).astype(jnp.int32)
        row0_kind = jnp.where(serve_read, RRSP, FWD).astype(jnp.int32)
        row0_pay = jnp.where(
            serve_read,
            jnp.stack([f[0], r_val, r_ver, f[3], f[4]]),
            jnp.stack([f[0], f[1], fwd_ver, f[3], f[4]]),
        )
        # hop-ack to upstream when a FWD was accepted; commit ack when
        # the tail accepted (redelivered FWDs re-ack: idempotent at the
        # client thanks to the echoed-creq_t match)
        row1_hack = f_ok & is_fwd
        row1_cack = f_ok & is_fwd & is_tail
        row1_valid = row1_hack | row1_cack
        # the tail emits CACK in row 1 and its HACK rides row 0? No — the
        # tail never forwards, so row 0 is free for its HACK; middle nodes
        # use row 0 for the relay FWD and row 1 for the HACK.
        row0_valid = row0_valid | (row1_hack & is_tail)
        row0_dst = jnp.where(
            row1_hack & is_tail & ~serve_read,
            jnp.maximum(nid - 1, 0), row0_dst,
        ).astype(jnp.int32)
        row0_kind = jnp.where(
            row1_hack & is_tail & ~serve_read, HACK, row0_kind
        ).astype(jnp.int32)
        row0_pay = jnp.where(
            (row1_hack & is_tail & ~serve_read),
            jnp.stack([f[0], jnp.int32(0), f[2], jnp.int32(0), jnp.int32(0)]),
            row0_pay,
        )
        row1_dst = jnp.where(
            row1_cack, f[3], jnp.maximum(nid - 1, 0)
        ).astype(jnp.int32)
        row1_kind = jnp.where(row1_cack, CACK, HACK).astype(jnp.int32)
        row1_pay = jnp.where(
            row1_cack,
            jnp.stack([f[0], f[1], f[2], f[3], f[4]]),
            jnp.stack([f[0], jnp.int32(0), f[2], jnp.int32(0), jnp.int32(0)]),
        )
        out = Outbox(
            valid=jnp.stack([row0_valid, jnp.where(is_tail, row1_cack,
                                                   row1_valid)]),
            dst=jnp.stack([row0_dst, row1_dst]),
            kind=jnp.stack([row0_kind, row1_kind]),
            payload=jnp.stack([row0_pay, row1_pay]),
        )
        return state, out, jnp.int32(-1)

    # --------------------------------------------------------------- restart

    def on_restart(s: ChainState, nid, now, key):
        z = jnp.int32(0)
        state = s._replace(
            fw_valid=z, creq_kind=z,
        )
        return state, now + prng.randint(key, 54, 0, tick_us)

    # ------------------------------------------------------------ invariants

    def check_invariants(ns: ChainState, alive, now):
        # ns leaves are [N, ...] for one lane
        # 1. chain monotonicity: versions never increase downstream
        mono = ~(ns.kv_ver[:-1] < ns.kv_ver[1:]).any()
        # 2. version coherence: same (key, ver>0) => same value
        same_ver = (
            (ns.kv_ver[:, None, :] == ns.kv_ver[None, :, :])
            & (ns.kv_ver[:, None, :] > 0)
        )
        diff_val = ns.kv_val[:, None, :] != ns.kv_val[None, :, :]
        coherent = ~(same_ver & diff_val).any()
        # 3. client-observed monotonicity (incremental register vs
        # watermarks, the kv pattern): an op invoked after some node's
        # higher-version watermark was established is stale
        la_ok = ns.la_kind > 0  # [N]
        key_oh = ns.la_key[:, None, None] == kidx[None, None, :]  # [N,1,K]
        wm_stale = (
            la_ok[:, None, None]
            & key_oh
            & (ns.wm_t[None, :, :] < ns.la_tinv[:, None, None])
            & (ns.wm_ver[None, :, :] > ns.la_ver[:, None, None])
        )
        return mono & coherent & ~wm_stale.any()

    # ------------------------------------------------------------ diagnostics

    def lane_metrics(node):
        return {
            "mean_committed_vers": node.kv_ver[:, -1].sum(-1).astype(
                jnp.float32
            ),
            "mean_acked_like": node.ccount.sum(-1).astype(jnp.float32),
        }

    return fuse_two_handlers(ProtocolSpec(
        name=f"chain{N}",
        n_nodes=N,
        payload_width=PAYLOAD_WIDTH,
        max_out=2,
        max_out_msg=2,
        init=init,
        on_message=on_message,
        on_timer=on_timer,
        on_restart=on_restart,
        check_invariants=check_invariants,
        lane_metrics=lane_metrics,
        msg_kind_names=("FWD", "HACK", "WREQ", "RREQ", "RRSP", "CACK"),
        time_fields=("fw_t", "fw_echo", "creq_t", "wm_t", "la_tinv"),
        # r8 carry compaction (docs/state_layout.md): fw_valid is a bool
        # flag, *_kind ops are {0, OP_READ, OP_WRITE}, fw_writer a node id
        # (< 32 by the engine's packed-plane cap), keys index [0, K).
        # Versions (kv_ver/vnext/fw_ver/wm_ver/la_ver) stay i32: they
        # advance once per committed write per key with no hard cadence
        # floor, and the write-monotonicity oracle compares them — a
        # wrapped version IS a violation, so no latent bound is allowed.
        narrow_fields={
            "fw_valid": jnp.uint8,
            "fw_writer": jnp.uint8,
            "creq_kind": jnp.uint8,
            "la_kind": jnp.uint8,
            **({"fw_key": jnp.uint8, "creq_key": jnp.uint8,
                "la_key": jnp.uint8} if K <= 255 else {}),
        },
        # explicitly declared: every narrowed field is a step-closed
        # flag/id/enum/key — no rate-argument bounds, so the Layer-3
        # range certifier (analysis/ranges.py) must certify this spec
        # trivially (unbounded safe horizon). Versions staying i32 (the
        # monotonicity oracle compares them) is what keeps this table
        # floor-free.
        rate_floors={},
    ))


def chain_workload(n_nodes: int = 5, virtual_secs: float = 10.0,
                   loss_rate: float = 0.1):
    """Chain replication under loss + crash/restart chaos (partitions are
    omitted: a partitioned fixed chain simply stalls — every hop is a
    cut point — so partitions only measure timeout plumbing here). A
    violating seed gets both microscopes: the device trace and the host
    twin (workloads/chain_host.py), verified by the same oracle."""
    from .batch import BatchWorkload
    from .spec import SimConfig, pool_kw_for

    spec = make_chain_spec(n_nodes)

    def host_repro(seed: int):
        from ..workloads import chain_host

        try:
            out = chain_host.fuzz_one_seed(
                seed, n_nodes=n_nodes, virtual_secs=virtual_secs,
                loss_rate=loss_rate,
            )
            out["violations"] = 0
            return out
        except chain_host.InvariantViolation as e:
            return {"violations": 1, "violation": str(e)}
    cfg = SimConfig(
        horizon_us=int(virtual_secs * 1e6),
        **pool_kw_for(
            spec,
            fused=dict(msg_depth_msg=2, msg_spare_slots=2),
            two_handler=dict(msg_depth_msg=2, msg_depth_timer=2),
        ),
        loss_rate=loss_rate,
        crash_interval_lo_us=400_000,
        crash_interval_hi_us=2_000_000,
        restart_delay_lo_us=200_000,
        restart_delay_hi_us=1_000_000,
    )
    return BatchWorkload(spec=spec, config=cfg, host_repro=host_repro)
