"""Single-decree Paxos — the fourth device fuzz protocol.

A fourth *shape* again (tpu/raft.py: symmetric replicated log; tpu/kv.py:
primary/backup quorum rounds; tpu/twopc.py: asymmetric one-shot commit):
ballot-numbered two-phase consensus where EVERY node is proposer, acceptor
and learner at once, and dueling proposers are the steady state rather
than a fault. Written mask-merged from the start per
docs/authoring_protocol_specs.md (this file is also the guide's
"a fourth protocol is an afternoon" claim, made good).

Protocol (the synod, Paxos Made Simple):

  * An undecided node's timer starts a PREPARE round with a fresh unique
    ballot b = round * N + nid; acceptors promise (never going back on a
    higher promise) and report their highest accepted (ballot, value).
  * On a promise majority the proposer enters phase 2 proposing THE
    HIGHEST-BALLOT ACCEPTED VALUE IT SAW — its own candidate value only
    if phase 1 found none (the rule that makes Paxos safe; dropping it is
    this spec's canonical injected bug).
  * Acceptors accept b's value unless already promised higher; on an
    ACCEPTED majority the proposer decides and broadcasts DECIDED;
    learners record it. Decided nodes gossip DECIDED on their timer so
    laggards (crashed through the decision, partitioned minority) learn.
  * Random per-node retry timers break proposer duels (the classic
    livelock); chaos (loss, crashes, partitions, heavy tails) supplies
    the rest of the adversary.

Safety invariant (per lane, per step): AGREEMENT — all recorded decisions
across nodes name one value. (Validity holds by construction: values only
ever originate from proposer candidates or discovered accepteds.)

Durable across crashes: promised / accepted / decided (the acceptor's
stable storage, Paxos' one hard requirement). Volatile: every proposer
bookkeeping field.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import prng
from .spec import Outbox, ProtocolSpec, fuse_two_handlers, majority as majority_of

PREPARE, PROMISE, ACCEPT, ACCEPTED, DECIDED = range(5)
PAYLOAD_WIDTH = 3  # (ballot, value, acc_ballot)


class PaxosState(NamedTuple):
    promised: jnp.ndarray  # i32 highest ballot promised      (durable)
    acc_bal: jnp.ndarray  # i32 accepted ballot, -1 none      (durable)
    acc_val: jnp.ndarray  # i32 accepted value                (durable)
    decided: jnp.ndarray  # i32 decided value, 0 none         (durable)
    # proposer bookkeeping (volatile)
    prop_bal: jnp.ndarray  # i32 my live ballot, -1 none
    prop_phase: jnp.ndarray  # i32 0 idle | 1 preparing | 2 accepting
    prop_val: jnp.ndarray  # i32 value being pushed in phase 2
    best_bal: jnp.ndarray  # i32 highest accepted ballot seen in phase 1
    best_val: jnp.ndarray  # i32 its value
    acks: jnp.ndarray  # i32 bitmask (promises or accepteds for prop_bal)
    round: jnp.ndarray  # i32 ballot round counter            (durable)


def make_paxos_spec(
    n_nodes: int = 5,
    retry_lo_us: int = 150_000,
    retry_hi_us: int = 400_000,
    gossip_us: int = 200_000,
    buggy_ignore_discovered: bool = False,
) -> ProtocolSpec:
    """`buggy_ignore_discovered=True` plants the canonical Paxos mistake:
    phase 2 proposes the proposer's OWN value even when phase 1 discovered
    an accepted one — safe on a calm network, agreement-splitting the
    moment chaos lets two ballots' quorums interleave."""
    N = n_nodes
    peers = jnp.arange(N, dtype=jnp.int32)

    def majority(mask):
        return majority_of(mask, N)

    # ------------------------------------------------------------------ init

    def init(key, nid):
        z = jnp.int32(0)
        state = PaxosState(
            promised=jnp.int32(-1),
            acc_bal=jnp.int32(-1),
            acc_val=z,
            decided=z,
            prop_bal=jnp.int32(-1),
            prop_phase=z,
            prop_val=z,
            best_bal=jnp.int32(-1),
            best_val=z,
            acks=z,
            round=z,
        )
        return state, prng.randint(key, 40, 0, retry_hi_us)

    # ----------------------------------------------------------------- timer

    def on_timer(s: PaxosState, nid, now, key):
        # decided nodes gossip the decision; undecided nodes (re)start a
        # prepare round with a fresh unique ballot — a stale in-flight
        # round is simply abandoned (its ballot can never win against the
        # new one's promises)
        is_decided = s.decided != 0
        new_round = s.round + 1
        bal = new_round * N + nid
        start = ~is_decided
        # THE PROPOSER'S OWN NODE IS AN ACCEPTOR TOO — counting a self
        # promise/acceptance in the quorum without RECORDING it in the
        # acceptor state is the "phantom self-vote" bug this spec shipped
        # with and this framework's own fuzz caught within seconds (5/256
        # lanes; two ACCEPT rounds with different values whose quorums
        # intersected only at the phantom voter — docs/bugs_found.md #8).
        # Self-promise follows the same rule as any acceptor: only if the
        # fresh ballot beats every prior promise, else the round starts
        # without the self vote. Self-DISCOVERY likewise: phase 1 begins
        # from the proposer's own accepted (ballot, value), not from -1.
        self_prom = start & (bal > s.promised)
        state = s._replace(
            promised=jnp.where(self_prom, bal, s.promised),
            prop_bal=jnp.where(start, bal, s.prop_bal),
            prop_phase=jnp.where(start, 1, s.prop_phase),
            prop_val=jnp.where(start, nid * 100_000 + new_round, s.prop_val),
            best_bal=jnp.where(start, s.acc_bal, s.best_bal),
            best_val=jnp.where(start, s.acc_val, s.best_val),
            acks=jnp.where(
                start,
                jnp.where(self_prom, jnp.int32(1) << nid, 0),
                s.acks,
            ),
            round=jnp.where(start, new_round, s.round),
        )
        pay_prep = jnp.stack([bal, jnp.int32(0), jnp.int32(0)])
        pay_dec = jnp.stack([jnp.int32(0), s.decided, jnp.int32(0)])
        out = Outbox(
            valid=peers != nid,
            dst=peers,
            kind=jnp.where(is_decided, DECIDED, PREPARE)
            * jnp.ones((N,), jnp.int32),
            payload=jnp.broadcast_to(
                jnp.where(is_decided, pay_dec, pay_prep)[None, :],
                (N, PAYLOAD_WIDTH),
            ),
        )
        timer = now + jnp.where(
            is_decided,
            gossip_us,
            prng.randint(key, 41, retry_lo_us, retry_hi_us),
        )
        return state, out, timer

    # --------------------------------------------------------------- message

    def on_message(s: PaxosState, nid, src, kind, payload, now, key):
        """All five kinds, mask-merged (see the authoring guide on why:
        a vmapped lax.switch executes every branch)."""
        bal, val, a_bal = payload[0], payload[1], payload[2]
        is_prep = kind == PREPARE
        is_prom = kind == PROMISE
        is_acc = kind == ACCEPT
        is_acd = kind == ACCEPTED
        is_dec = kind == DECIDED

        # -- acceptor, PREPARE: promise iff ballot beats any prior promise
        prep_ok = is_prep & (bal > s.promised)
        # -- acceptor, ACCEPT: accept iff not promised beyond this ballot
        acc_ok = is_acc & (bal >= s.promised)
        promised = jnp.where(
            prep_ok | acc_ok, jnp.maximum(s.promised, bal), s.promised
        )
        acc_bal = jnp.where(acc_ok, bal, s.acc_bal)
        acc_val = jnp.where(acc_ok, val, s.acc_val)

        # -- proposer, PROMISE tally (phase 1)
        p_live = (s.prop_phase == 1) & (bal == s.prop_bal)
        prom_mine = is_prom & p_live
        acks = jnp.where(prom_mine, s.acks | (jnp.int32(1) << src), s.acks)
        # fold the responder's highest accepted into the discovery
        better = prom_mine & (a_bal > s.best_bal)
        best_bal = jnp.where(better, a_bal, s.best_bal)
        best_val = jnp.where(better, val, s.best_val)
        to_phase2 = prom_mine & majority(acks)
        # THE rule: push the discovered value when one exists
        if buggy_ignore_discovered:
            push_val = s.prop_val
        else:
            push_val = jnp.where(best_bal >= 0, best_val, s.prop_val)

        # -- proposer, ACCEPTED tally (phase 2)
        a_live = (s.prop_phase == 2) & (bal == s.prop_bal)
        acd_mine = is_acd & a_live
        acks = jnp.where(acd_mine, acks | (jnp.int32(1) << src), acks)
        wins = acd_mine & majority(acks)

        # -- learner
        decided = jnp.where(
            is_dec & (s.decided == 0), val,
            jnp.where(wins & (s.decided == 0), s.prop_val, s.decided),
        )

        # entering phase 2, the proposer SELF-ACCEPTS (recording it!) iff
        # its ballot still satisfies its own acceptor's promise — the other
        # half of the phantom-self-vote fix
        self_acc = to_phase2 & (s.prop_bal >= promised)
        state = s._replace(
            promised=jnp.where(self_acc, jnp.maximum(promised, s.prop_bal),
                               promised),
            acc_bal=jnp.where(self_acc, s.prop_bal, acc_bal),
            acc_val=jnp.where(self_acc, push_val, acc_val),
            decided=decided,
            prop_phase=jnp.where(
                to_phase2, 2, jnp.where(wins, 0, s.prop_phase)
            ),
            prop_val=jnp.where(to_phase2, push_val, s.prop_val),
            best_bal=best_bal,
            best_val=best_val,
            acks=jnp.where(
                to_phase2,
                jnp.where(self_acc, jnp.int32(1) << nid, 0),
                acks,
            ),
        )

        # -- outbox: replies are single-target (placed in row `src`, so
        # replies to different peers never share a pool ring); phase
        # transitions broadcast from all rows
        bc = to_phase2 | wins  # ACCEPT round or DECIDED announcement
        bc_kind = jnp.where(to_phase2, ACCEPT, DECIDED)
        bc_pay = jnp.where(
            to_phase2,
            jnp.stack([s.prop_bal, push_val, jnp.int32(0)]),
            jnp.stack([jnp.int32(0), state.decided, jnp.int32(0)]),
        )
        reply = prep_ok | acc_ok
        r_kind = jnp.where(is_prep, PROMISE, ACCEPTED)
        r_pay = jnp.where(
            is_prep,
            jnp.stack([bal, s.acc_val, s.acc_bal]),
            jnp.stack([bal, jnp.int32(0), jnp.int32(0)]),
        )
        at_row = peers == jnp.where(bc, -1, src)  # row src for replies
        out = Outbox(
            valid=jnp.where(bc, peers != nid, reply & at_row),
            dst=jnp.where(bc, peers, jnp.full((N,), src, jnp.int32)),
            kind=jnp.where(bc, bc_kind, r_kind) * jnp.ones((N,), jnp.int32),
            payload=jnp.where(
                jnp.reshape(bc, (1, 1)),
                jnp.broadcast_to(bc_pay[None, :], (N, PAYLOAD_WIDTH)),
                jnp.where(at_row[:, None], r_pay[None, :], 0),
            ),
        )
        return state, out, jnp.int32(-1)

    # --------------------------------------------------------------- restart

    def on_restart(s: PaxosState, nid, now, key):
        state = s._replace(
            prop_bal=jnp.int32(-1),
            prop_phase=jnp.int32(0),
            prop_val=jnp.int32(0),
            best_bal=jnp.int32(-1),
            best_val=jnp.int32(0),
            acks=jnp.int32(0),
        )
        return state, now + prng.randint(key, 42, 0, retry_hi_us)

    # ------------------------------------------------------------ invariants

    def check_invariants(ns: PaxosState, alive, now):
        # AGREEMENT: all nonzero decisions equal (pairwise over [N])
        d = ns.decided
        have = d != 0
        disagree = (
            have[:, None] & have[None, :] & (d[:, None] != d[None, :])
        )
        return ~disagree.any()

    def lane_metrics(node):
        have = node.decided != 0  # [L,N]
        return {
            "all_decided_lanes": have.all(axis=-1),
            "mean_decided_nodes": have.sum(axis=-1).astype(jnp.float32),
        }

    return fuse_two_handlers(ProtocolSpec(
        name=f"paxos{N}",
        n_nodes=N,
        payload_width=PAYLOAD_WIDTH,
        max_out=N,
        max_out_msg=N,  # a final PROMISE/ACCEPTED triggers a broadcast
        init=init,
        on_message=on_message,
        on_timer=on_timer,
        on_restart=on_restart,
        check_invariants=check_invariants,
        lane_metrics=lane_metrics,
        msg_kind_names=("PREPARE", "PROMISE", "ACCEPT", "ACCEPTED", "DECIDED"),
        # r8 carry compaction (docs/state_layout.md): only the provably
        # bounded fields narrow. prop_phase is a 3-state enum; acks an
        # N-bit quorum mask. Ballots/round stay i32 on purpose: the retry
        # timer draw is randint(0, retry_hi) with NO lower bound, so a
        # pathological lane can mint rounds every step and no u16/i16
        # ballot bound survives an adversarial horizon (contrast raft,
        # whose election_lo_us floor makes u16 terms safe). Values stay
        # i32: prop_val encodes nid * 100_000 + round.
        narrow_fields={
            "prop_phase": jnp.uint8,
            **({"acks": jnp.uint8} if N <= 8 else
               {"acks": jnp.uint16} if N <= 16 else {}),
        },
        # explicitly declared: every narrowed field is a step-closed
        # enum/mask — no rate-argument bounds, so the Layer-3 range
        # certifier (analysis/ranges.py) must certify this spec
        # trivially (unbounded safe horizon) from the interval pass
        # alone. Ballots/round staying i32 (see above) is exactly what
        # keeps this table floor-free.
        rate_floors={},
    ))


def paxos_workload(n_nodes: int = 5, virtual_secs: float = 10.0,
                   loss_rate: float = 0.1):
    """Single-decree consensus under the full chaos battery. A violating
    seed gets BOTH microscopes: the device trace and the host twin
    (workloads/paxos_host.py — the same synod as breakpointable
    coroutines, continuously verified by the same agreement oracle)."""
    from .batch import BatchWorkload
    from .spec import SimConfig

    def host_repro(seed: int):
        from ..workloads import paxos_host

        try:
            out = paxos_host.fuzz_one_seed(
                seed, n_nodes=n_nodes, virtual_secs=virtual_secs,
                loss_rate=loss_rate,
            )
            out["violations"] = 0
            return out
        except paxos_host.InvariantViolation as e:
            return {"violations": 1, "violation": str(e)}

    from .spec import pool_kw_for

    the_spec = make_paxos_spec(n_nodes)
    pool_kw = pool_kw_for(
        the_spec,
        fused=dict(msg_depth_msg=2, msg_spare_slots=2),
        two_handler=dict(msg_depth_msg=3, msg_depth_timer=2),
    )
    cfg = SimConfig(
        horizon_us=int(virtual_secs * 1e6),
        # node-pooled budget: a proposer can broadcast ACCEPT and DECIDED
        # from the same rows within one latency window, on top of in-flight
        # replies (per-row depth 2 dropped ~1 per 32 lanes before node
        # pooling); depth 2 x N rows + 2 spare covers the burst
        **pool_kw,
        loss_rate=loss_rate,
        crash_interval_lo_us=400_000,
        crash_interval_hi_us=2_000_000,
        restart_delay_lo_us=200_000,
        restart_delay_hi_us=1_000_000,
        partition_interval_lo_us=300_000,
        partition_interval_hi_us=1_500_000,
        partition_heal_lo_us=400_000,
        partition_heal_hi_us=1_500_000,
    )
    return BatchWorkload(
        spec=the_spec, config=cfg, host_repro=host_repro
    )
