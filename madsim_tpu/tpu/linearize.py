"""Per-key linearizability checking over recorded KV histories.

The device oracle (tpu/kv.py check_invariants) is a cheap per-step net:
real-time revision monotonicity + same-revision value coherence + max-rev
watermarks. Those are necessary conditions, not linearizability — e.g. a
read that observes a value BEFORE the write that produced it even started
(a "future read") carries a perfectly monotone revision and passes. This
module is the real checker (SURVEY §7 step 5 / BASELINE config #4: "etcd
linearizability under partitions"), run host-side by `run_batch` on
violating lanes plus a sampled clean subset.

Method: linearizability is compositional over keys (Herlihy & Wing) and the
KV's registers are independent, so each key is checked alone as an atomic
register history. Client writes carry globally unique values
(nid * 100_000 + counter), so each read maps to at most one write, and the
Wing-Gong depth-first search with memoization decides the key's history
exactly; the concurrency frontier is bounded by the client count (= N), so
the search is effectively linear in ops.

Honest limits, by construction of the recorded histories:
  * only ACKED ops are recorded, so a read may observe a value whose write
    record was never acked (client timed out but the write committed) or
    was evicted from the bounded history ring. Such reads cannot be placed
    against a witness write and are EXCLUDED from the search (reported as
    `unmatched_reads`); the device-side watermark oracle still covers their
    revision ordering.
  * ops are timestamped with the lane's rebased offsets; all entries shift
    together (kv time_fields), so intervals are mutually consistent.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class Op:
    tinv: int
    trsp: int
    is_write: bool
    key: int
    val: int
    rev: int
    node: int  # recording node (diagnostics)

    def __str__(self) -> str:
        k = "W" if self.is_write else "R"
        return (
            f"{k}(key={self.key}, val={self.val}, rev={self.rev}) "
            f"@[{self.tinv}, {self.trsp}] node{self.node}"
        )


OP_READ, OP_WRITE = 1, 2  # mirrors tpu/kv.py


def extract_ops(node, lane: int) -> List[Op]:
    """Pull one lane's acked ops out of the KvState history rings.

    `node` is the engine's node pytree (leaves [L, N, ...]); entries with
    kind == 0 are empty ring slots.
    """
    kind = np.asarray(node.h_kind)[lane]  # [N, OPS]
    key = np.asarray(node.h_key)[lane]
    val = np.asarray(node.h_val)[lane]
    rev = np.asarray(node.h_rev)[lane]
    tinv = np.asarray(node.h_tinv)[lane]
    trsp = np.asarray(node.h_trsp)[lane]
    N, OPS = kind.shape
    ops = []
    for n in range(N):
        for i in range(OPS):
            if kind[n, i] > 0:
                ops.append(
                    Op(
                        tinv=int(tinv[n, i]), trsp=int(trsp[n, i]),
                        is_write=int(kind[n, i]) == OP_WRITE,
                        key=int(key[n, i]), val=int(val[n, i]),
                        rev=int(rev[n, i]), node=n,
                    )
                )
    return ops


def check_key_history(ops: List[Op]) -> Tuple[bool, Optional[List[Op]], int]:
    """Wing-Gong linearizability for one key's register history.

    Returns (linearizable, counterexample_suffix_or_None, unmatched_reads).
    The register's initial value is 0 (reads of val 0 with no witness write
    are reads of the initial state).
    """
    writes_by_val: Dict[int, Op] = {}
    for o in ops:
        if o.is_write:
            if o.val in writes_by_val:
                # duplicate write values break read->write matching; the kv
                # spec guarantees uniqueness (nid * 100_000 + counter), so
                # a duplicate is itself a finding — report it as a failed
                # key rather than crash the whole lane_check pass (and
                # unlike an assert, this survives python -O)
                return False, [writes_by_val[o.val], o], 0
            writes_by_val[o.val] = o

    checked: List[Op] = []
    unmatched = 0
    for o in ops:
        if o.is_write or o.val == 0 or o.val in writes_by_val:
            checked.append(o)
        else:
            unmatched += 1  # read of an unacked/evicted write: no witness

    n = len(checked)
    if n == 0:
        return True, None, unmatched
    order = sorted(range(n), key=lambda i: (checked[i].tinv, checked[i].trsp))
    checked = [checked[i] for i in order]

    # Wing-Gong DFS: linearize one op at a time. An op may go next iff no
    # other remaining op RESPONDED before it was invoked (real-time order).
    # State = (remaining-mask, register value); memoize failures.
    full = (1 << n) - 1
    seen = set()

    def dfs(remaining: int, value: int) -> bool:
        if remaining == 0:
            return True
        if (remaining, value) in seen:
            return False
        # the real-time frontier: ops whose invocation precedes every
        # remaining op's response
        min_trsp = min(
            checked[i].trsp for i in range(n) if remaining >> i & 1
        )
        for i in range(n):
            if not (remaining >> i & 1):
                continue
            o = checked[i]
            if o.tinv > min_trsp:
                break  # sorted by tinv: no later op can be minimal either
            if not o.is_write and o.val != value:
                continue  # read must return the current register value
            nxt = value if not o.is_write else o.val
            if dfs(remaining & ~(1 << i), nxt):
                return True
        seen.add((remaining, value))
        return False

    import sys

    limit = sys.getrecursionlimit()
    if n + 50 > limit:
        sys.setrecursionlimit(n + 100)
    try:
        ok = dfs(full, 0)
    finally:
        sys.setrecursionlimit(limit)
    if ok:
        return True, None, unmatched
    return False, checked, unmatched


def check_lane(node, lane: int) -> dict:
    """Full per-key linearizability verdict for one lane's history."""
    ops = extract_ops(node, lane)
    by_key: Dict[int, List[Op]] = {}
    for o in ops:
        by_key.setdefault(o.key, []).append(o)
    failures = []
    unmatched_total = 0
    for k, key_ops in sorted(by_key.items()):
        ok, ce, unmatched = check_key_history(key_ops)
        unmatched_total += unmatched
        if not ok:
            failures.append({
                "key": k,
                "ops": [str(o) for o in ce],
            })
    return {
        "lane": lane,
        "ops_checked": len(ops) - unmatched_total,
        "unmatched_reads": unmatched_total,
        "keys": len(by_key),
        "linearizable": not failures,
        "violations": len(failures),
        "failures": failures,
    }


def check_lanes(node, lanes) -> dict:
    """Aggregate check over several lanes (run_batch's oracle hook)."""
    results = [check_lane(node, int(lane)) for lane in lanes]
    bad = [r for r in results if not r["linearizable"]]
    return {
        "histories_checked": len(results),
        "ops_checked": sum(r["ops_checked"] for r in results),
        "unmatched_reads": sum(r["unmatched_reads"] for r in results),
        "non_linearizable_lanes": [r["lane"] for r in bad],
        "violations": len(bad),
        "failures": [f for r in bad for f in r["failures"]][:8],
    }
