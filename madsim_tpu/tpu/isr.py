"""Kafka-family ISR log replication — the reconfig-era fuzz protocol.

A sixth *shape* (raft: symmetric replicated log; kv: primary/backup
quorum rounds; twopc: one-shot commit; paxos: ballot duels; chain: fixed
linear topology): a FIXED LEADER (node 0, the partition leader) with a
dynamic In-Sync Replica set, follower fetch/response replication, and a
high watermark advanced to the minimum acked offset across the ISR —
the Kafka replication contract (KIP-101 family). Written with
`fuse_two_handlers` per docs/authoring_protocol_specs.md.

Protocol:

  * Followers FETCH(leo, sent_t) from the leader on their tick. The
    leader applies a fetch only when its sent time beats the last one it
    applied from that replica (`lf_t`, the reorder/duplicate guard —
    regression of a replica's acked offset after a wipe-join is
    LEGITIMATE and must not be masked by a monotone max), records the
    acked offset `fa[src] = min(f_leo, leo)`, and replies FRESP(leo, hw,
    echo). The follower adopts the leader's (leo, hw) wholesale when the
    echo matches its latest fetch — instant catch-up, which keeps the
    spec small; truncation after a leader wipe falls out for free.
  * The leader produces on its tick (leo += 1 at `produce_rate`, its own
    ack rides along), evicts followers whose last applied fetch is older
    than `repl_timeout_us` from the ISR, and advances
    `hw = max(hw, min over ISR of fa)`. The leader's own ISR bit is
    pinned. ISR membership changes ONLY at the leader — the bitmask and
    `fa` are meaningful at node 0 alone (followers carry init values).
  * Admission (the Kafka catch-up contract): a fetching replica is IN
    the ISR iff its freshly acked offset has caught up to the high
    watermark — the correct leader demotes a replica whose applied ack
    regressed below `hw` and admits one at `ack >= hw`, so
    `fa[r] >= hw` holds for every ISR member BY CONSTRUCTION at every
    mutation point (admission, eviction, and hw-advance all preserve
    it). Crash/restart keeps the log (leo/hw durable); a reconfig
    wipe-join restarts the replica from offset 0 via the engine's
    `_init` path.

Device invariants (per lane, per step — leader-local, hence race-free
under per-node clock skew: the engine's virtual time is global and all
checked fields live on node 0 except hw<=leo which is node-local):
  * ISR catch-up contract: every replica in node 0's ISR has
    `fa[r] >= hw`.
  * Watermark sanity: `hw <= leo` on every node (the leader's min runs
    over an ISR containing itself; followers adopt (leo, hw) pairs).

The canonical injected bug (`buggy_stale_isr=True`): the leader
re-admits a fetching replica into the ISR UNCONDITIONALLY — no catch-up
check on admission and no demotion on a regressed ack. A replica
removed by the reconfig nemesis and later re-joined as a fresh disk
fetches at offset 0; the buggy leader puts it straight back into the
ISR while `hw` is already ahead, acking a stale high-watermark — the
`fa[r] >= hw` contract fires on the next check. (Plain crash/restart
can also fire it — a durably lagging replica is evicted, hw advances,
and its first fetch after restart is re-admitted stale — so the
reconfig smoke plan isolates the membership axis by running reconfig
WITHOUT crash clauses.)
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from . import prng
from .spec import Outbox, ProtocolSpec, RateFloor, fuse_two_handlers

FETCH, FRESP = range(2)
PAYLOAD_WIDTH = 3  # FETCH: (leo, sent_t, 0) / FRESP: (leo, hw, echo)


class IsrState(NamedTuple):
    # the replicated log, abstracted to its end offset (durable)
    leo: jnp.ndarray  # i32 log end offset
    hw: jnp.ndarray  # i32 high watermark (leader authoritative; followers
    # hold the last adopted copy)
    # leader-only replication bookkeeping (durable; junk on followers)
    in_sync: jnp.ndarray  # i32 [N] 0|1; replica r in the ISR (a 0/1
    # array, not a bitmask: the range certifier proves closed u8 fields
    # by interval, and bit-twiddling would escape it)
    fa: jnp.ndarray  # i32 [N] last acked offset per replica
    lf_t: jnp.ndarray  # i32 [N] sent time of the last APPLIED fetch per
    # replica (eviction clock + the stale-fetch guard)
    # follower fetch bookkeeping (volatile)
    ft: jnp.ndarray  # i32 sent time of my latest FETCH (FRESP echo match)


def make_isr_spec(
    n_nodes: int = 5,
    tick_us: int = 25_000,
    repl_timeout_us: int = 150_000,
    produce_rate: float = 0.7,
    buggy_stale_isr: bool = False,
) -> ProtocolSpec:
    N = n_nodes
    assert N >= 3
    peers = jnp.arange(N, dtype=jnp.int32)
    LEADER = 0

    def _min_acked(member, fa):
        # min over ISR members' acked offsets. The non-member fallback
        # is fa[LEADER] — the leader's bit is pinned, so this equals the
        # true member-min while keeping the interval bounded (an INF
        # sentinel would poison the u16 range certificate)
        return jnp.where(member, fa, fa[LEADER]).min()

    # ------------------------------------------------------------------ init

    def init(key, nid):
        z = jnp.int32(0)
        state = IsrState(
            leo=z, hw=z,
            in_sync=jnp.ones((N,), jnp.int32),
            fa=jnp.zeros((N,), jnp.int32),
            lf_t=jnp.zeros((N,), jnp.int32),
            ft=z,
        )
        # first fire >= tick_us out: the leo rate-floor argument wants
        # every inter-produce gap >= tick_us, including the first
        return state, tick_us + prng.randint(key, 60, 0, tick_us)

    # ----------------------------------------------------------------- timer

    def on_timer(s: IsrState, nid, now, key):
        is_leader = nid == LEADER
        # leader: produce at most one record per tick
        produce = is_leader & (prng.uniform(key, 61) < produce_rate)
        leo = s.leo + produce.astype(jnp.int32)
        fa = jnp.where(produce & (peers == nid), leo, s.fa)
        # leader: evict replicas whose last applied fetch went stale;
        # the leader's own bit is pinned
        stale = is_leader & (peers != nid) & (now - s.lf_t > repl_timeout_us)
        in_sync = jnp.where(stale, 0, s.in_sync)
        hw = jnp.where(
            is_leader,
            jnp.maximum(s.hw, _min_acked(in_sync > 0, fa)),
            s.hw,
        )
        # follower: fetch every tick
        fetch = ~is_leader
        state = s._replace(
            leo=leo, hw=hw, in_sync=in_sync, fa=fa,
            ft=jnp.where(fetch, now, s.ft),
        )
        pay = jnp.stack([s.leo, now, jnp.int32(0)])
        out = Outbox(
            valid=jnp.stack([fetch]),
            dst=jnp.stack([jnp.int32(LEADER)]),
            kind=jnp.stack([jnp.int32(FETCH)]),
            payload=jnp.stack([pay]),
        )
        return state, out, now + tick_us

    # --------------------------------------------------------------- message

    def on_message(s: IsrState, nid, src, kind, payload, now, key):
        f = payload
        is_leader = nid == LEADER
        is_fetch = (kind == FETCH) & is_leader
        is_fresp = (kind == FRESP) & ~is_leader

        # -- leader: apply a fetch only when it beats the last applied
        # one from this replica (sent-time guard: reordered/duplicated
        # fetches are rejected, while a wipe-join's offset regression —
        # fresh send time, smaller leo — applies, as it must)
        sel = is_fetch & (peers == src) & (f[1] > s.lf_t)  # [N]
        ack = jnp.minimum(f[0], s.leo)
        fa = jnp.where(sel, ack, s.fa)
        lf_t = jnp.where(sel, f[1], s.lf_t)
        if buggy_stale_isr:
            # THE PLANTED BUG: unconditional re-admission — no catch-up
            # check, no demotion on a regressed ack. A wipe-joined
            # replica fetching at offset 0 re-enters the ISR while hw is
            # ahead, acking a stale high-watermark.
            in_sync = jnp.where(sel, 1, s.in_sync)
        else:
            # Kafka contract: in the ISR iff caught up to the watermark
            in_sync = jnp.where(
                sel, (ack >= s.hw).astype(jnp.int32), s.in_sync
            )
        hw = jnp.where(
            is_fetch,
            jnp.maximum(s.hw, _min_acked(in_sync > 0, fa)),
            s.hw,
        )

        # -- follower: adopt the leader's (leo, hw) when the echo matches
        # my latest fetch (stale/reordered responses drop)
        adopt = is_fresp & (f[2] == s.ft) & (s.ft > 0)
        resp_pay = jnp.stack([s.leo, hw, f[1]])
        state = s._replace(
            leo=jnp.where(adopt, f[0], s.leo),
            hw=jnp.where(adopt, f[1], hw),
            in_sync=in_sync, fa=fa, lf_t=lf_t,
        )
        # reply to every fetch (stale ones re-ack: the follower's echo
        # guard makes redelivery idempotent)
        out = Outbox(
            valid=jnp.stack([is_fetch]),
            dst=jnp.stack([src.astype(jnp.int32)]),
            kind=jnp.stack([jnp.int32(FRESP)]),
            payload=jnp.stack([resp_pay]),
        )
        return state, out, jnp.int32(-1)

    # --------------------------------------------------------------- restart

    def on_restart(s: IsrState, nid, now, key):
        state = s._replace(ft=jnp.int32(0))
        # re-arm >= tick_us out (part of the leo rate-floor argument)
        return state, now + tick_us + prng.randint(key, 62, 0, tick_us)

    # ------------------------------------------------------------ invariants

    def check_invariants(ns: IsrState, alive, now):
        # ns leaves are [N, ...] for one lane; everything checked is
        # leader-local (node 0) or node-local — race-free under skew
        member = ns.in_sync[LEADER] > 0  # [N]
        fa0, hw0 = ns.fa[LEADER], ns.hw[LEADER]
        catch_up = ~(member & (fa0 < hw0)).any()
        hw_sane = (ns.hw <= ns.leo).all()
        return catch_up & hw_sane

    # ------------------------------------------------------------ diagnostics

    def lane_metrics(node):
        return {
            "mean_hw": node.hw[:, LEADER].astype(jnp.float32),
            "mean_isr_size": (
                node.in_sync[:, LEADER] > 0
            ).sum(-1).astype(jnp.float32),
        }

    floor_why = (
        "leo advances by at most 1 per leader tick: produce happens only "
        "in on_timer, the re-arm is always now + tick_us, and init/"
        "restart arm the first fire >= tick_us out"
    )
    return fuse_two_handlers(ProtocolSpec(
        name=f"isr{N}",
        n_nodes=N,
        payload_width=PAYLOAD_WIDTH,
        max_out=1,
        max_out_msg=1,
        init=init,
        on_message=on_message,
        on_timer=on_timer,
        on_restart=on_restart,
        check_invariants=check_invariants,
        lane_metrics=lane_metrics,
        msg_kind_names=("FETCH", "FRESP"),
        time_fields=("lf_t", "ft"),
        # r8 carry compaction (docs/state_layout.md): the offsets are
        # rate-bounded counters — leo ticks up at most once per leader
        # tick, and hw/fa only ever copy leo-family values (min/max over
        # acked offsets, payload copies), so they ride the same budget
        # under the certifier's copy premise. in_sync is a 0/1 flag row.
        narrow_fields={
            "in_sync": jnp.uint8,
            "leo": jnp.uint16,
            "hw": jnp.uint16,
            "fa": jnp.uint16,
        },
        rate_floors={
            "leo": RateFloor(floor_us=tick_us, ratchet=1, inc=1,
                             why=floor_why),
            "hw": RateFloor(floor_us=tick_us, ratchet=1, inc=1,
                            why="copy: max/min over fa, itself leo copies"),
            "fa": RateFloor(floor_us=tick_us, ratchet=1, inc=1,
                            why="copy: min(fetched leo, own leo)"),
        },
        # u16 budget at one bump per tick, halved for skew derating and
        # engineering margin; benches run seconds, this proves ~13 min
        narrow_horizon_us=65_535 * tick_us // 2,
    ))


def isr_workload(n_nodes: int = 5, virtual_secs: float = 10.0,
                 loss_rate: float = 0.1, buggy: bool = False):
    """ISR replication under loss + crash + RECONFIG chaos — the
    membership axis is the point: wipe-joins regress a replica's acked
    offset, which only a catch-up-checking leader survives. A violating
    seed gets both microscopes: the device trace and the host twin
    (workloads/isr_host.py), verified by the same invariants."""
    from .batch import BatchWorkload
    from .spec import SimConfig, pool_kw_for

    spec = make_isr_spec(n_nodes, buggy_stale_isr=buggy)

    def host_repro(seed: int):
        from ..workloads import isr_host

        try:
            out = isr_host.fuzz_one_seed(
                seed, n_nodes=n_nodes, virtual_secs=virtual_secs,
                loss_rate=loss_rate, buggy=buggy,
            )
            out["violations"] = 0
            return out
        except isr_host.InvariantViolation as e:
            return {"violations": 1, "violation": str(e)}

    cfg = SimConfig(
        horizon_us=int(virtual_secs * 1e6),
        **pool_kw_for(
            spec,
            fused=dict(msg_depth_msg=2, msg_spare_slots=2),
            two_handler=dict(msg_depth_msg=2, msg_depth_timer=2),
        ),
        loss_rate=loss_rate,
        crash_interval_lo_us=500_000,
        crash_interval_hi_us=2_000_000,
        restart_delay_lo_us=200_000,
        restart_delay_hi_us=900_000,
        # membership churn: down windows comfortably above repl_timeout
        # so the removed replica is evicted before its fresh join
        nem_reconfig_interval_lo_us=600_000,
        nem_reconfig_interval_hi_us=1_800_000,
        nem_reconfig_down_lo_us=300_000,
        nem_reconfig_down_hi_us=900_000,
    )
    return BatchWorkload(spec=spec, config=cfg, host_repro=host_repro)
