"""Bit-packed bool planes: u32 words for the carry, bool tensors in the step.

The r8 compaction (docs/state_layout.md): XLA materializes `bool` as one
byte per element, so the engine's validity planes — `alive [L,N]`,
`link_ok [L,N,N]` and especially the message pool's `valid [L,N,CK]` —
cost 8x their information content in carry bytes, and the carry is read
AND written every fused step. The SimState at rest therefore stores these
planes packed 32-to-a-word along their last axis; `BatchedSim._step`
unpacks them into bool tensors on entry and repacks on exit. Both
directions are pure elementwise shift/mask arithmetic on uint32 (the same
op vocabulary as the murmur3 draw chain in prng.py), so XLA fuses them
into the surrounding step work — the bool plane lives only inside the
fused kernel, never in HBM-resident state.

Packing is strictly value-preserving: `unpack_bits(pack_bits(m), K) == m`
for every bool tensor (tests/test_state_layout.py pins the round-trip),
so the compacted engine's trajectories are bit-identical to the r7
layout's.
"""

from __future__ import annotations

import jax.numpy as jnp


def packed_words(k: int) -> int:
    """Words needed to hold `k` bits (ceil(k / 32))."""
    return -(-k // 32)


def pack_bits(mask: jnp.ndarray) -> jnp.ndarray:
    """bool [..., K] -> u32 [..., ceil(K/32)], little-endian bit order
    (bit j of word w holds element w * 32 + j; trailing pad bits are 0)."""
    K = mask.shape[-1]
    W = packed_words(K)
    pad = W * 32 - K
    if pad:
        mask = jnp.concatenate(
            [mask, jnp.zeros(mask.shape[:-1] + (pad,), jnp.bool_)], axis=-1
        )
    bits = mask.reshape(mask.shape[:-1] + (W, 32)).astype(jnp.uint32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    # the shifted bits are disjoint, so a sum IS the bitwise OR — and sum
    # is a plain fusable reduce
    return (bits << shifts).sum(axis=-1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray, k: int) -> jnp.ndarray:
    """u32 [..., W] -> bool [..., k] (inverse of pack_bits)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    bits = (words[..., :, None] >> shifts) & jnp.uint32(1)
    flat = bits.reshape(words.shape[:-1] + (words.shape[-1] * 32,))
    return flat[..., :k] != 0


def full_mask_word(n: int) -> int:
    """The packed representation of n all-true bits in one word (n <= 32)."""
    if not 0 <= n <= 32:
        raise ValueError(f"n must be in [0, 32], got {n}")
    return (1 << n) - 1 if n < 32 else 0xFFFFFFFF
