"""The batched discrete-event simulation engine: thousands of seeds per step.

This is the TPU-native re-design of the reference's executor + virtual clock +
network (SURVEY.md §3.1-3.2, §7): instead of one OS thread per seed
(runtime/builder.rs:118-136), the whole discrete-event loop is a single jitted
step function over lane-major state tensors:

    clock        [L]        virtual time per lane (int32 microseconds)
    key          [L]        per-lane hash-chain PRNG word (see prng.py)
    alive        [L, N]     node liveness (crash/restart chaos)
    timer        [L, N]     per-node timer deadline
    node state   [L, N, ...]protocol pytree
    message pool [L, S]     in-flight messages with deliver times

One step = (1) advance each lane to its next event WINDOW — the conservative
parallel-DES lookahead [t_next, t_next + latency_lo): messages emitted inside
the window arrive after it, so in-window events on different nodes are
causally independent, (2) per node, pick its earliest in-window event —
message delivery or timer fire, never both (per-node order is exact) — and
run `on_message`/`on_timer` with the node's own event time, (3) run
crash/restart + partition chaos (the window collapses to the exact chaos
instant on those steps), (4) roll loss + latency for every emitted message
(the `test_link` analog, net/network.rs:261-269), stamped from the emitting
node's event time, and pack survivors into free pool slots, (5) check
invariants. Everything is vmapped over lanes and vectorized over nodes; the
step cost is N-wide regardless of how many nodes have due events, so the
lookahead window turns idle handler lanes into processed events for free.

Lanes are embarrassingly parallel, so the lane axis shards cleanly over a
device mesh (`shard_state`); the node axis can additionally be sharded for
large clusters, with XLA inserting collectives for the pool<->node gathers.

Determinism: jitted XLA programs are deterministic, and all randomness comes
from the per-lane threefry keys derived from the seed — one seed => one
bit-exact trajectory per backend (the per-backend determinism contract of
SURVEY.md §7 step 1).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from . import prng
from .spec import INF_US, Outbox, ProtocolSpec, SimConfig


class MsgPool(NamedTuple):
    valid: Any  # bool [L,S]
    deliver: Any  # i32 [L,S]
    src: Any  # i32 [L,S]
    dst: Any  # i32 [L,S]
    kind: Any  # i32 [L,S]
    payload: Any  # i32 [L,S,P]


class TraceRecord(NamedTuple):
    """One step's observable events, for per-lane violation traces.

    The reference's DX promise is an exact, inspectable repro from the
    printed seed (runtime/mod.rs:194-199). On device the equivalent is this
    record stream: re-running one violating seed through the SAME jitted
    step function yields every delivery, timer fire, crash/restart and
    partition event with virtual timestamps — debuggable without the host
    twin. All leaves are [L, ...]; tracing runs use L=1.
    """

    clock: Any  # i32 [L]
    t_evt: Any  # i32 [L,N] virtual time of node n's event this step
    msg_fired: Any  # bool [L,N] message delivered to node n this step
    msg_src: Any  # i32 [L,N]
    msg_kind: Any  # i32 [L,N]
    msg_payload: Any  # i32 [L,N,P]
    timer_fired: Any  # bool [L,N]
    crash: Any  # i32 [L] node crashed this step, -1 = none
    restart: Any  # i32 [L] node restarted this step, -1 = none
    split: Any  # bool [L] partition split happened this step
    heal: Any  # bool [L] partition healed this step
    side_mask: Any  # i32 [L] bitmask of nodes on side A after a split
    violation: Any  # bool [L] invariant first violated this step
    deadlock: Any  # bool [L]


class SimState(NamedTuple):
    clock: Any  # i32 [L]
    key: Any  # u32 [L] (hash-chain, prng.py)
    done: Any  # bool [L]
    violated: Any  # bool [L]
    violation_at: Any  # i32 [L]
    deadlocked: Any  # bool [L]
    steps: Any  # i32 [L]
    events: Any  # i32 [L]
    overflow: Any  # i32 [L] (messages dropped: pool full)
    alive: Any  # bool [L,N]
    crashed: Any  # i32 [L] (node id currently down, -1 = none)
    chaos_at: Any  # i32 [L] (next crash/restart event)
    link_ok: Any  # bool [L,N,N] (directed link up; the clog masks)
    partitioned: Any  # bool [L] (a partition is currently active)
    part_at: Any  # i32 [L] (next partition split/heal event)
    timer: Any  # i32 [L,N]
    node: Any  # protocol pytree, leaves [L,N,...]
    msgs: MsgPool


def _tree_where(mask: jnp.ndarray, a: Any, b: Any) -> Any:
    """Select pytree leaves by a [L,N]-shaped mask, broadcasting trailing dims."""

    def sel(x, y):
        m = mask.reshape(mask.shape + (1,) * (x.ndim - mask.ndim))
        return jnp.where(m, x, y)

    return jax.tree_util.tree_map(sel, a, b)


class BatchedSim:
    """Vectorized multi-lane simulator for one ProtocolSpec."""

    def __init__(self, spec: ProtocolSpec, config: Optional[SimConfig] = None) -> None:
        self.spec = spec
        self.config = config or SimConfig()
        N = spec.n_nodes
        # Message-pool layout: per-origin ring regions. Each of the
        # C = N*max_out_msg + N*max_out candidate positions owns K consecutive
        # slots, so packing a new message is a pure elementwise write into the
        # first free slot of its region — no rank-matching one-hot products
        # (the old pack built a [L,C,S] one-hot and a [L,C,S,P] contraction;
        # at L=16k that was ~220M MACs/step and dominated the step cost).
        # K is derived from msg_capacity: the budget is spread over regions.
        self._C = N * spec.max_out_msg + N * spec.max_out
        self._K = max(1, self.config.msg_capacity // self._C)
        self._S = self._C * self._K
        # source node of each candidate position (static: flat() reshapes
        # [L,N,e] row-major, so position c within each block maps to node
        # c // e) — used for send-time link tests
        import numpy as _np

        self._src_of_c = _np.concatenate(
            [
                _np.arange(N * spec.max_out_msg) // spec.max_out_msg,
                _np.arange(N * spec.max_out) // spec.max_out,
            ]
        )
        # scalar-style handlers -> [L,N] batched. `now` is per-(lane,node):
        # under the lookahead window, nodes in one step process events at
        # different virtual times.
        self._v_init = jax.vmap(jax.vmap(spec.init, in_axes=(0, 0)), in_axes=(0, None))
        self._v_on_message = jax.vmap(
            jax.vmap(spec.on_message, in_axes=(0, 0, 0, 0, 0, 0, 0)),
            in_axes=(0, 0, 0, 0, 0, 0, 0),
        )
        self._v_on_timer = jax.vmap(
            jax.vmap(spec.on_timer, in_axes=(0, 0, 0, 0)),
            in_axes=(0, 0, 0, 0),
        )
        self._v_on_restart = jax.vmap(
            jax.vmap(spec.on_restart, in_axes=(0, 0, None, 0)), in_axes=(0, 0, 0, 0)
        )
        self._v_check = jax.vmap(spec.check_invariants, in_axes=(0, 0, 0))
        self.step = jax.jit(self._step)

    # ------------------------------------------------------------------ init

    def init(self, seeds: jnp.ndarray) -> SimState:
        """Build lane state for a batch of seeds (int array [L])."""
        spec, cfg = self.spec, self.config
        seeds = jnp.asarray(seeds, jnp.uint32)
        L, N, S = seeds.shape[0], spec.n_nodes, self._S

        key = prng.key_from(seeds)  # u32 [L]
        node_keys = prng.fold(key[:, None], jnp.arange(N, dtype=jnp.uint32))
        node_state, timer = self._v_init(node_keys, jnp.arange(N, dtype=jnp.int32))

        if cfg.chaos_enabled:
            chaos_at = prng.randint(
                key, 11, cfg.crash_interval_lo_us, cfg.crash_interval_hi_us
            )
        else:
            chaos_at = jnp.full((L,), INF_US, jnp.int32)
        if cfg.partition_enabled:
            part_at = prng.randint(
                key, 12, cfg.partition_interval_lo_us, cfg.partition_interval_hi_us
            )
        else:
            part_at = jnp.full((L,), INF_US, jnp.int32)

        return SimState(
            clock=jnp.zeros((L,), jnp.int32),
            key=key,
            done=jnp.zeros((L,), jnp.bool_),
            violated=jnp.zeros((L,), jnp.bool_),
            violation_at=jnp.full((L,), INF_US, jnp.int32),
            deadlocked=jnp.zeros((L,), jnp.bool_),
            steps=jnp.zeros((L,), jnp.int32),
            events=jnp.zeros((L,), jnp.int32),
            overflow=jnp.zeros((L,), jnp.int32),
            alive=jnp.ones((L, N), jnp.bool_),
            crashed=jnp.full((L,), -1, jnp.int32),
            chaos_at=chaos_at,
            link_ok=jnp.ones((L, N, N), jnp.bool_),
            partitioned=jnp.zeros((L,), jnp.bool_),
            part_at=part_at,
            timer=jnp.asarray(timer, jnp.int32),
            node=node_state,
            msgs=MsgPool(
                valid=jnp.zeros((L, S), jnp.bool_),
                deliver=jnp.full((L, S), INF_US, jnp.int32),
                src=jnp.zeros((L, S), jnp.int32),
                dst=jnp.zeros((L, S), jnp.int32),
                kind=jnp.zeros((L, S), jnp.int32),
                payload=jnp.zeros((L, S, spec.payload_width), jnp.int32),
            ),
        )

    # ------------------------------------------------------------------ step

    def _step(self, state: SimState) -> SimState:
        return self._step_traced(state)[0]

    def _step_traced(self, state: SimState) -> Tuple[SimState, TraceRecord]:
        """One engine step + the step's TraceRecord.

        Untraced callers discard the record; XLA dead-code-eliminates its
        construction, so the trace costs nothing unless collected."""
        spec, cfg = self.spec, self.config
        N, S, E, P = spec.n_nodes, self._S, spec.max_out, spec.payload_width
        L = state.clock.shape[0]
        msgs = state.msgs

        # -- 1. advance each lane to its next event window -----------------
        # (the advance_to_next_event analog, time/mod.rs:45-60, batched)
        # NOTE on style: this step avoids gather/scatter ops in favor of
        # one-hot multiply-reduce — XLA lowers small-domain gathers to slow
        # serial kernels on TPU, while one-hot forms fuse into fast VPU loops
        # (measured ~20x difference on this step).
        dst_oh = msgs.dst[:, :, None] == jnp.arange(N)[None, None, :]  # [L,S,N]
        alive_dst = (dst_oh & state.alive[:, None, :]).any(-1)  # [L,S]
        live_msg = msgs.valid & alive_dst
        # per-(lane,node) pending message times (alive is already folded in:
        # live_msg requires the destination alive, and dst_oh pins n == dst)
        pend_ln = live_msg[:, None, :] & dst_oh.transpose(0, 2, 1)  # [L,N,S]
        t_ln = jnp.where(pend_ln, msgs.deliver[:, None, :], INF_US)
        tmsg_n = t_ln.min(axis=2)  # [L,N] earliest pending message per node
        ttmr_n = jnp.where(state.alive, state.timer, INF_US)  # [L,N]
        t_next = jnp.minimum(
            jnp.minimum(jnp.minimum(tmsg_n.min(axis=1), ttmr_n.min(axis=1)),
                        state.chaos_at),
            state.part_at,
        )

        deadlocked = (~state.done) & (t_next >= INF_US)
        active = (~state.done) & (t_next < INF_US)

        # conservative-DES lookahead window [t_next, t_next + latency_lo):
        # any message EMITTED by an in-window event arrives at
        # >= t_next + latency_lo, so in-window events on different nodes are
        # causally independent and each node may process its earliest one
        # this step (classic PDES lookahead; see SimConfig.lookahead).
        # Whenever the next crash/partition instant falls anywhere inside
        # the window, the window shrinks to the exact instant t_next (the
        # chaos itself fires only once it IS t_next), so chaos state never
        # applies to sends from earlier virtual times.
        lo_w = max(0, cfg.latency_lo_us - 1) if cfg.lookahead else 0
        w_end = jnp.minimum(t_next, INF_US - lo_w - 1) + lo_w
        if lo_w and (cfg.chaos_enabled or cfg.partition_enabled):
            chaos_in_w = jnp.minimum(state.chaos_at, state.part_at) <= w_end
            w_end = jnp.where(chaos_in_w, t_next, w_end)

        # -- 2. advance per-lane keys (cheap hash chain, see prng.py) ------
        key = prng.fold(state.key, 1)
        node_key = prng.fold(key[:, None], jnp.arange(N, dtype=jnp.uint32))  # [L,N]
        mkeys = prng.fold(node_key, 101)
        tkeys = prng.fold(node_key, 102)
        rkeys = prng.fold(node_key, 103)
        ckey = prng.fold(key, 104)  # [L]

        # -- 3. pick each node's event: earliest in-window message or timer
        # (one event per node per step keeps per-node order exact)
        msg_due = active[:, None] & (tmsg_n <= w_end[:, None])  # [L,N]
        tmr_due = active[:, None] & (ttmr_n <= w_end[:, None])  # [L,N]
        if cfg.sched_randomize:
            # message-vs-timer order: when both are due at the SAME instant,
            # half the time the timer fires first (the message waits a step;
            # its deliver time has passed so it stays due) — same-instant
            # event reordering, the utils/mpsc.rs:71-84 analog
            timer_first = prng.bernoulli(prng.fold(node_key, 108), 1, 0.5)
        else:
            timer_first = jnp.zeros((L, N), jnp.bool_)
        tie = msg_due & tmr_due & (tmsg_n == ttmr_n)
        has_msg = msg_due & (
            ~tmr_due | (tmsg_n < ttmr_n) | (tie & ~timer_first)
        )
        due_t = tmr_due & (
            ~msg_due | (ttmr_n < tmsg_n) | (tie & timer_first)
        )
        # per-node event time; inactive nodes default to the window start
        t_evt = jnp.where(has_msg, tmsg_n, jnp.where(due_t, ttmr_n, t_next[:, None]))

        # slot choice: among this node's earliest-time pending slots
        head_ln = pend_ln & (t_ln == tmsg_n[:, :, None])  # [L,N,S]
        if cfg.sched_randomize:
            # random tie-break among equal-timestamp due messages — the
            # scheduling-nondeterminism amplifier (utils/mpsc.rs:71-84):
            # seeds that share a chaos schedule still explore different
            # delivery orders, the reference's biggest bug-finding lever
            prio = prng.bits(
                prng.fold(key, 107)[:, None], 1,
                index=jnp.arange(S, dtype=jnp.uint32)[None, :],
            )  # u32 [L,S]
            prio_ln = jnp.where(head_ln, prio[:, None, :], jnp.uint32(0xFFFFFFFF))
            slot = jnp.argmin(prio_ln, axis=2)  # [L,N]
        else:
            slot = jnp.argmin(
                jnp.where(head_ln, t_ln, INF_US), axis=2
            )  # [L,N] first earliest slot
        slot_oh = (
            head_ln
            & (jnp.arange(S)[None, None, :] == slot[:, :, None])
            & has_msg[:, :, None]
        )

        slot_ohi = slot_oh.astype(jnp.int32)
        m_src = (msgs.src[:, None, :] * slot_ohi).sum(-1)
        m_kind = (msgs.kind[:, None, :] * slot_ohi).sum(-1)
        m_pay = (msgs.payload[:, None, :, :] * slot_ohi[:, :, :, None]).sum(2)
        node_ids = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32), (L, N))

        # -- 4. run handlers (at most one event per node => masks are
        # disjoint, so both handlers read state.node and XLA may overlap them)
        ns_m, out_m, timer_m = self._v_on_message(
            state.node, node_ids, m_src, m_kind, m_pay, t_evt, mkeys
        )
        ns_t, out_t, timer_t = self._v_on_timer(state.node, node_ids, t_evt, tkeys)
        node = _tree_where(has_msg, ns_m, state.node)
        node = _tree_where(due_t, ns_t, node)
        # message handlers return a negative timer to keep the current
        # deadline; timer handlers return a negative value to disarm
        timer = jnp.where(has_msg & (timer_m >= 0), timer_m, state.timer)
        timer = jnp.where(
            due_t, jnp.where(timer_t >= 0, timer_t, INF_US), timer
        )
        consumed = slot_oh.any(1)  # [L,S]
        valid = msgs.valid & ~consumed

        # lane clock: the latest event time processed this step (chaos-only
        # steps advance to the chaos instant t_next)
        clock = jnp.where(
            active,
            jnp.maximum(state.clock, t_evt.max(axis=1)),
            state.clock,
        )

        # -- 5. crash/restart chaos (Handle::kill/restart analog) ----------
        alive = state.alive
        crashed, chaos_at = state.crashed, state.chaos_at
        tr_crash = jnp.full((L,), -1, jnp.int32)
        tr_restart = jnp.full((L,), -1, jnp.int32)
        if cfg.chaos_enabled:
            chaos_due = active & (state.chaos_at <= t_next)
            is_restart = state.crashed >= 0
            do_crash = chaos_due & ~is_restart
            do_restart = chaos_due & is_restart

            victim = prng.randint(ckey, 1, 0, N)
            crash_mask = do_crash[:, None] & (node_ids == victim[:, None])
            restart_node = jnp.clip(state.crashed, 0, N - 1)
            restart_mask = do_restart[:, None] & (node_ids == restart_node[:, None])

            alive = (alive & ~crash_mask) | restart_mask
            ns_r, timer_r = self._v_on_restart(node, node_ids, clock, rkeys)
            node = _tree_where(restart_mask, ns_r, node)
            timer = jnp.where(restart_mask, timer_r, timer)

            restart_delay = prng.randint(
                ckey, 2, cfg.restart_delay_lo_us, cfg.restart_delay_hi_us
            )
            next_crash = prng.randint(
                ckey, 3, cfg.crash_interval_lo_us, cfg.crash_interval_hi_us
            )
            crashed = jnp.where(
                do_crash, victim, jnp.where(do_restart, -1, state.crashed)
            )
            tr_crash = jnp.where(do_crash, victim, -1)
            tr_restart = jnp.where(do_restart, restart_node, -1)
            chaos_at = jnp.where(
                do_crash,
                clock + restart_delay,
                jnp.where(do_restart, clock + next_crash, state.chaos_at),
            )
            # in-flight messages to a crashed node are lost (reset_node closes
            # sockets, network.rs:142-147)
            dst_alive_now = (dst_oh & alive[:, None, :]).any(-1)
            valid = valid & dst_alive_now

        # -- 5b. partition chaos: random bipartition splits, later heals ----
        # (the clog_link masks of network.rs:261-269, lane-batched)
        link_ok = state.link_ok
        partitioned, part_at = state.partitioned, state.part_at
        tr_split = jnp.zeros((L,), jnp.bool_)
        tr_heal = jnp.zeros((L,), jnp.bool_)
        tr_side = jnp.zeros((L,), jnp.int32)
        if cfg.partition_enabled:
            part_due = active & (state.part_at <= t_next)
            do_split = part_due & ~state.partitioned
            do_heal = part_due & state.partitioned
            pkey = prng.fold(key, 106)
            # each node draws a side; links crossing the cut go down both ways
            side = (
                prng.uniform(
                    pkey[:, None], 7, index=jnp.arange(N, dtype=jnp.uint32)[None, :]
                )
                < 0.5
            )  # [L,N]
            same_side = side[:, :, None] == side[:, None, :]  # [L,N,N]
            link_ok = jnp.where(
                do_split[:, None, None],
                same_side,
                jnp.where(do_heal[:, None, None], True, state.link_ok),
            )
            partitioned = (state.partitioned | do_split) & ~do_heal
            heal_delay = prng.randint(
                pkey, 8, cfg.partition_heal_lo_us, cfg.partition_heal_hi_us
            )
            next_split = prng.randint(
                pkey, 9, cfg.partition_interval_lo_us, cfg.partition_interval_hi_us
            )
            part_at = jnp.where(
                do_split,
                clock + heal_delay,
                jnp.where(do_heal, clock + next_split, state.part_at),
            )
            tr_split, tr_heal = do_split, do_heal
            tr_side = (
                side.astype(jnp.int32) * (1 << jnp.arange(N, dtype=jnp.int32))
            ).sum(-1)

        # -- 6. collect outboxes, roll the network, pack into pool ---------
        def flat(out: Outbox, emitting, e):  # [L,N,e,...] -> [L, N*e, ...]
            v = (out.valid & emitting[:, :, None]).reshape(L, N * e)
            return (
                v,
                out.dst.reshape(L, N * e),
                out.kind.reshape(L, N * e),
                out.payload.reshape(L, N * e, P),
                jnp.broadcast_to(node_ids[:, :, None], (L, N, e)).reshape(L, N * e),
            )

        E_m = self.spec.max_out_msg
        mv, md, mk, mp, ms_ = flat(out_m, has_msg, E_m)
        tv, td, tk, tp, ts_ = flat(out_t, due_t, E)
        C, K = self._C, self._K
        cand_valid = jnp.concatenate([mv, tv], axis=1)  # [L,C]
        cand_dst = jnp.clip(jnp.concatenate([md, td], axis=1), 0, N - 1)
        cand_kind = jnp.concatenate([mk, tk], axis=1)
        cand_pay = jnp.concatenate([mp, tp], axis=1)
        cand_src = jnp.concatenate([ms_, ts_], axis=1)

        # network rolls: loss + latency (test_link analog)
        cidx = jnp.arange(C, dtype=jnp.uint32)[None, :]
        net_key = prng.fold(key, 105)[:, None]
        u = prng.uniform(net_key, 1, index=cidx)
        lat = prng.randint(
            net_key, 2, cfg.latency_lo_us,
            max(cfg.latency_hi_us, cfg.latency_lo_us + 1), index=cidx,
        )
        cand_dst_oh = cand_dst[:, :, None] == jnp.arange(N)[None, None, :]  # [L,C,N]
        keep = cand_valid & (u >= cfg.loss_rate)
        # sends to currently-dead nodes are dropped (clogged-node semantics)
        keep = keep & (cand_dst_oh & alive[:, None, :]).any(-1)
        if cfg.partition_enabled:
            # link test at send time (test_link, network.rs:261-269): the
            # candidate's source node is static per position, so the link row
            # is a constant-index gather, then matched against the dst one-hot
            src_rows = link_ok[:, self._src_of_c, :]  # [L,C,N]
            keep = keep & (cand_dst_oh & src_rows).any(-1)
        # stamp each send from its EMITTING node's event time (candidate
        # positions map statically to their source node), so latency is
        # measured from the send instant, not the lane's window maximum
        deliver_at = t_evt[:, self._src_of_c] + lat.astype(jnp.int32)

        # pack survivors into their origin's ring region: candidate c owns
        # slots [c*K, (c+1)*K); the message lands in the first free slot of
        # the region, else it overflows (counted). Pure elementwise writes —
        # no [L,C,S] one-hot products.
        region_free = ~valid.reshape(L, C, K)  # [L,C,K]
        first_free = region_free & (
            jnp.cumsum(region_free.astype(jnp.int8), axis=2) == 1
        )
        place = keep[:, :, None] & first_free  # [L,C,K]
        placed = place.any(2)  # [L,C]
        written = place.reshape(L, S)

        def put(pool_vals, cand_vals):
            if cand_vals.ndim == 2:  # [L,C] -> [L,S]
                incoming = jnp.broadcast_to(
                    cand_vals[:, :, None], (L, C, K)
                ).reshape(L, S)
                return jnp.where(written, incoming, pool_vals)
            incoming = jnp.broadcast_to(  # [L,C,P] -> [L,S,P]
                cand_vals[:, :, None, :], (L, C, K, P)
            ).reshape(L, S, P)
            return jnp.where(written[:, :, None], incoming, pool_vals)

        new_valid = valid | written
        new_deliver = put(jnp.where(valid, msgs.deliver, INF_US), deliver_at)
        new_src = put(msgs.src, cand_src)
        new_dst = put(msgs.dst, cand_dst)
        new_kind = put(msgs.kind, cand_kind)
        new_payload = put(msgs.payload, cand_pay)
        overflow = state.overflow + (keep & ~placed).sum(axis=1)

        # -- 7. invariants + lane lifecycle --------------------------------
        ok = self._v_check(node, alive, clock)
        new_violation = active & ~ok & ~state.violated
        violated = state.violated | new_violation
        violation_at = jnp.where(new_violation, clock, state.violation_at)
        reached_horizon = clock >= cfg.horizon_us
        done = state.done | deadlocked | reached_horizon | violated

        new_state = SimState(
            clock=clock,
            key=key,
            done=done,
            violated=violated,
            violation_at=violation_at,
            deadlocked=state.deadlocked | deadlocked,
            steps=state.steps + active.astype(jnp.int32),
            events=state.events
            + has_msg.sum(axis=1, dtype=jnp.int32)
            + due_t.sum(axis=1, dtype=jnp.int32),
            overflow=overflow,
            alive=alive,
            crashed=crashed,
            chaos_at=chaos_at,
            link_ok=link_ok,
            partitioned=partitioned,
            part_at=part_at,
            timer=timer,
            node=node,
            msgs=MsgPool(
                valid=new_valid,
                deliver=new_deliver,
                src=new_src,
                dst=new_dst,
                kind=new_kind,
                payload=new_payload,
            ),
        )
        record = TraceRecord(
            clock=clock,
            t_evt=t_evt,
            msg_fired=has_msg,
            msg_src=m_src,
            msg_kind=m_kind,
            msg_payload=m_pay,
            timer_fired=due_t,
            crash=tr_crash,
            restart=tr_restart,
            split=tr_split,
            heal=tr_heal,
            side_mask=tr_side,
            violation=new_violation,
            deadlock=deadlocked,
        )
        return new_state, record

    # ------------------------------------------------------------------ run

    @functools.partial(jax.jit, static_argnums=(0, 2))
    def _run(self, state: SimState, max_steps: int) -> SimState:
        def cond(carry):
            s, i = carry
            return jnp.logical_and(i < max_steps, jnp.any(~s.done))

        def body(carry):
            s, i = carry
            return self._step(s), i + 1

        final, _ = jax.lax.while_loop(cond, body, (state, jnp.int32(0)))
        return final

    def run(
        self, seeds, max_steps: int = 100_000, dispatch_steps: int = 10_000
    ) -> SimState:
        """Run lanes until every lane is done (or max_steps).

        The while_loop is dispatched in chunks of `dispatch_steps`: a long
        horizon at high lane counts would otherwise be ONE device kernel
        running for minutes, which remote-tunnel TPU runtimes have been
        observed to kill (worker crash at ~70s on a 32k-lane, 24k-step
        dispatch). Chunking bounds each kernel's runtime and lets the host
        stop as soon as every lane is done, at the cost of one host sync
        per chunk. At most two programs compile (chunk size + final tail).
        """
        if dispatch_steps <= 0:
            raise ValueError(f"dispatch_steps must be positive, got {dispatch_steps}")
        state = self.init(seeds)
        remaining = max_steps
        while remaining > 0:
            n = min(dispatch_steps, remaining)
            state = self._run(state, n)
            remaining -= n
            if remaining > 0 and bool(state.done.all()):
                break
        return state

    @functools.partial(jax.jit, static_argnums=(0, 2))
    def run_steps(self, state: SimState, n_steps: int) -> SimState:
        """Fixed-step scan (benchmark-friendly: no host syncs)."""

        def body(s, _):
            return self._step(s), None

        final, _ = jax.lax.scan(body, state, None, length=n_steps)
        return final

    @functools.partial(jax.jit, static_argnums=(0, 2))
    def _run_traced(self, state: SimState, n_steps: int):
        def body(s, _):
            s2, rec = self._step_traced(s)
            return s2, rec

        return jax.lax.scan(body, state, None, length=n_steps)

    def run_traced(self, seed: int, max_steps: int = 20_000):
        """Re-run ONE seed with full event capture (the violation microscope).

        Returns (final_state, TraceRecord with [T, 1, ...] leaves). Use
        trace.extract_trace to turn the records into readable events. The
        trajectory is bit-identical to the same seed inside any batch: the
        step function is the same jitted program and all randomness is
        derived from the lane seed, never from lane position.
        """
        state = self.init(jnp.asarray([seed], jnp.uint32))
        return self._run_traced(state, max_steps)

    # ------------------------------------------------------------ sharding

    def shard_state(
        self, state: SimState, mesh: jax.sharding.Mesh, lane_axis: str = "seeds",
        node_axis: Optional[str] = None,
    ) -> SimState:
        """Shard lane (and optionally node) axes over a device mesh.

        Lanes are independent, so lane-sharding needs no collectives at all —
        the scaling-book data-parallel recipe. Node-sharding additionally
        splits per-node state; XLA inserts gathers for pool<->node routing.
        """
        P = jax.sharding.PartitionSpec

        def shard(x):
            if x.ndim == 0:
                return x
            axes: list = [lane_axis] + [None] * (x.ndim - 1)
            if node_axis is not None and x.ndim >= 2:
                axes[1] = node_axis
            return jax.device_put(
                x, jax.sharding.NamedSharding(mesh, P(*axes))
            )

        return jax.tree_util.tree_map(shard, state)


def summarize(state: SimState, spec: Optional[ProtocolSpec] = None) -> dict:
    """Host-side summary of a finished batch (bug reports with repro info).

    Pass the spec to include its `lane_metrics` diagnostics — e.g. the Raft
    spec reports how many lanes saturated their fixed-capacity log (a lane
    whose log stopped appending is a lane that stopped finding bugs; that
    must be visible, not silent).
    """
    import numpy as np

    violated = np.asarray(state.violated)
    out = {
        "lanes": int(violated.shape[0]),
        "violations": int(violated.sum()),
        "violation_lanes": np.nonzero(violated)[0].tolist()[:32],
        "deadlocked": int(np.asarray(state.deadlocked).sum()),
        "total_events": int(np.asarray(state.events).sum()),
        "total_overflow": int(np.asarray(state.overflow).sum()),
        "mean_steps": float(np.asarray(state.steps).mean()),
        "mean_virtual_secs": float(np.asarray(state.clock).mean()) / 1e6,
    }
    if spec is not None and spec.lane_metrics is not None:
        for name, arr in spec.lane_metrics(state.node).items():
            a = np.asarray(arr)
            if a.dtype == np.bool_:
                out[name] = int(a.sum())
            else:
                out[name] = float(a.mean())
    return out
