"""The batched discrete-event simulation engine: thousands of seeds per step.

This is the TPU-native re-design of the reference's executor + virtual clock +
network (SURVEY.md §3.1-3.2, §7): instead of one OS thread per seed
(runtime/builder.rs:118-136), the whole discrete-event loop is a single jitted
step function over lane-major state tensors:

    clock        [L]        virtual time per lane (int32 us OFFSET)
    epoch        [L]        rebase count: abs time = epoch * REBASE_US + off
    key          [L]        per-lane hash-chain PRNG word (see prng.py)
    alive        [L, N]     node liveness (crash/restart chaos)
    timer        [L, N]     per-node timer deadline
    node state   [L, N, ...]protocol pytree
    message pool [L, N, CK] validity bits + [L, CK] per-candidate ring

One step = (1) advance each lane to its next event WINDOW — the conservative
parallel-DES lookahead [t_next, t_next + latency_lo): messages emitted inside
the window arrive after it, so in-window events on different nodes are
causally independent, (2) per node, pick its earliest in-window event —
message delivery or timer fire, never both (per-node order is exact) — and
run `on_message`/`on_timer` with the node's own event time, (3) run
crash/restart + partition chaos (the window collapses to the exact chaos
instant on those steps), (4) roll loss + latency (+ the heavy-tail buggify
coin) for every emitted message (the `test_link` analog,
net/network.rs:261-269), stamped from the emitting node's event time, and
pack survivors into free pool slots, (5) check invariants, (6) rebase lanes
whose clock offset crossed REBASE_US (unbounded virtual time with int32
hot-path arithmetic; see spec.REBASE_US).

Pool layout (the round-4 redesign, iterated under measurement): a message's
(deliver time, kind, payload) lives ONCE in a per-candidate ring slot
(`[L, CK]`, CK = send positions x depth; see MsgPool), and only a validity
bit is kept per destination (`[L, N, CK]`). Consequences:
  * the DELIVERY side needs no destination matching at all — node n's
    pending set is the static slice `valid[:, n, :]` over the shared ring,
    and its earliest event is a plain min-reduce (the r3 layout's `[L,S,N]`
    one-hot expansions and `[L,N,S,P]` payload contraction, measured as the
    dominant step cost, are gone);
  * the PACK side is pure elementwise writes: a send takes the first of
    its K ring slots unreferenced by every destination, dst routing via a
    tiny `[L,C,N]` one-hot; with all K pending the send is dropped and
    counted (`overflow`) rather than corrupted;
  * the message's source is a compile-time constant per slot
    (`src_of_slot`), and pool bandwidth — the pool is rewritten every step,
    so its bytes are a top step cost — is ~N x smaller than materializing
    per-destination copies.

Heavy-tail (buggify) delays ride a small side pool with one region per
candidate position (`[L, C, K4]`): tail messages are rare, so the side
pool's dst-matching one-hots stay tiny while the main pool keeps its
latency bound (which is also the lookahead bound).

Lanes are embarrassingly parallel, so the lane axis shards cleanly over a
device mesh (`shard_state`); the node axis (dim 1 of every per-node tensor,
including the pool) can additionally be sharded for large clusters.

Determinism: jitted XLA programs are deterministic, and all randomness comes
from the per-lane hash-chain keys derived from the seed — one seed => one
bit-exact trajectory per backend (the per-backend determinism contract of
SURVEY.md §7 step 1). Lane-position independence: no draw ever folds the
lane INDEX, only the lane SEED, so a seed's trajectory is identical in any
batch, any chunk, any mesh sharding.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from . import bitpack, prng
from .spec import (
    EID_NONE,
    INF_GUARD,
    INF_US,
    Outbox,
    ProtocolSpec,
    REBASE_US,
    SimConfig,
    derate_horizon,
)
from ..nemesis import (
    COIN_DENOM,
    FIRE_INDEX,
    FIRE_KINDS,
    key_from_seed,
    META_SITE_DRAW,
    mutation_vocab,
    OCC_CLAUSES,
    OCC_ROW,
    RATE_CLAUSES,
    RATE_ROW,
    TRIAGE_BIT,
    NEM_SITE_CLOG_DST,
    NEM_SITE_CLOG_HEAL,
    NEM_SITE_CLOG_IV,
    NEM_SITE_CLOG_SRC,
    NEM_SITE_CRASH_DOWN,
    NEM_SITE_CRASH_IV,
    NEM_SITE_CRASH_VICTIM,
    NEM_SITE_CRASH_WIPE,
    NEM_SITE_DISK_DOWN,
    NEM_SITE_DISK_IV,
    NEM_SITE_DISK_SLOW,
    NEM_SITE_DISK_TORN,
    NEM_SITE_DISK_VICTIM,
    NEM_SITE_PART_HEAL,
    NEM_SITE_PART_IV,
    NEM_SITE_PART_SIDE,
    NEM_SITE_RECONF_DUR,
    NEM_SITE_RECONF_IV,
    NEM_SITE_RECONF_VICTIM,
    NEM_SITE_SKEW,
    NEM_SITE_SPIKE_DUR,
    NEM_SITE_SPIKE_IV,
    NET_SITE_DUP,
    NET_SITE_NEM_LOSS,
    NET_SITE_REORDER,
    NET_SITE_REORDER_EXTRA,
)


# --------------------------------------------------------------------------
# coverage instrumentation (the explorer's novelty signal; madsim_tpu/explore)
# --------------------------------------------------------------------------
# Each lane accumulates a fixed-width bitmap of EVENT CLASSES it exercised:
# one bit per hash of (node, event type, state-transition bucket), folded
# through the same murmur3 chain as every other draw. The encoding is a pure
# function of trace-visible event fields — (dst node, src, msg kind,
# payload[0] magnitude bucket) for deliveries, (node,) for timer fires — so
# the pure-Python mirror in explore.py can recompute a lane's exact bitmap
# from its TraceRecord stream (the coverage analog of the nemesis
# schedule-mirror invariant). 8192 bits ~ AFL's map scale for protocols of
# this size; collisions just merge two classes, which coverage search
# tolerates by design.

COV_WORDS = 256  # u32 words per lane bitmap
COV_BITS = COV_WORDS * 32  # 8192 coverage bits
COV_SALT = 0x5EEDC0DE  # base key of the event-class hash chain
# The event-class hash folds EXACTLY these fields, in this order, on BOTH
# faces: the in-jit chain in _step_traced (step 7b) and the pure trace
# mirror explore.cov_index. The analysis both-faces rule counts the fold
# chains in each face's source against this registry — adding a field to
# one face without the other (and without updating this tuple) is the
# silent mirror break that desyncs every recorded cov_digest downstream.
COV_FIELDS = ("node", "src", "kind", "bucket")

# the sweep segment length: how many steps one device dispatch covers.
# ONE definition — run_batch, the autotuner's default assignment and the
# smoke gates all reference it, so re-tuning the engine default can
# never leave a caller pinned to a stale copy (it is also a Tier-A knob:
# madsim_tpu/tune.py searches it per device).
DEFAULT_DISPATCH_STEPS = 10_000


class Coverage(NamedTuple):
    """Per-lane coverage accumulators (present iff BatchedSim(coverage=True)).

    `bitmap` is the event-class bitmap above. The scalars ride along as
    extra novelty features the bitmap can't express: `hiwater` is the
    message-pool occupancy high-water mark (queue-pressure regimes),
    `transitions` counts delivered/timer events whose handler actually
    CHANGED the node's state (protocol progress vs idle traffic — e.g. a
    raft lane where every AppendEntries is a no-op heartbeat scores low).
    Chaos clause x occurrence coverage lives in SimState.occ_fired, which
    also feeds the per-occurrence chaos report.
    """

    bitmap: Any  # u32 [L, COV_WORDS]
    hiwater: Any  # i32 [L] pool-occupancy high-water (main + straggler)
    transitions: Any  # i32 [L] events that changed node state


class Lineage(NamedTuple):
    """Per-lane causal-lineage plane (present iff `BatchedSim(lineage=True)`;
    docs/causality.md).

    `lam` is a per-node Lamport clock over the lane's global event-id
    scale: a timer fire ticks `lam[n] += 1`, a delivery updates
    `lam[n] = max(lam[n], sender) + 1` where `sender` is the delivered
    message's send-event id (the classic Lamport update with the sent_eid
    stamp as the sender's value — eids are assigned in step order, so the
    eid order is itself consistent with happens-before and the clock law
    `lam(deliver) > lam(send-event owner's clock at send)` holds). `eid`
    is the lane's global event counter: every delivery/timer-fire gets
    the next id, assigned in node order within a step. Neither value
    feeds any draw or any protocol state — lineage is OBSERVE-ONLY, and
    all non-lineage outputs are bit-identical with lineage on/off (pinned
    like coverage was in r7; tests/test_causal.py)."""

    lam: Any  # i32 [L,N] per-node Lamport clock (event-id scale)
    eid: Any  # u32 [L] next event id (== events processed so far)


class MsgPool(NamedTuple):
    """In-flight messages: per-destination validity + per-candidate ring.

    A send event from candidate position c (static source node) carries
    ONE (deliver time, kind, payload) — latency is rolled per candidate —
    so those fields live once in a per-candidate ring slot (c, k), and only
    the validity bit is per destination. The destination slot (n, c, k)
    references ring slot (c, k) BY POSITION. A send takes the first of its
    K ring slots that no destination still references (globally free); if
    all K are pending, the send drops (counted in `overflow`) rather than
    corrupt one in flight. This keeps pool bandwidth ~N x smaller than
    materializing per-destination copies — the pool is rewritten every
    step, so its bytes are a top step cost — and first-free placement
    needs roughly half the depth of strict rotation for burst traffic
    (measured: raft reply bursts need K=4 rotating, K=2 first-free).

    r8 compaction (docs/state_layout.md): the validity plane is stored
    BIT-PACKED along the slot axis (bool costs a full byte in HBM and the
    pool is rewritten every step), and `kind` is u8 at rest for specs
    that DECLARE msg_kind_names (the dense [0, len) enum every in-tree
    spec uses; BatchedSim validates len <= 256). A spec without declared
    kind names might use sparse values >= 256, which a u8 cast would
    silently wrap — those keep i32 kinds (BatchedSim._kind_dtype). The
    step unpacks/widens on entry and repacks on exit; use the `valid`
    property for the bool view outside the step.
    """

    valid_p: Any  # u32 [L,N,ceil(CK/32)] packed validity bits over the ring
    deliver: Any  # i32 [L,CK] (offset us)
    kind: Any  # u8 [L,CK] (i32 when msg_kind_names is undeclared)
    payload: Any  # i32 [L,CK,P]
    # lineage stamp (BatchedSim(lineage=True) only, else None — zero
    # bytes off): the send event's global eid, stored NARROW per the r8
    # narrow-field rules — u16 at rest (pool bytes are a top step cost),
    # widened back to the full u32 eid at delivery by rolling-window
    # reconstruction against the lane's eid counter (exact while fewer
    # than 65536 lane events occur during any message's flight — the
    # same reconstruction idiom as the epoch rebase; the decoder
    # verifies the bound instead of trusting it, causal.graph_from_trace)
    sent_eid: Any = None  # u16 [L,CK] | None

    @property
    def valid(self):
        """bool [L,N,CK] validity view (unpacks valid_p)."""
        return bitpack.unpack_bits(self.valid_p, self.deliver.shape[-1])


class StragPool(NamedTuple):
    """Heavy-tail straggler side pool: one region of K4 slots per candidate
    position ([L, C, K4] flattened to [L, B]); dst is dynamic (stored).
    `valid` stays an unpacked bool plane — the side pool only exists while
    buggify_delay_rate > 0 and is ~N x smaller than the main pool; dst is
    u8 at rest (node ids < 32, engine-enforced) and kind follows the main
    pool's dtype rule (u8 iff msg_kind_names is declared)."""

    valid: Any  # bool [L,B]
    deliver: Any  # i32 [L,B]
    dst: Any  # u8 [L,B]
    kind: Any  # u8 [L,B] (i32 when msg_kind_names is undeclared)
    payload: Any  # i32 [L,B,P]
    sent_eid: Any = None  # u16 [L,B] | None (lineage stamp, see MsgPool)


class NemesisState(NamedTuple):
    """Per-lane nemesis bookkeeping (present iff a schedule-level clause
    is enabled; see SimConfig `nem_*` knobs and madsim_tpu/nemesis.py).

    The occurrence counters (`*_k`) are the whole trick: every nemesis
    draw — event time delta, crash victim, partition side, clog pair —
    is indexed by (lane base key, clause site, k), a pure function of the
    SEED, never of the trajectory clock. That is what makes the fault
    schedule identical on the host twin and replayable as
    `FaultPlan.schedule(seed, ...)` without running the engine at all.
    The crash clause shares `SimState.chaos_at`/`crashed` and the
    partition clause shares `part_at`/`partitioned`/`link_ok` with the
    legacy trajectory-coupled knobs (one machinery, two time sources);
    clog and spike windows carry their own next-toggle offsets here.
    """

    crash_k: Any  # i32 [L] crash/restart cycle counter
    wipe: Any  # bool [L] current down node restarts with wiped state
    part_k: Any  # i32 [L] split/heal cycle counter
    clog_at: Any  # i32 [L] next clog toggle (offset us; INF_US disabled)
    clogged: Any  # bool [L] a directed link is currently clogged
    clog_src: Any  # i32 [L]
    clog_dst: Any  # i32 [L]
    clog_k: Any  # i32 [L]
    spike_at: Any  # i32 [L] next latency-spike toggle
    spiking: Any  # bool [L]
    spike_k: Any  # i32 [L]
    reconfig_at: Any  # i32 [L] next membership toggle (INF_US disabled)
    reconf_node: Any  # i32 [L] node currently OUT of the membership (-1 =
    #           all in; the next reconfig event is a REMOVE, else a JOIN)
    reconfig_k: Any  # i32 [L] remove/join cycle counter
    disk_at: Any  # i32 [L] next disk-fault phase toggle (INF_US disabled)
    disk_phase: Any  # i32 [L] DiskFault 3-phase cursor: 0 = healthy (next
    #           event disk_slow), 1 = degraded window open (next event
    #           disk_crash), 2 = down (next event disk_recover). The
    #           victim and torn bit are NOT carried: both are pure draws
    #           at (key0, site, disk_k), recomputed identically at every
    #           phase of occurrence k — the schedule-purity discipline
    #           applied to the carry itself
    disk_k: Any  # i32 [L] disk-fault occurrence counter (bumps at recover)
    skew_ppm: Any  # i32 [L,N] per-node timer rate skew in ppm (0 = none)
    #           | None. Integer ppm, not an f32 rate: the r8 precision fix
    #           — f32 multiply loses integer microseconds above 2^24 us
    #           (~16.7 virtual seconds); scale_delay_ppm is exact for every
    #           i32 delay. Loop-invariant: drawn once per (seed, node) at
    #           init, hoisted out of the sweep carry by split_state.


class TriageCtl(NamedTuple):
    """Per-lane shrink controls (present iff `BatchedSim(..., triage=True)`).

    The triage subsystem (madsim_tpu/triage.py) evaluates every ddmin
    shrink candidate as a LANE of one batched dispatch: all lanes share
    the full plan's compiled knobs, and these tensors switch clauses,
    individual clause occurrences, message-coin rates and the time horizon
    off PER LANE. Disabling never perturbs anything else's draws — clause
    times/victims are indexed by (lane base key, clause site, occurrence)
    and a disabled occurrence still advances the timing machinery through
    its window — so a shrink candidate IS the original seed's trajectory
    minus exactly the suppressed faults, and one compiled step program
    serves every generation of the shrink.
    """

    off: Any  # i32 [L] clause-disable bitmask over nemesis.TRIAGE_CLAUSES
    occ: Any  # i32 [L, len(OCC_CLAUSES)] occurrence-disable bitmasks (OCC_CLAUSES
    #           rows; bit k suppresses occurrence k; occurrences past the
    #           mask are always enabled — triage.py caps atoms at bit 30,
    #           the int32 sign bit being unusable)
    rate_scale: Any  # f32 [L, 3] scales the loss/dup/reorder coin rates
    #           (nemesis.RATE_CLAUSES rows; the coin is `u < rate * scale`,
    #           so a scaled-down lane's fires are a SUBSET of the full run's)
    h_epoch: Any  # i32 [L] per-lane horizon, epoch part (see REBASE_US)
    h_off: Any  # i32 [L] per-lane horizon, offset part


class RefillQueue(NamedTuple):
    """The device-resident admission queue (continuous batching, r9).

    One row per ADMISSION — a (seed, ctl genome) unit of work. The queue
    is loop-INVARIANT (ConstState side): only the cursor in `RefillLog`
    moves. Admission a < L starts resident in lane a at init; admissions
    a >= L are admitted in retirement order — when a lane violates or
    reaches its per-lane horizon, it re-inits from the next queue row
    inside the jitted step, with no host round-trip until the queue
    drains. The ctl rows exist iff the sim is in triage mode (every
    admission then carries its own clause/occurrence/rate/horizon
    genome — the ddmin and explorer refill face); a plain sweep queues
    seeds only.
    """

    seeds: Any  # u32 [A] admission seeds
    off: Any  # i32 [A] | None (triage: per-admission TriageCtl rows)
    occ: Any  # i32 [A, len(OCC_CLAUSES)] | None
    rate_scale: Any  # f32 [A, len(RATE_CLAUSES)] | None
    h_epoch: Any  # i32 [A] | None
    h_off: Any  # i32 [A] | None


class RefillLog(NamedTuple):
    """Refill-mode carry: per-lane admission bookkeeping, the queue
    cursor, occupancy counters, and the per-ADMISSION result buffers the
    decode reads in admission order (the retirement-time harvest of the
    cold accumulators a re-init would otherwise wipe).

    Everything here is donated carry (cold side): the result buffers are
    written by a masked scatter exactly once per admission — at the step
    its lane retires — and `run_refill`'s decode performs one final
    host-side harvest for lanes still mid-admission when the step budget
    ran out (the chunked path's truncation semantics)."""

    cursor: Any  # i32 [] next queue row to admit (starts at L)
    admitted: Any  # i32 [L] lane's CURRENT admission index
    step_cap: Any  # i32 [] per-ADMISSION step budget == the chunked
    #            path's max_steps: an admission reaching it retires
    #            TRUNCATED (violated as-is, normally False) exactly like
    #            a chunked lane at its loop bound — without this, a
    #            violation past max_steps would be found by refill but
    #            not by the chunked twin (or vice versa under skewed
    #            retirement), breaking per-admission bit-identity
    iters: Any  # i32 [] sweep-loop iterations run (occupancy denominator)
    busy: Any  # i32 [L] per-lane active-step count (occupancy numerator)
    # -- per-admission result rows ([A, ...]; written at retirement) --
    retired: Any  # i32 [A] global step index at retirement (-1 = live)
    violated: Any  # bool [A]
    deadlocked: Any  # bool [A]
    violation_at: Any  # i32 [A] (offset us; INF_US = none)
    violation_epoch: Any  # i32 [A]
    violation_step: Any  # i32 [A] first violating step of the ADMISSION
    #            (admission-relative: its own `steps` counter, exactly
    #             what the chunked path records for the same seed)
    steps: Any  # i32 [A]
    events: Any  # i32 [A]
    overflow: Any  # i32 [A]
    dead_drops: Any  # i32 [A]
    nonmember_drops: Any  # i32 [A]
    unsynced_loss: Any  # i32 [A]
    clock: Any  # i32 [A] final clock offset at retirement
    epoch: Any  # i32 [A]
    fires: Any  # i32 [A, len(FIRE_KINDS)]
    occ_fired: Any  # u32 [A, len(OCC_CLAUSES)] | None
    cov_bitmap: Any  # u32 [A, COV_WORDS] | None (coverage mode)
    cov_hiwater: Any  # i32 [A] | None
    cov_transitions: Any  # i32 [A] | None


class DevLoopPlan(NamedTuple):
    """STATIC shape/vocabulary parameters of the device-resident search
    loop (r19, docs/explore.md): everything the traced generation-boundary
    program bakes in as Python constants. Fixed at `BatchedSim(...,
    devloop=plan)` construction — the jitted step caches on the sim, so a
    plan change needs a new sim (exactly like triage/coverage flags).

    The population split and mutation vocabulary MIRROR the host
    `Explorer` field-for-field (build both through `make_devloop_plan` so
    they cannot drift): `ops` is the weighted op menu `Explorer._mutate`
    draws from, `sched_rows`/`tog_bits`/`rate_rows` the per-op choice
    tables, and the fresh/mutant/swarm counts use the Explorer's exact
    integer-truncation arithmetic."""

    pop: int  # A — candidates per generation (== the admission queue)
    top_k: int  # K — corpus-ring capacity (the host's top_k)
    seen_cap: int  # S — dedup-table capacity (append-only rows)
    n_fresh: int
    n_mut: int
    n_swarm: int
    swarm_group: int
    fresh_stride: int
    full_h: int  # the config horizon (genome horizon 0 decodes to this)
    ops: Tuple[str, ...]  # weighted mutation-op menu, host order
    sched_rows: Tuple[int, ...]  # OCC_ROW of each enabled schedule clause
    tog_bits: Tuple[int, ...]  # TRIAGE_BIT of each togglable clause
    rate_rows: Tuple[int, ...]  # RATE_ROW of each scalable message clause


def make_devloop_plan(
    config: SimConfig, pop: int, top_k: int = 16,
    seen_cap: int = 1 << 17, fresh_frac: float = 0.5,
    mutant_frac: float = 0.3, swarm_group: int = 8,
    fresh_stride: int = 1,
) -> DevLoopPlan:
    """Derive the device-loop plan from a compiled SimConfig with the
    SAME vocabulary source (`nemesis.mutation_vocab`) and split
    arithmetic as `explore.Explorer.__init__` / `_population`, so the
    in-jit mutator and the host mirror can never disagree about which
    clauses are togglable or how a generation splits."""
    cfg = config
    sched, rate, togglable = mutation_vocab(cfg)
    ops: list = []
    if sched:
        ops += ["occ"] * 3
    if togglable:
        ops += ["clause"] * 2
    if rate:
        ops.append("rate")
    ops.append("horizon")
    L = int(pop)
    n_mut = int(L * float(mutant_frac))
    n_fresh = int(L * float(fresh_frac))
    n_swarm = L - n_mut - n_fresh if togglable else 0
    n_fresh = L - n_mut - n_swarm
    if seen_cap & (seen_cap - 1):
        raise ValueError(f"seen_cap must be a power of two, got {seen_cap}")
    return DevLoopPlan(
        pop=L,
        top_k=int(top_k),
        seen_cap=int(seen_cap),
        n_fresh=n_fresh,
        n_mut=n_mut,
        n_swarm=n_swarm,
        swarm_group=max(1, int(swarm_group)),
        fresh_stride=max(1, int(fresh_stride)),
        full_h=int(cfg.horizon_us),
        ops=tuple(ops),
        sched_rows=tuple(OCC_ROW[n] for n in sched),
        tog_bits=tuple(TRIAGE_BIT[n] for n in togglable),
        rate_rows=tuple(RATE_ROW[n] for n in rate),
    )


class DevLoop(NamedTuple):
    """Device-resident search-loop carry (r19): the corpus ring, the
    global coverage union, the genome-dedup table, the MetaRng cursor and
    the per-generation result archives — everything the host explorer
    used to rebuild between generations, now donated cold carry so a
    whole WINDOW of generations runs as one dispatch chain with zero
    host sync (decode happens once, in `devloop_results`).

    Capacities are array shapes (A = plan.pop admissions, K = plan.top_k
    ring rows, S = plan.seen_cap dedup rows, G = the window's generation
    count), so they are jit cache keys like every other shape.

    DETERMINISM: every value here is a pure function of (uploaded search
    state, meta-seed counter chain, admission results) — the boundary
    folds admissions in ADMISSION ORDER (the same order the host
    `_fold_part` replays), the ring is the host corpus's stable
    top-K-by-novelty exactly (insertion keeps ties in admission order),
    and dedup compares the SAME 64-bit genome hash both faces compute
    (nemesis.GENOME_H1/H2), so a hash collision — the only divergence a
    hash-based set can introduce — hits both loops identically."""

    # meta-rng cursor (the host MetaRng's (seed-key, counter) pair)
    meta_key: Any  # u32 [] key_from_seed(meta_seed)
    counter: Any  # i32 [] next MetaRng draw index
    next_fresh: Any  # u32 [] next fresh-seed value (advances by stride)
    gens_done: Any  # i32 [] generations fully executed + archived
    target_gens: Any  # i32 [] generations this window must run (== G)
    accepts: Any  # i32 [] corpus-ring admissions this window (telemetry)
    # corpus ring: top-K genomes by novelty, sorted desc, stable ties
    ring_n: Any  # i32 [] valid rows
    ring_bits: Any  # i32 [K] new_bits at admission (the sort key)
    ring_seed: Any  # u32 [K]
    ring_off: Any  # i32 [K]
    ring_occ: Any  # i32 [K, len(OCC_CLAUSES)]
    ring_rate: Any  # f32 [K, len(RATE_CLAUSES)]
    ring_h: Any  # i32 [K] raw genome horizon (0 = full)
    # global coverage union (the novelty reference)
    union: Any  # u32 [COV_WORDS]
    # genome-dedup table: append-only (h1, h2) rows; membership is an
    # exact masked compare over the valid prefix, so row ORDER never
    # affects a dedup decision — only set contents do
    seen_h1: Any  # u32 [S]
    seen_h2: Any  # u32 [S]
    seen_n: Any  # i32 []
    # current generation's provenance (the queue holds the ctl ENCODING,
    # which is lossy: genome horizon 0 encodes as the full horizon)
    gen_h_raw: Any  # i32 [A] raw genome horizons of the live generation
    gen_origin: Any  # i32 [A] 0 = fresh, 1 = mutant, 2 = swarm
    # per-generation archives, written at each generation boundary —
    # the ONE host sync per window decodes these
    arch_seed: Any  # u32 [G, A]
    arch_off: Any  # i32 [G, A]
    arch_occ: Any  # i32 [G, A, len(OCC_CLAUSES)]
    arch_rate: Any  # f32 [G, A, len(RATE_CLAUSES)]
    arch_h: Any  # i32 [G, A] raw genome horizons
    arch_origin: Any  # i32 [G, A]
    arch_violated: Any  # bool [G, A]
    arch_bitmap: Any  # u32 [G, A, COV_WORDS]
    arch_hiwater: Any  # i32 [G, A]
    arch_transitions: Any  # i32 [G, A]


# origin enum shared by DevLoop.gen_origin / arch_origin and the host
# decode (explore.Candidate.origin strings, in enum order)
DEVLOOP_ORIGINS = ("fresh", "mutant", "swarm")


def default_ctl(L: int, horizon_us: int) -> TriageCtl:
    """The no-op ctl: every clause and occurrence on, full horizon."""
    eh, oh = divmod(int(horizon_us), REBASE_US)
    return TriageCtl(
        off=jnp.zeros((L,), jnp.int32),
        occ=jnp.zeros((L, len(OCC_CLAUSES)), jnp.int32),
        rate_scale=jnp.ones((L, len(RATE_CLAUSES)), jnp.float32),
        h_epoch=jnp.full((L,), eh, jnp.int32),
        h_off=jnp.full((L,), oh, jnp.int32),
    )


def _clause_on(ctl: TriageCtl, name: str) -> jnp.ndarray:
    """bool [L]: clause `name` enabled per lane."""
    return (ctl.off & TRIAGE_BIT[name]) == 0


def _occ_on(ctl: TriageCtl, name: str, k) -> jnp.ndarray:
    """bool [L]: occurrence `k` of schedule clause `name` enabled per lane
    (k: i32 [L], the lane's current occurrence counter)."""
    bit = (
        ctl.occ[:, OCC_ROW[name]].astype(jnp.uint32)
        >> jnp.clip(k, 0, 31).astype(jnp.uint32)
    ) & jnp.uint32(1)
    return _clause_on(ctl, name) & ((bit == 0) | (k >= 32))


class TraceRecord(NamedTuple):
    """One step's observable events, for per-lane violation traces.

    The reference's DX promise is an exact, inspectable repro from the
    printed seed (runtime/mod.rs:194-199). On device the equivalent is this
    record stream: re-running one violating seed through the SAME jitted
    step function yields every delivery, timer fire, crash/restart and
    partition event with virtual timestamps — debuggable without the host
    twin. All leaves are [L, ...]; tracing runs use L=1. Times are offsets;
    absolute = epoch * REBASE_US + offset (trace.extract_trace combines).
    """

    clock: Any  # i32 [L]
    epoch: Any  # i32 [L]
    t_evt: Any  # i32 [L,N] virtual time of node n's event this step
    msg_fired: Any  # bool [L,N] message delivered to node n this step
    msg_src: Any  # i32 [L,N]
    msg_kind: Any  # i32 [L,N]
    msg_payload: Any  # i32 [L,N,P]
    timer_fired: Any  # bool [L,N]
    crash: Any  # i32 [L] node crashed this step, -1 = none
    restart: Any  # i32 [L] node restarted this step, -1 = none
    split: Any  # bool [L] partition split happened this step
    heal: Any  # bool [L] partition healed this step
    side_mask: Any  # i32 [L] bitmask of nodes on side A after a split
    violation: Any  # bool [L] invariant first violated this step
    deadlock: Any  # bool [L]
    clog_src: Any  # i32 [L] link clogged src this step, -1 = none
    clog_dst: Any  # i32 [L]
    unclog: Any  # bool [L] link unclogged this step
    spike_on: Any  # bool [L] latency spike opened this step
    spike_off: Any  # bool [L]
    remove: Any  # i32 [L] node removed from membership this step, -1 = none
    join: Any  # i32 [L] node (re)joined this step (fresh-init), -1 = none
    disk_slow: Any  # i32 [L] disk degraded-window opened on node, -1 = none
    disk_crash: Any  # i32 [L] disk died on node (unsynced loss), -1 = none
    disk_recover: Any  # i32 [L] node recovered from watermark, -1 = none
    disk_torn: Any  # bool [L] the occurrence's torn-write coin (marked on
    #           the crash and recover halves; the torn tail itself is a
    #           host-face FsSim effect and a device-face on_recover input)
    # -- lineage plane (BatchedSim(lineage=True) only, else None): the
    # device edge ring. Each step's events carry their global event id
    # and, for deliveries, the RECONSTRUCTED full send eid — so a traced
    # replay's record stream IS the (send_eid -> deliver_eid) edge list,
    # with zero extra carry (untraced callers discard the record and XLA
    # DCEs its construction like the rest of the trace).
    lam: Any = None  # i32 [L,N] post-step Lamport clocks
    evt_eid: Any = None  # u32 [L,N] this step's event id (EID_NONE = none)
    sent_eid: Any = None  # u32 [L,N] delivered msg's send eid (EID_NONE)


class SimState(NamedTuple):
    """The full per-lane state pytree (the sweep carry).

    r8 layout discipline (docs/state_layout.md, tests/test_state_layout.py):
    the fields split three ways for the sweep loop —

      HOT    mutated by (nearly) every step: clocks, keys, pools, timers,
             chaos cursors, node state. Carried through the while_loop.
      COLD   write-rarely / accumulate-only metadata (violation records,
             counters, fire masks, coverage): still carried (XLA aliases
             the carry in place) but grouped in ColdState so the layout
             lint can hold its growth separately.
      CONST  loop-invariant (key0, ctl, skew_ppm): split OUT of the
             while_loop carry entirely by split_state — the step reads
             them as invariant operands and never rewrites them, so they
             stop being re-materialized by every fused step.

    Bool planes (alive, link_ok, pool validity) are stored bit-packed
    (bitpack.py); the `alive` / `link_ok` properties give the bool view.
    """

    clock: Any  # i32 [L] (offset us; see epoch)
    epoch: Any  # i32 [L] rebase count (abs = epoch * REBASE_US + clock)
    key: Any  # u32 [L] (hash-chain, prng.py)
    key0: Any  # u32 [L] the lane's BASE key (constant; nemesis draws
    #           index off it so fault schedules are trajectory-free)
    done: Any  # bool [L]
    violated: Any  # bool [L]
    violation_at: Any  # i32 [L] (offset; INF_US = none)
    violation_epoch: Any  # i32 [L]
    violation_step: Any  # i32 [L] first violating step index (-1 = none;
    #            with run(max_steps=step+1) this is the run-to-step
    #            truncation handle the triage shrinker bisects to)
    deadlocked: Any  # bool [L]
    steps: Any  # i32 [L]
    events: Any  # i32 [L]
    overflow: Any  # i32 [L] (messages dropped: pool full)
    dead_drops: Any  # i32 [L] (messages dropped: destination node down —
    #            distinct from `overflow` so graceful-degradation
    #            assertions can tell pool pressure from crash fallout)
    nonmember_drops: Any  # i32 [L] (messages dropped: destination not a
    #            cluster MEMBER — removed by the reconfig clause. Checked
    #            before liveness, so the classes are disjoint: a crashed
    #            member counts in dead_drops, a removed node here)
    unsynced_loss: Any  # i32 [L] disk crashes that lost unsynced durable
    #            state: the victim's durable fields differed from its
    #            watermark at the crash instant (every disk crash counts
    #            when the spec declares no durable_fields — the whole
    #            state is then unsynced by definition). Always present,
    #            like nonmember_drops: a zero column when the DiskFault
    #            clause is off costs nothing and spares every consumer
    #            an Optional branch
    fires: Any  # i32 [L, len(FIRE_KINDS)] per-fault-kind chaos fire counts
    occ_fired: Any  # u32 [L, len(OCC_CLAUSES)] | None — bit k set when
    #            occurrence k of the schedule clause APPLIED in this lane
    #            (occurrences >= 31 fold into bit 31; triage caps its atoms
    #            at bit 30 so the fold never aliases a shrinkable atom).
    #            None unless a nemesis schedule clause is enabled. This is
    #            the clause x occurrence half of the coverage signal AND the
    #            raw data of the per-occurrence chaos report.
    alive_p: Any  # u32 [L,1] packed node-liveness bits (N <= 32)
    crashed: Any  # i32 [L] (node id currently down, -1 = none)
    chaos_at: Any  # i32 [L] (next crash/restart event)
    member_p: Any  # u32 [L,1] packed cluster-MEMBERSHIP bits (the reconfig
    #           clause's plane; all-ones when the clause is off). Liveness
    #           and membership are independent axes: a removed node keeps
    #           its alive bit state, but non-members receive nothing
    #           (sends to them count in nonmember_drops) and a join
    #           rebuilds the node from the real _init (fresh replica).
    member_epoch: Any  # i32 [L] membership-epoch counter: increments on
    #           every remove AND every join (the reconfig clause's
    #           configuration-change ordinal, exposed to traces/summaries)
    link_ok_p: Any  # u32 [L,N,1] packed directed-link-up bits, row = src
    partitioned: Any  # bool [L] (a partition is currently active)
    part_at: Any  # i32 [L] (next partition split/heal event)
    timer: Any  # i32 [L,N]
    node: Any  # protocol pytree, leaves [L,N,...] (fields named in
    #           spec.narrow_fields are stored at their narrow dtypes and
    #           widened to i32 before every handler call)
    dur: Any  # durable WATERMARK | None — the DiskFault clause's
    #           durability plane (None unless nem_disk is enabled AND the
    #           spec declares durable_fields). A namedtuple over
    #           spec.durable_fields with leaves [L,N,...] at the same
    #           at-rest (narrowed) dtypes as the node carry: the last
    #           value of each durable field the node made it to disk.
    #           Initialized from spec.init (boot is fsynced), re-snapshot
    #           whenever spec.sync_field increases (the spec's declared
    #           fsync points), reset to the node's fresh state on
    #           wipe / join / disk-recover. A disk crash recovery
    #           rebuilds the victim FROM this plane, not from live state
    msgs: MsgPool
    strag: Any  # StragPool | None (None unless buggify_delay_rate > 0)
    nem: Any  # NemesisState | None (None unless a nemesis clause is on)
    ctl: Any  # TriageCtl | None (None unless BatchedSim(triage=True))
    cov: Any  # Coverage | None (None unless BatchedSim(coverage=True))
    lin: Any  # Lineage | None (None unless BatchedSim(lineage=True)):
    #           per-node Lamport clocks + the global per-lane event
    #           counter — hot carry, rewritten every step
    queue: Any  # RefillQueue | None — loop-invariant admission queue
    #           (None unless the state was built by init_refill; see
    #           docs/continuous_batching.md)
    refill: Any  # RefillLog | None — refill carry: queue cursor, per-lane
    #           admission ids, occupancy counters, per-admission results
    loop: Any = None  # DevLoop | None — device-resident search carry
    #           (None unless the state was built by init_devloop; r19,
    #           docs/explore.md). Trailing with a default so every
    #           existing positional/keyword construction site stays
    #           valid. Requires refill mode: the generation boundary
    #           rides _refill_apply's retire path.

    @property
    def alive(self):
        """bool [L,N] node-liveness view (unpacks alive_p)."""
        return bitpack.unpack_bits(self.alive_p, self.timer.shape[1])

    @property
    def link_ok(self):
        """bool [L,N,N] directed-link view (unpacks link_ok_p)."""
        return bitpack.unpack_bits(self.link_ok_p, self.timer.shape[1])

    @property
    def member(self):
        """bool [L,N] cluster-membership view (unpacks member_p)."""
        return bitpack.unpack_bits(self.member_p, self.timer.shape[1])


class ColdState(NamedTuple):
    """The accumulate-only half of the sweep carry (see SimState). Grouped
    so the state-layout lint budgets hot and cold bytes separately and the
    split is visible in the compiled program's carry structure."""

    violation_at: Any
    violation_epoch: Any
    violation_step: Any
    deadlocked: Any
    steps: Any
    events: Any
    overflow: Any
    dead_drops: Any
    nonmember_drops: Any
    unsynced_loss: Any
    fires: Any
    occ_fired: Any
    cov: Any
    refill: Any  # RefillLog | None (refill mode only): the result
    #            buffers accumulate, the cursor advances rarely — cold
    loop: Any  # DevLoop | None (device-loop mode only): corpus ring,
    #            union bitmap, seen table, generation archives — touched
    #            once per generation boundary, cold by construction


COLD_FIELDS = ColdState._fields


class ConstState(NamedTuple):
    """Loop-invariant lane state, split OUT of the sweep carry: the step
    reads these but never writes them, so keeping them in the while_loop
    carry made every fused step re-emit them as outputs (copied bytes per
    step, and per-segment donation rotation). key0 feeds every
    schedule-pure nemesis draw; ctl is the triage shrinker's per-lane
    switchboard; skew_ppm the per-(seed, node) clock-skew assignment.

    REFILL mode inverts the first three: a refilled lane adopts a NEW
    seed's key0/ctl/skew mid-sweep, so those become carry and the only
    loop invariant left is the admission queue itself (the queue rows
    never change; only RefillLog's cursor moves)."""

    key0: Any
    ctl: Any
    skew_ppm: Any
    queue: Any  # RefillQueue | None (refill mode only)


def split_state(state: SimState):
    """SimState -> (hot, cold, const) for the sweep loop. Pure pytree
    restructuring: no data moves, the leaves are the same buffers.

    Two partitions, selected by the state's structure:
      * plain sweeps: const = (key0, ctl, skew_ppm) — the r8 split;
      * refill sweeps (state.refill is not None): key0/ctl/skew_ppm
        STAY IN THE CARRY (a refilled lane rewrites them from its new
        admission), and const = the admission queue alone;
      * device-loop sweeps (state.loop is not None): NOTHING is loop-
        invariant — the generation boundary rewrites even the admission
        queue from the mutated corpus ring, so the queue rides the
        carry and const is empty."""
    nem = state.nem
    cold = ColdState(*(getattr(state, f) for f in COLD_FIELDS))
    if state.loop is not None:
        hot = state._replace(**{f: None for f in COLD_FIELDS})
        const = ConstState(key0=None, ctl=None, skew_ppm=None, queue=None)
        return hot, cold, const
    if state.refill is not None:
        hot = state._replace(
            queue=None, **{f: None for f in COLD_FIELDS},
        )
        const = ConstState(
            key0=None, ctl=None, skew_ppm=None, queue=state.queue,
        )
        return hot, cold, const
    hot = state._replace(
        key0=None, ctl=None, queue=None,
        nem=None if nem is None else nem._replace(skew_ppm=None),
        **{f: None for f in COLD_FIELDS},
    )
    const = ConstState(
        key0=state.key0, ctl=state.ctl,
        skew_ppm=None if nem is None else nem.skew_ppm,
        queue=None,
    )
    return hot, cold, const


def merge_state(hot: SimState, cold: ColdState, const: ConstState) -> SimState:
    """(hot, cold, const) -> flat SimState (inverse of split_state)."""
    if cold.loop is not None:  # device-loop partition: const is empty,
        # the queue never left the hot carry — just graft cold back on
        return hot._replace(**dict(zip(COLD_FIELDS, cold)))
    if const.queue is not None:  # refill partition: key0/ctl/skew in hot
        return hot._replace(
            queue=const.queue, **dict(zip(COLD_FIELDS, cold)),
        )
    nem = hot.nem
    if nem is not None:
        nem = nem._replace(skew_ppm=const.skew_ppm)
    return hot._replace(
        key0=const.key0, ctl=const.ctl, nem=nem,
        **dict(zip(COLD_FIELDS, cold)),
    )


def named_leaves(tree: Any, prefix: str = "") -> list:
    """(dotted-path, leaf) pairs in jax flatten order, with NamedTuple
    FIELD NAMES instead of positional keys (tree_flatten_with_path only
    yields indices for namedtuples). None subtrees are dropped, matching
    tree_leaves. The analysis verifier keys its per-leaf rules (taint
    roots, donation coverage, narrow dtypes) on these names."""
    out: list = []

    def rec(name, obj):
        if obj is None:
            return
        if hasattr(obj, "_fields"):  # NamedTuple node
            for f in obj._fields:
                rec(f"{name}.{f}" if name else f, getattr(obj, f))
        elif isinstance(obj, (tuple, list)):
            for i, v in enumerate(obj):
                rec(f"{name}[{i}]" if name else f"[{i}]", v)
        elif isinstance(obj, dict):
            for k in sorted(obj):
                rec(f"{name}[{k!r}]" if name else f"[{k!r}]", obj[k])
        else:
            out.append((name, obj))

    rec(prefix, tree)
    return out


def carry_partition(state: SimState) -> dict:
    """{'hot'|'cold'|'const' -> [leaf path]} for the sweep-loop split.

    The donated-leaf introspection hook for the static verifier
    (madsim_tpu/analysis): hot + cold are the while_loop carry (donated
    across dispatch boundaries); const rides as a loop-invariant operand
    and must never be donated, rotated, or re-emitted per step."""
    hot, cold, const = split_state(state)
    return {
        "hot": [n for n, _ in named_leaves(hot)],
        "cold": [n for n, _ in named_leaves(cold)],
        "const": [n for n, _ in named_leaves(const)],
    }


def interval_hints(
    sim: "BatchedSim", refill: bool = False, devloop: bool = False,
) -> dict:
    """{carry leaf name -> (lo, hi, may_inf)} seed intervals for the
    ENGINE-OWNED leaves, keyed by the `named_leaves` hot/cold/const paths.

    `refill=True` keys the hints for the refill carry partition (key0 /
    ctl / skew_ppm live under `hot.`, the queue under `const.queue.`)
    and adds the RefillLog leaves — notably the queue cursor and the
    per-admission `retired` step rows the range certifier must bound.

    `devloop=True` (implies refill) keys the device-loop partition: the
    queue ALSO rides the carry (`hot.queue.*` — the generation boundary
    rewrites it from the mutated ring), and the `cold.loop.*` DevLoop
    leaves gain rows — notably the ring/seen cursors every dynamic
    ring-scatter index is clipped against.

    The introspection hook behind the Layer-3 range certifier
    (analysis/ranges.py): these are the engine's own documented value
    invariants — live time OFFSETS stay below INF_GUARD (the rebase
    guard `rb` relies on exactly this: values >= INF_GUARD are sentinels
    and are never rebased), node ids index [0, N), occurrence counters
    and diagnostic counters stay far from i32 overflow — stated where
    the invariants LIVE so the analyzer cannot drift from the engine.
    `may_inf` marks leaves that may additionally hold the INF_US
    sentinel exactly (disarmed timers, empty pool slots, disabled
    chaos). Leaves NOT named here are protocol-owned (node state,
    payloads) and are seeded by the analyzer from the spec's own
    declarations (narrow_fields / rate_floors / time_fields)."""
    cfg = sim.config
    N = sim.spec.n_nodes
    off_hi = int(INF_GUARD) - 1  # live-offset invariant (see rb())
    ctr_hi = 1 << 30  # diagnostics counters: far below i32 wrap
    ep_hi = 1 << 22  # epochs: ~35k virtual years of rebase headroom
    u32 = (0, (1 << 32) - 1, False)
    toff = (-1, off_hi, True)  # time offset; -1 = "keep/disarm" in flight
    hints = {
        "hot.clock": (0, off_hi, True),
        "hot.epoch": (0, ep_hi, False),
        "hot.key": u32,
        "hot.done": (0, 1, False),
        "hot.violated": (0, 1, False),
        "hot.alive_p": u32,
        "hot.crashed": (-1, N - 1, False),
        "hot.chaos_at": toff,
        "hot.link_ok_p": u32,
        "hot.partitioned": (0, 1, False),
        "hot.part_at": toff,
        "hot.timer": toff,
        "hot.msgs.valid_p": u32,
        "hot.msgs.deliver": toff,
        "hot.strag.valid": (0, 1, False),
        "hot.strag.deliver": toff,
        "hot.strag.dst": (0, N - 1, False),
        "hot.nem.crash_k": (0, ctr_hi, False),
        "hot.nem.wipe": (0, 1, False),
        "hot.nem.part_k": (0, ctr_hi, False),
        "hot.nem.clog_at": toff,
        "hot.nem.clogged": (0, 1, False),
        "hot.nem.clog_src": (0, N - 1, False),
        "hot.nem.clog_dst": (0, N - 1, False),
        "hot.nem.clog_k": (0, ctr_hi, False),
        "hot.nem.spike_at": toff,
        "hot.nem.spiking": (0, 1, False),
        "hot.nem.spike_k": (0, ctr_hi, False),
        "hot.nem.reconfig_at": toff,
        "hot.nem.reconf_node": (-1, N - 1, False),
        "hot.nem.reconfig_k": (0, ctr_hi, False),
        "hot.nem.disk_at": toff,
        "hot.nem.disk_phase": (0, 2, False),
        "hot.nem.disk_k": (0, ctr_hi, False),
        "hot.member_p": u32,
        "hot.member_epoch": (0, ctr_hi, False),
        "cold.violation_at": toff,
        "cold.violation_epoch": (0, ep_hi, False),
        "cold.violation_step": (-1, ctr_hi, False),
        "cold.deadlocked": (0, 1, False),
        "cold.steps": (0, ctr_hi, False),
        "cold.events": (0, ctr_hi, False),
        "cold.overflow": (0, ctr_hi, False),
        "cold.dead_drops": (0, ctr_hi, False),
        "cold.nonmember_drops": (0, ctr_hi, False),
        "cold.unsynced_loss": (0, ctr_hi, False),
        "cold.fires": (0, ctr_hi, False),
        "cold.occ_fired": u32,
        "cold.cov.bitmap": u32,
        "cold.cov.hiwater": (0, ctr_hi, False),
        "cold.cov.transitions": (0, ctr_hi, False),
        # causal-lineage plane (lineage=True): the eid counter gains one
        # per processed event, so it shares the diagnostics-counter
        # invariant (events << 2^31 per admission); Lamport clocks live
        # on the same event-id scale (max(local, send eid)+1 adds at most
        # one per event); the pool stamp is the send eid's low 16 bits
        "hot.lin.lam": (0, ctr_hi, False),
        "hot.lin.eid": (0, ctr_hi, False),
        "hot.msgs.sent_eid": (0, (1 << 16) - 1, False),
        "hot.strag.sent_eid": (0, (1 << 16) - 1, False),
        "const.key0": u32,
        "const.ctl.off": (0, (1 << 31) - 1, False),
        "const.ctl.occ": (0, (1 << 31) - 1, False),
        "const.ctl.rate_scale": (0, 1, False),
        "const.ctl.h_epoch": (0, ep_hi, False),
        "const.ctl.h_off": (0, REBASE_US - 1, False),
        "const.skew_ppm": (
            -cfg.nem_skew_max_ppm, cfg.nem_skew_max_ppm, False
        ),
    }
    n_kinds = (
        len(sim.spec.msg_kind_names)
        if sim.spec.msg_kind_names is not None else 256
    )
    hints["hot.msgs.kind"] = (0, n_kinds - 1, False)
    hints["hot.strag.kind"] = (0, n_kinds - 1, False)
    # absolute-time node fields (spec.time_fields) share the live-offset
    # invariant: they are rebased with the lane's epoch like every other
    # time tensor
    for f in sim.spec.time_fields:
        hints[f"hot.node.{f}"] = toff
    # the durability watermark mirrors node fields value-for-value: every
    # dur leaf is a SNAPSHOT of its node leaf (advance/reset both copy),
    # so it inherits the node field's interval — the certifier seeds
    # hot.dur.* from the same spec declarations as hot.node.* and these
    # engine-owned hints only exist for fields the engine itself bounds
    if refill or devloop:
        # the refill carry partition: key0/ctl/skew ride in hot (a
        # refilled lane rewrites them), only the queue is const
        ren = {
            "const.key0": "hot.key0",
            "const.skew_ppm": "hot.nem.skew_ppm",
        }
        hints = {
            ren.get(k, k.replace("const.ctl.", "hot.ctl.")): v
            for k, v in hints.items()
        }
        ctr = (0, ctr_hi, False)
        hints.update({
            # the queue cursor / admission ids are bounded by the queue
            # length at runtime; ctr_hi is the sound static envelope the
            # certifier needs (the gathers are clipped, the scatters
            # drop-moded — both provable/guarded from these seeds)
            "cold.refill.cursor": ctr,
            "cold.refill.admitted": ctr,
            "cold.refill.step_cap": ctr,
            "cold.refill.iters": ctr,
            "cold.refill.busy": ctr,
            "cold.refill.retired": (-1, ctr_hi, False),
            "cold.refill.violated": (0, 1, False),
            "cold.refill.deadlocked": (0, 1, False),
            "cold.refill.violation_at": toff,
            "cold.refill.violation_epoch": (0, ep_hi, False),
            "cold.refill.violation_step": (-1, ctr_hi, False),
            "cold.refill.steps": ctr,
            "cold.refill.events": ctr,
            "cold.refill.overflow": ctr,
            "cold.refill.dead_drops": ctr,
            "cold.refill.nonmember_drops": ctr,
            "cold.refill.unsynced_loss": ctr,
            "cold.refill.clock": (0, off_hi, True),
            "cold.refill.epoch": (0, ep_hi, False),
            "cold.refill.fires": ctr,
            "cold.refill.occ_fired": u32,
            "cold.refill.cov_bitmap": u32,
            "cold.refill.cov_hiwater": ctr,
            "cold.refill.cov_transitions": ctr,
            "const.queue.seeds": u32,
            "const.queue.off": (0, (1 << 31) - 1, False),
            "const.queue.occ": (0, (1 << 31) - 1, False),
            "const.queue.rate_scale": (0, 1, False),
            "const.queue.h_epoch": (0, ep_hi, False),
            "const.queue.h_off": (0, REBASE_US - 1, False),
        })
    if devloop:
        # device-loop partition: const is EMPTY — the boundary rewrites
        # the queue from the mutated ring, so its rows ride the carry
        hints = {
            k.replace("const.queue.", "hot.queue."): v
            for k, v in hints.items()
        }
        plan = sim.devloop
        K, S = plan.top_k, plan.seen_cap
        full_h = plan.full_h
        ctr = (0, ctr_hi, False)
        hints.update({
            "cold.loop.meta_key": u32,
            "cold.loop.counter": ctr,
            "cold.loop.next_fresh": u32,
            "cold.loop.gens_done": ctr,
            "cold.loop.target_gens": ctr,
            "cold.loop.accepts": ctr,
            # ring/seen cursors: the invariants every dynamic ring index
            # is clipped against (ring_n <= K, seen_n <= S by the host
            # pre-dispatch headroom check in Explorer._run_device_window)
            "cold.loop.ring_n": (0, K, False),
            "cold.loop.ring_bits": (0, COV_BITS, False),
            "cold.loop.ring_seed": u32,
            "cold.loop.ring_off": (0, (1 << 31) - 1, False),
            "cold.loop.ring_occ": (0, (1 << 31) - 1, False),
            "cold.loop.ring_rate": (0, 1, False),
            "cold.loop.ring_h": (0, full_h, False),
            "cold.loop.union": u32,
            "cold.loop.seen_h1": u32,
            "cold.loop.seen_h2": u32,
            "cold.loop.seen_n": (0, S, False),
            "cold.loop.gen_h_raw": (0, full_h, False),
            "cold.loop.gen_origin": (0, 2, False),
            "cold.loop.arch_seed": u32,
            "cold.loop.arch_off": (0, (1 << 31) - 1, False),
            "cold.loop.arch_occ": (0, (1 << 31) - 1, False),
            "cold.loop.arch_rate": (0, 1, False),
            "cold.loop.arch_h": (0, full_h, False),
            "cold.loop.arch_origin": (0, 2, False),
            "cold.loop.arch_violated": (0, 1, False),
            "cold.loop.arch_bitmap": u32,
            "cold.loop.arch_hiwater": ctr,
            "cold.loop.arch_transitions": ctr,
        })
    return hints


def scale_delay_ppm(d: jnp.ndarray, ppm) -> jnp.ndarray:
    """Stretch a non-negative i32 microsecond delay by (1 + ppm * 1e-6),
    EXACTLY, in pure int32 arithmetic: d + trunc(d * |ppm| / 1e6) * sign.

    Replaces the r1 `(d.astype(f32) * rate).astype(i32)` path, which
    loses integer precision once d exceeds 2^24 us (~16.7 virtual
    seconds — well inside a 30 s horizon). The 64-bit product d * ppm is
    decomposed into i32-safe partial products: with d = q * 1e6 + r,
    r = r1 * 1e3 + r0 and |ppm| = p1 * 1e3 + p0, every term below stays
    under 2^31 for d < 2^31 and |ppm| < 1e6 (the SimConfig validation
    bound). The host runtime mirrors the same truncation in
    core/vtime.skew_delay_ns (exact there via Python ints).
    """
    ppm = jnp.asarray(ppm, jnp.int32)
    mag = jnp.abs(ppm)
    q, r = d // 1_000_000, d % 1_000_000
    r1, r0 = r // 1000, r % 1000
    p1, p0 = mag // 1000, mag % 1000
    frac = ((r1 * p0 + r0 * p1) * 1000 + r0 * p0) // 1_000_000
    adj = q * mag + r1 * p1 + frac
    return jnp.where(ppm >= 0, d + adj, d - adj)


def _first_free(free: jnp.ndarray, K: int) -> jnp.ndarray:
    """First-free-slot mask along the last axis (length K, static).

    Unrolled prefix: K is tiny, and cumsum is a scan op that breaks XLA's
    elementwise fusion.
    """
    if K == 1:
        return free
    prev = jnp.zeros_like(free[..., 0])
    cols = []
    for k in range(K):
        cols.append(free[..., k] & ~prev)
        prev = prev | free[..., k]
    return jnp.stack(cols, axis=-1)


def _tree_where(mask: jnp.ndarray, a: Any, b: Any) -> Any:
    """Select pytree leaves by a [L,N]-shaped mask, broadcasting trailing dims."""

    def sel(x, y):
        m = mask.reshape(mask.shape + (1,) * (x.ndim - mask.ndim))
        return jnp.where(m, x, y)

    return jax.tree_util.tree_map(sel, a, b)


class BatchedSim:
    """Vectorized multi-lane simulator for one ProtocolSpec."""

    def __init__(
        self, spec: ProtocolSpec, config: Optional[SimConfig] = None,
        triage: bool = False, coverage: bool = False,
        lineage: bool = False, devloop: Optional[DevLoopPlan] = None,
    ) -> None:
        """`triage=True` threads a per-lane `TriageCtl` through the state:
        the same compiled step program then evaluates shrink candidates
        (clauses / occurrences / rates / horizons switched off per lane)
        as lanes of one dispatch — see madsim_tpu/triage.py. `coverage=True`
        additionally accumulates the per-lane Coverage bitmap + scalars the
        explorer's novelty search feeds on (madsim_tpu/explore.py).
        `lineage=True` carries the causal-lineage plane — per-node Lamport
        clocks, the global per-lane event counter, and a u16 `sent_eid`
        stamp per pool slot — so a traced replay records exact
        happens-before (send_eid -> deliver_eid) edges for
        madsim_tpu/causal.py (docs/causality.md). All off by default:
        normal sweeps pay nothing for any of them, and every non-lineage
        output is bit-identical with lineage on/off."""
        self.spec = spec
        self.config = config or SimConfig()
        self.triage = bool(triage)
        self.coverage = bool(coverage)
        self.lineage = bool(lineage)
        # `devloop` arms the device-resident search loop (r19,
        # docs/explore.md): a DevLoopPlan whose STATIC vocabulary/split
        # parameters the generation-boundary program bakes in. The loop
        # mutates TriageCtl genomes and ranks coverage novelty in-jit,
        # so both planes must be threaded.
        if devloop is not None and not (triage and coverage):
            raise ValueError(
                "devloop needs BatchedSim(..., triage=True, coverage=True) "
                "— the device loop mutates ctl genomes and ranks coverage "
                "novelty in-jit"
            )
        self.devloop = devloop
        cfg = self.config
        N = spec.n_nodes
        # fail loudly at construction, not as shape errors deep inside jit
        if N < 2:
            raise ValueError(f"spec.n_nodes must be >= 2, got {N}")
        if N > 32:
            # the packed alive/link_ok planes keep one u32 word per row
            # (and spec.majority's bitmask already caps quorum specs at 31)
            raise ValueError(
                f"spec.n_nodes must be <= 32 (packed bool planes), got {N}"
            )
        if spec.msg_kind_names is not None and len(spec.msg_kind_names) > 256:
            raise ValueError(
                "message kinds must fit u8 (pool `kind` is stored narrow): "
                f"got {len(spec.msg_kind_names)} named kinds"
            )
        # pool `kind` narrows to u8 only for specs that DECLARE their kind
        # vocabulary (msg_kind_names = the dense [0, len) enum every
        # in-tree spec uses, validated <= 256 above); an undeclared spec
        # might use sparse kind values >= 256, which a blind u8 cast would
        # silently wrap — those keep i32 kinds.
        self._kind_dtype = (
            jnp.uint8 if spec.msg_kind_names is not None else jnp.int32
        )
        # node-state leaves the spec declares narrow (docs/state_layout.md):
        # stored at the narrow dtype in the carry, widened back to i32
        # before every handler call — handlers stay wall-to-wall i32.
        self._narrow = dict(spec.narrow_fields or {})
        bad = set(self._narrow) & set(spec.time_fields)
        if bad:
            raise ValueError(
                "time_fields hold absolute epoch-rebased times and must "
                f"stay i32 — remove {sorted(bad)} from narrow_fields"
            )
        # rate_floors entries are ANALYZER metadata (analysis/ranges.py
        # reads them per narrow field; entries for fields outside the
        # live narrow table are inert — `replace(spec, narrow_fields=
        # ...)` is a documented experimentation/escape idiom and must
        # not force re-deriving the floor table). Only the entry TYPES
        # are validated here, so a malformed declaration fails at
        # construction rather than silently un-certifying a field.
        from .spec import HardCap, RateFloor

        for fname, entry in (spec.rate_floors or {}).items():
            if not isinstance(entry, (RateFloor, HardCap)):
                raise ValueError(
                    f"rate_floors[{fname!r}] must be a RateFloor or "
                    f"HardCap, got {type(entry).__name__}"
                )
        if self._narrow and spec.narrow_horizon_us is not None:
            # rate-argument narrow bounds ("one tid per coordinator-timer
            # floor") only hold up to the spec-declared horizon; past it
            # a narrow counter would wrap SILENTLY — refuse instead.
            # The cap derates with the config's clock skew through the
            # SAME helper the range certifier uses (spec.derate_horizon),
            # so refusal and certificate can never disagree.
            cap = derate_horizon(
                spec.narrow_horizon_us,
                cfg.nem_skew_max_ppm if cfg.nem_skew_enabled else 0,
            )
            if cfg.horizon_us > cap:
                raise ValueError(
                    f"horizon_us={cfg.horizon_us} exceeds this spec's "
                    f"narrow-dtype safe horizon ({cap} us"
                    + (" after clock-skew derating"
                       if cfg.nem_skew_enabled else "")
                    + "): strip spec.narrow_fields (dataclasses.replace("
                    "spec, narrow_fields=None)) for long soaks, or "
                    "shorten the horizon"
                )
        if spec.payload_width < 1 or spec.max_out < 1 or spec.max_out_msg < 1:
            raise ValueError(
                "spec payload_width / max_out / max_out_msg must be >= 1 "
                f"(got {spec.payload_width}/{spec.max_out}/{spec.max_out_msg})"
            )
        if cfg.latency_lo_us < 0 or cfg.latency_hi_us < cfg.latency_lo_us:
            raise ValueError(
                f"latency range [{cfg.latency_lo_us}, {cfg.latency_hi_us}] "
                "must satisfy 0 <= lo <= hi"
            )
        if not (0.0 <= cfg.loss_rate < 1.0):
            raise ValueError(f"loss_rate must be in [0, 1), got {cfg.loss_rate}")
        if cfg.horizon_us <= 0:
            raise ValueError(f"horizon_us must be positive, got {cfg.horizon_us}")
        for name in ("msg_depth_msg", "msg_depth_timer"):
            v = getattr(cfg, name)
            if v is not None and v < 1:
                raise ValueError(f"{name} must be >= 1, got {v}")
        if cfg.msg_spare_slots < 0:
            raise ValueError(
                f"msg_spare_slots must be >= 0, got {cfg.msg_spare_slots}"
            )
        if spec.on_event is None and cfg.msg_spare_slots > 0:
            raise ValueError(
                "msg_spare_slots only applies to fused (on_event) specs — "
                "the two-handler path places per-candidate rings; use "
                "msg_depth_msg/msg_depth_timer there"
            )
        # nemesis knobs: validate here with the same messages as the host
        # config layer, and reject legacy+nemesis combos for the same
        # machinery (the two time sources would fight over chaos_at)
        for name in (
            "nem_loss_rate", "nem_dup_rate", "nem_reorder_rate",
            "nem_crash_wipe_rate", "nem_disk_torn_rate",
        ):
            v = getattr(cfg, name)
            if not (0.0 <= v < 1.0):
                raise ValueError(f"{name} must be in [0, 1), got {v}")
        if cfg.nem_crash_enabled and cfg.chaos_enabled:
            raise ValueError(
                "nem_crash_* and crash_interval_* cannot both be enabled — "
                "one crash machinery, one time source (use the FaultPlan)"
            )
        if cfg.nem_partition_enabled and cfg.partition_enabled:
            raise ValueError(
                "nem_partition_* and partition_interval_* cannot both be "
                "enabled — one partition machinery, one time source"
            )
        for prefix, pairs in (
            ("nem_crash", (("interval", True), ("down", False))),
            ("nem_partition", (("interval", True), ("heal", False))),
            ("nem_clog", (("interval", True), ("heal", False))),
            ("nem_spike", (("interval", True), ("duration", False))),
            ("nem_reconfig", (("interval", True), ("down", False))),
            ("nem_disk", (("interval", True), ("slow", False), ("down", False))),
        ):
            if getattr(cfg, f"{prefix}_interval_hi_us") <= 0:
                continue  # clause disabled
            for part, _is_iv in pairs:
                lo = getattr(cfg, f"{prefix}_{part}_lo_us")
                hi = getattr(cfg, f"{prefix}_{part}_hi_us")
                if lo < 0 or hi < lo or hi <= 0:
                    raise ValueError(
                        f"{prefix}_{part} range [{lo}, {hi}] must satisfy "
                        "0 <= lo <= hi and hi > 0"
                    )
        if cfg.nem_reorder_rate > 0 and cfg.nem_reorder_window_us <= 0:
            raise ValueError(
                "nem_reorder_rate needs nem_reorder_window_us > 0, got "
                f"{cfg.nem_reorder_window_us}"
            )
        if cfg.nem_spike_enabled and cfg.nem_spike_extra_us <= 0:
            raise ValueError(
                f"nem_spike_extra_us must be > 0, got {cfg.nem_spike_extra_us}"
            )
        if not (0 <= cfg.nem_skew_max_ppm < 1_000_000):
            raise ValueError(
                "nem_skew_max_ppm must be in [0, 1e6) (the timer rate "
                f"1 + ppm*1e-6 must stay positive), got {cfg.nem_skew_max_ppm}"
            )
        # all latency lengtheners must keep deliver offsets far below the
        # sentinel guard (rebase arithmetic headroom)
        if (
            cfg.latency_hi_us + cfg.nem_spike_extra_us
            + cfg.nem_reorder_window_us
        ) >= int(INF_GUARD) // 4:
            raise ValueError(
                "latency_hi + nem_spike_extra + nem_reorder_window must stay "
                f"below {int(INF_GUARD) // 4} us"
            )
        if spec.on_event is not None and cfg.msg_depth_timer is not None and (
            cfg.msg_depth_timer != cfg.msg_depth_msg
        ):
            # covers both "3/2 mixed" and "timer set alone" — either way
            # the knob would be silently ignored on the fused path
            raise ValueError(
                "fused (on_event) specs have ONE candidate class: "
                "msg_depth_timer has no effect and must equal msg_depth_msg "
                f"(got {cfg.msg_depth_timer} vs {cfg.msg_depth_msg}); tune "
                "msg_depth_msg and msg_spare_slots instead"
            )
        import numpy as _np

        # Candidate positions: the fixed send sites of one step. Fused
        # (spec.on_event) specs have ONE event per node per step emitting up
        # to max_out rows => C = N * max_out; two-handler specs have each
        # node's max_out_msg on_message slots then its max_out on_timer
        # slots, in flat() order. Position c's source node is a
        # compile-time constant either way.
        self._fused = spec.on_event is not None
        if self._fused:
            self._C = N * spec.max_out
            self._src_of_c = _np.arange(self._C) // spec.max_out
        else:
            self._C = N * spec.max_out_msg + N * spec.max_out
            self._src_of_c = _np.concatenate(
                [
                    _np.arange(N * spec.max_out_msg) // spec.max_out_msg,
                    _np.arange(N * spec.max_out) // spec.max_out,
                ]
            )
        # nemesis duplication doubles the candidate axis: position 2c is
        # the original send, 2c+1 its (coin-gated) duplicate with an
        # independent latency/loss roll. Interleaving (repeat, not tile)
        # keeps each node's candidate block contiguous, so the fused pack's
        # [L, N, E] reshape and the two-handler segment split both survive
        # unchanged with E and the segment bounds doubled. Pool sizing
        # scales with the doubled axis — paid only when the clause is on.
        self._dup = cfg.nem_dup_rate > 0
        self._Cb = self._C  # base (pre-duplication) candidate count
        if self._dup:
            self._C *= 2
            self._src_of_c = _np.repeat(self._src_of_c, 2)
        _mult = 2 if self._dup else 1
        # Main pool: candidate position c owns K consecutive ring slots;
        # msg_capacity is the TOTAL ring-slot budget per lane (C * K ~
        # msg_capacity, the r3 semantics — per-destination state is just
        # validity bits over the shared ring, so it doesn't divide the
        # budget). Handler-reply and timer-broadcast positions can get
        # separate depths — see SimConfig.
        uniform = max(1, cfg.msg_capacity // self._C)
        self._Km = cfg.msg_depth_msg or uniform
        if self._fused:
            # NODE-POOLED slots: node n owns the SK = E*K (+ spare)
            # contiguous slots [n*SK, (n+1)*SK), shared by ALL its sends —
            # a send takes the i-th free slot of its node's pool, not a
            # fixed per-row ring. Bursts that cluster on one row (an ack
            # burst plus a broadcast in one latency window) then borrow
            # slack from quiet rows: depth 2 + 2 spare absorbs election
            # storms that per-row rings drop, at 2 extra slots instead of
            # a whole extra depth level (+E slots).
            self._Kt = self._Km
            self._E_pack = spec.max_out * _mult  # candidate rows per node
            self._SK = self._E_pack * self._Km + cfg.msg_spare_slots
            self._CK = N * self._SK
            self._src_of_slot = jnp.asarray(
                _np.repeat(_np.arange(N), self._SK), jnp.int32
            )  # [CK]
            self._segs = None
        else:
            self._Kt = cfg.msg_depth_timer or uniform
            self._Cm = N * spec.max_out_msg * _mult
            self._Ct = N * spec.max_out * _mult
            self._Sm = self._Cm * self._Km  # slots of the msg-position segment
            self._CK = self._Sm + self._Ct * self._Kt
            self._src_of_slot = jnp.asarray(
                _np.concatenate([
                    _np.repeat(self._src_of_c[: self._Cm], self._Km),
                    _np.repeat(self._src_of_c[self._Cm :], self._Kt),
                ]),
                jnp.int32,
            )  # [CK]
            # pack segments: (cand lo, cand hi, depth, slot lo, slot hi).
            # Equal depths collapse to ONE segment: the per-segment path
            # concatenates full pool-sized parts (extra HBM copies), so the
            # uniform case must not pay for the split.
            if self._Km == self._Kt:
                self._segs = ((0, self._C, self._Km, 0, self._CK),)
            else:
                self._segs = (
                    (0, self._Cm, self._Km, 0, self._Sm),
                    (self._Cm, self._C, self._Kt, self._Sm, self._CK),
                )
        # Straggler side pool (only when the heavy tail is on)
        if cfg.buggify_delay_rate > 0:
            self._K4 = max(1, cfg.buggify_depth)
            self._B = self._C * self._K4
            self._src_of_b = jnp.asarray(
                _np.repeat(self._src_of_c, self._K4), jnp.int32
            )  # [B]
        else:
            self._K4 = 0
            self._B = 0
        # nemesis per-lane bookkeeping exists iff a schedule-level clause
        # (or skew) is on; message-level coins (loss/dup/reorder) need none
        self._nem_state = (
            cfg.nem_crash_enabled or cfg.nem_partition_enabled
            or cfg.nem_clog_enabled or cfg.nem_spike_enabled
            or cfg.nem_skew_enabled or cfg.nem_reconfig_enabled
            or cfg.nem_disk_enabled
        )
        # occurrence-fire tracking exists iff a nemesis SCHEDULE clause is
        # on (legacy trajectory-coupled chaos has no occurrence index):
        # clause x occurrence coverage + the per-occurrence chaos report
        self._occ_track = (
            cfg.nem_crash_enabled or cfg.nem_partition_enabled
            or cfg.nem_clog_enabled or cfg.nem_spike_enabled
            or cfg.nem_reconfig_enabled or cfg.nem_disk_enabled
        )
        # durability plane (DiskFault clause, docs/nemesis.md r18): carried
        # iff the clause can fire AND the spec declares what is durable —
        # a disk-faulted spec without durable_fields recovers like a wipe
        # (nothing survives), and a durable contract without the clause
        # costs nothing
        if spec.on_recover is not None and not spec.durable_fields:
            raise ValueError(
                "spec.on_recover requires spec.durable_fields — the hook "
                "receives the durable watermark, and without declared "
                "durable fields there is nothing durable to recover from"
            )
        if spec.durable_fields and spec.sync_field is None:
            raise ValueError(
                "spec.durable_fields requires spec.sync_field — the i32 "
                "node-state counter the spec's handlers bump at their "
                "fsync points; without it the watermark could never "
                "advance past boot"
            )
        if spec.durable_fields and spec.sync_field in spec.durable_fields:
            raise ValueError(
                "spec.sync_field must not itself be durable: the watermark "
                "advance compares its live value against the PREVIOUS "
                "step's, not against the snapshot"
            )
        bad_dur = set(spec.durable_fields) & set(spec.time_fields)
        if bad_dur:
            raise ValueError(
                "durable_fields cannot include time_fields (the watermark "
                "snapshot is not epoch-rebased; an absolute time in it "
                f"would go stale): remove {sorted(bad_dur)}"
            )
        self._dur_state = cfg.nem_disk_enabled and bool(spec.durable_fields)
        if spec.durable_fields:
            import collections

            # a stable namedtuple type (created once per sim) so the dur
            # pytree structure is identical across every jitted call
            self._DurTuple = collections.namedtuple(
                "DurState", spec.durable_fields
            )
        else:
            self._DurTuple = None
        # scalar-style handlers -> [L,N] batched. `now` is per-(lane,node):
        # under the lookahead window, nodes in one step process events at
        # different virtual times.
        self._v_init = jax.vmap(jax.vmap(spec.init, in_axes=(0, 0)), in_axes=(0, None))
        if self._fused:
            self._v_on_event = jax.vmap(
                jax.vmap(spec.on_event, in_axes=(0, 0, 0, 0, 0, 0, 0)),
                in_axes=(0, 0, 0, 0, 0, 0, 0),
            )
        else:
            self._v_on_message = jax.vmap(
                jax.vmap(spec.on_message, in_axes=(0, 0, 0, 0, 0, 0, 0)),
                in_axes=(0, 0, 0, 0, 0, 0, 0),
            )
            self._v_on_timer = jax.vmap(
                jax.vmap(spec.on_timer, in_axes=(0, 0, 0, 0)),
                in_axes=(0, 0, 0, 0),
            )
        self._v_on_restart = jax.vmap(
            jax.vmap(spec.on_restart, in_axes=(0, 0, None, 0)), in_axes=(0, 0, 0, 0)
        )
        if spec.on_recover is not None:
            # on_recover(durable_state, node_id, now_us, torn, key):
            # now_us and the torn bit are per-LANE (the disk clause's
            # crash instant and schedule coin), everything else per-node
            self._v_on_recover = jax.vmap(
                jax.vmap(spec.on_recover, in_axes=(0, 0, None, None, 0)),
                in_axes=(0, 0, 0, 0, 0),
            )
        else:
            self._v_on_recover = None
        self._v_check = jax.vmap(spec.check_invariants, in_axes=(0, 0, 0))
        self.step = jax.jit(self._step)
        # jitted: eager init measured ~1.4 s PER SWEEP at 32k lanes over
        # the tunnel runtime (dozens of small ops, each paying dispatch
        # latency) — comparable to the entire 1,270-step simulation it
        # precedes. One jitted call collapses it to one dispatch.
        self.init = jax.jit(self._init)
        # tiny scalar reduction for the chunked sweep's early-stop check:
        # dispatched BEFORE the next segment so reading it never leaves
        # the device idle for a host round-trip (see run())
        self._any_alive = jax.jit(lambda s: jnp.any(~s.done))
        # per-(mesh, segment-length) compiled shard_map'd refill segment
        # programs (see _sharded_segment): at most two lengths compile
        # per mesh (chunk + tail), exactly like the unsharded run_state
        self._sharded_cache: Dict[Tuple[Any, int], Any] = {}
        # device program launches made by this sim's run paths (init +
        # sweep segments + early-stop reductions + sharding device_put).
        # run_batch snapshots the counter around a sweep to fill
        # BatchResult.dispatches, and the dispatch-budget regression test
        # pins it: an eager-init-style regression (the r5 ~1.4 s/sweep
        # bug: dozens of per-op dispatches where one jitted program
        # should be) blows the budget loudly instead of silently eating
        # the sweep.
        self.dispatch_count = 0

    # ------------------------------------------------ node-state narrowing
    # spec.narrow_fields: {field -> narrow dtype}. The carry stores those
    # leaves narrow; the step widens them back to i32 before every handler
    # call, so spec handler arithmetic is untouched (and the narrowing is
    # value-preserving by the spec's declared bound — a field that can go
    # negative must declare a SIGNED narrow dtype). The layout lint
    # (tests/test_state_layout.py) pins the narrowing-invariance: a spec
    # run with narrow_fields stripped must produce bit-identical
    # trajectories.

    def _narrow_node(self, node):
        if not self._narrow:
            return node
        return node._replace(**{
            f: getattr(node, f).astype(dt) for f, dt in self._narrow.items()
        })

    def _widen_node(self, node):
        if not self._narrow:
            return node
        return node._replace(**{
            f: getattr(node, f).astype(jnp.int32) for f in self._narrow
        })

    def _check_narrow(self, node) -> None:
        for f, dt in self._narrow.items():
            if not hasattr(node, f):
                raise ValueError(
                    f"narrow_fields names unknown node-state field {f!r}"
                )
            if getattr(node, f).dtype != jnp.int32:
                raise ValueError(
                    f"narrow_fields[{f!r}]: only i32 fields can be "
                    f"narrowed (field is {getattr(node, f).dtype})"
                )
            if jnp.dtype(dt).itemsize >= 4:
                raise ValueError(
                    f"narrow_fields[{f!r}] = {jnp.dtype(dt)} is not "
                    "narrower than i32"
                )

    # ----------------------------------------------- durability watermark
    # spec.durable_fields: the DiskFault clause's at-rest plane. The
    # watermark stores each durable field at the SAME narrowed dtype as
    # the node carry (it is a snapshot of those exact leaves), and widens
    # back to i32 only at recovery — symmetric with _narrow_node.

    def _check_durable(self, node) -> None:
        for f in self.spec.durable_fields:
            if not hasattr(node, f):
                raise ValueError(
                    f"durable_fields names unknown node-state field {f!r}"
                )
        sf = self.spec.sync_field
        if sf is not None and not hasattr(node, sf):
            raise ValueError(
                f"sync_field names unknown node-state field {sf!r}"
            )

    def _dur_of(self, node):
        """Snapshot the durable fields of a WIDE node pytree, narrowed to
        their at-rest dtypes (the watermark's storage form)."""
        return self._DurTuple(**{
            f: (
                getattr(node, f).astype(self._narrow[f])
                if f in self._narrow else getattr(node, f)
            )
            for f in self.spec.durable_fields
        })

    def _widen_dur(self, dur):
        return dur._replace(**{
            f: getattr(dur, f).astype(jnp.int32)
            for f in self.spec.durable_fields
            if f in self._narrow
        })

    # ------------------------------------------------------------------ init

    def _init(self, seeds: jnp.ndarray, ctl=None) -> SimState:
        """Build lane state for a batch of seeds (int array [L]).

        `ctl` (triage mode only) carries the per-lane shrink controls; by
        default every clause is on and the horizon is the config's."""
        spec, cfg = self.spec, self.config
        seeds = jnp.asarray(seeds, jnp.uint32)
        L, N, CK = seeds.shape[0], spec.n_nodes, self._CK
        if ctl is not None and not self.triage:
            raise ValueError(
                "a TriageCtl requires BatchedSim(..., triage=True)"
            )
        if self.triage and ctl is None:
            ctl = default_ctl(L, cfg.horizon_us)

        key = prng.key_from(seeds)  # u32 [L]
        node_keys = prng.fold(key[:, None], jnp.arange(N, dtype=jnp.uint32))
        node_state, timer = self._v_init(node_keys, jnp.arange(N, dtype=jnp.int32))
        timer = jnp.asarray(timer, jnp.int32)
        self._check_narrow(node_state)
        if self.spec.durable_fields:
            self._check_durable(node_state)

        # per-node clock skew (nemesis): timer rate drawn once per
        # (seed, node) — the same formula FaultPlan.skew_ppm mirrors.
        # Stored as integer ppm; delays stretch via scale_delay_ppm (exact
        # int32 math — the f32 rate multiply lost microseconds past 2^24).
        fires = jnp.zeros((L, len(FIRE_KINDS)), jnp.int32)
        skew_ppm = None
        if cfg.nem_skew_enabled:
            ppm = prng.randint(
                key[:, None], NEM_SITE_SKEW, -cfg.nem_skew_max_ppm,
                cfg.nem_skew_max_ppm + 1,
                index=jnp.arange(N, dtype=jnp.uint32)[None, :],
            )  # [L,N]
            skew_applied = ppm != 0
            if self.triage:
                # a skew-disabled lane runs every node at ppm 0; the ppm
                # draws still happen (sites untouched), they just don't apply
                en_skew = _clause_on(ctl, "skew")
                ppm = jnp.where(en_skew[:, None], ppm, jnp.int32(0))
                skew_applied = skew_applied & en_skew[:, None]
            skew_ppm = ppm
            fires = fires.at[:, FIRE_INDEX["skew"]].set(
                skew_applied.sum(axis=1, dtype=jnp.int32)
            )
            # initial timers are armed at local t=0: scale the delay
            sk_ok = (timer >= 0) & (timer < INF_GUARD)
            timer = jnp.where(sk_ok, scale_delay_ppm(timer, skew_ppm), timer)

        if cfg.nem_crash_enabled:
            # occurrence-indexed: the first crash interval is draw k=0 of
            # the pure schedule (key here IS the lane base key)
            chaos_at = prng.randint(
                key, NEM_SITE_CRASH_IV, cfg.nem_crash_interval_lo_us,
                cfg.nem_crash_interval_hi_us, index=0,
            )
        elif cfg.chaos_enabled:
            chaos_at = prng.randint(
                key, 11, cfg.crash_interval_lo_us, cfg.crash_interval_hi_us
            )
        else:
            chaos_at = jnp.full((L,), INF_US, jnp.int32)
        if cfg.nem_partition_enabled:
            part_at = prng.randint(
                key, NEM_SITE_PART_IV, cfg.nem_partition_interval_lo_us,
                cfg.nem_partition_interval_hi_us, index=0,
            )
        elif cfg.partition_enabled:
            part_at = prng.randint(
                key, 12, cfg.partition_interval_lo_us, cfg.partition_interval_hi_us
            )
        else:
            part_at = jnp.full((L,), INF_US, jnp.int32)

        if self._nem_state:
            zi = jnp.zeros((L,), jnp.int32)
            zb = jnp.zeros((L,), jnp.bool_)
            nem = NemesisState(
                crash_k=zi, wipe=zb, part_k=zi,
                clog_at=(
                    prng.randint(
                        key, NEM_SITE_CLOG_IV, cfg.nem_clog_interval_lo_us,
                        cfg.nem_clog_interval_hi_us, index=0,
                    )
                    if cfg.nem_clog_enabled
                    else jnp.full((L,), INF_US, jnp.int32)
                ),
                clogged=zb, clog_src=zi, clog_dst=zi, clog_k=zi,
                spike_at=(
                    prng.randint(
                        key, NEM_SITE_SPIKE_IV, cfg.nem_spike_interval_lo_us,
                        cfg.nem_spike_interval_hi_us, index=0,
                    )
                    if cfg.nem_spike_enabled
                    else jnp.full((L,), INF_US, jnp.int32)
                ),
                spiking=zb, spike_k=zi,
                reconfig_at=(
                    prng.randint(
                        key, NEM_SITE_RECONF_IV,
                        cfg.nem_reconfig_interval_lo_us,
                        cfg.nem_reconfig_interval_hi_us, index=0,
                    )
                    if cfg.nem_reconfig_enabled
                    else jnp.full((L,), INF_US, jnp.int32)
                ),
                reconf_node=jnp.full((L,), -1, jnp.int32),
                reconfig_k=zi,
                disk_at=(
                    prng.randint(
                        key, NEM_SITE_DISK_IV, cfg.nem_disk_interval_lo_us,
                        cfg.nem_disk_interval_hi_us, index=0,
                    )
                    if cfg.nem_disk_enabled
                    else jnp.full((L,), INF_US, jnp.int32)
                ),
                disk_phase=zi,
                disk_k=zi,
                skew_ppm=skew_ppm,
            )
        else:
            nem = None

        if self._B:
            strag = StragPool(
                valid=jnp.zeros((L, self._B), jnp.bool_),
                deliver=jnp.full((L, self._B), INF_US, jnp.int32),
                dst=jnp.zeros((L, self._B), jnp.uint8),
                kind=jnp.zeros((L, self._B), self._kind_dtype),
                payload=jnp.zeros((L, self._B, spec.payload_width), jnp.int32),
                sent_eid=(
                    jnp.zeros((L, self._B), jnp.uint16)
                    if self.lineage else None
                ),
            )
        else:
            strag = None

        return SimState(
            clock=jnp.zeros((L,), jnp.int32),
            epoch=jnp.zeros((L,), jnp.int32),
            key=key,
            key0=key,
            done=jnp.zeros((L,), jnp.bool_),
            violated=jnp.zeros((L,), jnp.bool_),
            violation_at=jnp.full((L,), INF_US, jnp.int32),
            violation_epoch=jnp.zeros((L,), jnp.int32),
            violation_step=jnp.full((L,), -1, jnp.int32),
            deadlocked=jnp.zeros((L,), jnp.bool_),
            steps=jnp.zeros((L,), jnp.int32),
            events=jnp.zeros((L,), jnp.int32),
            overflow=jnp.zeros((L,), jnp.int32),
            dead_drops=jnp.zeros((L,), jnp.int32),
            nonmember_drops=jnp.zeros((L,), jnp.int32),
            unsynced_loss=jnp.zeros((L,), jnp.int32),
            fires=fires,
            occ_fired=(
                jnp.zeros((L, len(OCC_CLAUSES)), jnp.uint32)
                if self._occ_track else None
            ),
            alive_p=jnp.full(
                (L, 1), bitpack.full_mask_word(N), jnp.uint32
            ),
            crashed=jnp.full((L,), -1, jnp.int32),
            chaos_at=chaos_at,
            member_p=jnp.full(
                (L, 1), bitpack.full_mask_word(N), jnp.uint32
            ),
            member_epoch=jnp.zeros((L,), jnp.int32),
            link_ok_p=jnp.full(
                (L, N, 1), bitpack.full_mask_word(N), jnp.uint32
            ),
            partitioned=jnp.zeros((L,), jnp.bool_),
            part_at=part_at,
            timer=timer,
            node=self._narrow_node(node_state),
            # boot is fsynced: the watermark starts as the init snapshot
            dur=self._dur_of(node_state) if self._dur_state else None,
            msgs=MsgPool(
                valid_p=jnp.zeros(
                    (L, N, bitpack.packed_words(CK)), jnp.uint32
                ),
                deliver=jnp.full((L, CK), INF_US, jnp.int32),
                kind=jnp.zeros((L, CK), self._kind_dtype),
                payload=jnp.zeros((L, CK, spec.payload_width), jnp.int32),
                sent_eid=(
                    jnp.zeros((L, CK), jnp.uint16) if self.lineage else None
                ),
            ),
            strag=strag,
            nem=nem,
            ctl=ctl,
            cov=(
                Coverage(
                    bitmap=jnp.zeros((L, COV_WORDS), jnp.uint32),
                    hiwater=jnp.zeros((L,), jnp.int32),
                    transitions=jnp.zeros((L,), jnp.int32),
                )
                if self.coverage else None
            ),
            lin=(
                Lineage(
                    lam=jnp.zeros((L, N), jnp.int32),
                    eid=jnp.zeros((L,), jnp.uint32),
                )
                if self.lineage else None
            ),
            queue=None,
            refill=None,
        )

    # ------------------------------------------------------------------ step

    def _step(self, state: SimState) -> SimState:
        return self._step_traced(state)[0]

    def _step_split(self, hot: SimState, cold: ColdState, const: ConstState):
        """One step in the sweep loop's (hot, cold | const) form: const is
        an invariant OPERAND, not part of the returned carry — the compiled
        loop body reads key0/ctl/skew_ppm but never re-emits them. This is
        the program benches/roofline.py accounts bytes for (the step the
        sweep actually runs); merge/split are free pytree restructuring."""
        s2, rec = self._step_traced(merge_state(hot, cold, const))
        h2, c2, _ = split_state(s2)
        return h2, c2, rec

    def _step_traced(self, state: SimState) -> Tuple[SimState, TraceRecord]:
        """One engine step + the step's TraceRecord.

        Untraced callers discard the record; XLA dead-code-eliminates its
        construction, so the trace costs nothing unless collected."""
        spec, cfg = self.spec, self.config
        N, CK, P = spec.n_nodes, self._CK, spec.payload_width
        L = state.clock.shape[0]
        msgs = state.msgs
        strag: Optional[StragPool] = state.strag
        narange = jnp.arange(N, dtype=jnp.int32)

        # -- 0. unpack the compacted carry (r8, docs/state_layout.md):
        # bit-packed bool planes -> bool tensors, narrow node leaves ->
        # i32. Pure elementwise shifts/converts that fuse into the step;
        # the wide forms live only inside this kernel and are repacked at
        # the end, so the HBM-resident carry stays narrow.
        valid = bitpack.unpack_bits(msgs.valid_p, CK)  # bool [L,N,CK]
        alive = bitpack.unpack_bits(state.alive_p, N)  # bool [L,N]
        link_ok = bitpack.unpack_bits(state.link_ok_p, N)  # bool [L,N,N]
        node0 = self._widen_node(state.node)

        # -- 1. advance each lane to its next event window -----------------
        # (the advance_to_next_event analog, time/mod.rs:45-60, batched).
        # Node n's pending messages are the static slice valid[:, n, :]
        # over the shared ring — no destination matching (see MsgPool).
        t_pend = jnp.where(valid, msgs.deliver[:, None, :], INF_US)  # [L,N,CK]
        tmsg_n = t_pend.min(axis=2)  # [L,N]
        if self._B:
            sd_oh = strag.dst[:, :, None] == narange[None, None, :]  # [L,B,N]
            ts_b = jnp.where(strag.valid, strag.deliver, INF_US)  # [L,B]
            t_sn = jnp.where(sd_oh, ts_b[:, :, None], INF_US)  # [L,B,N]
            tmsg_strag = t_sn.min(axis=1)  # [L,N]
            tmsg_n = jnp.minimum(tmsg_n, tmsg_strag)
        tmsg_n = jnp.where(alive, tmsg_n, INF_US)
        ttmr_n = jnp.where(alive, state.timer, INF_US)  # [L,N]
        t_next = jnp.minimum(
            jnp.minimum(jnp.minimum(tmsg_n.min(axis=1), ttmr_n.min(axis=1)),
                        state.chaos_at),
            state.part_at,
        )
        # nemesis clog/spike toggles are events too: lanes must advance to
        # them even when the protocol is quiet (chaos_at/part_at already
        # carry the crash and partition clauses, legacy or nemesis)
        if cfg.nem_clog_enabled:
            t_next = jnp.minimum(t_next, state.nem.clog_at)
        if cfg.nem_spike_enabled:
            t_next = jnp.minimum(t_next, state.nem.spike_at)
        if cfg.nem_reconfig_enabled:
            t_next = jnp.minimum(t_next, state.nem.reconfig_at)
        if cfg.nem_disk_enabled:
            t_next = jnp.minimum(t_next, state.nem.disk_at)

        deadlocked = (~state.done) & (t_next >= INF_US)
        active = (~state.done) & (t_next < INF_US)

        # conservative-DES lookahead window [t_next, t_next + latency_lo):
        # any message EMITTED by an in-window event arrives at
        # >= t_next + latency_lo, so in-window events on different nodes are
        # causally independent and each node may process its earliest one
        # this step (classic PDES lookahead; see SimConfig.lookahead).
        # Whenever the next crash/partition instant falls anywhere inside
        # the window, the window shrinks to the exact instant t_next (the
        # chaos itself fires only once it IS t_next), so chaos state never
        # applies to sends from earlier virtual times. The buggify tail only
        # LENGTHENS latencies, so latency_lo remains the lookahead bound.
        lo_w = max(0, cfg.latency_lo_us - 1) if cfg.lookahead else 0
        w_end = jnp.minimum(t_next, INF_US - lo_w - 1) + lo_w
        if lo_w and (
            cfg.any_crash_enabled or cfg.any_partition_enabled
            or cfg.nem_clog_enabled or cfg.nem_spike_enabled
            or cfg.nem_reconfig_enabled or cfg.nem_disk_enabled
        ):
            next_chaos = jnp.minimum(state.chaos_at, state.part_at)
            if cfg.nem_clog_enabled:
                next_chaos = jnp.minimum(next_chaos, state.nem.clog_at)
            if cfg.nem_spike_enabled:
                next_chaos = jnp.minimum(next_chaos, state.nem.spike_at)
            if cfg.nem_reconfig_enabled:
                next_chaos = jnp.minimum(next_chaos, state.nem.reconfig_at)
            if cfg.nem_disk_enabled:
                next_chaos = jnp.minimum(next_chaos, state.nem.disk_at)
            chaos_in_w = next_chaos <= w_end
            w_end = jnp.where(chaos_in_w, t_next, w_end)

        # -- 2. advance per-lane keys (cheap hash chain, see prng.py) ------
        key = prng.fold(state.key, 1)
        node_key = prng.fold(key[:, None], jnp.arange(N, dtype=jnp.uint32))  # [L,N]
        mkeys = prng.fold(node_key, 101)
        tkeys = prng.fold(node_key, 102)
        rkeys = prng.fold(node_key, 103)
        ckey = prng.fold(key, 104)  # [L]

        # -- 3. pick each node's event: earliest in-window message or timer
        # (one event per node per step keeps per-node order exact)
        msg_due = active[:, None] & (tmsg_n <= w_end[:, None])  # [L,N]
        tmr_due = active[:, None] & (ttmr_n <= w_end[:, None])  # [L,N]
        if cfg.sched_randomize:
            # message-vs-timer order: when both are due at the SAME instant,
            # half the time the timer fires first (the message waits a step;
            # its deliver time has passed so it stays due) — same-instant
            # event reordering, the utils/mpsc.rs:71-84 analog
            timer_first = prng.bernoulli(prng.fold(node_key, 108), 1, 0.5)
        else:
            timer_first = jnp.zeros((L, N), jnp.bool_)
        tie = msg_due & tmr_due & (tmsg_n == ttmr_n)
        has_msg = msg_due & (
            ~tmr_due | (tmsg_n < ttmr_n) | (tie & ~timer_first)
        )
        due_t = tmr_due & (
            ~msg_due | (ttmr_n < tmsg_n) | (tie & timer_first)
        )
        # per-node event time; inactive nodes default to the window start
        t_evt = jnp.where(has_msg, tmsg_n, jnp.where(due_t, ttmr_n, t_next[:, None]))

        # main-pool slot choice: among this node's earliest-time slots
        head = valid & (t_pend == tmsg_n[:, :, None])  # [L,N,CK]
        if cfg.sched_randomize:
            # random tie-break among equal-timestamp due messages — the
            # scheduling-nondeterminism amplifier (utils/mpsc.rs:71-84):
            # seeds that share a chaos schedule still explore different
            # delivery orders, the reference's biggest bug-finding lever.
            # Priorities are drawn per RING SLOT and shared across
            # destination nodes (measured ~4% of the step to draw per
            # (node, slot)): two nodes tying over the SAME slot set pick
            # the same winner that step, but the draw refolds from the
            # lane key every step and per-seed variation is unaffected —
            # the per-node event ORDER stays randomized across steps/seeds
            slot_idx = jnp.arange(CK, dtype=jnp.uint32)
            prio = prng.bits(
                prng.fold(key, 107)[:, None], 1, index=slot_idx[None]
            )[:, None, :]  # u32 [L,1,CK]
            prio_m = jnp.where(head, prio, jnp.uint32(0xFFFFFFFF))
            slot = jnp.argmin(prio_m, axis=2)  # [L,N]
        else:
            slot = jnp.argmin(jnp.where(head, t_pend, INF_US), axis=2)  # [L,N]

        # straggler beats the main pool only with a strictly earlier time
        # (same-instant cross-pool ties go to the main pool; tail events are
        # rare enough that the ordering bias is negligible)
        if self._B:
            strag_win = has_msg & (tmsg_strag < t_pend.min(axis=2))
            s_head = jnp.where(
                t_sn == tmsg_strag[:, None, :], ts_b[:, :, None], INF_US
            )  # [L,B,N]
            s_slot = jnp.argmin(
                jnp.where(t_sn == tmsg_strag[:, None, :], t_sn, INF_US), axis=1
            )  # [L,N]
            del s_head
        else:
            strag_win = jnp.zeros((L, N), jnp.bool_)

        # field extraction via one-hot multiply-reduce over the node's OWN
        # slot region [L,N,CK] — small because the pool is dest-major.
        # (NOT gathers: take_along_axis here measured ~8x slower end-to-end
        # on TPU v5e — XLA lowers batched small-domain gathers poorly, while
        # the one-hot form fuses into the surrounding elementwise work.)
        pick_oh = jnp.arange(CK)[None, None, :] == slot[:, :, None]  # [L,N,CK]
        pick_ohi = pick_oh.astype(jnp.int32)
        m_src = (self._src_of_slot[None, None, :] * pick_ohi).sum(2)
        m_kind = (msgs.kind.astype(jnp.int32)[:, None, :] * pick_ohi).sum(2)
        m_pay = (msgs.payload[:, None, :, :] * pick_ohi[:, :, :, None]).sum(2)
        if self._B:
            s_pick = (
                jnp.arange(self._B)[None, None, :] == s_slot[:, :, None]
            ).astype(jnp.int32)  # [L,N,B]
            sm_src = (self._src_of_b[None, None, :] * s_pick).sum(2)
            sm_kind = (strag.kind.astype(jnp.int32)[:, None, :] * s_pick).sum(2)
            sm_pay = (strag.payload[:, None, :, :] * s_pick[:, :, :, None]).sum(2)
            m_src = jnp.where(strag_win, sm_src, m_src)
            m_kind = jnp.where(strag_win, sm_kind, m_kind)
            m_pay = jnp.where(strag_win[:, :, None], sm_pay, m_pay)
        node_ids = jnp.broadcast_to(narange, (L, N))

        # -- 3b. causal lineage (BatchedSim(lineage=True); docs/causality.md)
        # Event ids: every delivery/timer-fire gets the lane's next global
        # id, assigned in node order within the step (the same order the
        # host-side decoder and the host-runtime mirror use). The delivered
        # slot's u16 sent_eid stamp widens back to the full u32 send eid by
        # rolling-window reconstruction against the lane's event counter:
        # every in-flight message was sent at an earlier step, so its eid
        # is the largest value <= eid-1 congruent to the stamp mod 2^16 —
        # exact while < 65536 lane events happen during any flight (the
        # decoder verifies this, never trusts it). Lamport clocks update
        # max(local, sender)+1 on delivery with the send eid as the
        # sender's value, +1 on timer fires. OBSERVE-ONLY: nothing here
        # feeds a draw, a handler, or any non-lineage output.
        lin: Optional[Lineage] = state.lin
        if lin is not None:
            evt_lin = has_msg | due_t  # [L,N]
            acc_e = jnp.zeros((L,), jnp.uint32)
            rank_cols = []
            for n_i in range(N):  # N is small + static: unrolled prefix
                rank_cols.append(acc_e)
                acc_e = acc_e + evt_lin[:, n_i].astype(jnp.uint32)
            evt_eid_full = lin.eid[:, None] + jnp.stack(rank_cols, axis=1)
            new_lin_eid = lin.eid + acc_e
            # delivered slot's stamp (same one-hot extraction as m_kind)
            m_seid16 = (
                msgs.sent_eid.astype(jnp.int32)[:, None, :] * pick_ohi
            ).sum(2)
            if self._B:
                sm_seid16 = (
                    strag.sent_eid.astype(jnp.int32)[:, None, :] * s_pick
                ).sum(2)
                m_seid16 = jnp.where(strag_win, sm_seid16, m_seid16)
            prev_e = (lin.eid - jnp.uint32(1))[:, None]  # eids in flight <= this
            m_seid = prev_e - (
                (prev_e - m_seid16.astype(jnp.uint32)) & jnp.uint32(0xFFFF)
            )  # u32 [L,N] full send eid (garbage where ~has_msg, masked below)
            new_lam = jnp.where(
                has_msg,
                jnp.maximum(lin.lam, m_seid.astype(jnp.int32)) + 1,
                jnp.where(due_t, lin.lam + 1, lin.lam),
            )
            tr_lam = new_lam
            tr_evt_eid = jnp.where(evt_lin, evt_eid_full, EID_NONE)
            tr_sent_eid = jnp.where(has_msg, m_seid, EID_NONE)
        else:
            evt_eid_full = None
            tr_lam = tr_evt_eid = tr_sent_eid = None

        # -- 4. run handlers + fused state select. The three masks are
        # pairwise DISJOINT: at most one event per node per step (msg vs
        # timer), and a restarting node was dead all step (dead nodes'
        # queues and timers are masked out of the event pick), so its event
        # masks are false. One tree pass merges all three outcomes instead
        # of three full-state passes.
        any_crash = cfg.any_crash_enabled
        ctl: Optional[TriageCtl] = state.ctl
        if any_crash:
            chaos_due = active & (state.chaos_at <= t_next)
            is_restart_evt = state.crashed >= 0
            do_crash = chaos_due & ~is_restart_evt
            do_restart = chaos_due & is_restart_evt
            if cfg.nem_crash_enabled:
                # nemesis: victim is draw k of the pure schedule — a
                # function of the SEED, not of when the crash fires
                victim = prng.randint(
                    state.key0, NEM_SITE_CRASH_VICTIM, 0, N,
                    index=state.nem.crash_k,
                )
            else:
                victim = prng.randint(ckey, 1, 0, N)
            # triage: a suppressed occurrence keeps the timing machinery
            # (chaos_at / crashed / crash_k advance through the window as
            # always — do_crash/do_restart below) but applies NO effect:
            # ap_* gate the kill, the restart handler, the pool drops, the
            # trace rows and the fire counts. Later occurrences keep their
            # schedule-pure times, so one dropped atom never moves another.
            if self.triage:
                k_idx = (
                    state.nem.crash_k if cfg.nem_crash_enabled
                    else jnp.zeros((L,), jnp.int32)
                )
                crash_en = _occ_on(ctl, "crash", k_idx)
            else:
                crash_en = jnp.ones((L,), jnp.bool_)
            ap_crash = do_crash & crash_en
            ap_restart = do_restart & crash_en
            crash_mask = ap_crash[:, None] & (node_ids == victim[:, None])
            restart_node = jnp.clip(state.crashed, 0, N - 1)
            restart_mask = ap_restart[:, None] & (node_ids == restart_node[:, None])
        else:
            restart_mask = None

        if any_crash:
            # `now` for a restarting node is the chaos instant t_next (the
            # window collapses to it on chaos steps), never an earlier
            # clock — a restart timer must not be armed in the past
            ns_r, timer_r = self._v_on_restart(
                node0, node_ids, t_next, rkeys
            )
            if cfg.nem_crash_enabled and cfg.nem_crash_wipe_rate > 0:
                # crash-with-state-wipe: the marked node restarts from
                # `init` (durable state gone too), its declared absolute
                # time fields and first timer shifted to the restart
                # instant. The wipe flag was drawn at crash time and rides
                # state.nem.wipe through the down window.
                ns_w, timer_w = self._v_init(rkeys, narange)
                timer_w = jnp.asarray(timer_w, jnp.int32)
                w_ok = (timer_w >= 0) & (timer_w < INF_GUARD)
                timer_w = jnp.where(w_ok, timer_w + t_next[:, None], timer_w)
                if spec.time_fields:
                    ns_w = ns_w._replace(**{
                        f: getattr(ns_w, f)
                        + t_next.reshape((L,) + (1,) * (getattr(ns_w, f).ndim - 1))
                        for f in spec.time_fields
                    })
                wipe_mask = restart_mask & state.nem.wipe[:, None]
                if self.triage:
                    # wipe is its own triage atom: with it off, the crash
                    # occurrence still happens but restarts via on_restart
                    wipe_mask = wipe_mask & _clause_on(ctl, "wipe")[:, None]
                ns_r = _tree_where(wipe_mask, ns_w, ns_r)
                timer_r = jnp.where(wipe_mask, timer_w, timer_r)

        if self._fused:
            # ONE handler invocation per node per step: kind == -1 encodes
            # "your timer fired" (see ProtocolSpec.on_event). This avoids
            # materializing two full candidate states and the 3-way merge —
            # the dual-handler tax measured larger than either handler body.
            evt = has_msg | due_t
            evt_kind = jnp.where(has_msg, m_kind, jnp.int32(-1))
            ns_e, out_e, timer_e = self._v_on_event(
                node0, node_ids, m_src, evt_kind, m_pay, t_evt, mkeys
            )

            def merge(old, e, r):
                ek = evt.reshape(evt.shape + (1,) * (old.ndim - 2))
                out = jnp.where(ek, e, old)
                if r is not None:
                    rk = restart_mask.reshape(ek.shape)
                    out = jnp.where(rk, r, out)
                return out

            if any_crash:
                node = jax.tree_util.tree_map(merge, node0, ns_e, ns_r)
            else:
                node = jax.tree_util.tree_map(
                    lambda old, e: merge(old, e, None), node0, ns_e
                )
            timer_m = timer_t = timer_e
        else:
            ns_m, out_m, timer_m = self._v_on_message(
                node0, node_ids, m_src, m_kind, m_pay, t_evt, mkeys
            )
            ns_t, out_t, timer_t = self._v_on_timer(
                node0, node_ids, t_evt, tkeys
            )

            def merge(old, m, t, r):
                mk = has_msg.reshape(has_msg.shape + (1,) * (old.ndim - 2))
                tk = due_t.reshape(mk.shape)
                out = jnp.where(tk, t, jnp.where(mk, m, old))
                if r is not None:
                    rk = restart_mask.reshape(mk.shape)
                    out = jnp.where(rk, r, out)
                return out

            if any_crash:
                node = jax.tree_util.tree_map(
                    merge, node0, ns_m, ns_t, ns_r
                )
            else:
                node = jax.tree_util.tree_map(
                    lambda old, m, t: merge(old, m, t, None),
                    node0, ns_m, ns_t,
                )
        # message handlers return a negative timer to keep the current
        # deadline; timer handlers return a negative value to disarm
        if cfg.nem_skew_enabled:
            # per-node clock skew: a handler's ABSOLUTE deadline encodes a
            # relative delay from its own event time — stretch/shrink that
            # delay by the node's ppm rate (sentinels and keep/disarm
            # negatives pass through untouched). Integer ppm math
            # (scale_delay_ppm) is EXACT for every i32 delay; the old f32
            # rate multiply dropped microseconds once deadlines passed
            # 2^24 us, i.e. ~16.7 s into any lane's virtual time.
            skew_ppm_now = state.nem.skew_ppm  # i32 [L,N]

            def skew_deadline(deadline, now):
                d = deadline - now
                stretched = now + scale_delay_ppm(d, skew_ppm_now)
                ok = (deadline >= 0) & (deadline < INF_GUARD) & (d > 0)
                return jnp.where(ok, stretched, deadline)

            if self._fused:
                timer_m = timer_t = skew_deadline(timer_e, t_evt)
            else:
                timer_m = skew_deadline(timer_m, t_evt)
                timer_t = skew_deadline(timer_t, t_evt)
            if any_crash:
                timer_r = skew_deadline(
                    timer_r, jnp.broadcast_to(t_next[:, None], (L, N))
                )
        timer = jnp.where(has_msg & (timer_m >= 0), timer_m, state.timer)
        timer = jnp.where(
            due_t, jnp.where(timer_t >= 0, timer_t, INF_US), timer
        )
        if any_crash:
            timer = jnp.where(restart_mask, timer_r, timer)
        # consume the delivered slot (reusing the extraction one-hots)
        consumed_main = has_msg & ~strag_win  # [L,N]
        valid = valid & ~(pick_oh & consumed_main[:, :, None])
        if self._B:
            s_oh = (s_pick > 0) & strag_win[:, :, None]  # [L,N,B]
            svalid = strag.valid & ~s_oh.any(axis=1)
        # lane clock: the latest event time processed this step (chaos-only
        # steps advance to the chaos instant t_next)
        clock = jnp.where(
            active,
            jnp.maximum(state.clock, t_evt.max(axis=1)),
            state.clock,
        )

        # -- 5. crash/restart chaos (Handle::kill/restart analog) ----------
        # (`alive` was unpacked from the carry at step 0)
        crashed, chaos_at = state.crashed, state.chaos_at
        tr_crash = jnp.full((L,), -1, jnp.int32)
        tr_restart = jnp.full((L,), -1, jnp.int32)
        nem_crash_k, nem_wipe = None, None
        if any_crash:
            alive = (alive & ~crash_mask) | restart_mask
            if cfg.nem_crash_enabled:
                # schedule arithmetic: next toggle = PREVIOUS toggle time
                # plus an occurrence-indexed delta — never `clock + delta`,
                # which would couple the schedule to the trajectory
                ck_n = state.nem.crash_k
                restart_delay = prng.randint(
                    state.key0, NEM_SITE_CRASH_DOWN, cfg.nem_crash_down_lo_us,
                    cfg.nem_crash_down_hi_us, index=ck_n,
                )
                next_crash = prng.randint(
                    state.key0, NEM_SITE_CRASH_IV, cfg.nem_crash_interval_lo_us,
                    cfg.nem_crash_interval_hi_us, index=ck_n + 1,
                )
                chaos_at = jnp.where(
                    do_crash,
                    state.chaos_at + restart_delay,
                    jnp.where(
                        do_restart, state.chaos_at + next_crash, state.chaos_at
                    ),
                )
                nem_crash_k = ck_n + do_restart.astype(jnp.int32)
                wipe_coin = (
                    prng.bits(state.key0, NEM_SITE_CRASH_WIPE, index=ck_n)
                    % jnp.uint32(COIN_DENOM)
                ) < jnp.uint32(round(cfg.nem_crash_wipe_rate * COIN_DENOM))
                nem_wipe = jnp.where(
                    do_crash, wipe_coin,
                    jnp.where(do_restart, False, state.nem.wipe),
                )
            else:
                restart_delay = prng.randint(
                    ckey, 2, cfg.restart_delay_lo_us, cfg.restart_delay_hi_us
                )
                next_crash = prng.randint(
                    ckey, 3, cfg.crash_interval_lo_us, cfg.crash_interval_hi_us
                )
                chaos_at = jnp.where(
                    do_crash,
                    clock + restart_delay,
                    jnp.where(do_restart, clock + next_crash, state.chaos_at),
                )
            crashed = jnp.where(
                do_crash, victim, jnp.where(do_restart, -1, state.crashed)
            )
            tr_crash = jnp.where(ap_crash, victim, -1)
            tr_restart = jnp.where(ap_restart, restart_node, -1)
            # in-flight messages to a crashed node are lost (reset_node closes
            # sockets, network.rs:142-147): its pool slice simply empties
            valid = valid & ~crash_mask[:, :, None]
            if self._B:
                svalid = svalid & ~(
                    ap_crash[:, None] & (strag.dst == victim[:, None])
                )

        # -- 5b. partition chaos: random bipartition splits, later heals ----
        # (the clog_link masks of network.rs:261-269, lane-batched;
        # `link_ok` was unpacked from the carry at step 0)
        partitioned, part_at = state.partitioned, state.part_at
        tr_split = jnp.zeros((L,), jnp.bool_)
        tr_heal = jnp.zeros((L,), jnp.bool_)
        tr_side = jnp.zeros((L,), jnp.int32)
        nem_part_k = None
        if cfg.any_partition_enabled:
            part_due = active & (state.part_at <= t_next)
            do_split = part_due & ~state.partitioned
            do_heal = part_due & state.partitioned
            if cfg.nem_partition_enabled:
                pk_n = state.nem.part_k
                # per-node side bit at occurrence k: index = k * 64 + node
                # (pure in the seed; FaultPlan.schedule draws the same bit)
                side = (
                    prng.bits(
                        state.key0[:, None], NEM_SITE_PART_SIDE,
                        index=pk_n[:, None].astype(jnp.uint32) * 64
                        + jnp.arange(N, dtype=jnp.uint32)[None, :],
                    )
                    & 1
                ) == 1  # [L,N]
                heal_delay = prng.randint(
                    state.key0, NEM_SITE_PART_HEAL, cfg.nem_partition_heal_lo_us,
                    cfg.nem_partition_heal_hi_us, index=pk_n,
                )
                next_split = prng.randint(
                    state.key0, NEM_SITE_PART_IV,
                    cfg.nem_partition_interval_lo_us,
                    cfg.nem_partition_interval_hi_us, index=pk_n + 1,
                )
                part_at = jnp.where(
                    do_split,
                    state.part_at + heal_delay,
                    jnp.where(do_heal, state.part_at + next_split, state.part_at),
                )
                nem_part_k = pk_n + do_heal.astype(jnp.int32)
            else:
                pkey = prng.fold(key, 106)
                # each node draws a side; links crossing the cut go down
                # both ways
                side = (
                    prng.uniform(
                        pkey[:, None], 7,
                        index=jnp.arange(N, dtype=jnp.uint32)[None, :],
                    )
                    < 0.5
                )  # [L,N]
                heal_delay = prng.randint(
                    pkey, 8, cfg.partition_heal_lo_us, cfg.partition_heal_hi_us
                )
                next_split = prng.randint(
                    pkey, 9, cfg.partition_interval_lo_us,
                    cfg.partition_interval_hi_us,
                )
                part_at = jnp.where(
                    do_split,
                    clock + heal_delay,
                    jnp.where(do_heal, clock + next_split, state.part_at),
                )
            if self.triage:
                pk_idx = (
                    state.nem.part_k if cfg.nem_partition_enabled
                    else jnp.zeros((L,), jnp.int32)
                )
                part_en = _occ_on(ctl, "partition", pk_idx)
            else:
                part_en = jnp.ones((L,), jnp.bool_)
            # a suppressed occurrence toggles `partitioned` (timing) but
            # never touches link_ok: its heal is then a no-op on links that
            # were never cut (part_k is the same k at split and heal)
            ap_split = do_split & part_en
            ap_heal = do_heal & part_en
            same_side = side[:, :, None] == side[:, None, :]  # [L,N,N]
            link_ok = jnp.where(
                ap_split[:, None, None],
                same_side,
                jnp.where(ap_heal[:, None, None], True, link_ok),
            )
            partitioned = (state.partitioned | do_split) & ~do_heal
            tr_split, tr_heal = ap_split, ap_heal
            tr_side = (
                side.astype(jnp.int32) * (1 << jnp.arange(N, dtype=jnp.int32))
            ).sum(-1)

        # -- 5c. nemesis link-clog + latency-spike windows ------------------
        # (toggle machinery like crash/partition, schedule-timed; the clog
        # is ASYMMETRIC — src->dst only — unlike the bipartition masks)
        tr_clog_src = jnp.full((L,), -1, jnp.int32)
        tr_clog_dst = jnp.full((L,), -1, jnp.int32)
        tr_unclog = jnp.zeros((L,), jnp.bool_)
        clogged = clog_src = clog_dst = None
        clog_en = None
        nem_clog_at = nem_clog_k = None
        if cfg.nem_clog_enabled:
            nst = state.nem
            clog_due = active & (nst.clog_at <= t_next)
            do_clog = clog_due & ~nst.clogged
            do_unclog = clog_due & nst.clogged
            kk = nst.clog_k
            # triage: clog_k names the window open (or opening) this step,
            # so one gate covers the toggle trace rows AND every in-window
            # send filtered below (the window still opens/closes on time)
            clog_en = (
                _occ_on(ctl, "clog", kk) if self.triage
                else jnp.ones((L,), jnp.bool_)
            )
            src_d = prng.randint(state.key0, NEM_SITE_CLOG_SRC, 0, N, index=kk)
            dst_d = prng.randint(
                state.key0, NEM_SITE_CLOG_DST, 0, N - 1, index=kk
            )
            dst_d = dst_d + (dst_d >= src_d).astype(jnp.int32)  # skip src
            clog_src = jnp.where(do_clog, src_d, nst.clog_src)
            clog_dst = jnp.where(do_clog, dst_d, nst.clog_dst)
            clogged = (nst.clogged | do_clog) & ~do_unclog
            heal_d = prng.randint(
                state.key0, NEM_SITE_CLOG_HEAL, cfg.nem_clog_heal_lo_us,
                cfg.nem_clog_heal_hi_us, index=kk,
            )
            next_d = prng.randint(
                state.key0, NEM_SITE_CLOG_IV, cfg.nem_clog_interval_lo_us,
                cfg.nem_clog_interval_hi_us, index=kk + 1,
            )
            nem_clog_at = jnp.where(
                do_clog, nst.clog_at + heal_d,
                jnp.where(do_unclog, nst.clog_at + next_d, nst.clog_at),
            )
            nem_clog_k = kk + do_unclog.astype(jnp.int32)
            tr_clog_src = jnp.where(do_clog & clog_en, src_d, -1)
            tr_clog_dst = jnp.where(do_clog & clog_en, dst_d, -1)
            tr_unclog = do_unclog & clog_en
        tr_spike_on = jnp.zeros((L,), jnp.bool_)
        tr_spike_off = jnp.zeros((L,), jnp.bool_)
        spiking = None
        spike_en = None
        nem_spike_at = nem_spike_k = None
        if cfg.nem_spike_enabled:
            nst = state.nem
            spike_due = active & (nst.spike_at <= t_next)
            do_spike = spike_due & ~nst.spiking
            do_unspike = spike_due & nst.spiking
            sk = nst.spike_k
            spike_en = (
                _occ_on(ctl, "spike", sk) if self.triage
                else jnp.ones((L,), jnp.bool_)
            )
            spiking = (nst.spiking | do_spike) & ~do_unspike
            dur_d = prng.randint(
                state.key0, NEM_SITE_SPIKE_DUR, cfg.nem_spike_duration_lo_us,
                cfg.nem_spike_duration_hi_us, index=sk,
            )
            next_d = prng.randint(
                state.key0, NEM_SITE_SPIKE_IV, cfg.nem_spike_interval_lo_us,
                cfg.nem_spike_interval_hi_us, index=sk + 1,
            )
            nem_spike_at = jnp.where(
                do_spike, nst.spike_at + dur_d,
                jnp.where(do_unspike, nst.spike_at + next_d, nst.spike_at),
            )
            nem_spike_k = sk + do_unspike.astype(jnp.int32)
            tr_spike_on = do_spike & spike_en
            tr_spike_off = do_unspike & spike_en

        # -- 5d. nemesis membership reconfiguration (remove/join windows) --
        # Same toggle machinery as crash's down-window, on the MEMBERSHIP
        # plane: a remove takes the schedule-drawn victim out of the
        # cluster (member + alive bits cleared, in-flight messages to it
        # lost), the paired join brings the SAME node back as a FRESH
        # replica — rebuilt through the real spec.init like wipe-restart,
        # never from its pre-removal state. member_epoch counts every
        # applied configuration change. reconf_node doubles as the
        # open/closed discriminator (-1 = all members, next event is a
        # remove), exactly like `crashed` does for the crash clause.
        tr_remove = jnp.full((L,), -1, jnp.int32)
        tr_join = jnp.full((L,), -1, jnp.int32)
        member = None
        member_epoch = state.member_epoch
        nem_reconfig_at = nem_reconf_node = nem_reconfig_k = None
        if cfg.nem_reconfig_enabled:
            nst = state.nem
            member = bitpack.unpack_bits(state.member_p, N)  # bool [L,N]
            reconf_due = active & (nst.reconfig_at <= t_next)
            do_remove = reconf_due & (nst.reconf_node < 0)
            do_join = reconf_due & (nst.reconf_node >= 0)
            rk = nst.reconfig_k
            # one gate per occurrence covers BOTH halves (k increments at
            # the join, like clog/spike close their windows): a suppressed
            # occurrence advances the timing machinery through its window
            # but applies no membership change at all
            reconf_en = (
                _occ_on(ctl, "reconfig", rk) if self.triage
                else jnp.ones((L,), jnp.bool_)
            )
            victim_d = prng.randint(
                state.key0, NEM_SITE_RECONF_VICTIM, 0, N, index=rk
            )
            join_node = jnp.clip(nst.reconf_node, 0, N - 1)
            ap_remove = do_remove & reconf_en
            ap_join = do_join & reconf_en
            remove_mask = ap_remove[:, None] & (node_ids == victim_d[:, None])
            join_mask = ap_join[:, None] & (node_ids == join_node[:, None])
            member = (member & ~remove_mask) | join_mask
            # liveness and membership stay INDEPENDENT planes (a crashed
            # member is dead_drops, a removed node nonmember_drops), but a
            # remove also downs the node and a join revives it: a removed
            # replica must not keep firing timers against the cluster
            alive = (alive & ~remove_mask) | join_mask
            member_epoch = member_epoch + (
                ap_remove | ap_join
            ).astype(jnp.int32)
            # in-flight messages to the removed node are lost, like a
            # crash (its pool slice empties; not counted as drops either)
            valid = valid & ~remove_mask[:, :, None]
            if self._B:
                svalid = svalid & ~(
                    ap_remove[:, None] & (strag.dst == victim_d[:, None])
                )
            # the joining node is a fresh replica: rebuilt through the
            # real spec.init (the wipe-restart idiom), its first timer and
            # declared absolute-time fields shifted to the join instant
            ns_j, timer_j = self._v_init(rkeys, narange)
            timer_j = jnp.asarray(timer_j, jnp.int32)
            j_ok = (timer_j >= 0) & (timer_j < INF_GUARD)
            timer_j = jnp.where(j_ok, timer_j + t_next[:, None], timer_j)
            if cfg.nem_skew_enabled:
                dj = timer_j - t_next[:, None]
                sk_j = j_ok & (dj > 0)
                timer_j = jnp.where(
                    sk_j,
                    t_next[:, None] + scale_delay_ppm(dj, state.nem.skew_ppm),
                    timer_j,
                )
            if spec.time_fields:
                ns_j = ns_j._replace(**{
                    f: getattr(ns_j, f)
                    + t_next.reshape((L,) + (1,) * (getattr(ns_j, f).ndim - 1))
                    for f in spec.time_fields
                })
            node = _tree_where(join_mask, ns_j, node)
            timer = jnp.where(join_mask, timer_j, timer)
            # schedule arithmetic: next toggle = previous toggle time plus
            # an occurrence-indexed delta (never clock + delta)
            down_d = prng.randint(
                state.key0, NEM_SITE_RECONF_DUR, cfg.nem_reconfig_down_lo_us,
                cfg.nem_reconfig_down_hi_us, index=rk,
            )
            next_d = prng.randint(
                state.key0, NEM_SITE_RECONF_IV,
                cfg.nem_reconfig_interval_lo_us,
                cfg.nem_reconfig_interval_hi_us, index=rk + 1,
            )
            nem_reconfig_at = jnp.where(
                do_remove, nst.reconfig_at + down_d,
                jnp.where(do_join, nst.reconfig_at + next_d, nst.reconfig_at),
            )
            nem_reconf_node = jnp.where(
                do_remove, victim_d, jnp.where(do_join, -1, nst.reconf_node)
            )
            nem_reconfig_k = rk + do_join.astype(jnp.int32)
            tr_remove = jnp.where(ap_remove, victim_d, -1)
            tr_join = jnp.where(ap_join, join_node, -1)

        # durability watermark ADVANCE (DiskFault plane, half 1 of 2):
        # re-snapshot the durable fields of every node whose sync counter
        # increased this step — the spec's declared fsync points. Done
        # BEFORE the disk clause below, so the ordering is the safety
        # argument for correct specs: the handler ran, THEN the watermark
        # advanced, THEN the disk crash measures its loss — a spec that
        # syncs before acking can never lose an acked write to this
        # clause, even when the sync and the crash land on one step.
        dur_mid = state.dur
        if self._dur_state:
            sf = spec.sync_field
            dur_adv = getattr(node, sf) > getattr(node0, sf)  # [L,N]
            dur_mid = _tree_where(dur_adv, self._dur_of(node), state.dur)

        # -- 5e. nemesis disk-fault cycle (slow -> crash -> recover) --------
        # The durability clause (docs/nemesis.md r18): occurrence k opens
        # a DEGRADED window at the schedule-drawn victim (host face:
        # writes pay extra latency, fsync raises EIO; device face: a pure
        # fire/trace marker), then the disk DIES — the victim is killed
        # and, at recovery, rebuilt from its durable WATERMARK instead of
        # live state: exactly the unsynced-tail-lost middle regime that
        # crash-preserve (on_restart keeps everything) and wipe (init
        # keeps nothing) both structurally miss. All three phases of
        # occurrence k share ONE triage gate at k (like a reconfig's
        # remove/join pair), and the victim + torn bit are recomputed
        # pure draws at index k, never carried state.
        tr_dslow = jnp.full((L,), -1, jnp.int32)
        tr_dcrash = jnp.full((L,), -1, jnp.int32)
        tr_drecover = jnp.full((L,), -1, jnp.int32)
        tr_dtorn = jnp.zeros((L,), jnp.bool_)
        ap_dslow = ap_dcrash = ap_drecover = None
        drec_mask = None
        unsynced_lost = jnp.zeros((L,), jnp.int32)
        nem_disk_at = nem_disk_phase = nem_disk_k = None
        if cfg.nem_disk_enabled:
            nst = state.nem
            disk_due = active & (nst.disk_at <= t_next)
            dk = nst.disk_k
            do_dslow = disk_due & (nst.disk_phase == 0)
            do_dcrash = disk_due & (nst.disk_phase == 1)
            do_drecover = disk_due & (nst.disk_phase == 2)
            disk_en = (
                _occ_on(ctl, "disk", dk) if self.triage
                else jnp.ones((L,), jnp.bool_)
            )
            dvictim = prng.randint(
                state.key0, NEM_SITE_DISK_VICTIM, 0, N, index=dk
            )
            if cfg.nem_disk_torn_rate > 0:
                torn = (
                    prng.bits(state.key0, NEM_SITE_DISK_TORN, index=dk)
                    % jnp.uint32(COIN_DENOM)
                ) < jnp.uint32(round(cfg.nem_disk_torn_rate * COIN_DENOM))
            else:
                torn = jnp.zeros((L,), jnp.bool_)
            ap_dslow = do_dslow & disk_en
            ap_dcrash = do_dcrash & disk_en
            ap_drecover = do_drecover & disk_en
            dcrash_mask = ap_dcrash[:, None] & (node_ids == dvictim[:, None])
            drec_mask = ap_drecover[:, None] & (node_ids == dvictim[:, None])
            # the disk crash kills the victim like a crash-clause kill:
            # liveness bit down, in-flight messages to it lost
            alive = (alive & ~dcrash_mask) | drec_mask
            valid = valid & ~dcrash_mask[:, :, None]
            if self._B:
                svalid = svalid & ~(
                    ap_dcrash[:, None] & (strag.dst == dvictim[:, None])
                )
            # unsynced loss: the victim's durable fields differ from its
            # watermark at the crash instant — everything acked since the
            # last sync point is about to vanish (no durable contract =
            # the whole node state is unsynced by definition)
            if self._dur_state:
                differs = jnp.zeros((L, N), jnp.bool_)
                for f in spec.durable_fields:
                    d = (
                        getattr(dur_mid, f).astype(jnp.int32)
                        != getattr(node, f)
                    )
                    differs = differs | d.reshape(L, N, -1).any(axis=2)
                unsynced_lost = (
                    (dcrash_mask & differs).any(axis=1).astype(jnp.int32)
                )
            else:
                unsynced_lost = ap_dcrash.astype(jnp.int32)
            # RECOVERY: rebuild from what the disk durably holds — a fresh
            # init state with the durable fields replaced by the (widened)
            # watermark, optionally refined by spec.on_recover (which sees
            # the torn bit); no durable contract degenerates to a wipe.
            # The hook's returned timer is a RELATIVE delay from the
            # recovery instant (init semantics), shifted + skew-rescaled
            # exactly like a join's.
            ns_d, timer_d = self._v_init(rkeys, narange)
            timer_d = jnp.asarray(timer_d, jnp.int32)
            if self._dur_state:
                wm = self._widen_dur(dur_mid)
                ns_d = ns_d._replace(**{
                    f: getattr(wm, f) for f in spec.durable_fields
                })
            if self._v_on_recover is not None:
                ns_d, timer_d = self._v_on_recover(
                    ns_d, node_ids, t_next, torn, rkeys
                )
                timer_d = jnp.asarray(timer_d, jnp.int32)
            d_ok = (timer_d >= 0) & (timer_d < INF_GUARD)
            timer_d = jnp.where(d_ok, timer_d + t_next[:, None], timer_d)
            if cfg.nem_skew_enabled:
                dd = timer_d - t_next[:, None]
                sk_d = d_ok & (dd > 0)
                timer_d = jnp.where(
                    sk_d,
                    t_next[:, None] + scale_delay_ppm(dd, state.nem.skew_ppm),
                    timer_d,
                )
            if spec.time_fields:
                ns_d = ns_d._replace(**{
                    f: getattr(ns_d, f)
                    + t_next.reshape((L,) + (1,) * (getattr(ns_d, f).ndim - 1))
                    for f in spec.time_fields
                })
            node = _tree_where(drec_mask, ns_d, node)
            timer = jnp.where(drec_mask, timer_d, timer)
            # schedule arithmetic: next toggle = previous toggle time plus
            # an occurrence-indexed delta (never clock + delta)
            slow_d = prng.randint(
                state.key0, NEM_SITE_DISK_SLOW, cfg.nem_disk_slow_lo_us,
                cfg.nem_disk_slow_hi_us, index=dk,
            )
            down_d = prng.randint(
                state.key0, NEM_SITE_DISK_DOWN, cfg.nem_disk_down_lo_us,
                cfg.nem_disk_down_hi_us, index=dk,
            )
            next_d = prng.randint(
                state.key0, NEM_SITE_DISK_IV, cfg.nem_disk_interval_lo_us,
                cfg.nem_disk_interval_hi_us, index=dk + 1,
            )
            nem_disk_at = jnp.where(
                do_dslow, nst.disk_at + slow_d,
                jnp.where(
                    do_dcrash, nst.disk_at + down_d,
                    jnp.where(
                        do_drecover, nst.disk_at + next_d, nst.disk_at
                    ),
                ),
            )
            nem_disk_phase = jnp.where(
                do_dslow, 1,
                jnp.where(
                    do_dcrash, 2, jnp.where(do_drecover, 0, nst.disk_phase)
                ),
            )
            nem_disk_k = dk + do_drecover.astype(jnp.int32)
            tr_dslow = jnp.where(ap_dslow, dvictim, -1)
            tr_dcrash = jnp.where(ap_dcrash, dvictim, -1)
            tr_drecover = jnp.where(ap_drecover, dvictim, -1)
            tr_dtorn = (ap_dcrash | ap_drecover) & torn

        # durability watermark RESET (half 2 of 2, node now final): where
        # wipe / join / disk-recover just installed a fresh node state,
        # that state IS the new on-disk truth (a wiped or joining node
        # boots fsynced like init; a recovered node's durable fields were
        # just read FROM the disk). Reset targets are disjoint from the
        # advance targets above — an event-processing node is never also
        # restarting — so the reset simply layers on dur_mid.
        new_dur = dur_mid
        if self._dur_state:
            reset = drec_mask
            if (
                any_crash and cfg.nem_crash_enabled
                and cfg.nem_crash_wipe_rate > 0
            ):
                reset = reset | wipe_mask
            if cfg.nem_reconfig_enabled:
                reset = reset | join_mask
            new_dur = _tree_where(reset, self._dur_of(node), dur_mid)

        # -- 6. collect outboxes, roll the network, pack into pool ---------
        def flat(out: Outbox, emitting, e):  # [L,N,e,...] -> [L, N*e, ...]
            v = (out.valid & emitting[:, :, None]).reshape(L, N * e)
            return (
                v,
                out.dst.reshape(L, N * e),
                out.kind.reshape(L, N * e),
                out.payload.reshape(L, N * e, P),
            )

        C = self._C
        if self._fused:
            cand_valid, cd, cand_kind, cand_pay = flat(out_e, evt, spec.max_out)
            cand_dst = jnp.clip(cd, 0, N - 1)
        else:
            E_m, E_t = spec.max_out_msg, spec.max_out
            mv, md, mk, mp = flat(out_m, has_msg, E_m)
            tv, td, tk, tp = flat(out_t, due_t, E_t)
            cand_valid = jnp.concatenate([mv, tv], axis=1)  # [L,Cb]
            cand_dst = jnp.clip(jnp.concatenate([md, td], axis=1), 0, N - 1)
            cand_kind = jnp.concatenate([mk, tk], axis=1)
            cand_pay = jnp.concatenate([mp, tp], axis=1)

        net_key = prng.fold(key, 105)[:, None]
        if self._dup:
            # nemesis duplication: interleave a coin-gated copy of every
            # candidate (position 2c+1 mirrors 2c); the copy rolls its own
            # loss/latency below, so it can arrive reordered or die alone
            bidx = jnp.arange(self._Cb, dtype=jnp.uint32)[None, :]
            if self.triage:
                # per-lane scaled rate on the SAME uniform stream
                # (bernoulli is `uniform < p`): a scaled-down lane's dup
                # set is a strict subset of the full-rate lane's
                p_dup = (
                    jnp.float32(cfg.nem_dup_rate)
                    * ctl.rate_scale[:, RATE_ROW["dup"]]
                    * _clause_on(ctl, "dup").astype(jnp.float32)
                )[:, None]
            else:
                p_dup = cfg.nem_dup_rate
            dcoin = prng.uniform(net_key, NET_SITE_DUP, index=bidx) < p_dup
            dup_fires = (cand_valid & dcoin).sum(axis=1, dtype=jnp.int32)

            def il(x):
                if x.ndim == 2:
                    return jnp.stack([x, x], axis=2).reshape(L, C)
                return jnp.stack([x, x], axis=2).reshape(L, C, P)

            cand_valid = jnp.stack(
                [cand_valid, cand_valid & dcoin], axis=2
            ).reshape(L, C)
            cand_dst, cand_kind, cand_pay = il(cand_dst), il(cand_kind), il(cand_pay)
        else:
            dup_fires = jnp.zeros((L,), jnp.int32)

        # network rolls: loss + latency (+ buggify heavy-tail coin)
        cidx = jnp.arange(C, dtype=jnp.uint32)[None, :]
        u = prng.uniform(net_key, 1, index=cidx)
        lat = prng.randint(
            net_key, 2, cfg.latency_lo_us,
            max(cfg.latency_hi_us, cfg.latency_lo_us + 1), index=cidx,
        )
        cand_dst_oh = cand_dst[:, :, None] == narange[None, None, :]  # [L,C,N]
        keep = cand_valid & (u >= cfg.loss_rate)
        # sends to currently-dead nodes are dropped (clogged-node
        # semantics) and counted in their OWN lane counter: pool-overflow
        # drops mean back-pressure, dead-node drops mean crash fallout,
        # and graceful-degradation assertions need to tell them apart
        if cfg.nem_reconfig_enabled:
            # membership filter FIRST, so the two drop classes stay
            # disjoint: a send to a REMOVED node counts here (whatever its
            # alive bit says), a send to a crashed member in dead_dropped
            member_dst = (cand_dst_oh & member[:, None, :]).any(-1)
            nonmember_dropped = (keep & ~member_dst).sum(
                axis=1, dtype=jnp.int32
            )
            keep = keep & member_dst
        else:
            nonmember_dropped = jnp.zeros((L,), jnp.int32)
        alive_dst = (cand_dst_oh & alive[:, None, :]).any(-1)
        dead_dropped = (keep & ~alive_dst).sum(axis=1, dtype=jnp.int32)
        keep = keep & alive_dst
        if cfg.any_partition_enabled:
            # link test at send time (test_link, network.rs:261-269): the
            # candidate's source node is static per position, so the link row
            # is a constant-index gather, then matched against the dst one-hot
            src_rows = link_ok[:, self._src_of_c, :]  # [L,C,N]
            keep = keep & (cand_dst_oh & src_rows).any(-1)
        if cfg.nem_clog_enabled:
            # asymmetric clog: drop candidates whose (static source,
            # dynamic dst) match the lane's clogged directed link
            src_const = jnp.asarray(self._src_of_c, jnp.int32)  # [C]
            clog_hit = (
                clogged[:, None]
                & (src_const[None, :] == clog_src[:, None])
                & (cand_dst == clog_dst[:, None])
            )
            if self.triage:
                clog_hit = clog_hit & clog_en[:, None]
            keep = keep & ~clog_hit
        if cfg.nem_loss_rate > 0:
            # nemesis extra loss coin, rolled LAST — only on messages that
            # survived base loss, dead destinations, partitions and clogs.
            # fires_loss therefore counts the clause's own coin on traffic
            # that would otherwise have been delivered, which is what the
            # host NetSim counts too (its clog check precedes the coin);
            # the coverage report reads the same on both backends
            u2 = prng.uniform(net_key, NET_SITE_NEM_LOSS, index=cidx)
            if self.triage:
                p_loss = (
                    jnp.float32(cfg.nem_loss_rate)
                    * ctl.rate_scale[:, RATE_ROW["loss"]]
                    * _clause_on(ctl, "loss").astype(jnp.float32)
                )[:, None]
            else:
                p_loss = cfg.nem_loss_rate
            nem_lost = keep & (u2 < p_loss)
            loss_drops = nem_lost.sum(axis=1, dtype=jnp.int32)
            keep = keep & ~nem_lost
        else:
            loss_drops = jnp.zeros((L,), jnp.int32)
        if cfg.nem_reorder_rate > 0:
            # bounded reordering: an extra uniform delay in [0, window] —
            # latency only LENGTHENS, so the conservative lookahead bound
            # (latency_lo) is untouched while later sends overtake
            if self.triage:
                p_ro = (
                    jnp.float32(cfg.nem_reorder_rate)
                    * ctl.rate_scale[:, RATE_ROW["reorder"]]
                    * _clause_on(ctl, "reorder").astype(jnp.float32)
                )[:, None]
            else:
                p_ro = cfg.nem_reorder_rate
            rcoin = keep & (
                prng.uniform(net_key, NET_SITE_REORDER, index=cidx) < p_ro
            )
            extra = prng.randint(
                net_key, NET_SITE_REORDER_EXTRA, 0,
                cfg.nem_reorder_window_us + 1, index=cidx,
            )
            lat = jnp.where(rcoin, lat + extra, lat)
            reorder_fires = rcoin.sum(axis=1, dtype=jnp.int32)
        else:
            reorder_fires = jnp.zeros((L,), jnp.int32)
        if cfg.nem_spike_enabled:
            spike_open = spiking & spike_en if self.triage else spiking
            lat = jnp.where(
                spike_open[:, None], lat + jnp.int32(cfg.nem_spike_extra_us),
                lat,
            )
        if self._B:
            # the rand_delay buggify tail (net/mod.rs:287-295): a surviving
            # message occasionally takes seconds instead of milliseconds
            bug = keep & prng.bernoulli(net_key, 3, cfg.buggify_delay_rate,
                                        index=cidx)
            tail = prng.randint(
                net_key, 4, cfg.buggify_delay_lo_us,
                max(cfg.buggify_delay_hi_us, cfg.buggify_delay_lo_us + 1),
                index=cidx,
            )
            lat = jnp.where(bug, tail, lat)
        else:
            bug = jnp.zeros((L, C), jnp.bool_)
        # stamp each send from its EMITTING node's event time (candidate
        # positions map statically to their source node), so latency is
        # measured from the send instant, not the lane's window maximum
        deliver_at = t_evt[:, self._src_of_c] + lat.astype(jnp.int32)  # [L,C]

        send = keep & ~bug  # [L,C] candidate sends this step
        if self._fused:
            # NODE-POOLED pack (fused specs): the i-th valid send of node n
            # takes the i-th free slot of n's SK-slot pool — rank matching,
            # fully parallel (no sequential first-free over rows), and
            # bursts that cluster on one outbox row borrow slack from quiet
            # rows. A send ranks past the free count => DROPPED (counted):
            # overwriting a pending slot would corrupt a message in flight.
            E, SK = self._E_pack, self._SK  # E doubles under duplication
            send_n = send.reshape(L, N, E)
            free = (~valid.any(1)).reshape(L, N, SK)  # [L,Nsrc,SK]

            def prefix_counts(m):
                # exclusive prefix count, UNROLLED on purpose: cumsum is a
                # scan op that breaks XLA's elementwise fusion in this
                # context (measured for the first-free masks, see
                # docs/perf_notes.md "dtypes and ops"); the trailing dims
                # here are tiny statics (E, SK)
                out = []
                acc = jnp.zeros(m.shape[:-1], jnp.int32)
                for k in range(m.shape[-1]):
                    out.append(acc)
                    acc = acc + m[..., k].astype(jnp.int32)
                return jnp.stack(out, -1), acc

            r_send, _ = prefix_counts(send_n)  # [L,N,E]
            r_free, n_free = prefix_counts(free)  # [L,N,SK], [L,N]
            place = (
                send_n[:, :, :, None]
                & free[:, :, None, :]
                & (r_send[:, :, :, None] == r_free[:, :, None, :])
            )  # [L,N,E,SK]
            ring_w = place.any(2).reshape(L, CK)
            overflow = state.overflow + (
                send_n & (r_send >= n_free[:, :, None])
            ).sum(axis=(1, 2), dtype=jnp.int32)
            place_i = place.astype(jnp.int32)

            def put(ring_vals, cand_vals):
                # the one-hot multiply runs in i32 (u8 products could wrap);
                # the result narrows back to the ring's at-rest dtype
                cv = cand_vals.astype(jnp.int32).reshape(
                    (L, N, E) + cand_vals.shape[2:]
                )
                if cand_vals.ndim == 2:
                    inc = (place_i * cv[:, :, :, None]).sum(2)
                    return jnp.where(
                        ring_w,
                        inc.reshape(L, CK).astype(ring_vals.dtype),
                        ring_vals,
                    )
                inc = (place_i[:, :, :, :, None] * cv[:, :, :, None, :]).sum(2)
                return jnp.where(
                    ring_w[:, :, None],
                    inc.reshape(L, CK, P).astype(ring_vals.dtype),
                    ring_vals,
                )

            # validity bits: dst d references slot s iff the send that
            # took s targets d
            dsts = cand_dst_oh.reshape(L, N, E, N)
            written = (
                place[:, :, :, :, None] & dsts[:, :, :, None, :]
            ).any(2).transpose(0, 3, 1, 2).reshape(L, N, CK)
        else:
            # per-candidate rings: candidate c's message takes the FIRST of
            # its K ring slots that no destination still references; if all
            # K are pending the send is DROPPED (counted). Everything is
            # elementwise on [L,c,K] / [L,N,c,K] masks, per depth segment
            # (see SimConfig).
            dst_major = cand_dst_oh.transpose(0, 2, 1)  # [L,N,C]
            ring_w_parts = []  # [L, nc*K] ring-slot write masks
            place_parts = []  # [L, N, nc*K] validity-bit writes
            ovf = jnp.zeros((L,), jnp.int32)
            for c0, c1, K, s0, s1 in self._segs:
                nc = c1 - c0
                send_seg = send[:, c0:c1]  # [L,nc]
                free = ~valid[:, :, s0:s1].reshape(L, N, nc, K).any(1)
                ring_w = send_seg[:, :, None] & _first_free(free, K)
                placed = ring_w.any(2)  # [L,nc]
                ovf = ovf + (send_seg & ~placed).sum(axis=1, dtype=jnp.int32)
                ring_w_parts.append(ring_w.reshape(L, nc * K))
                place_parts.append(
                    (dst_major[:, :, c0:c1, None] & ring_w[:, None]).reshape(
                        L, N, nc * K
                    )
                )
            ring_w = (
                ring_w_parts[0] if len(ring_w_parts) == 1
                else jnp.concatenate(ring_w_parts, axis=1)
            )  # [L,CK]
            written = (
                place_parts[0] if len(place_parts) == 1
                else jnp.concatenate(place_parts, axis=2)
            )  # [L,N,CK]
            overflow = state.overflow + ovf

            def ring_expand(cand_vals):  # [L,C(,P)] -> [L,CK(,P)] per segment
                outs = []
                for c0, c1, K, s0, s1 in self._segs:
                    nc = c1 - c0
                    seg = cand_vals[:, c0:c1]
                    if cand_vals.ndim == 2:
                        outs.append(
                            jnp.broadcast_to(
                                seg[:, :, None], (L, nc, K)
                            ).reshape(L, nc * K)
                        )
                    else:
                        outs.append(
                            jnp.broadcast_to(
                                seg[:, :, None, :], (L, nc, K, P)
                            ).reshape(L, nc * K, P)
                        )
                return (
                    outs[0] if len(outs) == 1
                    else jnp.concatenate(outs, axis=1)
                )

            def put(ring_vals, cand_vals):
                inc = ring_expand(cand_vals)
                if cand_vals.ndim == 2:
                    return jnp.where(ring_w, inc, ring_vals)
                return jnp.where(ring_w[:, :, None], inc, ring_vals)

        new_valid = valid | written
        # slots no destination references anymore reset their deliver
        # offset to INF_US: a stale offset would be rebased epoch after
        # epoch (rb() below) and eventually wrap int32 — benign for current
        # readers (validity-gated) but a trap, and it makes long-soak state
        # non-canonical (ADVICE r4)
        new_deliver = put(
            jnp.where(valid.any(1), msgs.deliver, INF_US), deliver_at
        )
        new_kind = put(msgs.kind, cand_kind.astype(self._kind_dtype))
        new_payload = put(msgs.payload, cand_pay)
        if lin is not None:
            # lineage stamp: a send carries its emitting EVENT's id — the
            # candidate's source node is static per position, so this is a
            # constant-index gather; duplicates share their original's
            # send event (one cause, two deliveries). Freed slots reset to
            # 0 like deliver resets to INF_US (canonical at-rest state).
            cand_seid16 = (
                evt_eid_full[:, self._src_of_c] & jnp.uint32(0xFFFF)
            ).astype(jnp.uint16)  # [L,C]
            new_sent_eid = put(
                jnp.where(valid.any(1), msgs.sent_eid, jnp.uint16(0)),
                cand_seid16,
            )
        else:
            cand_seid16 = None
            new_sent_eid = None

        # straggler pack: region c owns K4 slots of the side pool
        if self._B:
            K4, B = self._K4, self._B
            sb = keep & bug  # [L,C]
            sfree = ~svalid.reshape(L, C, K4)
            splace = sb[:, :, None] & _first_free(sfree, K4)  # [L,C,K4]
            swritten = splace.reshape(L, B)
            overflow = overflow + (sb & ~splace.any(2)).sum(axis=1, dtype=jnp.int32)

            def sput(pool_vals, cand_vals):
                if cand_vals.ndim == 2:
                    inc = jnp.broadcast_to(
                        cand_vals[:, :, None], (L, C, K4)
                    ).reshape(L, B)
                    return jnp.where(swritten, inc, pool_vals)
                inc = jnp.broadcast_to(
                    cand_vals[:, :, None, :], (L, C, K4, P)
                ).reshape(L, B, P)
                return jnp.where(swritten[:, :, None], inc, pool_vals)

            new_strag = StragPool(
                valid=svalid | swritten,
                deliver=sput(jnp.where(svalid, strag.deliver, INF_US), deliver_at),
                dst=sput(strag.dst, cand_dst.astype(jnp.uint8)),
                kind=sput(strag.kind, cand_kind.astype(self._kind_dtype)),
                payload=sput(strag.payload, cand_pay),
                sent_eid=(
                    None if lin is None
                    else sput(
                        jnp.where(svalid, strag.sent_eid, jnp.uint16(0)),
                        cand_seid16,
                    )
                ),
            )
        else:
            new_strag = None

        # -- 6b. chaos fire counts (the coverage report's raw data) --------
        # every enabled clause must show nonzero fires over a seed batch;
        # an enabled clause with zero fires is dead chaos (nemesis.py)
        zl = jnp.zeros((L,), jnp.int32)
        cols = [zl] * len(FIRE_KINDS)

        def _count(kind, arr):
            cols[FIRE_INDEX[kind]] = cols[FIRE_INDEX[kind]] + (
                arr.astype(jnp.int32) if arr.dtype == jnp.bool_ else arr
            )

        if any_crash:
            _count("crash", ap_crash)
            _count("restart", ap_restart)
            if cfg.nem_crash_enabled and cfg.nem_crash_wipe_rate > 0:
                ap_wipe = ap_crash & wipe_coin
                if self.triage:
                    ap_wipe = ap_wipe & _clause_on(ctl, "wipe")
                _count("wipe", ap_wipe)
        if cfg.any_partition_enabled:
            _count("partition", ap_split)
            _count("heal", ap_heal)
        if cfg.nem_clog_enabled:
            _count("clog", do_clog & clog_en)
        if cfg.nem_spike_enabled:
            _count("spike", do_spike & spike_en)
        if cfg.nem_reconfig_enabled:
            _count("remove", ap_remove)
            _count("join", ap_join)
        if cfg.nem_disk_enabled:
            _count("disk_slow", ap_dslow)
            _count("disk_crash", ap_dcrash)
            _count("disk_recover", ap_drecover)
        _count("loss", loss_drops)
        _count("dup", dup_fires)
        _count("reorder", reorder_fires)
        fires = state.fires + jnp.stack(cols, axis=1)

        # clause x occurrence fire bitmasks (the occurrence dimension of the
        # chaos report and of the explorer's novelty signal). A window's bit
        # is set when its OPEN half applies; suppressed (triage) occurrences
        # stay unset, so a shrunk lane's occ_fired is the survivors only.
        occ_fired = state.occ_fired
        if occ_fired is not None:
            ocols = [occ_fired[:, i] for i in range(len(OCC_CLAUSES))]

            def _occ_mark(row, fired, k):
                bit = jnp.uint32(1) << jnp.clip(k, 0, 31).astype(jnp.uint32)
                ocols[row] = jnp.where(fired, ocols[row] | bit, ocols[row])

            if cfg.nem_crash_enabled:
                _occ_mark(OCC_ROW["crash"], ap_crash, state.nem.crash_k)
            if cfg.nem_partition_enabled:
                _occ_mark(OCC_ROW["partition"], ap_split, state.nem.part_k)
            if cfg.nem_clog_enabled:
                _occ_mark(OCC_ROW["clog"], do_clog & clog_en, state.nem.clog_k)
            if cfg.nem_spike_enabled:
                _occ_mark(
                    OCC_ROW["spike"], do_spike & spike_en, state.nem.spike_k
                )
            if cfg.nem_reconfig_enabled:
                # the OPEN half marks the occurrence, like every clause
                # (k is shared by the remove and its paired join)
                _occ_mark(
                    OCC_ROW["reconfig"], ap_remove, state.nem.reconfig_k
                )
            if cfg.nem_disk_enabled:
                # the OPEN half (disk_slow) marks the occurrence; k is
                # shared by all three phases of the cycle
                _occ_mark(OCC_ROW["disk"], ap_dslow, state.nem.disk_k)
            occ_fired = jnp.stack(ocols, axis=1)

        # -- 7. invariants + lane lifecycle --------------------------------
        ok = self._v_check(node, alive, clock)
        new_violation = active & ~ok & ~state.violated
        violated = state.violated | new_violation
        violation_at = jnp.where(new_violation, clock, state.violation_at)
        violation_epoch = jnp.where(new_violation, state.epoch,
                                    state.violation_epoch)
        # first violating step index: state.steps is the count of completed
        # active steps BEFORE this one, i.e. this step's 0-based index —
        # run(max_steps=violation_step + 1) re-reaches the violation
        violation_step = jnp.where(
            new_violation, state.steps, state.violation_step
        )
        # horizon in (epoch, offset) space: horizon_us may exceed int32
        if self.triage:
            # per-lane horizon: the shrinker's time-truncation axis
            reached_horizon = (state.epoch > ctl.h_epoch) | (
                (state.epoch == ctl.h_epoch) & (clock >= ctl.h_off)
            )
        else:
            eh, oh = divmod(int(cfg.horizon_us), REBASE_US)
            reached_horizon = (state.epoch > eh) | (
                (state.epoch == eh) & (clock >= oh)
            )
        done = state.done | deadlocked | reached_horizon | violated

        # -- 7b. coverage accumulation (BatchedSim(coverage=True) only) ----
        # One bit per exercised event class: hash(dst node, src, msg kind,
        # payload[0] magnitude bucket) for deliveries, hash(node, -1, -1, 0)
        # for timer fires — all trace-visible fields, so explore.py's pure
        # mirror recomputes the exact bitmap from a TraceRecord stream.
        # Computed BEFORE the epoch rebase: the transition compare below
        # must not see time_fields shifts as state changes.
        cov: Optional[Coverage] = state.cov
        if cov is not None:
            evt_cov = has_msg | due_t  # [L,N] (active-gated via the picks)
            src_w = jnp.where(has_msg, m_src, jnp.int32(-1))
            kind_w = jnp.where(has_msg, m_kind, jnp.int32(-1))
            p0 = jnp.where(has_msg, m_pay[:, :, 0], 0).astype(jnp.uint32)
            # magnitude bucket = bit_length(payload[0] as u32): state-bearing
            # payload words (terms, indices) contribute ~log2 buckets, not a
            # fresh bit per value — AFL-style bucketing so high-cardinality
            # counters can't drown structural novelty
            bucket = jnp.where(
                has_msg, jnp.int32(32) - jax.lax.clz(p0).astype(jnp.int32), 0
            )
            ck = prng.fold(jnp.uint32(COV_SALT), node_ids)  # [L,N]
            ck = prng.fold(ck, src_w)
            ck = prng.fold(ck, kind_w)
            ck = prng.fold(ck, bucket)
            idx = prng.mix(ck) % jnp.uint32(COV_BITS)  # [L,N]
            word = (idx // 32).astype(jnp.int32)
            wbit = jnp.uint32(1) << (idx % 32)
            bm = cov.bitmap
            warange = jnp.arange(COV_WORDS, dtype=jnp.int32)[None, :]
            for ni in range(N):  # N is small + static: unrolled OR-scatter
                sel = evt_cov[:, ni : ni + 1] & (
                    warange == word[:, ni : ni + 1]
                )
                bm = bm | jnp.where(sel, wbit[:, ni : ni + 1], jnp.uint32(0))
            # scalar features: pool-occupancy high water + state-changing
            # event count (protocol progress vs idle traffic)
            occupancy = new_valid.any(axis=1).sum(axis=1, dtype=jnp.int32)
            if self._B:
                occupancy = occupancy + new_strag.valid.sum(
                    axis=1, dtype=jnp.int32
                )
            changed = jnp.zeros((L, N), jnp.bool_)
            for old_leaf, new_leaf in zip(
                jax.tree_util.tree_leaves(node0),
                jax.tree_util.tree_leaves(node),
            ):
                changed = changed | (old_leaf != new_leaf).reshape(
                    L, N, -1
                ).any(axis=2)
            cov = Coverage(
                bitmap=bm,
                hiwater=jnp.maximum(cov.hiwater, occupancy),
                transitions=cov.transitions
                + (evt_cov & changed).sum(axis=1, dtype=jnp.int32),
            )

        # -- 8. epoch rebase: unbounded virtual time, int32 arithmetic -----
        # (see spec.REBASE_US). Done lanes freeze as-is; sentinel values
        # (INF_US timers / disabled chaos) are never rebased.
        do_shift = (~done) & (clock >= REBASE_US)
        shift = jnp.where(do_shift, jnp.int32(REBASE_US), 0)  # [L]

        def rb(x, s):  # rebase a live-offset tensor, guarding sentinels
            s = s.reshape(s.shape + (1,) * (x.ndim - 1))
            return jnp.where(x < INF_GUARD, x - s, x)

        clock = clock - shift
        epoch = state.epoch + do_shift.astype(jnp.int32)
        timer = rb(timer, shift)
        chaos_at = rb(chaos_at, shift)
        part_at = rb(part_at, shift)
        new_deliver = rb(new_deliver, shift)
        if state.nem is not None:
            nst = state.nem
            new_nem = NemesisState(
                crash_k=nem_crash_k if nem_crash_k is not None else nst.crash_k,
                wipe=nem_wipe if nem_wipe is not None else nst.wipe,
                part_k=nem_part_k if nem_part_k is not None else nst.part_k,
                clog_at=rb(
                    nem_clog_at if nem_clog_at is not None else nst.clog_at,
                    shift,
                ),
                clogged=clogged if clogged is not None else nst.clogged,
                clog_src=clog_src if clog_src is not None else nst.clog_src,
                clog_dst=clog_dst if clog_dst is not None else nst.clog_dst,
                clog_k=nem_clog_k if nem_clog_k is not None else nst.clog_k,
                spike_at=rb(
                    nem_spike_at if nem_spike_at is not None else nst.spike_at,
                    shift,
                ),
                spiking=spiking if spiking is not None else nst.spiking,
                spike_k=nem_spike_k if nem_spike_k is not None else nst.spike_k,
                reconfig_at=rb(
                    nem_reconfig_at if nem_reconfig_at is not None
                    else nst.reconfig_at,
                    shift,
                ),
                reconf_node=(
                    nem_reconf_node if nem_reconf_node is not None
                    else nst.reconf_node
                ),
                reconfig_k=(
                    nem_reconfig_k if nem_reconfig_k is not None
                    else nst.reconfig_k
                ),
                disk_at=rb(
                    nem_disk_at if nem_disk_at is not None else nst.disk_at,
                    shift,
                ),
                disk_phase=(
                    nem_disk_phase if nem_disk_phase is not None
                    else nst.disk_phase
                ),
                disk_k=nem_disk_k if nem_disk_k is not None else nst.disk_k,
                skew_ppm=nst.skew_ppm,
            )
        else:
            new_nem = None
        if self._B:
            new_strag = new_strag._replace(
                deliver=rb(new_strag.deliver, shift)
            )
        if spec.time_fields:
            node = node._replace(**{
                f: getattr(node, f)
                - shift.reshape((L,) + (1,) * (getattr(node, f).ndim - 1))
                for f in spec.time_fields
            })

        new_state = SimState(
            clock=clock,
            epoch=epoch,
            key=key,
            key0=state.key0,
            done=done,
            violated=violated,
            violation_at=violation_at,
            violation_epoch=violation_epoch,
            violation_step=violation_step,
            deadlocked=state.deadlocked | deadlocked,
            steps=state.steps + active.astype(jnp.int32),
            events=state.events
            + has_msg.sum(axis=1, dtype=jnp.int32)
            + due_t.sum(axis=1, dtype=jnp.int32),
            overflow=overflow,
            dead_drops=state.dead_drops + dead_dropped,
            nonmember_drops=state.nonmember_drops + nonmember_dropped,
            unsynced_loss=state.unsynced_loss + unsynced_lost,
            fires=fires,
            occ_fired=occ_fired,
            alive_p=bitpack.pack_bits(alive),
            crashed=crashed,
            chaos_at=chaos_at,
            member_p=(
                bitpack.pack_bits(member) if member is not None
                else state.member_p
            ),
            member_epoch=member_epoch,
            link_ok_p=bitpack.pack_bits(link_ok),
            partitioned=partitioned,
            part_at=part_at,
            timer=timer,
            node=self._narrow_node(node),
            dur=new_dur,
            msgs=MsgPool(
                valid_p=bitpack.pack_bits(new_valid),
                deliver=new_deliver,
                kind=new_kind,
                payload=new_payload,
                sent_eid=new_sent_eid,
            ),
            strag=new_strag,
            nem=new_nem,
            ctl=state.ctl,
            cov=cov,
            lin=(
                None if lin is None
                else Lineage(lam=new_lam, eid=new_lin_eid)
            ),
            queue=state.queue,
            refill=state.refill,
            loop=state.loop,
        )
        # -- 9. continuous batching: retire finished lanes, admit the next
        # queued seed/genome in-jit (docs/continuous_batching.md). A no-op
        # branch (lax.cond) on steps where no lane retires, so plain sweep
        # steps pay one lane-axis any() and nothing else.
        if state.refill is not None:
            new_state = self._refill_apply(state, new_state, active)
        # -- 10. device-resident search (r19, docs/explore.md): when the
        # whole generation has retired, fold its coverage into the corpus
        # ring, mutate the next population from the meta-rng chain, and
        # rewrite the admission queue — all under a lax.cond that stays a
        # no-op until the LAST admission of a generation retires.
        if state.loop is not None:
            new_state = self._devloop_apply(new_state)
        record = TraceRecord(
            clock=clock,
            epoch=epoch,
            # report event times in the post-rebase basis, consistent with
            # the record's epoch (extract_trace adds epoch * REBASE_US)
            t_evt=t_evt - shift[:, None],
            msg_fired=has_msg,
            msg_src=m_src,
            msg_kind=m_kind,
            msg_payload=m_pay,
            timer_fired=due_t,
            crash=tr_crash,
            restart=tr_restart,
            split=tr_split,
            heal=tr_heal,
            side_mask=tr_side,
            violation=new_violation,
            deadlock=deadlocked,
            clog_src=tr_clog_src,
            clog_dst=tr_clog_dst,
            unclog=tr_unclog,
            spike_on=tr_spike_on,
            spike_off=tr_spike_off,
            remove=tr_remove,
            join=tr_join,
            disk_slow=tr_dslow,
            disk_crash=tr_dcrash,
            disk_recover=tr_drecover,
            disk_torn=tr_dtorn,
            lam=tr_lam,
            evt_eid=tr_evt_eid,
            sent_eid=tr_sent_eid,
        )
        return new_state, record

    # ------------------------------------------------- continuous batching

    def _refill_apply(
        self, state: SimState, ns: SimState, active: jnp.ndarray
    ) -> SimState:
        """Retire lanes that finished THIS step and admit queued work.

        Runs at the end of every refill-mode step: (1) occupancy counters
        tick unconditionally; (2) under `lax.cond` (taken only on steps
        where some lane retired — each admission retires exactly once, so
        this branch runs at most A times per sweep): harvest the retiring
        lanes' cold accumulators into the per-admission result buffers
        (masked scatter at their admission index, drop-moded), then admit
        the next queue rows — retiring lanes take queue slots in LANE
        ORDER (the exclusive prefix count over the retire mask), re-init
        from the admitted seed (and ctl genome, in triage mode), and the
        cursor advances by the number admitted.

        DETERMINISM: the admitted-seed assignment is the ONLY cross-lane
        coupling in the engine, and it never touches a surviving lane's
        draws — a refilled lane's state is exactly `_init(seed)`'s row,
        so every admission's trajectory is the pure per-seed function the
        chunked path computes, and results are a pure function of
        (admission order, seeds): bit-identical to the chunked sweep for
        any fixed admission order. The lane-axis cumsum/any/sum here are
        the engine's one sanctioned exception to the lane-independence
        rule (see analysis REFILL_LANE_ALLOW)."""
        rf: RefillLog = state.refill
        q: RefillQueue = state.queue
        L = ns.done.shape[0]
        A = q.seeds.shape[0]
        rf = rf._replace(
            iters=rf.iters + jnp.int32(1),
            busy=rf.busy + active.astype(jnp.int32),
        )
        # per-admission step budget: an admission at step_cap retires
        # truncated — the exact state a chunked lane holds when its
        # run(max_steps=cap) loop ends (steps counts active steps, and a
        # live lane is active every iteration, so the cut lands on the
        # same step)
        expired = ~ns.done & (ns.steps >= rf.step_cap)
        ns = ns._replace(done=ns.done | expired)
        just = ns.done & ~state.done  # lanes whose admission retired now

        def retire_and_admit(ns: SimState, rf: RefillLog) -> SimState:
            # -- harvest: one masked scatter per result buffer. idx = A
            # for non-retiring lanes — out of bounds, dropped by mode.
            idx = jnp.where(just, rf.admitted, jnp.int32(A))

            def put(dst, src):
                return dst.at[idx].set(src, mode="drop")

            rf2 = rf._replace(
                retired=put(
                    rf.retired,
                    jnp.broadcast_to(rf.iters - 1, (L,)),
                ),
                violated=put(rf.violated, ns.violated),
                deadlocked=put(rf.deadlocked, ns.deadlocked),
                violation_at=put(rf.violation_at, ns.violation_at),
                violation_epoch=put(rf.violation_epoch, ns.violation_epoch),
                violation_step=put(rf.violation_step, ns.violation_step),
                steps=put(rf.steps, ns.steps),
                events=put(rf.events, ns.events),
                overflow=put(rf.overflow, ns.overflow),
                dead_drops=put(rf.dead_drops, ns.dead_drops),
                nonmember_drops=put(
                    rf.nonmember_drops, ns.nonmember_drops
                ),
                unsynced_loss=put(rf.unsynced_loss, ns.unsynced_loss),
                clock=put(rf.clock, ns.clock),
                epoch=put(rf.epoch, ns.epoch),
                fires=put(rf.fires, ns.fires),
                occ_fired=(
                    None if rf.occ_fired is None
                    else put(rf.occ_fired, ns.occ_fired)
                ),
                cov_bitmap=(
                    None if rf.cov_bitmap is None
                    else put(rf.cov_bitmap, ns.cov.bitmap)
                ),
                cov_hiwater=(
                    None if rf.cov_hiwater is None
                    else put(rf.cov_hiwater, ns.cov.hiwater)
                ),
                cov_transitions=(
                    None if rf.cov_transitions is None
                    else put(rf.cov_transitions, ns.cov.transitions)
                ),
            )

            # -- admit: retiring lane r takes queue row cursor + rank(r),
            # rank = exclusive prefix count over the retire mask in lane
            # order (admission order is therefore deterministic given the
            # retirement schedule, which is itself a pure function of the
            # admitted seeds)
            ji = just.astype(jnp.int32)
            rank = jnp.cumsum(ji) - ji
            adm = rf.cursor + rank
            take = just & (adm < A)
            n_take = jnp.sum(take.astype(jnp.int32))
            adm_c = jnp.clip(adm, 0, A - 1)  # provably in-bounds gathers
            seeds_new = jnp.take(q.seeds, adm_c, axis=0)
            ctl_new = None
            if self.triage:
                ctl_new = TriageCtl(
                    off=jnp.take(q.off, adm_c, axis=0),
                    occ=jnp.take(q.occ, adm_c, axis=0),
                    rate_scale=jnp.take(q.rate_scale, adm_c, axis=0),
                    h_epoch=jnp.take(q.h_epoch, adm_c, axis=0),
                    h_off=jnp.take(q.h_off, adm_c, axis=0),
                )
            # full-width re-init (the REAL _init: same draws, same
            # schedule roots as a fresh chunked lane), then a lane-masked
            # select: non-refilled lanes keep their post-step state
            # bit-for-bit — the schedule-purity half of the contract
            fresh = self._init(seeds_new, ctl_new)
            # strip the non-lane planes before the masked merge (loop too:
            # _init builds loop=None, and the devloop carry is per-window,
            # not per-lane — reattached below untouched)
            base = ns._replace(queue=None, refill=None, loop=None)
            fresh = fresh._replace(queue=None, refill=None, loop=None)

            def sel(f, b):
                m = take.reshape(take.shape + (1,) * (f.ndim - 1))
                return jnp.where(m, f, b)

            merged = jax.tree_util.tree_map(sel, fresh, base)
            rf2 = rf2._replace(
                cursor=rf.cursor + n_take,
                admitted=jnp.where(take, adm, rf.admitted),
            )
            return merged._replace(queue=q, refill=rf2, loop=ns.loop)

        def tick_only(ns: SimState, rf: RefillLog) -> SimState:
            return ns._replace(refill=rf)

        return jax.lax.cond(jnp.any(just), retire_and_admit, tick_only,
                            ns, rf)

    # ------------------------------------------- device-resident search

    def _devloop_apply(self, ns: SimState) -> SimState:
        """Fire the generation boundary once the live generation has
        fully retired (r19, docs/explore.md).

        Runs at the end of every device-loop step, AFTER `_refill_apply`
        (so the final retirements of a generation are already harvested
        into the RefillLog result buffers). The `lax.cond` is a no-op on
        every other step: the predicate — queue drained AND every lane
        done AND the window unfinished — holds exactly once per
        generation, on the step its last admission retires, and the
        boundary both folds the finished generation and (if the window
        has generations left) respawns all lanes on the next population,
        so the sweep never spends an idle step between generations."""
        dl: DevLoop = ns.loop
        rf: RefillLog = ns.refill
        A = int(ns.queue.seeds.shape[0])
        fire = (
            jnp.all(ns.done)
            & (rf.cursor >= jnp.int32(A))
            & (dl.gens_done < dl.target_gens)
        )
        return jax.lax.cond(
            fire, self._devloop_boundary, lambda s: s, ns
        )

    def _devloop_boundary(self, ns: SimState) -> SimState:
        """One in-jit generation boundary: archive -> fold -> mutate ->
        respawn. The traced mirror of what `Explorer` does on the host
        between dispatches, drawing the SAME murmur3 counter chain at
        META_SITE_DRAW so the two faces are draw-for-draw identical
        (explore._run_device_window replays the host face per window and
        asserts exactly that).

          1. ARCHIVE: the finished generation's genomes + per-admission
             results land in the DevLoop arch_* row `gens_done` (the one
             host decode per window reads these).
          2. FOLD (admission order — the order `_fold_part` replays):
             novelty = popcount(bitmap & ~union); a novel admission ORs
             its bitmap into the union and stable-inserts into the
             corpus ring at position = #{rows with bits >= new_bits},
             which keeps the ring equal to the host's
             sorted-by-(-new_bits, dispatch) top-K exactly (ties keep
             admission order; a displaced row has >= K permanent
             dominators, so it can never re-enter on either face).
          3. MUTATE/RESPAWN (only when the window has generations left):
             build the next population with the host `_population`'s
             exact draw schedule — fresh block (no draws), mutants
             (parent choice + `_mutate`'s op draws, genome-hash dedup
             against the seen table with single fresh fallback), swarm
             groups (one coin per togglable clause per group) — then
             encode it into the admission queue and re-`_init` every
             lane on the head rows.

        The seen-table append discipline matches the host claim order
        (mutants at choice time, fresh/swarm at population end; exactly
        one append per candidate), so `seen_n` tracks `len(_seen)` and
        membership — an EXACT masked compare over the valid prefix, not
        a probabilistic filter — diverges from the host only on a 64-bit
        hash collision, which by construction both faces resolve the
        same way."""
        from . import nemesis as tpun

        plan: DevLoopPlan = self.devloop
        dl: DevLoop = ns.loop
        rf: RefillLog = ns.refill
        q: RefillQueue = ns.queue
        L = int(ns.done.shape[0])
        A, K, S = plan.pop, plan.top_k, plan.seen_cap
        G = int(dl.arch_seed.shape[0])
        n_occ = len(OCC_CLAUSES)
        n_rate = len(RATE_CLAUSES)
        meta_key = dl.meta_key

        # -- 1. archive the finished generation at row gens_done (clipped
        # so the dynamic row index is provably in-bounds)
        g = jnp.clip(dl.gens_done, 0, G - 1)

        def arch(dst, src):
            return jax.lax.dynamic_update_slice(
                dst, src[None].astype(dst.dtype),
                (g,) + (jnp.int32(0),) * src.ndim,
            )

        # -- 2. fold admissions into union + ring, in admission order
        kidx = jnp.arange(K, dtype=jnp.int32)

        def fold_body(i, carry):
            union, rb, rs, ro, rocc, rrate, rh, rn, acc = carry
            bm = rf.cov_bitmap[i]
            new = bm & ~union
            nb = jnp.sum(jax.lax.population_count(new).astype(jnp.int32))
            accept = nb > 0
            union2 = jnp.where(accept, union | bm, union)
            # stable top-K insert: after every row with bits >= nb
            pos = jnp.sum((rb >= nb).astype(jnp.int32))
            do = accept & (pos < K)

            def ins(dst, val):
                shifted = jnp.roll(dst, 1, axis=0)
                m = kidx.reshape((K,) + (1,) * (dst.ndim - 1))
                out = jnp.where(
                    m < pos, dst, jnp.where(m == pos, val, shifted)
                )
                return jnp.where(do, out, dst)

            return (
                union2,
                ins(rb, nb),
                ins(rs, q.seeds[i]),
                ins(ro, q.off[i]),
                ins(rocc, q.occ[i]),
                ins(rrate, q.rate_scale[i]),
                ins(rh, dl.gen_h_raw[i]),
                jnp.where(do, jnp.minimum(rn + 1, K), rn),
                acc + accept.astype(jnp.int32),
            )

        (union, ring_bits, ring_seed, ring_off, ring_occ, ring_rate,
         ring_h, ring_n, accepts) = jax.lax.fori_loop(
            0, A, fold_body,
            (dl.union, dl.ring_bits, dl.ring_seed, dl.ring_off,
             dl.ring_occ, dl.ring_rate, dl.ring_h, dl.ring_n,
             dl.accepts),
        )
        gens_done = dl.gens_done + jnp.int32(1)
        folded_loop = dl._replace(
            gens_done=gens_done, accepts=accepts, union=union,
            ring_n=ring_n, ring_bits=ring_bits, ring_seed=ring_seed,
            ring_off=ring_off, ring_occ=ring_occ, ring_rate=ring_rate,
            ring_h=ring_h,
            arch_seed=arch(dl.arch_seed, q.seeds),
            arch_off=arch(dl.arch_off, q.off),
            arch_occ=arch(dl.arch_occ, q.occ),
            arch_rate=arch(dl.arch_rate, q.rate_scale),
            arch_h=arch(dl.arch_h, dl.gen_h_raw),
            arch_origin=arch(dl.arch_origin, dl.gen_origin),
            arch_violated=arch(dl.arch_violated, rf.violated),
            arch_bitmap=arch(dl.arch_bitmap, rf.cov_bitmap),
            arch_hiwater=arch(dl.arch_hiwater, rf.cov_hiwater),
            arch_transitions=arch(
                dl.arch_transitions, rf.cov_transitions
            ),
        )

        # -- 3. next population (only when the window continues)
        stride = jnp.uint32(plan.fresh_stride)
        sarange = jnp.arange(S, dtype=jnp.int32)
        op_code = {"occ": 0, "clause": 1, "rate": 2, "horizon": 3}
        menu = jnp.asarray([op_code[o] for o in plan.ops], jnp.int32)
        # meta draws consumed per op (parent choice + op choice + the
        # op's own draws — Explorer._mutate's exact schedule)
        adv_of = jnp.asarray([4, 3, 4, 3], jnp.int32)
        n_sched = max(1, len(plan.sched_rows))
        n_tog = max(1, len(plan.tog_bits))
        n_rateops = max(1, len(plan.rate_rows))
        sched_rows = jnp.asarray(plan.sched_rows or (0,), jnp.int32)
        tog_bits = jnp.asarray(plan.tog_bits or (0,), jnp.int32)
        rate_rows = jnp.asarray(plan.rate_rows or (0,), jnp.int32)
        scale_menu = jnp.asarray([0.25, 0.5, 1.0], jnp.float32)
        full_h = jnp.int32(plan.full_h)
        occ_cols = jnp.arange(n_occ, dtype=jnp.int32)
        rate_cols = jnp.arange(n_rate, dtype=jnp.int32)

        def fresh_hash(seed):
            return tpun.genome_hash64(
                seed, jnp.int32(0), jnp.zeros((n_occ,), jnp.int32),
                jnp.ones((n_rate,), jnp.float32), jnp.int32(0),
            )

        def build_mixed(c0, nf0, sh1, sh2, sn):
            nF, nM, nS_ = plan.n_fresh, plan.n_mut, plan.n_swarm
            seeds = jnp.zeros((A,), jnp.uint32)
            offs = jnp.zeros((A,), jnp.int32)
            occs = jnp.zeros((A, n_occ), jnp.int32)
            rates = jnp.ones((A, n_rate), jnp.float32)
            hs = jnp.zeros((A,), jnp.int32)
            origins = jnp.zeros((A,), jnp.int32)
            # fresh block: sequential seeds, NO meta draws
            if nF:
                seeds = seeds.at[:nF].set(
                    nf0 + stride * jnp.arange(nF, dtype=jnp.uint32)
                )
            nf = nf0 + stride * jnp.uint32(nF)

            def mut_body(i, carry):
                (c, nf, sh1, sh2, sn,
                 seeds, offs, occs, rates, hs, origins) = carry
                d0 = prng.bits(meta_key, META_SITE_DRAW, c)
                pidx = jnp.clip(
                    (d0 % jnp.maximum(ring_n, 1).astype(jnp.uint32))
                    .astype(jnp.int32),
                    0, K - 1,
                )
                p_seed = ring_seed[pidx]
                p_off = ring_off[pidx]
                p_occ = ring_occ[pidx]
                p_rate = ring_rate[pidx]
                p_h = ring_h[pidx]
                d1 = prng.bits(meta_key, META_SITE_DRAW, c + 1)
                op = menu[
                    (d1 % jnp.uint32(len(plan.ops))).astype(jnp.int32)
                ]
                d2 = prng.bits(meta_key, META_SITE_DRAW, c + 2)
                d3 = prng.bits(meta_key, META_SITE_DRAW, c + 3)
                # occ: flip window bit k of one schedule clause's row
                occ_row = sched_rows[
                    (d2 % jnp.uint32(n_sched)).astype(jnp.int32)
                ]
                k = (d3 % jnp.uint32(10)).astype(jnp.int32)
                m_occ = jnp.where(
                    occ_cols == occ_row, p_occ ^ (jnp.int32(1) << k),
                    p_occ,
                )
                # clause: toggle one togglable clause's disable bit
                m_off = p_off ^ tog_bits[
                    (d2 % jnp.uint32(n_tog)).astype(jnp.int32)
                ]
                # rate: set one message clause's scale from the menu
                rate_row = rate_rows[
                    (d2 % jnp.uint32(n_rateops)).astype(jnp.int32)
                ]
                sc = scale_menu[(d3 % jnp.uint32(3)).astype(jnp.int32)]
                m_rate = jnp.where(rate_cols == rate_row, sc, p_rate)
                # horizon: bisect toward the prefix, or restore full
                h_eff = jnp.where(p_h == 0, full_h, p_h)
                alt = jnp.maximum(h_eff // 2, full_h // 8)
                m_h = jnp.where(
                    (d2 % jnp.uint32(2)) == jnp.uint32(0),
                    jnp.int32(0), alt,
                )
                cand_occ = jnp.where(op == 0, m_occ, p_occ)
                cand_off = jnp.where(op == 1, m_off, p_off)
                cand_rate = jnp.where(op == 2, m_rate, p_rate)
                cand_h = jnp.where(op == 3, m_h, p_h)
                c2 = c + adv_of[op]
                h1m, h2m = tpun.genome_hash64(
                    p_seed, cand_off, cand_occ, cand_rate, cand_h
                )
                dup = jnp.any(
                    (sarange < sn) & (sh1 == h1m) & (sh2 == h2m)
                )
                # dup -> single fresh fallback (consumes the next fresh
                # seed, no extra meta draws — the restructured host path)
                f_seed = nf
                h1f, h2f = fresh_hash(f_seed)
                seed_i = jnp.where(dup, f_seed, p_seed)
                off_i = jnp.where(dup, jnp.int32(0), cand_off)
                occ_i = jnp.where(dup, jnp.zeros_like(cand_occ), cand_occ)
                rate_i = jnp.where(
                    dup, jnp.ones_like(cand_rate), cand_rate
                )
                h_i = jnp.where(dup, jnp.int32(0), cand_h)
                org_i = jnp.where(dup, jnp.int32(0), jnp.int32(1))
                nf2 = jnp.where(dup, nf + stride, nf)
                # claim immediately: a second mutant drawing this genome
                # within THIS generation must fall back too
                sh1b = sh1.at[sn].set(
                    jnp.where(dup, h1f, h1m), mode="drop"
                )
                sh2b = sh2.at[sn].set(
                    jnp.where(dup, h2f, h2m), mode="drop"
                )
                sn2 = jnp.minimum(sn + 1, S)
                at = nF + i
                return (
                    c2, nf2, sh1b, sh2b, sn2,
                    seeds.at[at].set(seed_i),
                    offs.at[at].set(off_i),
                    occs.at[at].set(occ_i),
                    rates.at[at].set(rate_i),
                    hs.at[at].set(h_i),
                    origins.at[at].set(org_i),
                )

            (c, nf, sh1, sh2, sn,
             seeds, offs, occs, rates, hs, origins) = jax.lax.fori_loop(
                0, nM, mut_body,
                (c0, nf, sh1, sh2, sn,
                 seeds, offs, occs, rates, hs, origins),
            )
            # swarm groups: one coin per togglable clause per group,
            # statically unrolled (group layout is plan arithmetic)
            base = nF + nM
            for start in range(0, nS_, plan.swarm_group):
                gsz = min(plan.swarm_group, nS_ - start)
                off_g = jnp.int32(0)
                for b in plan.tog_bits:
                    coin = (
                        prng.bits(meta_key, META_SITE_DRAW, c)
                        % jnp.uint32(COIN_DENOM)
                    ) < jnp.uint32(COIN_DENOM // 2)
                    off_g = jnp.where(coin, off_g | jnp.int32(b), off_g)
                    c = c + jnp.int32(1)
                p0 = base + start
                seeds = seeds.at[p0:p0 + gsz].set(
                    nf + stride * jnp.arange(gsz, dtype=jnp.uint32)
                )
                offs = offs.at[p0:p0 + gsz].set(off_g)
                origins = origins.at[p0:p0 + gsz].set(jnp.int32(2))
                nf = nf + stride * jnp.uint32(gsz)
            # claim fresh + swarm genomes (mutants claimed in-loop):
            # exactly one append per pop candidate, so seen_n tracks the
            # host len(_seen) — fresh/swarm seeds are brand-new, so each
            # append is genuinely a new genome
            claim = list(range(nF)) + list(range(base, A))
            if claim:
                ci = jnp.asarray(claim, jnp.int32)
                hh1, hh2 = tpun.genome_hash64(
                    seeds[ci], offs[ci], occs[ci], rates[ci], hs[ci]
                )
                slots = sn + jnp.arange(len(claim), dtype=jnp.int32)
                sh1 = sh1.at[slots].set(hh1, mode="drop")
                sh2 = sh2.at[slots].set(hh2, mode="drop")
                sn = jnp.minimum(sn + len(claim), S)
            return (seeds, offs, occs, rates, hs, origins,
                    c, nf, sh1, sh2, sn)

        def build_fresh(c0, nf0, sh1, sh2, sn):
            # empty ring (host: `not parents`): ALL fresh, no meta draws
            seeds = nf0 + stride * jnp.arange(A, dtype=jnp.uint32)
            offs = jnp.zeros((A,), jnp.int32)
            occs = jnp.zeros((A, n_occ), jnp.int32)
            rates = jnp.ones((A, n_rate), jnp.float32)
            hs = jnp.zeros((A,), jnp.int32)
            origins = jnp.zeros((A,), jnp.int32)
            h1a, h2a = tpun.genome_hash64(seeds, offs, occs, rates, hs)
            slots = sn + jnp.arange(A, dtype=jnp.int32)
            return (
                seeds, offs, occs, rates, hs, origins, c0,
                nf0 + stride * jnp.uint32(A),
                sh1.at[slots].set(h1a, mode="drop"),
                sh2.at[slots].set(h2a, mode="drop"),
                jnp.minimum(sn + A, S),
            )

        def next_gen(_):
            (seeds_new, off_new, occ_new, rate_new, h_new, origin_new,
             c_next, nf_next, sh1n, sh2n, sn_next) = jax.lax.cond(
                ring_n > 0, build_mixed, build_fresh,
                dl.counter, dl.next_fresh,
                dl.seen_h1, dl.seen_h2, dl.seen_n,
            )
            h_ep, h_of = tpun.genome_ctl_rows(h_new, plan.full_h)
            queue2 = RefillQueue(
                seeds=seeds_new, off=off_new, occ=occ_new,
                rate_scale=rate_new, h_epoch=h_ep, h_off=h_of,
            )
            head_ctl = TriageCtl(
                off=off_new[:L], occ=occ_new[:L],
                rate_scale=rate_new[:L],
                h_epoch=h_ep[:L], h_off=h_of[:L],
            )
            # whole-state respawn: at a boundary EVERY lane re-inits on
            # the new head admissions (no masked merge — the refill path
            # handles partial retirement; a boundary is total)
            fresh = self._init(seeds_new[:L], head_ctl)
            zi = functools.partial(jnp.zeros, dtype=jnp.int32)
            rf2 = rf._replace(
                # step_cap, iters and busy carry over (cumulative
                # occupancy accounting across the whole window)
                cursor=jnp.int32(L),
                admitted=jnp.arange(L, dtype=jnp.int32),
                retired=jnp.full((A,), -1, jnp.int32),
                violated=jnp.zeros((A,), jnp.bool_),
                deadlocked=jnp.zeros((A,), jnp.bool_),
                violation_at=jnp.full((A,), INF_US, jnp.int32),
                violation_epoch=zi((A,)),
                violation_step=jnp.full((A,), -1, jnp.int32),
                steps=zi((A,)),
                events=zi((A,)),
                overflow=zi((A,)),
                dead_drops=zi((A,)),
                nonmember_drops=zi((A,)),
                unsynced_loss=zi((A,)),
                clock=zi((A,)),
                epoch=zi((A,)),
                fires=zi((A, len(FIRE_KINDS))),
                occ_fired=(
                    None if rf.occ_fired is None
                    else jnp.zeros((A, n_occ), jnp.uint32)
                ),
                cov_bitmap=jnp.zeros((A, COV_WORDS), jnp.uint32),
                cov_hiwater=zi((A,)),
                cov_transitions=zi((A,)),
            )
            loop2 = folded_loop._replace(
                counter=c_next, next_fresh=nf_next,
                seen_h1=sh1n, seen_h2=sh2n, seen_n=sn_next,
                gen_h_raw=h_new, gen_origin=origin_new,
            )
            return fresh._replace(queue=queue2, refill=rf2, loop=loop2)

        def window_done(_):
            return ns._replace(loop=folded_loop)

        return jax.lax.cond(
            gens_done < dl.target_gens, next_gen, window_done, None
        )

    def init_refill(
        self, seeds, lanes: int, ctl=None,
        step_cap: int = 100_000,
    ) -> SimState:
        """Build a refill-mode state: `lanes` device lanes fed from a
        device-resident queue of ALL `seeds` (one admission per seed).

        `ctl` (triage mode) is an [A]-row TriageCtl giving EVERY
        admission its own clause/occurrence/rate/horizon genome — the
        shape `triage.build_ctl` / `explore.ctl_for` already produce.
        Admissions 0..L-1 start resident (lane order == admission
        order); the rest admit in retirement order. `step_cap` is the
        per-admission step budget — the chunked path's max_steps, and
        the truncation semantics are identical. See run_refill."""
        seeds = jnp.asarray(seeds, jnp.uint32)
        if seeds.ndim != 1 or seeds.shape[0] == 0:
            raise ValueError("init_refill needs a non-empty 1-D seed array")
        A = int(seeds.shape[0])
        L = max(1, min(int(lanes), A))
        if ctl is not None and not self.triage:
            raise ValueError(
                "a refill ctl queue requires BatchedSim(..., triage=True)"
            )
        if self.triage and ctl is None:
            ctl = default_ctl(A, self.config.horizon_us)
        head_ctl = None
        if self.triage:
            if int(ctl.off.shape[0]) != A:
                raise ValueError(
                    f"refill ctl has {int(ctl.off.shape[0])} rows for "
                    f"{A} admissions — one genome per admission"
                )
            head_ctl = jax.tree_util.tree_map(lambda x: x[:L], ctl)
        state = (
            self.init(seeds[:L]) if head_ctl is None
            else self.init(seeds[:L], head_ctl)
        )
        self.dispatch_count += 1
        # jnp.array (COPY), never asarray: the queue rides the donated
        # sweep carry, so an aliased caller array would be DELETED by the
        # first segment's donation — a caller must be able to reuse its
        # seed/ctl arrays (e.g. to run the same queue sharded and
        # unsharded for a bit-identity check)
        queue = RefillQueue(
            seeds=jnp.array(seeds, jnp.uint32),
            off=None if ctl is None else jnp.array(ctl.off, jnp.int32),
            occ=None if ctl is None else jnp.array(ctl.occ, jnp.int32),
            rate_scale=(
                None if ctl is None
                else jnp.array(ctl.rate_scale, jnp.float32)
            ),
            h_epoch=(
                None if ctl is None else jnp.array(ctl.h_epoch, jnp.int32)
            ),
            h_off=(
                None if ctl is None else jnp.array(ctl.h_off, jnp.int32)
            ),
        )
        zi = functools.partial(jnp.zeros, dtype=jnp.int32)
        if step_cap <= 0:
            raise ValueError(f"step_cap must be positive, got {step_cap}")
        log = RefillLog(
            cursor=jnp.int32(L),
            admitted=jnp.arange(L, dtype=jnp.int32),
            step_cap=jnp.int32(step_cap),
            iters=jnp.int32(0),
            busy=zi((L,)),
            retired=jnp.full((A,), -1, jnp.int32),
            violated=jnp.zeros((A,), jnp.bool_),
            deadlocked=jnp.zeros((A,), jnp.bool_),
            violation_at=jnp.full((A,), INF_US, jnp.int32),
            violation_epoch=zi((A,)),
            violation_step=jnp.full((A,), -1, jnp.int32),
            steps=zi((A,)),
            events=zi((A,)),
            overflow=zi((A,)),
            dead_drops=zi((A,)),
            nonmember_drops=zi((A,)),
            unsynced_loss=zi((A,)),
            clock=zi((A,)),
            epoch=zi((A,)),
            fires=zi((A, len(FIRE_KINDS))),
            occ_fired=(
                jnp.zeros((A, len(OCC_CLAUSES)), jnp.uint32)
                if self._occ_track else None
            ),
            cov_bitmap=(
                jnp.zeros((A, COV_WORDS), jnp.uint32)
                if self.coverage else None
            ),
            cov_hiwater=zi((A,)) if self.coverage else None,
            cov_transitions=zi((A,)) if self.coverage else None,
        )
        return state._replace(queue=queue, refill=log)

    def run_refill(
        self, seeds, lanes: int, max_steps: int = 100_000,
        dispatch_steps: int = DEFAULT_DISPATCH_STEPS, ctl=None,
        total_steps: Optional[int] = None,
    ) -> SimState:
        """Run ALL `seeds` as admissions of a continuously batched sweep
        over `lanes` device lanes: a lane that violates or reaches its
        per-admission horizon retires and admits the next queued seed
        inside the jitted loop, so the chip never idles on finished
        lanes (docs/continuous_batching.md). Decode with
        `refill_results` / `summarize_refill`.

        `max_steps` is the PER-ADMISSION step budget, with exactly the
        chunked path's semantics: an admission reaching it retires
        truncated (violated as-is) inside the step, so a violation past
        max_steps is invisible to both paths alike. `total_steps` bounds
        the WHOLE sweep's loop iterations; its default (max_steps * A)
        can never bind — even fully serialized admissions fit — and the
        speculative early-stop exits the segment loop as soon as the
        queue drains, so the generous bound costs at most one no-op
        segment."""
        state = self.init_refill(seeds, lanes, ctl, step_cap=max_steps)
        A = int(state.queue.seeds.shape[0])
        if total_steps is None:
            total_steps = int(max_steps) * A
        return self.run_state(state, total_steps, dispatch_steps)

    def init_devloop(
        self, seeds, lanes: int, ctl, window: int,
        step_cap: int = 100_000,
        meta_seed: int = 0, meta_counter: int = 0, next_fresh: int = 0,
        target_gens: Optional[int] = None,
        gen_h_raw=None, gen_origin=None,
        ring: Optional[dict] = None, union=None,
        seen: Optional[dict] = None,
    ) -> SimState:
        """Build a device-loop state: a refill sweep whose generation
        boundary — fold, rank, mutate, respawn — runs IN-JIT, so a
        window of up to `window` generations is one dispatch chain with
        zero host sync (r19, docs/explore.md).

        `seeds`/`ctl` are generation 0's population, exactly as the host
        `Explorer._population` built it (the host runs the first
        population itself so both faces share the same entry point);
        `meta_seed`/`meta_counter`/`next_fresh` resume the MetaRng
        cursor at the point the host left it. `gen_h_raw`/`gen_origin`
        carry generation 0's raw genome horizons and origin codes (the
        ctl encode is lossy: genome horizon 0 encodes as full horizon).
        `ring`/`union`/`seen` upload the explorer's current corpus
        top-K, coverage union, and genome-hash dedup set — all optional
        (a cold start begins empty). `window` (G) is a SHAPE: the
        archive capacity and jit cache key; `target_gens` <= G lets a
        final partial window reuse the compiled program."""
        import numpy as np

        plan = self.devloop
        if plan is None:
            raise ValueError(
                "init_devloop needs BatchedSim(..., devloop=plan)"
            )
        if ctl is None:
            raise ValueError("init_devloop requires a ctl queue (triage)")
        seeds = jnp.asarray(seeds, jnp.uint32)
        A = plan.pop
        if int(seeds.shape[0]) != A:
            raise ValueError(
                f"devloop population is {A} admissions per generation, "
                f"got {int(seeds.shape[0])} seeds"
            )
        G = int(window)
        if G < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        tg = G if target_gens is None else int(target_gens)
        if not 1 <= tg <= G:
            raise ValueError(
                f"target_gens must be in [1, {G}], got {target_gens}"
            )
        K, S = plan.top_k, plan.seen_cap
        n_occ = len(OCC_CLAUSES)
        n_rate = len(RATE_CLAUSES)
        state = self.init_refill(seeds, lanes, ctl, step_cap=step_cap)

        # -- ring upload (the host corpus's current top-K, sorted)
        ring = dict(ring or {})
        rn = int(ring.get("n", 0))
        if not 0 <= rn <= K:
            raise ValueError(f"ring has {rn} rows, capacity {K}")

        def buf(key, shape, dtype, fill=0):
            src = ring.get(key)
            out = np.full(shape, fill, dtype=dtype)
            if src is not None and rn:
                out[:rn] = np.asarray(src, dtype=dtype)[:rn]
            return jnp.array(out)

        ring_bits = buf("bits", (K,), np.int32)
        ring_seed = buf("seed", (K,), np.uint32)
        ring_off = buf("off", (K,), np.int32)
        ring_occ = buf("occ", (K, n_occ), np.int32)
        ring_rate = buf("rate", (K, n_rate), np.float32, fill=1.0)
        ring_h = buf("h", (K,), np.int32)

        # -- dedup-table upload + headroom: the window appends at most
        # one row per candidate, so a full window must fit
        seen = dict(seen or {})
        sn = int(seen.get("n", 0))
        if sn + G * A > S:
            raise ValueError(
                f"seen table has {sn} rows + window appends {G * A} "
                f"> capacity {S}; raise seen_cap or shrink the window"
            )
        s1 = np.zeros((S,), np.uint32)
        s2 = np.zeros((S,), np.uint32)
        if sn:
            s1[:sn] = np.asarray(seen["h1"], np.uint32)[:sn]
            s2[:sn] = np.asarray(seen["h2"], np.uint32)[:sn]

        un = (
            np.zeros((COV_WORDS,), np.uint32) if union is None
            else np.asarray(union, np.uint32)
        )
        if un.shape != (COV_WORDS,):
            raise ValueError(
                f"union bitmap must be [{COV_WORDS}] u32, got {un.shape}"
            )
        gh = (
            np.zeros((A,), np.int32) if gen_h_raw is None
            else np.asarray(gen_h_raw, np.int32)
        )
        go = (
            np.zeros((A,), np.int32) if gen_origin is None
            else np.asarray(gen_origin, np.int32)
        )
        zi = functools.partial(jnp.zeros, dtype=jnp.int32)
        # jnp.array COPIES throughout (donation safety — the loop carry
        # is donated every segment, same rule as the refill queue)
        loop = DevLoop(
            meta_key=jnp.uint32(key_from_seed(int(meta_seed))),
            counter=jnp.int32(int(meta_counter)),
            next_fresh=jnp.uint32(int(next_fresh) & 0xFFFFFFFF),
            gens_done=jnp.int32(0),
            target_gens=jnp.int32(tg),
            accepts=jnp.int32(0),
            ring_n=jnp.int32(rn),
            ring_bits=ring_bits,
            ring_seed=ring_seed,
            ring_off=ring_off,
            ring_occ=ring_occ,
            ring_rate=ring_rate,
            ring_h=ring_h,
            union=jnp.array(un),
            seen_h1=jnp.array(s1),
            seen_h2=jnp.array(s2),
            seen_n=jnp.int32(sn),
            gen_h_raw=jnp.array(gh),
            gen_origin=jnp.array(go),
            arch_seed=jnp.zeros((G, A), jnp.uint32),
            arch_off=zi((G, A)),
            arch_occ=zi((G, A, n_occ)),
            arch_rate=jnp.ones((G, A, n_rate), jnp.float32),
            arch_h=zi((G, A)),
            arch_origin=zi((G, A)),
            arch_violated=jnp.zeros((G, A), jnp.bool_),
            arch_bitmap=jnp.zeros((G, A, COV_WORDS), jnp.uint32),
            arch_hiwater=zi((G, A)),
            arch_transitions=zi((G, A)),
        )
        return state._replace(loop=loop)

    def run_devloop(
        self, state: SimState,
        dispatch_steps: int = DEFAULT_DISPATCH_STEPS,
        total_steps: Optional[int] = None,
    ) -> SimState:
        """Run a device-loop window to completion: segments of the SAME
        jitted step as every other mode, with the generation boundary
        firing inside the step whenever a generation fully retires. The
        default `total_steps` bound (step_cap * A * G) can never bind —
        even fully serialized admissions across every generation fit —
        and the speculative early-stop exits once the final generation
        drains, so the generous bound costs at most one no-op segment.
        Decode ONCE with `devloop_results` — that single transfer is the
        window's only host sync."""
        if state.loop is None:
            raise ValueError("run_devloop needs an init_devloop state")
        A = int(state.queue.seeds.shape[0])
        G = int(state.loop.arch_seed.shape[0])
        if total_steps is None:
            total_steps = int(state.refill.step_cap) * A * G
        return self.run_state(state, total_steps, dispatch_steps)

    # --------------------------------------------------- sharded refill

    def init_refill_sharded(
        self, seeds, lanes: int, mesh: jax.sharding.Mesh, ctl=None,
        step_cap: int = 100_000,
    ) -> SimState:
        """Build the MULTI-CHIP refill state: the admission list is
        partitioned into one contiguous, equal-length sub-queue per mesh
        device (tail-padded with repeats of the first seed; the pad rows
        run normally and are stripped by `refill_results_sharded`), each
        device gets its own `lanes`-lane engine plus its own RefillLog
        result buffers and cursor, and every state leaf gains a leading
        device axis [D, ...] sharded one row per device.

        Device d's block IS the single-device refill state of sub-queue
        d — same shapes, same init draws — which is what makes the
        sharded sweep's per-admission rows bit-identical to the 1-device
        refill path (and hence to the chunked path) by construction:
        concatenating per-device rows in device order restores global
        admission (= seed) order."""
        import numpy as np

        seeds = np.asarray(seeds, np.uint32)
        if seeds.ndim != 1 or seeds.shape[0] == 0:
            raise ValueError(
                "init_refill_sharded needs a non-empty 1-D seed array"
            )
        D = int(mesh.devices.size)
        A = int(seeds.shape[0])
        Ad = -(-A // D)  # per-device sub-queue length (ceil)
        pad = Ad * D - A
        if pad:
            seeds_in = np.concatenate([seeds, np.repeat(seeds[:1], pad)])
        else:
            seeds_in = seeds
        ctl_in = ctl
        if ctl is not None and pad:
            ctl_in = jax.tree_util.tree_map(
                lambda x: jnp.concatenate(
                    [jnp.asarray(x), jnp.repeat(
                        jnp.asarray(x)[:1], pad, axis=0
                    )]
                ),
                ctl,
            )
        states = []
        for d in range(D):
            sub = seeds_in[d * Ad : (d + 1) * Ad]
            sub_ctl = (
                None if ctl_in is None
                else jax.tree_util.tree_map(
                    lambda x: x[d * Ad : (d + 1) * Ad], ctl_in
                )
            )
            states.append(
                self.init_refill(sub, lanes, sub_ctl, step_cap=step_cap)
            )
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *states
        )
        sh = jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec(mesh.axis_names[0])
        )
        # ONE device_put over the whole pytree (see shard_state)
        stacked = jax.device_put(
            stacked, jax.tree_util.tree_map(lambda _: sh, stacked)
        )
        self.dispatch_count += 1
        return stacked

    def _sharded_segment(self, mesh: jax.sharding.Mesh, n_steps: int):
        """The compiled multi-chip sweep segment: shard_map over the
        leading device axis, each device running the REAL per-device
        refill segment — `split_state`, the donated while_loop over
        `_step_split` (its `lax.cond` retire-and-admit branch stays a
        real cond, not a vmap-degraded select), per-device early exit
        when the device's own queue drains. ZERO cross-device
        collectives inside the step or the segment: devices touch only
        their own sub-queue, lanes, and result buffers; the harvest /
        early-stop gathers happen at segment end only, on the host side
        (run_state_sharded / refill_results_sharded). The analysis
        lane-independence rule walks this exact program and allowlists
        collectives by exact primitive name (none in-tree)."""
        key = (mesh, int(n_steps))
        fn = self._sharded_cache.get(key)
        if fn is not None:
            return fn
        from jax.experimental.shard_map import shard_map

        spec = jax.sharding.PartitionSpec(mesh.axis_names[0])

        def seg(stacked: SimState) -> SimState:
            # each device sees its [1, ...] block: strip the device axis,
            # run the ordinary refill segment, put the axis back
            st = jax.tree_util.tree_map(lambda x: x[0], stacked)
            hot, cold, const = split_state(st)

            def cond(carry):
                h, _c, i = carry
                return jnp.logical_and(i < n_steps, jnp.any(~h.done))

            def body(carry):
                h, c, i = carry
                h2, c2, _ = self._step_split(h, c, const)
                return h2, c2, i + 1

            h, c, _ = jax.lax.while_loop(
                cond, body, (hot, cold, jnp.int32(0))
            )
            out = merge_state(h, c, const)
            return jax.tree_util.tree_map(lambda x: x[None], out)

        fn = jax.jit(
            shard_map(
                seg, mesh=mesh, in_specs=(spec,), out_specs=spec,
                check_rep=False,
            ),
            donate_argnums=(0,),
        )
        self._sharded_cache[key] = fn
        return fn

    def run_state_sharded(
        self, state: SimState, mesh: jax.sharding.Mesh, max_steps: int,
        dispatch_steps: int = DEFAULT_DISPATCH_STEPS,
    ) -> SimState:
        """run_state's segment loop over the shard_map'd segment program:
        same speculative early-stop (the all-done reduction over the
        sharded `done` plane is the one cross-device gather, dispatched
        at segment boundaries only), same donation discipline — ONE
        loop, parameterized by the segment runner."""
        return self.run_state(
            state, max_steps, dispatch_steps,
            segment=lambda st, n: self._sharded_segment(mesh, n)(st),
        )

    def run_refill_sharded(
        self, seeds, lanes: int, mesh: jax.sharding.Mesh,
        max_steps: int = 100_000, dispatch_steps: int = DEFAULT_DISPATCH_STEPS, ctl=None,
        total_steps: Optional[int] = None,
    ) -> SimState:
        """The multi-chip continuously batched sweep: ALL `seeds` run as
        admissions of D independent per-device refill engines (`lanes`
        lanes EACH), one shard_map'd program per segment. Decode with
        `refill_results_sharded(state, admissions=len(seeds))`.

        `max_steps` keeps the per-admission chunked-truncation semantics
        of run_refill; `total_steps` bounds each DEVICE's segment-loop
        iterations (default max_steps * per-device queue length — never
        binding). Per-admission rows are bit-identical to run_refill's
        and to the chunked path's for any fixed admission order (the
        multichip matrix tests pin this)."""
        state = self.init_refill_sharded(
            seeds, lanes, mesh, ctl, step_cap=max_steps
        )
        Ad = int(state.queue.seeds.shape[1])
        if total_steps is None:
            total_steps = int(max_steps) * Ad
        return self.run_state_sharded(state, mesh, total_steps, dispatch_steps)

    # ------------------------------------------------------------------ run

    # donate_argnums=1: the carry state's buffers are DONATED to each sweep
    # segment — XLA writes the output state into the input's HBM instead of
    # allocating a fresh ~100 MB pytree per dispatch and leaving the old one
    # live until the host drops its reference. Inside the while_loop XLA
    # already aliases the loop carry; donation extends that aliasing across
    # the chunked-dispatch boundary, so a long sweep's peak HBM is ONE state
    # (not two) and the inter-segment allocate/copy round-trip disappears.
    # Safe because `run` immediately rebinds `state` to the result: the
    # donated input is never read again (jax invalidates it loudly if a
    # future caller tries).
    @functools.partial(
        jax.jit, static_argnums=(0, 2), donate_argnums=(1,)
    )
    def _run(self, state: SimState, max_steps: int) -> SimState:
        # hot/cold/const split (r8): the while_loop carries only the hot +
        # cold pytrees; ConstState (key0, ctl, skew_ppm) rides as a
        # loop-invariant operand, so the fused step stops rewriting those
        # bytes every iteration and the donated segment stops rotating
        # them through fresh buffers at every dispatch boundary.
        hot, cold, const = split_state(state)

        def cond(carry):
            h, _c, i = carry
            return jnp.logical_and(i < max_steps, jnp.any(~h.done))

        def body(carry):
            h, c, i = carry
            h2, c2, _ = self._step_split(h, c, const)
            return h2, c2, i + 1

        h, c, _ = jax.lax.while_loop(cond, body, (hot, cold, jnp.int32(0)))
        return merge_state(h, c, const)

    def run(
        self, seeds, max_steps: int = 100_000, dispatch_steps: int = DEFAULT_DISPATCH_STEPS,
        mesh: Optional[jax.sharding.Mesh] = None, ctl=None,
    ) -> SimState:
        """Run lanes until every lane is done (or max_steps).

        With `mesh`, the lane axis is sharded over the mesh's first axis —
        the production multi-device sweep path (the reference uses ALL
        available parallel hardware for a seed sweep, one thread per seed,
        runtime/builder.rs:118-136; here it is one lane shard per chip,
        zero cross-device traffic). Results are bit-identical to the
        unsharded run: no draw folds the lane index, so a seed's trajectory
        does not depend on which device its lane landed on.

        The while_loop is dispatched in chunks of `dispatch_steps`: a long
        horizon at high lane counts would otherwise be ONE device kernel
        running for minutes, which remote-tunnel TPU runtimes have been
        observed to kill (worker crash at ~70s on a 32k-lane, 24k-step
        dispatch). Chunking bounds each kernel's runtime and lets the host
        stop soon after every lane is done. At most two programs compile
        (chunk size + final tail).

        The early-stop check is SPECULATIVE (r6): segment k+1 is enqueued
        before the host reads segment k's all-done reduction, so segments
        run back-to-back with no host round-trip between them (the r5
        loop blocked on `done.all()` before each dispatch — one tunnel
        RTT of device idle per segment). When segment k did finish every
        lane, the speculatively-enqueued k+1 is a device no-op (the
        while_loop's cond is false on entry) and the loop exits one
        dispatch later than strictly needed; results are bit-identical
        either way.
        """
        if dispatch_steps <= 0:
            raise ValueError(f"dispatch_steps must be positive, got {dispatch_steps}")
        state = self.init(seeds) if ctl is None else self.init(seeds, ctl)
        self.dispatch_count += 1
        if mesh is not None:
            L = state.clock.shape[0]
            n_dev = int(mesh.devices.size)
            if L % n_dev:
                raise ValueError(
                    f"lane count {L} not divisible by mesh size {n_dev}; "
                    "pad the seed batch (run_batch does this automatically)"
                )
            state = self.shard_state(state, mesh, lane_axis=mesh.axis_names[0])
            self.dispatch_count += 1  # the single whole-pytree device_put
        return self.run_state(state, max_steps, dispatch_steps)

    def run_state(
        self, state: SimState, max_steps: int, dispatch_steps: int = DEFAULT_DISPATCH_STEPS,
        segment=None,
    ) -> SimState:
        """run()'s chunked segment loop on a PRE-BUILT state (the shared
        tail of run / run_refill / run_refill_sharded): speculative
        early-stop, donated segments, dispatch accounting — see run()'s
        docstring. `segment(state, n)` overrides the donated `_run`
        program (run_state_sharded passes the shard_map'd segment), so
        the loop logic exists exactly once."""
        if dispatch_steps <= 0:
            raise ValueError(
                f"dispatch_steps must be positive, got {dispatch_steps}"
            )
        run_segment = segment or (lambda st, n: self._run(st, n))
        remaining = max_steps
        alive = None
        while remaining > 0:
            if alive is not None:
                # enqueue the previous segment's all-done reduction FIRST
                # (tiny scalar; reads state.done before the donation
                # below — PJRT keeps the buffer alive for the in-flight
                # reader, so donation stays safe)
                alive = self._any_alive(state)
                self.dispatch_count += 1
            n = min(dispatch_steps, remaining)
            # the segment DONATES state: the rebinding here is what makes
            # that legal — the pre-segment buffers are dead the moment
            # the segment is dispatched
            state = run_segment(state, n)
            self.dispatch_count += 1
            remaining -= n
            # block on the reduction only AFTER the next segment is in
            # flight: the early stop costs at most one no-op segment,
            # never a device-idle host round-trip
            if alive is not None and not bool(alive):
                break
            if alive is None and remaining > 0:
                alive = True  # arm the check from the second segment on
        return state

    @functools.partial(jax.jit, static_argnums=(0, 2))
    def run_steps(self, state: SimState, n_steps: int) -> SimState:
        """Fixed-step scan (benchmark-friendly: no host syncs)."""
        hot, cold, const = split_state(state)

        def body(carry, _):
            h, c = carry
            h2, c2, _ = self._step_split(h, c, const)
            return (h2, c2), None

        (h, c), _ = jax.lax.scan(body, (hot, cold), None, length=n_steps)
        return merge_state(h, c, const)

    # donated like _run: run_traced hands the freshly-built init state in
    # and never touches it again (the [T, 1, ...] record stream is a new
    # allocation either way)
    @functools.partial(
        jax.jit, static_argnums=(0, 2), donate_argnums=(1,)
    )
    def _run_traced(self, state: SimState, n_steps: int):
        hot, cold, const = split_state(state)

        def body(carry, _):
            h, c = carry
            h2, c2, rec = self._step_split(h, c, const)
            return (h2, c2), rec

        (h, c), recs = jax.lax.scan(body, (hot, cold), None, length=n_steps)
        return merge_state(h, c, const), recs

    def run_traced(self, seed: int, max_steps: int = 20_000, ctl=None):
        """Re-run ONE seed with full event capture (the violation microscope).

        Returns (final_state, TraceRecord with [T, 1, ...] leaves). Use
        trace.extract_trace to turn the records into readable events. The
        trajectory is bit-identical to the same seed inside any batch: the
        step function is the same jitted program and all randomness is
        derived from the lane seed, never from lane position. `ctl` (triage
        mode) traces a SHRUNK candidate — e.g. a repro bundle's — with the
        suppressed faults absent from the record stream.
        """
        seeds = jnp.asarray([seed], jnp.uint32)
        state = self.init(seeds) if ctl is None else self.init(seeds, ctl)
        self.dispatch_count += 2  # init + the traced scan below
        return self._run_traced(state, max_steps)

    # ------------------------------------------------------------ sharding

    def shard_state(
        self, state: SimState, mesh: jax.sharding.Mesh, lane_axis: str = "seeds",
        node_axis: Optional[str] = None,
    ) -> SimState:
        """Shard lane (and optionally node) axes over a device mesh.

        Lanes are independent, so lane-sharding needs no collectives at all —
        the scaling-book data-parallel recipe. Node-sharding additionally
        splits per-node state (dim 1 of every [L, N, ...] leaf, which in the
        dest-major layout includes the message pool); XLA inserts gathers
        for the cross-node routing. The straggler side pool's dim 1 is the
        candidate axis, not the node axis — it stays lane-sharded only.

        WHEN TO USE WHICH (measured, benches/node_sharding.py + the table
        in docs/perf_notes.md): shard the LANE axis for throughput — on an
        8-device mesh the 2-D layouts LOSE at every N measured (12x
        slower at N = 8, still behind at N = 32): node sharding pays
        per-step cross-device gathers for message routing, lane sharding
        pays nothing. Pass `node_axis` only when a single device cannot
        HOLD the per-node state (very large N x state: a memory-capacity
        lever, not a speed lever).
        """
        P = jax.sharding.PartitionSpec
        N = self.spec.n_nodes

        def sharding_for(x, node_ok=True):
            if x.ndim == 0:
                return jax.sharding.NamedSharding(mesh, P())
            axes: list = [lane_axis] + [None] * (x.ndim - 1)
            if (
                node_axis is not None and node_ok and x.ndim >= 2
                and x.shape[1] == N
            ):
                axes[1] = node_axis
            return jax.sharding.NamedSharding(mesh, P(*axes))

        # ONE device_put over the whole pytree (a per-leaf loop dispatches
        # ~40 transfers; each pays the tunnel's dispatch latency)
        strag = state.strag
        shardings = jax.tree_util.tree_map(
            sharding_for, state._replace(strag=None)
        )
        rest = jax.device_put(state._replace(strag=None), shardings)
        if strag is not None:
            strag = jax.device_put(
                strag,
                jax.tree_util.tree_map(
                    functools.partial(sharding_for, node_ok=False), strag
                ),
            )
        return rest._replace(strag=strag)


def abs_time_us(state: SimState):
    """Absolute virtual time per lane as int64 numpy (epoch * REBASE + off)."""
    import numpy as np

    return np.asarray(state.epoch, np.int64) * REBASE_US + np.asarray(
        state.clock, np.int64
    )


def _sum64(x: jnp.ndarray, axis=0):
    """Exact lane sum of a non-negative i32 tensor WITHOUT int64 (x64 mode
    is off): split into 16-bit halves, sum each in u32 — hi * 2^16 + lo is
    recombined host-side as a Python int. Both partials stay below 2^32
    only for lanes <= 65536 (values < 2^31), so that bound is ENFORCED:
    a bigger batch must be summarized in chunks (run_batch already
    chunks), not allowed to wrap the u32 partials silently."""
    if x.shape[axis] > 65536:
        raise ValueError(
            f"_sum64: lane axis {x.shape[axis]} > 65536 would overflow "
            "the u32 partial sums — summarize in chunks"
        )
    xu = x.astype(jnp.uint32)
    return (
        jnp.sum(xu >> 16, axis=axis, dtype=jnp.uint32),
        jnp.sum(xu & jnp.uint32(0xFFFF), axis=axis, dtype=jnp.uint32),
    )


def _join64(hi, lo) -> int:
    import numpy as np

    return int(np.asarray(hi, np.int64) * 65536 + np.asarray(lo, np.int64))


def _summary_reduction(state: SimState) -> dict:
    """The decode-side fusion (r8): every per-summary reduction — lane
    counters, chaos fire totals, per-occurrence fire counts, coverage
    popcounts — folded into ONE jitted device program. summarize()
    previously pulled a dozen full [L, ...] tensors to the host and
    reduced them in numpy; a chunked sweep paid those transfers per chunk.
    Now the device reduces and the host reads back only scalars/rows."""
    violated = state.violated
    out = {
        "violations": jnp.sum(violated, dtype=jnp.int32),
        "deadlocked": jnp.sum(state.deadlocked, dtype=jnp.int32),
        "events64": _sum64(state.events),
        "overflow64": _sum64(state.overflow),
        "dead_drops64": _sum64(state.dead_drops),
        "nonmember_drops64": _sum64(state.nonmember_drops),
        "unsynced_loss64": _sum64(state.unsynced_loss),
        "steps64": _sum64(state.steps),
        "epoch64": _sum64(state.epoch),
        "clock64": _sum64(state.clock),
        # earliest first-violation step over violating lanes: the triage
        # shrinker's run-to-step truncation anchor (INT32_MAX = none)
        "first_violation_step": jnp.min(
            jnp.where(violated, state.violation_step, jnp.int32(2**31 - 1))
        ),
        "fires64": _sum64(state.fires, axis=0),  # ([K], [K])
    }
    if state.occ_fired is not None:
        # per-(clause row, occurrence bit) lane counts [R, 32]
        bits = (
            state.occ_fired[:, :, None]
            >> jnp.arange(32, dtype=jnp.uint32)[None, None, :]
        ) & jnp.uint32(1)
        out["occ_counts"] = bits.sum(axis=0, dtype=jnp.int32)
    if state.cov is not None:
        out["cov_union"] = jax.lax.reduce(
            state.cov.bitmap, jnp.uint32(0), jax.lax.bitwise_or, (0,)
        )  # [COV_WORDS]
        out["cov_union_bits"] = jax.lax.population_count(
            out["cov_union"]
        ).sum(dtype=jnp.int32)
        out["cov_hiwater"] = jnp.max(state.cov.hiwater)
        out["cov_transitions64"] = _sum64(state.cov.transitions)
    return out


_SUMMARY_RED = jax.jit(_summary_reduction)


def summarize(state: SimState, spec: Optional[ProtocolSpec] = None) -> dict:
    """Host-side summary of a finished batch (bug reports with repro info).

    Pass the spec to include its `lane_metrics` diagnostics — e.g. the Raft
    spec reports how many lanes saturated their fixed-capacity log (a lane
    whose log stopped appending is a lane that stopped finding bugs; that
    must be visible, not silent).

    All batch-wide reductions run on device in one fused decode program
    (`_summary_reduction`); the host pulls back only the reduced rows plus
    the [L] violation bitmap (for lane indices).
    """
    import numpy as np

    red = _SUMMARY_RED(state)
    violated = np.asarray(state.violated)
    L = int(violated.shape[0])
    steps_total = _join64(*red["steps64"])
    vt_total_us = (
        _join64(*red["epoch64"]) * REBASE_US + _join64(*red["clock64"])
    )
    out = {
        "lanes": L,
        "violations": int(red["violations"]),
        "violation_lanes": np.nonzero(violated)[0].tolist()[:32],
        "deadlocked": int(red["deadlocked"]),
        "total_events": _join64(*red["events64"]),
        "total_overflow": _join64(*red["overflow64"]),
        "total_dead_drops": _join64(*red["dead_drops64"]),
        "total_nonmember_drops": _join64(*red["nonmember_drops64"]),
        "total_unsynced_loss": _join64(*red["unsynced_loss64"]),
        "mean_steps": steps_total / L,
        "mean_virtual_secs": vt_total_us / L / 1e6,
    }
    if out["violations"]:
        out["first_violation_step"] = int(red["first_violation_step"])
    # per-fault-kind chaos fire counts (the coverage report's raw data)
    f_hi, f_lo = red["fires64"]
    f_hi, f_lo = np.asarray(f_hi, np.int64), np.asarray(f_lo, np.int64)
    for i, name in enumerate(FIRE_KINDS):
        out[f"fires_{name}"] = int(f_hi[i] * 65536 + f_lo[i])
    # per-occurrence fire counts (nemesis schedule clauses only): lanes in
    # which occurrence k of the clause applied — coverage_report renders
    # these next to the clause totals, and chunked run_batch sums them
    if state.occ_fired is not None:
        occ_counts = np.asarray(red["occ_counts"])
        for row, clause in enumerate(OCC_CLAUSES):
            for k in range(32):
                n = int(occ_counts[row, k])
                if n:
                    out[f"occfires_{clause}_k{k}"] = n
    if state.cov is not None:
        out["coverage_bits"] = int(red["cov_union_bits"])
        out["coverage_hiwater"] = int(red["cov_hiwater"])
        out["coverage_transitions"] = _join64(*red["cov_transitions64"])
    if spec is not None and spec.lane_metrics is not None:
        for name, arr in spec.lane_metrics(state.node).items():
            a = np.asarray(arr)
            if a.dtype == np.bool_:
                out[name] = int(a.sum())
            else:
                out[name] = float(a.mean())
    return out


def refill_results(state: SimState) -> dict:
    """Decode a finished refill sweep into per-ADMISSION numpy rows.

    Rows are in admission order (== the seed order handed to
    run_refill), so chunked-vs-refill comparisons are row-for-row. Each
    retired admission's row was harvested on device at its retirement
    step; admissions still mid-flight when the step budget ran out (the
    truncation case — see run_refill) are harvested here from their
    lane's final state, which is exactly what the chunked path reports
    for a lane truncated at max_steps. Also computes the sweep's lane
    OCCUPANCY: busy-lane-steps / total-lane-steps — the continuous-
    batching headline metric (benches/roofline.py reports it)."""
    import numpy as np

    rf = state.refill
    if rf is None:
        raise ValueError("refill_results needs a run_refill final state")
    if np.asarray(state.queue.seeds).ndim != 1:
        raise ValueError(
            "state has a leading device axis (run_refill_sharded) — "
            "decode it with refill_results_sharded"
        )
    # np.array (COPY), not np.asarray: the jax-array views are read-only
    # and the final-harvest loop below writes rows in place
    out = {
        f: np.array(getattr(rf, f))
        for f in (
            "retired", "violated", "deadlocked", "violation_at",
            "violation_epoch", "violation_step", "steps", "events",
            "overflow", "dead_drops", "nonmember_drops", "unsynced_loss",
            "clock", "epoch", "fires",
        )
    }
    for f in ("occ_fired", "cov_bitmap", "cov_hiwater", "cov_transitions"):
        v = getattr(rf, f)
        out[f] = None if v is None else np.array(v)
    A = out["violated"].shape[0]
    L = int(np.asarray(rf.busy).shape[0])
    # final harvest: lanes that ran out of step budget mid-admission
    done = np.asarray(state.done)
    live = ~done
    li = np.asarray(rf.admitted)[live]
    if li.size:
        pairs = {
            "violated": state.violated, "deadlocked": state.deadlocked,
            "violation_at": state.violation_at,
            "violation_epoch": state.violation_epoch,
            "violation_step": state.violation_step,
            "steps": state.steps, "events": state.events,
            "overflow": state.overflow, "dead_drops": state.dead_drops,
            "nonmember_drops": state.nonmember_drops,
            "unsynced_loss": state.unsynced_loss,
            "clock": state.clock, "epoch": state.epoch,
            "fires": state.fires,
        }
        if out["occ_fired"] is not None:
            pairs["occ_fired"] = state.occ_fired
        if out["cov_bitmap"] is not None:
            pairs["cov_bitmap"] = state.cov.bitmap
            pairs["cov_hiwater"] = state.cov.hiwater
            pairs["cov_transitions"] = state.cov.transitions
        for name, src in pairs.items():
            out[name][li] = np.asarray(src)[live]
    iters = int(np.asarray(rf.iters))
    busy = int(np.asarray(rf.busy, np.int64).sum())
    out["admissions"] = A
    out["lanes"] = L
    out["iters"] = iters
    out["busy_lane_steps"] = busy
    out["total_lane_steps"] = iters * L
    out["occupancy"] = busy / max(iters * L, 1)
    out["truncated"] = int(live.sum())
    return out


def devloop_results(state: SimState) -> dict:
    """Decode a finished device-loop window — the ONE host sync the
    window pays (r19, docs/explore.md). Returns the search cursors
    (meta counter, next_fresh, seen_n), the corpus ring + coverage
    union as upload-ready dicts (feed them straight back into
    `init_devloop` for the next window), and one dict per executed
    generation with the archived genomes and per-admission results in
    admission order — exactly what the host `Explorer._fold_part`
    replays to rebuild its corpus."""
    import numpy as np

    dl = state.loop
    if dl is None:
        raise ValueError("devloop_results needs a run_devloop final state")
    rn = int(np.asarray(dl.ring_n))
    gens_done = int(np.asarray(dl.gens_done))
    rf = state.refill
    out = {
        "gens_done": gens_done,
        "target_gens": int(np.asarray(dl.target_gens)),
        "counter": int(np.asarray(dl.counter)),
        "next_fresh": int(np.asarray(dl.next_fresh)),
        "accepts": int(np.asarray(dl.accepts)),
        "seen_n": int(np.asarray(dl.seen_n)),
        "union": np.array(dl.union),
        "ring": {
            "n": rn,
            "bits": np.array(dl.ring_bits)[:rn],
            "seed": np.array(dl.ring_seed)[:rn],
            "off": np.array(dl.ring_off)[:rn],
            "occ": np.array(dl.ring_occ)[:rn],
            "rate": np.array(dl.ring_rate)[:rn],
            "h": np.array(dl.ring_h)[:rn],
        },
        "iters": int(np.asarray(rf.iters)),
        "busy_lane_steps": int(np.asarray(rf.busy, np.int64).sum()),
    }
    arch = {
        f: np.array(getattr(dl, "arch_" + f))
        for f in (
            "seed", "off", "occ", "rate", "h", "origin", "violated",
            "bitmap", "hiwater", "transitions",
        )
    }
    out["gens"] = [
        {f: a[g] for f, a in arch.items()} for g in range(gens_done)
    ]
    return out


def refill_results_sharded(
    state: SimState, admissions: Optional[int] = None,
) -> dict:
    """Decode a finished SHARDED refill sweep (run_refill_sharded) into
    the same per-admission rows `refill_results` produces, in global
    admission (= seed) order: device d's rows are sub-queue d's rows,
    concatenated in device order and stripped of the tail pad
    (`admissions` = the original un-padded seed count).

    This is the segment-end gather the multi-chip determinism contract
    allows: the step itself never crosses devices, so each device's rows
    are bit-identical to a 1-device refill of its sub-queue, and the
    concatenation is bit-identical to the 1-device refill (and chunked)
    rows of the whole list. Occupancy comes back both aggregate and
    per-device (`per_device`): each device's busy-lane-steps over its
    OWN iteration count — the per-chip utilization the mesh_scaling
    bench and the multichip smoke assert on. `lane_steps_per_iter` is
    the aggregate busy-lane-step throughput per sweep iteration
    (busy total / max device iters): the hardware-independent scaling
    number (1 device caps at L; D devices at D * L)."""
    import numpy as np

    if state.refill is None or state.queue is None:
        raise ValueError(
            "refill_results_sharded needs a run_refill_sharded final state"
        )
    lead = np.asarray(state.queue.seeds).ndim
    if lead != 2:
        raise ValueError(
            "state has no leading device axis — use refill_results for "
            "single-device refill sweeps"
        )
    D = int(np.asarray(state.queue.seeds).shape[0])
    per = [
        refill_results(jax.tree_util.tree_map(lambda x, _d=d: x[_d], state))
        for d in range(D)
    ]
    row_fields = [
        "retired", "violated", "deadlocked", "violation_at",
        "violation_epoch", "violation_step", "steps", "events",
        "overflow", "dead_drops", "nonmember_drops", "unsynced_loss",
        "clock", "epoch",
        "fires", "occ_fired", "cov_bitmap", "cov_hiwater",
        "cov_transitions",
    ]
    out: dict = {}
    for f in row_fields:
        if per[0][f] is None:
            out[f] = None
            continue
        rows = np.concatenate([p[f] for p in per])
        out[f] = rows if admissions is None else rows[:admissions]
    A = int(out["violated"].shape[0])
    iters = [p["iters"] for p in per]
    busy = [p["busy_lane_steps"] for p in per]
    total = [p["total_lane_steps"] for p in per]
    out["admissions"] = A
    out["lanes"] = per[0]["lanes"]
    out["devices"] = D
    out["iters"] = max(iters)
    out["busy_lane_steps"] = sum(busy)
    out["total_lane_steps"] = sum(total)
    out["occupancy"] = sum(busy) / max(sum(total), 1)
    # count truncated admissions from the STRIPPED rows (a truncated
    # admission never got its retirement scatter, so its `retired` row
    # is still -1) — the per-device counts include tail-pad duplicates
    out["truncated"] = int((out["retired"] == -1).sum())
    out["per_device"] = [
        {
            "iters": iters[d],
            "busy_lane_steps": busy[d],
            "total_lane_steps": total[d],
            "occupancy": busy[d] / max(total[d], 1),
        }
        for d in range(D)
    ]
    out["lane_steps_per_iter"] = sum(busy) / max(max(iters), 1)
    return out


def summarize_refill(res: dict) -> dict:
    """summarize()'s vocabulary over refill_results rows: the same keys,
    aggregated over ADMISSIONS, so run_batch's chunk-total folding and
    the chaos-coverage report read both paths identically. (lane_metrics
    diagnostics need final node state, which a refilled lane no longer
    holds — the refill path reports the engine counters only.)"""
    import numpy as np

    A = int(res["admissions"])
    violated = res["violated"]
    steps_total = int(res["steps"].astype(np.int64).sum())
    vt_total_us = int(
        res["epoch"].astype(np.int64).sum() * REBASE_US
        + res["clock"].astype(np.int64).sum()
    )
    out = {
        "lanes": A,
        "violations": int(violated.sum()),
        "violation_lanes": np.nonzero(violated)[0].tolist()[:32],
        "deadlocked": int(res["deadlocked"].sum()),
        "total_events": int(res["events"].astype(np.int64).sum()),
        "total_overflow": int(res["overflow"].astype(np.int64).sum()),
        "total_dead_drops": int(res["dead_drops"].astype(np.int64).sum()),
        "total_nonmember_drops": int(
            res["nonmember_drops"].astype(np.int64).sum()
        ),
        "total_unsynced_loss": int(
            res["unsynced_loss"].astype(np.int64).sum()
        ),
        "mean_steps": steps_total / A,
        "mean_virtual_secs": vt_total_us / A / 1e6,
        "occupancy": round(float(res["occupancy"]), 4),
    }
    if out["violations"]:
        out["first_violation_step"] = int(
            res["violation_step"][violated].min()
        )
    fires = res["fires"].astype(np.int64).sum(axis=0)
    for i, name in enumerate(FIRE_KINDS):
        out[f"fires_{name}"] = int(fires[i])
    if res.get("occ_fired") is not None:
        bits = (
            res["occ_fired"][:, :, None]
            >> np.arange(32, dtype=np.uint32)[None, None, :]
        ) & np.uint32(1)
        occ_counts = bits.sum(axis=0)
        for row, clause in enumerate(OCC_CLAUSES):
            for k in range(32):
                n = int(occ_counts[row, k])
                if n:
                    out[f"occfires_{clause}_k{k}"] = n
    if res.get("cov_bitmap") is not None:
        union = np.bitwise_or.reduce(res["cov_bitmap"], axis=0)
        out["coverage_bits"] = int(
            np.unpackbits(union.view(np.uint8)).sum()
        )
        out["coverage_hiwater"] = int(res["cov_hiwater"].max())
        out["coverage_transitions"] = int(
            res["cov_transitions"].astype(np.int64).sum()
        )
    return out
